"""Benchmark harness — prints ONE JSON line for the driver.

Headline metric: AlexNet bs=128 fwd+bwd+update ms/batch, the reference's
own flagship number (benchmark/README.md:37 — 334 ms/batch on a K40m,
measured by `paddle train --job=time`, parameter update included).
vs_baseline = baseline_ms / our_ms (>1 means faster than the reference).

The single stdout line also carries a `suite` object with every
single-chip BASELINE.md row (AlexNet bs128/bs512, SmallNet, GoogleNet,
LSTM h256/h1280, ResNet-50 north-star), each with achieved TFLOP/s and
MFU (model FLOPs from XLA's compiled cost analysis / device peak).
Multi-GPU rows (4xK40m) need a multi-chip slice and are listed under
`skipped`. Default numeric mode is mixed precision: f32 params, bf16
MXU passes (--dtype float32 for full-precision runs).

Per-suite lines additionally go to stderr for humans.
"""

from __future__ import annotations

import argparse
import json
import sys
import time

import numpy as np

BASELINES_MS = {
    # reference benchmark/README.md (1xK40m, ms/batch, update included)
    "alexnet_bs128": 334.0,
    "alexnet_bs512": 1629.0,
    "smallnet_bs128": 18.184,
    "googlenet_bs128": 1149.0,
    "lstm_bs64_h256": 83.0,
    "lstm_bs128_h1280": 1007.0,
    "resnet50_bs128": None,  # no reference number exists (BASELINE.md note)
}

# Rows that need >1 chip (4xK40m data-parallel, benchmark/README.md:68-152).
MULTICHIP_ROWS = ["alexnet_4x_bs512", "googlenet_4x_bs512", "lstm_4x_bs256"]

# The peak tables, device lookup, compiled-cost readers and roofline
# math moved to paddle_tpu/obs/profile.py so the CONTINUOUS profiler's
# live MFU/roofline gauges and these offline rows are one computation
# (the acceptance criterion is that they agree). Thin aliases keep the
# bench-side names every row below uses.
from paddle_tpu.obs.profile import (compiled_bytes as _compiled_bytes,
                                    compiled_flops as _compiled_flops,
                                    device_hbm_gbps as _device_hbm_gbps,
                                    device_peak_flops as _device_peak_flops,
                                    roofline as _roofline)


def _add_roofline(res, bytes_acc, flops, dev):
    """The decode-row discipline generalized to every row: a step cannot
    beat its HBM traffic at peak bandwidth NOR its model FLOPs at peak
    MXU, so the BINDING bound (max of the two) is a hard per-row floor —
    `roofline_frac` drifting up is a regression, and `roofline_bound`
    says which resource certifies the row's ceiling."""
    bw = _device_hbm_gbps(dev)
    if bytes_acc and bw:
        res["hbm_gb_per_step"] = round(bytes_acc / 1e9, 4)
        res["hbm_gbps_assumed"] = bw
    from paddle_tpu.config import global_config
    rf = _roofline(res["ms"], flops=flops, bytes_acc=bytes_acc,
                   peak_flops=_device_peak_flops(dev), hbm_gbps=bw,
                   mxu=global_config().compute_dtype == "bfloat16")
    if rf.get("roofline_ms") is not None:
        res["roofline_ms"] = round(rf["roofline_ms"], 4)
        res["roofline_bound"] = rf["roofline_bound"]
        res["roofline_frac"] = round(rf["roofline_frac"], 2)
    return res


#: repetitions per bench row; the recorded ms is the MEDIAN of this many
#: independent slope measurements, with min/max kept as the spread.
#: Single-shot rows through a flaky tunnel produced a 2.8x LSTM
#: contradiction between BENCH_r03.json and docs/perf.md — never again.
N_REPS = 5


#: one slope chain must run at least this long so tunnel RTT jitter
#: (tens of ms per readback) amortizes below ~1 ms/step of slope noise
_MIN_CHAIN_MS = 1200.0


def _slope_time(step, carry, extra, iters, warmup, reps=N_REPS):
    """Median-of-`reps` slope timings with spread, plus the live carry.

    One slope sample runs N and 2N chained steps (each chain ends in ONE
    device->host readback of the loss, the only sync every transport
    honors) and takes (T2N - TN)/N: the difference cancels the constant
    sync/transport latency, which on a tunneled TPU (~100 ms RTT) would
    otherwise dominate. The chain serializes on-device because each step
    consumes the previous step's params. N is grown adaptively until a
    single chain takes >= _MIN_CHAIN_MS: with short chains the slope
    inherits RTT jitter / N, which at N=5 was +-10 ms/step on the
    transformer row — worse than the effect being measured. Mirrors
    paddle --job=time (update time included). The caller's (p, o, s) are
    dead after the first call (the step donates its buffers); the live
    carry is returned."""
    feed, key, n_real = extra
    p, o, s = carry

    def chain(n):
        nonlocal p, o, s
        t0 = time.perf_counter()
        for _ in range(n):
            p, o, s, loss, *_ = step(p, o, s, feed, key, n_real)
        float(loss)
        return (time.perf_counter() - t0) * 1000.0

    for _ in range(warmup):
        chain(1)
    n = max(iters // 2, 2)
    while chain(n) < _MIN_CHAIN_MS and n < 4096:
        n = min(n * 2, 4096)
    samples = []
    for _ in range(reps):
        t1 = chain(n)
        t2 = chain(2 * n)
        samples.append(max((t2 - t1) / n, 1e-6))
    return sorted(samples), (p, o, s)


def _spread(samples):
    """{ms: median, min, max, reps} from sorted slope samples."""
    mid = len(samples) // 2
    med = (samples[mid] if len(samples) % 2 else
           (samples[mid - 1] + samples[mid]) / 2)
    return {"ms": med, "min": round(samples[0], 4),
            "max": round(samples[-1], 4), "reps": len(samples)}


def _build(name):
    from paddle_tpu import models
    if name.startswith("alexnet"):
        return models.alexnet(), 227 * 227 * 3, 1000
    if name.startswith("smallnet"):
        return models.smallnet(), 32 * 32 * 3, 10
    if name.startswith("googlenet"):
        return models.googlenet(), 224 * 224 * 3, 1000
    if name.startswith("resnet50"):
        return (models.resnet50(tpu_stem="tpustem" in name),
                224 * 224 * 3, 1000)
    if name.startswith("vgg16"):
        return models.vgg16(), 224 * 224 * 3, 1000
    raise KeyError(name)


def _measure(trainer, feed, batch, iters, warmup, extra_flops=0.0):
    """ms/batch + TFLOP/s + MFU for one trainer/feed pair. Uses the AOT
    compiled step both for cost analysis and timing (one compilation).

    extra_flops: analytic model FLOPs of Pallas custom calls, which
    XLA's cost analysis cannot see (it returns -2 for custom calls) —
    without this the flash-attention and fused-LSTM rows undercount
    their own matmuls. Callers pass the MODEL-FLOPs convention
    (forward + 2x forward for backward) — NOT the kernels' actual
    recompute FLOPs, so MFU stays the standard conservative metric."""
    import jax
    import jax.numpy as jnp

    n_real = jnp.asarray(batch, jnp.int32)
    key = jax.random.PRNGKey(0)
    p, o, s = (trainer.parameters.raw, trainer.opt_state,
               trainer.parameters.state)
    try:
        compiled = trainer._train_step.lower(p, o, s, feed, key,
                                             n_real).compile()
        step, flops = compiled, _compiled_flops(compiled)
    except Exception:
        step, flops = trainer._train_step, None
    samples, carry = _slope_time(step, (p, o, s), (feed, key, n_real),
                                 iters, warmup)
    res = _spread([max(s, 1e-3) for s in samples])  # clamp timing noise
    ms = res["ms"]
    res["ms"] = round(ms, 4)
    res["samples_per_sec"] = round(batch / (ms / 1e3), 1)
    if flops:
        flops += extra_flops
        tflops = flops / (ms / 1e3) / 1e12
        res["tflops"] = round(tflops, 2)
        peak = _device_peak_flops(jax.devices()[0])
        from paddle_tpu.config import global_config
        if peak and global_config().compute_dtype == "bfloat16":
            # the peak table is dense-bf16; an f32 run has a different
            # (pass-count-dependent) ceiling, so report tflops only there
            res["mfu"] = round(tflops * 1e12 / peak, 4)
    _add_roofline(res, _compiled_bytes(step), flops, jax.devices()[0])
    return res


def bench_image(name: str, batch: int, iters: int = 20, warmup: int = 3):
    """forward+backward+update of an image model (NHWC, mixed precision)."""
    import jax
    import paddle_tpu as paddle

    spec, in_dim, n_classes = _build(name)
    params = paddle.create_parameters(paddle.Topology(spec.cost))
    trainer = paddle.SGD(
        cost=spec.cost, parameters=params,
        update_equation=paddle.optimizer.Momentum(
            learning_rate=0.01 / batch, momentum=0.9,
            regularization=paddle.optimizer.L2Regularization(0.0005 * batch)))
    rng = np.random.RandomState(0)
    img = rng.randn(batch, in_dim).astype("float32")
    lbl = rng.randint(0, n_classes, (batch,)).astype("int32")
    feed = {spec.data.name: jax.device_put(img),
            spec.label.name: jax.device_put(lbl)}
    return _measure(trainer, feed, batch, iters, warmup)


def bench_lstm(batch: int, hidden: int, seq_len: int = 100,
               vocab: int = 30000, iters: int = 20, warmup: int = 3):
    """IMDB stacked-LSTM benchmark (benchmark/paddle/rnn/rnn.py shape)."""
    import jax
    import jax.numpy as jnp
    import paddle_tpu as paddle
    from paddle_tpu import models
    from paddle_tpu.core.sequence import SequenceBatch

    spec = models.stacked_lstm_net(vocab_size=vocab, emb_size=128,
                                   hidden_size=hidden, lstm_num=1)
    params = paddle.create_parameters(paddle.Topology(spec.cost))
    trainer = paddle.SGD(
        cost=spec.cost, parameters=params,
        update_equation=paddle.optimizer.Adam(learning_rate=2e-3))
    rng = np.random.RandomState(0)
    ids = rng.randint(0, vocab, (batch, seq_len)).astype("int32")
    lengths = np.full((batch,), seq_len, np.int32)
    feed = {spec.data.name: SequenceBatch(jax.device_put(jnp.asarray(ids)),
                                          jax.device_put(jnp.asarray(lengths))),
            spec.label.name: jax.device_put(
                rng.randint(0, 2, (batch,)).astype("int32"))}
    # the Pallas LSTM kernels hide the recurrent matmuls from XLA's cost
    # analysis: T steps of [b,h]x[h,4h] in the forward and the same chain
    # again for dh in the backward (the weight-grad matmul runs OUTSIDE
    # the kernel and is already counted)
    recurrent = 2 * seq_len * batch * hidden * 4 * hidden * 2
    return _measure(trainer, feed, batch, iters, warmup,
                    extra_flops=float(recurrent))


def bench_transformer(batch: int = 8, seq_len: int = 1024,
                      d_model: int = 512, n_layers: int = 6,
                      iters: int = 10, warmup: int = 3):
    """Decoder-only LM train step (flash-attention path end-to-end).
    No 2017 baseline exists; reported for the TPU-era model family."""
    import jax
    import jax.numpy as jnp
    import paddle_tpu as paddle
    from paddle_tpu import models
    from paddle_tpu.core.sequence import SequenceBatch

    # tie_embeddings: the modern convention at this scale (one 32k x 512
    # table serves embedding + transposed head) — measured 21.97 vs
    # 23.03 ms untied (fewer vocab-sized optimizer passes)
    spec = models.transformer_lm(vocab_size=32000, d_model=d_model,
                                 n_heads=8, n_layers=n_layers,
                                 d_ff=4 * d_model, max_len=seq_len,
                                 tie_embeddings=True)
    params = paddle.create_parameters(paddle.Topology(spec.cost))
    trainer = paddle.SGD(cost=spec.cost, parameters=params,
                         update_equation=paddle.optimizer.Adam(
                             learning_rate=1e-4))
    rng = np.random.RandomState(0)
    lens = np.full((batch,), seq_len, np.int32)

    def seq_feed(arr):
        return SequenceBatch(jax.device_put(jnp.asarray(arr)),
                             jax.device_put(jnp.asarray(lens)))

    ids = rng.randint(0, 32000, (batch, seq_len + 1))
    feed = {spec.data.name: seq_feed(ids[:, :-1].astype("int32")),
            f"{'tfm'}_positions": seq_feed(
                np.tile(np.arange(seq_len, dtype="int32"), (batch, 1))),
            spec.label.name: seq_feed(ids[:, 1:].astype("int32"))}
    # flash attention is a Pallas custom call = invisible to XLA's cost
    # analysis; add its analytic MODEL FLOPs (causal: T^2/2 valid pairs,
    # 2 matmuls x 2d each in the forward, 2x that for the backward — the
    # kernels' score recomputation is deliberately NOT counted)
    head_dim = d_model // 8
    attn_fwd = n_layers * batch * 8 * (seq_len ** 2 / 2) * head_dim * 4
    return _measure(trainer, feed, batch, iters, warmup,
                    extra_flops=3.0 * attn_fwd)


def bench_flash_attention(batch: int = 4, seq_len: int = 4096, heads: int = 8,
                          head_dim: int = 128, iters: int = 20,
                          warmup: int = 3):
    """Fused flash attention vs plain XLA attention (causal, bf16) — the
    long-context primitive. Reports flash ms, xla ms, and their ratio;
    no 2017 baseline row exists (the reference had no attention kernel)."""
    import time

    import jax
    import jax.numpy as jnp
    from paddle_tpu.ops.pallas_attention import (_lens_mask, _reference,
                                                 flash_attention)

    rng = np.random.RandomState(0)
    shape = (batch, seq_len, heads, head_dim)
    q = jax.device_put(jnp.asarray(rng.randn(*shape))).astype(jnp.bfloat16)
    k = jax.device_put(jnp.asarray(rng.randn(*shape))).astype(jnp.bfloat16)
    v = jax.device_put(jnp.asarray(rng.randn(*shape))).astype(jnp.bfloat16)
    lens = jnp.full((batch,), seq_len, jnp.int32)
    f = jax.jit(lambda q, k, v: flash_attention(q, k, v, kv_lens=lens,
                                                causal=True))
    mask = _lens_mask(lens, lens, seq_len, seq_len, True)
    r = jax.jit(lambda q, k, v: _reference(q, k, v, mask,
                                           head_dim ** -0.5))

    def measure(fn):
        for _ in range(warmup):
            fn(q, k, v).block_until_ready()
        samples = []
        for _ in range(N_REPS):
            t0 = time.perf_counter()
            for _ in range(iters):
                out = fn(q, k, v)
            out.block_until_ready()
            samples.append((time.perf_counter() - t0) / iters * 1e3)
        return sorted(samples)

    flash_s = measure(f)
    xla_s = measure(r)
    flash_ms, xla_ms = _spread(flash_s)["ms"], _spread(xla_s)["ms"]

    # training step (fwd+bwd) — exercises the Pallas backward kernels
    def loss_of(fn):
        return jax.jit(jax.grad(lambda q, k, v: jnp.sum(
            fn(q, k, v).astype(jnp.float32) ** 2), argnums=(0, 1, 2)))

    fg, rg = loss_of(f._fun if hasattr(f, "_fun") else (
        lambda q, k, v: flash_attention(q, k, v, kv_lens=lens,
                                        causal=True))), \
        loss_of(lambda q, k, v: _reference(q, k, v, mask, head_dim ** -0.5))

    def measure_grad(fn):
        for _ in range(warmup):
            jax.block_until_ready(fn(q, k, v))
        samples = []
        for _ in range(N_REPS):
            t0 = time.perf_counter()
            for _ in range(iters):
                out = fn(q, k, v)
            jax.block_until_ready(out)
            samples.append((time.perf_counter() - t0) / iters * 1e3)
        return sorted(samples)

    fg_s, rg_s = measure_grad(fg), measure_grad(rg)
    flash_grad_ms, xla_grad_ms = _spread(fg_s)["ms"], _spread(rg_s)["ms"]
    # causal forward FLOPs: two [T, d] matmuls over the T^2/2 valid pairs
    flops = batch * heads * (seq_len ** 2 / 2) * head_dim * 2 * 2
    return {"ms": round(flash_ms, 4),
            "min": round(flash_s[0], 4), "max": round(flash_s[-1], 4),
            "reps": N_REPS, "xla_ms": round(xla_ms, 4),
            "vs_xla": round(xla_ms / flash_ms, 3),
            "grad_ms": round(flash_grad_ms, 4),
            "xla_grad_ms": round(xla_grad_ms, 4),
            "grad_vs_xla": round(xla_grad_ms / flash_grad_ms, 3),
            "tflops": round(flops / flash_ms / 1e9, 2)}


def bench_decode(batch: int = 8, prompt_len: int = 32, max_len: int = 544,
                 d_model: int = 512, n_layers: int = 6, iters: int = 3,
                 n_kv_heads: int = None):
    """KV-cache autoregressive decoding throughput (tokens/sec across the
    batch) on the transformer LM. No 2017 baseline; the RNN era's
    generation analogue is beam_search. `ms` is per-token latency.
    n_kv_heads < 8 benches the GQA decoder (kv-sized caches)."""
    import time

    import jax
    import paddle_tpu as paddle
    from paddle_tpu import models

    kv_h = n_kv_heads or 8
    spec = models.transformer_lm(vocab_size=32000, d_model=d_model,
                                 n_heads=8, n_layers=n_layers,
                                 d_ff=4 * d_model, max_len=max_len,
                                 n_kv_heads=n_kv_heads)
    topo = paddle.Topology(spec.cost, extra_outputs=[spec.output])
    params = topo.init_params(jax.random.PRNGKey(0))
    # the decoder computes in the params' dtype; cast so this row matches
    # the suite's mixed-precision mode instead of silently running f32
    from paddle_tpu.config import global_config
    cdt = global_config().compute_dtype
    if cdt != "float32":
        params = {k: v.astype(cdt) for k, v in params.items()}
    dec = models.TransformerDecoder(params, n_layers=n_layers, n_heads=8)
    # HBM roofline for one decode step: every step must read ALL params
    # (batch-independent) plus each sequence's KV cache (batch-linear,
    # kv-head-sized under GQA). Worst-case cache length = max_len;
    # bytes/elt from the cast dtype; bandwidth from the device kind.
    esize = 2 if cdt != "float32" else 4
    param_bytes = sum(int(np.prod(v.shape)) for v in params.values()) * esize
    cache_bytes = (2 * n_layers * max_len * (d_model * kv_h // 8)
                   * esize * batch)
    hbm_gb = (param_bytes + cache_bytes) / 1e9
    hbm_gbps = _device_hbm_gbps(jax.devices()[0]) or 819.0
    roofline_ms = hbm_gb / hbm_gbps * 1e3
    prompt = np.random.RandomState(0).randint(
        0, 32000, (batch, prompt_len)).astype("int32")
    dec.generate(prompt, max_len=max_len)        # compile
    samples = []
    for _ in range(N_REPS):
        t0 = time.perf_counter()
        for _ in range(iters):
            rows = dec.generate(prompt, max_len=max_len)
        samples.append((time.perf_counter() - t0) / iters)
    samples.sort()
    n_new = len(rows[0])
    mid = len(samples) // 2
    dt = samples[mid] if len(samples) % 2 else \
        (samples[mid - 1] + samples[mid]) / 2  # median seconds-per-generate
    ms_tok = dt / n_new * 1e3
    return {"ms": round(ms_tok, 4),
            "min": round(samples[0] / n_new * 1e3, 4),
            "max": round(samples[-1] / n_new * 1e3, 4), "reps": N_REPS,
            "tokens_per_sec": round(batch * n_new / dt, 1),
            "new_tokens": n_new, "batch": batch,
            # anchor: a per-token step cannot beat reading params + KV
            # cache once from HBM; regressions show as roofline_frac
            # drifting up
            "hbm_gb_per_step": round(hbm_gb, 4),
            "hbm_gbps_assumed": hbm_gbps,
            "roofline_ms": round(roofline_ms, 4),
            "roofline_bound": "hbm",
            "roofline_frac": round(ms_tok / roofline_ms, 2)}


def bench_decode_continuous(num_slots: int = 8, n_requests: int = 32,
                            page_size: int = 16,
                            prompt_lens=(16, 96),
                            new_tokens=(64, 256),
                            d_model: int = 512, n_layers: int = 6,
                            n_heads: int = 8, n_kv_heads: int = None,
                            vocab_size: int = 32000,
                            max_len: int = 544, seed: int = 0):
    """Continuous-batching decode engine (serving/engine.py) on a
    seeded RAGGED workload: n_requests with uniform-random prompt and
    generation lengths, more requests than slots, so sequences join and
    leave the running jitted step mid-flight (joins interleave prefill
    with other slots' decoding; finished sequences free their KV pages
    immediately).

    Metrics: `tokens_per_sec` (generated tokens / wall), per-token
    latency `ms` (p50 inter-token) + `p99_ms` + `ttft_p50_ms`, slot
    utilization, KV-page high water, preemptions. `roofline_frac` is
    throughput-based against the paged floor: every step reads all
    params once plus each ACTIVE sequence's cache at its ACTUAL length
    (the engine counts cache tokens read exactly) — a tighter floor
    than the dense rows' worst-case max_len bound, so the same frac is
    a stronger claim. The CPU smoke slice of this row runs in tier-1
    (tests/test_paged_decode.py::TestBenchSmoke)."""
    import time

    import jax
    import paddle_tpu as paddle
    from paddle_tpu import models
    from paddle_tpu.serving import DecodeEngine

    kv_h = n_kv_heads or n_heads
    spec = models.transformer_lm(vocab_size=vocab_size, d_model=d_model,
                                 n_heads=n_heads, n_layers=n_layers,
                                 d_ff=4 * d_model, max_len=max_len,
                                 n_kv_heads=n_kv_heads)
    topo = paddle.Topology(spec.cost, extra_outputs=[spec.output])
    params = topo.init_params(jax.random.PRNGKey(0))
    from paddle_tpu.config import global_config
    cdt = global_config().compute_dtype
    if cdt != "float32":
        params = {k: v.astype(cdt) for k, v in params.items()}
    dec = models.TransformerDecoder(params, n_layers=n_layers,
                                    n_heads=n_heads)
    rng = np.random.RandomState(seed)
    prompts = [rng.randint(0, vocab_size,
                           (int(rng.randint(*prompt_lens)),))
               .astype("int32") for _ in range(n_requests)]
    news = [int(rng.randint(*new_tokens)) for _ in range(n_requests)]
    eng = DecodeEngine(dec, num_slots=num_slots, page_size=page_size,
                       max_seq_len=max_len)
    # one warm token compiles the step outside the timed window
    eng.submit(prompts[0][:4], 1)
    eng.run(timeout=600)
    st0 = eng.stats()
    t0 = time.perf_counter()
    reqs = [eng.submit(p, n) for p, n in zip(prompts, news)]
    eng.run(timeout=600)
    dt = time.perf_counter() - t0
    for r in reqs:
        r.get(timeout=1)               # surface any typed failure
    st = eng.stats()
    gen = st["tokens_out"] - st0["tokens_out"]
    steps = st["steps"] - st0["steps"]
    cache_read = st["cache_tokens_read"] - st0["cache_tokens_read"]
    active_steps = st["active_slot_steps"] - st0["active_slot_steps"]
    util = active_steps / (steps * num_slots) if steps else 0.0
    esize = 2 if cdt != "float32" else 4
    param_bytes = sum(int(np.prod(v.shape))
                      for v in params.values()) * esize
    per_tok_cache = 2 * n_layers * (d_model // n_heads) * kv_h * esize
    hbm_gb = (param_bytes * steps + cache_read * per_tok_cache) / 1e9
    hbm_gbps = _device_hbm_gbps(jax.devices()[0]) or 819.0
    roofline_s = hbm_gb / hbm_gbps
    return {"ms": st["token_latency_p50_ms"],
            "p99_ms": st["token_latency_p99_ms"],
            "ttft_p50_ms": st["ttft_p50_ms"],
            "tokens_per_sec": round(gen / dt, 1),
            "new_tokens": gen, "tokens_out": gen,
            "prefill_tokens": st["prefill_tokens"]
            - st0["prefill_tokens"],
            "requests": n_requests, "slots": num_slots,
            "page_size": page_size,
            "slot_utilization": round(util, 4),
            "kv_page_high_water": st["kv_page_high_water"],
            "preemptions": st["preemptions"] - st0["preemptions"],
            "steps": steps,
            "hbm_gb_total": round(hbm_gb, 4),
            "hbm_gbps_assumed": hbm_gbps,
            "roofline_bound": "hbm",
            "roofline_frac": round(dt / roofline_s, 2)
            if roofline_s > 0 else None}


def bench_moe_lm(batch: int = 8, seq_len: int = 1024, d_model: int = 512,
                 n_layers: int = 6, experts: int = 8, iters: int = 10,
                 warmup: int = 3):
    """MoE transformer LM train step (sort-dispatch single-host path) —
    the beyond-parity expert-parallel leg's regression row. Same shape
    as transformer_lm_bs8_t1024 with every FFN an 8-expert top-2 MoE."""
    import jax
    import jax.numpy as jnp
    import paddle_tpu as paddle
    from paddle_tpu import models
    from paddle_tpu.core.sequence import SequenceBatch

    spec = models.transformer_lm(vocab_size=32000, d_model=d_model,
                                 n_heads=8, n_layers=n_layers,
                                 d_ff=4 * d_model, max_len=seq_len,
                                 tie_embeddings=True, moe_experts=experts)
    params = paddle.create_parameters(
        paddle.Topology(spec.cost, extra_outputs=[spec.output]))
    # NOTE: no extra_layers — SGD computes extra layers INSIDE the timed
    # step, and spec.output is the [b, T, 32000] softmax probs side
    # branch the training forward deliberately never materializes; the
    # dense transformer row omits it too, so adding it here would skew
    # the comparison by ~2 GB/step of softmax traffic
    trainer = paddle.SGD(cost=spec.cost, parameters=params,
                         update_equation=paddle.optimizer.Adam(
                             learning_rate=1e-4))
    rng = np.random.RandomState(0)
    lens = np.full((batch,), seq_len, np.int32)

    def seq_feed(arr):
        return SequenceBatch(jax.device_put(jnp.asarray(arr)),
                             jax.device_put(jnp.asarray(lens)))

    ids = rng.randint(0, 32000, (batch, seq_len + 1))
    feed = {spec.data.name: seq_feed(ids[:, :-1].astype("int32")),
            "tfm_positions": seq_feed(
                np.tile(np.arange(seq_len, dtype="int32"), (batch, 1))),
            spec.label.name: seq_feed(ids[:, 1:].astype("int32"))}
    head_dim = d_model // 8
    attn_fwd = n_layers * batch * 8 * (seq_len ** 2 / 2) * head_dim * 4
    return _measure(trainer, feed, batch, iters, warmup,
                    extra_flops=3.0 * attn_fwd)


#: rows of the CPU smoke tier; tools/bench_gate.py gates them against
#: BENCH_SMOKE_BASELINE.json in tier-1 (docs/observability.md)
SMOKE_ROWS = ("train_tiny", "serving_infer", "decode_engine",
              "decode_prefix_hit", "decode_speculative",
              "flight_recorder_overhead", "profiler_overhead",
              "lockdep_overhead", "protocol_witness_overhead",
              "contract_check", "coord_reshard", "embed_lookup",
              "embed_update", "fleet_route", "fleet_failover",
              "cold_start_to_first_token", "fleet_deploy",
              "fleet_autoscale", "router_ha", "soak_smoke",
              "kv_capacity_multiplier", "kv_dequant_overhead",
              "kv_restore_latency")


def _smoke_trainer(batch: int = 16):
    """A CPU-trivial 2-layer classifier — the smoke tier measures the
    FRAMEWORK's step machinery (compiles, host syncs, dispatch), not
    the model."""
    import paddle_tpu as paddle
    from paddle_tpu.core.registry import reset_name_counters
    reset_name_counters()
    x = paddle.layer.data("smoke_x", paddle.data_type.dense_vector(16))
    y = paddle.layer.data("smoke_y", paddle.data_type.integer_value(4))
    h = paddle.layer.fc(x, size=8, act=paddle.activation.Relu(),
                        name="smoke_h")
    out = paddle.layer.fc(h, size=4, act=paddle.activation.Softmax(),
                          name="smoke_prob")
    cost = paddle.layer.classification_cost(out, y, name="smoke_cost")
    params = paddle.create_parameters(paddle.Topology(cost))
    trainer = paddle.SGD(
        cost=cost, parameters=params,
        update_equation=paddle.optimizer.Momentum(learning_rate=1e-3,
                                                  momentum=0.9))
    rng = np.random.RandomState(0)
    data = [(rng.randn(16).astype("float32"), int(rng.randint(0, 4)))
            for _ in range(batch)]
    return trainer, data


def _smoke_decoder():
    """Tiny transformer decoder (the serving chaos suite's shape) for
    the continuous-batching engine row."""
    import jax
    import paddle_tpu as paddle
    from paddle_tpu import models
    from paddle_tpu.core.registry import reset_name_counters
    reset_name_counters()
    spec = models.transformer_lm(vocab_size=40, d_model=16, n_heads=2,
                                 n_layers=2, d_ff=32, max_len=32)
    costs = spec.cost if isinstance(spec.cost, list) else [spec.cost]
    topo = paddle.Topology(costs, extra_outputs=[spec.output])
    params = topo.init_params(jax.random.PRNGKey(7))
    return models.TransformerDecoder(params, n_layers=2, n_heads=2)


def bench_smoke(train_steps: int = 12, serve_requests: int = 16,
                decode_requests: int = 5, rows=SMOKE_ROWS,
                force_recompile_per_step: bool = False) -> dict:
    """The CPU smoke tier of the perf regression gate (ROADMAP item 5).

    Deliberately two-faced: COUNT metrics (XLA compiles via
    compile_watch, host syncs per step via host_sync_watch — both
    analysis/sanitizer.py) are deterministic and gated tightly, while
    TIMING metrics (steps/s, serving p50/p99, engine tokens/s) carry
    loose machine-to-machine tolerances and only catch order-of-
    magnitude regressions. ``tools/bench_gate.py`` compares the result
    against the committed BENCH_SMOKE_BASELINE.json; the tier-1 test
    (tests/test_bench_gate.py) runs both an untouched pass and a
    forced-recompile-per-step injection that must FAIL the gate.

    ``force_recompile_per_step`` is that injection seam: it rebuilds
    the jitted train step every iteration — the classic shape-drift /
    jit-in-loop regression ptlint R2 lints for, reproduced at runtime.
    """
    import paddle_tpu as paddle
    from paddle_tpu.analysis.sanitizer import compile_watch, \
        host_sync_watch

    paddle.init(seed=0)
    out = {}
    if "train_tiny" in rows:
        trainer, data = _smoke_trainer()
        with compile_watch() as cw, host_sync_watch() as hs:
            trainer.train_batch(data)           # compile + warm
            syncs0 = hs.total
            t0 = time.perf_counter()
            for _ in range(train_steps):
                if force_recompile_per_step:
                    trainer._train_step = trainer._build_train_step()
                trainer.train_batch(data)
            dt = time.perf_counter() - t0
        out["train_tiny"] = {
            "steps_per_s": round(train_steps / dt, 2),
            "step_compiles": cw.total,
            "host_syncs_per_step": round(
                (hs.total - syncs0) / train_steps, 3),
        }
    if "serving_infer" in rows:
        from paddle_tpu.serving import InferenceServer
        from paddle_tpu.trainer.inference import Inference
        from paddle_tpu.core.registry import reset_name_counters
        reset_name_counters()
        import paddle_tpu as _p
        x = _p.layer.data("smoke_sx", _p.data_type.dense_vector(8))
        o = _p.layer.fc(x, size=4, act=_p.activation.Softmax(),
                        name="smoke_sprob")
        inf = Inference(output_layer=o,
                        parameters=_p.create_parameters(_p.Topology(o)))
        rng = np.random.RandomState(0)
        reqs = [(rng.randn(8).astype("float32"),) for _ in range(2)]
        srv = InferenceServer(inf, max_queue=64, workers=2,
                              breaker=False).start()
        try:
            srv.infer(reqs)                     # compile + warm
            for _ in range(serve_requests):
                srv.infer(reqs)
            st = srv.stats()
        finally:
            srv.shutdown(drain=True)
        out["serving_infer"] = {
            "p50_ms": st["p50_ms"],
            "p99_ms": st["p99_ms"],
            "served": st["served"],
        }
    if "decode_engine" in rows:
        from paddle_tpu.analysis.sanitizer import compile_watch as _cwf
        from paddle_tpu.serving import DecodeEngine
        dec = _smoke_decoder()
        rng = np.random.RandomState(0)
        prompts = [rng.randint(0, 40, (int(rng.randint(3, 8)),))
                   .astype("int32") for _ in range(decode_requests)]
        news = [int(rng.randint(4, 12)) for _ in range(decode_requests)]
        with _cwf() as cw:
            eng = DecodeEngine(dec, num_slots=2, page_size=4,
                               max_seq_len=32)
            eng.submit(prompts[0][:2], 1)       # compile + warm
            eng.run(timeout=300)
            st0 = eng.stats()
            t0 = time.perf_counter()
            for p, n in zip(prompts, news):
                eng.submit(p, n)
            eng.run(timeout=300)
            dt = time.perf_counter() - t0
        st = eng.stats()
        gen = st["tokens_out"] - st0["tokens_out"]
        out["decode_engine"] = {
            "tokens_per_s": round(gen / dt, 1),
            "token_p50_ms": st["token_latency_p50_ms"],
            "decode_compiles": cw.total,
            "steps": st["steps"] - st0["steps"],
            "tokens_out": gen,
        }
    if "decode_prefix_hit" in rows:
        # ISSUE 13 tentpole (a): warm-prefix TTFT vs cold. The same
        # prompts run twice through one engine — the first pass
        # prefills and indexes its pages in the radix trie, the second
        # attaches the cached pages and feeds only the final token, so
        # both the step count (deterministic) and the wall time
        # collapse. ``warm_step_ratio`` and ``hit_pages`` are the
        # gated metrics: prefix reuse silently breaking drives the
        # ratio to ~1 and the hits to 0.
        from paddle_tpu.serving import DecodeEngine
        dec = _smoke_decoder()
        eng = DecodeEngine(dec, num_slots=2, page_size=4,
                           max_seq_len=32)
        rng = np.random.RandomState(1)
        hit_prompts = [rng.randint(0, 40, (13,)).astype("int32")
                       for _ in range(4)]
        eng.submit(hit_prompts[0][:2], 1)       # compile + warm
        eng.run(timeout=300)
        st0 = eng.stats()
        t0 = time.perf_counter()
        cold = [eng.submit(p, 1) for p in hit_prompts]
        eng.run(timeout=300)
        dt_cold = time.perf_counter() - t0
        st1 = eng.stats()
        t0 = time.perf_counter()
        warm = [eng.submit(p, 1) for p in hit_prompts]
        eng.run(timeout=300)
        dt_warm = time.perf_counter() - t0
        st2 = eng.stats()
        for r in cold + warm:
            r.get(timeout=1)                    # surface failures
        steps_cold = st1["steps"] - st0["steps"]
        steps_warm = st2["steps"] - st1["steps"]
        out["decode_prefix_hit"] = {
            "ttft_cold_ms": round(dt_cold / len(hit_prompts) * 1e3, 3),
            "ttft_warm_ms": round(dt_warm / len(hit_prompts) * 1e3, 3),
            "steps_cold": steps_cold, "steps_warm": steps_warm,
            "warm_step_ratio": round(steps_cold / max(steps_warm, 1),
                                     2),
            "hit_pages": sum(r.prefix_hit_pages for r in warm),
        }
    if "decode_speculative" in rows:
        # ISSUE 13 tentpole (b): speculative decoding with a
        # same-weights draft (the acceptance best case at smoke
        # scale) — spec_k proposals verified per [S, W] target step.
        # ``tokens_per_step`` is the gated acceptance metric: the
        # ISSUE contract is > 1.0 committed tokens per target
        # dispatch; a broken verify path degrades it to <= 1.
        from paddle_tpu.analysis.sanitizer import compile_watch as _cws
        from paddle_tpu.serving import DecodeEngine
        dec = _smoke_decoder()
        draft = _smoke_decoder()
        rng = np.random.RandomState(2)
        prompts = [rng.randint(0, 40, (int(rng.randint(3, 8)),))
                   .astype("int32") for _ in range(decode_requests)]
        news = [int(rng.randint(6, 12)) for _ in range(decode_requests)]
        with _cws() as cw:
            eng = DecodeEngine(dec, num_slots=2, page_size=4,
                               max_seq_len=32, draft=draft, spec_k=2)
            eng.submit(prompts[0][:2], 1)       # compile + warm
            eng.run(timeout=300)
            st0 = eng.stats()
            t0 = time.perf_counter()
            reqs = [eng.submit(p, n) for p, n in zip(prompts, news)]
            eng.run(timeout=300)
            dt = time.perf_counter() - t0
        for r in reqs:
            r.get(timeout=1)
        st = eng.stats()
        gen = st["tokens_out"] - st0["tokens_out"]
        steps = st["steps"] - st0["steps"]
        out["decode_speculative"] = {
            "tokens_per_s": round(gen / dt, 1),
            "tokens_per_step": round(gen / max(steps, 1), 2),
            "accepted_tokens": st["spec_accepted_tokens"]
            - st0["spec_accepted_tokens"],
            "proposed_tokens": st["spec_proposed_tokens"]
            - st0["spec_proposed_tokens"],
            "spec_compiles": cw.total,
            "steps": steps, "tokens_out": gen,
        }
    if "flight_recorder_overhead" in rows:
        # the always-on cost of the flight recorder (obs/flight.py):
        # same tiny train loop with the recorder off vs on. The gated
        # metric is the RATIO (off/on steps/s) — machine-independent;
        # > 2.0 means always-on recording doubled the step time and
        # the gate fails (BENCH_SMOKE_BASELINE.json).
        from paddle_tpu.obs.flight import FLIGHT
        trainer, data = _smoke_trainer()
        trainer.train_batch(data)               # compile + warm

        def _steps_per_s(n):
            t0 = time.perf_counter()
            for _ in range(n):
                trainer.train_batch(data)
            return n / (time.perf_counter() - t0)

        prev = FLIGHT.enabled
        try:
            FLIGHT.enabled = False
            _steps_per_s(4)                     # settle both modes
            off = _steps_per_s(train_steps)
            FLIGHT.enabled = True
            _steps_per_s(4)
            on = _steps_per_s(train_steps)
        finally:
            FLIGHT.enabled = prev
        out["flight_recorder_overhead"] = {
            "steps_per_s_off": round(off, 2),
            "steps_per_s_on": round(on, 2),
            "overhead_ratio": round(off / on, 3),
        }
    if "profiler_overhead" in rows:
        # the continuous step profiler's cost (obs/profile.py): the
        # same tiny train loop with PROFILER off vs on at the default
        # sampling cadence. Gated like the flight recorder — the RATIO
        # (off/on steps/s) is machine-independent; the acceptance
        # budget is a few percent of steps/s, and > 2x fails the gate
        # outright (BENCH_SMOKE_BASELINE.json).
        from paddle_tpu.obs.profile import PROFILER
        trainer, data = _smoke_trainer()
        trainer.train_batch(data)               # compile + warm

        def _steps_per_s_prof(n):
            t0 = time.perf_counter()
            for _ in range(n):
                trainer.train_batch(data)
            return n / (time.perf_counter() - t0)

        # alternating off/on reps with the MEDIAN of each: a dozen
        # sub-ms steps is a ~10 ms window, where scheduler jitter alone
        # reads as several percent — the ratio of medians is what the
        # <= few-percent acceptance budget is judged on
        offs, ons = [], []
        try:
            PROFILER.enable(sample_every=8)
            # 10 settle steps so the first sampled step (and its
            # one-time AOT cost_analysis compile) lands OUTSIDE the
            # measured window — the row gates steady-state overhead
            _steps_per_s_prof(10)
            for _ in range(5):
                PROFILER.disable()
                _steps_per_s_prof(4)            # settle the mode flip
                offs.append(_steps_per_s_prof(train_steps))
                PROFILER.enable(sample_every=8)
                _steps_per_s_prof(4)
                ons.append(_steps_per_s_prof(train_steps))
        finally:
            PROFILER.reset()
        off = sorted(offs)[len(offs) // 2]
        on = sorted(ons)[len(ons) // 2]
        out["profiler_overhead"] = {
            "steps_per_s_off": round(off, 2),
            "steps_per_s_on": round(on, 2),
            "overhead_ratio": round(off / on, 3),
        }
    if "lockdep_overhead" in rows:
        # the lockdep witness's cost (analysis/lockdep.py): an
        # uncontended with-lock loop over a raw threading.Lock vs an
        # InstrumentedLock. Every hot shared lock in the framework is
        # instrumented, so this ratio bounds what the deadlock witness
        # adds to every critical section; the RATIO is
        # machine-independent and gated like the profiler
        # (BENCH_SMOKE_BASELINE.json). Medians of alternating reps for
        # the same jitter reasons as profiler_overhead.
        import threading as _threading
        from paddle_tpu.analysis.lockdep import InstrumentedLock
        n_ops = 20000

        def _ops_per_s(lk, n=n_ops):
            t0 = time.perf_counter()
            for _ in range(n):
                with lk:
                    pass
            return n / (time.perf_counter() - t0)

        raw_lk = _threading.Lock()
        inst_lk = InstrumentedLock("bench.lockdep")
        _ops_per_s(raw_lk, 2000)                # warm both paths
        _ops_per_s(inst_lk, 2000)
        raws, insts = [], []
        for _ in range(5):
            raws.append(_ops_per_s(raw_lk))
            insts.append(_ops_per_s(inst_lk))
        raw = sorted(raws)[len(raws) // 2]
        inst = sorted(insts)[len(insts) // 2]
        out["lockdep_overhead"] = {
            "ops_per_s_raw": round(raw, 0),
            "ops_per_s_instrumented": round(inst, 0),
            "overhead_ratio": round(raw / inst, 3),
        }
    if "protocol_witness_overhead" in rows:
        # the protocol witness's cost (obs/protocol.py): a start/settle
        # emit pair into a bare journal vs one with the witness
        # observing — the witness rides the SAME observer seam in
        # production (obs/__init__.py), so this ratio bounds what ptproto
        # adds to every journaled protocol event. Medians of alternating
        # reps, ratio gated like lockdep_overhead.
        from paddle_tpu.obs.events import EventJournal
        from paddle_tpu.obs.protocol import ProtocolWitness
        n_pairs = 4000

        def _pairs_per_s(j, n=n_pairs):
            t0 = time.perf_counter()
            for i in range(n):
                t = f"bench-{i}"
                j.emit("serving", "hop", trace_id=t, phase="start")
                j.emit("serving", "hop", trace_id=t, phase="settle")
            return n / (time.perf_counter() - t0)

        bare_j = EventJournal()
        wit_j = EventJournal()
        witness = ProtocolWitness()
        wit_j.add_observer(witness.observe_journal)
        _pairs_per_s(bare_j, 500)               # warm both paths
        _pairs_per_s(wit_j, 500)
        bares, wits = [], []
        for _ in range(5):
            bares.append(_pairs_per_s(bare_j))
            wits.append(_pairs_per_s(wit_j))
        bare = sorted(bares)[len(bares) // 2]
        wit = sorted(wits)[len(wits) // 2]
        out["protocol_witness_overhead"] = {
            "pairs_per_s_bare": round(bare, 0),
            "pairs_per_s_witnessed": round(wit, 0),
            "overhead_ratio": round(bare / wit, 3),
            "violations": witness.violation_count,   # must stay 0
        }
    if "contract_check" in rows:
        # wall time of the full-repo R11/R12/R13 contract sweep (the
        # `paddle_tpu lint --contracts` view) — info only: it tracks
        # catalog growth, nothing latency-critical rides on it
        import os as _os

        from paddle_tpu.analysis.runner import (_contracts_view,
                                                load_config)
        t0 = time.perf_counter()
        res = _contracts_view(
            load_config(_os.path.dirname(_os.path.abspath(__file__))),
            use_baseline=True)
        out["contract_check"] = {
            "wall_ms": round((time.perf_counter() - t0) * 1000.0, 1),
            "files": res.files,
            "findings": len(res.new),
        }
    if "coord_reshard" in rows:
        # elastic-membership control-plane latency: time from a
        # membership change (join) to the FIRST task grant stamped with
        # the post-reshape generation — the window during which the
        # fleet is reorganizing instead of training. Tiny shapes, pure
        # control plane (no XLA), gated by the latency kind's absolute
        # floor so any machine passes unless the reshard path grows
        # real work (docs/robustness.md "Elastic training").
        from paddle_tpu.trainer.coordinator import Coordinator
        coord = Coordinator(list(range(64)), chunks_per_task=4,
                            timeout_s=60.0)
        coord.join("bench-w0")
        reshards = 8
        lat = []
        for i in range(reshards):
            wid = f"bench-w{i + 1}"
            t0 = time.perf_counter()
            gen = coord.join(wid)["generation"]
            while True:
                grant = coord.get_task(worker_id=wid)
                if grant is None or grant["generation"] >= gen:
                    break
            lat.append((time.perf_counter() - t0) * 1000.0)
            if grant is not None:
                coord.task_finished(grant["task_id"],
                                    grant["generation"])
        lat.sort()
        out["coord_reshard"] = {
            "reshard_latency_ms": round(lat[len(lat) // 2], 3),
            "reshards": reshards,
            "generation": coord.generation,
        }
    if "embed_lookup" in rows or "embed_update" in rows:
        # the sharded embedding store (paddle_tpu/embed): pure host/RPC
        # control plane, no XLA. embed_lookup gates the serving gather
        # path (rows/s + per-gather latency through the XML-RPC plane);
        # embed_update gates the async-SGD push path (acked update
        # rows/s through the exactly-once ledger). Latencies carry the
        # latency kind's absolute floor; rates are loose like every
        # timing metric here (docs/observability.md "The perf gate").
        from paddle_tpu.embed import EmbedService
        n_keys, n_dim = 256, 16
        with EmbedService(2, n_dim, seed=0) as esvc:
            with esvc.client(client_id="bench-embed") as ecl:
                if "embed_lookup" in rows:
                    rng = np.random.RandomState(0)
                    ecl.gather(np.arange(n_keys, dtype="int64"))  # warm
                    lats = []
                    t_all = time.perf_counter()
                    reps = 12
                    for _ in range(reps):
                        keys = rng.randint(0, 100000, n_keys) \
                            .astype("int64")
                        t0 = time.perf_counter()
                        ecl.gather(keys, max_stale_s=0.0)  # forced RPC
                        lats.append((time.perf_counter() - t0) * 1e3)
                    dt = time.perf_counter() - t_all
                    lats.sort()
                    out["embed_lookup"] = {
                        "rows_per_s": round(reps * n_keys / dt, 1),
                        "gather_p50_ms": round(lats[len(lats) // 2], 3),
                        "gather_p99_ms": round(lats[-1], 3),
                        "gathers": reps,
                    }
                if "embed_update" in rows:
                    rng = np.random.RandomState(1)
                    n_batches = 12
                    t0 = time.perf_counter()
                    for _ in range(n_batches):
                        keys = rng.randint(0, 100000, n_keys) \
                            .astype("int64")
                        ecl.push(keys,
                                 np.ones((n_keys, n_dim), "float32"),
                                 lr=0.1)
                    ecl.flush(timeout=60.0)
                    dt = time.perf_counter() - t0
                    st = ecl.stats()
                    out["embed_update"] = {
                        "updates_per_s": round(st["pushed_rows"] / dt, 1),
                        "push_failures": st["push_failures"],
                        "batches": n_batches,
                    }
    if "fleet_route" in rows or "fleet_failover" in rows:
        # ISSUE 15 tentpole: the serving-fleet router. fleet_route
        # gates the ROUTER'S OVERHEAD — the same request through the
        # router hop (radix-affinity choose + HTTP stream relay) vs
        # straight at the replica; both are latency metrics with the
        # absolute floor, so only a real control-plane regression
        # (scrape under the route lock, affinity scan gone quadratic)
        # fails. fleet_failover is an info row: mid-stream kill ->
        # time-to-resume on the sibling, recorded for trend reading
        # (docs/robustness.md "Serving fleet").
        import threading as _th
        import urllib.request as _rq

        from paddle_tpu.fleet import Router
        from paddle_tpu.serving import (DecodeEngine, InferenceServer,
                                        build_http_server)
        from paddle_tpu.testing import FaultPlan

        def _fleet_replica():
            eng = DecodeEngine(_smoke_decoder(), num_slots=2,
                               page_size=4, max_seq_len=32)
            srv = InferenceServer(None, max_queue=32, workers=1,
                                  breaker=False, engine=eng).start()
            httpd = build_http_server(srv, "127.0.0.1", 0)
            _th.Thread(target=httpd.serve_forever, daemon=True,
                       name="pt-bench-replica").start()
            ep = f"http://127.0.0.1:{httpd.server_address[1]}"
            return {"engine": eng, "server": srv, "httpd": httpd,
                    "endpoint": ep, "killed": False}

        def _direct(ep, prompt, n):
            body = json.dumps({"prompt": prompt,
                               "max_new_tokens": n}).encode()
            req = _rq.Request(ep + "/generate", data=body,
                              headers={"Content-Type":
                                       "application/json"})
            with _rq.urlopen(req, timeout=60) as r:
                return json.loads(r.read())

        reps = [_fleet_replica(), _fleet_replica()]
        router = Router(endpoints={f"r{i}": rep["endpoint"]
                                   for i, rep in enumerate(reps)},
                        affinity="prefix", page_size=4,
                        scrape_interval=0.1, queue_timeout=10.0)
        try:
            rng = np.random.RandomState(3)
            shared = [int(t) for t in rng.randint(0, 40, (9,))]
            for rep in reps:                    # compile + warm BOTH
                _direct(rep["endpoint"], shared, 1)
            router.refresh()
            if "fleet_route" in rows:
                reqs = 16
                direct_ms, routed_ms = [], []
                for i in range(reqs):
                    p = shared + [i % 40]
                    t0 = time.perf_counter()
                    _direct(reps[0]["endpoint"], p, 4)
                    direct_ms.append((time.perf_counter() - t0) * 1e3)
                for i in range(reqs):
                    p = shared + [i % 40]
                    t0 = time.perf_counter()
                    router.generate(p, 4)
                    routed_ms.append((time.perf_counter() - t0) * 1e3)
                direct_ms.sort()
                routed_ms.sort()
                st = router.stats()
                out["fleet_route"] = {
                    "route_p50_ms": round(
                        routed_ms[len(routed_ms) // 2], 3),
                    "route_p99_ms": round(routed_ms[-1], 3),
                    "direct_p50_ms": round(
                        direct_ms[len(direct_ms) // 2], 3),
                    "routed": st["routed"],
                    "affinity_hits": st["affinity_hits"],
                }
            if "fleet_failover" in rows:
                # pin the stream on a known victim, throttle it so the
                # kill lands MID-stream, and time kill -> first token
                # relayed off the sibling
                prime = router.generate(shared + [38], 2)
                victim_i = int(prime.replica_chain[-1][1:])
                victim = reps[victim_i]
                victim["engine"]._step_interceptor = \
                    lambda s: time.sleep(0.01)
                marks = {}

                def _kill():
                    victim["killed"] = True
                    marks["kill"] = time.perf_counter()
                    victim["httpd"].kill()

                def _tok(_t):
                    if "kill" in marks and "resume" not in marks:
                        marks["resume"] = time.perf_counter()

                with FaultPlan.kill_replica(
                        router, f"r{victim_i}", _kill, at=2):
                    res = router.generate(shared + [38], 10,
                                          on_token=_tok)
                out["fleet_failover"] = {
                    "resume_ms": round(
                        (marks["resume"] - marks["kill"]) * 1e3, 3),
                    "hops": res.hops,
                    "tokens_out": len(res.tokens),
                }
        finally:
            router.shutdown(drain=True, timeout=10)
            for rep in reps:
                if not rep["killed"]:
                    rep["httpd"].shutdown()
                    rep["httpd"].server_close()
                rep["server"].shutdown(drain=True, timeout=30)

    if "cold_start_to_first_token" in rows:
        # ISSUE 18 tentpole row: what crash recovery / autoscale-up
        # actually costs. Cold = fresh engine with NOTHING warm (jit
        # caches and the executable cache both emptied) — construction
        # plus the first token, compile included. Warm = the same
        # respawn with the warm-start plane populated: the engine
        # resolves its executable instead of compiling, so
        # warm_ttft_ms IS the autoscale-MTTR decode bound and
        # warm_step_compiles is gated at 0. The artifact store fills
        # as a side effect (artifacts_built); the cross-process disk
        # rung is proven in tests/test_artifacts.py.
        import shutil as _sh
        import tempfile as _tf

        import jax as _jax

        from paddle_tpu import artifacts as _arts
        from paddle_tpu.serving import DecodeEngine as _DEng

        _croot = _tf.mkdtemp(prefix="pt_bench_arts_")
        _cstore = _arts.configure(_croot)
        try:
            _arts.EXECUTABLES.clear()
            _jax.clear_caches()

            def _ttft_ms():
                t0 = time.perf_counter()
                eng = _DEng(_smoke_decoder(), num_slots=2, page_size=4,
                            max_seq_len=32, prefix_cache=False)
                r = eng.submit(np.array([1, 2, 3, 4], np.int32), 1)
                eng.run(timeout=300)
                assert len(r.get(timeout=1)) == 1
                return (time.perf_counter() - t0) * 1e3

            cold_ms = _ttft_ms()
            with compile_watch() as _ccw:
                warm_ms = _ttft_ms()
            out["cold_start_to_first_token"] = {
                "cold_ttft_ms": round(cold_ms, 3),
                "warm_ttft_ms": round(warm_ms, 3),
                "warm_speedup": round(cold_ms / max(warm_ms, 1e-6), 2),
                "warm_step_compiles": sum(
                    v for k, v in _ccw.per_function.items()
                    if "_step_impl" in k),
                "artifacts_built": len(_cstore.entries()),
            }
        finally:
            _arts.configure(None)
            _sh.rmtree(_croot, ignore_errors=True)

    if "fleet_deploy" in rows:
        # ISSUE 16 tentpole leg (b): the SLO-gated rolling deploy.
        # failed_requests is the GATED metric (count, slack 0): a
        # drain->restart->rejoin cycle over every replica under an
        # open-loop burst must fail NOTHING — the zero-downtime
        # contract. Wall time is a loose latency trend row.
        import threading as _th

        from paddle_tpu.fleet import Router
        from paddle_tpu.fleet.autopilot import RollingDeploy
        from paddle_tpu.serving import (DecodeEngine, InferenceServer,
                                        build_http_server)
        from paddle_tpu.testing import FaultPlan

        class _Watch:                      # no SLO pressure in a bench
            breaches = 0

        def _dep_replica():
            eng = DecodeEngine(_smoke_decoder(), num_slots=2,
                               page_size=4, max_seq_len=32)
            srv = InferenceServer(None, max_queue=32, workers=1,
                                  breaker=False, engine=eng).start()
            httpd = build_http_server(srv, "127.0.0.1", 0)
            _th.Thread(target=httpd.serve_forever, daemon=True,
                       name="pt-bench-deploy-replica").start()
            ep = f"http://127.0.0.1:{httpd.server_address[1]}"
            return {"server": srv, "httpd": httpd, "endpoint": ep}

        dreps = {f"r{i}": _dep_replica() for i in range(2)}
        drouter = Router(endpoints={rid: rep["endpoint"]
                                    for rid, rep in dreps.items()},
                         affinity="prefix", page_size=4,
                         scrape_interval=0.1, queue_timeout=10.0,
                         queue_poll=0.02, drain_timeout=5.0).start()
        try:
            # compile + warm — the same request shape as the burst,
            # twice: the first caches its prefix pages, the second's
            # prefix hit resolves the CoW copy_page executable, so
            # nothing is left to compile during the rollout
            drouter.generate([1, 2, 3], 4)
            drouter.generate([1, 2, 3], 4)
            dl = time.monotonic() + 5
            while time.monotonic() < dl and any(
                    s.last_scrape == 0 for s in
                    drouter.balancer.replicas().values()):
                time.sleep(0.05)

            def _restart(rid):
                old = dreps[rid]
                old["httpd"].shutdown()
                old["httpd"].server_close()
                old["server"].shutdown(drain=True, timeout=30)
                dreps[rid] = _dep_replica()
                return {"endpoint": dreps[rid]["endpoint"]}

            # max_compiles=0: with the warm-start plane live, a whole
            # rolling restart must not compile ANYTHING (ISSUE 18) —
            # the gate keeps rollout_compiles pinned at zero
            roll = RollingDeploy(drouter, _restart, watchdog=_Watch(),
                                 settle_timeout=30.0, max_compiles=0)
            deploy_out = {}

            def _run_deploy():
                deploy_out.update(roll.run())

            dt = _th.Thread(target=_run_deploy, daemon=True,
                            name="pt-bench-deploy")
            t0 = time.perf_counter()
            dt.start()

            def _one(i):
                res = drouter.generate([1 + i % 5, 2, 3], 4)
                assert len(res.tokens) == 4
                return res
            results, errors = FaultPlan.burst(_one, n=24, threads=4,
                                              timeout=120)
            dt.join(timeout=60)
            wall_ms = (time.perf_counter() - t0) * 1e3
            out["fleet_deploy"] = {
                "failed_requests": sum(e is not None for e in errors),
                "requests": sum(r is not None for r in results),
                "deploy_steps": len(deploy_out.get("steps", [])),
                "deploy_complete": int(
                    deploy_out.get("status") == "complete"),
                "deploy_wall_ms": round(wall_ms, 3),
                # 99 (not 0) when the deploy thread died: losing the
                # measurement must FAIL the count gate, not pass it
                "rollout_compiles": deploy_out.get(
                    "rollout_compiles", 99),
            }
        finally:
            drouter.shutdown(drain=True, timeout=10)
            for rep in dreps.values():
                rep["httpd"].shutdown()
                rep["httpd"].server_close()
                rep["server"].shutdown(drain=True, timeout=30)

    if "fleet_autoscale" in rows:
        # ISSUE 16 tentpole leg (a), info row: the hysteresis policy
        # replayed over the canonical seeded bursty trace (same replay
        # as tests/test_autopilot.py) — decision counts and how many
        # ticks the shed spike takes to turn into a spawn decision.
        from paddle_tpu.fleet.autopilot import AutopilotPolicy
        from paddle_tpu.testing import FaultPlan

        trace = FaultPlan.bursty_trace(seed=0, ticks=30)
        pol = AutopilotPolicy(min_replicas=1, max_replicas=2,
                              up_cooldown_s=2.0, down_cooldown_s=3.0,
                              down_stable_s=2.0)
        live, ups, downs, first_up = 1, 0, 0, None
        burst_edge = 8                       # bursty_trace burst_start
        for t, load in enumerate(trace):
            shed = max(0, load - 4 * live)
            sig = {"replicas_live": live, "shed_rate": float(shed),
                   "headroom_frac": 0.9 if shed == 0 else 0.2,
                   "headroom_trend_per_s": 0.0, "slo_breaches": 0}
            d = pol.decide(sig, float(t))
            if d is None:
                continue
            if d["action"] == "scale_up":
                ups += 1
                live += 1
                if first_up is None:
                    first_up = t
            else:
                downs += 1
                live -= 1
        out["fleet_autoscale"] = {
            "scale_ups": ups,
            "scale_downs": downs,
            "decisions": ups + downs,
            "ticks_to_scale_up": (first_up - burst_edge
                                  if first_up is not None else -1),
            "final_replicas": live,
        }

    if "router_ha" in rows:
        # ISSUE 16 tentpole leg (c): N independent router planes must
        # agree on cold-prompt placement (rendezvous over the stable
        # first-page key — no shared state). placement_agreement is
        # RATE-gated at >= 0.9 in BENCH_SMOKE_BASELINE.json: the HA
        # property a client retry on a sibling router depends on.
        from paddle_tpu.fleet import FleetBalancer

        planes = []
        for _ in range(2):
            bal = FleetBalancer(affinity="prefix", page_size=4)
            for i in range(3):
                bal.upsert(f"r{i}", f"http://bench:{i}")
                bal.record_scrape(f"r{i}", kv_pages_total=64,
                                  kv_pages_free=64, page_size=4)
            planes.append(bal)
        rng = np.random.RandomState(11)
        agree = total = 0
        homes = set()
        for _ in range(64):
            plen = int(rng.randint(6, 20))
            prompt = [int(v) for v in rng.randint(2, 40, (plen,))]
            picks = [b.choose(prompt, plen + 4)[0] for b in planes]
            total += 1
            agree += int(picks[0] == picks[1])
            homes.add(picks[0])
        out["router_ha"] = {
            "placement_agreement": round(agree / total, 4),
            "prompts": total,
            "replicas_spread": len(homes),
        }

    if "soak_smoke" in rows:
        # ISSUE 17 tentpole: a seconds-bounded seeded soak (mixed
        # CTR + chat, replica-kill + shard-kill fault families) whose
        # verdict counters gate CORRECTNESS, not speed: settle
        # duplicates/losses and verdict failures are count-gated at 0
        # slack in BENCH_SMOKE_BASELINE.json — one duplicated settle
        # anywhere in the fleet fails the perf gate. ttft_p99 is
        # latency-gated loosely (first streams pay XLA compile).
        from paddle_tpu.loadgen import run_soak

        report = run_soak(seed=11, duration_s=3.0, workload="mixed",
                          families="po")
        eo = report["checks"]["exactly_once"]
        ttft = report["checks"]["latency_slo"]["ttft_p99_ms"]
        out["soak_smoke"] = {
            "verdict_failures": int(not report["ok"]),
            "settle_dups": len(eo["duplicates"]),
            "settle_lost": len(eo["lost"]),
            "ttft_p99_ms": round(float(ttft), 3)
            if ttft is not None else 1e9,
            "requests": report["counts"]["requests"],
            "faults_injected": report["counts"]["faults"],
        }

    if "kv_capacity_multiplier" in rows:
        # ISSUE 20 tentpole leg (a): int8 pages with per-row scales
        # must hold >= 2x the KV tokens per HBM byte of the fp32 pools.
        # tokens_per_byte_x is RATE-gated at >= 2.0 (0.75x of the
        # 4*dh/(dh+4) = 2.67 analytic value at dh=8) — deterministic,
        # computed from the engines' REAL pool buffers, not the
        # formula. effective_pages adds the spill tier on top.
        from paddle_tpu.serving import DecodeEngine
        e32 = DecodeEngine(_smoke_decoder(), num_slots=2, page_size=4,
                           max_seq_len=32)
        e8 = DecodeEngine(_smoke_decoder(), num_slots=2, page_size=4,
                          max_seq_len=32, kv_quant="int8",
                          kv_spill_pages=16)
        b32, b8 = e32.paged.pool_bytes(), e8.paged.pool_bytes()
        acc = e8.page_accounting()
        out["kv_capacity_multiplier"] = {
            "tokens_per_byte_x": round(b32 / b8, 3),
            "fp32_pool_bytes": b32,
            "int8_pool_bytes": b8,
            "device_pages": acc["total_usable"],
            "effective_pages": acc["total_usable"]
            + acc["spill_capacity"],
        }

    if "kv_dequant_overhead" in rows:
        # the dequant read path's decode-throughput cost: int8 vs fp32
        # over identical engines and prompts. throughput_ratio is
        # ratio-gated (rate, loose floor) — it catches the dequant
        # path falling off a cliff, not CPU timing noise.
        from paddle_tpu.serving import DecodeEngine

        def _toks_per_s(kv_quant):
            eng = DecodeEngine(_smoke_decoder(), num_slots=2,
                               page_size=4, max_seq_len=32,
                               kv_quant=kv_quant)
            rng = np.random.RandomState(3)
            prompts = [[int(t) for t in rng.randint(0, 40, 6)]
                       for _ in range(4)]
            warm = eng.submit(prompts[0], 2)   # compile prefill + step
            eng.run(timeout=300)
            warm.get(timeout=1)
            t0 = time.perf_counter()
            reqs = [eng.submit(p, 8) for p in prompts]
            eng.run(timeout=300)
            toks = sum(len(r.get(timeout=1)) for r in reqs)
            return toks / (time.perf_counter() - t0)

        f32 = _toks_per_s(None)
        i8 = _toks_per_s("int8")
        out["kv_dequant_overhead"] = {
            "throughput_ratio": round(i8 / f32, 3),
            "fp32_toks_per_s": round(f32, 2),
            "int8_toks_per_s": round(i8, 2),
        }

    if "kv_restore_latency" in rows:
        # ISSUE 20 tentpole leg (b), info row: cost of bringing a
        # spilled prefix back from the host store on a revisit —
        # end-to-end revisit wall time and the per-page restore share.
        from paddle_tpu.serving import DecodeEngine
        from paddle_tpu.testing import FaultPlan as _FPk
        eng = DecodeEngine(_smoke_decoder(), num_slots=2, page_size=4,
                           max_seq_len=20, num_pages=9,
                           kv_spill_pages=16)
        plan = _FPk(seed=5)
        # revisit_from past the last wave: the storm only spills, so
        # the store still holds the early prompts' pages afterwards
        schedule, submitted = plan.spill_storm(
            eng, waves=4, per_wave=2, gap=4, prompt_len=8, max_new=3,
            vocab=40, revisit_from=4)
        with _FPk.decode_script(eng, schedule):
            eng.run(timeout=300)
        acc0 = eng.page_accounting()
        p0 = submitted[0][1]
        t0 = time.perf_counter()
        req = eng.submit(p0, 3)
        eng.run(timeout=300)
        req.get(timeout=1)
        dt_ms = (time.perf_counter() - t0) * 1e3
        acc1 = eng.page_accounting()
        restored = acc1["spill_restores"] - acc0["spill_restores"]
        out["kv_restore_latency"] = {
            "revisit_ms": round(dt_ms, 3),
            "restored_pages": restored,
            "restore_ms_per_page": round(dt_ms / max(restored, 1), 3),
        }
    return {"v": 1, "suite": "smoke", "rows": out}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--suite", default="all",
                    choices=["headline", "all", "smoke"])
    ap.add_argument("--dtype", default="bfloat16",
                    choices=["bfloat16", "float32"])
    ap.add_argument("--iters", type=int, default=20)
    ap.add_argument("--out", default=None,
                    help="also write the result JSON to this path "
                         "(the smoke tier's hand-off to "
                         "tools/bench_gate.py)")
    args = ap.parse_args()

    if args.suite == "smoke":
        # CPU smoke tier: f32, tiny shapes, count metrics — the perf
        # regression gate's input (tools/bench_gate.py)
        res = bench_smoke()
        blob = json.dumps(res)
        print(blob)
        if args.out:
            with open(args.out, "w") as f:
                f.write(blob + "\n")
        return 0

    import jax
    import paddle_tpu as paddle
    paddle.init(compute_dtype=args.dtype)
    dev = jax.devices()[0]

    # rows whose device step is faster than the tunnel can dispatch:
    # the recorded ms is a DISPATCH floor, not a device number
    # (docs/perf.md "Small-model floors" — smallnet ~0.30 ms on-device,
    # lstm h256 ~0.25 ms; the tunnel reads 1.6-4.5 / ~2 ms)
    FLOOR_ROWS = {"smallnet_bs128", "lstm_bs64_h256"}

    def _emit(name, res):
        b = BASELINES_MS.get(name)
        res = dict(res)
        if name in FLOOR_ROWS:
            res["floor"] = True
        if b and res["ms"] > 0:
            res["vs_baseline"] = round(b / res["ms"], 3)
            lo, hi = res.get("min"), res.get("max")
            if lo and hi and (hi - lo) > 0.4 * res["ms"]:
                # spread past +-20%: self-describe the range so a
                # downstream reader never quotes the scalar alone
                res["vs_baseline_range"] = [round(b / hi, 3),
                                            round(b / lo, 3)]
        print(json.dumps({"bench": name, **res}), file=sys.stderr)
        return res

    def _row(name, thunk, retries=2):
        """One suite row, retried on transient failure. The tunneled TPU's
        compile RPC can reset mid-suite ("response body closed"); a flaky
        row must cost a retry, not the whole artifact."""
        err = None
        for attempt in range(retries + 1):
            try:
                return _emit(name, thunk())
            except Exception as e:  # noqa: BLE001 — record and move on
                err = e
                print(json.dumps({"bench": name, "attempt": attempt,
                                  "error": str(e)[:300]}), file=sys.stderr)
        return {"ms": -1.0, "error": str(err)[:300]}

    suite = {}
    suite["alexnet_bs128"] = _row(
        "alexnet_bs128",
        lambda: bench_image("alexnet_bs128", 128, iters=args.iters))

    if args.suite == "all":
        half = max(args.iters // 2, 5)
        suite["alexnet_bs512"] = _row(
            "alexnet_bs512",
            lambda: bench_image("alexnet_bs512", 512, iters=half))
        suite["smallnet_bs128"] = _row(
            "smallnet_bs128",
            lambda: bench_image("smallnet_bs128", 128, iters=args.iters))
        suite["googlenet_bs128"] = _row(
            "googlenet_bs128",
            lambda: bench_image("googlenet_bs128", 128, iters=half))
        suite["resnet50_bs128"] = _row(
            "resnet50_bs128",
            lambda: bench_image("resnet50_bs128", 128, iters=half))
        suite["resnet50_bs128_tpustem"] = _row(
            "resnet50_bs128_tpustem",
            lambda: bench_image("resnet50_bs128_tpustem", 128, iters=half))
        suite["vgg16_bs128"] = _row(
            "vgg16_bs128",
            lambda: bench_image("vgg16_bs128", 128, iters=half))
        suite["lstm_bs64_h256"] = _row(
            "lstm_bs64_h256", lambda: bench_lstm(64, 256, iters=args.iters))
        suite["lstm_bs128_h1280"] = _row(
            "lstm_bs128_h1280", lambda: bench_lstm(128, 1280, iters=half))
        suite["flash_attention_t4096"] = _row(
            "flash_attention_t4096", lambda: bench_flash_attention(iters=half))
        suite["transformer_lm_bs8_t1024"] = _row(
            "transformer_lm_bs8_t1024", lambda: bench_transformer(iters=half))
        # batch sweep anchors the claim "throughput scales with batch
        # until cache reads saturate HBM" (docs/perf.md)
        suite["decode_bs1_512tok"] = _row(
            "decode_bs1_512tok", lambda: bench_decode(batch=1))
        suite["decode_bs8_512tok"] = _row(
            "decode_bs8_512tok", lambda: bench_decode())
        suite["decode_bs32_512tok"] = _row(
            "decode_bs32_512tok", lambda: bench_decode(batch=32))
        # beyond-parity rows, driver-captured so regressions are visible
        # (VERDICT r4: the GQA/MoE claims lived only in dev captures)
        suite["decode_bs32_gqa"] = _row(
            "decode_bs32_gqa",
            lambda: bench_decode(batch=32, n_kv_heads=2))
        # continuous-batching engine rows (paged KV cache, ragged
        # workload — serving/engine.py): the roofline_frac here is
        # against the PAGED floor (actual cache lengths), the
        # ROADMAP item-1 target of < 1.3 across bs 1/8/32
        suite["decode_continuous_bs1"] = _row(
            "decode_continuous_bs1",
            lambda: bench_decode_continuous(num_slots=1, n_requests=6,
                                            new_tokens=(64, 128)))
        suite["decode_continuous_bs8"] = _row(
            "decode_continuous_bs8",
            lambda: bench_decode_continuous())
        suite["decode_continuous_bs32"] = _row(
            "decode_continuous_bs32",
            lambda: bench_decode_continuous(num_slots=32,
                                            n_requests=96))
        suite["decode_continuous_bs32_gqa"] = _row(
            "decode_continuous_bs32_gqa",
            lambda: bench_decode_continuous(num_slots=32,
                                            n_requests=96,
                                            n_kv_heads=2))
        suite["moe_lm_bs8_t1024"] = _row(
            "moe_lm_bs8_t1024", lambda: bench_moe_lm(iters=half))

    head_name = "alexnet_bs128"
    head = suite[head_name]
    if head.get("ms", -1) <= 0:  # headline row lost to a persistent flake:
        # fall back to another successful row, RENAMING the metric so a
        # consumer never records a different benchmark under the alexnet
        # label; if nothing succeeded, exit non-zero with a null value.
        head_name, head = next(
            ((n, r) for n, r in suite.items() if r.get("ms", -1) > 0),
            (head_name, head))
    ok = head.get("ms", -1) > 0
    print(json.dumps({
        "metric": f"{head_name}_train_ms_per_batch",
        "value": head["ms"] if ok else None,
        "unit": "ms/batch",
        "vs_baseline": head.get("vs_baseline"),
        "dtype": args.dtype,
        "device": getattr(dev, "device_kind", str(dev)),
        "suite": suite,
        # BASELINE.json metric: ResNet-50 samples/sec/chip >= V100
        # use_gpu throughput (~400 f32 / ~900 mixed samples/s); the row
        # runs under --suite all (the default)
        "north_star": {
            "resnet50_samples_per_sec_per_chip":
                suite.get("resnet50_bs128", {}).get("samples_per_sec"),
            "target": ">= V100 use_gpu throughput (BASELINE.json)",
        } if "resnet50_bs128" in suite else {
            "note": "run --suite all for the resnet50 north-star row"},
        "skipped": {k: "needs multi-chip slice" for k in MULTICHIP_ROWS},
    }))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
