"""Benchmark harness — prints ONE JSON line for the driver.

Headline metric: AlexNet bs=128 fwd+bwd+update ms/batch, the reference's
own flagship number (benchmark/README.md:37 — 334 ms/batch on a K40m,
measured by `paddle train --job=time`, parameter update included).
vs_baseline = baseline_ms / our_ms (>1 means faster than the reference).

Extra suites (`python bench.py --suite all`) mirror the rest of the
reference table (SmallNet, GoogleNet, LSTM) and the ResNet-50 north-star;
each extra prints one JSON line to STDERR so stdout always carries exactly
the single headline line the driver expects.
"""

from __future__ import annotations

import argparse
import json
import sys
import time

import numpy as np

BASELINES_MS = {
    # reference benchmark/README.md (1xK40m, ms/batch, update included)
    "alexnet_bs128": 334.0,
    "alexnet_bs512": 1629.0,
    "smallnet_bs128": 18.184,
    "googlenet_bs128": 1149.0,
    "lstm_bs64_h256": 83.0,
    "lstm_bs128_h1280": 1007.0,
    "resnet50_bs128": None,  # no reference number exists (BASELINE.md note)
}


def _slope_time(step, carry, extra, iters, warmup):
    """Update-inclusive ms/batch via slope timing: run N and 2N chained
    steps (each chain ends in ONE device->host readback of the loss, the
    only sync every transport honors) and take (T2N - TN)/N. The
    difference cancels the constant sync/transport latency, which on a
    tunneled TPU (~100 ms RTT) would otherwise dominate; the chain itself
    serializes on-device because each step consumes the previous step's
    params. Mirrors paddle --job=time (update time included)."""
    feed, key, n_real = extra
    p, o, s = carry

    def chain(n):
        nonlocal p, o, s
        t0 = time.perf_counter()
        for _ in range(n):
            p, o, s, loss, _ = step(p, o, s, feed, key, n_real)
        float(loss)
        return (time.perf_counter() - t0) * 1000.0

    for _ in range(warmup):
        chain(1)
    n = max(iters // 2, 2)
    t1 = chain(n)
    t2 = chain(2 * n)
    return max((t2 - t1) / n, 1e-6)


def _build(name):
    from paddle_tpu import models
    if name.startswith("alexnet"):
        return models.alexnet(), 227 * 227 * 3, 1000
    if name.startswith("smallnet"):
        return models.smallnet(), 32 * 32 * 3, 10
    if name.startswith("googlenet"):
        return models.googlenet(), 224 * 224 * 3, 1000
    if name.startswith("resnet50"):
        return models.resnet50(), 224 * 224 * 3, 1000
    raise KeyError(name)


def bench_image(name: str, batch: int, iters: int = 20, warmup: int = 3):
    """ms/batch for forward+backward+update of an image model."""
    import jax
    import paddle_tpu as paddle

    spec, in_dim, n_classes = _build(name)
    params = paddle.create_parameters(paddle.Topology(spec.cost))
    trainer = paddle.SGD(
        cost=spec.cost, parameters=params,
        update_equation=paddle.optimizer.Momentum(
            learning_rate=0.01 / batch, momentum=0.9,
            regularization=paddle.optimizer.L2Regularization(0.0005 * batch)))
    rng = np.random.RandomState(0)
    img = rng.randn(batch, in_dim).astype("float32")
    lbl = rng.randint(0, n_classes, (batch,)).astype("int32")
    feed = {spec.data.name: jax.device_put(img),
            spec.label.name: jax.device_put(lbl)}
    import jax.numpy as jnp
    n_real = jnp.asarray(batch, jnp.int32)
    key = jax.random.PRNGKey(0)

    step = trainer._train_step
    p, o, s = trainer.parameters.raw, trainer.opt_state, \
        trainer.parameters.state
    return _slope_time(step, (p, o, s), (feed, key, n_real), iters, warmup)


def bench_lstm(batch: int, hidden: int, seq_len: int = 100,
               vocab: int = 30000, iters: int = 20, warmup: int = 3):
    """IMDB stacked-LSTM benchmark (benchmark/paddle/rnn/rnn.py shape)."""
    import jax
    import jax.numpy as jnp
    import paddle_tpu as paddle
    from paddle_tpu import models
    from paddle_tpu.core.sequence import SequenceBatch

    spec = models.stacked_lstm_net(vocab_size=vocab, emb_size=128,
                                   hidden_size=hidden, lstm_num=1)
    params = paddle.create_parameters(paddle.Topology(spec.cost))
    trainer = paddle.SGD(
        cost=spec.cost, parameters=params,
        update_equation=paddle.optimizer.Adam(learning_rate=2e-3))
    rng = np.random.RandomState(0)
    ids = rng.randint(0, vocab, (batch, seq_len)).astype("int32")
    lengths = np.full((batch,), seq_len, np.int32)
    feed = {spec.data.name: SequenceBatch(jax.device_put(jnp.asarray(ids)),
                                          jax.device_put(jnp.asarray(lengths))),
            spec.label.name: jax.device_put(
                rng.randint(0, 2, (batch,)).astype("int32"))}
    n_real = jnp.asarray(batch, jnp.int32)
    key = jax.random.PRNGKey(0)
    step = trainer._train_step
    p, o, s = trainer.parameters.raw, trainer.opt_state, \
        trainer.parameters.state
    return _slope_time(step, (p, o, s), (feed, key, n_real), iters, warmup)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--suite", default="headline",
                    choices=["headline", "all"])
    ap.add_argument("--iters", type=int, default=20)
    args = ap.parse_args()

    ms = bench_image("alexnet_bs128", 128, iters=args.iters)
    base = BASELINES_MS["alexnet_bs128"]
    print(json.dumps({
        "metric": "alexnet_bs128_train_ms_per_batch",
        "value": round(ms, 3),
        "unit": "ms/batch",
        "vs_baseline": round(base / ms, 3),
    }))

    if args.suite == "all":
        extras = {}
        extras["smallnet_bs128"] = bench_image("smallnet_bs128", 128,
                                               iters=args.iters)
        extras["googlenet_bs128"] = bench_image("googlenet_bs128", 128,
                                                iters=max(args.iters // 2, 5))
        extras["resnet50_bs128"] = bench_image("resnet50_bs128", 128,
                                               iters=max(args.iters // 2, 5))
        extras["lstm_bs64_h256"] = bench_lstm(64, 256, iters=args.iters)
        for k, v in extras.items():
            b = BASELINES_MS.get(k)
            print(json.dumps({
                "metric": f"{k}_train_ms_per_batch", "value": round(v, 3),
                "unit": "ms/batch",
                "vs_baseline": round(b / v, 3) if b else None,
            }), file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
