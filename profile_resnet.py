"""Capture a TPU profile of one image-model train step and print the top
HLO time sinks (the trace-backed breakdown VERDICT asked for)."""

import argparse
import glob
import json
import os
import sys

import numpy as np


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--model", default="resnet50_bs128")
    ap.add_argument("--batch", type=int, default=128)
    ap.add_argument("--steps", type=int, default=3)
    ap.add_argument("--dtype", default="bfloat16")
    ap.add_argument("--out", default="/tmp/jax_trace")
    args = ap.parse_args()

    import jax
    import paddle_tpu as paddle
    paddle.init(compute_dtype=args.dtype)
    import bench

    spec, in_dim, n_classes = bench._build(args.model)
    params = paddle.create_parameters(paddle.Topology(spec.cost))
    trainer = paddle.SGD(
        cost=spec.cost, parameters=params,
        update_equation=paddle.optimizer.Momentum(
            learning_rate=0.01 / args.batch, momentum=0.9))
    rng = np.random.RandomState(0)
    img = rng.randn(args.batch, in_dim).astype("float32")
    lbl = rng.randint(0, n_classes, (args.batch,)).astype("int32")
    feed = {spec.data.name: jax.device_put(img),
            spec.label.name: jax.device_put(lbl)}
    import jax.numpy as jnp
    n_real = jnp.asarray(args.batch, jnp.int32)
    key = jax.random.PRNGKey(0)
    p, o, s = (trainer.parameters.raw, trainer.opt_state,
               trainer.parameters.state)
    compiled = trainer._train_step.lower(p, o, s, feed, key, n_real).compile()
    # warmup
    for _ in range(2):
        p, o, s, *rest = compiled(p, o, s, feed, key, n_real)
    jax.block_until_ready(rest)

    os.makedirs(args.out, exist_ok=True)
    with jax.profiler.trace(args.out):
        for _ in range(args.steps):
            p, o, s, *rest = compiled(p, o, s, feed, key, n_real)
        jax.block_until_ready(rest)

    xs = sorted(glob.glob(os.path.join(args.out, "**", "*.xplane.pb"),
                          recursive=True), key=os.path.getmtime)
    print("xplane:", xs[-1] if xs else "NONE")


if __name__ == "__main__":
    main()
