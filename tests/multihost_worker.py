"""Worker for the REAL two-process multi-host test.

Launched twice by tests/test_multihost.py (process_id 0 and 1); forms an
actual jax.distributed process group over localhost (the in-process
cluster discipline of the reference's test_ParameterServer2.cpp /
test_CompareSparse.cpp, but with OS processes), then runs two dp training
steps where each process feeds its own data shard and the global batch is
assembled with multihost.global_batch. Prints one line per step:
``STEP <i> <loss>`` — the parent asserts both processes printed the same
losses.

Usage: python multihost_worker.py <coordinator_port> <process_id> [mode]
mode: "sync" (default, dp over a global mesh) or "async" (local-SGD
islands: each process trains alone, reconciling by parameter averaging
every few steps — parallel/async_sgd.py, the pserver asyncSGD parity).
"""

import os
import sys

os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "") +
                           " --xla_force_host_platform_device_count=4")
os.environ["JAX_PLATFORMS"] = "cpu"

import jax                                    # noqa: E402
import jax.numpy as jnp                       # noqa: E402
import numpy as np                            # noqa: E402

jax.config.update("jax_platforms", "cpu")
# cross-process collectives on the CPU backend need the gloo transport;
# without it process_allgather raises "Multiprocess computations aren't
# implemented on the CPU backend" the moment the group forms
jax.config.update("jax_cpu_collectives_implementation", "gloo")


def main():
    port, pid = int(sys.argv[1]), int(sys.argv[2])
    mode = sys.argv[3] if len(sys.argv) > 3 else "sync"
    from paddle_tpu.parallel import (global_batch, init_distributed,
                                     is_coordinator, process_reader)
    from paddle_tpu.parallel.mesh import DP_AXIS, batch_sharding, create_mesh

    pi, pc = init_distributed(f"localhost:{port}", num_processes=2,
                              process_id=pid)
    assert (pi, pc) == (pid, 2), (pi, pc)
    assert is_coordinator() == (pid == 0)
    assert len(jax.devices()) == 8, jax.devices()

    if mode == "async":
        return async_main(pid)

    mesh = create_mesh([(DP_AXIS, 8)])
    sharding = batch_sharding(mesh)

    # identical global stream on both processes; each keeps its own half
    rng = np.random.RandomState(0)
    xs = rng.randn(16, 4).astype(np.float32)
    ys = rng.randn(16, 1).astype(np.float32)

    def reader():
        for i in range(16):
            yield xs[i], ys[i]

    local = list(process_reader(reader, pi, pc)())
    assert len(local) == 8

    w = jnp.zeros((4, 1), jnp.float32)

    from jax.sharding import NamedSharding, PartitionSpec as P
    rep = NamedSharding(mesh, P())

    @jax.jit
    def step(w, x, y):
        def loss_fn(w):
            return jnp.mean((x @ w - y) ** 2)
        loss, g = jax.value_and_grad(loss_fn)(w)
        return w - 0.1 * g, loss

    w = jax.device_put(w, rep)
    for it in range(2):
        # same batch both steps so the loss provably decreases
        xl = np.stack([s[0] for s in local[:4]])
        yl = np.stack([s[1] for s in local[:4]])
        x = global_batch(xl, mesh, sharding.spec)
        y = global_batch(yl, mesh, sharding.spec)
        w, loss = step(w, x, y)
        print(f"STEP {it} {float(loss):.10f}", flush=True)

    jax.distributed.shutdown()


def async_main(pid):
    """Local-SGD islands across two real processes: train independently
    on different shards, reconcile every 4 steps via average_pytree."""
    from paddle_tpu.parallel import average_pytree
    rng = np.random.RandomState(100 + pid)     # DIFFERENT data per island
    w_true = np.random.RandomState(9).randn(4, 1).astype(np.float32)
    w = jnp.zeros((4, 1), jnp.float32)

    @jax.jit
    def step(w, x, y):
        def loss_fn(w):
            return jnp.mean((x @ w - y) ** 2)
        loss, g = jax.value_and_grad(loss_fn)(w)
        return w - 0.2 * g, loss

    first = last = None
    for it in range(12):
        x = jnp.asarray(rng.randn(32, 4).astype(np.float32))
        y = x @ jnp.asarray(w_true)
        w, loss = step(w, x, y)
        if first is None:
            first = float(loss)
        last = float(loss)
        if (it + 1) % 4 == 0:
            w = average_pytree(w)
            # after reconciliation both islands hold identical weights
            print(f"SYNCW {it} {float(jnp.sum(jnp.abs(w))):.8f}",
                  flush=True)
    print(f"STEP 0 {first:.10f}", flush=True)
    print(f"STEP 1 {last:.10f}", flush=True)
    jax.distributed.shutdown()


if __name__ == "__main__":
    main()
