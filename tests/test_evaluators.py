"""Evaluator framework tests — gserver/evaluators parity
(Evaluator.h:42, ChunkEvaluator.cpp, CTCErrorEvaluator.cpp).

Unit tests pin each metric against a hand-computed / exact-numpy value;
integration tests run the VERDICT exit criteria: the CTR model reporting
AUC and the CRF tagger reporting chunk-F1 through SGD.train/test."""

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import evaluator as E


class _FakeLayer:
    def __init__(self, name):
        self.name = name


def _exact_auc(score, label):
    """Exact pairwise ROC AUC via rank statistics."""
    pos = score[label == 1]
    neg = score[label == 0]
    gt = (pos[:, None] > neg[None, :]).sum()
    eq = (pos[:, None] == neg[None, :]).sum()
    return (gt + 0.5 * eq) / (len(pos) * len(neg))


class TestAuc:
    def test_matches_exact(self):
        rng = np.random.RandomState(0)
        score = rng.rand(4000)
        label = (rng.rand(4000) < score).astype(np.int64)  # informative
        ev = E.auc(_FakeLayer("s"), _FakeLayer("l"))
        # stream in four batches
        for i in range(0, 4000, 1000):
            ev.eval_batch([score[i:i + 1000], label[i:i + 1000]], 1000)
        got = ev.result()["auc"]
        want = _exact_auc(score, label)
        assert abs(got - want) < 2e-3

    def test_two_column_softmax_input(self):
        ev = E.auc(_FakeLayer("s"), _FakeLayer("l"))
        probs = np.array([[0.9, 0.1], [0.2, 0.8], [0.4, 0.6], [0.7, 0.3]])
        label = np.array([0, 1, 1, 0])
        ev.eval_batch([probs, label], 4)
        assert ev.result()["auc"] == 1.0    # perfectly separable

    def test_start_resets(self):
        ev = E.auc(_FakeLayer("s"), _FakeLayer("l"))
        ev.eval_batch([np.array([0.9, 0.1]), np.array([0, 1])], 2)
        assert ev.result()["auc"] == 0.0    # inverted
        ev.start()
        ev.eval_batch([np.array([0.9, 0.1]), np.array([1, 0])], 2)
        assert ev.result()["auc"] == 1.0


class TestPrecisionRecall:
    def test_binary_counts(self):
        ev = E.precision_recall(_FakeLayer("p"), _FakeLayer("l"),
                                positive_label=1)
        pred = np.array([1, 1, 1, 0, 0, 0])
        label = np.array([1, 1, 0, 0, 0, 1])
        ev.eval_batch([pred, label], 6)
        r = ev.result()
        assert r["precision_recall_precision"] == pytest.approx(2 / 3)
        assert r["precision_recall_recall"] == pytest.approx(2 / 3)
        assert r["precision_recall_f1"] == pytest.approx(2 / 3)

    def test_probs_argmaxed(self):
        ev = E.precision_recall(_FakeLayer("p"), _FakeLayer("l"),
                                positive_label=1)
        probs = np.array([[0.1, 0.9], [0.8, 0.2]])
        ev.eval_batch([probs, np.array([1, 0])], 2)
        assert ev.result()["precision_recall_f1"] == 1.0


class TestChunk:
    # IOB encoding with 2 chunk types: id = type*2 + tag (B=0, I=1), O=4
    def test_extract_chunks_iob(self):
        #            B0 I0 O  B1 I1 I1 O  B0
        ids = np.array([0, 1, 4, 2, 3, 3, 4, 0])
        chunks = E.extract_chunks(ids, "IOB", 2)
        assert chunks == [(0, 1, 0), (3, 5, 1), (7, 7, 0)]

    def test_extract_chunks_iob_b_restarts(self):
        # B0 B0 I0 -> two chunks (B begins a new chunk)
        assert E.extract_chunks(np.array([0, 0, 1]), "IOB", 2) == \
            [(0, 0, 0), (1, 2, 0)]

    def test_extract_chunks_iobes(self):
        # IOBES 1 type: B=0 I=1 E=2 S=3, O=4
        ids = np.array([0, 1, 2, 4, 3])
        assert E.extract_chunks(ids, "IOBES", 1) == [(0, 2, 0), (4, 4, 0)]

    def test_extract_chunks_iobes_e_after_e(self):
        # malformed-but-common model output: E right after E starts a new
        # chunk (ChunkEvaluator begin-of-chunk rule); no (None,...) tuples
        chunks = E.extract_chunks(np.array([0, 2, 2]), "IOBES", 1)
        assert chunks == [(0, 1, 0), (2, 2, 0)]
        # trailing I after E is an (unclosed) chunk, not dropped
        chunks = E.extract_chunks(np.array([0, 2, 1]), "IOBES", 1)
        assert chunks == [(0, 1, 0), (2, 2, 0)]

    def test_f1(self):
        ev = E.chunk(_FakeLayer("p"), _FakeLayer("l"),
                     chunk_scheme="IOB", num_chunk_types=2)
        gold = np.array([[0, 1, 4, 2, 3, 3]])       # chunks (0,1,t0) (3,5,t1)
        pred = np.array([[0, 1, 4, 2, 3, 4]])       # (0,1,t0) (3,4,t1): 1 hit
        lengths = np.array([6])
        ev.eval_batch([(pred, lengths), (gold, lengths)], 1)
        r = ev.result()
        assert r["chunk_precision"] == pytest.approx(0.5)
        assert r["chunk_recall"] == pytest.approx(0.5)
        assert r["chunk_f1"] == pytest.approx(0.5)


class TestCTCError:
    def test_edit_distance(self):
        assert E.edit_distance([1, 2, 3], [1, 2, 3]) == 0
        assert E.edit_distance([1, 2, 3], [1, 3]) == 1       # delete
        assert E.edit_distance([1, 2], [1, 2, 3]) == 1       # insert
        assert E.edit_distance([1, 2, 3], [1, 4, 3]) == 1    # substitute
        assert E.edit_distance([], [1, 2]) == 2

    def test_best_path_decode_and_rate(self):
        # 3 classes + blank(id 3); frames argmax: [1,1,3,2,2] -> [1,2]
        frames = np.zeros((1, 5, 4), np.float32)
        for t, c in enumerate([1, 1, 3, 2, 2]):
            frames[0, t, c] = 1.0
        flens = np.array([5])
        gold = np.array([[1, 2]])
        glens = np.array([2])
        ev = E.ctc_error(_FakeLayer("p"), _FakeLayer("l"), blank=3)
        ev.eval_batch([(frames, flens), (gold, glens)], 1)
        assert ev.result()["ctc_error"] == 0.0
        ev.start()
        gold2 = np.array([[1, 1]])                   # one substitution
        ev.eval_batch([(frames, flens), (gold2, glens)], 1)
        assert ev.result()["ctc_error"] == pytest.approx(0.5)


class TestPairMetrics:
    def test_pnpair(self):
        ev = E.pnpair(_FakeLayer("s"), _FakeLayer("l"), _FakeLayer("q"))
        score = np.array([0.9, 0.1, 0.3, 0.8])
        label = np.array([1, 0, 0, 1])
        qid = np.array([0, 0, 1, 1])
        ev.eval_batch([score, label, qid], 4)
        r = ev.result()
        assert r["pnpair_pos"] == 2.0 and r["pnpair_neg"] == 0.0

    def test_rank_auc(self):
        ev = E.rank_auc(_FakeLayer("s"), _FakeLayer("l"), _FakeLayer("q"))
        score = np.array([0.9, 0.1, 0.2, 0.8])
        label = np.array([1, 0, 1, 0])               # q1 inverted
        qid = np.array([0, 0, 1, 1])
        ev.eval_batch([score, label, qid], 4)
        assert ev.result()["rank_auc"] == pytest.approx(0.5)


class TestSums:
    def test_sum(self):
        ev = E.sum_evaluator(_FakeLayer("v"))
        ev.eval_batch([np.ones((3, 2))], 2)          # only 2 real rows
        assert ev.result()["sum"] == 4.0

    def test_column_sum(self):
        ev = E.column_sum(_FakeLayer("v"), column=1)
        ev.eval_batch([np.array([[1., 2.], [3., 4.]])], 2)
        assert ev.result()["column_sum"] == 6.0

    def test_printer_no_metrics(self, capsys):
        ev = E.value_printer(_FakeLayer("v"), name="dbg")
        ev.eval_batch([np.array([1.0])], 1)
        assert "dbg" in capsys.readouterr().out
        assert ev.result() == {}


# ---------------------------------------------------------------------------
# integration: the VERDICT exit criteria


def _ctr_reader(rng, n=64, dense_dim=4, dims=(50, 50, 20)):
    def reader():
        batch = []
        for _ in range(n):
            ids = [int(rng.randint(d)) for d in dims]
            dense = rng.randn(dense_dim).astype("float32")
            label = int(ids[0] % 2)                  # learnable signal
            # feed order follows topology.data_type(): sparse_*, dense, label
            batch.append((*ids, dense, label))
        yield batch
    return reader


class TestIntegration:
    def test_ctr_model_reports_auc(self):
        from paddle_tpu import models as M
        spec = M.wide_and_deep(sparse_dims=(50, 50, 20), dense_dim=4,
                               emb_size=8, hidden_sizes=(16, 8))
        lbl = _FakeLayer("label")
        ev = E.auc(spec.output, lbl)
        params = paddle.create_parameters(paddle.Topology(spec.cost))
        tr = paddle.SGD(cost=spec.cost, parameters=params,
                        update_equation=paddle.optimizer.Adam(
                            learning_rate=5e-3),
                        evaluators=[ev])
        rng = np.random.RandomState(0)
        seen = []
        tr.train(_ctr_reader(rng, n=256), num_passes=40,
                 event_handler=lambda e: seen.append(e.metrics.get("auc"))
                 if isinstance(e, paddle.event.EndPass) else None)
        assert all(a is not None for a in seen)
        assert seen[-1] > 0.9                        # learned the signal
        res = tr.test(_ctr_reader(np.random.RandomState(1), n=256))
        assert res.metrics["auc"] > 0.85

    def test_crf_tagger_reports_chunk_f1(self):
        from paddle_tpu import models as M
        # IOB, 2 chunk types -> 5 labels; tiny model
        spec = M.crf_tagger(vocab_size=30, num_labels=5, emb_size=8,
                            hidden_size=16, context_len=3)
        labels_layer = _FakeLayer("labels")
        ev = E.chunk(spec.decoded, labels_layer, chunk_scheme="IOB",
                     num_chunk_types=2)
        params = paddle.create_parameters(paddle.Topology(spec.cost))
        tr = paddle.SGD(cost=spec.cost, parameters=params,
                        update_equation=paddle.optimizer.Adam(
                            learning_rate=5e-3),
                        evaluators=[ev])
        rng = np.random.RandomState(0)

        def reader():
            # word i deterministically tagged: even -> B0(0), odd -> O(4)
            batch = []
            for _ in range(16):
                n = rng.randint(3, 7)
                words = rng.randint(0, 30, n)
                tags = [0 if w % 2 == 0 else 4 for w in words]
                batch.append(([int(w) for w in words], tags))
            yield batch

        tr.train(reader, num_passes=30)
        res = tr.test(reader)
        assert "chunk_f1" in res.metrics
        assert res.metrics["chunk_f1"] > 0.9         # learnable rule


def test_typod_evaluator_input_fails_at_construction():
    """A wrong evaluator input name must fail when the SGD is built, not
    as a KeyError deep inside the first jitted step."""
    import pytest as _pytest
    from paddle_tpu.core import registry
    registry.reset_name_counters()
    paddle.init(use_tpu=False, seed=0)
    x = paddle.layer.data("x", paddle.data_type.dense_vector(4))
    out = paddle.layer.fc(x, size=2, act=paddle.activation.Softmax())
    lbl = paddle.layer.data("y", paddle.data_type.integer_value(2))
    cost = paddle.layer.classification_cost(out, lbl)
    params = paddle.create_parameters(paddle.Topology(cost))

    class NameOnly:
        name = "labelz"          # typo: feed layer is "y"
    ev = paddle.evaluator.classification_error(out, lbl)
    ev.inputs = [out, NameOnly()]
    with _pytest.raises(ValueError, match="labelz"):
        paddle.SGD(cost=cost, parameters=params,
                   update_equation=paddle.optimizer.Adam(1e-3),
                   evaluators=[ev])


class TestPrinterFamily:
    """seq_text / max_frame / gradient printers (Evaluator.cpp:1319,
    1142, 1046)."""

    def test_seq_text_printer_decodes_ids(self):
        import io
        buf = io.StringIO()
        ev = E.seq_text_printer(
            _FakeLayer("ids"),
            dict_data=["the", "cat", "sat", "on", "mat"], stream=buf)
        ev.start()
        data = np.array([[1, 2, 3, 0], [4, 0, 0, 0]])
        lengths = np.array([3, 1])
        ev.eval_batch([(data, lengths)], 2)
        lines = buf.getvalue().splitlines()
        assert lines == ["0\tcat sat on", "1\tmat"]
        assert ev.result() == {}
        # sample ids keep counting across batches within a pass
        ev.eval_batch([(data[:1], lengths[:1])], 1)
        assert buf.getvalue().splitlines()[-1] == "2\tcat sat on"

    def test_seq_text_printer_argmax_and_dict_file(self, tmp_path):
        import io
        d = tmp_path / "dict.txt"
        d.write_text("a\nb\nc\n")
        buf = io.StringIO()
        ev = E.seq_text_printer(_FakeLayer("scores"), dict_file=str(d),
                                delimited=False, stream=buf)
        ev.start()
        # [b=1, T=3, C=3] scores -> argmax ids 2,0,1 -> "cab"
        scores = np.array([[[0, 0, 9], [9, 0, 0], [0, 9, 0]]], np.float32)
        ev.eval_batch([(scores, np.array([3]))], 1)
        assert buf.getvalue().splitlines() == ["0\tcab"]

    def test_max_frame_printer(self):
        import io
        buf = io.StringIO()
        ev = E.max_frame_printer(_FakeLayer("s"), stream=buf)
        ev.start()
        data = np.array([[[0.1], [0.9], [0.3]],
                         [[0.5], [0.2], [0.8]]], np.float32)
        lengths = np.array([3, 2])   # seq1's frame 2 is PADDING
        ev.eval_batch([(data, lengths)], 2)
        lines = buf.getvalue().splitlines()
        assert "seq0: frame 1 : 0.9" in lines[0]
        assert "seq1: frame 0 : 0.5" in lines[1]   # 0.8 is past length 2
        with pytest.raises(ValueError):
            ev.eval_batch([np.zeros((2, 3))], 2)   # non-sequence input

    def test_gradient_printer_prints_activation_grad(self):
        """End to end through SGD: for cost = 0.5*sum((xW-y)^2)/n, the
        activation gradient of the output layer is (xW - y)/n."""
        import io
        from paddle_tpu.core import registry
        registry.reset_name_counters()
        paddle.init(use_tpu=False, seed=0)
        x = paddle.layer.data("x", paddle.data_type.dense_vector(3))
        y = paddle.layer.data("y", paddle.data_type.dense_vector(1))
        out = paddle.layer.fc(x, size=1, act=None, bias_attr=False,
                              name="out")
        cost = paddle.layer.mse_cost(out, y)
        buf = io.StringIO()
        ev = E.gradient_printer(out, stream=buf)
        params = paddle.create_parameters(paddle.Topology(cost))
        W = np.array([[0.5], [-1.0], [2.0]], np.float32)
        import jax.numpy as jnp
        params.raw["_out.w0"] = jnp.asarray(W)
        tr = paddle.SGD(cost=cost, parameters=params,
                        update_equation=paddle.optimizer.Momentum(
                            learning_rate=0.0),   # keep W fixed
                        evaluators=[ev])
        rng = np.random.RandomState(0)
        xs = rng.randn(4, 3).astype("float32")
        ys = rng.randn(4, 1).astype("float32")

        def reader():
            yield [(xs[i], ys[i]) for i in range(4)]

        tr.train(reader, num_passes=1, event_handler=lambda e: None)
        txt = buf.getvalue()
        assert "[gradient_printer] grad" in txt
        want = (xs @ W - ys) / 4.0
        got = np.array([float(v) for v in
                        txt.replace("[", " ").replace("]", " ").split()
                        if _is_float(v)][-4:]).reshape(4, 1)
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-6)


def _is_float(s):
    try:
        float(s)
        return True
    except ValueError:
        return False
