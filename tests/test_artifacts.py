"""Warm-start artifact plane (ISSUE 18, docs/robustness.md "Warm
start & artifact integrity").

The contract under test: compiled decode executables round-trip
through the fingerprinted on-disk store and come back WITHOUT tracing
or XLA compilation, token-identical to plain JIT; every way the store
can be wrong — torn frame, flipped payload bytes, internally-
consistent-but-stale fingerprint, unloadable payload, orphaned tmp
from a killed writer, N racing writers — is detected, journaled
(``artifacts/fallback``), counted, and degrades to JIT instead of
crashing the starting replica. Chaos family (r) in
paddle_tpu/testing/faults.py drives the damage.
"""

import json
import os

import jax
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import artifacts as A
from paddle_tpu import models
from paddle_tpu.analysis.sanitizer import compile_watch
from paddle_tpu.artifacts import cache as compile_cache
from paddle_tpu.artifacts.fingerprint import (device_signature,
                                              fingerprint)
from paddle_tpu.artifacts.runtime import ExecutableCache
from paddle_tpu.obs.events import JOURNAL
from paddle_tpu.obs.metrics import REGISTRY
from paddle_tpu.serving.engine import DecodeEngine
from paddle_tpu.testing import FaultPlan

DEC_CFG = dict(vocab_size=40, d_model=16, n_heads=2, n_layers=2,
               d_ff=32, max_len=32)


def tiny_decoder(seed=7):
    paddle.init(use_tpu=False, seed=0)
    from paddle_tpu.core.registry import reset_name_counters
    reset_name_counters()
    spec = models.transformer_lm(**DEC_CFG)
    costs = spec.cost if isinstance(spec.cost, list) else [spec.cost]
    topo = paddle.Topology(costs, extra_outputs=[spec.output])
    params = topo.init_params(jax.random.PRNGKey(seed))
    return models.TransformerDecoder(params,
                                     n_layers=DEC_CFG["n_layers"],
                                     n_heads=DEC_CFG["n_heads"])


@pytest.fixture(scope="module")
def decoder():
    return tiny_decoder()


@pytest.fixture
def store(tmp_path):
    st = A.configure(str(tmp_path / "arts"))
    A.EXECUTABLES.clear()
    yield st
    A.configure(None)
    A.EXECUTABLES.clear()


def _journal(kind=None):
    return JOURNAL.tail(50, domain="artifacts", kind=kind)


def _gauge(name):
    return REGISTRY.gauge(name).value()


@jax.jit
def _toy(x, y):
    return x * 2.0 + y


def _toy_args():
    return (np.arange(4, dtype=np.float32),
            np.ones((4,), np.float32))


def _toy_fp(plan=None):
    return fingerprint("toy", {"w": _toy_args()[0]},
                       plan=plan or {"n": 4})


# ---------------------------------------------------------- fingerprints
class TestFingerprint:
    def test_deterministic_and_sensitive(self, decoder):
        plan = {"num_slots": 2, "page_size": 4}
        a = fingerprint("paged_step", decoder.p, plan=plan)
        b = fingerprint("paged_step", decoder.p, plan=plan)
        assert a == b and a.digest == b.digest
        # plan knobs, kind, and model SHAPES all separate executables
        c = fingerprint("paged_step", decoder.p,
                        plan={"num_slots": 4, "page_size": 4})
        d = fingerprint("draft_step", decoder.p, plan=plan)
        assert len({a.digest, c.digest, d.digest}) == 3
        # values do NOT: params are runtime arguments, not identity
        other = tiny_decoder(seed=11)
        assert fingerprint("paged_step", other.p,
                           plan=plan).digest == a.digest

    def test_env_in_identity(self):
        sig = device_signature()
        assert sig["backend"] and sig["jax"] and sig["jaxlib"]
        fp = _toy_fp()
        assert fp.fields["env"]["backend"] == sig["backend"]
        # round-trips through the frame header
        from paddle_tpu.artifacts.fingerprint import Fingerprint
        again = Fingerprint.from_dict(fp.to_dict())
        assert again == fp


# ---------------------------------------------------------------- store
class TestStore:
    def test_round_trip_and_inspect(self, store):
        fp = _toy_fp()
        payload = b"\x00\x01" * 600
        path = store.put("toy-exe", fp, payload, meta={"build_ms": 3})
        assert store.get("toy-exe", fp) == payload
        assert _gauge("paddle_tpu_artifacts_hits") == 1
        row = store.inspect(path)
        assert row["ok"] and row["digest"] == fp.digest
        assert row["kind"] == "toy" and row["size"] > len(payload)
        assert row["meta"]["build_ms"] == 3 and row["age_s"] >= 0

    def test_missing_is_a_miss_not_a_fallback(self, store):
        assert store.get("nope", _toy_fp()) is None
        assert _gauge("paddle_tpu_artifacts_misses") == 1
        assert _gauge("paddle_tpu_artifacts_fallbacks") == 0

    @pytest.mark.parametrize("mode", ["payload", "torn", "magic"])
    def test_corrupt_artifact_degrades_and_journals(self, store, mode):
        fp = _toy_fp()
        payload = b"payload" * 100
        store.put("toy-exe", fp, payload)
        with FaultPlan.corrupt_artifact(store, mode=mode) as stats:
            assert store.get("toy-exe", fp) is None
            assert _gauge("paddle_tpu_artifacts_fallbacks") == 1
            rec = _journal("fallback")[-1]
            assert rec["reason"] == "corrupt"
            assert rec["path"] == stats["path"]
            # verify flags the same defect, with its own audit record
            bad = store.verify()
            assert len(bad) == 1 and not bad[0]["ok"]
            assert _journal("verify_failed")
        # restoration: the artifact serves again, and verify is clean
        assert store.get("toy-exe", fp) == payload
        assert store.verify() == []

    def test_stale_fingerprint_degrades_as_stale(self, store):
        fp = _toy_fp()
        store.put("toy-exe", fp, b"x" * 64)
        with FaultPlan.stale_fingerprint(store) as stats:
            # the doctored frame is INTACT — verify passes it...
            assert store.verify() == []
            # ...only the fingerprint comparison catches it
            assert store.get("toy-exe", fp) is None
            rec = _journal("fallback")[-1]
            assert rec["reason"] == "stale"
            assert stats["doctored_digest"] in rec["detail"]
        assert store.get("toy-exe", fp) == b"x" * 64

    def test_cache_race_single_complete_winner(self, store):
        fp = _toy_fp()
        payloads = [bytes([i]) * (512 + i) for i in range(12)]
        stats = FaultPlan.cache_race(store, "toy-exe", fp, payloads,
                                     threads=8)
        assert stats["errors"] == [] and stats["writes"] == 12
        assert stats["winner"]["ok"], stats["winner"]
        # the survivor is one of the candidates, complete
        assert store.get("toy-exe", fp) in payloads
        # no tmp litter once the dust settles
        leftovers = [n for n in os.listdir(store.root) if ".tmp." in n]
        assert leftovers == []

    def test_killed_writer_leaves_loadable_store(self, store):
        """A writer SIGKILLed mid-write leaves only a private tmp
        sibling — never a partial frame under the final name. Readers
        ignore it; the next put() sweeps it once it is old enough to
        be an orphan (not a live writer's in-flight tmp)."""
        fp = _toy_fp()
        store.put("toy-exe", fp, b"good" * 50)
        orphan = store.path("toy-exe") + ".tmp.99999.1"
        with open(orphan, "wb") as f:
            f.write(b"PTA1\x00partial-frame-from-a-dead-writer")
        # reads are untouched by the orphan
        assert store.get("toy-exe", fp) == b"good" * 50
        assert _gauge("paddle_tpu_artifacts_fallbacks") == 0
        # a FRESH tmp (a live concurrent writer) survives the sweep...
        store.put("toy-exe", fp, b"good" * 50)
        assert os.path.exists(orphan)
        # ...an aged one is swept
        os.utime(orphan, (1, 1))
        store.put("toy-exe", fp, b"good" * 50)
        assert not os.path.exists(orphan)


# -------------------------------------------------------------- resolver
class TestResolver:
    def test_warm_ladder_and_backfill(self, store):
        args = tuple(map(jax.numpy.asarray, _toy_args()))
        fp = _toy_fp()
        exe = A.resolve(fp, _toy, args)
        want = np.asarray(exe(*args))
        # cold build journaled + persisted
        assert _journal("build")[-1]["digest"] == fp.digest
        assert _gauge("paddle_tpu_artifacts_build_ms") > 0
        assert len(store.entries()) == 1
        # rung 1: in-process cache
        assert A.resolve(fp, _toy, args) is exe
        # rung 2: the store (a "new process"), no recompiling
        A.EXECUTABLES.clear()
        exe2 = A.resolve(fp, _toy, args)
        assert exe2 is not exe
        assert _journal("load")[-1]["source"] == "store"
        np.testing.assert_array_equal(np.asarray(exe2(*args)), want)

    def test_unloadable_payload_recovers_by_rebuild(self, store):
        """A valid frame around bytes that don't deserialize (wrong
        jaxlib, junk): journal ``unloadable``, rebuild cold, and the
        backfill REPAIRS the store."""
        args = tuple(map(jax.numpy.asarray, _toy_args()))
        fp = _toy_fp()
        store.put(A.runtime._artifact_name(fp), fp, b"not-an-executable")
        exe = A.resolve(fp, _toy, args)
        assert _journal("fallback")[-1]["reason"] == "unloadable"
        np.testing.assert_array_equal(
            np.asarray(exe(*args)), _toy_args()[0] * 2.0 + 1.0)
        # the junk was overwritten by the rebuild's backfill
        A.EXECUTABLES.clear()
        A.resolve(fp, _toy, args)
        assert _journal("load")[-1]["digest"] == fp.digest

    def test_warm_false_returns_plain_jit(self, store):
        assert A.resolve(_toy_fp(), _toy, _toy_args(),
                         warm=False) is _toy
        assert store.entries() == []

    def test_executable_cache_lru_bounded(self):
        cache = ExecutableCache(capacity=2)
        fps = [_toy_fp(plan={"n": i}) for i in range(3)]
        for i, fp in enumerate(fps):
            cache.put(fp, f"exe{i}")
        assert cache.stats()["entries"] == 2
        assert cache.get(fps[0]) is None       # evicted (oldest)
        assert cache.get(fps[2]) == "exe2"


# ------------------------------------------------------------ the golden
class TestWarmDecode:
    def test_in_process_respawn_token_identical_zero_compiles(
            self, store, decoder):
        """Rung 1 of the warm ladder: a REBUILT engine in the same
        process (a rolling deploy's in-process restart) shares the
        first engine's executable — token-identical, zero step
        compiles. The disk rung's golden is
        TestCrossProcessWarmStart, where a fresh process must load
        from the store."""
        rng = np.random.RandomState(3)
        prompts = [rng.randint(0, 40, (n,)).astype("int32")
                   for n in (4, 6)]
        news = [8, 6]

        def run(engine):
            reqs = [engine.submit(p, n)
                    for p, n in zip(prompts, news)]
            engine.run(timeout=300)
            return [r.get(timeout=1) for r in reqs]

        # plain-JIT baseline: no artifact plane at all
        want = run(DecodeEngine(decoder, num_slots=2, page_size=4,
                                max_seq_len=DEC_CFG["max_len"],
                                prefix_cache=False, warm_start=False))

        # cold warm-start engine: builds + backfills the store
        got_cold = run(DecodeEngine(decoder, num_slots=2, page_size=4,
                                    max_seq_len=DEC_CFG["max_len"],
                                    prefix_cache=False))
        assert got_cold == want
        names = [r["name"] for r in store.entries()]
        assert any(n.startswith("paged_step-") for n in names)

        # "respawned engine", same process: the executable cache
        # serves it — no disk read, no trace, no compile
        hits0 = A.EXECUTABLES.stats()["hits"]
        with compile_watch() as watch:
            got_warm = run(DecodeEngine(decoder, num_slots=2,
                                        page_size=4,
                                        max_seq_len=DEC_CFG["max_len"],
                                        prefix_cache=False))
        assert got_warm == want
        step_compiles = {k: v for k, v in watch.per_function.items()
                         if "_step_impl" in k}
        assert step_compiles == {}, step_compiles
        assert A.EXECUTABLES.stats()["hits"] > hits0
        # and no second build was journaled — one artifact, shared
        assert len(_journal("build")) == 1

    def test_corrupt_store_still_serves_token_identical(
            self, store, decoder):
        """Acceptance: a corrupt artifact on one replica degrades to
        JIT — journaled — and serves the SAME tokens."""
        rng = np.random.RandomState(4)
        prompt = rng.randint(0, 40, (5,)).astype("int32")

        def run(engine):
            r = engine.submit(prompt, 8)
            engine.run(timeout=300)
            return r.get(timeout=1)

        want = run(DecodeEngine(decoder, num_slots=2, page_size=4,
                                max_seq_len=DEC_CFG["max_len"],
                                prefix_cache=False))   # builds store
        A.EXECUTABLES.clear()
        with FaultPlan.corrupt_artifact(store, mode="payload"):
            got = run(DecodeEngine(decoder, num_slots=2, page_size=4,
                                   max_seq_len=DEC_CFG["max_len"],
                                   prefix_cache=False))
            assert got == want
            assert _journal("fallback")[-1]["reason"] == "corrupt"

    def test_engine_warmup_resolves_before_traffic(self, store,
                                                   decoder):
        eng = DecodeEngine(decoder, num_slots=2, page_size=4,
                           max_seq_len=DEC_CFG["max_len"],
                           prefix_cache=False)
        stats = eng.warmup()
        assert stats["warm_start"] is True
        assert any(r["name"].startswith("paged_step-")
                   for r in store.entries())
        # warmup wrote only the null page: decode still correct
        rng = np.random.RandomState(5)
        prompt = rng.randint(0, 40, (4,)).astype("int32")
        want = [int(t) for t in decoder.generate(
            prompt[None, :], max_len=4 + 6)[0]]
        r = eng.submit(prompt, 6)
        eng.run(timeout=300)
        assert r.get(timeout=1) == want


_CHILD_TEMPLATE = """
import os
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
os.environ["JAX_PLATFORMS"] = "cpu"
import json
import jax
import jax.numpy as jnp
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_log_compiles", True)
from paddle_tpu import artifacts as A
from paddle_tpu.artifacts.fingerprint import fingerprint
from paddle_tpu.analysis.sanitizer import compile_watch
A.configure({root!r})
@jax.jit
def step(x, y):
    return jnp.tanh(x @ y) * 2.0 + 1.0
args = (jnp.arange(12, dtype=jnp.float32).reshape(3, 4),
        jnp.ones((4, 3), jnp.float32) * 0.1)
fp = fingerprint("xproc_step", {{"w": args[0]}}, plan={{"n": 3}})
with compile_watch() as watch:
    exe = A.resolve(fp, step, args)
    out = exe(*args)
print(json.dumps({{
    "out": [float(v) for v in jnp.ravel(out)],
    "step_compiles": {{k: v for k, v in watch.per_function.items()
                       if "step" in k}},
    "is_jit_wrapper": exe is step,
}}))
"""


_DECODE_CHILD = """
import os
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
os.environ["JAX_PLATFORMS"] = "cpu"
import json
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
import paddle_tpu as paddle
from paddle_tpu import models, artifacts as A
from paddle_tpu.core.registry import reset_name_counters
from paddle_tpu.analysis.sanitizer import compile_watch
from paddle_tpu.serving.engine import DecodeEngine
from paddle_tpu.obs.events import JOURNAL
A.configure({root!r})
paddle.init(use_tpu=False, seed=0)
reset_name_counters()
spec = models.transformer_lm(vocab_size=40, d_model=16, n_heads=2,
                             n_layers=2, d_ff=32, max_len=32)
costs = spec.cost if isinstance(spec.cost, list) else [spec.cost]
topo = paddle.Topology(costs, extra_outputs=[spec.output])
params = topo.init_params(jax.random.PRNGKey(7))
dec = models.TransformerDecoder(params, n_layers=2, n_heads=2)
eng = DecodeEngine(dec, num_slots=2, page_size=4, max_seq_len=32,
                   prefix_cache=False)
with compile_watch() as w:
    r = eng.submit(np.array([5, 9, 3, 1], np.int32), 6)
    eng.run(timeout=300)
print(json.dumps({{
    "tokens": r.get(timeout=1),
    "step_compiles": {{k: v for k, v in w.per_function.items()
                       if "_step_impl" in k}},
    "journal": [e["kind"]
                for e in JOURNAL.tail(20, domain="artifacts")],
}}))
"""


class TestCrossProcessWarmStart:
    def test_fresh_process_loads_without_compiling(self, tmp_path):
        """The respawn contract, end to end: process A builds and
        persists; a GENUINELY fresh process B resolves the same
        fingerprint from disk and never compiles the step — the
        cold_start_to_first_token warm path and the autoscale-up
        MTTR bound both rest on exactly this."""
        import subprocess
        import sys
        root = str(tmp_path / "arts")

        def spawn():
            env = dict(os.environ,
                       PYTHONPATH=os.path.dirname(
                           os.path.dirname(os.path.abspath(__file__))))
            env.pop("PADDLE_TPU_COMPILE_CACHE", None)
            r = subprocess.run(
                [sys.executable, "-c",
                 _CHILD_TEMPLATE.format(root=root)],
                capture_output=True, text=True, timeout=240, env=env)
            assert r.returncode == 0, r.stderr[-2000:]
            return json.loads(r.stdout.strip().splitlines()[-1])

        cold = spawn()
        assert cold["step_compiles"], "cold child must compile"
        assert not cold["is_jit_wrapper"]
        assert os.listdir(root)
        warm = spawn()
        assert warm["step_compiles"] == {}, warm["step_compiles"]
        assert not warm["is_jit_wrapper"]
        np.testing.assert_allclose(warm["out"], cold["out"],
                                   rtol=1e-6)

    def test_fresh_process_decode_token_identical(self, tmp_path):
        """The disk-rung golden at full fidelity: a fresh process
        builds + persists the paged decode executable, a second fresh
        process serves the SAME tokens through the store-loaded
        executable with ZERO decode-step compiles — the acceptance
        row for `paddle_tpu artifacts build` + warm `serve`."""
        import subprocess
        import sys
        child = _DECODE_CHILD.format(root=str(tmp_path / "arts"))
        env = dict(os.environ,
                   PYTHONPATH=os.path.dirname(
                       os.path.dirname(os.path.abspath(__file__))))
        env.pop("PADDLE_TPU_COMPILE_CACHE", None)

        def spawn():
            r = subprocess.run([sys.executable, "-c", child],
                               capture_output=True, text=True,
                               timeout=240, env=env)
            assert r.returncode == 0, r.stderr[-2000:]
            return json.loads(r.stdout.strip().splitlines()[-1])

        cold = spawn()
        assert cold["step_compiles"] and "build" in cold["journal"]
        warm = spawn()
        assert warm["tokens"] == cold["tokens"]
        assert warm["step_compiles"] == {}, warm["step_compiles"]
        assert "load" in warm["journal"]
        assert "fallback" not in warm["journal"]


# ------------------------------------------------------------------- CLI
class TestArtifactsCli:
    DEC_SRC = (
        "import jax\n"
        "import paddle_tpu as paddle\n"
        "from paddle_tpu import models\n"
        "from paddle_tpu.core.registry import reset_name_counters\n"
        "paddle.init(use_tpu=False, seed=0)\n"
        "reset_name_counters()\n"
        "spec = models.transformer_lm(vocab_size=40, d_model=16,\n"
        "                             n_heads=2, n_layers=2, d_ff=32,\n"
        "                             max_len=32)\n"
        "costs = (spec.cost if isinstance(spec.cost, list)\n"
        "         else [spec.cost])\n"
        "topo = paddle.Topology(costs, extra_outputs=[spec.output])\n"
        "params = topo.init_params(jax.random.PRNGKey(7))\n"
        "decoder = models.TransformerDecoder(params, n_layers=2,\n"
        "                                    n_heads=2)\n")

    @pytest.fixture
    def built_dir(self, tmp_path, capsys):
        from paddle_tpu import cli
        cfg = tmp_path / "dec.py"
        cfg.write_text(self.DEC_SRC)
        d = str(tmp_path / "arts")
        try:
            rc = cli.main(["artifacts", "build", "--dir", d,
                           "--decode_config", str(cfg),
                           "--gen_slots", "2",
                           "--gen_page_size", "4"])
            assert rc == 0
            out = json.loads(capsys.readouterr().out)
            assert out["action"] == "build" and out["entries"]
            yield d
        finally:
            A.configure(None)
            A.EXECUTABLES.clear()

    def test_build_ls_verify_round_trip(self, built_dir, capsys):
        from paddle_tpu import cli
        assert cli.main(["artifacts", "ls", "--dir", built_dir]) == 0
        ls = json.loads(capsys.readouterr().out)
        assert ls["count"] >= 1
        row = ls["entries"][0]
        assert row["ok"] and row["digest"] and row["age_s"] >= 0
        assert cli.main(["artifacts", "verify",
                         "--dir", built_dir]) == 0
        assert json.loads(capsys.readouterr().out)["defective"] == []

    def test_verify_corrupt_exits_nonzero_and_journals(
            self, built_dir, capsys):
        from paddle_tpu import cli
        victim = next(os.path.join(built_dir, n)
                      for n in sorted(os.listdir(built_dir))
                      if n.endswith(".ptaf"))
        with open(victim, "r+b") as f:
            f.seek(-3, os.SEEK_END)
            f.write(b"\xff\xff\xff")
        rc = cli.main(["artifacts", "verify", "--dir", built_dir])
        assert rc == 1
        out = json.loads(capsys.readouterr().out)
        assert len(out["defective"]) == 1
        assert out["defective"][0]["path"] == victim
        assert _journal("verify_failed")

    def test_dir_required_without_env(self, monkeypatch):
        from paddle_tpu import cli
        monkeypatch.delenv("PADDLE_TPU_ARTIFACTS", raising=False)
        with pytest.raises(SystemExit):
            cli.main(["artifacts", "ls"])


# ---------------------------------------------------- compile-cache seam
class TestCompileCacheSeam:
    def test_resolve_dir_grammar(self, monkeypatch):
        monkeypatch.delenv(compile_cache.ENV_VAR, raising=False)
        assert compile_cache.resolve_dir("/x") == "/x"
        assert compile_cache.resolve_dir("0") is None
        assert compile_cache.resolve_dir("off") is None
        assert compile_cache.resolve_dir(None) is None
        assert compile_cache.resolve_dir(None, fallback="/f") == "/f"
        monkeypatch.setenv(compile_cache.ENV_VAR, "/e")
        assert compile_cache.resolve_dir(None) == "/e"
        assert compile_cache.resolve_dir("/x") == "/x"
        monkeypatch.setenv(compile_cache.ENV_VAR, "0")
        assert compile_cache.resolve_dir(None, fallback="/f") is None
        assert compile_cache.ensure_default() is None

    def test_enable_points_jax_at_dir(self, tmp_path, monkeypatch):
        monkeypatch.delenv(compile_cache.ENV_VAR, raising=False)
        prev = jax.config.jax_compilation_cache_dir
        try:
            d = compile_cache.enable(str(tmp_path / "cc"))
            assert d == str(tmp_path / "cc") and os.path.isdir(d)
            assert jax.config.jax_compilation_cache_dir == d
        finally:
            jax.config.update("jax_compilation_cache_dir", prev)

    def test_disabled_scopes_and_restores(self):
        assert jax.config.jax_enable_compilation_cache is True
        with compile_cache.disabled():
            assert jax.config.jax_enable_compilation_cache is False
            with compile_cache.disabled():
                assert jax.config.jax_enable_compilation_cache is False
        assert jax.config.jax_enable_compilation_cache is True
