"""Serving chaos suite — InferenceServer under injected faults.

The acceptance contract (ISSUE: hardened inference serving): N client
threads with injected hung-forward, poisoned-bytes, mid-request-destroy
and burst-overload faults produce zero interpreter crashes or
deadlocks, only typed errors at the boundary, and the circuit breaker
opens under fault and recovers (serves successfully) after the faults
stop. Faults come from paddle_tpu.testing.FaultPlan (e)-(g); every
test is @chaos so a wedge dumps all thread stacks (tests/conftest.py).

Round 6 adds the DECODE-ENGINE chaos family (FaultPlan (j), ISSUE 6):
mid-decode joins/evictions/cancellations and client disconnects
against the continuous-batching engine. The invariant every fault must
preserve: KV pages ALWAYS return to the pool (zero leaks), and
sequences that were not faulted stay TOKEN-IDENTICAL to undisturbed
runs.
"""

import threading
import time

import numpy as np
import pytest

import jax
import paddle_tpu as paddle
from paddle_tpu import models
from paddle_tpu.serving import (CircuitBreaker, DecodeEngine, Expired,
                                InferenceServer, Rejected, ServerClosed,
                                ServingError, build_http_server,
                                prometheus_text)
from paddle_tpu.testing import FaultPlan
from paddle_tpu.trainer.inference import Inference

pytestmark = pytest.mark.chaos


def tiny_inference(dim=8, out=4, seed=5):
    paddle.init(seed=seed)
    x = paddle.layer.data("x", paddle.data_type.dense_vector(dim))
    o = paddle.layer.fc(x, size=out, act=paddle.activation.Softmax())
    params = paddle.create_parameters(paddle.Topology(o))
    return Inference(output_layer=o, parameters=params)


DEC_CFG = dict(vocab_size=40, d_model=16, n_heads=2, n_layers=2,
               d_ff=32, max_len=32)


def tiny_decoder(seed=7):
    paddle.init(use_tpu=False, seed=0)
    from paddle_tpu.core.registry import reset_name_counters
    reset_name_counters()
    spec = models.transformer_lm(**DEC_CFG)
    costs = spec.cost if isinstance(spec.cost, list) else [spec.cost]
    topo = paddle.Topology(costs, extra_outputs=[spec.output])
    params = topo.init_params(jax.random.PRNGKey(seed))
    return models.TransformerDecoder(params, n_layers=DEC_CFG["n_layers"],
                                     n_heads=DEC_CFG["n_heads"])


def samples(batch=2, dim=8, seed=0):
    rng = np.random.RandomState(seed)
    return [(rng.randn(dim).astype(np.float32),) for _ in range(batch)]


def assert_pool_balanced(eng):
    """Round-9 pool invariant: zero leaks AND zero refcount drift.
    With the prefix cache on (the default) a drained engine may park
    finished sequences' pages in the trie, so "everything returned"
    means free + trie-held covers every usable page, and the live
    refcounts are exactly the slot-table + trie references."""
    acc = eng.page_accounting()
    assert acc["leaked"] == 0
    assert acc["free"] + acc["held_by_trie"] == acc["total_usable"]
    assert acc["refs_total"] == \
        acc["held_by_slots"] + acc["held_by_trie"]
    # two-tier extension (ISSUE 20): with a spill store attached, the
    # HOST tier must conserve too — every page ever spilled is either
    # restored, dropped (LRU / integrity / recovery clear) or still
    # resident, and residency never exceeds the configured capacity.
    # A SIGKILL mid-spill (kill_during_spill) must not break this: the
    # ordering contract means a torn spill leaves no store entry.
    if getattr(eng, "spill", None) is not None:
        assert 0 <= acc["spilled"] <= acc["spill_capacity"]
        assert acc["spill_puts"] == (
            acc["spill_restores"] + acc["spill_evicted_lru"]
            + acc["spill_dropped_integrity"] + acc["spill_cleared"]
            + acc["spilled"]), acc
    return acc


class TestServerBasics:
    def test_serves_and_snapshots(self):
        inf = tiny_inference()
        srv = InferenceServer(inf, max_queue=8, workers=2,
                              breaker=False).start()
        try:
            want = np.asarray(inf.infer(samples()))
            got = np.asarray(srv.infer(samples()))
            np.testing.assert_allclose(got, want, rtol=1e-6)
            for _ in range(5):
                srv.infer(samples())
            st = srv.stats()
            assert st["served"] == 6
            assert st["p50_ms"] > 0.0
            assert srv.health()["status"] == "ok"
        finally:
            srv.shutdown(drain=True)
        assert srv.health()["status"] == "stopped"

    def test_graceful_drain_completes_queued_work(self):
        inf = tiny_inference()
        plan = FaultPlan(seed=3)
        srv = InferenceServer(inf, max_queue=16, workers=1,
                              breaker=False).start()
        with plan.flaky_forward(inf, delay={i: 0.05 for i in range(8)}):
            reqs = [srv.submit(samples(seed=i)) for i in range(6)]
            t = threading.Thread(target=srv.shutdown,
                                 kwargs={"drain": True},
                                 name="pt-test-drain")
            t.start()
            for r in reqs:                  # all queued work completes
                assert np.asarray(r.get(timeout=30)).shape == (2, 4)
            t.join(30)
            assert not t.is_alive()
        with pytest.raises(ServerClosed):
            srv.submit(samples())
        assert srv.stats()["served"] == 6

    def test_shutdown_without_drain_fails_queued_typed(self):
        inf = tiny_inference()
        plan = FaultPlan(seed=4)
        srv = InferenceServer(inf, max_queue=16, workers=1,
                              breaker=False).start()
        with plan.flaky_forward(inf, delay={0: 0.2}):
            first = srv.submit(samples())          # occupies the worker
            queued = [srv.submit(samples(seed=i)) for i in range(4)]
            time.sleep(0.05)                        # worker picked first
            srv.shutdown(drain=False, timeout=10)
            dropped = 0
            for r in queued:
                try:
                    r.get(timeout=10)
                except ServerClosed:
                    dropped += 1
            assert dropped >= 3                     # queue was flushed
            first.get(timeout=10)                   # in-flight completed


class TestBackpressure:
    def test_burst_overload_rejects_with_retry_after(self):
        """Burst fault: 30 concurrent requests against queue=3/worker=1
        with a slowed forward — the bounded queue sheds the overflow
        with Rejected(retry_after>0), everything settles, nothing
        crashes or deadlocks."""
        inf = tiny_inference()
        plan = FaultPlan(seed=9)
        srv = InferenceServer(inf, max_queue=3, workers=1,
                              breaker=False).start()
        try:
            with plan.flaky_forward(
                    inf, delay={i: 0.03 for i in range(64)}):
                results, errors = FaultPlan.burst(
                    lambda i: srv.infer(samples(seed=i)), 30,
                    threads=8, timeout=60)
            served = sum(r is not None for r in results)
            rejected = [e for e in errors if isinstance(e, Rejected)]
            other = [e for e in errors
                     if e is not None and not isinstance(e, Rejected)]
            assert other == []              # typed backpressure only
            assert served + len(rejected) == 30
            assert len(rejected) > 0        # the bound actually bound
            assert all(e.retry_after > 0 and e.reason == "queue_full"
                       for e in rejected)
            st = srv.stats()
            assert st["rejected_full"] == len(rejected)
            assert st["served"] == served
        finally:
            srv.shutdown(drain=True)

    def test_deadline_expires_queued_requests(self):
        inf = tiny_inference()
        plan = FaultPlan(seed=10)
        srv = InferenceServer(inf, max_queue=16, workers=1,
                              breaker=False).start()
        try:
            with plan.flaky_forward(inf, delay={0: 0.3}):
                slow = srv.submit(samples())
                doomed = srv.submit(samples(seed=1), deadline=0.05)
                with pytest.raises(Expired):
                    doomed.get()
                slow.get(timeout=10)
            assert srv.stats()["expired"] >= 1
        finally:
            srv.shutdown(drain=True)


class TestHungForwardAndBreaker:
    def test_hung_forward_expires_then_recovers(self):
        """A hung forward (blocks on an Event) must not hang the client:
        the deadline bounds the wait, the request is typed Expired, and
        after the fault is released the server serves again."""
        inf = tiny_inference()
        plan = FaultPlan(seed=11)
        release = threading.Event()
        srv = InferenceServer(inf, max_queue=8, workers=1,
                              breaker=False).start()
        try:
            with plan.flaky_forward(inf, hang={0: release}):
                req = srv.submit(samples(), deadline=0.2)
                t0 = time.monotonic()
                with pytest.raises(Expired):
                    req.get()
                assert time.monotonic() - t0 < 5.0   # client not hung
                release.set()                        # un-wedge the worker
            out = srv.infer(samples(), deadline=10.0)
            assert np.asarray(out).shape == (2, 4)
        finally:
            release.set()
            srv.shutdown(drain=True, timeout=10)

    def test_breaker_opens_under_faults_and_half_open_recovers(self):
        """Poisoned forwards push the failure rate over threshold: the
        breaker OPENS (submit -> Rejected(breaker_open)), then after the
        cooldown it half-opens, probes succeed, and serving resumes."""
        inf = tiny_inference()
        plan = FaultPlan(seed=12)
        breaker = CircuitBreaker(window=16, failure_threshold=0.5,
                                 min_requests=4, cooldown=0.3,
                                 half_open_probes=2)
        srv = InferenceServer(inf, max_queue=16, workers=1,
                              breaker=breaker).start()
        try:
            with plan.flaky_forward(inf, fail_rate=1.0):
                failures = 0
                for i in range(8):
                    try:
                        srv.infer(samples(seed=i), deadline=5.0)
                    except ServingError:
                        failures += 1
                assert failures >= 4
                assert breaker.state == "open"
                with pytest.raises(Rejected) as ei:
                    srv.submit(samples())
                assert ei.value.reason == "breaker_open"
                assert ei.value.retry_after > 0
                assert srv.health()["status"] == "shedding"
            # faults stop; wait out the cooldown, probes close it
            time.sleep(0.35)
            for i in range(3):
                out = srv.infer(samples(seed=100 + i), deadline=10.0)
                assert np.asarray(out).shape == (2, 4)
            assert breaker.state == "closed"
            assert srv.stats()["rejected_breaker"] >= 1
            assert srv.stats()["breaker"]["trips"] >= 1
        finally:
            srv.shutdown(drain=True)


class TestMixedChaosAcceptance:
    def test_eight_clients_mixed_faults_no_crash_no_deadlock(self):
        """THE acceptance run: 8 client threads of mixed traffic against
        a live server while the fault plan injects slow forwards, failed
        (poisoned) forwards, and burst overload — plus concurrent C-ABI
        clone/forward/destroy traffic with a mid-request destroy. Zero
        untyped exceptions, zero deadlocks; the breaker opens under the
        fault storm and the server serves again after it passes."""
        from paddle_tpu import capi_host as ch
        from paddle_tpu.trainer.inference import save_inference_model
        import tempfile
        import os

        inf = tiny_inference()
        # the C-ABI lane gets its own tiny artifact
        tar = os.path.join(tempfile.mkdtemp(), "m.tar")
        paddle.init(seed=6)
        x2 = paddle.layer.data("px", paddle.data_type.dense_vector(8))
        o2 = paddle.layer.fc(x2, size=4,
                             act=paddle.activation.Softmax())
        p2 = paddle.create_parameters(paddle.Topology(o2))
        save_inference_model(tar, o2, p2)

        plan = FaultPlan(seed=13)
        breaker = CircuitBreaker(window=16, failure_threshold=0.5,
                                 min_requests=4, cooldown=0.25,
                                 half_open_probes=1)
        srv = InferenceServer(inf, max_queue=8, workers=2,
                              default_deadline=5.0,
                              breaker=breaker).start()
        src = ch.create(tar)
        assert src > 0
        payload = np.linspace(0, 1, 16).astype(np.float32).tobytes()
        untyped = []

        def http_client(tid):
            import random as _r
            rng = _r.Random(tid)
            for i in range(25):
                try:
                    srv.infer(samples(seed=tid * 100 + i),
                              deadline=rng.choice([0.5, 2.0, 5.0]))
                except (Rejected, Expired, ServingError):
                    pass                        # typed: expected
                except BaseException as e:      # the failure under test
                    untyped.append(repr(e))

        def capi_client(tid):
            import random as _r
            rng = _r.Random(1000 + tid)
            for i in range(25):
                c = ch.create_shared(src)
                if c > 0:
                    blob = payload if rng.random() < 0.5 else \
                        plan.poison_bytes(payload, flips=3,
                                          truncate=rng.randrange(16))
                    r = ch.forward(c, blob, 2, 8)
                    if not isinstance(r, (int, tuple)):
                        untyped.append(repr(r))
                    ch.destroy(c)
                elif c != ch.ERR_BAD_HANDLE:
                    untyped.append(f"create_shared -> {c}")

        # fault storm: half the forwards fail, some are slow
        with plan.flaky_forward(inf, fail_rate=0.5,
                                delay={i: 0.02 for i in range(0, 60, 7)}):
            threads = ([threading.Thread(target=http_client, args=(t,),
                                         name=f"pt-test-http-{t}")
                        for t in range(5)] +
                       [threading.Thread(target=capi_client, args=(t,),
                                         name=f"pt-test-capi-{t}")
                        for t in range(3)])
            killer = FaultPlan.destroy_during(ch.destroy, src,
                                              delay_s=0.4)
            for t in threads:
                t.start()
            for t in threads:
                t.join(120)
                assert not t.is_alive(), "client thread wedged"
            killer.join(10)
        assert untyped == []

        # recovery: faults gone — after cooldown the breaker must close
        # and real traffic serves again
        deadline = time.monotonic() + 30
        ok = False
        while time.monotonic() < deadline:
            try:
                out = srv.infer(samples(seed=999), deadline=10.0)
                assert np.asarray(out).shape == (2, 4)
                ok = True
                break
            except (Rejected, Expired):
                time.sleep(0.1)
        assert ok, "server never recovered after faults stopped"
        st = srv.stats()
        assert st["served"] > 0
        srv.shutdown(drain=True, timeout=30)
        ch.destroy(src)                 # typed even if killer got it


class TestHTTPFront:
    def test_http_infer_health_stats(self):
        import json
        import urllib.error
        import urllib.request

        inf = tiny_inference()
        srv = InferenceServer(inf, max_queue=8, workers=1,
                              breaker=False).start()
        httpd = build_http_server(srv, "127.0.0.1", 0)
        port = httpd.server_address[1]
        t = threading.Thread(target=httpd.serve_forever, daemon=True,
                             name="pt-test-httpd")
        t.start()
        try:
            base = f"http://127.0.0.1:{port}"
            rows = [[0.1] * 8, [0.2] * 8]
            req = urllib.request.Request(
                base + "/infer",
                data=json.dumps({"rows": rows}).encode(),
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=30) as r:
                body = json.loads(r.read())
            assert np.asarray(body["outputs"]).shape == (2, 4)
            with urllib.request.urlopen(base + "/health",
                                        timeout=10) as r:
                assert json.loads(r.read())["status"] == "ok"
            with urllib.request.urlopen(base + "/stats",
                                        timeout=10) as r:
                assert json.loads(r.read())["served"] == 1
            # malformed payload is a 400, not a stack trace
            bad = urllib.request.Request(
                base + "/infer", data=b"{\"rows\": \"nope\"}",
                headers={"Content-Type": "application/json"})
            try:
                urllib.request.urlopen(bad, timeout=10)
                assert False, "expected HTTPError"
            except urllib.error.HTTPError as e:
                assert e.code == 400
        finally:
            httpd.shutdown()
            srv.shutdown(drain=True)


class TestDecodeEngineChaos:
    """Continuous-batching engine under scheduler chaos (FaultPlan (j)):
    joins, cancellations and evictions land mid-decode; pages must
    always return to the pool and unfaulted sequences stay
    token-identical to undisturbed runs."""

    def test_mid_decode_join_and_cancel_pages_return(self):
        dec = tiny_decoder()
        rng = np.random.RandomState(0)
        p0 = rng.randint(0, 40, (4,)).astype("int32")
        p1 = rng.randint(0, 40, (6,)).astype("int32")
        p2 = rng.randint(0, 40, (5,)).astype("int32")
        # undisturbed references for the two requests that will SURVIVE
        want1 = dec.generate(p1[None, :], max_len=6 + 8)[0]
        want2 = dec.generate(p2[None, :], max_len=5 + 7)[0]

        eng = DecodeEngine(dec, num_slots=2, page_size=4,
                           max_seq_len=DEC_CFG["max_len"])
        r0 = eng.submit(p0, 14)
        joined = []
        with FaultPlan.decode_script(eng, {
                2: lambda: joined.append(eng.submit(p1, 8)),
                4: lambda: joined.append(eng.submit(p2, 7)),
                6: lambda: r0.cancel()}) as script:
            eng.run(timeout=300)
        assert script["fired"] == [2, 4, 6]
        # the cancelled stream settles with its partial tokens
        assert r0.state == "cancelled"
        assert 0 < r0.num_generated < 14
        assert r0.get(timeout=1) == r0.tokens
        # the survivors are token-identical to solo runs
        assert joined[0].get(timeout=1) == [int(t) for t in want1]
        assert joined[1].get(timeout=1) == [int(t) for t in want2]
        assert_pool_balanced(eng)
        st = eng.stats()
        assert st["cancelled"] == 1 and st["finished"] == 2

    def test_eviction_storm_under_tiny_pool_no_leaks(self):
        """Pool pressure forces repeated preemption while requests keep
        arriving mid-flight; every request still completes exactly, and
        the pool balances to fully free."""
        dec = tiny_decoder()
        rng = np.random.RandomState(1)
        prompts = [rng.randint(0, 40, (int(rng.randint(3, 7)),))
                   .astype("int32") for _ in range(5)]
        news = [int(rng.randint(6, 12)) for _ in range(5)]
        want = [dec.generate(p[None, :], max_len=len(p) + n)[0]
                for p, n in zip(prompts, news)]
        # 2 slots x up to ~5 pages of demand against 6 usable pages
        eng = DecodeEngine(dec, num_slots=2, page_size=4,
                           max_seq_len=20, num_pages=7)
        reqs = [eng.submit(p, n) for p, n in zip(prompts, news)]
        eng.run(timeout=300)
        for i, r in enumerate(reqs):
            assert r.get(timeout=1) == [int(t) for t in want[i]], i
        assert_pool_balanced(eng)

    def test_client_disconnect_during_generation(self):
        """A client that walks away mid-stream (disconnect_after): the
        engine cancels at its next step, frees the pages, and the other
        in-flight sequence is token-identical to a solo run."""
        dec = tiny_decoder()
        rng = np.random.RandomState(2)
        pa = rng.randint(0, 40, (4,)).astype("int32")
        pb = rng.randint(0, 40, (5,)).astype("int32")
        want_b = dec.generate(pb[None, :], max_len=5 + 10)[0]
        eng = DecodeEngine(dec, num_slots=2, page_size=4,
                           max_seq_len=DEC_CFG["max_len"]).start()
        try:
            ra = eng.submit(pa, 20)
            rb = eng.submit(pb, 10)
            killer = FaultPlan.disconnect_after(ra, 4)
            assert rb.get(timeout=120) == [int(t) for t in want_b]
            killer.join(60)
            assert not killer.is_alive()
            ra.done.wait(60)
            assert ra.state == "cancelled"
            assert ra.num_generated >= 4
        finally:
            eng.shutdown(drain=True, timeout=60)
        assert_pool_balanced(eng)
        assert eng.stats()["cancelled"] == 1

    def test_burst_overload_typed_rejections_only(self):
        """A thread-pool burst against a small engine: every submit
        either serves exactly or sheds with a typed Rejected; zero
        untyped errors, zero deadlocks, zero page leaks."""
        dec = tiny_decoder()
        eng = DecodeEngine(dec, num_slots=2, page_size=4,
                           max_seq_len=20, max_waiting=3).start()
        rng = np.random.RandomState(3)
        prompts = [rng.randint(0, 40, (int(rng.randint(3, 7)),))
                   .astype("int32") for _ in range(16)]

        def one(i):
            return eng.submit(prompts[i], 4).get(timeout=120)

        try:
            results, errors = FaultPlan.burst(one, 16, threads=6,
                                              timeout=120)
        finally:
            eng.shutdown(drain=True, timeout=60)
        served = sum(r is not None for r in results)
        rejected = [e for e in errors if isinstance(e, Rejected)]
        other = [e for e in errors
                 if e is not None and not isinstance(e, Rejected)]
        assert other == []
        assert served + len(rejected) == 16
        assert served >= 1
        for i, r in enumerate(results):
            if r is not None:
                assert len(r) == 4, i
        assert all(e.reason == "queue_full" and e.retry_after > 0
                   for e in rejected)
        assert_pool_balanced(eng)

    def test_deadline_and_shutdown_are_typed(self):
        dec = tiny_decoder()
        eng = DecodeEngine(dec, num_slots=1, page_size=4,
                           max_seq_len=20)
        blocker = eng.submit(np.zeros((3,), "int32"), 10)
        doomed = eng.submit(np.zeros((3,), "int32"), 10,
                            deadline=0.0)            # expired on arrival
        for _ in range(3):
            eng.step()
        with pytest.raises(Expired):
            doomed.get(timeout=5)
        # drainless shutdown: in-flight settles ServerClosed, pages back
        eng.shutdown(drain=False)
        with pytest.raises(ServerClosed):
            blocker.get(timeout=5)
        with pytest.raises(ServerClosed):
            eng.submit(np.zeros((3,), "int32"), 2)
        assert_pool_balanced(eng)
        assert eng.stats()["expired"] == 1


class TestServerEngineIntegration:
    """InferenceServer with an attached DecodeEngine: generate() routes
    through page-aware admission, stats() carries the KV/slot gauges,
    and /metrics exposes them in Prometheus text format."""

    def _server(self):
        dec = tiny_decoder()
        eng = DecodeEngine(dec, num_slots=2, page_size=4,
                           max_seq_len=DEC_CFG["max_len"])
        srv = InferenceServer(tiny_inference(), max_queue=8, workers=1,
                              breaker=False, engine=eng).start()
        return dec, eng, srv

    def test_generate_and_engine_stats(self):
        dec, eng, srv = self._server()
        try:
            prompt = np.zeros((3,), "int32")
            want = dec.generate(prompt[None, :], max_len=3 + 6)[0]
            got = srv.generate(prompt, 6, deadline=60.0)
            assert got == [int(t) for t in want]
            st = srv.stats()
            assert st["engine"]["finished"] == 1
            assert st["engine"]["kv_pages_total"] > 0
            assert_pool_balanced(eng)
        finally:
            srv.shutdown(drain=True)
        # shutdown drained the engine thread too
        assert eng.stats()["finished"] == 1
        with pytest.raises(ServerClosed):
            srv.generate(np.zeros((3,), "int32"), 2)

    def test_prometheus_metrics_text(self):
        dec, eng, srv = self._server()
        try:
            srv.infer(samples())
            srv.generate(np.zeros((3,), "int32"), 4, deadline=60.0)
            text = prometheus_text(srv)
        finally:
            srv.shutdown(drain=True)
        assert "# TYPE paddle_tpu_serving_served counter" in text
        assert "paddle_tpu_serving_served 1" in text
        assert "# TYPE paddle_tpu_serving_engine_kv_pages_free gauge" \
            in text
        assert "paddle_tpu_serving_engine_tokens_out 4" in text
        assert "paddle_tpu_serving_engine_slot_utilization" in text
        assert "paddle_tpu_serving_engine_token_latency_p99_ms" in text
        # every line is exposition-format: HELP/TYPE comment or
        # "name value" (the unified registry adds # HELP lines)
        for line in text.strip().splitlines():
            assert line.startswith(("# TYPE ", "# HELP ")) or \
                len(line.split(" ")) == 2, line

    def test_http_generate_and_metrics_endpoints(self):
        import json
        import urllib.error
        import urllib.request

        dec, eng, srv = self._server()
        httpd = build_http_server(srv, "127.0.0.1", 0)
        port = httpd.server_address[1]
        t = threading.Thread(target=httpd.serve_forever, daemon=True,
                             name="pt-test-httpd")
        t.start()
        try:
            base = f"http://127.0.0.1:{port}"
            prompt = [0, 0, 0]
            want = dec.generate(np.asarray(prompt, "int32")[None, :],
                                max_len=3 + 5)[0]
            req = urllib.request.Request(
                base + "/generate",
                data=json.dumps({"prompt": prompt,
                                 "max_new_tokens": 5,
                                 "deadline_ms": 60000}).encode(),
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=60) as r:
                body = json.loads(r.read())
            assert body["tokens"] == [int(x) for x in want]
            # round-9 response fields: prefix-cache reuse + speculation
            # telemetry ride every /generate reply
            assert body["prefix_hit_pages"] >= 0
            assert body["accepted_tokens"] >= 0
            with urllib.request.urlopen(base + "/metrics",
                                        timeout=10) as r:
                assert r.headers["Content-Type"].startswith("text/plain")
                text = r.read().decode()
            assert "paddle_tpu_serving_engine_finished 1" in text
            # malformed generate payload is a 400
            bad = urllib.request.Request(
                base + "/generate", data=b'{"prompt": []}',
                headers={"Content-Type": "application/json"})
            try:
                urllib.request.urlopen(bad, timeout=10)
                assert False, "expected HTTPError"
            except urllib.error.HTTPError as e:
                assert e.code == 400
            # max_new_tokens < 1 is a 400 too, not the engine's
            # ValueError escaping as a torn connection
            bad = urllib.request.Request(
                base + "/generate",
                data=b'{"prompt": [1], "max_new_tokens": 0}',
                headers={"Content-Type": "application/json"})
            try:
                urllib.request.urlopen(bad, timeout=10)
                assert False, "expected HTTPError"
            except urllib.error.HTTPError as e:
                assert e.code == 400
        finally:
            httpd.shutdown()
            srv.shutdown(drain=True)

    def test_http_generate_without_engine_is_501(self):
        import json
        import urllib.error
        import urllib.request

        srv = InferenceServer(tiny_inference(), max_queue=4, workers=1,
                              breaker=False).start()
        httpd = build_http_server(srv, "127.0.0.1", 0)
        port = httpd.server_address[1]
        t = threading.Thread(target=httpd.serve_forever, daemon=True,
                             name="pt-test-httpd-2")
        t.start()
        try:
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}/generate",
                data=json.dumps({"prompt": [1],
                                 "max_new_tokens": 2}).encode(),
                headers={"Content-Type": "application/json"})
            try:
                urllib.request.urlopen(req, timeout=10)
                assert False, "expected HTTPError"
            except urllib.error.HTTPError as e:
                assert e.code == 501
        finally:
            httpd.shutdown()
            srv.shutdown(drain=True)


class TestPrefixSpecChaos:
    """FaultPlan family (n): prefix-cache / CoW / speculation chaos
    (ISSUE 13). The round-9 invariants under every scenario: zero page
    leaks AND zero refcount underflows (``refs_total`` ==
    ``held_by_slots`` + ``held_by_trie``), and unfaulted sequences stay
    TOKEN-IDENTICAL to undisturbed dense runs — shared-prefix attach,
    copy-on-write and rejected speculation must never corrupt KV."""

    def _want(self, dec, prompt, max_new):
        p = np.asarray(prompt, "int32")
        return [int(t) for t in
                dec.generate(p[None, :], max_len=len(p) + max_new)[0]]

    def test_divergent_twins_cow_token_identity(self):
        """Request pairs sharing a prefix that splits mid-page: the
        late joiners attach the shared full page and CoW the split
        page; every stream is token-exact vs a solo dense run."""
        dec = tiny_decoder()
        eng = DecodeEngine(dec, num_slots=2, page_size=4,
                           max_seq_len=DEC_CFG["max_len"])
        plan = FaultPlan(seed=21)
        twins = plan.divergent_twins(eng, max_new=4, pairs=2, vocab=40)
        eng.run(timeout=300)
        for i, (req, prompt) in enumerate(twins):
            assert req.get(timeout=1) == self._want(dec, prompt, 4), i
        st = eng.stats()
        # the first pair misses (cold trie); the second pair walks the
        # radix index: at least one full shared page attaches and the
        # mid-page divergence copies-on-write
        assert st["prefix_hit_pages"] >= 1
        assert st["prefix_cow_copies"] >= 1
        assert st["finished"] == 4
        assert_pool_balanced(eng)

    def test_prefix_evict_storm_reclaims_trie_not_slots(self):
        """Distinct-prompt waves stack finished pages into the trie
        until admission must reclaim LRU leaves; every request still
        completes token-exact and the pool balances."""
        dec = tiny_decoder()
        eng = DecodeEngine(dec, num_slots=2, page_size=4,
                           max_seq_len=20, num_pages=9)
        plan = FaultPlan(seed=22)
        schedule, submitted = plan.prefix_evict_storm(
            eng, waves=4, per_wave=2, gap=3, prompt_len=8, max_new=3,
            vocab=40)
        with FaultPlan.decode_script(eng, schedule) as script:
            eng.run(timeout=300)
        assert script["fired"] == sorted(schedule)
        assert len(submitted) == 8
        for i, (req, prompt) in enumerate(submitted):
            assert req.get(timeout=1) == self._want(dec, prompt, 3), i
        st = eng.stats()
        assert st["finished"] == 8
        # the storm actually forced trie reclamation (journaled as
        # engine/prefix_evict), not just slot preemption
        assert st["prefix_evicted_pages"] >= 1
        assert_pool_balanced(eng)

    def test_cancel_mid_verify_returns_shared_refs(self):
        """With speculation on, a cancel lands between a draft
        proposal and the target's verify: the victim's pages AND its
        shared-prefix refs return, the survivor is token-exact."""
        dec = tiny_decoder()
        eng = DecodeEngine(dec, num_slots=2, page_size=4,
                           max_seq_len=DEC_CFG["max_len"],
                           draft=tiny_decoder(), spec_k=2)
        rng = np.random.RandomState(23)
        shared = [int(t) for t in rng.randint(0, 40, 6)]
        victim_p = shared + [int(t) for t in rng.randint(0, 40, 3)]
        surv_p = shared + [int(t) for t in rng.randint(0, 40, 3)]
        victim = eng.submit(victim_p, 12)
        surv = eng.submit(surv_p, 8)
        with FaultPlan.decode_script(
                eng, FaultPlan.cancel_mid_verify(victim, at=2)) as s:
            eng.run(timeout=300)
        assert s["fired"] == [2]
        assert victim.state == "cancelled"
        assert victim.get(timeout=1) == victim.tokens
        assert surv.get(timeout=1) == self._want(dec, surv_p, 8)
        st = eng.stats()
        # the same-weights draft means speculation genuinely committed
        # multi-token steps before/around the cancel
        assert st["spec_proposed_tokens"] > 0
        assert st["spec_accepted_tokens"] > 0
        assert st["cancelled"] == 1 and st["finished"] == 1
        assert_pool_balanced(eng)

    def test_spec_identity_with_disagreeing_draft(self):
        """A draft with DIFFERENT weights proposes mostly-wrong tokens:
        acceptance filters them and the output is still token-exact —
        rejected speculation rows never become readable KV."""
        dec = tiny_decoder()
        eng = DecodeEngine(dec, num_slots=2, page_size=4,
                           max_seq_len=DEC_CFG["max_len"],
                           draft=tiny_decoder(seed=11), spec_k=2)
        rng = np.random.RandomState(24)
        prompts = [[int(t) for t in rng.randint(0, 40, n)]
                   for n in (5, 7)]
        reqs = [eng.submit(p, 8) for p in prompts]
        eng.run(timeout=300)
        for i, (req, p) in enumerate(zip(reqs, prompts)):
            assert req.get(timeout=1) == self._want(dec, p, 8), i
        st = eng.stats()
        assert st["spec_proposed_tokens"] > 0
        assert_pool_balanced(eng)


class TestTwoTierChaos:
    """FaultPlan family (s): two-tier KV spill/restore chaos (ISSUE
    20). The invariants under every scenario: BOTH tiers balance
    (``assert_pool_balanced`` incl. host-tier conservation), every
    settled request is token-exact, and a torn spill — crash at the
    read or the commit point — never leaves a page simultaneously
    device-owned and host-stored."""

    def _want(self, dec, prompt, max_new):
        p = np.asarray(prompt, "int32")
        return [int(t) for t in
                dec.generate(p[None, :], max_len=len(p) + max_new)[0]]

    def _engine(self, dec, **over):
        kw = dict(num_slots=2, page_size=4, max_seq_len=20,
                  num_pages=9, kv_spill_pages=8)
        kw.update(over)
        return DecodeEngine(dec, **kw)

    def test_spill_storm_restores_and_balances(self):
        """Distinct-prompt waves overflow the tiny pool so cold trie
        leaves spill host-ward; later waves revisit the earliest
        prompts and must RESTORE their pages. Every stream token-exact,
        both tiers conserved."""
        dec = tiny_decoder()
        eng = self._engine(dec)
        plan = FaultPlan(seed=31)
        schedule, submitted = plan.spill_storm(
            eng, waves=5, per_wave=2, gap=4, prompt_len=8, max_new=3,
            vocab=40, revisit_from=2)
        with FaultPlan.decode_script(eng, schedule) as script:
            eng.run(timeout=300)
        assert script["fired"] == sorted(schedule)
        for i, (req, prompt) in enumerate(submitted):
            assert req.get(timeout=1) == self._want(dec, prompt, 3), i
        acc = assert_pool_balanced(eng)
        # the storm genuinely exercised BOTH directions of the tier
        # boundary — pages went host-ward and came back
        assert acc["spill_puts"] >= 1
        assert acc["spill_restores"] >= 1
        st = eng.stats()
        assert st["finished"] == len(submitted)
        assert st["kv_pages_spilled_now"] == acc["spilled"]

    def test_spill_storm_int8_identity(self):
        """The same storm over int8-quantized pages: restore feeds the
        dequant read path and greedy decode stays token-identical to
        the dense float reference (the pinned int8 tolerance contract
        — INT8_KV_RTOL/ATOL on attention outputs keeps argmax stable
        at this scale)."""
        dec = tiny_decoder()
        eng = self._engine(dec, kv_quant="int8")
        assert eng.stats()["kv_quant_bits"] == 8
        plan = FaultPlan(seed=32)
        schedule, submitted = plan.spill_storm(
            eng, waves=4, per_wave=2, gap=4, prompt_len=8, max_new=3,
            vocab=40, revisit_from=2)
        with FaultPlan.decode_script(eng, schedule):
            eng.run(timeout=300)
        for i, (req, prompt) in enumerate(submitted):
            assert req.get(timeout=1) == self._want(dec, prompt, 3), i
        acc = assert_pool_balanced(eng)
        assert acc["spill_puts"] >= 1

    def test_corrupt_spilled_page_degrades_to_miss(self):
        """Bit-rot EVERY host-resident entry (CRC left stale), then
        revisit the stormed prompts: each attempted restore must fail
        verification, drop the entry (``spill_dropped_integrity``) and
        degrade to a prefix miss — recompute, token-exact, balanced."""
        dec = tiny_decoder()
        eng = self._engine(dec)
        plan = FaultPlan(seed=33)
        # revisit_from past the last wave: storm only spills, so the
        # store is populated (not drained) when the corruption lands
        schedule, submitted = plan.spill_storm(
            eng, waves=4, per_wave=2, gap=4, prompt_len=8, max_new=3,
            vocab=40, revisit_from=4)
        with FaultPlan.decode_script(eng, schedule):
            eng.run(timeout=300)
        acc0 = assert_pool_balanced(eng)
        assert acc0["spilled"] >= 1

        class _Rotate:  # deterministic rng stub: hit EVERY entry once
            def __init__(self):
                self.i = 0

            def choice(self, xs):
                xs = sorted(xs)
                v = xs[self.i % len(xs)]
                self.i += 1
                return v

            def randrange(self, n):
                return 0

        rot = _Rotate()
        for _ in range(acc0["spilled"]):
            assert eng.spill.corrupt_one("bitflip", rng=rot) is not None
        # revisit every distinct stormed prompt: restores are attempted
        # against corrupted entries only
        prompts = []
        for _, p in submitted:
            if p not in prompts:
                prompts.append(p)
        reqs = [eng.submit(p, 3) for p in prompts]
        eng.run(timeout=300)
        for i, (req, p) in enumerate(zip(reqs, prompts)):
            assert req.get(timeout=1) == self._want(dec, p, 3), i
        acc = assert_pool_balanced(eng)
        # at least one corrupted entry was hit, failed CRC and was
        # dropped (the revisit churn may also spill-and-restore FRESH
        # uncorrupted pages, so restores can legitimately grow — the
        # pinned contract is that corruption is always caught)
        assert acc["spill_dropped_integrity"] >= 1

    @pytest.mark.parametrize("stage", ["read", "commit"])
    def test_kill_during_spill_stays_balanced(self, stage):
        """WorkerCrash at the read point (nothing changed) or the
        commit point (trie evicted + page freed, store entry NOT yet
        committed): the SIGKILL twin. The survivor's accounting must
        show no page both device-owned and host-stored, and a resumed
        engine drains every request token-exact."""
        from paddle_tpu.testing import WorkerCrash
        dec = tiny_decoder()
        eng = self._engine(dec)
        plan = FaultPlan(seed=34)
        schedule, submitted = plan.spill_storm(
            eng, waves=4, per_wave=2, gap=4, prompt_len=8, max_new=3,
            vocab=40, revisit_from=4)
        with FaultPlan.decode_script(eng, schedule):
            with FaultPlan.kill_during_spill(eng, at=0, stage=stage) \
                    as ks:
                with pytest.raises(WorkerCrash):
                    eng.run(timeout=300)
        assert ks["fired"] == 1 and ks["path"] is not None
        # mid-crash: slots still hold in-flight pages, but nothing
        # leaked, refs match, and the host tier conserves — the torn
        # spill left NO store entry for the in-flight path
        acc = eng.page_accounting()
        assert acc["leaked"] == 0
        assert acc["refs_total"] == \
            acc["held_by_slots"] + acc["held_by_trie"]
        assert acc["spill_puts"] == (
            acc["spill_restores"] + acc["spill_evicted_lru"]
            + acc["spill_dropped_integrity"] + acc["spill_cleared"]
            + acc["spilled"])
        assert tuple(ks["path"]) not in eng.spill._entries
        # the interceptor is disarmed; the engine finishes the storm
        eng.run(timeout=300)
        for i, (req, prompt) in enumerate(submitted):
            assert req.get(timeout=1) == self._want(dec, prompt, 3), i
        assert_pool_balanced(eng)
