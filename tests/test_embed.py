"""paddle_tpu.embed — the hash-partitioned embedding/parameter store.

Unit coverage for the pserver pair (shard durability + exactly-once
ledger, client cache/routing/async push), the `layers.embedding(
remote=True)` transparency contract (bit-equal with a local table),
the online/continuous-training loop (serving journal -> self-healing
reader pipeline -> live sparse updates), and the ``paddle_tpu_embed_*``
gauge catalog. The failure-mode story lives in tests/test_embed_faults.py
(chaos family (o))."""

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.core.registry import ParamAttr, reset_name_counters
from paddle_tpu.embed import (EmbeddingClient, EmbeddingShard, EmbedService,
                              RemoteLookup, journal_sample_reader,
                              log_sample, run_online, serving_sample_log,
                              shard_of, stable_hash64)
from paddle_tpu.obs.events import JOURNAL
from paddle_tpu.trainer.coordinator import (InMemStore, KVStoreServer,
                                            RpcStore)

DIM = 8


class TestRouting:
    def test_stable_hash_is_process_independent(self):
        # golden values pin the splitmix64 mix — a drift here would
        # strand every key on the wrong shard after an upgrade
        assert stable_hash64(0) == 16294208416658607535
        assert stable_hash64(1) == 10451216379200822465
        assert stable_hash64(-1) == 16490336266968443936

    def test_shard_of_in_range_and_spread(self):
        owners = [shard_of(k, 4) for k in range(1000)]
        assert set(owners) <= {0, 1, 2, 3}
        counts = np.bincount(owners, minlength=4)
        assert counts.min() > 150        # roughly uniform


class TestShard:
    def _shard(self, store=None, **kw):
        return EmbeddingShard(0, 1, DIM, seed=3, store=store, **kw)

    def test_lazy_init_is_deterministic_and_unmaterialized(self):
        a, b = self._shard(), self._shard()
        keys = np.arange(10, dtype=np.int64)
        np.testing.assert_array_equal(a.gather(keys), b.gather(keys))
        # gathers must not materialize rows: the digest covers exactly
        # the UPDATED state, so it is failover-comparable
        assert a.stats()["rows"] == 0
        assert a.digest() == b.digest()

    def test_exactly_once_ledger_dup_and_gap(self):
        s = self._shard()
        keys = np.arange(4, dtype=np.int64)
        g = np.ones((4, DIM), np.float32)
        assert s.apply_updates("c", 1, keys, g, 0.1)["applied"]
        d0 = s.digest()
        res = s.apply_updates("c", 1, keys, g, 0.1)       # retry: dedupe
        assert res["dup"] and not res["applied"]
        assert s.digest() == d0                           # not re-applied
        with pytest.raises(ValueError, match="gap"):
            s.apply_updates("c", 3, keys, g, 0.1)
        assert s.apply_updates("c", 2, keys, g, 0.1)["applied"]
        assert s.applied_seqs() == {"c": 2}

    def test_snapshot_plus_wal_replay_restores_digest(self):
        store = InMemStore()
        s = self._shard(store=store)
        rng = np.random.default_rng(0)
        for seq in (1, 2, 3):
            s.apply_updates("c", seq, np.arange(seq * 5, dtype=np.int64),
                            rng.normal(size=(seq * 5, DIM)).astype(
                                np.float32), 0.1)
        s.save_snapshot()
        for seq in (4, 5):        # past the snapshot horizon: WAL only
            s.apply_updates("c", seq, np.arange(8, dtype=np.int64),
                            rng.normal(size=(8, DIM)).astype(np.float32),
                            0.2)
        r = self._shard(store=store)
        assert r.restore_from_store()
        assert r.stats()["replayed_wal"] == 2
        assert r.digest() == s.digest()
        assert r.applied_seqs() == {"c": 5}

    def test_multi_mb_snapshot_rides_chunked_rpcstore(self):
        srv = KVStoreServer(host="127.0.0.1", port=0).start()
        try:
            store = RpcStore("127.0.0.1", srv.port, chunk_bytes=4096)
            s = self._shard(store=store)
            keys = np.arange(600, dtype=np.int64)
            s.apply_updates("c", 1,
                            keys, np.ones((600, DIM), np.float32), 0.5)
            s.save_snapshot()                # ~19KB frame -> 5 chunks
            assert srv.store.get("embed/shard0/snap.chunk.0") is not None
            r = self._shard(store=RpcStore("127.0.0.1", srv.port))
            assert r.restore_from_store()
            assert r.digest() == s.digest()
        finally:
            srv.stop()


class TestClient:
    def test_cache_hits_and_staleness_bound(self):
        with EmbedService(2, DIM, seed=1) as svc:
            with svc.client(client_id="c1", staleness_s=60.0) as c:
                keys = np.arange(12, dtype=np.int64)
                first = c.gather(keys)
                rpc_gathers = sum(svc.shard(s).stats()["gathers"]
                                  for s in range(2))
                second = c.gather(keys)              # all cached
                np.testing.assert_array_equal(first, second)
                assert sum(svc.shard(s).stats()["gathers"]
                           for s in range(2)) == rpc_gathers
                assert c.stats()["cache_hits"] == len(keys)
                c.gather(keys, max_stale_s=0.0)      # bound 0: refetch
                assert sum(svc.shard(s).stats()["gathers"]
                           for s in range(2)) > rpc_gathers

    def test_push_applies_and_invalidates_cache(self):
        with EmbedService(2, DIM, seed=1) as svc:
            with svc.client(client_id="c2") as c:
                keys = np.arange(6, dtype=np.int64)
                before = c.gather(keys)
                g = np.full((6, DIM), 2.0, np.float32)
                c.push(keys, g, lr=0.5)
                assert c.flush(timeout=15.0)
                after = c.gather(keys)       # cache invalidated by push
                np.testing.assert_allclose(after, before - 0.5 * g,
                                           rtol=1e-6)
                assert c.stats()["push_failures"] == 0

    def test_duplicate_keys_accumulate(self):
        with EmbedService(1, DIM, seed=1) as svc:
            with svc.client(client_id="c3") as c:
                k = np.array([7, 7], np.int64)
                before = c.gather(np.array([7], np.int64))
                g = np.ones((2, DIM), np.float32)
                c.push(k, g, lr=0.1)         # same row twice in one push
                assert c.flush(timeout=15.0)
                after = c.gather(np.array([7], np.int64))
                np.testing.assert_allclose(after, before - 0.2, rtol=1e-6)

    def test_poisoned_rows_dropped_at_source(self):
        with EmbedService(1, DIM, seed=1) as svc:
            with svc.client(client_id="c4") as c:
                keys = np.arange(3, dtype=np.int64)
                before = c.gather(keys)
                g = np.zeros((3, DIM), np.float32)
                g[1] = np.nan                          # reconcile guard
                g[0] = g[2] = 1.0
                c.push(keys, g, lr=1.0)
                assert c.flush(timeout=15.0)
                after = c.gather(keys)
                np.testing.assert_allclose(after[1], before[1])  # survived
                np.testing.assert_allclose(after[0], before[0] - 1.0,
                                           rtol=1e-6)


def _remote_pair(vocab):
    """The same 1-layer model twice: local table vs remote=True."""
    reset_name_counters()
    paddle.init(seed=11)
    ids = paddle.layer.data("ids", paddle.data_type.integer_value(vocab))
    local = paddle.layer.embedding(ids, size=DIM, name="tbl",
                                   param_attr=ParamAttr(name="_tbl_w"))
    topo_local = paddle.Topology(local)
    reset_name_counters()
    ids = paddle.layer.data("ids", paddle.data_type.integer_value(vocab))
    rem = paddle.layer.embedding(ids, size=DIM, name="tbl",
                                 param_attr=ParamAttr(name="_tbl_w"),
                                 remote=True)
    return topo_local, paddle.Topology(rem)


class TestRemoteLayer:
    def test_remote_table_never_materializes(self):
        _, topo_rem = _remote_pair(vocab=40)
        assert topo_rem.remote_tables() == {"_tbl_w": "ids"}
        assert "_tbl_w" not in topo_rem.param_specs
        assert topo_rem.init_params() == {}

    def test_forward_matches_local_table(self):
        """The transparency contract: with the local table set to the
        store's rows, remote and local forwards are bit-equal."""
        import jax.numpy as jnp
        vocab = 40
        topo_local, topo_rem = _remote_pair(vocab)
        with EmbedService(2, DIM, seed=9) as svc:
            with svc.client(client_id="lkp") as client:
                lookup = RemoteLookup(topo_rem, client)
                table = client.gather(np.arange(vocab, dtype=np.int64))
                ids = np.random.default_rng(4).integers(
                    0, vocab, 16).astype(np.int64)
                out_l, _ = topo_local.forward(
                    {"_tbl_w": jnp.asarray(table)}, {}, {"ids": ids},
                    mode="test")
                sub = lookup.sparse_sub({"ids": ids})
                out_r, _ = topo_rem.forward({}, {}, {"ids": ids},
                                            mode="test", sparse_sub=sub)
                np.testing.assert_allclose(np.asarray(out_r["tbl"]),
                                           np.asarray(out_l["tbl"]),
                                           rtol=1e-6)

    def test_forward_without_sparse_sub_raises(self):
        _, topo_rem = _remote_pair(vocab=40)
        ids = np.arange(4, dtype=np.int64)
        with pytest.raises(KeyError, match="REMOTE table"):
            topo_rem.forward({}, {}, {"ids": ids}, mode="test")

    def test_push_grads_updates_store(self):
        _, topo_rem = _remote_pair(vocab=40)
        with EmbedService(2, DIM, seed=9) as svc:
            with svc.client(client_id="upd") as client:
                lookup = RemoteLookup(topo_rem, client)
                ids = np.array([3, 3, 11], np.int64)
                sub = lookup.sparse_sub({"ids": ids})
                uids, rows = sub["_tbl_w"]
                np.testing.assert_array_equal(uids, [3, 11])
                g = np.ones_like(rows)
                lookup.push_grads(sub, {"_tbl_w": g}, lr=0.25)
                assert client.flush(timeout=15.0)
                fresh = client.gather(uids)
                np.testing.assert_allclose(fresh, rows - 0.25, rtol=1e-6)


class TestOnline:
    def _journal_samples(self, path, n=24, vocab=60):
        """Serving writes the feedback journal; labels follow a fixed
        rule so the loop has something to learn."""
        rng = np.random.default_rng(8)
        JOURNAL.configure(str(path))
        try:
            for _ in range(n):
                ids = rng.integers(0, vocab, 5)
                log_sample(ids, float(ids.sum() % 2))
        finally:
            JOURNAL.configure(None)

    def test_online_pass_trains_against_live_store(self, tmp_path):
        path = tmp_path / "serve.jsonl"
        self._journal_samples(path)
        with EmbedService(2, DIM, seed=2) as svc:
            with svc.client(client_id="online") as client:
                virgin = client.gather(np.arange(10, dtype=np.int64),
                                       max_stale_s=0.0).copy()
                stats = run_online(
                    client, journal_sample_reader(str(path)),
                    batch_size=4, lr=0.3, num_workers=2, seed=0)
                assert stats["batches"] == 6
                assert stats["samples"] == 24
                assert np.isfinite(stats["loss_mean"])
                assert stats["client"]["push_failures"] == 0
                applied = sum(svc.shard(s).stats()["applied_updates"]
                              for s in range(2))
                assert applied >= stats["batches"]
                # the live store moved: the very next lookup (bound 0 —
                # no cache) observes the trained rows
                after = client.gather(np.arange(10, dtype=np.int64),
                                      max_stale_s=0.0)
                assert not np.allclose(after, virgin)
        recs = [r for r in JOURNAL.tail(50, domain="embed")
                if r["kind"] == "online_pass"]
        assert recs and recs[-1]["batches"] == 6

    def test_serving_sample_log_seam(self):
        """InferenceServer(sample_log=...) journals every served batch —
        the feedback record the online loop trains from."""
        from paddle_tpu.serving import InferenceServer
        from paddle_tpu.trainer.inference import Inference
        reset_name_counters()
        paddle.init(seed=5)
        ids = paddle.layer.data("ids",
                                paddle.data_type.integer_value(30))
        emb = paddle.layer.embedding(ids, size=DIM, name="emb")
        out = paddle.layer.fc(emb, size=2,
                              act=paddle.activation.Softmax())
        params = paddle.create_parameters(paddle.Topology(out))
        inf = Inference(output_layer=out, parameters=params)
        srv = InferenceServer(
            inf, workers=1, breaker=False,
            sample_log=serving_sample_log(label_fn=lambda s: 1.0)).start()
        try:
            srv.infer([(3,), (17,)])
        finally:
            srv.shutdown(drain=True)
        recs = [r for r in JOURNAL.tail(50, domain="embed")
                if r["kind"] == "sample"]
        assert len(recs) >= 2
        assert recs[-2]["ids"] == [3] and recs[-1]["ids"] == [17]
        assert recs[-1]["label"] == 1.0


class TestEmbedObservability:
    def test_gauge_catalog_and_flight_provider(self):
        from paddle_tpu.obs.flight import FLIGHT
        from paddle_tpu.obs.metrics import REGISTRY
        with EmbedService(2, DIM, seed=1) as svc:
            with svc.client(client_id="obs") as c:
                c.gather(np.arange(8, dtype=np.int64))
                c.push(np.arange(8, dtype=np.int64),
                       np.ones((8, DIM), np.float32))
                assert c.flush(timeout=15.0)
                text = REGISTRY.exposition()
                for gauge in ("paddle_tpu_embed_shard_rows",
                              "paddle_tpu_embed_shard_applied_updates",
                              "paddle_tpu_embed_client_cached_rows",
                              "paddle_tpu_embed_client_pushes"):
                    assert gauge in text, f"missing {gauge}"
                assert 'shard="0"' in text and 'shard="1"' in text
                state = FLIGHT.bundle(reason="test")["state"]
                assert "embed" in state
                assert any(s["shard_id"] == 0
                           for s in state["embed"]["shards"])