"""CLI trainer tests — `paddle train --config --job=train|time|test`
parity (TrainerMain.cpp:32-58, TrainerBenchmark.cpp)."""

import json
import os
import subprocess
import sys

import numpy as np

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CONFIG = os.path.join(REPO, "demo", "mnist", "config.py")


def _run_cli(args, timeout=600):
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"
    for attempt in range(2):
        r = subprocess.run(
            [sys.executable, "-m", "paddle_tpu.cli"] + args,
            capture_output=True, text=True, timeout=timeout, env=env)
        if r.returncode == 0:
            return r
        # each CLI test boots a fresh JAX process; under a saturated
        # host (the full suite) imports/compiles can starve — one retry
        # separates real CLI bugs from load-induced subprocess deaths
    return r


class TestCLI:
    def test_job_time_prints_json(self, tmp_path):
        r = _run_cli(["train", "--config", CONFIG, "--job", "time",
                      "--batch_size", "32", "--iters", "4"])
        assert r.returncode == 0, r.stderr[-2000:]
        line = [l for l in r.stdout.splitlines()
                if l.startswith("{")][-1]
        rec = json.loads(line)
        assert rec["metric"] == "train_ms_per_batch"
        assert rec["value"] > 0

    def test_job_profile_writes_xplane(self, tmp_path):
        prof = str(tmp_path / "prof")
        r = _run_cli(["train", "--config", CONFIG, "--job", "profile",
                      "--batch_size", "16", "--iters", "3",
                      "--profile_dir", prof])
        assert r.returncode == 0, r.stderr[-2000:]
        line = [l for l in r.stdout.splitlines() if l.startswith("{")][0]
        rec = json.loads(line)
        assert rec["job"] == "profile" and rec["status"] == "ok"
        # the CPU backend also emits xplane traces, so the artifact must
        # exist even in the virtual-device test lane
        assert rec["xplane"] and os.path.exists(rec["xplane"])

    def test_version(self):
        r = _run_cli(["version"])
        assert r.returncode == 0, r.stderr[-500:]
        rec = json.loads(r.stdout.strip().splitlines()[-1])
        assert rec["framework"] == "paddle_tpu" and rec["version"]

    def test_coordinator_daemon(self, tmp_path):
        """`paddle_tpu coordinator` is the paddle_master binary's role
        (go/cmd/master): partition a RecordIO file, serve tasks over
        RPC, stop cleanly on SIGTERM."""
        import signal
        import time as _time

        from paddle_tpu.reader import recordio as rio
        from paddle_tpu.trainer.coordinator import connect

        data = str(tmp_path / "train.ptr")
        rio.write_records(data, [f"r{i}".encode() for i in range(64)],
                          max_chunk_bytes=256)
        env = dict(os.environ)
        env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
        env["JAX_PLATFORMS"] = "cpu"
        proc = subprocess.Popen(
            [sys.executable, "-m", "paddle_tpu.cli", "coordinator",
             "--data", data, "--chunks_per_task", "2",
             "--snapshot", str(tmp_path / "snap")],
            stdout=subprocess.PIPE, text=True, env=env)
        try:
            line = proc.stdout.readline()
            rec = json.loads(line)
            assert rec["status"] == "serving" and rec["chunks"] >= 2
            client = connect("127.0.0.1", rec["port"])
            task = client.get_task(0)     # epoch-0 task request
            assert task and task["chunks"]
            client.task_finished(task["task_id"])
            assert os.path.isdir(str(tmp_path / "snap"))
        finally:
            proc.send_signal(signal.SIGTERM)
            try:
                proc.wait(timeout=30)
            except subprocess.TimeoutExpired:
                proc.kill()
                raise
        assert proc.returncode == 0

        # restart against the same snapshot: the daemon must recover the
        # dispatched-task state (service.go recover:166) and SAY so
        proc = subprocess.Popen(
            [sys.executable, "-m", "paddle_tpu.cli", "coordinator",
             "--data", data, "--chunks_per_task", "4",
             "--snapshot", str(tmp_path / "snap")],
            stdout=subprocess.PIPE, text=True, env=env)
        try:
            rec2 = json.loads(proc.stdout.readline())
            assert rec2["status"] == "serving"
            assert rec2["recovered"] is True
            # the snapshot's partitioning wins over the new CLI args
            assert rec2["chunks_per_task"] == 2
        finally:
            proc.send_signal(signal.SIGTERM)
            try:
                proc.wait(timeout=30)
            except subprocess.TimeoutExpired:
                proc.kill()
                raise
        assert proc.returncode == 0

    def test_job_train_saves_and_test_restores(self, tmp_path):
        save = str(tmp_path / "out")
        r = _run_cli(["train", "--config", CONFIG, "--job", "train",
                      "--num_passes", "1", "--save_dir", save,
                      "--log_period", "16"])
        assert r.returncode == 0, r.stderr[-2000:]
        assert "Pass 0 done" in r.stdout
        tar = os.path.join(save, "pass-00000", "params.tar")
        assert os.path.exists(tar)

        r2 = _run_cli(["train", "--config", CONFIG, "--job", "test",
                       "--init_model_path", tar])
        assert r2.returncode == 0, r2.stderr[-2000:]
        assert "Test: cost=" in r2.stdout

    def test_job_time_from_serialized_topology(self, tmp_path):
        # the JSON topology contract has a consumer outside the tests now
        import jax
        jax.config.update("jax_platforms", "cpu")
        import paddle_tpu as paddle
        from paddle_tpu.core import registry
        registry.reset_name_counters()
        img = paddle.layer.data("x", paddle.data_type.dense_vector(16))
        out = paddle.layer.fc(img, size=4,
                              act=paddle.activation.Softmax())
        lbl = paddle.layer.data("y", paddle.data_type.integer_value(4))
        cost = paddle.layer.classification_cost(out, lbl, name="cost")
        blob = paddle.Topology(cost).serialize()
        p = tmp_path / "model.json"
        p.write_text(blob)
        r = _run_cli(["train", "--config", str(p), "--job", "time",
                      "--batch_size", "16", "--iters", "3"])
        assert r.returncode == 0, r.stderr[-2000:]
        rec = json.loads([l for l in r.stdout.splitlines()
                          if l.startswith("{")][-1])
        assert rec["value"] > 0


class TestCheckGrad:
    def test_checkgrad_on_demo_config(self):
        """--job=checkgrad (Trainer.h:43 checkGradient parity): the
        finite-difference audit runs over an arbitrary --config."""
        r = _run_cli(["train", "--config", CONFIG, "--job", "checkgrad",
                      "--batch_size", "8"])
        assert r.returncode == 0, r.stderr[-2000:]
        rec = json.loads([l for l in r.stdout.splitlines()
                          if l.startswith("{")][-1])
        assert rec["job"] == "checkgrad" and rec["status"] == "ok"
        assert rec["params_checked"] >= 6   # 3 fc layers x (w, b)


class TestMergeInfer:
    def test_train_merge_infer_capi_roundtrip(self, tmp_path):
        """The VERDICT exit criterion for MergeModel parity: train one
        pass -> `paddle_tpu merge` -> `paddle_tpu infer` -> a C-ABI
        forward over the SAME merged artifact."""
        save = str(tmp_path / "out")
        r = _run_cli(["train", "--config", CONFIG, "--job", "train",
                      "--num_passes", "1", "--save_dir", save,
                      "--log_period", "64"])
        assert r.returncode == 0, r.stderr[-2000:]
        tar = os.path.join(save, "pass-00000", "params.tar")

        merged = str(tmp_path / "merged.tar")
        r = _run_cli(["merge", "--config", CONFIG,
                      "--init_model_path", tar, "--out", merged])
        assert r.returncode == 0, r.stderr[-2000:]
        assert os.path.exists(merged)

        r = _run_cli(["infer", "--model", merged, "--batch_size", "4"])
        assert r.returncode == 0, r.stderr[-2000:]
        rec = json.loads([l for l in r.stdout.splitlines()
                          if l.startswith("{")][-1])
        assert rec["output_shape"] == [4, 10]
        probs = np.array(rec["row0"])
        assert (probs >= 0).all() and probs.sum() < 1.0 + 1e-3

        # C ABI forward over the merged artifact (capi parity)
        from tests.test_capi import TestCABI
        import sysconfig
        exe = TestCABI()._build(tmp_path)
        site = sysconfig.get_path("purelib")
        env = dict(os.environ)
        env["PYTHONPATH"] = os.pathsep.join(
            [REPO, site, env.get("PYTHONPATH", "")])
        env["JAX_PLATFORMS"] = "cpu"
        rc = subprocess.run([exe, merged, "784"], capture_output=True,
                            text=True, timeout=600, env=env)
        assert rc.returncode == 0, (rc.stdout[-2000:], rc.stderr[-2000:])
        assert "out_dim=10" in rc.stdout


class TestMemoryFlags:
    """--microbatch/--oom_probe (train) and --max_batch_memory (serve)
    wiring: the flags must reach SGD.train / InferenceServer
    (docs/robustness.md "Memory pressure")."""

    def _tiny_config(self, tmp_path):
        cfg = tmp_path / "conf.py"
        cfg.write_text(
            "import numpy as np\n"
            "import paddle_tpu as paddle\n"
            "x = paddle.layer.data('x', paddle.data_type.dense_vector(4))\n"
            "y = paddle.layer.data('y', paddle.data_type.integer_value(2))\n"
            "out = paddle.layer.fc(x, size=2,"
            " act=paddle.activation.Softmax())\n"
            "cost = paddle.layer.classification_cost(out, y)\n"
            "def train_reader():\n"
            "    rng = np.random.RandomState(0)\n"
            "    for _ in range(2):\n"
            "        f = rng.randn(4, 4).astype('float32')\n"
            "        yield [(f[i], int(rng.randint(0, 2)))"
            " for i in range(4)]\n")
        return str(cfg)

    def test_train_microbatch_flags_reach_sgd(self, tmp_path,
                                              monkeypatch):
        import paddle_tpu as paddle
        from paddle_tpu import cli

        captured = {}

        def fake_train(self, reader=None, **kw):
            captured.update(kw)

        monkeypatch.setattr(paddle.SGD, "train", fake_train)
        cfg = self._tiny_config(tmp_path)
        rc = cli.main(["train", "--config", cfg,
                       "--microbatch", "auto", "--oom_probe"])
        assert rc == 0
        assert captured["microbatch"] == "auto"
        assert captured["oom_probe"] is True

        captured.clear()
        rc = cli.main(["train", "--config", cfg, "--microbatch", "16"])
        assert rc == 0
        assert captured["microbatch"] == 16     # numeric form -> int
        assert captured["oom_probe"] is False

        captured.clear()
        rc = cli.main(["train", "--config", cfg])
        assert rc == 0
        assert captured["microbatch"] is None   # default: off

    def test_train_microbatch_end_to_end(self, tmp_path):
        # the real path (no mocks): a tiny config trains microbatched
        # through the CLI in-process
        from paddle_tpu import cli
        rc = cli.main(["train", "--config", self._tiny_config(tmp_path),
                       "--microbatch", "2", "--num_passes", "1",
                       "--log_period", "1"])
        assert rc == 0

    def test_serve_max_batch_memory_reaches_server(self, monkeypatch):
        from paddle_tpu import cli

        class FakeServer:
            def __init__(self, model, **kw):
                self.kw = kw

            def start(self):
                return self

        class FakeBreaker:
            def __init__(self, **kw):
                pass

        import argparse
        ns = argparse.Namespace(
            model="m.tar", max_queue=8, workers=1, deadline_ms=0,
            max_batch_memory=4096, breaker_window=4,
            breaker_threshold=0.5, breaker_cooldown=1.0,
            host="127.0.0.1", port=0)
        server, httpd = cli._build_server(
            ns, FakeServer, FakeBreaker,
            lambda srv, host, port, on_quit=None:
            ("httpd", host, port))
        assert server.kw["max_batch_memory"] == 4096
        assert httpd == ("httpd", "127.0.0.1", 0)

        ns.max_batch_memory = 0                 # 0 -> disabled (None)
        server, _ = cli._build_server(
            ns, FakeServer, FakeBreaker, lambda *a, **k: None)
        assert server.kw["max_batch_memory"] is None


class TestObservabilityFlags:
    """ISSUE 7 satellite: train --metrics_port/--event_log wiring and
    the `paddle_tpu events tail` subcommand."""

    def _tiny_config(self, tmp_path):
        cfg = tmp_path / "conf.py"
        cfg.write_text(
            "import numpy as np\n"
            "import paddle_tpu as paddle\n"
            "x = paddle.layer.data('x', paddle.data_type.dense_vector(4))\n"
            "y = paddle.layer.data('y', paddle.data_type.integer_value(2))\n"
            "out = paddle.layer.fc(x, size=2,"
            " act=paddle.activation.Softmax())\n"
            "cost = paddle.layer.classification_cost(out, y)\n"
            "def train_reader():\n"
            "    rng = np.random.RandomState(0)\n"
            "    for _ in range(2):\n"
            "        f = rng.randn(4, 4).astype('float32')\n"
            "        yield [(f[i], int(rng.randint(0, 2)))"
            " for i in range(4)]\n")
        return str(cfg)

    def test_train_event_log_writes_journal(self, tmp_path):
        from paddle_tpu import cli
        from paddle_tpu.obs.events import read_journal
        log = str(tmp_path / "train.jsonl")
        rc = cli.main(["train", "--config", self._tiny_config(tmp_path),
                       "--num_passes", "1", "--log_period", "1",
                       "--event_log", log])
        assert rc == 0
        kinds = [(r["domain"], r["kind"]) for r in read_journal(log)]
        assert ("trainer", "run_start") in kinds
        assert ("trainer", "run_end") in kinds

    def test_train_metrics_port_starts_obs_server(self, tmp_path,
                                                  monkeypatch):
        import paddle_tpu as paddle
        from paddle_tpu import cli
        from paddle_tpu.obs import httpd as obs_httpd

        started = []

        class FakeServer:
            server_address = ("127.0.0.1", 12345)

            def shutdown(self):
                started.append("shutdown")

        def fake_start(host="127.0.0.1", port=0):
            started.append(port)
            return FakeServer()

        monkeypatch.setattr(obs_httpd, "start_obs_server", fake_start)
        monkeypatch.setattr(paddle.SGD, "train",
                            lambda self, reader=None, **kw: None)
        rc = cli.main(["train", "--config", self._tiny_config(tmp_path),
                       "--metrics_port", "0"])
        assert rc == 0
        # started with the requested port, and shut down on exit
        assert started == [0, "shutdown"]

    def test_events_tail_subcommand(self, tmp_path, capsys):
        from paddle_tpu import cli
        from paddle_tpu.obs.events import EventJournal
        log = str(tmp_path / "j.jsonl")
        j = EventJournal()
        j.configure(log)
        for i in range(5):
            j.emit("data", "quarantine", count=i)
        j.emit("serving", "shed", reason="queue_full")
        j.configure(None)
        rc = cli.main(["events", "tail", "--log", log, "-n", "2",
                       "--domain", "data"])
        assert rc == 0
        lines = [json.loads(l) for l in
                 capsys.readouterr().out.strip().splitlines()]
        assert [l["count"] for l in lines] == [3, 4]
        assert all(l["domain"] == "data" for l in lines)
        rc = cli.main(["events", "tail", "--log", log,
                       "--kind", "shed"])
        assert rc == 0
        out = capsys.readouterr().out
        assert json.loads(out.strip())["reason"] == "queue_full"
        with pytest.raises(SystemExit):
            cli.main(["events", "tail", "--log",
                      str(tmp_path / "missing.jsonl")])

    def test_serve_event_log_configures_journal(self, tmp_path,
                                                monkeypatch):
        # serve --event_log must attach the journal sink before the
        # server loop starts (the loop itself is stubbed out)
        from paddle_tpu import cli
        from paddle_tpu.obs.events import JOURNAL
        log = str(tmp_path / "serve.jsonl")
        monkeypatch.setattr(cli, "_cmd_serve", lambda args: 0)
        rc = cli.main(["serve", "--model", "m.tar",
                       "--event_log", log])
        assert rc == 0
        assert JOURNAL.path == log
        JOURNAL.configure(None)


class TestDecodeEngineFlags:
    """ISSUE 13 satellite: serve --decode_config/--draft_config/
    --spec_k/--prefix_cache wiring down to DecodeEngine."""

    DEC_SRC = (
        "import jax\n"
        "import paddle_tpu as paddle\n"
        "from paddle_tpu import models\n"
        "from paddle_tpu.core.registry import reset_name_counters\n"
        "paddle.init(use_tpu=False, seed=0)\n"
        "reset_name_counters()\n"
        "spec = models.transformer_lm(vocab_size=40, d_model=16,\n"
        "                             n_heads=2, n_layers=2, d_ff=32,\n"
        "                             max_len=32)\n"
        "costs = (spec.cost if isinstance(spec.cost, list)\n"
        "         else [spec.cost])\n"
        "topo = paddle.Topology(costs, extra_outputs=[spec.output])\n"
        "params = topo.init_params(jax.random.PRNGKey({seed}))\n"
        "{name} = models.TransformerDecoder(params, n_layers=2,\n"
        "                                   n_heads=2)\n")

    def test_serve_flags_parse_with_defaults(self, monkeypatch):
        from paddle_tpu import cli
        seen = {}
        monkeypatch.setattr(cli, "_cmd_serve",
                            lambda args: seen.update(vars(args)) or 0)
        assert cli.main(["serve", "--model", "m.tar"]) == 0
        assert seen["decode_config"] is None
        assert seen["draft_config"] is None
        assert seen["spec_k"] == 0
        assert seen["prefix_cache"] == "on"
        assert seen["gen_slots"] == 4 and seen["gen_page_size"] == 16
        assert cli.main(["serve", "--model", "m.tar",
                        "--decode_config", "dec.py",
                         "--draft_config", "draft.py",
                         "--spec_k", "3", "--prefix_cache", "off",
                         "--gen_slots", "2",
                         "--gen_page_size", "8"]) == 0
        assert seen["decode_config"] == "dec.py"
        assert seen["draft_config"] == "draft.py"
        assert seen["spec_k"] == 3
        assert seen["prefix_cache"] == "off"
        assert seen["gen_slots"] == 2 and seen["gen_page_size"] == 8

    def test_build_server_attaches_engine_via_builder(self):
        import argparse

        from paddle_tpu import cli

        class FakeServer:
            def __init__(self, model, **kw):
                self.kw = kw

            def start(self):
                return self

        class FakeBreaker:
            def __init__(self, **kw):
                pass

        sentinel = object()
        built = []

        def builder(a):
            built.append(a)
            return sentinel

        ns = argparse.Namespace(
            model="m.tar", max_queue=8, workers=1, deadline_ms=0,
            max_batch_memory=0, breaker_window=4,
            breaker_threshold=0.5, breaker_cooldown=1.0,
            host="127.0.0.1", port=0, decode_config="dec.py")
        server, _ = cli._build_server(
            ns, FakeServer, FakeBreaker, lambda *a, **k: None,
            engine_builder=builder)
        assert built == [ns]
        assert server.kw["engine"] is sentinel
        # no --decode_config -> no engine construction at all
        ns2 = argparse.Namespace(
            model="m.tar", max_queue=8, workers=1, deadline_ms=0,
            max_batch_memory=0, breaker_window=4,
            breaker_threshold=0.5, breaker_cooldown=1.0,
            host="127.0.0.1", port=0)
        server2, _ = cli._build_server(
            ns2, FakeServer, FakeBreaker, lambda *a, **k: None,
            engine_builder=builder)
        assert server2.kw["engine"] is None and len(built) == 1

    def test_build_engine_from_config_scripts(self, tmp_path):
        import argparse

        from paddle_tpu import cli
        dec = tmp_path / "dec.py"
        dec.write_text(self.DEC_SRC.format(seed=7, name="decoder"))
        dr = tmp_path / "draft.py"
        dr.write_text(self.DEC_SRC.format(seed=11,
                                          name="draft_decoder"))
        ns = argparse.Namespace(
            decode_config=str(dec), draft_config=str(dr), spec_k=2,
            prefix_cache="on", gen_slots=2, gen_page_size=4)
        eng = cli._build_engine(ns)
        st = eng.stats()
        assert st["slots"] == 2 and st["page_size"] == 4
        assert st["spec_k"] == 2 and st["window"] == 3
        assert eng.prefix is not None
        # prefix off + no draft: classic one-token window
        ns2 = argparse.Namespace(
            decode_config=str(dec), draft_config=None, spec_k=2,
            prefix_cache="off", gen_slots=2, gen_page_size=4)
        eng2 = cli._build_engine(ns2)
        st2 = eng2.stats()
        assert eng2.prefix is None
        assert st2["spec_k"] == 0 and st2["window"] == 1
        # a config without `decoder` is a typed CLI error
        bad = tmp_path / "bad.py"
        bad.write_text("x = 1\n")
        ns3 = argparse.Namespace(
            decode_config=str(bad), draft_config=None, spec_k=0,
            prefix_cache="on", gen_slots=2, gen_page_size=4)
        with pytest.raises(SystemExit):
            cli._build_engine(ns3)

    def test_two_tier_kv_flags_reach_engine(self, tmp_path):
        """ISSUE 20 satellite: serve --kv_quant/--kv_spill_pages parse
        (with single-tier defaults) and wire through _build_engine to
        the int8 pools and the host spill store."""
        import argparse

        from paddle_tpu import cli

        seen = {}

        def _grab(args):
            seen.update(vars(args))
            return 0

        import unittest.mock as mock
        with mock.patch.object(cli, "_cmd_serve", _grab):
            assert cli.main(["serve", "--model", "m.tar"]) == 0
            assert seen["kv_quant"] == "none"
            assert seen["kv_spill_pages"] == 0
            assert cli.main(["serve", "--model", "m.tar",
                             "--kv_quant", "int8",
                             "--kv_spill_pages", "32"]) == 0
            assert seen["kv_quant"] == "int8"
            assert seen["kv_spill_pages"] == 32

        dec = tmp_path / "dec.py"
        dec.write_text(self.DEC_SRC.format(seed=7, name="decoder"))
        ns = argparse.Namespace(
            decode_config=str(dec), draft_config=None, spec_k=0,
            prefix_cache="on", gen_slots=2, gen_page_size=4,
            kv_quant="int8", kv_spill_pages=8)
        eng = cli._build_engine(ns)
        st = eng.stats()
        assert st["kv_quant"] == "int8" and st["kv_quant_bits"] == 8
        assert st["kv_spill_capacity"] == 8
        assert eng.spill is not None
        # defaults stay single-tier fp32
        ns2 = argparse.Namespace(
            decode_config=str(dec), draft_config=None, spec_k=0,
            prefix_cache="on", gen_slots=2, gen_page_size=4,
            kv_quant="none", kv_spill_pages=0)
        eng2 = cli._build_engine(ns2)
        assert eng2.spill is None and eng2.kv_quant is None

    def test_router_kv_flags_extend_spawn_cmd(self):
        """router --kv_quant/--kv_spill_pages append to the autopilot
        spawn command so autoscaled replicas boot in the fleet's KV
        mode."""
        import argparse

        from paddle_tpu import cli

        class _Router:
            pass

        ns = argparse.Namespace(
            spawn_cmd="paddle_tpu serve --decode_config d.py",
            kv_quant="int8", kv_spill_pages=16, min_replicas=1,
            max_replicas=2, autopilot_interval=1.0, drain_timeout=5.0)
        ap = cli._build_autopilot(ns, _Router())
        argv = ap.provisioner.argv
        assert argv[-4:] == ["--kv_quant", "int8",
                             "--kv_spill_pages", "16"]
        # single-tier defaults: the spawn command is left untouched
        ns2 = argparse.Namespace(
            spawn_cmd="paddle_tpu serve --decode_config d.py",
            kv_quant="none", kv_spill_pages=0, min_replicas=1,
            max_replicas=2, autopilot_interval=1.0, drain_timeout=5.0)
        ap2 = cli._build_autopilot(ns2, _Router())
        assert "--kv_quant" not in ap2.provisioner.argv
        assert "--kv_spill_pages" not in ap2.provisioner.argv


class TestFlightCLI:
    """ISSUE 8 satellites: `obs selfcheck`/`obs dump`, `events tail
    --follow`, and the serve/train flight/run_id flag wiring."""

    def test_obs_selfcheck_smoke(self, capsys):
        # the tier-1 smoke step: every observability surface
        # exercised end-to-end in one verb
        from paddle_tpu import cli
        rc = cli.main(["obs", "selfcheck"])
        out = json.loads(capsys.readouterr().out.strip())
        assert rc == 0
        assert out["status"] == "ok"
        assert set(out["checks"]) == {"metrics_scrape",
                                      "journal_roundtrip",
                                      "trace_spans", "flight_dump"}
        assert all(out["checks"].values())

    def test_obs_dump_writes_bundle(self, tmp_path, capsys):
        from paddle_tpu import cli
        from paddle_tpu.obs.flight import FLIGHT
        FLIGHT.record("mark", "cli-probe")
        out = str(tmp_path / "bundle.json")
        rc = cli.main(["obs", "dump", "--out", out])
        assert rc == 0
        assert json.loads(capsys.readouterr().out)["out"] == out
        with open(out) as f:
            bundle = json.load(f)
        assert bundle["reason"] == "cli"
        assert any(r["name"] == "cli-probe" for r in bundle["ring"])

    def test_events_follow_streams_appended_records(self, tmp_path):
        """The --follow seam: records appended AFTER the follower
        starts are yielded; it exits on idle timeout."""
        import threading
        import time as _time

        from paddle_tpu import cli
        from paddle_tpu.obs.events import EventJournal
        log = str(tmp_path / "f.jsonl")
        j = EventJournal()
        j.configure(log)
        j.emit("t", "before")

        def appender():
            _time.sleep(0.3)
            j.emit("t", "live-1")
            j.emit("x", "filtered-out")
            _time.sleep(0.1)
            j.emit("t", "live-2")
            j.configure(None)

        t = threading.Thread(target=appender, daemon=True,
                             name="pt-test-follow")
        t.start()
        got = list(cli._iter_journal_follow(
            log, domain="t", poll=0.05, idle_timeout=1.5,
            from_pos=os.path.getsize(log)))
        t.join()
        assert [r["kind"] for r in got] == ["live-1", "live-2"]

    def test_events_tail_follow_flag_exits_after_idle(self, tmp_path,
                                                      capsys):
        from paddle_tpu import cli
        from paddle_tpu.obs.events import EventJournal
        log = str(tmp_path / "f2.jsonl")
        j = EventJournal()
        j.configure(log)
        j.emit("t", "k0")
        j.configure(None)
        rc = cli.main(["events", "tail", "--log", log, "--follow",
                       "--exit-after-idle", "0.3"])
        assert rc == 0
        lines = capsys.readouterr().out.strip().splitlines()
        assert json.loads(lines[-1])["kind"] == "k0"

    def test_serve_flight_flags_arm_recorder(self, tmp_path,
                                             monkeypatch):
        from paddle_tpu import cli
        from paddle_tpu.obs import context as obs_context
        from paddle_tpu.obs.flight import FLIGHT
        monkeypatch.setattr(cli, "_cmd_serve", lambda args: 0)
        fdir = str(tmp_path / "flight")
        rc = cli.main(["serve", "--model", "m.tar",
                       "--flight_dir", fdir,
                       "--run_id", "run-cli-test"])
        assert rc == 0
        assert FLIGHT.dump_dir == fdir
        assert obs_context.get_run_id() == "run-cli-test"

    def test_trace_merge_subcommand(self, tmp_path, capsys):
        from paddle_tpu import cli
        from paddle_tpu.obs.events import EventJournal, read_journal
        log = str(tmp_path / "one.jsonl")
        j = EventJournal()
        j.configure(log)
        j.emit("t", "a")
        j.emit("t", "b")
        j.configure(None)
        out = str(tmp_path / "merged.jsonl")
        rc = cli.main(["trace", "merge", "--journal", log,
                       "--out-journal", out])
        assert rc == 0
        summary = json.loads(capsys.readouterr().out.strip())
        assert summary["records"] == 2
        assert [r["mseq"] for r in read_journal(out)] == [1, 2]


class TestPserverCLI:
    def test_pserver_daemon_snapshot_restart_restores(self, tmp_path):
        """`paddle_tpu pserver` is the 2017 parameter-server binary
        reborn: serve one shard's gather/scatter RPCs, register on a
        coordinator daemon's membership plane, snapshot on SIGTERM,
        and restore the key range digest-stable on restart."""
        import signal

        from paddle_tpu.embed import EmbeddingClient
        from paddle_tpu.reader import recordio as rio
        from paddle_tpu.trainer.coordinator import connect

        env = dict(os.environ)
        env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
        env["JAX_PLATFORMS"] = "cpu"

        def _stop(proc):
            proc.send_signal(signal.SIGTERM)
            try:
                out, _ = proc.communicate(timeout=30)
            except subprocess.TimeoutExpired:
                proc.kill()
                raise
            assert proc.returncode == 0
            return json.loads(out.strip().splitlines()[-1])

        data = str(tmp_path / "train.ptr")
        rio.write_records(data, [b"r0", b"r1"], max_chunk_bytes=64)
        coord = subprocess.Popen(
            [sys.executable, "-m", "paddle_tpu.cli", "coordinator",
             "--data", data, "--worker_lease", "30"],
            stdout=subprocess.PIPE, text=True, env=env)
        ps = None
        try:
            cport = json.loads(coord.stdout.readline())["port"]
            ps = subprocess.Popen(
                [sys.executable, "-m", "paddle_tpu.cli", "pserver",
                 "--shard_id", "0", "--shards", "1", "--dim", "8",
                 "--coordinator", f"127.0.0.1:{cport}",
                 "--snapshot_dir", str(tmp_path / "snap")],
                stdout=subprocess.PIPE, text=True, env=env)
            rec = json.loads(ps.stdout.readline())
            assert rec["status"] == "serving" and rec["shard_id"] == 0
            assert rec["restored"] is False
            assert isinstance(rec["generation"], int)

            # the membership directory answers with the daemon's endpoint
            info = connect("127.0.0.1", cport).worker_info("embed/0")
            assert info and info["endpoint"] == rec["endpoint"]

            keys = np.arange(6, dtype=np.int64)
            with EmbeddingClient(1, 8, endpoints={0: rec["endpoint"]},
                                 client_id="cli-test") as client:
                before = client.gather(keys)
                client.push(keys, np.ones((6, 8), np.float32), lr=0.5)
                assert client.flush(timeout=20.0)
                after = client.gather(keys, max_stale_s=0.0)
            np.testing.assert_allclose(after, before - 0.5, rtol=1e-6)

            stopped = _stop(ps)
            assert stopped["status"] == "stopped"
            assert stopped["stats"]["applied_updates"] == 1

            # a replacement with the same flags restores the key range
            ps = subprocess.Popen(
                [sys.executable, "-m", "paddle_tpu.cli", "pserver",
                 "--shard_id", "0", "--shards", "1", "--dim", "8",
                 "--snapshot_dir", str(tmp_path / "snap")],
                stdout=subprocess.PIPE, text=True, env=env)
            rec2 = json.loads(ps.stdout.readline())
            assert rec2["restored"] is True
            with EmbeddingClient(1, 8, endpoints={0: rec2["endpoint"]},
                                 client_id="cli-test-2") as client:
                restored = client.gather(keys)
            np.testing.assert_array_equal(restored, after)
            assert _stop(ps)["status"] == "stopped"
            ps = None
        finally:
            if ps is not None:
                ps.kill()
            coord.send_signal(signal.SIGTERM)
            try:
                coord.wait(timeout=30)
            except subprocess.TimeoutExpired:
                coord.kill()
                raise


class TestRouterCLI:
    """ISSUE 15 satellite: `paddle_tpu router` flag wiring down to
    Router, and the SIGTERM teardown contract (drain, leave, close —
    in that order)."""

    def test_router_flags_parse_with_defaults(self, monkeypatch):
        from paddle_tpu import cli
        seen = {}
        monkeypatch.setattr(cli, "_cmd_router",
                            lambda args: seen.update(vars(args)) or 0)
        assert cli.main(["router", "--coordinator",
                         "127.0.0.1:9001"]) == 0
        assert seen["coordinator"] == "127.0.0.1:9001"
        assert seen["host"] == "127.0.0.1" and seen["port"] == 0
        assert seen["affinity"] == "prefix"
        assert seen["drain_timeout"] == 10.0
        assert seen["page_size"] == 16
        assert seen["scrape_interval"] == 0.5
        assert seen["queue_timeout"] == 5.0
        assert seen["heartbeat"] == 1.0
        assert cli.main(["router", "--coordinator", "h:1",
                         "--port", "8088", "--affinity", "load",
                         "--drain_timeout", "3.5", "--page_size", "4",
                         "--scrape_interval", "0.1",
                         "--queue_timeout", "2.0"]) == 0
        assert seen["port"] == 8088 and seen["affinity"] == "load"
        assert seen["drain_timeout"] == 3.5 and seen["page_size"] == 4
        # --coordinator is required; --affinity is a closed choice
        with pytest.raises(SystemExit):
            cli.main(["router"])
        with pytest.raises(SystemExit):
            cli.main(["router", "--coordinator", "h:1",
                      "--affinity", "random"])

    def test_build_router_wires_flags(self):
        import argparse

        from paddle_tpu import cli

        coord_sentinel = object()
        connected = []

        def fake_connect(host, port):
            connected.append((host, port))
            return coord_sentinel

        class FakeRouter:
            def __init__(self, coordinator=None, **kw):
                self.coordinator = coordinator
                self.kw = kw
                self.started = False

            def start(self):
                self.started = True
                return self

        built = []

        def fake_http(router, host, port, autopilot=None):
            built.append((router, host, port, autopilot))
            return object()

        ns = argparse.Namespace(
            coordinator="10.0.0.5:4321", affinity="load", page_size=8,
            scrape_interval=0.25, queue_timeout=3.0, drain_timeout=7.0,
            host="0.0.0.0", port=8088)
        router, httpd, coord, autopilot = cli._build_router(
            ns, FakeRouter, fake_http, fake_connect)
        assert connected == [("10.0.0.5", 4321)]
        assert coord is coord_sentinel
        assert router.coordinator is coord_sentinel
        assert router.started
        assert router.kw == {"affinity": "load", "page_size": 8,
                             "scrape_interval": 0.25,
                             "queue_timeout": 3.0,
                             "drain_timeout": 7.0}
        # no --autopilot/--spawn_cmd -> no control loop constructed
        assert autopilot is None
        assert built == [(router, "0.0.0.0", 8088, None)]

    def test_router_teardown_order_drain_leave_close(self):
        from paddle_tpu import cli

        calls = []

        class FakeRouter:
            def shutdown(self, drain=False, timeout=None):
                assert drain is True
                calls.append("drain")

        class FakeReg:
            def stop(self, leave=False):
                assert leave is True
                calls.append("leave")

        class FakeHttpd:
            def shutdown(self):
                calls.append("close")

            def server_close(self):
                calls.append("close_socket")

        class FakeAutopilot:
            def stop(self):
                calls.append("autopilot_stop")

        cli._router_teardown(FakeRouter(), FakeReg(), FakeHttpd())
        # the contract: stop admitting + settle in-flight FIRST, then
        # drop the directory entry, only then kill the socket
        assert calls == ["drain", "leave", "close", "close_socket"]
        # a router that never joined the directory still tears down
        calls.clear()
        cli._router_teardown(FakeRouter(), None, FakeHttpd())
        assert calls == ["drain", "close", "close_socket"]
        # with an autopilot, its control loop stops BEFORE the drain:
        # no scale/deploy decision may race the teardown
        calls.clear()
        cli._router_teardown(FakeRouter(), FakeReg(), FakeHttpd(),
                             autopilot=FakeAutopilot())
        assert calls == ["autopilot_stop", "drain", "leave", "close",
                         "close_socket"]

    def test_router_daemon_serves_and_sigterm_drains(self, tmp_path):
        """End-to-end daemon: a router fronting an EMPTY fleet still
        serves /health + /stats + /metrics, registers itself on the
        membership plane, and exits 0 with a stats line on SIGTERM."""
        import signal
        import urllib.request

        from paddle_tpu.reader import recordio as rio
        from paddle_tpu.trainer.coordinator import connect

        env = dict(os.environ)
        env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
        env["JAX_PLATFORMS"] = "cpu"
        data = str(tmp_path / "train.ptr")
        rio.write_records(data, [b"r0", b"r1"], max_chunk_bytes=64)
        coord = subprocess.Popen(
            [sys.executable, "-m", "paddle_tpu.cli", "coordinator",
             "--data", data, "--worker_lease", "30"],
            stdout=subprocess.PIPE, text=True, env=env)
        rt = None
        try:
            cport = json.loads(coord.stdout.readline())["port"]
            rt = subprocess.Popen(
                [sys.executable, "-m", "paddle_tpu.cli", "router",
                 "--coordinator", f"127.0.0.1:{cport}",
                 "--scrape_interval", "0.1",
                 "--event_log", str(tmp_path / "router.jsonl")],
                stdout=subprocess.PIPE, text=True, env=env)
            rec = json.loads(rt.stdout.readline())
            assert rec["job"] == "router"
            assert rec["status"] == "serving" and rec["replicas"] == 0
            base = f"http://127.0.0.1:{rec['port']}"
            with urllib.request.urlopen(base + "/health",
                                        timeout=10) as r:
                health = json.loads(r.read())
            assert health["status"] == "no_replicas"
            with urllib.request.urlopen(base + "/metrics",
                                        timeout=10) as r:
                text = r.read().decode()
            assert "paddle_tpu_fleet_routed 0" in text
            # the router keeps its own directory lease
            info = connect("127.0.0.1", cport).worker_info(
                "fleet/router")
            assert info and info["role"] == "fleet_router"
            assert info["endpoint"] == base

            rt.send_signal(signal.SIGTERM)
            out, _ = rt.communicate(timeout=30)
            assert rt.returncode == 0
            stopped = json.loads(out.strip().splitlines()[-1])
            assert stopped["status"] == "stopped"
            assert stopped["stats"]["routed"] == 0
            rt = None
            # the goodbye reached the directory before the exit
            assert connect("127.0.0.1", cport).worker_info(
                "fleet/router") is None
        finally:
            if rt is not None:
                rt.kill()
            coord.send_signal(signal.SIGTERM)
            try:
                coord.wait(timeout=30)
            except subprocess.TimeoutExpired:
                coord.kill()
                raise


class TestFleetCLI:
    """ISSUE 16: the `paddle_tpu fleet` operator verbs and the router
    daemon's autopilot flag wiring (docs/robustness.md "Fleet
    autopilot")."""

    def test_fleet_flags_parse(self, monkeypatch):
        from paddle_tpu import cli
        seen = {}
        monkeypatch.setattr(cli, "_cmd_fleet",
                            lambda args: seen.update(vars(args)) or 0)
        assert cli.main(["fleet", "deploy", "--router",
                         "http://127.0.0.1:8088", "--force"]) == 0
        assert seen["action"] == "deploy" and seen["force"] is True
        assert seen["timeout"] == 600.0
        assert cli.main(["fleet", "scale", "--router", "http://h:1",
                         "--replicas", "3"]) == 0
        assert seen["action"] == "scale" and seen["replicas"] == 3
        assert cli.main(["fleet", "status", "--router",
                         "http://h:1"]) == 0
        assert seen["action"] == "status"
        # --router is required; the action is a closed choice
        with pytest.raises(SystemExit):
            cli.main(["fleet", "deploy"])
        with pytest.raises(SystemExit):
            cli.main(["fleet", "restart", "--router", "http://h:1"])

    def test_build_fleet_request_shapes(self):
        import argparse

        from paddle_tpu import cli
        ns = argparse.Namespace(action="deploy", router="http://h:9/",
                                force=True, replicas=None)
        assert cli._build_fleet_request(ns) == \
            ("POST", "http://h:9/admin/deploy", {"force": True})
        ns = argparse.Namespace(action="scale", router="http://h:9",
                                force=False, replicas=4)
        assert cli._build_fleet_request(ns) == \
            ("POST", "http://h:9/admin/scale", {"replicas": 4})
        ns = argparse.Namespace(action="status", router="http://h:9",
                                force=False, replicas=None)
        assert cli._build_fleet_request(ns) == \
            ("GET", "http://h:9/stats", None)
        # scale without a target is an argument error, not a 400
        ns = argparse.Namespace(action="scale", router="http://h:9",
                                force=False, replicas=None)
        with pytest.raises(SystemExit):
            cli._build_fleet_request(ns)

    def test_router_autopilot_flags_parse(self, monkeypatch):
        from paddle_tpu import cli
        seen = {}
        monkeypatch.setattr(cli, "_cmd_router",
                            lambda args: seen.update(vars(args)) or 0)
        assert cli.main(["router", "--coordinator", "h:1"]) == 0
        assert seen["autopilot"] is False
        assert seen["spawn_cmd"] is None
        assert seen["min_replicas"] == 1
        assert seen["max_replicas"] == 8
        assert seen["autopilot_interval"] == 1.0
        assert cli.main(["router", "--coordinator", "h:1",
                         "--autopilot", "--spawn_cmd",
                         "serve {replica_id}", "--min_replicas", "2",
                         "--max_replicas", "5",
                         "--autopilot_interval", "0.5"]) == 0
        assert seen["autopilot"] is True
        assert seen["spawn_cmd"] == "serve {replica_id}"
        assert seen["min_replicas"] == 2 and seen["max_replicas"] == 5
        assert seen["autopilot_interval"] == 0.5

    def test_build_router_constructs_autopilot(self):
        import argparse

        from paddle_tpu import cli
        from paddle_tpu.fleet.autopilot import (Autopilot,
                                                SubprocessProvisioner)

        class FakeRouter:
            def __init__(self, coordinator=None, **kw):
                self.coordinator = coordinator
                self.kw = kw

            def start(self):
                return self

        built = []

        def fake_http(router, host, port, autopilot=None):
            built.append(autopilot)
            return object()

        ns = argparse.Namespace(
            coordinator="h:4321", affinity="prefix", page_size=16,
            scrape_interval=0.5, queue_timeout=5.0, drain_timeout=10.0,
            host="127.0.0.1", port=0, autopilot=True,
            spawn_cmd="serve {replica_id}", min_replicas=2,
            max_replicas=5, autopilot_interval=0.5)
        router, httpd, coord, ap = cli._build_router(
            ns, FakeRouter, fake_http, lambda h, p: object())
        try:
            assert isinstance(ap, Autopilot)
            assert isinstance(ap.provisioner, SubprocessProvisioner)
            assert ap.provisioner.argv == ["serve", "{replica_id}"]
            assert ap.policy.min_replicas == 2
            assert ap.policy.max_replicas == 5
            assert ap.interval == 0.5
            # the admin plane got the SAME autopilot instance
            assert built == [ap]
        finally:
            ap.stop()       # unhooks the SLO watchdog listener


class TestSoakCLI:
    """ISSUE 17 satellite: `paddle_tpu soak` flag wiring down to
    SoakConfig, and the SIGTERM teardown contract (generators ->
    fleet -> coordinator, in that order)."""

    def test_soak_flags_parse_with_defaults(self, monkeypatch):
        from paddle_tpu import cli
        seen = {}
        monkeypatch.setattr(cli, "_cmd_soak",
                            lambda args: seen.update(vars(args)) or 0)
        assert cli.main(["soak"]) == 0
        assert seen["seed"] == 7 and seen["duration"] == 8.0
        assert seen["workload"] == "mixed"
        assert seen["faults"] == "pokq"
        assert seen["chat_rate"] == 4.0 and seen["ctr_rate"] == 4.0
        assert seen["arrival"] == "diurnal"
        assert seen["event_log"] is None and seen["report"] is None
        assert seen["slo_ttft_ms"] == 8000.0
        assert seen["slo_token_ms"] == 4000.0
        assert cli.main(["soak", "--seed", "23", "--duration", "30",
                         "--workload", "chat", "--faults", "pk",
                         "--chat_rate", "12", "--arrival", "ramp",
                         "--event_log", "/tmp/s.jsonl",
                         "--report", "/tmp/r.json"]) == 0
        assert seen["seed"] == 23 and seen["duration"] == 30.0
        assert seen["workload"] == "chat" and seen["faults"] == "pk"
        assert seen["chat_rate"] == 12.0
        assert seen["arrival"] == "ramp"
        assert seen["event_log"] == "/tmp/s.jsonl"
        assert seen["report"] == "/tmp/r.json"
        # --workload / --arrival are closed choices
        with pytest.raises(SystemExit):
            cli.main(["soak", "--workload", "batch"])
        with pytest.raises(SystemExit):
            cli.main(["soak", "--arrival", "bursty"])

    def test_build_soak_wires_flags(self):
        import argparse

        from paddle_tpu import cli

        class FakeConfig:
            def __init__(self, **kw):
                self.kw = kw

        class FakeRunner:
            def __init__(self, cfg):
                self.cfg = cfg

        ns = argparse.Namespace(
            seed=3, duration=5.0, workload="chat", faults="pk",
            chat_rate=2.0, ctr_rate=1.5, arrival="ramp",
            event_log="/tmp/x.jsonl", slo_ttft_ms=123.0,
            slo_token_ms=45.0)
        runner = cli._build_soak(ns, FakeConfig, FakeRunner)
        kw = runner.cfg.kw
        assert kw["seed"] == 3 and kw["duration_s"] == 5.0
        assert kw["workload"] == "chat" and kw["families"] == "pk"
        assert kw["chat_rate"] == 2.0 and kw["ctr_rate"] == 1.5
        assert kw["arrival"] == "ramp"
        assert kw["journal"] == "/tmp/x.jsonl"
        assert kw["slo"].ttft_p99_ms == 123.0
        assert kw["slo"].token_p99_ms == 45.0

    def test_soak_teardown_order_generators_fleet_coordinator(self):
        """The pinned contract (loadgen/harness.py): load stops
        offering FIRST, then the serving fleet drains and leaves,
        and the coordinator outlives everyone who heartbeats into
        it."""
        from paddle_tpu.loadgen import SoakConfig, SoakRunner

        calls = []

        class FakeGen:
            def stop(self):
                calls.append("gen_stop")

            def join(self, timeout=None):
                calls.append("gen_join")

        class FakeConductor:
            def stop(self):
                calls.append("conductor_stop")

            def join(self, timeout=None):
                calls.append("conductor_join")

        class FakeOnline:
            def stop_and_join(self, timeout=30.0):
                calls.append("online_stop")

        class FakeClient:
            def close(self):
                calls.append("client_close")

        class FakeTopology:
            def stop_fleet(self):
                calls.append("fleet_stop")

            def stop_coordinator(self):
                calls.append("coordinator_stop")

        runner = SoakRunner(SoakConfig())
        runner.generators = [FakeGen()]
        runner.conductor = FakeConductor()
        runner.online = FakeOnline()
        runner.client = FakeClient()
        runner.topology = FakeTopology()
        runner.teardown()
        assert calls == ["gen_stop", "gen_join", "conductor_stop",
                         "conductor_join", "online_stop",
                         "client_close", "fleet_stop",
                         "coordinator_stop"]
        # the SIGTERM path only STOPS offering load (run() unwinds
        # through the same teardown) — it never tears the fleet from
        # a signal handler
        calls.clear()
        runner2 = SoakRunner(SoakConfig())
        runner2.generators = [FakeGen()]
        runner2.conductor = FakeConductor()
        runner2.stop()
        assert calls == ["gen_stop", "conductor_stop"]
