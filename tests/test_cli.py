"""CLI trainer tests — `paddle train --config --job=train|time|test`
parity (TrainerMain.cpp:32-58, TrainerBenchmark.cpp)."""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CONFIG = os.path.join(REPO, "demo", "mnist", "config.py")


def _run_cli(args, timeout=600):
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"
    return subprocess.run(
        [sys.executable, "-m", "paddle_tpu.cli"] + args,
        capture_output=True, text=True, timeout=timeout, env=env)


class TestCLI:
    def test_job_time_prints_json(self, tmp_path):
        r = _run_cli(["train", "--config", CONFIG, "--job", "time",
                      "--batch_size", "32", "--iters", "4"])
        assert r.returncode == 0, r.stderr[-2000:]
        line = [l for l in r.stdout.splitlines()
                if l.startswith("{")][-1]
        rec = json.loads(line)
        assert rec["metric"] == "train_ms_per_batch"
        assert rec["value"] > 0

    def test_job_train_saves_and_test_restores(self, tmp_path):
        save = str(tmp_path / "out")
        r = _run_cli(["train", "--config", CONFIG, "--job", "train",
                      "--num_passes", "1", "--save_dir", save,
                      "--log_period", "16"])
        assert r.returncode == 0, r.stderr[-2000:]
        assert "Pass 0 done" in r.stdout
        tar = os.path.join(save, "pass-00000", "params.tar")
        assert os.path.exists(tar)

        r2 = _run_cli(["train", "--config", CONFIG, "--job", "test",
                       "--init_model_path", tar])
        assert r2.returncode == 0, r2.stderr[-2000:]
        assert "Test: cost=" in r2.stdout

    def test_job_time_from_serialized_topology(self, tmp_path):
        # the JSON topology contract has a consumer outside the tests now
        import jax
        jax.config.update("jax_platforms", "cpu")
        import paddle_tpu as paddle
        from paddle_tpu.core import registry
        registry.reset_name_counters()
        img = paddle.layer.data("x", paddle.data_type.dense_vector(16))
        out = paddle.layer.fc(img, size=4,
                              act=paddle.activation.Softmax())
        lbl = paddle.layer.data("y", paddle.data_type.integer_value(4))
        cost = paddle.layer.classification_cost(out, lbl, name="cost")
        blob = paddle.Topology(cost).serialize()
        p = tmp_path / "model.json"
        p.write_text(blob)
        r = _run_cli(["train", "--config", str(p), "--job", "time",
                      "--batch_size", "16", "--iters", "3"])
        assert r.returncode == 0, r.stderr[-2000:]
        rec = json.loads([l for l in r.stdout.splitlines()
                          if l.startswith("{")][-1])
        assert rec["value"] > 0
