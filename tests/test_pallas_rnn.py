"""Fused Pallas LSTM/GRU kernels vs the lax.scan reference (interpret mode
on CPU — the CPU-as-fake-TPU discipline; on hardware the same kernels run
compiled)."""

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from paddle_tpu.core.sequence import SequenceBatch
from paddle_tpu.ops import pallas_rnn, recurrent


def _seq(b=4, t=12, dim=24, seed=0, ragged=True):
    rng = np.random.RandomState(seed)
    data = jnp.asarray(rng.randn(b, t, dim).astype("float32") * 0.5)
    lengths = jnp.asarray(rng.randint(3, t + 1, b) if ragged
                          else np.full(b, t), jnp.int32)
    return SequenceBatch(data, lengths)


class TestPallasLSTM:
    def test_matches_lax_scan(self):
        h = 6
        seq = _seq(dim=4 * h)
        rng = np.random.RandomState(1)
        w = jnp.asarray(rng.randn(h, 4 * h).astype("float32") * 0.3)
        bias = jnp.asarray(rng.randn(4 * h).astype("float32") * 0.1)
        peep = jnp.asarray(rng.randn(3 * h).astype("float32") * 0.1)
        ref = recurrent.lstm_scan(seq, w, bias, peep)
        out, hT, cT = pallas_rnn.lstm_sequence(
            seq.data, seq.lengths, w, bias, peep, interpret=True)
        np.testing.assert_allclose(np.asarray(ref.data), np.asarray(out),
                                   rtol=1e-5, atol=1e-6)

    def test_final_state_matches(self):
        h = 6
        seq = _seq(dim=4 * h, seed=2)
        rng = np.random.RandomState(3)
        w = jnp.asarray(rng.randn(h, 4 * h).astype("float32") * 0.3)
        ref, (rhT, rcT) = recurrent.lstm_scan(seq, w, None, None,
                                              return_state=True)
        out, hT, cT = pallas_rnn.lstm_sequence(
            seq.data, seq.lengths, w, None, None, interpret=True)
        np.testing.assert_allclose(np.asarray(rhT), np.asarray(hT),
                                   rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(np.asarray(rcT), np.asarray(cT),
                                   rtol=1e-5, atol=1e-6)

    def test_gradients_match(self):
        h = 6
        seq = _seq(dim=4 * h, seed=4)
        rng = np.random.RandomState(5)
        w = jnp.asarray(rng.randn(h, 4 * h).astype("float32") * 0.3)
        bias = jnp.asarray(rng.randn(4 * h).astype("float32") * 0.1)

        def loss_pallas(x, w, b):
            out, _, _ = pallas_rnn.lstm_sequence(x, seq.lengths, w, b, None,
                                                 interpret=True)
            return jnp.sum(out ** 2)

        def loss_ref(x, w, b):
            ref = recurrent.lstm_scan(SequenceBatch(x, seq.lengths), w, b,
                                      None)
            return jnp.sum(ref.data ** 2)

        gp = jax.grad(loss_pallas, argnums=(0, 1, 2))(seq.data, w, bias)
        gr = jax.grad(loss_ref, argnums=(0, 1, 2))(seq.data, w, bias)
        for a, b_ in zip(gp, gr):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                       rtol=1e-4, atol=1e-5)


class TestPallasGRU:
    def test_matches_lax_scan(self):
        h = 6
        seq = _seq(dim=3 * h, seed=6)
        rng = np.random.RandomState(7)
        w = jnp.asarray(rng.randn(h, 3 * h).astype("float32") * 0.3)
        bias = jnp.asarray(rng.randn(3 * h).astype("float32") * 0.1)
        ref = recurrent.gru_scan(seq, w, bias)
        out, hT = pallas_rnn.gru_sequence(seq.data, seq.lengths, w, bias,
                                          interpret=True)
        np.testing.assert_allclose(np.asarray(ref.data), np.asarray(out),
                                   rtol=1e-5, atol=1e-6)

    def test_gradients_match(self):
        h = 6
        seq = _seq(dim=3 * h, seed=8)
        rng = np.random.RandomState(9)
        w = jnp.asarray(rng.randn(h, 3 * h).astype("float32") * 0.3)

        def loss_pallas(x, w):
            out, _ = pallas_rnn.gru_sequence(x, seq.lengths, w, None,
                                             interpret=True)
            return jnp.sum(out ** 2)

        def loss_ref(x, w):
            return jnp.sum(recurrent.gru_scan(
                SequenceBatch(x, seq.lengths), w, None).data ** 2)

        gp = jax.grad(loss_pallas, argnums=(0, 1))(seq.data, w)
        gr = jax.grad(loss_ref, argnums=(0, 1))(seq.data, w)
        for a, b_ in zip(gp, gr):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                       rtol=1e-4, atol=1e-5)
