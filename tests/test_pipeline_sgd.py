"""Pipeline parallelism through the user API: SGD(..., mesh with pp,
pipeline_stages=...) trains a Topology-built model — the VERDICT exit
criterion for ParallelNeuralNetwork parity (ParallelNeuralNetwork.h:34).

The pipelined run must match the plain single-device run numerically:
GPipe microbatching changes the schedule, not the math."""

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.core import registry
from paddle_tpu.parallel import create_mesh
from paddle_tpu.parallel.mesh import PP_AXIS


def _model():
    registry.reset_name_counters()
    x = paddle.layer.data("x", paddle.data_type.dense_vector(32))
    h = x
    for i in range(4):
        h = paddle.layer.fc(h, size=32, act=paddle.activation.Relu(),
                            name=f"pfc{i}")
    out = paddle.layer.fc(h, size=4, act=paddle.activation.Softmax(),
                          name="head")
    lbl = paddle.layer.data("y", paddle.data_type.integer_value(4))
    cost = paddle.layer.classification_cost(out, lbl, name="cost")
    return cost


def _reader(n_batches=3, b=8):
    rng = np.random.RandomState(0)
    batches = [[(rng.randn(32).astype("float32"), int(rng.randint(4)))
                for _ in range(b)] for _ in range(n_batches)]

    def reader():
        yield from batches
    return reader


def _train(mesh=None, stages=None, remat=False, schedule="gpipe",
           microbatches=None):
    paddle.init(seed=0)
    cost = _model()
    params = paddle.create_parameters(paddle.Topology(cost))
    tr = paddle.SGD(cost=cost, parameters=params,
                    update_equation=paddle.optimizer.Momentum(
                        learning_rate=0.1, momentum=0.9),
                    mesh=mesh, pipeline_stages=stages,
                    pipeline_remat=remat, pipeline_schedule=schedule,
                    pipeline_microbatches=microbatches)
    losses = []
    tr.train(_reader(), num_passes=2,
             event_handler=lambda e: losses.append(e.cost)
             if isinstance(e, paddle.event.EndIteration) else None)
    return tr, losses


class TestPipelineSGD:
    def test_pp2_matches_single_device(self):
        mesh = create_mesh([(PP_AXIS, 2)])
        tr_pp, losses_pp = _train(mesh, [["pfc0", "pfc1"],
                                         ["pfc2", "pfc3"]])
        tr_ref, losses_ref = _train()
        np.testing.assert_allclose(losses_pp, losses_ref,
                                   rtol=1e-4, atol=1e-5)
        for k in tr_ref.parameters.raw:
            np.testing.assert_allclose(
                np.asarray(tr_pp.parameters.raw[k]),
                np.asarray(tr_ref.parameters.raw[k]),
                rtol=1e-4, atol=1e-5, err_msg=k)

    def test_pp2_remat_matches_single_device(self):
        """jax.checkpoint on the stages trades FLOPs for memory but must
        not change a single bit of the math."""
        mesh = create_mesh([(PP_AXIS, 2)])
        tr_pp, losses_pp = _train(mesh, [["pfc0", "pfc1"],
                                         ["pfc2", "pfc3"]], remat=True)
        tr_ref, losses_ref = _train()
        np.testing.assert_allclose(losses_pp, losses_ref,
                                   rtol=1e-4, atol=1e-5)
        for k in tr_ref.parameters.raw:
            np.testing.assert_allclose(
                np.asarray(tr_pp.parameters.raw[k]),
                np.asarray(tr_ref.parameters.raw[k]),
                rtol=1e-4, atol=1e-5, err_msg=k)

    def test_pp4(self):
        mesh = create_mesh([(PP_AXIS, 4)])
        _, losses = _train(mesh, [[f"pfc{i}"] for i in range(4)])
        assert np.isfinite(losses).all()
        assert losses[-1] < losses[0]

    def test_1f1b_pp2_matches_single_device(self):
        """The hand-scheduled 1F1B backward must reproduce the plain
        single-device numerics exactly — it is a schedule, not math."""
        mesh = create_mesh([(PP_AXIS, 2)])
        tr_pp, losses_pp = _train(mesh, [["pfc0", "pfc1"],
                                         ["pfc2", "pfc3"]],
                                  schedule="1f1b")
        tr_ref, losses_ref = _train()
        np.testing.assert_allclose(losses_pp, losses_ref,
                                   rtol=1e-4, atol=1e-5)
        for k in tr_ref.parameters.raw:
            np.testing.assert_allclose(
                np.asarray(tr_pp.parameters.raw[k]),
                np.asarray(tr_ref.parameters.raw[k]),
                rtol=1e-4, atol=1e-5, err_msg=k)

    def test_1f1b_pp4_many_microbatches(self):
        """m >> S (the regime 1F1B exists for: O(S) activation state)
        still pins to the single-device numerics."""
        mesh = create_mesh([(PP_AXIS, 4)])
        tr_pp, losses_pp = _train(mesh, [[f"pfc{i}"] for i in range(4)],
                                  schedule="1f1b", microbatches=8)
        tr_ref, losses_ref = _train()
        np.testing.assert_allclose(losses_pp, losses_ref,
                                   rtol=1e-4, atol=1e-5)
        for k in tr_ref.parameters.raw:
            np.testing.assert_allclose(
                np.asarray(tr_pp.parameters.raw[k]),
                np.asarray(tr_ref.parameters.raw[k]),
                rtol=1e-4, atol=1e-5, err_msg=k)

    def test_1f1b_memory_flat_in_microbatches(self):
        """The defining property: 1F1B's temp footprint is O(stages),
        flat in m, where GPipe's reversed scan carries O(m + stages)."""
        import jax
        import jax.numpy as jnp
        from paddle_tpu.parallel.pipeline import pipeline, pipeline_1f1b

        mesh = create_mesh([(PP_AXIS, 2)])
        S, D, MB = 2, 64, 8

        def stage_fn(params, x):
            return jnp.tanh(x @ params["w"])

        def temp_bytes(m, schedule):
            sp = {"w": jnp.stack([jnp.eye(D)] * S)}
            x = jnp.zeros((m * MB, D), jnp.float32)
            if schedule == "gpipe":
                fn = jax.jit(jax.grad(lambda sp, x: jnp.sum(pipeline(
                    stage_fn, sp, x, mesh, num_microbatches=m,
                    remat=True) ** 2)))
            else:
                def tail_vjp(y_mb, j):
                    loss_j, vjp = jax.vjp(lambda y: jnp.sum(y * y), y_mb)
                    return loss_j, vjp(jnp.float32(1.0))[0], {}

                def grads(sp, x):
                    return pipeline_1f1b(stage_fn, sp, x, tail_vjp, mesh,
                                         num_microbatches=m)[2]
                fn = jax.jit(grads)
            mem = fn.lower(sp, x).compile().memory_analysis()
            if not hasattr(mem, "temp_size_in_bytes"):
                pytest.skip("backend exposes no temp_size_in_bytes")
            return mem.temp_size_in_bytes

        g4, g32 = temp_bytes(4, "gpipe"), temp_bytes(32, "gpipe")
        f4, f32 = temp_bytes(4, "1f1b"), temp_bytes(32, "1f1b")
        assert g32 > g4 * 2, (g4, g32)          # gpipe grows with m
        assert f32 < f4 * 1.25, (f4, f32)       # 1f1b stays ~flat
        assert f32 < g32 / 2, (f32, g32)        # and wins at large m

    def test_stage_validation(self):
        paddle.init(seed=0)
        cost = _model()
        params = paddle.create_parameters(paddle.Topology(cost))
        mesh = create_mesh([(PP_AXIS, 2)])
        with pytest.raises(AssertionError, match="structurally identical"):
            paddle.SGD(cost=cost, parameters=params,
                       update_equation=paddle.optimizer.Momentum(
                           learning_rate=0.1),
                       mesh=mesh,
                       pipeline_stages=[["pfc0", "pfc1"],
                                        ["pfc2", "pfc3", "head"]])
        with pytest.raises(AssertionError, match="pipeline_stages"):
            paddle.SGD(cost=cost, parameters=params,
                       update_equation=paddle.optimizer.Momentum(
                           learning_rate=0.1),
                       mesh=mesh)


class TestTransformerPipeline:
    """The flagship actually pipelines: transformer blocks (residual
    DAG stages, SequenceBatch boundary, embedding prologue) over pp,
    matching single-device numerics under BOTH schedules."""

    def _run(self, schedule=None, microbatches=None):
        import jax
        from paddle_tpu import models
        from paddle_tpu.core import registry
        from paddle_tpu.core.sequence import SequenceBatch

        registry.reset_name_counters()
        paddle.init(seed=0)
        L_, T_, V_, B_ = 2, 8, 40, 8
        spec = models.transformer_lm(vocab_size=V_, d_model=16,
                                     n_heads=2, n_layers=L_, d_ff=32,
                                     max_len=T_)
        params = paddle.create_parameters(
            paddle.Topology(spec.cost, extra_outputs=[spec.output]))
        stages = None
        mesh = None
        if schedule is not None:
            mesh = create_mesh([(PP_AXIS, 2)])
            stages = [[f"tfm_l{i}_{s}" for s in
                       ("ln1", "q", "k", "v", "attn", "proj", "res1",
                        "ln2", "up", "down", "res2")]
                      for i in range(L_)]
        tr = paddle.SGD(cost=spec.cost, parameters=params,
                        extra_layers=[spec.output],
                        update_equation=paddle.optimizer.Adam(
                            learning_rate=1e-3),
                        mesh=mesh, pipeline_stages=stages,
                        pipeline_schedule=schedule or "gpipe",
                        pipeline_microbatches=microbatches)
        rng = np.random.RandomState(0)
        batches = []
        for _ in range(3):
            rows = []
            for _ in range(B_):
                ids = rng.randint(0, V_, T_ + 1)
                rows.append(([int(v) for v in ids[:T_]],
                             list(range(T_)),
                             [int(v) for v in ids[1:]]))
            batches.append(rows)

        losses = []
        tr.train(lambda: iter(batches), num_passes=2,
                 event_handler=lambda e: losses.append(e.cost)
                 if isinstance(e, paddle.event.EndIteration) else None)
        return tr, losses

    def test_gpipe_matches_single_device(self):
        tr_pp, losses_pp = self._run("gpipe")
        tr_ref, losses_ref = self._run()
        np.testing.assert_allclose(losses_pp, losses_ref,
                                   rtol=2e-4, atol=1e-5)
        for k in tr_ref.parameters.raw:
            np.testing.assert_allclose(
                np.asarray(tr_pp.parameters.raw[k]),
                np.asarray(tr_ref.parameters.raw[k]),
                rtol=2e-4, atol=2e-5, err_msg=k)

    def test_1f1b_matches_single_device(self):
        tr_pp, losses_pp = self._run("1f1b", microbatches=4)
        tr_ref, losses_ref = self._run()
        np.testing.assert_allclose(losses_pp, losses_ref,
                                   rtol=2e-4, atol=1e-5)
        for k in tr_ref.parameters.raw:
            np.testing.assert_allclose(
                np.asarray(tr_pp.parameters.raw[k]),
                np.asarray(tr_ref.parameters.raw[k]),
                rtol=2e-4, atol=2e-5, err_msg=k)
