"""Pipeline parallelism through the user API: SGD(..., mesh with pp,
pipeline_stages=...) trains a Topology-built model — the VERDICT exit
criterion for ParallelNeuralNetwork parity (ParallelNeuralNetwork.h:34).

The pipelined run must match the plain single-device run numerically:
GPipe microbatching changes the schedule, not the math."""

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.core import registry
from paddle_tpu.parallel import create_mesh
from paddle_tpu.parallel.mesh import PP_AXIS


def _model():
    registry.reset_name_counters()
    x = paddle.layer.data("x", paddle.data_type.dense_vector(32))
    h = x
    for i in range(4):
        h = paddle.layer.fc(h, size=32, act=paddle.activation.Relu(),
                            name=f"pfc{i}")
    out = paddle.layer.fc(h, size=4, act=paddle.activation.Softmax(),
                          name="head")
    lbl = paddle.layer.data("y", paddle.data_type.integer_value(4))
    cost = paddle.layer.classification_cost(out, lbl, name="cost")
    return cost


def _reader(n_batches=3, b=8):
    rng = np.random.RandomState(0)
    batches = [[(rng.randn(32).astype("float32"), int(rng.randint(4)))
                for _ in range(b)] for _ in range(n_batches)]

    def reader():
        yield from batches
    return reader


def _train(mesh=None, stages=None, remat=False):
    paddle.init(seed=0)
    cost = _model()
    params = paddle.create_parameters(paddle.Topology(cost))
    tr = paddle.SGD(cost=cost, parameters=params,
                    update_equation=paddle.optimizer.Momentum(
                        learning_rate=0.1, momentum=0.9),
                    mesh=mesh, pipeline_stages=stages,
                    pipeline_remat=remat)
    losses = []
    tr.train(_reader(), num_passes=2,
             event_handler=lambda e: losses.append(e.cost)
             if isinstance(e, paddle.event.EndIteration) else None)
    return tr, losses


class TestPipelineSGD:
    def test_pp2_matches_single_device(self):
        mesh = create_mesh([(PP_AXIS, 2)])
        tr_pp, losses_pp = _train(mesh, [["pfc0", "pfc1"],
                                         ["pfc2", "pfc3"]])
        tr_ref, losses_ref = _train()
        np.testing.assert_allclose(losses_pp, losses_ref,
                                   rtol=1e-4, atol=1e-5)
        for k in tr_ref.parameters.raw:
            np.testing.assert_allclose(
                np.asarray(tr_pp.parameters.raw[k]),
                np.asarray(tr_ref.parameters.raw[k]),
                rtol=1e-4, atol=1e-5, err_msg=k)

    def test_pp2_remat_matches_single_device(self):
        """jax.checkpoint on the stages trades FLOPs for memory but must
        not change a single bit of the math."""
        mesh = create_mesh([(PP_AXIS, 2)])
        tr_pp, losses_pp = _train(mesh, [["pfc0", "pfc1"],
                                         ["pfc2", "pfc3"]], remat=True)
        tr_ref, losses_ref = _train()
        np.testing.assert_allclose(losses_pp, losses_ref,
                                   rtol=1e-4, atol=1e-5)
        for k in tr_ref.parameters.raw:
            np.testing.assert_allclose(
                np.asarray(tr_pp.parameters.raw[k]),
                np.asarray(tr_ref.parameters.raw[k]),
                rtol=1e-4, atol=1e-5, err_msg=k)

    def test_pp4(self):
        mesh = create_mesh([(PP_AXIS, 4)])
        _, losses = _train(mesh, [[f"pfc{i}"] for i in range(4)])
        assert np.isfinite(losses).all()
        assert losses[-1] < losses[0]

    def test_stage_validation(self):
        paddle.init(seed=0)
        cost = _model()
        params = paddle.create_parameters(paddle.Topology(cost))
        mesh = create_mesh([(PP_AXIS, 2)])
        with pytest.raises(AssertionError, match="structurally identical"):
            paddle.SGD(cost=cost, parameters=params,
                       update_equation=paddle.optimizer.Momentum(
                           learning_rate=0.1),
                       mesh=mesh,
                       pipeline_stages=[["pfc0", "pfc1"],
                                        ["pfc2", "pfc3", "head"]])
        with pytest.raises(AssertionError, match="pipeline_stages"):
            paddle.SGD(cost=cost, parameters=params,
                       update_equation=paddle.optimizer.Momentum(
                           learning_rate=0.1),
                       mesh=mesh)
