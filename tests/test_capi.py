"""C inference ABI tests — capi/gradient_machine.h:36-88 parity.

Builds the real .so (embedding CPython), saves a merged MNIST model with
save_inference_model, and runs the C example program against it; its
output must match the in-process Python inference bit-for-tolerance."""

import os
import shutil
import subprocess
import sys
import sysconfig

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.core import registry
from paddle_tpu.trainer.inference import (load_inference_model,
                                          save_inference_model)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _train_small_mnist():
    registry.reset_name_counters()
    paddle.init(seed=3)
    img = paddle.layer.data("pixel", paddle.data_type.dense_vector(784))
    h = paddle.layer.fc(img, size=32, act=paddle.activation.Relu())
    out = paddle.layer.fc(h, size=10, act=paddle.activation.Softmax(),
                          name="output")
    lbl = paddle.layer.data("label", paddle.data_type.integer_value(10))
    cost = paddle.layer.classification_cost(out, lbl, name="cost")
    params = paddle.create_parameters(paddle.Topology(cost))
    tr = paddle.SGD(cost=cost, parameters=params,
                    update_equation=paddle.optimizer.Momentum(
                        learning_rate=0.01, momentum=0.9))
    reader = paddle.reader.batch(paddle.dataset.mnist.train(), 64,
                                 drop_last=True)
    tr.train(reader, num_passes=1, num_batches_per_pass=8,
             event_handler=lambda e: None)
    return out, tr.parameters


class TestMergedArtifact:
    def test_save_load_roundtrip(self, tmp_path):
        out, params = _train_small_mnist()
        path = str(tmp_path / "model.tar")
        save_inference_model(path, out, params)
        inf = load_inference_model(path)
        x = np.linspace(0, 1, 784).astype("float32")
        want = paddle.infer(output_layer=out, parameters=params,
                            input=[(x,)])
        got = inf.infer([(x,)])
        np.testing.assert_allclose(got, want, rtol=1e-6)


def _c_env():
    site = sysconfig.get_path("purelib")
    env = dict(os.environ)
    env["PYTHONPATH"] = os.pathsep.join(
        [REPO, site, env.get("PYTHONPATH", "")])
    env["JAX_PLATFORMS"] = "cpu"
    return env


_LIB_CACHE = {}


def _build_lib(into_dir: str) -> str:
    """Build the shim .so once per process (it is invariant across
    tests); returns the directory holding libpaddle_tpu_capi.so."""
    if "dir" in _LIB_CACHE:
        return _LIB_CACHE["dir"]
    cc = shutil.which("gcc") or shutil.which("cc")
    if cc is None:
        pytest.skip("no C compiler")
    inc = sysconfig.get_path("include")
    libdir = sysconfig.get_config_var("LIBDIR")
    ver = sysconfig.get_config_var("LDVERSION")
    lib = os.path.join(into_dir, "libpaddle_tpu_capi.so")
    subprocess.run(
        [cc, "-shared", "-fPIC", os.path.join(REPO, "capi",
                                              "paddle_tpu_capi.c"),
         f"-I{inc}", f"-L{libdir}", f"-lpython{ver}",
         f"-Wl,-rpath,{libdir}", "-o", lib], check=True)
    _LIB_CACHE["dir"] = into_dir
    return into_dir


@pytest.fixture(scope="session")
def capi_lib(tmp_path_factory):
    return _build_lib(str(tmp_path_factory.mktemp("capi_lib")))


class TestCABI:
    libdir = None

    @pytest.fixture(autouse=True)
    def _lib(self, capi_lib):
        self.libdir = capi_lib

    def _build(self, tmp_path, example="dense_infer"):
        # callable standalone too (tests/test_cli.py reuses it outside
        # the fixture machinery): build the lib on demand
        libdir = self.libdir or _build_lib(str(tmp_path))
        cc = shutil.which("gcc") or shutil.which("cc")
        pylibdir = sysconfig.get_config_var("LIBDIR")
        exe = str(tmp_path / example)
        subprocess.run(
            [cc, os.path.join(REPO, "capi", "examples", f"{example}.c"),
             f"-L{libdir}", "-lpaddle_tpu_capi", "-lpthread",
             f"-Wl,-rpath,{libdir}", f"-Wl,-rpath,{pylibdir}",
             "-o", exe], check=True)
        return exe

    def test_c_program_runs_mnist_inference(self, tmp_path):
        exe = self._build(tmp_path)
        out, params = _train_small_mnist()
        model = str(tmp_path / "model.tar")
        save_inference_model(model, out, params)

        env = _c_env()
        r = subprocess.run([exe, model, "784"], capture_output=True,
                           text=True, timeout=600, env=env)
        assert r.returncode == 0, (r.stdout[-2000:], r.stderr[-2000:])
        lines = [l for l in r.stdout.splitlines() if l.strip()]
        assert lines[0] == "out_dim=10"
        assert "shared_ok" in lines[-1]

        # parse row0 and compare against in-process inference
        row0 = np.array([float(v) for v in
                         lines[1].split(":")[1].split()])
        x = (0.001 * (np.arange(784) % 1000)).astype("float32")
        want = paddle.infer(output_layer=out, parameters=params,
                            input=[(x,)])[0]
        np.testing.assert_allclose(row0, want, rtol=1e-4, atol=1e-5)

    def test_c_sequence_serving(self, tmp_path):
        """A C program serves the LSTM tagger: integer ids + sequence
        start positions in, per-token softmax rows + output offsets back
        (capi/arguments.h:110,137; examples/model_inference/sequence)."""
        exe = self._build(tmp_path, "sequence_infer")
        registry.reset_name_counters()
        paddle.init(seed=11)
        toks = paddle.layer.data(
            "toks", paddle.data_type.integer_value_sequence(10))
        emb = paddle.layer.embedding(toks, size=8)
        rec = paddle.layer.lstmemory(emb)
        out = paddle.layer.fc(rec, size=3,
                              act=paddle.activation.Softmax(), name="tag")
        params = paddle.create_parameters(paddle.Topology(out))
        model = str(tmp_path / "seq_model.tar")
        save_inference_model(model, out, params)

        r = subprocess.run([exe, model], capture_output=True, text=True,
                           timeout=600, env=_c_env())
        assert r.returncode == 0, (r.stdout[-2000:], r.stderr[-2000:])
        lines = [l for l in r.stdout.splitlines() if l.strip()]
        assert lines[0] == "rows=8 dim=3"
        assert lines[1] == "starts: 0 5 8"
        got = np.array([[float(v) for v in l.split(":")[1].split()]
                        for l in lines[2:10]])

        ids = np.array([2, 3, 5, 7, 1, 4, 6, 8], np.int32)
        want = np.asarray(paddle.infer(
            output_layer=out, parameters=params,
            input=[(ids[:5],), (ids[5:],)]))
        np.testing.assert_allclose(got[:5], want[0, :5], rtol=1e-4,
                                   atol=1e-5)
        np.testing.assert_allclose(got[5:8], want[1, :3], rtol=1e-4,
                                   atol=1e-5)

    def test_c_sparse_serving(self, tmp_path):
        """A C program serves a sparse-binary-input ranker via CSR rows
        (capi/matrix.h:44-114; examples/model_inference/sparse_binary)."""
        exe = self._build(tmp_path, "sparse_infer")
        registry.reset_name_counters()
        paddle.init(seed=12)
        x = paddle.layer.data(
            "x", paddle.data_type.sparse_binary_vector(16))
        h = paddle.layer.fc(x, size=8, act=paddle.activation.Relu())
        out = paddle.layer.fc(h, size=4, act=paddle.activation.Softmax())
        params = paddle.create_parameters(paddle.Topology(out))
        model = str(tmp_path / "sparse_model.tar")
        save_inference_model(model, out, params)

        r = subprocess.run([exe, model, "16"], capture_output=True,
                           text=True, timeout=600, env=_c_env())
        assert r.returncode == 0, (r.stdout[-2000:], r.stderr[-2000:])
        lines = [l for l in r.stdout.splitlines() if l.strip()]
        assert lines[0] == "rows=2 dim=4"
        got = np.array([[float(v) for v in l.split(":")[1].split()]
                        for l in lines[1:3]])
        want = np.asarray(paddle.infer(
            output_layer=out, parameters=params,
            input=[([1, 5, 9],), ([0, 7],)]))
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)

    def test_c_multi_thread_serving(self, tmp_path):
        """A pthreads C client serves concurrently over shared weights
        (capi/gradient_machine.h:88; examples/model_inference/multi_thread):
        every thread's every forward must match the main thread's
        reference output."""
        exe = self._build(tmp_path, "multi_thread_infer")
        out, params = _train_small_mnist()
        model = str(tmp_path / "model.tar")
        save_inference_model(model, out, params)

        r = subprocess.run([exe, model, "784", "4", "6"],
                           capture_output=True, text=True, timeout=600,
                           env=_c_env())
        assert r.returncode == 0, (r.stdout[-2000:], r.stderr[-2000:])
        assert "threads_ok n=4 iters=6" in r.stdout
