"""C inference ABI tests — capi/gradient_machine.h:36-88 parity.

Builds the real .so (embedding CPython), saves a merged MNIST model with
save_inference_model, and runs the C example program against it; its
output must match the in-process Python inference bit-for-tolerance."""

import os
import shutil
import subprocess
import sys
import sysconfig

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.core import registry
from paddle_tpu.trainer.inference import (load_inference_model,
                                          save_inference_model)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _train_small_mnist():
    registry.reset_name_counters()
    paddle.init(seed=3)
    img = paddle.layer.data("pixel", paddle.data_type.dense_vector(784))
    h = paddle.layer.fc(img, size=32, act=paddle.activation.Relu())
    out = paddle.layer.fc(h, size=10, act=paddle.activation.Softmax(),
                          name="output")
    lbl = paddle.layer.data("label", paddle.data_type.integer_value(10))
    cost = paddle.layer.classification_cost(out, lbl, name="cost")
    params = paddle.create_parameters(paddle.Topology(cost))
    tr = paddle.SGD(cost=cost, parameters=params,
                    update_equation=paddle.optimizer.Momentum(
                        learning_rate=0.01, momentum=0.9))
    reader = paddle.reader.batch(paddle.dataset.mnist.train(), 64,
                                 drop_last=True)
    tr.train(reader, num_passes=1, num_batches_per_pass=8,
             event_handler=lambda e: None)
    return out, tr.parameters


class TestMergedArtifact:
    def test_save_load_roundtrip(self, tmp_path):
        out, params = _train_small_mnist()
        path = str(tmp_path / "model.tar")
        save_inference_model(path, out, params)
        inf = load_inference_model(path)
        x = np.linspace(0, 1, 784).astype("float32")
        want = paddle.infer(output_layer=out, parameters=params,
                            input=[(x,)])
        got = inf.infer([(x,)])
        np.testing.assert_allclose(got, want, rtol=1e-6)


class TestCABI:
    def _build(self, tmp_path):
        cc = shutil.which("gcc") or shutil.which("cc")
        if cc is None:
            pytest.skip("no C compiler")
        inc = sysconfig.get_path("include")
        libdir = sysconfig.get_config_var("LIBDIR")
        ver = sysconfig.get_config_var("LDVERSION")
        lib = str(tmp_path / "libpaddle_tpu_capi.so")
        exe = str(tmp_path / "dense_infer")
        subprocess.run(
            [cc, "-shared", "-fPIC", os.path.join(REPO, "capi",
                                                  "paddle_tpu_capi.c"),
             f"-I{inc}", f"-L{libdir}", f"-lpython{ver}",
             f"-Wl,-rpath,{libdir}", "-o", lib], check=True)
        subprocess.run(
            [cc, os.path.join(REPO, "capi", "examples", "dense_infer.c"),
             f"-L{tmp_path}", "-lpaddle_tpu_capi",
             f"-Wl,-rpath,{tmp_path}", f"-Wl,-rpath,{libdir}", "-o", exe],
            check=True)
        return exe

    def test_c_program_runs_mnist_inference(self, tmp_path):
        exe = self._build(tmp_path)
        out, params = _train_small_mnist()
        model = str(tmp_path / "model.tar")
        save_inference_model(model, out, params)

        site = sysconfig.get_path("purelib")
        env = dict(os.environ)
        env["PYTHONPATH"] = os.pathsep.join(
            [REPO, site, env.get("PYTHONPATH", "")])
        env["JAX_PLATFORMS"] = "cpu"
        r = subprocess.run([exe, model, "784"], capture_output=True,
                           text=True, timeout=600, env=env)
        assert r.returncode == 0, (r.stdout[-2000:], r.stderr[-2000:])
        lines = [l for l in r.stdout.splitlines() if l.strip()]
        assert lines[0] == "out_dim=10"
        assert "shared_ok" in lines[-1]

        # parse row0 and compare against in-process inference
        row0 = np.array([float(v) for v in
                         lines[1].split(":")[1].split()])
        x = (0.001 * (np.arange(784) % 1000)).astype("float32")
        want = paddle.infer(output_layer=out, parameters=params,
                            input=[(x,)])[0]
        np.testing.assert_allclose(row0, want, rtol=1e-4, atol=1e-5)
