"""Performance observability plane — acceptance suite (ISSUE 11).

Covers the tentpole contract: the continuous step profiler's per-phase
breakdown and cost/roofline gauges (with the bench.py-shared math —
live MFU and an offline bench-style computation from the same inputs
must agree within 10%), device-memory telemetry off-thread, deep
profile windows, the SLO watchdog's declarative objectives +
burn-rate breaches, and THE chaos acceptance: an injected 5x step
stall is journaled as ``slo/step_regression`` naming the injected
phase, and the auto-dumped flight bundle carries the per-phase
breakdown that attributes it.
"""

import json
import os
import threading
import time
import warnings

import pytest

import paddle_tpu as paddle
from paddle_tpu.obs.events import JOURNAL, read_journal, validate
from paddle_tpu.obs.flight import FLIGHT
from paddle_tpu.obs.profile import (PROFILER, cost_of, device_hbm_gbps,
                                    device_peak_flops, roofline)
from paddle_tpu.obs.slo import WATCHDOG, Objective, parse_objective
from paddle_tpu.utils.stats import stat_timer


class _Dev:
    def __init__(self, kind):
        self.device_kind = kind


# ------------------------------------------------- shared roofline math

class TestRooflineMath:
    def test_device_peak_tables(self):
        assert device_peak_flops(_Dev("TPU v4")) == 275e12
        assert device_hbm_gbps(_Dev("TPU v5e")) == 819.0
        assert device_peak_flops(_Dev("cpu")) is None
        assert device_hbm_gbps(_Dev("NVIDIA A100")) is None

    def test_roofline_bounds_and_mfu(self):
        # 1 GFLOP at 1 TFLOP/s peak -> 1 ms mxu bound; 0.1 GB at
        # 100 GB/s -> 1 ms hbm bound; measured 2 ms -> frac 2.0
        rf = roofline(2.0, flops=1e9, bytes_acc=1e8,
                      peak_flops=1e12, hbm_gbps=100.0, mxu=True)
        assert rf["mfu"] == pytest.approx(0.5)
        assert rf["roofline_ms"] == pytest.approx(1.0)
        assert rf["roofline_frac"] == pytest.approx(2.0)
        # mxu=False (f32 run): only the hbm bound can bind
        rf = roofline(2.0, flops=1e9, bytes_acc=1e8,
                      peak_flops=1e12, hbm_gbps=50.0, mxu=False)
        assert rf["roofline_bound"] == "hbm"
        assert rf["roofline_ms"] == pytest.approx(2.0)
        # degenerate inputs stay empty, never raise
        assert roofline(0.0, flops=1e9, peak_flops=1e12) == {}
        assert roofline(1.0) == {}

    def test_cost_of_jitted_callable(self):
        import jax
        import jax.numpy as jnp

        @jax.jit
        def f(a, b):
            return a @ b

        x = jnp.ones((16, 16), dtype=jnp.float32)
        flops, nbytes = cost_of(f, x, x)
        assert flops and flops > 0
        assert nbytes and nbytes > 0


# ------------------------------------------------- continuous profiler

def _drive_train_steps(n, compute_ms=2.0):
    """n profiler-observed steps with a known compute-phase cost."""
    for _ in range(n):
        with stat_timer("train/data_wait"):
            pass
        with stat_timer("train/h2d"):
            pass
        with stat_timer("train_step"):
            time.sleep(compute_ms / 1e3)
        with stat_timer("train/settle"):
            pass
        PROFILER.on_step("train")


class TestStepProfiler:
    def test_disabled_is_noop(self):
        assert not PROFILER.enabled
        PROFILER.on_step("train")
        assert PROFILER.snapshot()["kinds"] == {}

    def test_phase_breakdown_and_snapshot_shape(self):
        PROFILER.enable(sample_every=2)
        try:
            _drive_train_steps(6)
        finally:
            PROFILER.disable()
        snap = PROFILER.snapshot()
        st = snap["kinds"]["train"]
        assert st["steps"] == 6
        assert st["step_ms_median"] > 0
        assert set(st["phases"]) == {"data_wait", "h2d", "compute",
                                     "settle"}
        # the stall budget went where it was spent
        assert st["phases"]["compute"] > st["phases"]["data_wait"]
        assert set(snap["window"]) == {"remaining", "last_trace_dir"}
        json.dumps(snap)                      # served on GET /profile

    def test_deep_window_captures_trace_artifact(self, tmp_path):
        out = str(tmp_path / "trace")
        PROFILER.enable(sample_every=1)
        try:
            got = PROFILER.arm_window(2, out_dir=out)
            assert got == out
            _drive_train_steps(3)
        finally:
            PROFILER.disable()
        snap = PROFILER.snapshot()
        assert snap["window"]["remaining"] == 0
        assert snap["window"]["last_trace_dir"] == out
        assert os.path.isdir(out) and os.listdir(out)
        recs = JOURNAL.tail(50, domain="profile", kind="window")
        assert recs and recs[-1]["dir"] == out

    def test_memory_sampler_thread_lifecycle_and_pools(self):
        acct = {"total_usable": 10, "allocated": 5}
        PROFILER.register_pool("kv", lambda: dict(acct))
        PROFILER.start_memory_sampler(interval=0.05)
        try:
            deadline = time.monotonic() + 5.0
            while time.monotonic() < deadline:
                if "kv" in PROFILER.snapshot()["pools"]:
                    break
                time.sleep(0.02)
            names = [t.name for t in threading.enumerate()]
            assert "pt-obs-profiler" in names
            snap = PROFILER.snapshot()
            assert snap["pools"]["kv"]["occupancy"] == \
                pytest.approx(0.5)
            assert set(snap["memory"]) == {"bytes_in_use",
                                           "watermark_bytes"}
        finally:
            PROFILER.stop_memory_sampler()
        assert not any(t.name == "pt-obs-profiler"
                       for t in threading.enumerate())

    def test_dead_pool_source_dropped(self):
        PROFILER.register_pool("gone", lambda: None)
        PROFILER.sample_memory()
        assert "gone" not in PROFILER.snapshot()["pools"]

    def test_live_mfu_agrees_with_bench_computation(self):
        """THE agreement acceptance: the live gauge and a bench.py-style
        offline computation over the same measured window land within
        10% — they share roofline() by construction, so the only slack
        is mean-vs-median over the sample window."""
        PROFILER.configure(peak_flops=1e12, hbm_gbps=1000.0,
                           assume_mxu=True)
        PROFILER.set_cost_source("train", lambda: (5.0e6, 2.0e6))
        PROFILER.enable(sample_every=4)
        try:
            _drive_train_steps(12, compute_ms=5.0)
        finally:
            PROFILER.disable()
        snap = PROFILER.snapshot()
        live = snap["mfu"]["train"]
        offline = roofline(snap["kinds"]["train"]["step_ms_median"],
                           flops=5.0e6, bytes_acc=2.0e6,
                           peak_flops=1e12, hbm_gbps=1000.0,
                           mxu=True)["mfu"]
        assert live > 0 and offline > 0
        assert abs(live - offline) / offline < 0.10
        assert snap["roofline_frac"]["train"] > 0


# ---------------------------------------------------------- slo watchdog

class TestSLOWatchdog:
    def test_parse_objective_specs(self):
        o = parse_objective("ttft_p50_ms<=50")
        assert (o.metric, o.target, o.kind, o.window) == \
            ("ttft_p50_ms", 50.0, "upper", 32)
        o = parse_objective("tokens_per_s>=100@64")
        assert (o.metric, o.target, o.kind, o.window) == \
            ("tokens_per_s", 100.0, "lower", 64)
        with pytest.raises(ValueError):
            parse_objective("tokens_per_s=100")

    def test_objective_burn_rate_breach_journaled(self):
        WATCHDOG.configure(objectives=[Objective(
            name="p99", metric="p99_ms", target=5.0, window=8)],
            cooldown_s=0.0)
        WATCHDOG.add_source("fake", lambda: {"p99_ms": 50.0})
        breaches = []
        for _ in range(4):                    # window//2 samples arm it
            breaches += WATCHDOG.evaluate()
        assert breaches and breaches[0]["objective"] == "p99"
        assert breaches[0]["burn_rate"] == 1.0
        assert breaches[0]["bound"] == "upper"
        recs = JOURNAL.tail(50, domain="slo", kind="breach")
        assert recs and recs[-1]["value"] == 50.0
        assert WATCHDOG.breaches >= 1

    def test_lower_bound_objective_and_healthy_source(self):
        WATCHDOG.configure(objectives=[Objective(
            name="tput", metric="tokens_per_s", target=100.0,
            kind="lower", window=4)], cooldown_s=0.0)
        WATCHDOG.add_source("fake", lambda: {"tokens_per_s": 500.0})
        for _ in range(6):
            assert WATCHDOG.evaluate() == []   # healthy: no breach
        WATCHDOG.add_source("fake", lambda: {"tokens_per_s": 3.0})
        out = []
        for _ in range(4):
            out += WATCHDOG.evaluate()
        assert out and out[0]["objective"] == "tput"

    def test_dead_source_dropped(self):
        WATCHDOG.configure(objectives=[Objective(
            name="x", metric="x", target=1.0)])
        WATCHDOG.add_source("dying", lambda: None)
        WATCHDOG.evaluate()
        assert "dying" not in WATCHDOG.snapshot()["sources"]

    def test_regression_detected_attributed_and_baseline_unpolluted(self):
        WATCHDOG.configure(regression_factor=3.0, regression_steps=2,
                           min_samples=4, cooldown_s=0.0)
        healthy = {"compute": 8.0, "h2d": 1.0}
        for _ in range(8):
            WATCHDOG.observe_step("train", 10.0, dict(healthy))
        stalled = {"compute": 48.0, "h2d": 1.0}
        for _ in range(2):
            WATCHDOG.observe_step("train", 50.0, dict(stalled))
        recs = JOURNAL.tail(50, domain="slo", kind="step_regression")
        assert len(recs) == 1
        r = validate(recs[-1])
        assert r["step_kind"] == "train" and r["phase"] == "compute"
        assert r["factor"] >= 3.0
        # anomalous samples were NOT folded into the rolling median:
        # a continued stall keeps firing against the pre-stall baseline
        for _ in range(2):
            WATCHDOG.observe_step("train", 50.0, dict(stalled))
        recs = JOURNAL.tail(50, domain="slo", kind="step_regression")
        assert len(recs) == 2
        assert recs[-1]["median_ms"] == pytest.approx(10.0)

    def test_disabled_watchdog_observes_nothing(self):
        assert not WATCHDOG.enabled
        for _ in range(16):
            WATCHDOG.observe_step("train", 1000.0, None)
        assert WATCHDOG.evaluate() == []
        assert JOURNAL.tail(50, domain="slo") == []


# ------------------------------------------------- chaos: the acceptance

class TestChaosStallAttribution:
    """THE acceptance criterion: an injected 5x stall in a specific
    phase is journaled as ``slo/step_regression`` naming that phase,
    and auto-dumps a flight bundle whose reason names it and whose
    profiler state carries the per-phase breakdown."""

    @pytest.mark.chaos
    def test_train_stall_attributed_to_compute_and_bundled(
            self, tmp_path):
        from paddle_tpu.testing.faults import FaultPlan
        from tests.test_oom import _reader, _trainer

        path = str(tmp_path / "events.jsonl")
        dumps = str(tmp_path / "dumps")
        JOURNAL.configure(path)
        FLIGHT.configure(dump_dir=dumps, min_dump_interval=0)
        PROFILER.enable(sample_every=1)
        WATCHDOG.configure(regression_factor=3.0, regression_steps=2,
                           min_samples=4, cooldown_s=0.0)
        tr = _trainer()
        try:
            with FaultPlan.slow_step(tr, step=10, factor=5.0,
                                     n=4) as stats:
                with warnings.catch_warnings():
                    warnings.simplefilter("ignore")
                    tr.train(_reader(batches=24), num_passes=1,
                             event_handler=lambda e: None,
                             microbatch="auto")
        finally:
            PROFILER.disable()
        assert stats["injected"] >= 1 and stats["slept_ms"] > 0
        JOURNAL.configure(None)
        regs = [r for r in read_journal(path, domain="slo",
                                        kind="step_regression")
                if r["step_kind"] == "train"]
        assert regs, "the injected stall was never journaled"
        r = validate(regs[-1])
        assert r["phase"] == "compute"         # the injected phase
        assert r["factor"] >= 3.0
        # ... and the postmortem bundle rode along, reason naming it
        files = [f for f in os.listdir(dumps)
                 if "slo_step_regression_compute" in f]
        assert files, f"no bundle for the stall in {os.listdir(dumps)}"
        with open(os.path.join(dumps, files[0]),
                  encoding="utf-8") as f:
            bundle = json.load(f)
        assert bundle["reason"] == "slo_step_regression_compute"
        prof = bundle["state"]["profiler"]
        assert "compute" in prof["kinds"]["train"]["phases"]

    @pytest.mark.chaos
    def test_decode_stall_attributed_to_decode_step(self, tmp_path):
        from paddle_tpu.serving import DecodeEngine
        from paddle_tpu.testing.faults import FaultPlan
        from tests.test_serving_faults import tiny_decoder

        path = str(tmp_path / "events.jsonl")
        JOURNAL.configure(path)
        PROFILER.enable(sample_every=1)
        WATCHDOG.configure(regression_factor=3.0, regression_steps=2,
                           min_samples=4, cooldown_s=0.0)
        import numpy as np
        dec = tiny_decoder()
        eng = DecodeEngine(dec, num_slots=2, page_size=4,
                           max_seq_len=32, num_pages=16)
        prompt = np.arange(4, dtype="int32")
        try:
            with FaultPlan.slow_phase(eng, "decode_step", ms=40.0,
                                      at=18, n=4) as stats:
                res = eng.submit(prompt, 24)
                eng.run(timeout=300)
                assert len(res.get(timeout=1)) == 24
        finally:
            PROFILER.disable()
        assert stats["injected"] >= 2
        JOURNAL.configure(None)
        regs = [r for r in read_journal(path, domain="slo",
                                        kind="step_regression")
                if r["step_kind"] == "decode"]
        assert regs, "the injected decode stall was never journaled"
        assert regs[-1]["phase"] == "decode_step"
