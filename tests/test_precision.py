"""Mixed-precision (bf16 compute, f32 params) mode tests.

The reference has no bf16; on TPU the MXU's native fast path is bf16
with f32 accumulation, so `paddle.init(compute_dtype="bfloat16")` is the
benchmark mode. These tests pin: numeric sanity of the cast matmul/conv
path, parameters staying f32, and a model actually training under it.
"""

import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.config import global_config
from paddle_tpu.core import registry


@pytest.fixture
def bf16_mode():
    old = global_config().compute_dtype
    paddle.init(compute_dtype="bfloat16", seed=0)
    yield
    global_config().compute_dtype = old


def test_matmul_bf16_accumulates_f32(bf16_mode):
    from paddle_tpu.ops import linear
    rng = np.random.RandomState(0)
    a = rng.randn(64, 256).astype("float32")
    b = rng.randn(256, 128).astype("float32")
    out = linear.matmul(a, b)
    # mixed-precision policy: activations come back in the compute dtype
    # (f32 master weights must not promote the activation graph)
    assert out.dtype == jnp.bfloat16
    y = np.asarray(out.astype(jnp.float32))
    ref = a @ b
    # bf16 has ~8 mantissa bits; f32 accumulation + one bf16 output
    # rounding keeps mean relative error well under 2%.
    err = np.abs(y - ref) / (np.abs(ref) + 1e-3)
    assert float(err.mean()) < 0.02


def test_conv_bf16_close_to_f32(bf16_mode):
    from paddle_tpu.ops import conv
    rng = np.random.RandomState(1)
    x = rng.randn(2, 16, 16, 8).astype("float32")
    w = rng.randn(3, 3, 8, 16).astype("float32")
    out16 = conv.conv2d(x, w, stride=1, padding=1)
    global_config().compute_dtype = "float32"
    y32 = np.asarray(conv.conv2d(x, w, stride=1, padding=1))
    global_config().compute_dtype = "bfloat16"
    y16 = np.asarray(out16.astype(jnp.float32))
    # bf16 inputs, f32 accumulation: mean relative error ~1.5% on N(0,1)
    # data (relative error blows up only where the output is near zero).
    rel = np.abs(y16 - y32) / (np.abs(y32) + 1e-1)
    assert float(rel.mean()) < 0.02
    assert float(np.abs(y16 - y32).max()) < 0.25


def test_model_trains_in_bf16(bf16_mode):
    registry.reset_name_counters()
    img = paddle.layer.data("x", paddle.data_type.dense_vector(64))
    h = paddle.layer.fc(img, size=32, act=paddle.activation.Relu())
    out = paddle.layer.fc(h, size=4, act=paddle.activation.Softmax())
    lbl = paddle.layer.data("y", paddle.data_type.integer_value(4))
    cost = paddle.layer.classification_cost(out, lbl)
    params = paddle.create_parameters(paddle.Topology(cost))
    # parameters remain f32 at rest (mixed precision contract)
    for v in params.raw.values():
        assert v.dtype == np.float32
    tr = paddle.SGD(cost=cost, parameters=params,
                    update_equation=paddle.optimizer.Adam(learning_rate=1e-2))
    rng = np.random.RandomState(0)
    feats = rng.randn(64, 64).astype("float32")
    labels = rng.randint(0, 4, 64)

    def reader():
        yield [(feats[i], int(labels[i])) for i in range(64)]

    losses = []
    tr.train(reader, num_passes=20,
             event_handler=lambda e: losses.append(e.cost)
             if isinstance(e, paddle.event.EndIteration) else None)
    assert losses[-1] < losses[0] * 0.5        # actually learning
    for v in tr.parameters.raw.values():
        assert v.dtype == np.float32           # still f32 after updates
