"""Data-pipeline chaos suite (docs/robustness.md "Data pipeline").

Drives paddle_tpu/testing/faults.py's data-path faults — hung/slow
source, raising mapper, crashing worker, corrupt pickled records —
against the supervised pipeline (reader/pipeline.py) and the real train
loop, and proves the resumable-reader contract: a full training pass
completes under mixed injected faults with EXACT quarantine counts and
zero lost/duplicated good records, and a SIGKILL'd run auto-resumes
mid-pass consuming each remaining record exactly once.
"""

import os
import pickle
import threading
import time

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.reader import (CheckpointableReader, ErrorBudget,
                               ErrorBudgetExceeded, batch, supervised)
from paddle_tpu.reader import recordio as rio
from paddle_tpu.testing.faults import FaultPlan
from paddle_tpu.trainer.checkpoint import CheckpointManager
from paddle_tpu.trainer.event import DataFaultEvent, FaultEvent
from paddle_tpu.utils.stats import global_counters


def counts(n):
    def reader():
        return iter(range(n))
    return reader


def make_shard(path, n=32, corrupt_at=(), chunk_bytes=256, dim=8, seed=0):
    """A RecordIO shard of pickled (id, float32[dim], label) samples,
    with the chosen record indices replaced by unpicklable garbage."""
    rng = np.random.RandomState(seed)
    feats = rng.randn(n, dim).astype("float32")
    labels = rng.randint(0, 2, n)

    def records():
        for i in range(n):
            yield pickle.dumps((i, feats[i], int(labels[i])))
    recs = records()
    if corrupt_at:
        recs = FaultPlan(seed=seed).corrupt_records(recs, corrupt_at)
    rio.write_records(str(path), recs, max_chunk_bytes=chunk_bytes)
    return str(path)


# ------------------------------------------------------------ ErrorBudget

class TestErrorBudget:
    def test_counts_and_stat(self):
        base = global_counters.value("pipeline/quarantined")
        eb = ErrorBudget(max_bad=5)
        for i in range(3):
            eb.record(ValueError(f"e{i}"), where=f"s{i}")
        assert eb.bad == 3 and not eb.exhausted
        assert global_counters.value("pipeline/quarantined") == base + 3

    def test_exhaustion_emits_event_once(self):
        events = []
        eb = ErrorBudget(max_bad=1, on_bad="log", on_event=events.append)
        eb.record(ValueError("a"))
        eb.record(ValueError("b"))
        eb.record(ValueError("c"))
        data = [e for e in events if isinstance(e, DataFaultEvent)]
        assert len(data) == 1 and data[0].kind == "data_budget"
        assert isinstance(data[0], FaultEvent)   # one handler sees both
        assert eb.exhausted

    def test_raise_mode(self):
        eb = ErrorBudget(max_bad=2, on_bad="raise")
        eb.record(ValueError("a"))
        eb.record(ValueError("b"))
        with pytest.raises(ErrorBudgetExceeded):
            eb.record(ValueError("c"))

    def test_validation(self):
        with pytest.raises(ValueError):
            ErrorBudget(on_bad="explode")
        with pytest.raises(ValueError):
            ErrorBudget(max_bad=-1)


# ----------------------------------------------------- supervised pipeline

class TestSupervisedPipeline:
    def test_passthrough_no_mapper(self):
        sr = supervised(counts(100), buffer_size=8)
        assert list(sr()) == list(range(100))

    def test_mapper_ordered_and_unordered(self):
        sr = supervised(counts(50), mapper=lambda v: v * 2, num_workers=4,
                        order=True)
        assert list(sr()) == [v * 2 for v in range(50)]
        sr = supervised(counts(50), mapper=lambda v: v * 2, num_workers=4)
        assert sorted(sr()) == [v * 2 for v in range(50)]

    def test_raising_mapper_quarantined_exact(self):
        plan = FaultPlan()
        eb = ErrorBudget(max_bad=10)
        sr = supervised(counts(40),
                        mapper=plan.raising_mapper(lambda v: v, [3, 11, 27]),
                        num_workers=2, order=True, error_budget=eb)
        out = list(sr())
        assert len(out) == 37 and eb.bad == 3
        assert out == [v for v in range(40) if v not in (3, 11, 27)]

    def test_budget_exhaustion_aborts_epoch(self):
        plan = FaultPlan()
        eb = ErrorBudget(max_bad=1, on_bad="raise")
        sr = supervised(counts(40),
                        mapper=plan.raising_mapper(lambda v: v, [1, 2, 3]),
                        num_workers=1, error_budget=eb)
        with pytest.raises(ErrorBudgetExceeded):
            list(sr())

    def test_crashed_worker_restarts_zero_loss(self):
        plan = FaultPlan()
        events = []
        base = global_counters.value("pipeline/worker_restarts")
        sr = supervised(counts(40),
                        mapper=plan.crashing_mapper(lambda v: v * 10, [7]),
                        num_workers=2, on_event=events.append)
        out = sorted(sr())
        # the in-flight sample was requeued: nothing lost or duplicated
        assert out == [v * 10 for v in range(40)]
        assert sr.restarts == 1
        assert global_counters.value("pipeline/worker_restarts") == base + 1
        kinds = [e.kind for e in events if isinstance(e, DataFaultEvent)]
        assert "worker_restart" in kinds

    def test_restart_budget_bounded(self):
        plan = FaultPlan()
        events = []
        sr = supervised(counts(40),
                        mapper=plan.crashing_mapper(
                            lambda v: v, [0, 1, 2, 3, 4, 5]),
                        num_workers=1, max_restarts=2,
                        on_event=events.append)
        with pytest.raises(RuntimeError, match="restart budget"):
            list(sr())
        kinds = [e.kind for e in events if isinstance(e, DataFaultEvent)]
        assert "restart_budget" in kinds

    def test_source_error_propagates(self):
        def dying():
            def r():
                yield 1
                raise OSError("disk gone")
            return r
        sr = supervised(dying(), mapper=lambda v: v, num_workers=2)
        with pytest.raises(OSError, match="disk gone"):
            list(sr())

    @pytest.mark.chaos(timeout=60)
    def test_hung_source_detected_and_survived(self):
        """A finite hang (stuck NFS read) past sample_timeout: the
        watchdog logs + counts + emits source_stall, and the late
        sample is still delivered — detection, zero loss."""
        events = []
        base = global_counters.value("pipeline/stalls")
        rdr = FaultPlan.hung_reader(counts(20), hang={10: 0.7})
        sr = supervised(rdr, buffer_size=4, sample_timeout=0.15,
                        on_event=events.append)
        out = list(sr())
        assert out == list(range(20))
        assert sr.stalls >= 1
        assert global_counters.value("pipeline/stalls") > base
        kinds = [e.kind for e in events if isinstance(e, DataFaultEvent)]
        assert "source_stall" in kinds

    @pytest.mark.chaos(timeout=60)
    def test_hung_source_raise_mode(self):
        """on_stall='raise': an indefinitely hung source surfaces as
        TimeoutError instead of hanging the trainer forever. The test
        releases the hang afterwards so the thread exits cleanly."""
        release = threading.Event()
        rdr = FaultPlan.hung_reader(counts(20), release={5: release})
        sr = supervised(rdr, buffer_size=4, sample_timeout=0.1,
                        on_stall="raise", stall_limit=3)
        try:
            with pytest.raises(TimeoutError, match="stalled"):
                list(sr())
        finally:
            release.set()

    def test_abandon_mid_epoch_shuts_down(self):
        sr = supervised(counts(10000), mapper=lambda v: v, num_workers=3,
                        buffer_size=4)
        g = sr()
        for _ in range(5):
            next(g)
        g.close()
        # the conftest leak fixture asserts pt-data-* threads are gone


# -------------------------------------------------- CheckpointableReader

class TestCheckpointableReader:
    def test_full_sweep_and_epoch_turn(self, tmp_path):
        shard = make_shard(tmp_path / "s0", n=25, chunk_bytes=128)
        cr = CheckpointableReader(shard)
        ids = [s[0] for s in cr()]
        assert ids == list(range(25))
        assert cr.state() == {"epoch": 1, "shard": 0, "chunk": 0,
                              "offset": 0}
        assert [s[0] for s in cr()] == list(range(25))   # next pass

    def test_state_resume_exact(self, tmp_path):
        shard = make_shard(tmp_path / "s0", n=25, chunk_bytes=128)
        cr = CheckpointableReader(shard)
        it = iter(cr())
        head = [next(it)[0] for _ in range(11)]
        st = cr.state()
        cr2 = CheckpointableReader(shard)
        cr2.set_state(st)
        tail = [s[0] for s in cr2()]
        assert head + tail == list(range(25))

    def test_multi_shard_resume(self, tmp_path):
        p0 = make_shard(tmp_path / "a", n=10, chunk_bytes=96, seed=1)
        p1 = make_shard(tmp_path / "b", n=10, chunk_bytes=96, seed=2)
        cr = CheckpointableReader([p0, p1])
        it = iter(cr())
        for _ in range(13):
            next(it)
        st = cr.state()
        assert st["shard"] == 1
        cr2 = CheckpointableReader([p0, p1])
        cr2.set_state(st)
        assert len(list(cr2())) == 7

    def test_corrupt_records_quarantined_exact(self, tmp_path):
        bad = {2, 9, 17}
        shard = make_shard(tmp_path / "s0", n=25, corrupt_at=bad,
                           chunk_bytes=128)
        eb = ErrorBudget(max_bad=10)
        cr = CheckpointableReader(shard, error_budget=eb)
        ids = [s[0] for s in cr()]
        assert ids == [i for i in range(25) if i not in bad]
        assert eb.bad == len(bad)

    def test_no_budget_is_strict(self, tmp_path):
        shard = make_shard(tmp_path / "s0", n=8, corrupt_at={3},
                           chunk_bytes=128)
        with pytest.raises(Exception):
            list(CheckpointableReader(shard)())

    def test_state_validation(self, tmp_path):
        shard = make_shard(tmp_path / "s0", n=8)
        cr = CheckpointableReader(shard)
        with pytest.raises(ValueError, match="missing keys"):
            cr.set_state({"epoch": 0})
        with pytest.raises(ValueError, match="out of range"):
            cr.set_state({"epoch": 0, "shard": 5, "chunk": 0, "offset": 0})

    def test_batch_state_for(self, tmp_path):
        shard = make_shard(tmp_path / "s0", n=25, chunk_bytes=128)
        b = batch(CheckpointableReader(shard), 4)
        assert hasattr(b, "state_for")
        list(b())
        st = b.state_for(2)            # after 3 batches = 12 samples
        cr = CheckpointableReader(shard)
        cr.set_state(st)
        assert [s[0] for s in cr()] == list(range(12, 25))


# ------------------------------------------- the mixed-fault acceptance

def _trainer(seed=0):
    from paddle_tpu.core import registry
    registry.reset_name_counters()
    paddle.init(use_tpu=False, seed=seed)
    x = paddle.layer.data("x", paddle.data_type.dense_vector(8))
    y = paddle.layer.data("y", paddle.data_type.integer_value(2))
    out = paddle.layer.fc(x, size=2, act=paddle.activation.Softmax(),
                          name="out")
    cost = paddle.layer.classification_cost(out, y, name="cost")
    params = paddle.create_parameters(paddle.Topology(cost))
    return paddle.SGD(cost=cost, parameters=params,
                      update_equation=paddle.optimizer.Momentum(
                          learning_rate=0.05))


class TestMixedFaultTrainingPass:
    @pytest.mark.chaos(timeout=120)
    def test_full_pass_under_mixed_faults(self, tmp_path):
        """Acceptance: a training pass over a recordio source with 1
        hung read, 1 crashing worker, 3 corrupt records and 1 raising
        mapper completes with EXACTLY the injected bad-sample count
        quarantined (3 corrupt + 1 raising = 4) and zero lost or
        duplicated good records."""
        plan = FaultPlan(seed=3)
        corrupt = {5, 19, 33}
        n = 48
        shard = make_shard(tmp_path / "s0", n=n, corrupt_at=corrupt,
                           chunk_bytes=256)

        events = []
        eb = ErrorBudget(max_bad=10, on_event=events.append)
        seen_lock = threading.Lock()
        mapped_ids = []

        def strip_id(sample):
            # the mapper delivers (feat, label); record which good
            # records flowed through so loss/duplication is provable
            # (both injected wrappers raise BEFORE this inner mapper, so
            # quarantined/crashed calls are never recorded)
            rid, feat, label = sample
            with seen_lock:
                mapped_ids.append(rid)
            return (feat, label)

        # raising mapper: quarantine 1 good record; crashing worker:
        # the in-flight record is requeued and recorded on the retry
        mapper = plan.crashing_mapper(
            plan.raising_mapper(strip_id, [12]), [24])
        # the hung read sits late in the pass, when the (compiled)
        # consumer is actively waiting on the pipeline — the watchdog
        # must see the stall, and the late sample must still arrive
        source = FaultPlan.hung_reader(
            CheckpointableReader(shard, error_budget=eb),
            hang={40: 0.6})
        pipe = supervised(source, mapper=mapper, num_workers=2,
                          buffer_size=8, sample_timeout=0.15,
                          error_budget=eb, order=True,
                          on_event=events.append, name="chaos")

        tr = _trainer()
        end_batches = []

        def handler(e):
            if isinstance(e, paddle.event.EndIteration):
                end_batches.append(e.batch_id)

        tr.train(batch(pipe, 8), num_passes=1, event_handler=handler,
                 feeding={"x": 0, "y": 1})

        # exactly 4 quarantined: 3 corrupt records + 1 raising-mapper
        assert eb.bad == 4, (eb.bad, list(eb.last_errors))
        # zero lost/duplicated good records: every surviving good id was
        # mapped exactly once (the crash victim's retry counts once; the
        # raising-mapper victim never reached the inner mapper)
        from collections import Counter
        c = Counter(mapped_ids)
        assert all(v == 1 for v in c.values()), c
        good = set(range(n)) - corrupt
        missing = good - set(c)
        assert len(missing) == 1                 # the raising-mapper one
        assert set(c) == good - missing
        # every trained record reached the train loop: batch count adds up
        n_trained = n - len(corrupt) - 1
        assert len(end_batches) == (n_trained + 7) // 8
        # the pipeline detected the hung read and restarted the worker
        assert pipe.stalls >= 1
        assert pipe.restarts == 1
        kinds = {e.kind for e in events if isinstance(e, DataFaultEvent)}
        assert {"source_stall", "worker_restart"} <= kinds


# ------------------------------------------- SIGKILL mid-pass auto-resume

def _cpu_env():
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"
    return env


def _parse_log(path):
    out = []
    with open(path) as f:
        for line in f:
            parts = dict(p.split("=", 1) for p in line.split())
            out.append((int(parts["pass"]), int(parts["batch"]),
                        [int(v) for v in parts["ids"].split(",")]))
    return out


class TestSigkillReaderResume:
    @pytest.mark.chaos(timeout=300)
    def test_mid_pass_kill_consumes_remainder_exactly_once(self, tmp_path):
        """Acceptance: SIGKILL mid-pass, relaunch with the same flags —
        the checkpointed reader position makes the resumed run consume
        each remaining record EXACTLY once (no record re-read, none
        dropped), and the combined run matches an uninterrupted one
        bit-for-bit."""
        import subprocess
        import sys as _sys

        shard = make_shard(tmp_path / "train-00000", n=32, chunk_bytes=192)
        worker = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                              "reader_fault_worker.py")

        def launch(ckpt, log, delay):
            return subprocess.Popen(
                [_sys.executable, worker, shard, ckpt, log, "2",
                 str(delay)],
                env=_cpu_env(), stdout=subprocess.PIPE,
                stderr=subprocess.PIPE, text=True)

        # reference: uninterrupted run
        ref = launch(str(tmp_path / "ref_ck"), str(tmp_path / "ref.log"),
                     0.0)
        out, err = ref.communicate(timeout=240)
        assert ref.returncode == 0, err[-2000:]
        ref_done = [l for l in out.splitlines()
                    if l.startswith("WORKER DONE")][0]
        ref_log = _parse_log(tmp_path / "ref.log")

        # chaos: SIGKILL at the step-4 marker (printed strictly AFTER
        # step 4's synchronous checkpoint landed), then relaunch
        ck, log = str(tmp_path / "chaos_ck"), str(tmp_path / "chaos.log")
        victim = launch(ck, log, 0.1)
        died_at = FaultPlan.kill_at_marker(victim, step=4)
        assert died_at >= 4 and victim.returncode != 0
        assert CheckpointManager(ck).latest_step() is not None

        resumed = launch(ck, log, 0.0)
        out2, err2 = resumed.communicate(timeout=240)
        assert resumed.returncode == 0, err2[-2000:]
        res_done = [l for l in out2.splitlines()
                    if l.startswith("WORKER DONE")][0]

        # bit-identical final state vs never having died
        assert res_done == ref_done
        # the resume SEEKED: pass 0's consumed prefix (>= 16 records at
        # kill step 4, batch size 4) was never re-read — a legacy
        # consume-and-discard replay would read all 64 (2 passes x 32)
        read2 = int([l for l in out2.splitlines()
                     if l.startswith("WORKER READ")][0].split("=")[1])
        assert read2 <= 32 - 16 + 32, read2
        chaos_log = _parse_log(log)
        # batch replay boundary: the combined log may repeat at most the
        # one batch stepped after the last marker's checkpoint — dedup
        # by (pass, batch) must reproduce the reference EXACTLY
        dedup = {}
        for pass_id, batch_id, ids in chaos_log:
            key = (pass_id, batch_id)
            if key in dedup:
                assert dedup[key] == ids    # a replay is bit-identical
            dedup[key] = ids
        assert [(p, b, i) for (p, b), i in sorted(dedup.items())] == \
            [(p, b, i) for p, b, i in ref_log]
        # exactly-once for the records AFTER the kill point: the
        # resumed run's log never repeats a batch the first run logged
        # after its last checkpoint... stronger: per pass, each record
        # id appears exactly once in the deduped consumption
        for pass_id in (0, 1):
            ids = [i for (p, _), ii in dedup.items() if p == pass_id
                   for i in ii]
            assert sorted(ids) == list(range(32)), (pass_id, sorted(ids))
