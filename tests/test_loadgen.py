"""Unit + golden tests for paddle_tpu.loadgen (ISSUE 17).

Everything here is pure data — no topology, no threads, no journal
file. The golden literals pin the ISSUE's reproducibility acceptance
("the same seed reproduces the identical fault schedule and request
stream"): if a refactor perturbs any seeded draw, these fail with the
new values so the change is a deliberate re-pin, never an accident.

The verdict tests feed :func:`paddle_tpu.loadgen.evaluate` synthetic
record lists and assert each check trips on exactly its own failure
mode (duplicate settle, lost trace, KV leak, stale read, broken fault
chain, TTFT breach) while the others stay green.
"""

import hashlib

import numpy as np
import pytest

from paddle_tpu.loadgen import (ChatRequest, CtrRequest, FaultAction,
                                RngPlane, SoakSLO, arrival_fn,
                                chat_requests, ctr_requests, evaluate,
                                open_loop_schedule, plan_faults,
                                zipf_pmf)


# --------------------------------------------------------------------------
# RNG plane
# --------------------------------------------------------------------------

class TestRngPlane:
    def test_same_name_same_instance(self):
        plane = RngPlane(11)
        assert plane.stream("a") is plane.stream("a")

    def test_streams_independent_of_creation_order(self):
        # drawing from stream "a" must not perturb stream "b": the
        # property that keeps the goldens stable as the harness grows
        p1, p2 = RngPlane(5), RngPlane(5)
        _ = p1.stream("a").random(100)
        b_after_a = p1.stream("b").random(8)
        b_alone = p2.stream("b").random(8)
        np.testing.assert_array_equal(b_after_a, b_alone)

    def test_different_seeds_different_draws(self):
        a = RngPlane(1).stream("x").random(8)
        b = RngPlane(2).stream("x").random(8)
        assert not np.array_equal(a, b)


class TestZipf:
    def test_pmf_normalized_and_monotone(self):
        p = zipf_pmf(64, alpha=1.1)
        assert p.shape == (64,)
        assert abs(float(p.sum()) - 1.0) < 1e-12
        assert np.all(np.diff(p) < 0)          # strictly head-heavy

    def test_head_mass_grows_with_alpha(self):
        assert zipf_pmf(100, 1.5)[0] > zipf_pmf(100, 1.01)[0]


# --------------------------------------------------------------------------
# Open-loop arrivals
# --------------------------------------------------------------------------

class TestArrival:
    def test_schedule_deterministic(self):
        f = arrival_fn("diurnal", 6.0)
        a = open_loop_schedule(RngPlane(3).stream("x"), 10.0, f)
        b = open_loop_schedule(RngPlane(3).stream("x"), 10.0, f)
        assert a == b

    def test_schedule_sorted_and_bounded(self):
        offs = open_loop_schedule(RngPlane(3).stream("x"), 10.0,
                                  arrival_fn("constant", 5.0))
        assert offs == sorted(offs)
        assert all(0.0 <= o < 10.0 for o in offs)

    def test_schedule_golden(self):
        offs = open_loop_schedule(RngPlane(3).stream("x"), 10.0,
                                  arrival_fn("constant", 5.0))
        assert len(offs) == 51
        np.testing.assert_allclose(
            offs[:3], [0.184229, 0.194018, 0.297526], atol=1e-6)

    def test_mean_rate_preserved_across_shapes(self):
        # arrival_fn contracts that every shape keeps mean ~= rate, so
        # --duration x --rate stays the expected request budget
        for kind in ("constant", "ramp", "diurnal"):
            f = arrival_fn(kind, 8.0)
            grid = np.linspace(0.0, 1.0, 4097)
            mean = float(np.mean([f(float(u)) for u in grid]))
            assert abs(mean - 8.0) < 0.05, (kind, mean)

    def test_unknown_shape_raises(self):
        with pytest.raises(ValueError):
            arrival_fn("bursty", 1.0)

    def test_zero_duration_empty(self):
        assert open_loop_schedule(RngPlane(0).stream("x"), 0.0,
                                  arrival_fn("constant", 5.0)) == []


# --------------------------------------------------------------------------
# Workload synthesis goldens
# --------------------------------------------------------------------------

def _build(seed=7):
    plane = RngPlane(seed)
    chat = chat_requests(plane, 8.0, arrival_fn("diurnal", 4.0))
    ctr = ctr_requests(plane, 8.0, arrival_fn("diurnal", 4.0))
    return chat, ctr


class TestWorkloadGoldens:
    def test_same_seed_same_stream(self):
        assert _build(7) == _build(7)

    def test_different_seed_different_stream(self):
        assert _build(7) != _build(8)

    def test_chat_golden(self):
        chat, _ = _build(7)
        assert len(chat) == 29
        assert chat[0] == ChatRequest(
            offset_s=pytest.approx(0.5981952388624608),
            trace_id="soak-7-chat-00000",
            prompt=(18, 24, 6, 26, 12, 19),
            max_new=6, disconnect_after=None)
        digest = hashlib.md5(repr(chat).encode()).hexdigest()
        assert digest == "dc5a5dea8b3525fe363f141b5e698352"

    def test_ctr_golden(self):
        _, ctr = _build(7)
        assert len(ctr) == 34
        assert ctr[0] == CtrRequest(
            offset_s=pytest.approx(0.38787831297355263),
            trace_id="soak-7-ctr-00000",
            ids=(28, 4, 1, 63, 0, 1040), label=0.0)
        digest = hashlib.md5(repr(ctr).encode()).hexdigest()
        assert digest == "092e02eb81fdb197bced1db9e5d34243"

    def test_chat_invariants(self):
        chat, _ = _build(7)
        traces = [r.trace_id for r in chat]
        assert len(set(traces)) == len(traces)
        for i, r in enumerate(chat):
            assert all(1 <= t < 40 for t in r.prompt)
            if (i + 1) % 7 == 0:
                assert r.disconnect_after == 2
            else:
                assert r.disconnect_after is None

    def test_ctr_invariants(self):
        _, ctr = _build(7)
        for r in ctr:
            assert len(r.ids) == 6
            assert all(0 <= k < 4096 for k in r.ids)
            assert r.label in (0.0, 1.0)


# --------------------------------------------------------------------------
# Fault schedule
# --------------------------------------------------------------------------

class TestPlanFaults:
    def test_deterministic(self):
        assert plan_faults(7, 8.0, "pokq") == plan_faults(7, 8.0, "pokq")

    def test_golden(self):
        plan = plan_faults(7, 8.0, "pokq")
        assert [a.family for a in plan] == ["k", "o", "q", "p"]
        assert plan[0] == FaultAction(
            "k", "lease_lapse", pytest.approx(1.955938158193134), 0)
        assert plan[3] == FaultAction(
            "p", "kill_replica", pytest.approx(5.323156750505509), 1)

    def test_p_and_k_pick_distinct_replicas(self):
        # the lapsed replica must never be the killed one — the soak
        # has to end with a live survivor serving
        for seed in range(40):
            plan = {a.family: a for a in plan_faults(seed, 10.0, "pk")}
            assert plan["p"].target != plan["k"].target

    def test_family_subset(self):
        plan = plan_faults(7, 8.0, "po")
        assert [a.family for a in plan] == ["o", "p"]

    def test_schedule_ordered_in_time(self):
        plan = plan_faults(7, 8.0, "pokq")
        ats = [a.at_s for a in plan]
        assert ats == sorted(ats)
        assert all(0.0 < t < 8.0 for t in ats)


# --------------------------------------------------------------------------
# Verdict engine on synthetic records
# --------------------------------------------------------------------------

def _rec(domain, kind, **fields):
    return dict(domain=domain, kind=kind, **fields)


def _passing_records():
    """A minimal soak's worth of records where every check passes:
    two chat streams (one finished, one deliberately disconnected that
    failed over off the killed replica), one CTR impression consumed
    by an online step, a (p) fault whose chain reconstructs, and one
    clean survivor."""
    return [
        _rec("soak", "request", workload="chat", trace_id="t1",
             outcome="done", ttft_ms=12.0, tok_ms=1.5),
        _rec("soak", "request", workload="chat", trace_id="t2",
             outcome="disconnect", ttft_ms=15.0, tok_ms=2.0),
        _rec("soak", "request", workload="ctr", trace_id="c1",
             outcome="done"),
        _rec("fleet", "route", trace_id="t1", replica="r0"),
        _rec("fleet", "settle", trace_id="t1", replica="r0"),
        _rec("fleet", "route", trace_id="t2", replica="r1"),
        _rec("soak", "fault_injected", family="p",
             action="kill_replica", target=1, at_s=1.0, fired=True,
             replica="r1", probe_trace="t2"),
        _rec("fleet", "failover", trace_id="t2", victim="r1"),
        _rec("fleet", "settle", trace_id="t2", replica="r0"),
        _rec("soak", "online_step", batches=1, samples=3, loss=0.1),
        _rec("soak", "replica_final", replica="r0",
             kv_pages_leaked=0, active_slots=0, kv_pages_used=0),
    ]


class TestVerdict:
    def test_passing_run(self):
        report = evaluate(_passing_records())
        assert report["ok"], report
        assert all(c["ok"] for c in report["checks"].values())
        assert report["counts"] == {
            "requests": 3, "chat": 2, "ctr": 1, "faults": 1,
            "records": 11}
        assert report["faults"][0]["family"] == "p"

    def test_duplicate_settle_fails(self):
        recs = _passing_records()
        recs.append(_rec("fleet", "settle", trace_id="t1",
                         replica="r1"))
        report = evaluate(recs)
        assert not report["ok"]
        assert not report["checks"]["exactly_once"]["ok"]
        assert report["checks"]["exactly_once"]["duplicates"] == {
            "t1": 2}

    def test_lost_trace_fails(self):
        recs = [r for r in _passing_records()
                if not (r["kind"] == "settle"
                        and r.get("trace_id") == "t1")]
        report = evaluate(recs)
        assert not report["checks"]["exactly_once"]["ok"]
        assert report["checks"]["exactly_once"]["lost"] == ["t1"]

    def test_kv_leak_fails(self):
        recs = _passing_records()
        recs.append(_rec("soak", "replica_final", replica="r2",
                         kv_pages_leaked=3, active_slots=0))
        report = evaluate(recs)
        assert not report["checks"]["kv_leaks"]["ok"]
        assert report["checks"]["kv_leaks"]["leaking"] == ["r2"]

    def test_stuck_slot_fails(self):
        recs = _passing_records()
        recs.append(_rec("soak", "replica_final", replica="r2",
                         kv_pages_leaked=0, active_slots=1))
        assert not evaluate(recs)["checks"]["kv_leaks"]["ok"]

    def test_no_finals_fails(self):
        recs = [r for r in _passing_records()
                if r["kind"] != "replica_final"]
        assert not evaluate(recs)["checks"]["kv_leaks"]["ok"]

    def test_stale_read_fails(self):
        recs = _passing_records()
        recs.append(_rec("embed", "stale_read", shard_id=0, rows=4,
                         age_s=9.0, bound_s=5.0))
        report = evaluate(recs)
        assert not report["checks"]["staleness"]["ok"]
        assert report["checks"]["staleness"]["stale_reads"] == 1

    def test_ttft_slo_breach_fails(self):
        report = evaluate(_passing_records(),
                          SoakSLO(ttft_p99_ms=10.0))
        assert not report["checks"]["latency_slo"]["ok"]
        assert report["checks"]["latency_slo"]["ttft_p99_ms"] > 10.0

    def test_chat_without_streams_fails_latency(self):
        recs = [_rec("soak", "request", workload="chat",
                     trace_id="t9", outcome="rejected"),
                _rec("soak", "replica_final", replica="r0",
                     kv_pages_leaked=0, active_slots=0),
                _rec("soak", "fault_injected", family="q", fired=True,
                     action="coordinator_outage", target=None,
                     at_s=1.0),
                _rec("fleet", "stale_view"),
                _rec("fleet", "view_recovered")]
        assert not evaluate(recs)["checks"]["latency_slo"]["ok"]

    def test_missing_failover_breaks_p_chain(self):
        recs = [r for r in _passing_records()
                if r["kind"] != "failover"]
        report = evaluate(recs)
        assert not report["checks"]["fault_chains"]["ok"]
        chain = report["checks"]["fault_chains"]["chains"][0]
        assert chain["family"] == "p" and not chain["ok"]

    def test_no_faults_injected_fails(self):
        # a wedged conductor (families planned, nothing injected)
        # must not pass the fault check
        recs = [r for r in _passing_records()
                if r["kind"] != "fault_injected"]
        assert not evaluate(recs)["checks"]["fault_chains"]["ok"]

    def test_faultless_baseline_run_passes(self):
        # ...but a run whose run_start says NO families were planned
        # (--faults '') passes the check vacuously
        recs = [r for r in _passing_records()
                if r["kind"] != "fault_injected"]
        recs.insert(0, _rec("soak", "run_start", seed=7,
                            families=""))
        report = evaluate(recs)
        assert report["checks"]["fault_chains"]["ok"]
        assert report["checks"]["fault_chains"]["injected"] == 0
        # a run_start that DID plan families still fails without
        # injections
        recs[0] = _rec("soak", "run_start", seed=7, families="po")
        assert not evaluate(recs)["checks"]["fault_chains"]["ok"]

    def test_o_chain_requires_kill_before_restore(self):
        base = [r for r in _passing_records()
                if r["kind"] != "fault_injected"]
        fault = _rec("soak", "fault_injected", family="o",
                     action="kill_shard_commit", target=0, at_s=1.0,
                     fired=True, shard=0)
        good = base + [
            fault,
            _rec("embed", "shard_killed", shard_id=0),
            _rec("embed", "shard_replaced", shard_id=0),
            _rec("embed", "restore", shard_id=0, rows=2),
        ]
        assert evaluate(good)["checks"]["fault_chains"]["ok"]
        # replacement journaled BEFORE the kill = a broken chain
        bad = base + [
            fault,
            _rec("embed", "shard_replaced", shard_id=0),
            _rec("embed", "restore", shard_id=0, rows=2),
            _rec("embed", "shard_killed", shard_id=0),
        ]
        assert not evaluate(bad)["checks"]["fault_chains"]["ok"]

    def test_k_chain_lapse_then_rejoin(self):
        base = [r for r in _passing_records()
                if r["kind"] != "fault_injected"]
        fault = _rec("soak", "fault_injected", family="k",
                     action="lease_lapse", target=0, at_s=1.0,
                     fired=True, replica="r0")
        good = base + [fault,
                       _rec("fleet", "lease_lapse", replica="r0"),
                       _rec("fleet", "rejoin", replica="r0")]
        assert evaluate(good)["checks"]["fault_chains"]["ok"]
        bad = base + [fault,
                      _rec("fleet", "lease_lapse", replica="r0")]
        assert not evaluate(bad)["checks"]["fault_chains"]["ok"]

    def test_ctr_errors_fail_loop(self):
        recs = _passing_records()
        recs.append(_rec("soak", "request", workload="ctr",
                         trace_id="c2", outcome="error"))
        assert not evaluate(recs)["checks"]["ctr_loop"]["ok"]

    def test_ctr_without_online_steps_fails(self):
        recs = [r for r in _passing_records()
                if r["kind"] != "online_step"]
        assert not evaluate(recs)["checks"]["ctr_loop"]["ok"]

    def test_no_ctr_skips_ctr_check(self):
        recs = [r for r in _passing_records()
                if r.get("workload") != "ctr"
                and r["kind"] != "online_step"]
        report = evaluate(recs)
        assert "ctr_loop" not in report["checks"]
        assert report["ok"]
