"""PyDataProvider2 compat shim (reader/provider.py) —
python/paddle/trainer/PyDataProvider2.py:365 protocol on the v2 reader path."""

import os

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.reader.provider import (CacheType, DataProvider,
                                        define_py_data_sources2, provider,
                                        provider_reader)


@provider(input_types=[paddle.data_type.dense_vector(4),
                       paddle.data_type.integer_value(3)],
          should_shuffle=False, cache=CacheType.CACHE_PASS_IN_MEM)
def sample_process(settings, filename):
    base = int(filename.rsplit("-", 1)[-1])
    for i in range(3):
        yield np.full(4, base + i, np.float32), (base + i) % 3


@provider(input_types=[paddle.data_type.integer_value_sequence(10)],
          should_shuffle=False,
          init_hook=lambda settings, **kw: setattr(
              settings, "offset", kw.get("offset", 0)))
def seq_process(settings, filename):
    yield [settings.offset, settings.offset + 1]


def test_provider_decorator_returns_data_provider():
    assert isinstance(sample_process, DataProvider)
    assert sample_process.cache == CacheType.CACHE_PASS_IN_MEM


def test_provider_reader_yields_all_files():
    reader = provider_reader(sample_process, ["f-0", "f-10"])
    got = list(reader())
    assert len(got) == 6
    assert got[0][1] == 0 and got[3][1] == 10 % 3
    np.testing.assert_allclose(got[4][0], np.full(4, 11.0))


def test_provider_cache_pass_in_mem():
    calls = []

    @provider(input_types=[paddle.data_type.dense_vector(2)],
              should_shuffle=False, cache=CacheType.CACHE_PASS_IN_MEM)
    def p(settings, filename):
        calls.append(filename)
        yield np.zeros(2, np.float32)

    reader = provider_reader(p, ["only"])
    list(reader())
    list(reader())          # second pass must hit the cache
    assert calls == ["only"]


def test_init_hook_and_args():
    reader = provider_reader(seq_process, ["x"], offset=5)
    first_sample = list(reader())[0]
    assert list(first_sample) == [5, 6]


def test_file_list_from_text_file(tmp_path):
    lst = tmp_path / "train.list"
    lst.write_text("f-1\nf-2\n")
    reader = provider_reader(sample_process, str(lst))
    assert len(list(reader())) == 6


def test_define_py_data_sources2(tmp_path):
    import sys
    import types
    mod = types.ModuleType("fake_provider_mod")
    mod.process = sample_process
    sys.modules["fake_provider_mod"] = mod
    try:
        srcs = define_py_data_sources2(["f-0"], ["f-3"],
                                       "fake_provider_mod", "process")
        assert len(list(srcs["train"]())) == 3
        assert list(srcs["test"]())[0][1] == 0
    finally:
        del sys.modules["fake_provider_mod"]


def test_trains_through_sgd():
    """End-to-end: a v1 provider feeds SGD.train via the adapter."""
    reader = provider_reader(sample_process, ["f-0", "f-10"])
    x = paddle.layer.data("x", paddle.data_type.dense_vector(4))
    out = paddle.layer.fc(x, size=3, act=paddle.activation.Softmax())
    lbl = paddle.layer.data("label", paddle.data_type.integer_value(3))
    cost = paddle.layer.classification_cost(out, lbl)
    params = paddle.create_parameters(paddle.Topology(cost))
    trainer = paddle.SGD(cost=cost, parameters=params,
                         update_equation=paddle.optimizer.Adam(
                             learning_rate=1e-2))
    seen = []
    trainer.train(paddle.reader.batch(reader, 3),
                  num_passes=1,
                  event_handler=lambda e: seen.append(e))
    assert any(isinstance(e, paddle.event.EndPass) for e in seen)
