"""ptlint rule-engine tests: each rule must catch its target pattern
(positive fixture) and stay quiet on the idiomatic-correct variant
(negative fixture), plus suppression semantics, baseline semantics,
config parsing, and the runtime sanitizer (compile budgets + leaked
tracers — including the induced-recompile-loop proof).
"""

import json
import textwrap

import pytest

from paddle_tpu.analysis import rules as R
from paddle_tpu.analysis.baseline import (load_baseline, match_baseline,
                                          write_baseline)
from paddle_tpu.analysis.core import iter_suppressions, parse_file
from paddle_tpu.analysis.runner import LintConfig, lint_paths


def run_rule(rule_cls, src, path="paddle_tpu/mod.py", options=None):
    ctx = parse_file("<mem>", path, text=textwrap.dedent(src))
    assert ctx is not None, "fixture snippet does not parse"
    return list(rule_cls(options).check(ctx))


# ================================================================== R1
class TestHostSync:
    def test_catches_float_on_traced_param(self):
        hits = run_rule(R.HostSyncRule, """
            import jax, jax.numpy as jnp
            @jax.jit
            def step(params, x):
                loss = jnp.sum(x)
                return float(loss)
        """)
        assert len(hits) == 1 and hits[0].rule == "R1"
        assert "float()" in hits[0].message

    def test_catches_item_and_asarray_and_device_get(self):
        hits = run_rule(R.HostSyncRule, """
            import jax
            import numpy as np
            @jax.jit
            def step(x):
                a = x.item()
                b = np.asarray(x)
                c = jax.device_get(x)
                return a, b, c
        """)
        assert sorted(h.line for h in hits) == [6, 7, 8]

    def test_catches_in_function_reached_from_jitted(self):
        # reachability: helper isn't decorated, but the jitted step
        # calls it — the sync still happens inside the trace
        hits = run_rule(R.HostSyncRule, """
            import jax, jax.numpy as jnp

            def helper(v):
                return float(v)

            @jax.jit
            def step(x):
                return helper(jnp.sum(x))
        """)
        assert len(hits) == 1 and hits[0].line == 5

    def test_quiet_on_untraced_function(self):
        assert not run_rule(R.HostSyncRule, """
            def host_side(e):
                return float(e.cost)
        """)

    def test_quiet_on_static_closure_value(self):
        # float(L) over a Python int closure is trace-time constant
        # folding, not a sync
        assert not run_rule(R.HostSyncRule, """
            import jax, jax.numpy as jnp
            def build(L, alpha):
                def run(p, scores):
                    return scores / float(L) ** alpha
                return jax.jit(run)
        """)


# ================================================================== R2
class TestRecompile:
    def test_catches_jit_in_loop(self):
        hits = run_rule(R.RecompileRule, """
            import jax
            def train(xs):
                for x in xs:
                    f = jax.jit(lambda v: v * 2)
                    f(x)
        """)
        assert len(hits) == 1 and "loop" in hits[0].message

    def test_catches_jit_decorated_def_in_loop(self):
        hits = run_rule(R.RecompileRule, """
            import jax
            def train(xs):
                while xs:
                    @jax.jit
                    def f(v):
                        return v * 2
                    f(xs.pop())
        """)
        assert hits and "fresh compile cache" in hits[0].message

    def test_catches_lambda_arg_to_jitted_callable(self):
        hits = run_rule(R.RecompileRule, """
            import jax
            g = jax.jit(lambda x, cb: cb(x))
            def drive(x):
                return g(x, lambda v: v + 1)
        """)
        assert len(hits) == 1 and "closure identity" in hits[0].message

    def test_quiet_on_hoisted_jit(self):
        assert not run_rule(R.RecompileRule, """
            import jax
            step = jax.jit(lambda v: v * 2)
            def train(xs):
                for x in xs:
                    step(x)
        """)

    def test_quiet_on_jit_built_once_in_function(self):
        assert not run_rule(R.RecompileRule, """
            import jax
            def build(fn):
                return jax.jit(fn, donate_argnums=(0,))
        """)


# ================================================================== R3
class TestTraceSideEffect:
    def test_catches_print_global_and_closure_append(self):
        hits = run_rule(R.TraceSideEffectRule, """
            import jax
            log = []
            @jax.jit
            def step(x):
                global total
                print("x =", x)
                log.append(x)
                return x * 2
        """)
        kinds = sorted(h.line for h in hits)
        assert kinds == [6, 7, 8]

    def test_quiet_on_local_list_append(self):
        # building a list of layer outputs locally is the normal idiom
        assert not run_rule(R.TraceSideEffectRule, """
            import jax
            @jax.jit
            def step(x):
                outs = []
                for i in range(3):
                    outs.append(x * i)
                return outs
        """)

    def test_quiet_outside_traced_code(self):
        assert not run_rule(R.TraceSideEffectRule, """
            log = []
            def host(e):
                print(e)
                log.append(e)
        """)


# ================================================================== R4
class TestPRNGReuse:
    def test_catches_sequential_reuse(self):
        hits = run_rule(R.PRNGReuseRule, """
            import jax
            def init(key):
                a = jax.random.normal(key, (3,))
                b = jax.random.uniform(key, (3,))
                return a + b
        """)
        assert len(hits) == 1 and "CORRELATED" in hits[0].message

    def test_catches_loop_reuse_without_split(self):
        hits = run_rule(R.PRNGReuseRule, """
            import jax
            def noise(key, n):
                out = []
                for _ in range(n):
                    out.append(jax.random.normal(key, (3,)))
                return out
        """)
        assert hits and "SAME randomness" in hits[0].message

    def test_quiet_with_split_between(self):
        assert not run_rule(R.PRNGReuseRule, """
            import jax
            def init(key):
                k1, key = jax.random.split(key)
                a = jax.random.normal(k1, (3,))
                k2, key = jax.random.split(key)
                b = jax.random.uniform(k2, (3,))
                return a + b
        """)

    def test_quiet_on_either_or_branches(self):
        # if/else arms are exclusive — one consumption per execution
        assert not run_rule(R.PRNGReuseRule, """
            import jax
            def sample(key, greedy):
                if greedy:
                    return jax.random.normal(key, (3,))
                else:
                    return jax.random.uniform(key, (3,))
        """)

    def test_quiet_on_loop_with_split_inside(self):
        assert not run_rule(R.PRNGReuseRule, """
            import jax
            def noise(key, n):
                out = []
                for _ in range(n):
                    sub, key = jax.random.split(key)
                    out.append(jax.random.normal(sub, (3,)))
                return out
        """)


# ================================================================== R5
class TestThreadHygiene:
    def test_catches_unnamed_and_misnamed_threads(self):
        hits = run_rule(R.ThreadHygieneRule, """
            import threading
            t1 = threading.Thread(target=print)
            t2 = threading.Thread(target=print, name="worker-0")
        """)
        assert len(hits) == 2
        assert "unnamed" in hits[0].message or "unnamed" in hits[1].message

    def test_catches_bare_acquire(self):
        hits = run_rule(R.ThreadHygieneRule, """
            import threading
            lock = threading.Lock()
            def f():
                lock.acquire()
                try:
                    pass
                finally:
                    lock.release()
        """)
        assert len(hits) == 1 and "with lock" in hits[0].message

    def test_quiet_on_convention(self):
        assert not run_rule(R.ThreadHygieneRule, """
            import threading
            PREFIX = "pt-data"
            t1 = threading.Thread(target=print, name="pt-serve-worker-0")
            t2 = threading.Thread(target=print, name=f"pt-data-w{3}")
            t3 = threading.Thread(target=print, name=f"{PREFIX}-src")
            lock = threading.Lock()
            def f():
                with lock:
                    pass
        """)


# ================================================================== R6
class TestDtypeWidening:
    SRC = """
        import numpy as np
        import jax.numpy as jnp
        def op(x):
            scale = np.asarray([0.5, 1.5])
            w = jnp.zeros((3,), dtype=np.float64)
            return x * scale + w
    """

    def test_catches_in_ops_paths(self):
        hits = run_rule(R.DtypeWideningRule, self.SRC,
                        path="paddle_tpu/ops/linear.py")
        assert len(hits) == 2
        assert {h.line for h in hits} == {5, 6}

    def test_quiet_outside_ops_paths(self):
        # host-side evaluator code legitimately accumulates in f64
        assert not run_rule(R.DtypeWideningRule, self.SRC,
                            path="paddle_tpu/evaluator/acc.py")

    def test_quiet_with_explicit_narrow_dtype(self):
        assert not run_rule(R.DtypeWideningRule, """
            import numpy as np
            def op(x):
                return x * np.asarray([0.5, 1.5], np.float32)
        """, path="paddle_tpu/ops/linear.py")

    def test_path_override_via_options(self):
        hits = run_rule(R.DtypeWideningRule, self.SRC,
                        path="custom/kernels/op.py",
                        options={"paths": ["custom/kernels"]})
        assert hits


# ================================================================== R7
class TestBroadExceptJit:
    def test_catches_broad_except_around_jit_assigned_name(self):
        hits = run_rule(R.BroadExceptJitRule, """
            import jax
            step = jax.jit(lambda p, x: p + x)
            def run(p, x):
                try:
                    return step(p, x)
                except Exception:
                    return None
        """)
        assert len(hits) == 1 and hits[0].rule == "R7"
        assert "step(...)" in hits[0].message

    def test_catches_bare_except_around_known_step_tail(self):
        hits = run_rule(R.BroadExceptJitRule, """
            def run(trainer, args):
                try:
                    out = trainer._train_step(*args)
                except:
                    out = None
                return out
        """)
        assert len(hits) == 1

    def test_catches_jit_producer_result(self):
        # a name assigned from _get_memory_step IS a jitted callable
        hits = run_rule(R.BroadExceptJitRule, """
            def run(trainer, k, args):
                fn = trainer._get_memory_step(k, False)
                try:
                    return fn(*args)
                except Exception:
                    return None
        """)
        assert len(hits) == 1

    def test_quiet_when_handler_reraises(self):
        # the adaptive-microbatcher idiom: absorb RESOURCE_EXHAUSTED,
        # re-raise everything else — a conditional raise satisfies R7
        assert not run_rule(R.BroadExceptJitRule, """
            def run(trainer, k, args, is_oom):
                fn = trainer._get_memory_step(k, False)
                try:
                    return fn(*args)
                except Exception as e:
                    if not is_oom(e):
                        raise
                    return None
        """)

    def test_quiet_on_specific_exception_types(self):
        assert not run_rule(R.BroadExceptJitRule, """
            import jax
            step = jax.jit(lambda p, x: p + x)
            def run(p, x):
                try:
                    return step(p, x)
                except (RuntimeError, MemoryError):
                    return None
        """)

    def test_quiet_on_broad_except_around_host_code(self):
        # a broad except around NON-jitted code is out of scope
        assert not run_rule(R.BroadExceptJitRule, """
            def load(path):
                try:
                    return open(path).read()
                except Exception:
                    return None
        """)


# ====================================================== R5 (daemon)
class TestDaemonLifecycle:
    def test_catches_daemon_thread_with_no_lifecycle(self):
        hits = run_rule(R.ThreadHygieneRule, """
            import threading
            class Poller:
                def start(self):
                    self._t = threading.Thread(
                        target=self._run, name="pt-x-poll", daemon=True)
                    self._t.start()
                def _run(self):
                    pass
        """)
        assert len(hits) == 1 and "daemon" in hits[0].message

    def test_quiet_when_scope_has_stop_lifecycle(self):
        assert not run_rule(R.ThreadHygieneRule, """
            import threading
            class Poller:
                def start(self):
                    self._stop = threading.Event()
                    self._t = threading.Thread(
                        target=self._run, name="pt-x-poll", daemon=True)
                    self._t.start()
                def close(self):
                    self._stop.set()
                    self._t.join(timeout=5)
                def _run(self):
                    pass
        """)


# ================================================================== R8
class TestLockOrder:
    def _finalize(self, src):
        import paddle_tpu.analysis.lockrules as LK
        ctx = parse_file("<mem>", "paddle_tpu/mod.py",
                         text=textwrap.dedent(src))
        rule = LK.LockOrderRule()
        assert not list(rule.check(ctx))     # findings come from finalize
        return rule, list(rule.finalize())

    def test_catches_in_file_order_cycle(self):
        _, hits = self._finalize("""
            from paddle_tpu.analysis.lockdep import named_lock
            class S:
                def __init__(self):
                    self._a = named_lock("t8.a")
                    self._b = named_lock("t8.b")
                def one(self):
                    with self._a:
                        with self._b:
                            pass
                def two(self):
                    with self._b:
                        with self._a:
                            pass
        """)
        assert len(hits) == 1 and hits[0].rule == "R8"
        assert "t8.a" in hits[0].message and "t8.b" in hits[0].message

    def test_quiet_on_consistent_order_and_graph_dump(self):
        rule, hits = self._finalize("""
            from paddle_tpu.analysis.lockdep import named_lock
            class S:
                def __init__(self):
                    self._a = named_lock("t8.a")
                    self._b = named_lock("t8.b")
                def one(self):
                    with self._a:
                        with self._b:
                            pass
                def two(self):
                    with self._a:
                        with self._b:
                            pass
        """)
        assert not hits
        assert "t8.a -> t8.b" in rule.graph_text()
        assert '"t8.a" -> "t8.b"' in rule.graph_dot()

    def test_catches_cross_file_cycle_through_runner(self, tmp_path):
        """The acquisition graph is GLOBAL: file one orders a->b, file
        two orders b->a, neither file alone has a cycle."""
        pkg = tmp_path / "pkg"
        pkg.mkdir()
        (pkg / "one.py").write_text(textwrap.dedent("""
            from paddle_tpu.analysis.lockdep import named_lock
            A = named_lock("x8.a")
            B = named_lock("x8.b")
            def fwd():
                with A:
                    with B:
                        pass
        """))
        (pkg / "two.py").write_text(textwrap.dedent("""
            from paddle_tpu.analysis.lockdep import named_lock
            A = named_lock("x8.a")
            B = named_lock("x8.b")
            def rev():
                with B:
                    with A:
                        pass
        """))
        cfg = LintConfig(root=str(tmp_path), paths=["pkg"],
                         rules=["R8"], baseline="")
        res = lint_paths(cfg, use_baseline=False)
        assert len(res.new) == 1 and res.new[0].rule == "R8"
        assert "x8.a" in res.new[0].message
        assert "one.py" in res.new[0].message or \
            "two.py" in res.new[0].message

    def test_journal_emit_under_lock_is_a_graph_edge(self):
        """The PR 9 shape: JOURNAL.emit while holding an app lock is an
        edge app-lock -> obs.journal even with no syntactic nesting."""
        rule, _ = self._finalize("""
            from paddle_tpu.analysis.lockdep import named_lock
            from paddle_tpu.obs.events import JOURNAL
            class S:
                def __init__(self):
                    self._lock = named_lock("t8.app")
                def work(self):
                    with self._lock:
                        JOURNAL.emit("x", "y")
        """)
        assert ("t8.app", "obs.journal") in rule._edges


# ================================================================== R9
class TestBlockingUnderLock:
    def _run(self, src):
        import paddle_tpu.analysis.lockrules as LK
        return run_rule(LK.BlockingUnderLockRule, src)

    def test_catches_sleep_join_queue_rpc_dump_under_lock(self):
        hits = self._run("""
            import time
            import queue
            from paddle_tpu.analysis.lockdep import named_lock
            from paddle_tpu.obs.flight import FLIGHT
            from paddle_tpu.utils.net import call_with_retry
            class S:
                def __init__(self):
                    self._lock = named_lock("t9.lock")
                    self.q = queue.Queue()
                def work(self, t):
                    with self._lock:
                        time.sleep(0.5)
                        t.join()
                        self.q.get()
                        call_with_retry(print, 1)
                        FLIGHT.dump("reason")
        """)
        reasons = sorted(h.message.split("(")[1].split(")")[0]
                         for h in hits)
        assert len(hits) == 5, reasons
        assert any("time.sleep" in r for r in reasons)
        assert any("queue.get" in r for r in reasons)
        assert any("RPC" in r for r in reasons)
        assert any("dump" in r for r in reasons)

    def test_catches_jitted_dispatch_under_lock(self):
        hits = self._run("""
            import jax
            from paddle_tpu.analysis.lockdep import named_lock
            class S:
                def __init__(self):
                    self._lock = named_lock("t9.lock")
                    self._step = jax.jit(lambda x: x)
                def work(self, mb):
                    with self._lock:
                        self._train_step(mb)
        """)
        assert len(hits) == 1 and "jitted dispatch" in hits[0].message

    def test_quiet_on_safe_variants(self):
        assert not self._run("""
            import time
            import queue
            from paddle_tpu.analysis.lockdep import (named_condition,
                                                     named_lock)
            class S:
                def __init__(self):
                    self._lock = named_lock("t9.lock")
                    self._cv = named_condition("t9.cv")
                    self.q = queue.Queue()
                def work(self):
                    time.sleep(0.1)             # not under a lock
                    with self._lock:
                        self.q.get(timeout=1.0)  # bounded wait
                        parts = ",".join(["a"])  # str.join, not Thread
                    with self._cv:
                        self._cv.wait(0.2)  # releases its own lock
        """)


# ================================================================= R10
class TestGuardedBy:
    def _run(self, src):
        import paddle_tpu.analysis.lockrules as LK
        return run_rule(LK.GuardedByRule, src)

    def test_catches_unguarded_mutation(self):
        hits = self._run("""
            from paddle_tpu.analysis.lockdep import named_lock
            class S:
                def __init__(self):
                    self._lock = named_lock("t10.lock")
                    self._items = []  # ptlint: guarded-by(t10.lock)
                def bad_append(self, x):
                    self._items.append(x)
                def bad_assign(self):
                    self._items = []
        """)
        assert len(hits) == 2
        assert all("guarded-by('t10.lock')" in h.message for h in hits)

    def test_quiet_under_lock_init_and_locked_helpers(self):
        assert not self._run("""
            from paddle_tpu.analysis.lockdep import named_lock
            class S:
                def __init__(self):
                    self._lock = named_lock("t10.lock")
                    self._items = []  # ptlint: guarded-by(t10.lock)
                def good(self, x):
                    with self._lock:
                        self._items.append(x)
                def _drain_locked(self):
                    self._items = []     # caller holds it by contract
                def read(self):
                    return len(self._items)   # reads are not checked
        """)


# ==================================================== suppressions
class TestSuppression:
    def test_inline_and_preceding_line_forms(self):
        text = textwrap.dedent("""
            import threading
            t = threading.Thread(target=print)  # ptlint: disable=R5(short-lived join below)
            # ptlint: disable=thread-hygiene(slug form, next line)
            u = threading.Thread(target=print)
            v = threading.Thread(target=print)
        """)
        sups = list(iter_suppressions(text))
        assert [s.line for s in sups] == [3, 5]
        assert sups[0].reason == "short-lived join below"
        ctx = parse_file("<mem>", "paddle_tpu/x.py", text=text)
        hits = list(R.ThreadHygieneRule().check(ctx))
        uncovered = [h for h in hits
                     if not any(s.covers(h) for s in sups)]
        assert [h.line for h in uncovered] == [6]

    def test_disable_inside_string_is_not_a_suppression(self):
        text = 's = "# ptlint: disable=R5(not a comment)"\n'
        assert not list(iter_suppressions(text))

    def test_wrong_rule_does_not_cover(self):
        text = ("import threading\n"
                "t = threading.Thread(target=print)"
                "  # ptlint: disable=R1(wrong rule)\n")
        sups = list(iter_suppressions(text))
        ctx = parse_file("<mem>", "paddle_tpu/x.py", text=text)
        hits = list(R.ThreadHygieneRule().check(ctx))
        assert hits and not any(s.covers(hits[0]) for s in sups)


# ======================================================== baseline
class TestBaseline:
    def _finding(self, src="t = threading.Thread(target=print)"):
        ctx = parse_file("<mem>", "paddle_tpu/x.py",
                         text=f"import threading\n{src}\n")
        return list(R.ThreadHygieneRule().check(ctx))[0]

    def test_match_consumes_and_reports_stale(self):
        f = self._finding()
        entry = {"rule": f.rule, "path": f.path, "source": f.source,
                 "count": 2, "why": "legacy"}
        new, old, stale = match_baseline([f], [entry])
        assert not new and old == [f]
        # one of the two budgeted occurrences is unused -> stale
        assert stale and stale[0]["source"] == f.source
        new2, old2, stale2 = match_baseline([f, f], [entry])
        assert not new2 and len(old2) == 2 and not stale2

    def test_unmatched_finding_stays_new(self):
        f = self._finding()
        entry = {"rule": "R1", "path": f.path, "source": f.source,
                 "count": 1, "why": "different rule"}
        new, old, stale = match_baseline([f], [entry])
        assert new == [f] and not old and stale

    def test_write_keeps_existing_justifications(self, tmp_path):
        f = self._finding()
        p = tmp_path / "baseline.json"
        write_baseline(str(p), [f], [])
        entries = load_baseline(str(p))
        assert entries[0]["why"].startswith("TODO")
        entries[0]["why"] = "grandfathered: fixed in the next PR"
        p.write_text(json.dumps({"entries": entries}))
        write_baseline(str(p), [f, f], load_baseline(str(p)))
        again = load_baseline(str(p))
        assert again[0]["count"] == 2
        assert again[0]["why"] == "grandfathered: fixed in the next PR"

    def test_entry_without_why_is_rejected(self, tmp_path):
        p = tmp_path / "baseline.json"
        p.write_text(json.dumps({"entries": [
            {"rule": "R5", "path": "x.py", "source": "s"}]}))
        with pytest.raises(ValueError, match="why"):
            load_baseline(str(p))


# ===================================================== runner/config
class TestRunnerConfig:
    def _tree(self, tmp_path, pyproject=True):
        pkg = tmp_path / "pkg"
        pkg.mkdir()
        (pkg / "good.py").write_text("import threading\n"
                                     "t = threading.Thread("
                                     "target=print, name='pt-x')\n")
        (pkg / "bad.py").write_text("import threading\n"
                                    "t = threading.Thread("
                                    "target=print)\n")
        if pyproject:
            (tmp_path / "pyproject.toml").write_text(textwrap.dedent("""
                [tool.ptlint]
                paths = ["pkg"]
                rules = ["R5"]
                baseline = "baseline.json"

                [tool.ptlint.dtype-widening]
                paths = ["pkg/ops"]
            """))
        return tmp_path

    def test_config_and_lint_roundtrip(self, tmp_path):
        from paddle_tpu.analysis.runner import load_config
        root = self._tree(tmp_path)
        cfg = load_config(str(root))
        assert cfg.paths == ["pkg"] and cfg.rules == ["R5"]
        assert cfg.rule_options.get("R6") == {"paths": ["pkg/ops"]}
        res = lint_paths(cfg)
        assert len(res.new) == 1 and res.new[0].path == "pkg/bad.py"
        assert res.files == 2 and not res.ok

    def test_baseline_round_trip_through_runner(self, tmp_path):
        from paddle_tpu.analysis.runner import load_config
        root = self._tree(tmp_path)
        cfg = load_config(str(root))
        res = lint_paths(cfg)
        write_baseline(str(root / "baseline.json"), res.new, [])
        res2 = lint_paths(load_config(str(root)))
        assert not res2.new and len(res2.baselined) == 1
        # fixing the finding makes the baseline entry STALE -> not ok
        (root / "pkg" / "bad.py").write_text(
            "import threading\n"
            "t = threading.Thread(target=print, name='pt-fixed')\n")
        res3 = lint_paths(load_config(str(root)))
        assert not res3.new and res3.stale_baseline and not res3.ok

    def test_unknown_rule_id_rejected(self, tmp_path):
        root = self._tree(tmp_path, pyproject=False)
        cfg = LintConfig(root=str(root), paths=["pkg"], rules=["R99"],
                         baseline="")
        with pytest.raises(ValueError, match="R99"):
            lint_paths(cfg)

    def test_syntax_error_is_reported_not_fatal(self, tmp_path):
        root = self._tree(tmp_path, pyproject=False)
        (root / "pkg" / "broken.py").write_text("def f(:\n")
        cfg = LintConfig(root=str(root), paths=["pkg"], rules=["R5"],
                         baseline="")
        res = lint_paths(cfg)
        assert any("broken.py" in e for e in res.errors)
        assert not res.ok

    def test_cli_lint_subcommand(self, tmp_path, capsys):
        from paddle_tpu import cli
        root = self._tree(tmp_path)
        rc = cli.main(["lint", str(root / "pkg"), "--format", "github",
                       "--no-baseline"])
        out = capsys.readouterr().out
        assert rc == 1
        assert "::error file=" in out and "bad.py,line=2" in out
        assert "R5[thread-hygiene]" in out

    def test_runner_root_flag(self, tmp_path, capsys):
        from paddle_tpu.analysis.runner import main as lint_main
        root = self._tree(tmp_path)
        rc = lint_main(["--root", str(root), "--format", "github"])
        out = capsys.readouterr().out
        assert rc == 1
        assert "::error file=pkg/bad.py,line=2" in out


# ====================================================== sanitizer
class TestSanitizer:
    def test_fails_on_induced_recompile_loop_passes_after_fix(self):
        """The acceptance-criteria proof: a jit-in-the-loop recompile
        storm blows the budget; hoisting the jit (the R2 fix) passes
        within it."""
        import jax
        import jax.numpy as jnp
        from paddle_tpu.analysis.sanitizer import (CompileBudgetExceeded,
                                                   compile_watch)
        x = jnp.ones((4,))
        with pytest.raises(CompileBudgetExceeded, match="retraces"):
            with compile_watch(max_compiles=3):
                for _ in range(6):
                    # the ptlint-R2 anti-pattern, induced on purpose
                    jax.jit(lambda v: v * 2)(x)  # ptlint: disable=R2(induced recompile loop — the sanitizer test target)
        # the fix: bind once, reuse the cache
        with compile_watch(max_compiles=3) as watch:
            f = jax.jit(lambda v: v * 2)
            for _ in range(6):
                f(x)
        assert watch.count("<lambda>") <= 1

    @pytest.mark.recompile_budget(max_compiles=2)
    def test_marker_enforces_budget_on_stable_step(self):
        """recompile_budget-marked: a shape-stable jitted step compiles
        once; the conftest fixture fails this test if it ever starts
        retracing."""
        import jax
        import jax.numpy as jnp
        f = jax.jit(lambda v: (v * 2).sum())
        for _ in range(8):
            f(jnp.ones((4, 4)))

    def test_watch_counts_per_function(self):
        import jax
        import jax.numpy as jnp
        from paddle_tpu.analysis.sanitizer import compile_watch

        def alpha(v):
            return v + 1

        with compile_watch() as watch:
            f = jax.jit(alpha)
            f(jnp.ones(3))      # compile 1
            f(jnp.ones(3))      # cache hit
            f(jnp.ones(5))      # new shape: compile 2
        assert watch.count("alpha") == 2

    def test_find_tracers_catches_closure_leak(self):
        import jax
        import jax.numpy as jnp
        from paddle_tpu.analysis.sanitizer import find_tracers
        leaked = []

        @jax.jit
        def step(x):
            leaked.append(x)  # ptlint: disable=R3(the leak this test exists to catch)
            return x * 2

        step(jnp.ones(3))
        hits = find_tracers({"stash": leaked})
        assert hits and "stash" in hits[0][0]
        assert not find_tracers({"clean": [1.0, jnp.ones(2)]})

    def test_no_leaked_tracers_raises_at_jit_boundary(self):
        import jax
        import jax.numpy as jnp
        from paddle_tpu.analysis.sanitizer import no_leaked_tracers
        leaked = []
        with pytest.raises(Exception, match="[Ll]eak"):
            with no_leaked_tracers():
                jax.jit(
                    # ptlint: disable=R3(the leak under test)
                    lambda x: (leaked.append(x), x * 3)[1]
                )(jnp.ones(3))


# ================================================================== R11
class TestJournalContract:
    """R11 journal-contract: literal emit sites proven against
    obs/catalog.py (docs/static_analysis.md 'Event & protocol
    contracts')."""

    def _run(self, src, path="paddle_tpu/mod.py", options=None):
        import paddle_tpu.analysis.contractrules as CR
        return run_rule(CR.JournalContractRule, src, path=path,
                        options=options)

    def test_catches_undeclared_domain_kind(self):
        hits = self._run("""
            from paddle_tpu.obs.events import emit as journal_emit
            def f():
                journal_emit("nope", "nada", x=1)
        """)
        assert len(hits) == 1 and hits[0].rule == "R11"
        assert "(nope/nada)" in hits[0].message

    def test_catches_missing_required_field(self):
        # serving/drain requires `action`
        hits = self._run("""
            from paddle_tpu.obs.events import emit as journal_emit
            def f():
                journal_emit("serving", "drain")
        """)
        assert len(hits) == 1
        assert "required" in hits[0].message
        assert "action" in hits[0].message

    def test_catches_undeclared_field(self):
        hits = self._run("""
            from paddle_tpu.obs.events import emit as journal_emit
            def f():
                journal_emit("serving", "drain", action="begin",
                             bogus=1)
        """)
        assert len(hits) == 1 and "bogus" in hits[0].message

    def test_quiet_on_conforming_site_and_method_form(self):
        assert not self._run("""
            from paddle_tpu.obs.events import JOURNAL
            def f():
                JOURNAL.emit("serving", "drain", action="begin")
        """)

    def test_star_kwargs_vets_kind_only(self):
        # **fields is not statically knowable: (domain, kind) is
        # still checked, the field lists are not
        assert not self._run("""
            from paddle_tpu.obs.events import emit
            def f(kw):
                emit("serving", "drain", **kw)
        """)
        hits = self._run("""
            from paddle_tpu.obs.events import emit
            def f(kw):
                emit("nope", "nada", **kw)
        """)
        assert len(hits) == 1

    def test_scoped_to_paddle_tpu_tree(self):
        # tests/ may emit anything (fixtures fabricate records)
        assert not self._run("""
            from paddle_tpu.obs.events import emit
            def f():
                emit("nope", "nada")
        """, path="tests/test_x.py")

    def test_wrapper_option_pins_domain(self):
        opts = {"wrappers": {"_emit_x": "serving"}}
        assert not self._run("""
            def f():
                _emit_x("drain", action="begin")
        """, options=opts)
        hits = self._run("""
            def f():
                _emit_x("nada")
        """, options=opts)
        assert len(hits) == 1 and "(serving/nada)" in hits[0].message

    def test_stale_entry_reported_in_finalize(self):
        import paddle_tpu.analysis.contractrules as CR
        rule = CR.JournalContractRule({"stale": True})
        ctx = parse_file("<mem>", "paddle_tpu/mod.py", text=textwrap.dedent("""
            from paddle_tpu.obs.events import emit
            def f():
                emit("serving", "drain", action="begin")
        """))
        assert not list(rule.check(ctx))
        stale = list(rule.finalize())
        # everything but serving/drain is unseen by this one-file run;
        # every stale finding anchors at the catalog itself, and
        # dynamic kinds (emit_event dispatch) are exempt
        assert stale
        assert all(f.path == CR.CATALOG_PATH for f in stale)
        assert not any("(serving/drain)" in f.message for f in stale)
        assert not any("(data/source_stall)" in f.message
                       for f in stale)

    def test_no_stale_without_option(self):
        import paddle_tpu.analysis.contractrules as CR
        rule = CR.JournalContractRule(None)
        assert not list(rule.finalize())


# ================================================================== R12
class TestMetricContract:
    """R12 metric-contract: registered paddle_tpu_* families vs the
    catalog vs docs/observability.md, drift both directions."""

    def _run(self, src, path="paddle_tpu/mod.py", options=None):
        import paddle_tpu.analysis.contractrules as CR
        opts = {"doc": "/nonexistent-ptlint-doc.md"}
        opts.update(options or {})
        rule = CR.MetricContractRule(opts)
        ctx = parse_file("<mem>", path, text=textwrap.dedent(src))
        assert ctx is not None
        found = list(rule.check(ctx))
        return found + list(rule.finalize())

    def test_catches_undeclared_family(self):
        hits = self._run("""
            from paddle_tpu.obs.metrics import REGISTRY
            REGISTRY.counter("paddle_tpu_bogus_total")
        """)
        assert len(hits) == 1 and hits[0].rule == "R12"
        assert "paddle_tpu_bogus_total" in hits[0].message

    def test_catches_type_mismatch(self):
        # catalogued as counter
        hits = self._run("""
            from paddle_tpu.obs.metrics import REGISTRY
            REGISTRY.gauge("paddle_tpu_prefix_hit_pages")
        """)
        assert len(hits) == 1 and "counter" in hits[0].message

    def test_catches_label_mismatch(self):
        # catalogued with labels ("kind",)
        hits = self._run("""
            from paddle_tpu.obs.metrics import REGISTRY
            REGISTRY.gauge("paddle_tpu_profile_step_ms")
        """)
        assert len(hits) == 1 and "label" in hits[0].message

    def test_quiet_on_conforming_registrations(self):
        assert not self._run("""
            from paddle_tpu.obs.metrics import REGISTRY, SampleFamily
            REGISTRY.counter("paddle_tpu_prefix_hit_pages")
            REGISTRY.gauge("paddle_tpu_profile_step_ms",
                           "mean wall ms", labelnames=("kind",))
            fam = SampleFamily("paddle_tpu_protocol_tracked", "gauge")
        """)

    def test_fstring_prefix_vetted(self):
        assert not self._run("""
            from paddle_tpu.obs.metrics import REGISTRY
            def reg(name):
                REGISTRY.counter(f"paddle_tpu_serving_{name}")
        """)
        hits = self._run("""
            from paddle_tpu.obs.metrics import REGISTRY
            def reg(name):
                REGISTRY.counter(f"paddle_tpu_bogus_{name}")
        """)
        assert len(hits) == 1 and "prefix" in hits[0].message

    def test_doc_drift_both_directions(self, tmp_path):
        doc = tmp_path / "observability.md"
        doc.write_text("| `paddle_tpu_made_up_total` | counter |\n")
        hits = self._run("""
            from paddle_tpu.obs.metrics import REGISTRY
            REGISTRY.counter("paddle_tpu_prefix_hit_pages")
        """, options={"doc": str(doc)})
        # docs -> catalog: the documented name does not exist
        assert any("paddle_tpu_made_up_total" in h.message
                   and str(doc) in h.path for h in hits)
        # catalog -> docs: declared families missing from the doc
        assert any("paddle_tpu_prefix_hit_pages" in h.message
                   and "absent" in h.message for h in hits)

    def test_real_docs_agree_with_catalog(self):
        # the repo's own doc tables are lint-enforced: zero drift
        hits = self._run("""
            x = 1
        """, options={"doc": "docs/observability.md"})
        assert hits == []


# ================================================================== R13
class TestProtocolPaths:
    """R13 protocol-emission-paths: a function emitting a check_paths
    protocol's start must reach a declared terminal on every exit
    path, exception edges included."""

    def _run(self, src, path="paddle_tpu/mod.py", options=None):
        import paddle_tpu.analysis.contractrules as CR
        return run_rule(CR.ProtocolPathsRule, src, path=path,
                        options=options)

    def test_catches_return_without_terminal(self):
        hits = self._run("""
            from paddle_tpu.obs.events import emit
            def f(t):
                emit("serving", "hop", trace_id=t, phase="start")
                work()
                return 1
        """)
        assert len(hits) == 1 and hits[0].rule == "R13"
        assert "serving_hop" in hits[0].message

    def test_catches_branch_missing_terminal(self):
        hits = self._run("""
            from paddle_tpu.obs.events import emit
            def f(t, ok):
                emit("serving", "hop", trace_id=t, phase="start")
                if ok:
                    emit("serving", "hop", trace_id=t, phase="settle")
                    return 1
                return 0
        """)
        assert len(hits) == 1

    def test_catches_typed_handler_exception_edge(self):
        # the try body can raise something OTHER than ValueError: that
        # edge exits the function with the machine still open
        hits = self._run("""
            from paddle_tpu.obs.events import emit
            def f(t):
                emit("serving", "hop", trace_id=t, phase="start")
                try:
                    work()
                except ValueError:
                    pass
                emit("serving", "hop", trace_id=t, phase="settle")
        """)
        assert len(hits) == 1

    def test_quiet_when_finally_holds_terminal(self):
        # the satellite-3 positive case: a terminal on the exception
        # path via try/finally proves every exit
        assert not self._run("""
            from paddle_tpu.obs.events import emit
            def f(t):
                emit("serving", "hop", trace_id=t, phase="start")
                try:
                    work()
                    emit("serving", "hop", trace_id=t, phase="settle",
                         tokens=3)
                    return 1
                finally:
                    emit("serving", "hop", trace_id=t, phase="torn",
                         reason="exception")
        """)

    def test_quiet_when_broad_handler_emits_terminal(self):
        assert not self._run("""
            from paddle_tpu.obs.events import emit
            def f(t):
                emit("serving", "hop", trace_id=t, phase="start")
                try:
                    work()
                    emit("serving", "hop", trace_id=t, phase="settle")
                except Exception:
                    emit("serving", "hop", trace_id=t, phase="error",
                         reason="boom")
        """)

    def test_quiet_on_terminal_every_branch(self):
        assert not self._run("""
            from paddle_tpu.obs.events import emit
            def f(t, ok):
                emit("serving", "hop", trace_id=t, phase="start")
                if ok:
                    emit("serving", "hop", trace_id=t, phase="settle")
                    return 1
                emit("serving", "hop", trace_id=t, phase="error",
                     reason="no")
                return 0
        """)

    def test_catches_raise_at_loop_top_after_open(self):
        # iteration 2 can hit the raise with iteration 1's machine
        # open — the two-pass back-edge approximation sees it
        hits = self._run("""
            from paddle_tpu.obs.events import emit
            def f(reqs):
                for t in reqs:
                    if stale(t):
                        raise RuntimeError(t)
                    emit("serving", "hop", trace_id=t, phase="start")
                    work()
        """)
        assert len(hits) == 1

    def test_handoff_option_closes_machine(self):
        opts = {"handoffs": ["enqueue_settle"]}
        assert not self._run("""
            from paddle_tpu.obs.events import emit
            def f(t):
                emit("serving", "hop", trace_id=t, phase="start")
                enqueue_settle(t)
                return 1
        """, options=opts)

    def test_non_protocol_emit_ignored(self):
        assert not self._run("""
            from paddle_tpu.obs.events import emit
            def f(t):
                emit("serving", "hop", trace_id=t, phase="settle")
                return 1
        """)

    def test_suppression_and_baseline_funnel(self, tmp_path):
        """R13 findings ride the standard funnel: inline disable with
        a reason, and a baselined finding with a `why`."""
        import json as _json
        (tmp_path / "pkg").mkdir()
        src = textwrap.dedent("""
            from paddle_tpu.obs.events import emit
            def f(t):
                emit("serving", "hop", trace_id=t, phase="start")  # ptlint: disable=R13(handoff: settled by the engine callback)
                return 1
        """)
        (tmp_path / "pkg" / "a.py").write_text(src)
        cfg = LintConfig(root=str(tmp_path), paths=["pkg"],
                         rules=["R13"], baseline="",
                         rule_options={"R13": {"paths": ["pkg"]}})
        res = lint_paths(cfg, use_baseline=False)
        assert not res.new and len(res.suppressed) == 1
        # same finding, no suppression -> baseline it
        (tmp_path / "pkg" / "a.py").write_text(
            src.replace("  # ptlint: disable=R13(handoff: settled "
                        "by the engine callback)", ""))
        res2 = lint_paths(cfg, use_baseline=False)
        assert len(res2.new) == 1
        write_baseline(str(tmp_path / "baseline.json"), res2.new, [])
        raw = _json.loads((tmp_path / "baseline.json").read_text())
        for e in raw["entries"]:
            e["why"] = "legacy path, settled by the engine callback"
        (tmp_path / "baseline.json").write_text(_json.dumps(raw))
        cfg.baseline = "baseline.json"
        res3 = lint_paths(cfg, use_baseline=True)
        assert not res3.new and len(res3.baselined) == 1
