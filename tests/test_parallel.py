"""Parallelism tests on the virtual 8-device CPU mesh (the 'CPU build as
fake device' discipline — mirrors MultiGradientMachine multi-thread tests
and test_CompareTwoNets: sharded training must match single-device)."""

import numpy as np
import pytest
import jax
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu.parallel import create_mesh, DP_AXIS, MP_AXIS
from paddle_tpu.parallel import tensor_parallel as tp


def _net(seed=0):
    img = paddle.layer.data("x", paddle.data_type.dense_vector(32))
    h = paddle.layer.fc(img, size=64, act=paddle.activation.Relu(),
                        name="h")
    out = paddle.layer.fc(h, size=8, act=paddle.activation.Softmax(),
                          name="out")
    lbl = paddle.layer.data("y", paddle.data_type.integer_value(8))
    cost = paddle.layer.classification_cost(out, lbl, name="cost")
    return cost


def _reader(n=64, dim=32, k=8, seed=3):
    rng = np.random.RandomState(seed)
    feats = rng.randn(n, dim).astype("float32")
    labels = rng.randint(0, k, n)

    def reader():
        yield [(feats[i], int(labels[i])) for i in range(n)]
    return reader


def _run(mesh, passes=3, trainer_count=1):
    from paddle_tpu.core import registry
    registry.reset_name_counters()
    paddle.init(use_tpu=False, seed=0, trainer_count=trainer_count)
    cost = _net()
    params = paddle.create_parameters(paddle.Topology(cost))
    tr = paddle.SGD(cost=cost, parameters=params,
                    update_equation=paddle.optimizer.Momentum(
                        learning_rate=0.1, momentum=0.9),
                    mesh=mesh)
    costs = []
    tr.train(_reader(), num_passes=passes,
             event_handler=lambda e: costs.append(e.cost)
             if isinstance(e, paddle.event.EndIteration) else None)
    return costs


class TestDataParallel:
    def test_dp_matches_single_device(self):
        single = _run(None)
        mesh = create_mesh([(DP_AXIS, 8)])
        dp = _run(mesh)
        np.testing.assert_allclose(single, dp, rtol=2e-4, atol=2e-5)

    def test_dp_mp_matches_single_device(self):
        single = _run(None)
        mesh = create_mesh([(DP_AXIS, 4), (MP_AXIS, 2)])
        both = _run(mesh)
        np.testing.assert_allclose(single, both, rtol=2e-4, atol=2e-5)


class TestShardingRules:
    def test_embedding_rows_sharded_fc_cols_sharded(self):
        mesh = create_mesh([(DP_AXIS, 4), (MP_AXIS, 2)])
        from jax.sharding import PartitionSpec as P
        assert tp.spec_for("_emb0.w0", (100, 64), mesh) == P(MP_AXIS, None)
        assert tp.spec_for("_fc1.w0", (64, 64), mesh) == P(None, MP_AXIS)
        assert tp.spec_for("_fc1.wbias", (64,), mesh) == P()
        # non-divisible dims fall back to replication
        assert tp.spec_for("_fc2.w0", (64, 63), mesh) == P()

    def test_param_placement(self):
        mesh = create_mesh([(DP_AXIS, 4), (MP_AXIS, 2)])
        from paddle_tpu.core import registry
        registry.reset_name_counters()
        cost = _net()
        topo = paddle.Topology(cost)
        shardings = tp.param_shardings(topo.param_specs, mesh)
        params = tp.shard_params(topo.init_params(), mesh, shardings)
        w = params["_h.w0"]   # (32, 64) -> cols over mp
        assert w.sharding.spec == shardings["_h.w0"].spec
        assert len(w.devices()) == 8


class TestGraftEntry:
    def test_dryrun_multichip(self):
        import sys
        sys.path.insert(0, "/root/repo")
        import __graft_entry__ as g
        g.dryrun_multichip(8)


class TestTrainerCountMesh:
    def test_trainer_count_builds_dp_mesh(self):
        """paddle.init(trainer_count=4) + plain SGD must shard over 4
        devices with no explicit mesh= (GradientMachine.cpp:29 —
        trainer_count>1 transparently selected MultiGradientMachine)."""
        from paddle_tpu.core import registry
        registry.reset_name_counters()
        paddle.init(use_tpu=False, seed=0, trainer_count=4)
        try:
            cost = _net()
            params = paddle.create_parameters(paddle.Topology(cost))
            tr = paddle.SGD(cost=cost, parameters=params,
                            update_equation=paddle.optimizer.Momentum(
                                learning_rate=0.1, momentum=0.9))
            assert tr.mesh is not None
            assert dict(tr.mesh.shape)[DP_AXIS] == 4
            costs = []
            tr.train(_reader(), num_passes=2,
                     event_handler=lambda e: costs.append(e.cost)
                     if isinstance(e, paddle.event.EndIteration) else None)
            assert costs and np.isfinite(costs).all()
        finally:
            paddle.init(use_tpu=False, seed=0, trainer_count=1)

    def test_trainer_count_numerics_match_explicit_mesh(self):
        explicit = _run(create_mesh([(DP_AXIS, 4)]))
        try:
            implicit = _run(None, trainer_count=4)
        finally:
            paddle.init(use_tpu=False, seed=0, trainer_count=1)
        np.testing.assert_allclose(implicit, explicit, rtol=1e-5)


class TestThreeAxisMesh:
    def test_dp_mp_sp_transformer_matches_single_device(self):
        """Composability: tensor-parallel fc columns + ring attention over
        sp + data parallelism in ONE mesh (dp2 x mp2 x sp2 = 8 devices)
        must reproduce single-device numerics exactly."""
        from paddle_tpu import models
        from paddle_tpu.core import registry

        def run(mesh):
            paddle.init(use_tpu=False, seed=0)
            registry.reset_name_counters()
            spec = models.transformer_lm(vocab_size=64, d_model=32,
                                         n_heads=4, n_layers=2, d_ff=64,
                                         max_len=32)
            params = paddle.create_parameters(
                paddle.Topology(spec.cost, extra_outputs=[spec.output]))
            tr = paddle.SGD(cost=spec.cost, parameters=params,
                            extra_layers=[spec.output],
                            update_equation=paddle.optimizer.Adam(
                                learning_rate=1e-3),
                            mesh=mesh)
            rng = np.random.RandomState(0)
            b, T = 4, 16
            ids = rng.randint(0, 64, (b, T + 1)).astype("int32")
            batch = [(ids[i, :T], np.arange(T, dtype="int32"), ids[i, 1:])
                     for i in range(b)]
            return [float(tr.train_batch(batch)[0]) for _ in range(3)]

        single = run(None)
        meshed = run(create_mesh([("dp", 2), ("mp", 2), ("sp", 2)]))
        np.testing.assert_allclose(single, meshed, rtol=2e-4)


class TestIslandReconcileGuard:
    """AsyncSGDIsland.reconcile under a poisoned island: the isfinite
    guard (the PR 1 discipline applied to reconcile) must drop the
    NaN/Inf island's tree from the average — counted in utils/stats —
    and heal the poisoned island with the healthy average instead of
    letting one bad island contaminate every peer."""

    def _island(self, seed=0):
        from paddle_tpu.core import registry
        registry.reset_name_counters()
        paddle.init(use_tpu=False, seed=seed)
        cost = _net()
        params = paddle.create_parameters(paddle.Topology(cost))
        tr = paddle.SGD(cost=cost, parameters=params,
                        update_equation=paddle.optimizer.Momentum(
                            learning_rate=0.1))
        return tr

    def test_poisoned_island_dropped_and_healed(self):
        from paddle_tpu.parallel.async_sgd import AsyncSGDIsland
        from paddle_tpu.utils.stats import global_counters

        t1, t2, t3 = (self._island(s) for s in (0, 1, 2))
        healthy = {k: np.asarray(v)
                   for k, v in t2.parameters.raw.items()}
        healthy3 = {k: np.asarray(v)
                    for k, v in t3.parameters.raw.items()}
        # island 1 went NaN (a poisoned batch that slipped the guard)
        k0 = sorted(t1.parameters.raw)[0]
        bad = dict(t1.parameters.raw)
        bad[k0] = jnp.full_like(bad[k0], jnp.nan)
        t1.parameters.replace(bad)

        island = AsyncSGDIsland(
            t1, sync_period=1,
            sync_group=[t1.parameters, t2.parameters, t3.parameters])
        before = global_counters.value("parallel/poisoned_islands")
        with pytest.warns(UserWarning, match="non-finite"):
            island.reconcile()
        assert global_counters.value(
            "parallel/poisoned_islands") == before + 1

        expect = {k: (healthy[k] + healthy3[k]) / 2.0 for k in healthy}
        for tr in (t1, t2, t3):
            for k in expect:
                got = np.asarray(tr.parameters.raw[k])
                assert np.isfinite(got).all()
                np.testing.assert_allclose(got, expect[k], rtol=1e-6,
                                           atol=1e-7)

    def test_all_poisoned_skips_reconcile(self):
        from paddle_tpu.parallel.async_sgd import AsyncSGDIsland

        t1, t2 = (self._island(s) for s in (0, 1))
        for tr in (t1, t2):
            bad = {k: jnp.full_like(v, jnp.inf)
                   for k, v in tr.parameters.raw.items()}
            tr.parameters.replace(bad)
        island = AsyncSGDIsland(t1, sync_period=1,
                                sync_group=[t1.parameters, t2.parameters])
        with pytest.warns(UserWarning, match="every island"):
            island.reconcile()          # no crash, params untouched
        assert not np.isfinite(
            np.asarray(t1.parameters.raw[sorted(t1.parameters.raw)[0]])
        ).any()

    def test_healthy_islands_unchanged_semantics(self):
        # no poison: reconcile is the plain average (regression guard
        # for the guarded path)
        from paddle_tpu.parallel.async_sgd import AsyncSGDIsland

        t1, t2 = (self._island(s) for s in (0, 1))
        raws = [{k: np.asarray(v) for k, v in t.parameters.raw.items()}
                for t in (t1, t2)]
        island = AsyncSGDIsland(t1, sync_period=1,
                                sync_group=[t1.parameters, t2.parameters])
        island.reconcile()
        for k in raws[0]:
            expect = (raws[0][k] + raws[1][k]) / 2.0
            np.testing.assert_allclose(np.asarray(t1.parameters.raw[k]),
                                       expect, rtol=1e-6, atol=1e-7)
