"""Elastic coordinator v2 chaos acceptance (docs/robustness.md
"Elastic training"): scale-out/in mid-job with deterministic reshard
and exactly-once data accounting.

Invariants under test:
  * join/leave/lease-expiry bump a monotonic GENERATION and reshard the
    todo queue into canonical (epoch, task_id) order;
  * completions carrying a superseded grant are REJECTED (stale_grants)
    while a live worker's pre-reshape grant is accepted exactly once;
  * task_release hands a reader position to the next holder, so no
    record is read twice or dropped across a reshape;
  * a joining replacement adopts the fleet's published MemoryPlan
    (provenance="adopted") instead of re-probing/re-OOMing;
  * killing one worker AND adding another mid-pass still yields
    exactly-once per-record accounting and (where the schedule permits)
    a digest-identical loss trajectory versus fixed membership.
"""

import collections
import os
import signal
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.obs.events import tail
from paddle_tpu.obs.metrics import REGISTRY
from paddle_tpu.testing.faults import FaultPlan
from paddle_tpu.trainer.checkpoint import CheckpointManager
from paddle_tpu.trainer.coordinator import (Coordinator, CoordinatorServer,
                                            FileStore, KVStoreServer,
                                            RpcStore, connect, task_reader)

RECORDS_PER_CHUNK = 4


def _small_trainer(seed=0):
    from paddle_tpu.core import registry
    registry.reset_name_counters()
    paddle.init(use_tpu=False, seed=seed)
    x = paddle.layer.data("x", paddle.data_type.dense_vector(16))
    out = paddle.layer.fc(x, size=4, act=paddle.activation.Softmax(),
                          name="out")
    y = paddle.layer.data("y", paddle.data_type.integer_value(4))
    cost = paddle.layer.classification_cost(out, y, name="cost")
    params = paddle.create_parameters(paddle.Topology(cost))
    return paddle.SGD(cost=cost, parameters=params,
                      update_equation=paddle.optimizer.Adam(
                          learning_rate=1e-2))


def _digest_chunks(chunk):
    r = np.random.RandomState(1000 + int(chunk))
    return [(r.randn(16).astype("float32"), int(r.randint(4)))
            for _ in range(RECORDS_PER_CHUNK)]


class TestMembershipProtocol:
    """join/leave/worker_heartbeat lease protocol + generation/reshard
    determinism (the unit half of the chaos acceptance)."""

    def test_join_bumps_generation_and_returns_roster(self):
        c = Coordinator(list(range(4)), chunks_per_task=1)
        r1 = c.join("w1", info={"host": "a"})
        assert r1["generation"] == 1 and r1["epoch"] == 0
        assert r1["workers"] == ["w1"]
        assert r1["memory_plan"] is None
        r2 = c.join("w2")
        assert r2["generation"] == 2
        assert r2["workers"] == ["w1", "w2"]
        # re-join of a live member renews the lease WITHOUT a reshape
        r3 = c.join("w1")
        assert r3["generation"] == 2
        assert c.workers() == ["w1", "w2"]

    def test_worker_heartbeat_renews_and_unknown_must_rejoin(self):
        c = Coordinator([1], chunks_per_task=1)
        assert c.worker_heartbeat("ghost") == -1
        c.join("w1")
        assert c.worker_heartbeat("w1") == c.generation

    def test_leave_requeues_without_penalty_in_canonical_order(self):
        c = Coordinator(list(range(6)), chunks_per_task=1)
        c.join("a")
        c.join("b")
        for _ in range(2):
            assert c.get_task(0, "a") is not None     # tasks 0, 1
        gb = c.get_task(0, "b")                       # task 2
        gen_before = c.generation
        assert c.leave("a") is True
        assert c.generation == gen_before + 1
        # a's tasks re-queued ahead, canonical (epoch, task_id) order,
        # and WITHOUT a failure penalty (it didn't fail — it shrank)
        assert [t.task_id for t in c._todo] == [0, 1, 3, 4, 5]
        assert all(t.num_failures == 0 for t in c._todo)
        order = []
        while True:
            t = c.get_task(0, "b")
            if t is None:
                break
            order.append(t["task_id"])
            assert c.task_finished(t["task_id"], t["generation"])
        assert order == [0, 1, 3, 4, 5]
        assert c.task_finished(gb["task_id"], gb["generation"])
        assert c.epoch == 1
        assert c.leave("a") is False                  # already gone

    def test_lease_expiry_is_an_implicit_leave(self):
        c = Coordinator([1, 2], chunks_per_task=1, timeout_s=30.0,
                        worker_lease_s=0.05)
        c.join("w1")
        g = c.get_task(0, "w1")
        assert g is not None
        time.sleep(0.08)
        gen_before = c.generation
        assert c.workers() == []                      # sweep expired w1
        assert c.generation == gen_before + 1
        # the dead worker's task went back to todo (with a penalty)
        assert g["task_id"] in [t.task_id for t in c._todo]
        assert c.worker_heartbeat("w1") == -1         # must re-join

    def test_stale_grant_rejected_after_requeue(self):
        c = Coordinator([7], chunks_per_task=1, timeout_s=30.0,
                        worker_lease_s=0.05)
        c.join("victim")
        g1 = c.get_task(0, "victim")
        time.sleep(0.08)
        c.join("spare")            # sweeps the victim, requeues its task
        g2 = c.get_task(0, "spare")
        assert g2["task_id"] == g1["task_id"]
        assert g2["generation"] > g1["generation"]
        # the zombie's completion carries the superseded stamp: refused
        assert c.task_finished(g1["task_id"], g1["generation"]) is False
        assert c.num_stale_grants() == 1
        assert [r for r in tail(50, domain="coordinator",
                                kind="stale_grant")]
        # the live holder's completion lands exactly once
        assert c.task_finished(g2["task_id"], g2["generation"]) is True
        assert c.epoch == 1

    def test_live_workers_pre_reshape_grant_still_accepted(self):
        # a join must NOT invalidate in-flight grants of live members —
        # or their records would be re-served and read twice
        c = Coordinator([1, 2], chunks_per_task=1)
        c.join("w1")
        g = c.get_task(0, "w1")
        c.join("w2")
        assert c.generation > g["generation"]
        assert c.task_finished(g["task_id"], g["generation"]) is True
        assert c.num_stale_grants() == 0

    def test_task_release_hands_position_to_next_holder(self):
        c = Coordinator([5], chunks_per_task=1)
        c.join("w1")
        g = c.get_task(0, "w1")
        assert c.task_release(g["task_id"], g["generation"],
                              {"records_consumed": 2}) is True
        g2 = c.get_task(0, "w1")
        assert g2["task_id"] == g["task_id"]
        assert g2["resume_state"] == {"records_consumed": 2}
        # the position was consumed by that grant, not left behind
        assert c.task_release(g2["task_id"], g2["generation"]) is True
        g3 = c.get_task(0, "w1")
        assert g3["resume_state"] is None

    def test_task_reader_skips_released_prefix(self):
        c = Coordinator(["c0"], chunks_per_task=1)
        c.join("w1")
        g = c.get_task(0, "w1")
        c.task_release(g["task_id"], g["generation"],
                       {"records_consumed": 2})
        c.join("w2")
        recs = list(task_reader(
            c, lambda ch: [(ch, i) for i in range(RECORDS_PER_CHUNK)],
            worker_id="w2")())
        assert recs == [("c0", 2), ("c0", 3)]         # exactly-once
        assert c.epoch == 1

    def test_membership_script_fires_at_exact_grants(self):
        c = Coordinator(list(range(4)), chunks_per_task=1)
        c.join("w1")
        with FaultPlan.membership_script(
                c, {1: lambda: c.join("mid-join")}) as st:
            while True:
                t = c.get_task(0, "w1")
                if t is None:
                    break
                assert c.task_finished(t["task_id"], t["generation"])
        assert st["fired"] == [1]
        assert "mid-join" in c.workers()
        assert c.epoch == 1                 # schedule unperturbed
        assert c.num_stale_grants() == 0    # live grants all honored


@pytest.mark.chaos(timeout=90)
class TestExactlyOnceChaos:
    """The tentpole acceptance: kill one worker AND add one mid-pass;
    every record of the pass is accounted exactly once, and no live
    worker's completion is ever refused."""

    def test_kill_and_join_mid_pass_exactly_once(self):
        coord = Coordinator(list(range(6)), chunks_per_task=1,
                            timeout_s=0.5, failure_max=10,
                            worker_lease_s=0.5)
        accepted = collections.Counter()
        lock = threading.Lock()
        deadline = time.time() + 30.0

        def worker(wid, die_after=None):
            coord.join(wid)
            my_grants = 0
            while time.time() < deadline:
                t = coord.get_task(0, wid)
                if t is None:
                    if coord.epoch != 0:
                        break
                    time.sleep(0.02)
                    continue
                my_grants += 1
                skip = int((t.get("resume_state") or {})
                           .get("records_consumed", 0))
                recs = [(c, i) for c in t["chunks"]
                        for i in range(RECORDS_PER_CHUNK)][skip:]
                if die_after is not None and my_grants >= die_after:
                    return        # SIGKILL twin: vanish holding a lease
                if coord.task_finished(t["task_id"], t["generation"]):
                    with lock:
                        accepted.update(recs)
            coord.leave(wid)

        joiners = []

        def scale_out():
            th = threading.Thread(target=worker, args=("w3",),
                                  daemon=True, name="pt-test-w3")
            joiners.append(th)
            th.start()

        with FaultPlan.membership_script(coord, {3: scale_out}) as st:
            threads = [
                threading.Thread(target=worker, args=("w1", 2),
                                 daemon=True,
                                 name="pt-test-w1"),    # dies on grant 2
                threading.Thread(target=worker, args=("w2",),
                                 daemon=True, name="pt-test-w2"),
            ]
            for th in threads:
                th.start()
            for th in threads + joiners:
                th.join(35.0)
        assert st["fired"] == [3]           # the join landed on schedule
        assert coord.epoch == 1, "pass never completed under churn"
        expected = collections.Counter(
            {(c, i): 1 for c in range(6)
             for i in range(RECORDS_PER_CHUNK)})
        assert accepted == expected         # exactly-once, every record
        # no live worker's own completion was ever refused
        assert coord.num_stale_grants() == 0
        assert coord.workers() == []        # survivors left, victim swept
        assert coord.generation >= 4        # 3 joins + expiry + leaves


@pytest.mark.chaos(timeout=150)
class TestDigestIdenticalTrajectory:
    """Where the dispatch schedule permits (scale-in at a pass boundary,
    replacement restores the checkpoint), the elastic run's loss
    trajectory is DIGEST-IDENTICAL to a fixed-membership run — the
    reshape moved work, not math."""

    def _run(self, coord, mgr, worker_id, num_passes, losses):
        tr = _small_trainer(seed=0)

        def on_ev(e):
            if isinstance(e, paddle.event.EndIteration):
                losses.append(float(e.cost))

        tr.train(coordinator=coord, chunk_reader=_digest_chunks,
                 batch_size=4, num_passes=num_passes,
                 checkpoint_manager=mgr, event_handler=on_ev,
                 worker_id=worker_id)

    def test_leave_join_at_pass_boundary_is_digest_identical(
            self, tmp_path):
        fixed, elastic = [], []
        coord_a = Coordinator(list(range(4)), chunks_per_task=1)
        self._run(coord_a, CheckpointManager(str(tmp_path / "fixed")),
                  "solo", 2, fixed)
        coord_b = Coordinator(list(range(4)), chunks_per_task=1)
        ck = str(tmp_path / "elastic")
        # w1 trains pass 0, checkpoints, and leaves (scale-in)...
        self._run(coord_b, CheckpointManager(ck), "w1", 1, elastic)
        assert len(elastic) == len(fixed) // 2
        # ...a FRESH trainer joins, restores, and finishes pass 1
        self._run(coord_b, CheckpointManager(ck), "w2", 2, elastic)
        assert len(elastic) == len(fixed)
        np.testing.assert_array_equal(np.asarray(elastic),
                                      np.asarray(fixed))
        assert coord_b.generation >= 2
        leaves = {r.get("worker_id")
                  for r in tail(100, domain="coordinator", kind="leave")}
        assert {"w1", "w2"} <= leaves


class TestMemoryPlanAdoption:
    """A replacement host adopts the fleet's published MemoryPlan from
    its join() response (provenance="adopted") — no re-probe, no
    re-discovered OOM."""

    def test_join_adopts_published_plan_without_probe(self):
        c = Coordinator(list(range(4)), chunks_per_task=1)
        assert c.put_memory_plan({"microbatch": 2, "accum_steps": 2,
                                  "provenance": "adapted"}) is True
        tr = _small_trainer(seed=0)
        tr.train(coordinator=c, chunk_reader=_digest_chunks,
                 batch_size=4, num_passes=1, worker_id="replacement",
                 microbatch="auto", oom_probe=True)
        plan = tr._memory_exec.plan
        # adopted verbatim; a probe would have stamped "probe"
        assert plan.provenance == "adopted"
        assert plan.microbatch == 2 and plan.accum_steps == 2
        kinds = [r["kind"] for r in tail(300, domain="trainer")]
        assert "plan_adopted" in kinds
        assert "oom" not in kinds           # zero induced OOMs

    def test_worker_publishes_its_plan_for_the_next_joiner(self):
        c = Coordinator(list(range(4)), chunks_per_task=1)
        tr = _small_trainer(seed=0)
        tr.train(coordinator=c, chunk_reader=_digest_chunks,
                 batch_size=4, num_passes=1, worker_id="w1",
                 microbatch=2)
        assert (c.memory_plan or {}).get("microbatch") == 2
        assert c.memory_plan["provenance"] == "configured"
        # and the NEXT joiner receives it in its join() response
        assert c.join("w2")["memory_plan"]["microbatch"] == 2


@pytest.mark.chaos(timeout=180)
class TestSigkillPlusJoin:
    """Subprocess acceptance: SIGKILL one elastic worker mid-pass, join
    a replacement, the job completes; the victim's membership lapses by
    lease (journaled) and its task is re-served."""

    def test_sigkill_then_join_completes(self, tmp_path):
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        worker = os.path.join(repo, "tests", "elastic_worker.py")
        ckpt = str(tmp_path / "ckpt")
        coord = Coordinator(list(range(6)), chunks_per_task=1,
                            timeout_s=1.5, failure_max=10)
        srv = CoordinatorServer(coord).start()
        env = dict(os.environ)
        env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
        env["JAX_PLATFORMS"] = "cpu"
        try:
            p1 = subprocess.Popen(
                [sys.executable, worker, str(srv.port), ckpt, "0.25",
                 "w1"],
                env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE)
            deadline = time.time() + 60
            while coord.epoch == 0 and not coord._done and \
                    time.time() < deadline:
                time.sleep(0.1)
            assert time.time() < deadline, "worker never started tasks"
            p1.send_signal(signal.SIGKILL)
            p1.communicate(timeout=30)
            p2 = subprocess.Popen(
                [sys.executable, worker, str(srv.port), ckpt, "0",
                 "w2"],
                env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE)
            out, err = p2.communicate(timeout=120)
            assert p2.returncode == 0, err.decode()
            assert b"WORKER DONE" in out
            assert coord.epoch >= 2          # both passes completed
            joined = {r.get("worker_id")
                      for r in tail(200, domain="coordinator",
                                    kind="join")}
            assert {"w1", "w2"} <= joined
            expired = {r.get("worker_id")
                       for r in tail(200, domain="coordinator",
                                     kind="lease_expired")}
            assert "w1" in expired           # the SIGKILL became a leave
            assert coord.num_stale_grants() == 0
            assert coord.workers() == []     # w2 left gracefully
        finally:
            srv.stop()


class TestThreadingServer:
    """Satellite: the RPC server is concurrent — one slow/blocked RPC
    must not starve heartbeats and expire a healthy worker's lease."""

    def test_blocked_rpc_does_not_expire_healthy_lease(self):
        coord = Coordinator([0, 1], chunks_per_task=1, timeout_s=1.0)
        srv = CoordinatorServer(coord).start()
        entered = threading.Event()
        release = threading.Event()

        def slow():
            entered.set()
            release.wait(15.0)
            return True

        srv.server.register_function(slow, "slow")
        try:
            c1 = connect("127.0.0.1", srv.port)
            t = c1.get_task()
            blocker = threading.Thread(
                target=lambda: connect("127.0.0.1", srv.port).slow(),
                daemon=True, name="pt-test-blocker")
            blocker.start()
            assert entered.wait(10.0), "slow RPC never reached the server"
            # heartbeat through MORE than one lease while slow() blocks
            c2 = connect("127.0.0.1", srv.port)
            until = time.time() + 1.6
            while time.time() < until:
                assert c2.heartbeat(t["task_id"]) is True
                time.sleep(0.2)
            names = [th.name for th in threading.enumerate()]
            assert any(n.startswith("pt-coord-rpc-") for n in names)
            release.set()
            blocker.join(15.0)
            # the lease survived: the task is still ours to finish
            assert c2.task_finished(t["task_id"],
                                    t["generation"]) is True
        finally:
            release.set()
            srv.stop()

    def test_membership_rpc_surface(self):
        coord = Coordinator([1, 2], chunks_per_task=1)
        srv = CoordinatorServer(coord).start()
        try:
            c = connect("127.0.0.1", srv.port)
            resp = c.join("rpc-w")
            assert resp["generation"] == 1
            assert c.worker_heartbeat("rpc-w") == 1
            assert c.generation() == 1
            assert c.workers() == ["rpc-w"]
            assert c.stats()["workers"] == 1
            assert c.num_stale_grants() == 0
            g = c.get_task(0, "rpc-w")
            assert c.task_release(g["task_id"], g["generation"],
                                  {"records_consumed": 1}) is True
            assert c.get_task(0, "rpc-w")["resume_state"] == \
                {"records_consumed": 1}
            assert c.leave("rpc-w") is True
        finally:
            srv.stop()


class TestRpcStore:
    """Snapshot durability WITHOUT a shared filesystem: the KVStore
    interface served over RPC, binary-safe, recoverable."""

    def test_binary_roundtrip_and_missing_key(self):
        kv = KVStoreServer().start()
        try:
            store = RpcStore("127.0.0.1", kv.port)
            store.put("k", b"\x00\xff raw \x01 bytes")
            assert store.get("k") == b"\x00\xff raw \x01 bytes"
            assert store.get("missing") is None
        finally:
            kv.stop()

    def test_coordinator_recovers_through_rpc_store(self):
        kv = KVStoreServer().start()
        try:
            c1 = Coordinator(list(range(4)), chunks_per_task=1,
                             store=RpcStore("127.0.0.1", kv.port))
            c1.join("w1")
            g = c1.get_task(0, "w1")
            assert g is not None
            c2 = Coordinator([], store=RpcStore("127.0.0.1", kv.port))
            assert c2.recovered
            assert c2.chunks == (0, 1, 2, 3)
            assert c2.generation == c1.generation
            # membership leases are deliberately NOT persisted: a fleet
            # re-joins a recovered master
            assert c2.workers() == []
        finally:
            kv.stop()


class TestStoreCoverage:
    """Satellite: FileStore degradation paths and dropped-task
    accounting across snapshot/recover."""

    def test_filestore_oserror_treated_as_absent(self, tmp_path):
        store = FileStore(str(tmp_path))
        os.makedirs(store._path("k"))       # open() -> IsADirectoryError
        with pytest.warns(UserWarning, match="could not read"):
            assert store.get("k") is None

    def test_legacy_unframed_snapshot_recovers(self, tmp_path):
        store = FileStore(str(tmp_path))
        c1 = Coordinator(list(range(3)), chunks_per_task=1, store=store)
        c1.join("w1")
        path = store._path("coordinator/state")
        with open(path, "rb") as f:
            blob = f.read()
        assert blob.startswith(FileStore._MAGIC)
        payload = blob[len(FileStore._MAGIC) + 12:]
        with open(path, "wb") as f:         # an older writer's raw JSON
            f.write(payload)
        c2 = Coordinator([], store=FileStore(str(tmp_path)))
        assert c2.recovered
        assert c2.chunks == (0, 1, 2)
        assert c2.generation == c1.generation

    def test_num_dropped_survives_snapshot_recover(self, tmp_path):
        store = FileStore(str(tmp_path))
        c1 = Coordinator([1, 2], chunks_per_task=1, failure_max=1,
                         store=store)
        t = c1.get_task()
        assert c1.task_failed(t["task_id"]) is True   # dropped outright
        assert c1.num_dropped() == 1
        assert c1.epoch == 0                # todo not drained: no turn
        c2 = Coordinator([], store=store)
        assert c2.recovered
        assert c2.num_dropped() == 1
        assert c2.get_task(0) is not None   # the healthy task re-serves


class TestObservability:
    """Satellite: every membership transition journals, the /metrics
    registry exposes paddle_tpu_coord_* gauges, and a lease-expiry
    storm auto-dumps a flight-recorder bundle."""

    def test_journal_events_and_gauges(self):
        c = Coordinator(list(range(4)), chunks_per_task=1,
                        timeout_s=30.0, worker_lease_s=0.05)
        c.join("w1")
        c.join("w2")
        assert c.get_task(0, "w1") is not None
        c.leave("w2")
        time.sleep(0.08)
        assert c.worker_heartbeat("w1") == -1         # swept: expired
        kinds = {r["kind"] for r in tail(300, domain="coordinator")}
        assert {"join", "leave", "lease_expired", "reshard",
                "generation"} <= kinds
        rec = tail(1, domain="coordinator")[0]
        assert "run_id" in rec and "host" in rec      # correlated
        text = REGISTRY.exposition()
        for gauge in ("paddle_tpu_coord_workers",
                      "paddle_tpu_coord_generation",
                      "paddle_tpu_coord_stale_grants",
                      "paddle_tpu_coord_tasks_todo"):
            assert gauge in text, f"missing {gauge} in exposition"

    def test_lease_expiry_storm_dumps_flight_bundle(self, tmp_path):
        from paddle_tpu.obs.flight import FLIGHT
        FLIGHT.configure(dump_dir=str(tmp_path), min_dump_interval=0.0)
        c = Coordinator([1, 2], chunks_per_task=1, timeout_s=30.0,
                        worker_lease_s=0.03)
        c.join("a")
        c.join("b")
        time.sleep(0.06)
        c.workers()                  # one sweep expires both: a storm
        deadline = time.time() + 10.0     # dump runs off-thread
        bundles = []
        while not bundles and time.time() < deadline:
            bundles = [p for p in os.listdir(tmp_path)
                       if "coord-lease-expiry-storm" in p]
            time.sleep(0.05)
        assert bundles, "lease-expiry storm did not auto-dump a bundle"
