"""Canonical model configs for the golden-topology regression corpus.

Reference: python/paddle/trainer_config_helpers/tests/configs/ — each
config file is parsed and its protostr committed
(configs/protostr/*.protostr); CI diffs freshly-generated output against
the golden copy so any silent DSL/shape-inference drift fails loudly.
Here the serialized JSON topology (core/topology.py serialize) plays the
protostr role.

Every builder returns the FINAL output node of a small canonical network.
Keep builders deterministic: fixed names, no randomness.
"""

import numpy as np

import paddle_tpu as paddle

L = paddle.layer
A = paddle.activation
P = paddle.pooling
D = paddle.data_type


def simple_fc():
    x = L.data("x", D.dense_vector(100))
    h = L.fc(x, size=64, act=A.Tanh(), name="hidden")
    out = L.fc(h, size=10, act=A.Softmax(), name="output")
    lbl = L.data("label", D.integer_value(10))
    return L.classification_cost(out, lbl, name="cost")


def img_layers():
    im = L.data("image", D.dense_vector(3 * 16 * 16), height=16, width=16)
    c = L.img_conv(im, filter_size=3, num_filters=8, padding=1,
                   act=A.Relu(), name="conv1")
    bn = L.batch_norm(c, act=A.Relu(), name="bn1")
    p = L.img_pool(bn, pool_size=2, stride=2, name="pool1")
    n = L.img_cmrnorm(p, size=5, name="norm1")
    return L.fc(n, size=10, act=A.Softmax(), name="output")


def img_trans_layers():
    im = L.data("image", D.dense_vector(2 * 8 * 8), height=8, width=8)
    c = L.img_conv(im, filter_size=3, num_filters=4, padding=1, name="convt",
                   trans=True)
    padded = L.pad(c, pad_c=[0, 1], pad_h=[1, 1], pad_w=[1, 1], name="pad1")
    cropped = L.crop(padded, shape=[4, 8, 8], offset=[0, 1, 1], name="crop1")
    r = L.rotate(cropped, name="rot1")
    return L.bilinear_interp(r, out_size_x=16, out_size_y=16, name="bi1")


def util_layers():
    a = L.data("a", D.dense_vector(10))
    b = L.data("b", D.dense_vector(10))
    add = L.addto([a, b], act=A.Relu(), bias_attr=False, name="add")
    cat = L.concat([a, b], name="cat")
    dm = L.dotmul(a, b, name="dm")
    w = L.data("w", D.dense_vector(1))
    interp = L.interpolation([a, b], w, name="interp")
    cs = L.cos_sim(a, b, name="cs")
    si = L.slope_intercept(add, slope=2.0, intercept=1.0, name="si")
    return L.concat([cat, dm, interp, cs, si], name="all")


def projections():
    x = L.data("x", D.dense_vector(20))
    ids = L.data("ids", D.integer_value(100))
    m = L.mixed(input=[
        L.full_matrix_projection(x, size=16),
        L.table_projection(ids, size=16),
        L.trans_full_matrix_projection(
            L.fc(x, size=16, name="pre"), size=16),
    ], act=A.Tanh(), name="mix")
    s = L.scaling_projection(m)
    d = L.dotmul_projection(s)
    return L.slice_projection(d, 2, 10)


def seq_ops_suite():
    s = L.data("s", D.dense_vector_sequence(8))
    pooled = L.pooling(s, pooling_type=P.Max(), name="pmax")
    last = L.last_seq(s, name="last")
    first = L.first_seq(s, name="first")
    ex = L.expand(last, s, name="ex")
    cat = L.seq_concat(s, ex, name="scat")
    rs = L.seq_reshape(s, reshape_size=4, name="rs")
    rev = L.seq_reverse(s, name="rev")
    p2 = L.pooling(rev, pooling_type=P.Avg(), name="pavg")
    return L.concat([pooled, last, first, p2,
                     L.last_seq(cat), L.last_seq(rs)], name="out")


def simple_rnn():
    ids = L.data("word", D.integer_value_sequence(1000))
    emb = L.embedding(ids, size=32, name="emb")
    rnn = L.recurrent(L.fc(emb, size=32, name="proj"), name="rnn")
    return L.fc(L.last_seq(rnn), size=2, act=A.Softmax(), name="output")


def simple_lstm_net():
    ids = L.data("word", D.integer_value_sequence(1000))
    emb = L.embedding(ids, size=32, name="emb")
    lstm = L.lstmemory(L.fc(emb, size=128, name="proj"), name="lstm")
    return L.fc(L.pooling(lstm, pooling_type=P.Max()), size=2,
                act=A.Softmax(), name="output")


def bidirectional_gru():
    ids = L.data("word", D.integer_value_sequence(500))
    emb = L.embedding(ids, size=16, name="emb")
    fwd = L.grumemory(L.fc(emb, size=48, name="pf"), name="gru_fwd")
    bwd = L.grumemory(L.fc(emb, size=48, name="pb"), reverse=True,
                      name="gru_bwd")
    return L.fc(L.concat([L.last_seq(fwd), L.first_seq(bwd)]), size=4,
                act=A.Softmax(), name="output")


def rnn_group():
    s = L.data("s", D.dense_vector_sequence(16))

    def step(x):
        mem = L.memory(name="h", size=16)
        return L.fc([x, mem], size=16, act=A.Tanh(), name="h")

    g = L.recurrent_group(step=step, input=s, name="rg")
    return L.last_seq(g, name="out")


def nested_rnn_group():
    ns = L.data("ns", D.dense_vector_sub_sequence(8))

    def outer(sub):
        mem = L.memory(name="oh", size=8)
        pooled = L.pooling(sub, pooling_type=P.Avg())
        return L.fc([pooled, mem], size=8, act=A.Tanh(), name="oh")

    g = L.recurrent_group(step=outer, input=L.SubsequenceInput(ns),
                          name="nrg")
    return L.last_seq(g, name="out")


def attention_net():
    src = L.data("src", D.dense_vector_sequence(32))
    q = L.data("q", D.dense_vector_sequence(32))
    att = L.dot_product_attention(q, src, src, num_heads=4, name="att")
    return L.fc(L.last_seq(att), size=8, name="output")


def cost_suite():
    x = L.data("x", D.dense_vector(16))
    out4 = L.fc(x, size=4, act=A.Softmax(), name="p4")
    lbl = L.data("label", D.integer_value(4))
    dense_lbl = L.data("dl", D.dense_vector(4))
    c1 = L.cross_entropy_cost(out4, lbl, name="ce")
    c2 = L.square_error_cost(out4, dense_lbl, name="mse")
    c3 = L.huber_regression_cost(out4, dense_lbl, name="huber")
    c4 = L.smooth_l1_cost(out4, dense_lbl, name="sl1")
    c5 = L.multi_binary_label_cross_entropy_cost(
        L.fc(x, size=4, act=A.Sigmoid(), name="p4b"), dense_lbl, name="mbce")
    return L.addto([c1, c2, c3, c4, c5], name="total")


def rank_costs():
    a = L.data("a", D.dense_vector(8))
    b = L.data("b", D.dense_vector(8))
    sa = L.fc(a, size=1, name="sa")
    sb = L.fc(b, size=1, name="sb")
    lbl = L.data("label", D.dense_vector(1))
    return L.rank_cost(sa, sb, lbl, name="rank")


def crf_tagger():
    s = L.data("s", D.dense_vector_sequence(16))
    emit = L.fc(s, size=8, name="emission")
    lbl = L.data("label", D.integer_value_sequence(8))
    return L.crf(emit, lbl, size=8, name="crf_cost")


def ctc_net():
    s = L.data("s", D.dense_vector_sequence(16))
    probs = L.fc(s, size=10, act=A.Softmax(), name="probs")
    lbl = L.data("label", D.integer_value_sequence(10))
    return L.ctc(probs, lbl, size=10, name="ctc_cost")


def nce_hsigmoid():
    x = L.data("x", D.dense_vector(16))
    lbl = L.data("label", D.integer_value(32))
    n = L.nce(L.fc(x, size=8, name="h1"), lbl, num_classes=32,
              num_neg_samples=5, name="nce_cost")
    h = L.hsigmoid(L.fc(x, size=8, name="h2"), lbl, num_classes=32,
                   name="hs_cost")
    return L.addto([n, h], name="total")


def detection_net():
    feat = L.data("feat", D.dense_vector(8 * 4 * 4), height=4, width=4)
    img = L.data("img", D.dense_vector(3 * 32 * 32), height=32, width=32)
    norm = L.cross_channel_norm(feat, name="ccn")
    pb = L.priorbox(norm, img, aspect_ratio=[2.0],
                    variance=[0.1, 0.1, 0.2, 0.2], min_size=[8.0],
                    max_size=[16.0], name="pb")
    loc = L.img_conv(norm, filter_size=3, num_filters=4 * 4, padding=1,
                     name="loc")
    conf = L.img_conv(norm, filter_size=3, num_filters=4 * 21, padding=1,
                      name="conf")
    return L.detection_output(loc, conf, pb, num_classes=21, name="det")


def multibox_net():
    feat = L.data("feat", D.dense_vector(8 * 4 * 4), height=4, width=4)
    img = L.data("img", D.dense_vector(3 * 32 * 32), height=32, width=32)
    pb = L.priorbox(feat, img, aspect_ratio=[2.0],
                    variance=[0.1, 0.1, 0.2, 0.2], min_size=[8.0],
                    name="pb")
    loc = L.img_conv(feat, filter_size=3, num_filters=3 * 4, padding=1,
                     name="loc")
    conf = L.img_conv(feat, filter_size=3, num_filters=3 * 21, padding=1,
                      name="conf")
    gt = L.data("gt", D.dense_vector_sequence(6))
    return L.multibox_loss(loc, conf, pb, gt, num_classes=21, name="mbloss")


def conv3d_net():
    v = L.data("v", D.dense_vector(2 * 8 * 8 * 8))
    c = L.img_conv3d(v, filter_size=3, num_filters=4, input_depth=8,
                     num_channels=2, input_height=8, input_width=8,
                     padding=1, act=A.Relu(), name="c3d")
    p = L.img_pool3d(c, pool_size=2, input_depth=8, num_channels=4,
                     input_height=8, input_width=8, stride=2, name="p3d")
    return L.fc(p, size=10, act=A.Softmax(), name="output")


def mdlstm_ocr():
    im = L.data("im", D.dense_vector(1 * 8 * 8), height=8, width=8)
    proj = L.img_conv(im, filter_size=1, num_filters=5 * 4, name="gates")
    md = L.mdlstm(proj, name="md")
    be = L.block_expand(md, block_x=1, block_y=8, stride_x=1, stride_y=8,
                        name="cols")
    return L.fc(be, size=11, act=A.Softmax(), name="probs")


def misc_utils():
    x = L.data("x", D.dense_vector(12))
    c = L.clip(x, min=-5.0, max=5.0, name="clip1")
    ss = L.scale_shift(c, name="ss1")
    dn = L.data_norm(ss, name="dn1")
    fe = L.featmap_expand(dn, num_filters=2, name="fe1")
    sn = L.sum_to_one_norm(L.fc(fe, size=6, act=A.Sigmoid(), name="h")
                           , name="sn1")
    w = L.data("w", D.dense_vector(1))
    return L.power(sn, w, name="pow1")


def selection_layers():
    x = L.data("x", D.dense_vector(16))
    sel = L.data("sel", D.dense_vector(32))
    sfc = L.selective_fc(x, size=32, select=sel, act=A.Tanh(), name="sfc")
    idx = L.data("idx", D.integer_value(2))
    a = L.fc(x, size=8, name="ca")
    b = L.fc(x, size=8, name="cb")
    mx = L.multiplex([idx, a, b], name="mx")
    return L.concat([L.fc(sfc, size=8, name="down"), mx], name="out")


def generation_helpers():
    s = L.data("s", D.dense_vector_sequence(16))
    scores = L.fc(s, size=1, name="score")
    km = L.kmax_seq_score(scores, beam_size=3, name="km")
    probs = L.fc(L.last_seq(s), size=10, act=A.Softmax(), name="probs")
    mid = L.max_id(probs, name="mid")
    e = L.eos(mid, eos_id=9, name="e")
    return [km, e]


def deep_speech_row_conv():
    s = L.data("audio", D.dense_vector_sequence(64))
    h = L.fc(s, size=64, act=A.Relu(), name="h1")
    rc = L.row_conv(h, context_len=4, act=A.Relu(), name="rc")
    return L.fc(rc, size=29, act=A.Softmax(), name="probs")


def word_embedding_ngram():
    ws = [L.data(f"w{i}", D.integer_value(1000)) for i in range(4)]
    shared = paddle.attr.ParamAttr(name="shared_emb")
    embs = [L.embedding(w, size=16, param_attr=shared) for w in ws]
    h = L.fc(L.concat(embs, name="ctx"), size=32, act=A.Tanh(), name="h")
    return L.fc(h, size=1000, act=A.Softmax(), name="next_word")


def extra_algebra_layers():
    """Round-3 zoo additions: tensor, conv_shift, linear_comb, prelu,
    row_l2_norm, switch_order."""
    a = L.data("a", D.dense_vector(6))
    b = L.data("b", D.dense_vector(5))
    t = L.tensor(a, b, size=4, act=A.Tanh(), name="bilinear1")
    shift = L.data("shift", D.dense_vector(3))
    cs = L.conv_shift(L.prelu(t, partial_sum=2, name="prelu1"), shift,
                      name="cshift1")
    lc = L.linear_comb(cs, L.fc(a, size=4 * 3, name="vecs"), name="lc1")
    return L.row_l2_norm(lc, name="rl2n1")


def switch_order_net():
    img = L.data("im", D.dense_vector(2 * 4 * 4), height=4, width=4)
    c = L.img_conv(img, filter_size=3, num_filters=3, padding=1,
                   name="so_conv")
    sw = L.switch_order(c, name="switch1")
    return L.fc(sw, size=5, name="so_fc")


def beam_cost_net():
    """Learning-to-search: kmax over level-1 and nested scores feeding
    cross_entropy_over_beam."""
    s1 = L.data("s1", D.dense_vector_sequence(1))
    s2 = L.data("s2", D.dense_vector_sub_sequence(1))
    sel1 = L.kmax_seq_score(s1, beam_size=2, name="sel1")
    sel2 = L.kmax_seq_score(s2, beam_size=2, name="sel2")
    g1 = L.data("g1", D.integer_value(100))
    g2 = L.data("g2", D.integer_value(100))
    return L.cross_entropy_over_beam(
        [L.BeamInput(s1, sel1, g1), L.BeamInput(s2, sel2, g2)],
        name="beam_ce")


def moe_block():
    """Transformer-style MoE FFN + its aux cost node (cost-list model)."""
    x = L.data("tok", D.dense_vector_sequence(16))
    ln = L.layer_norm(x, name="moe_ln")
    ffn = L.moe(ln, expert_num=4, expert_hidden=32, k=2, name="moe1")
    aux = L.moe_aux_cost(ln, ffn, coeff=0.01, name="moe_aux")
    lbl = L.data("y", D.integer_value_sequence(8))
    head = L.fc(ffn, size=8, act=A.Softmax(), name="moe_head")
    return [L.cross_entropy_cost(head, lbl, name="moe_ce"), aux]


def op_sugar_net():
    """paddle.op operator overloads (v2/op.py parity): the graphs
    `a+b`, `a*w`, `2-x`, `op.tanh` lower to — pinned so the sugar's
    auto-named slope_intercept/featmap_expand/scaling/addto chain
    can't drift silently."""
    from paddle_tpu import op
    a = L.data("a", D.dense_vector(6))
    b = L.data("b", D.dense_vector(6))
    w = L.data("w", D.dense_vector(1))
    y = op.tanh(a) + b          # addto of equal sizes
    y = 2.0 - y                 # single slope_intercept (slope+intercept)
    y = y * w                   # scaling by the size-1 layer
    y = y + w                   # featmap_expand broadcast + addto
    return L.fc(y, size=3, name="op_head")


def tpu_stem_net():
    """space_to_depth stem (resnet tpu_stem variant's shape chain)."""
    img = L.data("im", D.dense_vector(3 * 8 * 8), height=8, width=8)
    s2d = L.space_to_depth(img, factor=2, num_channels=3, name="s2d1")
    c = L.img_conv(s2d, filter_size=3, num_filters=8, padding=1,
                   name="stem_conv")
    return L.fc(c, size=4, name="stem_fc")


CONFIGS = {
    "simple_fc": simple_fc,
    "img_layers": img_layers,
    "img_trans_layers": img_trans_layers,
    "util_layers": util_layers,
    "projections": projections,
    "seq_ops_suite": seq_ops_suite,
    "simple_rnn": simple_rnn,
    "simple_lstm_net": simple_lstm_net,
    "bidirectional_gru": bidirectional_gru,
    "rnn_group": rnn_group,
    "nested_rnn_group": nested_rnn_group,
    "attention_net": attention_net,
    "cost_suite": cost_suite,
    "rank_costs": rank_costs,
    "crf_tagger": crf_tagger,
    "ctc_net": ctc_net,
    "nce_hsigmoid": nce_hsigmoid,
    "detection_net": detection_net,
    "multibox_net": multibox_net,
    "conv3d_net": conv3d_net,
    "mdlstm_ocr": mdlstm_ocr,
    "misc_utils": misc_utils,
    "selection_layers": selection_layers,
    "generation_helpers": generation_helpers,
    "deep_speech_row_conv": deep_speech_row_conv,
    "word_embedding_ngram": word_embedding_ngram,
    "extra_algebra_layers": extra_algebra_layers,
    "switch_order_net": switch_order_net,
    "beam_cost_net": beam_cost_net,
    "moe_block": moe_block,
    "op_sugar_net": op_sugar_net,
    "tpu_stem_net": tpu_stem_net,
}
