"""ptlockdep runtime-witness tests (paddle_tpu/analysis/lockdep.py).

The static half (ptlint R8-R10) is covered fixture-by-fixture in
tests/test_lint_rules.py; this file proves the RUNTIME half — named
instrumented locks feeding a global acquisition-order graph with
inversion detection, contention/hold-time telemetry, the journal /
flight-recorder integration — and closes with the chaos acceptance:
the PR 9 coordinator/metrics deadlock shape reconstructed and caught
BOTH statically (ptlint finds the fixture) and dynamically (the
witness journals the inversion with both stacks and the flight
recorder auto-dumps a postmortem bundle), while the SHIPPED code stays
clean (the tier-1 witness fixture in conftest asserts zero inversions
for every other test).
"""

import glob
import json
import textwrap
import threading
import time

import pytest

from paddle_tpu.analysis.lockdep import (LOCKDEP, InstrumentedLock,
                                         LockOrderInversion, find_lock,
                                         named_condition, named_lock,
                                         named_rlock)


# ==================================================== the lock itself
class TestInstrumentedLock:
    def test_lock_protocol(self):
        lk = named_lock("t.basic")
        assert not lk.locked()
        with lk:
            assert lk.locked()
            assert "t.basic" in LOCKDEP.held_names()
        assert not lk.locked()
        assert "t.basic" not in LOCKDEP.held_names()
        assert lk.acquire(timeout=1.0)  # ptlint: disable=R5(the acquire API under test; released on the next line)
        lk.release()

    def test_rlock_reentrancy_is_one_witness_entry(self):
        lk = named_rlock("t.rlock")
        with lk:
            with lk:            # reentrant: no self-deadlock
                # the witness sees ONE outermost acquire, not two
                # (one graph node per name; nesting is not an edge)
                assert LOCKDEP.held_names().count("t.rlock") == 1
            assert lk.locked()
        assert not lk.locked()

    def test_non_reentrant_lock_refuses_double_acquire(self):
        lk = named_lock("t.nonreent")
        with lk:
            assert not lk.acquire(blocking=False)  # ptlint: disable=R5(non-blocking probe under test; returns False, nothing to release)

    def test_condition_is_a_drop_in(self):
        cv = named_condition("t.cv")
        ready = []

        def waiter():
            with cv:
                while not ready:
                    cv.wait(timeout=2.0)

        t = threading.Thread(target=waiter, name="pt-test-cvwait")
        t.start()
        time.sleep(0.05)
        with cv:
            ready.append(1)
            cv.notify()
        t.join(timeout=2.0)
        assert not t.is_alive()
        # wait() released and re-acquired through the instrumented
        # protocol: the held stack is balanced afterwards
        assert "t.cv" not in LOCKDEP.held_names()

    def test_find_lock_resolves_the_live_instance(self):
        lk = named_lock("t.findme")
        assert find_lock("t.findme") is lk
        assert find_lock("t.no-such-lock") is None


# ==================================================== the order graph
class TestOrderGraph:
    def test_consistent_order_records_edge_no_inversion(self):
        a, b = named_lock("t.g.a"), named_lock("t.g.b")
        for _ in range(3):
            with a:
                with b:
                    pass
        assert ("t.g.a", "t.g.b", 3) in LOCKDEP.snapshot_edges()
        assert LOCKDEP.inversion_count == 0
        assert "t.g.a -> t.g.b" in LOCKDEP.format_text()
        assert '"t.g.a" -> "t.g.b"' in LOCKDEP.to_dot()

    @pytest.mark.lockdep_allow_inversion
    def test_opposite_order_is_an_inversion_with_both_stacks(self):
        a, b = named_lock("t.i.a"), named_lock("t.i.b")
        with a:
            with b:
                pass
        with b:
            with a:                 # closes the cycle
                pass
        assert LOCKDEP.inversion_count == 1
        rec = LOCKDEP.inversions[0]
        assert rec["acquiring"] == "t.i.a"
        assert rec["while_holding"] == "t.i.b"
        assert "t.i.a" in rec["cycle"] and "t.i.b" in rec["cycle"]
        assert rec["this_stack"] and rec["other_stack"]
        # journaled under the lockdep domain with the full record
        from paddle_tpu.obs.events import tail
        recs = tail(domain="lockdep", kind="inversion")
        assert recs and recs[-1]["acquiring"] == "t.i.a"

    @pytest.mark.lockdep_allow_inversion
    def test_inversion_reported_once_per_cycle(self):
        a, b = named_lock("t.once.a"), named_lock("t.once.b")
        with a:
            with b:
                pass
        for _ in range(5):
            with b:
                with a:
                    pass
        assert LOCKDEP.inversion_count == 1

    @pytest.mark.lockdep_allow_inversion
    def test_raise_mode_raises_into_the_acquiring_thread(self):
        a, b = named_lock("t.r.a"), named_lock("t.r.b")
        with a:
            with b:
                pass
        LOCKDEP.configure(on_inversion="raise")
        try:
            with pytest.raises(LockOrderInversion, match="t.r.a"):
                with b:
                    with a:
                        pass
        finally:
            LOCKDEP.configure(on_inversion="journal")


# ======================================================== telemetry
class TestTelemetry:
    def test_contention_and_hold_time_counted(self):
        lk = named_lock("t.tel")
        entered = threading.Event()

        def holder():
            with lk:
                entered.set()
                # ptlint: disable=R9(deliberate hold: this thread exists to create the contention under test)
                time.sleep(0.08)

        t = threading.Thread(target=holder, name="pt-test-holder")
        t.start()
        assert entered.wait(2.0)
        with lk:                    # contends with the holder
            pass
        t.join(timeout=2.0)
        snap = LOCKDEP.metrics_snapshot()
        assert snap["contentions"].get("t.tel", 0) >= 1
        assert snap["hold_ms"].get("t.tel", 0.0) >= 80.0 * 0.5
        assert snap["acquisitions"].get("t.tel", 0) >= 2

    def test_hold_lock_fault_injector_drives_contention(self):
        """faults family (m): hold_lock squats on a live named lock
        through the _step_interceptor seam, deterministically."""
        from paddle_tpu.testing.faults import FaultPlan

        class FakeTrainer:
            _step_interceptor = None

        lk = named_lock("t.faults")
        target = FakeTrainer()
        with pytest.raises(KeyError):
            with FaultPlan.hold_lock(target, "t.faults-typo"):
                pass
        with FaultPlan.hold_lock(target, "t.faults", at=1, ms=30,
                                 n=1) as stats:
            fired = threading.Event()

            def step_path():
                for k in range(3):
                    target._step_interceptor(k, None)
                fired.set()

            t = threading.Thread(target=step_path,
                                 name="pt-test-steps")
            t.start()
            time.sleep(0.01)
            with lk:                # contends during firing index 1
                pass
            assert fired.wait(2.0)
            t.join(timeout=2.0)
        assert stats["injected"] == 1
        assert stats["held_ms"] >= 30.0 * 0.5
        assert target._step_interceptor is None      # seam restored
        assert LOCKDEP.metrics_snapshot()["acquisitions"].get(
            "t.faults", 0) >= 2


# ================================================== chaos acceptance
#: the PR 9 deadlock, reduced: a coordinator-shaped worker that emits
#: telemetry while holding its state lock, racing a metrics-shaped
#: scraper that reads state while holding the metrics lock — the two
#: threads take {chaos.coord, chaos.metrics} in opposite orders.
_PR9_FIXTURE = """
    import time
    from paddle_tpu.analysis.lockdep import named_lock
    from paddle_tpu.obs.events import JOURNAL
    from paddle_tpu.obs.flight import FLIGHT

    class Coordinator:
        def __init__(self):
            self._lock = named_lock("chaos.coord")

        def heartbeat(self):
            with self._lock:
                # blocking telemetry inside the critical section —
                # the exact PR 9 bug class (ptlint R9)
                FLIGHT.maybe_autodump("lease_expired")
                time.sleep(0.2)
"""


class TestChaosDeadlockWitness:
    def test_static_twin_flags_the_fixture(self, tmp_path):
        """ptlint catches the PR 9 shape BEFORE it runs: R9 flags the
        blocking flight dump + sleep under chaos.coord."""
        from paddle_tpu.analysis.runner import LintConfig, lint_paths
        pkg = tmp_path / "pkg"
        pkg.mkdir()
        (pkg / "repro.py").write_text(textwrap.dedent(_PR9_FIXTURE))
        cfg = LintConfig(root=str(tmp_path), paths=["pkg"],
                         rules=["R9"], baseline="")
        res = lint_paths(cfg, use_baseline=False)
        assert len(res.new) == 2, [f.format() for f in res.new]
        assert all(f.rule == "R9" and "chaos.coord" in f.message
                   for f in res.new)

    @pytest.mark.lockdep_allow_inversion
    def test_runtime_witness_catches_and_dumps_the_inversion(
            self, tmp_path):
        """The dynamic half: two threads close the coord/metrics cycle;
        the witness journals lockdep/inversion with BOTH stacks and the
        armed flight recorder auto-dumps a postmortem bundle."""
        from paddle_tpu.obs.events import tail
        from paddle_tpu.obs.flight import FLIGHT
        FLIGHT.configure(dump_dir=str(tmp_path), min_dump_interval=0.0)

        coord = named_lock("chaos.coord")
        metrics = named_lock("chaos.metrics")

        def heartbeat():            # coord -> metrics
            with coord:
                with metrics:
                    pass

        def scrape():               # metrics -> coord: the inversion
            with metrics:
                # ptlint: disable=R8(the PR 9 order cycle this chaos test exists to provoke)
                with coord:
                    pass

        # serialized, NOT interleaved: the witness flags the ORDER
        # cycle from the acquisition graph alone, without the test
        # having to win the race into an actual deadlock — that is the
        # whole point of a lock-order witness
        t1 = threading.Thread(target=heartbeat, name="pt-test-coord")
        t1.start()
        t1.join(timeout=5.0)
        t2 = threading.Thread(target=scrape, name="pt-test-scrape")
        t2.start()
        t2.join(timeout=5.0)
        assert not t1.is_alive() and not t2.is_alive()

        assert LOCKDEP.inversion_count >= 1
        rec = LOCKDEP.inversions[0]
        cyc = {rec["acquiring"], rec["while_holding"]}
        assert cyc == {"chaos.coord", "chaos.metrics"}
        assert rec["this_stack"] and rec["other_stack"]
        assert rec["this_thread"] != rec["other_thread"]

        recs = tail(domain="lockdep", kind="inversion")
        assert recs and recs[-1]["this_stack"]

        bundles = glob.glob(str(tmp_path / "flight-*lockdep*"))
        assert bundles, "inversion did not auto-dump a flight bundle"
        with open(bundles[0], encoding="utf-8") as f:
            bundle = json.load(f)
        assert "lockdep_inversion" in bundle["reason"]
