"""Subprocess trainer for the elastic SIGKILL test (the stateless cloud
trainer of go/master's design: pulls tasks over RPC, checkpoints full
state, restartable at any instant).

argv: <coordinator_port> <ckpt_dir> <per_record_delay_s> [worker_id]

With a ``worker_id`` the trainer runs in elastic-membership mode:
join() on entry (adopting the fleet's generation + memory plan),
generation-stamped grants, graceful leave() on exit. Each optimizer
step prints ``STEP <k> LOSS <cost>`` so the chaos tests can both
kill-at-marker (testing/faults.py) and digest-compare the loss
trajectory against a fixed-membership run.
"""

import sys
import time

import numpy as np


def main():
    port = int(sys.argv[1])
    ckpt_dir = sys.argv[2]
    delay = float(sys.argv[3])
    worker_id = sys.argv[4] if len(sys.argv) > 4 else None

    import jax
    jax.config.update("jax_platforms", "cpu")
    import paddle_tpu as paddle
    from paddle_tpu.trainer.checkpoint import CheckpointManager
    from paddle_tpu.trainer.coordinator import connect

    paddle.init(seed=0)
    x = paddle.layer.data("x", paddle.data_type.dense_vector(8))
    y = paddle.layer.data("y", paddle.data_type.integer_value(2))
    out = paddle.layer.fc(x, size=2, act=paddle.activation.Softmax(),
                          name="out")
    cost = paddle.layer.classification_cost(out, y, name="cost")
    params = paddle.create_parameters(paddle.Topology(cost))
    tr = paddle.SGD(cost=cost, parameters=params,
                    update_equation=paddle.optimizer.Momentum(
                        learning_rate=0.05))

    def chunk_reader(chunk):
        r = np.random.RandomState(int(chunk))
        for _ in range(4):
            if delay:
                time.sleep(delay)
            yield (r.randn(8).astype("float32"), int(r.randint(2)))

    def on_step(e):
        if isinstance(e, paddle.event.EndIteration):
            print(f"STEP {e.batch_id} LOSS {e.cost:.10f}", flush=True)

    coord = connect("127.0.0.1", port)
    mgr = CheckpointManager(ckpt_dir, keep=2)
    tr.train(coordinator=coord, chunk_reader=chunk_reader, batch_size=4,
             num_passes=2, checkpoint_manager=mgr, checkpoint_period=1,
             event_handler=on_step, worker_id=worker_id)
    print(f"WORKER DONE steps={tr._step_count}", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
