"""Subprocess trainer for the elastic SIGKILL test (the stateless cloud
trainer of go/master's design: pulls tasks over RPC, checkpoints full
state, restartable at any instant).

argv: <coordinator_port> <ckpt_dir> <per_record_delay_s>
"""

import sys
import time

import numpy as np


def main():
    port = int(sys.argv[1])
    ckpt_dir = sys.argv[2]
    delay = float(sys.argv[3])

    import jax
    jax.config.update("jax_platforms", "cpu")
    import paddle_tpu as paddle
    from paddle_tpu.trainer.checkpoint import CheckpointManager
    from paddle_tpu.trainer.coordinator import connect

    paddle.init(seed=0)
    x = paddle.layer.data("x", paddle.data_type.dense_vector(8))
    y = paddle.layer.data("y", paddle.data_type.integer_value(2))
    out = paddle.layer.fc(x, size=2, act=paddle.activation.Softmax(),
                          name="out")
    cost = paddle.layer.classification_cost(out, y, name="cost")
    params = paddle.create_parameters(paddle.Topology(cost))
    tr = paddle.SGD(cost=cost, parameters=params,
                    update_equation=paddle.optimizer.Momentum(
                        learning_rate=0.05))

    def chunk_reader(chunk):
        r = np.random.RandomState(int(chunk))
        for _ in range(4):
            if delay:
                time.sleep(delay)
            yield (r.randn(8).astype("float32"), int(r.randint(2)))

    coord = connect("127.0.0.1", port)
    mgr = CheckpointManager(ckpt_dir, keep=2)
    tr.train(coordinator=coord, chunk_reader=chunk_reader, batch_size=4,
             num_passes=2, checkpoint_manager=mgr, checkpoint_period=1,
             event_handler=lambda e: None)
    print(f"WORKER DONE steps={tr._step_count}", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
