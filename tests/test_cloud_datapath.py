"""End-to-end cloud data path: dataset convert() -> RecordIO shards ->
coordinator partitions the shards' chunks as tasks -> two concurrent
workers train a pass via task_reader, every record consumed exactly once.

Reference contract: python/paddle/v2/dataset/common.py convert():143
emits the shards, go/master/service.go:106 partitions them chunk-wise,
go/master/client.go:232 NextRecord feeds the trainers.
"""

import threading

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.core import registry
from paddle_tpu.dataset import common, uci_housing
from paddle_tpu.reader import recordio as rio
from paddle_tpu.trainer.coordinator import Coordinator, task_reader


class TestConvert:
    def test_convert_shards_and_roundtrip(self, tmp_path):
        rows = list(uci_housing.train()())
        paths = common.convert(str(tmp_path), uci_housing.train(), 100,
                               "uci-train")
        assert len(paths) == (len(rows) + 99) // 100
        # every shard deserializes back to the original samples, in order
        got = []
        for p in paths:
            for k in range(rio.num_chunks(p)):
                got.extend(common.record_deserializer(r)
                           for r in rio.read_chunk(p, k))
        assert len(got) == len(rows)
        np.testing.assert_allclose(np.asarray(got[0][0]),
                                   np.asarray(rows[0][0]), rtol=1e-6)

    def test_dataset_convert_wrappers(self, tmp_path):
        from paddle_tpu.dataset import mnist
        mnist.convert(str(tmp_path / "m"))
        import os
        names = os.listdir(tmp_path / "m")
        assert any(n.startswith("mnist-train") for n in names)
        assert any(n.startswith("mnist-test") for n in names)


class TestCloudDataPath:
    def test_two_workers_train_a_pass_over_converted_shards(self, tmp_path):
        """convert -> coordinator -> two SGD workers via task_reader."""
        rows = list(uci_housing.train()())
        # many small shards so neither worker can drain the queue while
        # the other is still compiling its first step
        paths = common.convert(str(tmp_path), uci_housing.train(), 20,
                               "uci-train")
        descs = [d for p in paths for d in rio.chunk_descriptors(p)]
        assert len(descs) >= 10
        # timeout well above worst-case first-batch jit under a loaded
        # host: a premature requeue would double-deliver a task and
        # break the exactly-once assertion below
        coord = Coordinator(descs, chunks_per_task=1, timeout_s=300.0)

        counts = [0, 0]
        losses = [[], []]
        errors = []
        gate = threading.Barrier(2, timeout=300)

        def worker(i):
            try:
                registry.reset_name_counters()
                paddle.init(seed=i)
                x = paddle.layer.data(
                    "x", paddle.data_type.dense_vector(13))
                y = paddle.layer.data(
                    "y", paddle.data_type.dense_vector(1))
                fc = paddle.layer.fc(x, size=1, act=None,
                                     name=f"w{i}_fc")
                cost = paddle.layer.mse_cost(fc, y, name=f"w{i}_cost")
                params = paddle.create_parameters(paddle.Topology(cost))
                tr = paddle.SGD(cost=cost, parameters=params,
                                update_equation=paddle.optimizer.Momentum(
                                    learning_rate=1e-4))

                base = task_reader(
                    coord, rio.chunk_reader(common.record_deserializer),
                    idle_timeout=30.0)

                def counted():
                    gate.wait()   # both workers start pulling together
                    for rec in base():
                        counts[i] += 1
                        yield rec

                tr.train(paddle.reader.batch(counted, 32),
                         num_passes=1,
                         event_handler=lambda e: losses[i].append(e.cost)
                         if isinstance(e, paddle.event.EndIteration)
                         else None)
            except Exception as e:   # surface into the main thread
                errors.append(e)

        ts = [threading.Thread(target=worker, args=(i,),
                               name=f"pt-test-trainer-{i}")
              for i in (0, 1)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=600)
        assert not errors, errors
        # exactly-once delivery across the two workers
        assert counts[0] + counts[1] == len(rows), counts
        assert counts[0] > 0 and counts[1] > 0, counts
        for i in (0, 1):
            assert losses[i] and np.isfinite(losses[i]).all()
