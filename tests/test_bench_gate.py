"""Perf regression gate (tools/bench_gate.py) — the tier-1 guard.

THE acceptance pair (ISSUE 7): an untouched smoke run passes against
the committed BENCH_SMOKE_BASELINE.json, and a deliberately injected
perf regression (forced recompile-per-step — the classic jit-in-loop
bug ptlint R2 lints for, reproduced at runtime) makes the gate FAIL.
Plus unit coverage of the tolerance semantics, the --write-baseline
flow (tolerances survive re-baselining), and the output formats.
"""

import json
import os
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BASELINE = os.path.join(REPO, "BENCH_SMOKE_BASELINE.json")

sys.path.insert(0, os.path.join(REPO, "tools"))
try:
    import bench_gate
finally:
    sys.path.pop(0)


def _baseline():
    with open(BASELINE) as f:
        return json.load(f)


@pytest.fixture(scope="module")
def smoke():
    """One real smoke-tier run for the whole module (bench.py must be
    importable from the repo root)."""
    sys.path.insert(0, REPO)
    try:
        import bench
    finally:
        sys.path.pop(0)
    return bench.bench_smoke()


class TestGateAcceptance:
    def test_untouched_run_passes_committed_baseline(self, smoke):
        res = bench_gate.compare(smoke, _baseline())
        assert res.ok, bench_gate.format_gate(res)
        # the tight tier really ran: count metrics were checked
        kinds = {c.kind for c in res.checks}
        assert "count" in kinds and "rate" in kinds

    def test_forced_recompile_per_step_fails_gate(self):
        """The injected regression: rebuilding the jitted step every
        iteration must blow the compile-count budget (and collapse
        steps/s below the rate floor)."""
        sys.path.insert(0, REPO)
        try:
            import bench
        finally:
            sys.path.pop(0)
        bad = bench.bench_smoke(train_steps=6, rows=("train_tiny",),
                                force_recompile_per_step=True)
        res = bench_gate.compare(bad, _baseline())
        failed = {c.name for c in res.failures}
        assert "train_tiny.step_compiles" in failed, \
            bench_gate.format_gate(res)


class TestGateSemantics:
    BASE = {"v": 1, "rows": {"r": {
        "step_compiles": {"value": 3, "kind": "count", "max_slack": 2},
        "steps_per_s": {"value": 100.0, "kind": "rate",
                        "min_ratio": 0.1},
        "p50_ms": {"value": 10.0, "kind": "latency", "max_ratio": 4,
                   "abs_floor_ms": 5.0},
        "served": {"value": 7, "kind": "info"},
    }}}

    @staticmethod
    def _results(**over):
        row = {"step_compiles": 3, "steps_per_s": 100.0, "p50_ms": 10.0,
               "served": 7}
        row.update(over)
        return {"v": 1, "rows": {"r": row}}

    def test_within_tolerance_passes(self):
        res = bench_gate.compare(
            self._results(step_compiles=5, steps_per_s=11.0,
                          p50_ms=39.0, served=999),
            self.BASE)
        assert res.ok, bench_gate.format_gate(res)

    def test_count_over_slack_fails(self):
        res = bench_gate.compare(self._results(step_compiles=6),
                                 self.BASE)
        assert [c.name for c in res.failures] == ["r.step_compiles"]

    def test_rate_collapse_fails(self):
        res = bench_gate.compare(self._results(steps_per_s=9.9),
                                 self.BASE)
        assert [c.name for c in res.failures] == ["r.steps_per_s"]

    def test_latency_ceiling_uses_abs_floor(self):
        # ceiling = max(10 * 4, 5) = 40
        res = bench_gate.compare(self._results(p50_ms=41.0), self.BASE)
        assert [c.name for c in res.failures] == ["r.p50_ms"]
        # a tiny baseline never flakes below the absolute floor
        tiny = {"v": 1, "rows": {"r": {"p50_ms": {
            "value": 0.01, "kind": "latency", "max_ratio": 4,
            "abs_floor_ms": 50.0}}}}
        res = bench_gate.compare(
            {"v": 1, "rows": {"r": {"p50_ms": 49.0}}}, tiny)
        assert res.ok

    def test_info_never_gates(self):
        res = bench_gate.compare(self._results(served=0), self.BASE)
        assert res.ok

    def test_missing_metric_and_row_fail(self):
        blob = self._results()
        del blob["rows"]["r"]["step_compiles"]
        res = bench_gate.compare(blob, self.BASE)
        assert [c.name for c in res.failures] == ["r.step_compiles"]
        # a whole row vanishing fails EVERY baseline metric in it,
        # info rows included (lost coverage is itself a regression)
        res = bench_gate.compare({"v": 1, "rows": {}}, self.BASE)
        assert {c.name for c in res.failures} == {
            "r.step_compiles", "r.steps_per_s", "r.p50_ms", "r.served"}

    def test_uncovered_metric_is_a_note_not_a_failure(self):
        res = bench_gate.compare(self._results(new_metric=1.0),
                                 self.BASE)
        assert res.ok and any("new_metric" in n for n in res.notes)

    def test_write_baseline_preserves_tolerances(self, tmp_path):
        path = str(tmp_path / "b.json")
        loose = json.loads(json.dumps(self.BASE))
        loose["rows"]["r"]["steps_per_s"]["min_ratio"] = 0.5
        bench_gate.write_baseline(path, self._results(steps_per_s=200.0),
                                  loose)
        with open(path) as f:
            out = json.load(f)
        entry = out["rows"]["r"]["steps_per_s"]
        assert entry["value"] == 200.0
        assert entry["min_ratio"] == 0.5       # tolerance inherited
        assert out["rows"]["r"]["served"]["kind"] == "info"

    def test_formats(self):
        res = bench_gate.compare(self._results(step_compiles=6),
                                 self.BASE)
        text = bench_gate.format_gate(res, "text")
        assert "FAIL r.step_compiles" in text and "1 regression" in text
        gh = bench_gate.format_gate(res, "github")
        assert gh.startswith("::error::bench_gate r.step_compiles")
        blob = json.loads(bench_gate.format_gate(res, "json"))
        assert blob["ok"] is False
        assert blob["failures"] == ["r.step_compiles"]

    def test_cli_exit_codes(self, tmp_path):
        results = str(tmp_path / "res.json")
        base = str(tmp_path / "base.json")
        with open(results, "w") as f:
            json.dump(self._results(), f)
        with open(base, "w") as f:
            json.dump(self.BASE, f)
        assert bench_gate.main(["--results", results,
                                "--baseline", base]) == 0
        with open(results, "w") as f:
            json.dump(self._results(step_compiles=99), f)
        assert bench_gate.main(["--results", results,
                                "--baseline", base]) == 1
        assert bench_gate.main(["--baseline", base]) == 2   # no input
        assert bench_gate.main(["--results", results, "--baseline",
                                str(tmp_path / "nope.json")]) == 2
