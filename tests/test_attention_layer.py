"""dot_product_attention layer: plain vs ring (sp mesh) equivalence —
the VERDICT criterion that ring attention is usable FROM A LAYER with the
switch being purely a mesh decision."""

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.core import registry
from paddle_tpu.parallel import create_mesh
from paddle_tpu.parallel.mesh import SP_AXIS

T = 8


def _model(causal):
    registry.reset_name_counters()
    ids = paddle.layer.data(
        "ids", paddle.data_type.integer_value_sequence(50))
    lbl = paddle.layer.data("y", paddle.data_type.integer_value(2))
    emb = paddle.layer.embedding(ids, size=32, name="att_emb")
    att = paddle.layer.dot_product_attention(emb, num_heads=4,
                                             causal=causal, name="att")
    pooled = paddle.layer.pooling(
        att, pooling_type=paddle.pooling.Avg(), name="att_pool")
    out = paddle.layer.fc(pooled, size=2, act=paddle.activation.Softmax(),
                          name="att_out")
    cost = paddle.layer.classification_cost(out, lbl, name="att_cost")
    return cost


def _reader(n=2, b=8):
    rng = np.random.RandomState(0)
    batches = [[([int(v) for v in rng.randint(0, 50, T)],
                 int(rng.randint(2))) for _ in range(b)]
               for _ in range(n)]

    def reader():
        yield from batches
    return reader


def _train(mesh, causal):
    paddle.init(seed=0)
    cost = _model(causal)
    params = paddle.create_parameters(paddle.Topology(cost))
    tr = paddle.SGD(cost=cost, parameters=params,
                    update_equation=paddle.optimizer.Adam(
                        learning_rate=1e-2), mesh=mesh)
    losses = []
    tr.train(_reader(), num_passes=2,
             event_handler=lambda e: losses.append(e.cost)
             if isinstance(e, paddle.event.EndIteration) else None)
    return tr, losses


class TestAttentionLayer:
    @pytest.mark.parametrize("causal", [False, True])
    def test_sp2_matches_plain(self, causal):
        mesh = create_mesh([(SP_AXIS, 2)])
        tr_sp, losses_sp = _train(mesh, causal)
        tr_ref, losses_ref = _train(None, causal)
        np.testing.assert_allclose(losses_sp, losses_ref,
                                   rtol=1e-4, atol=1e-5)
        for k in tr_ref.parameters.raw:
            np.testing.assert_allclose(
                np.asarray(tr_sp.parameters.raw[k]),
                np.asarray(tr_ref.parameters.raw[k]),
                rtol=1e-3, atol=1e-5, err_msg=k)

    def test_ragged_masking(self):
        # padded positions must not contribute: two batches identical
        # except for values past the valid length give identical outputs
        paddle.init(seed=0)
        cost = _model(False)
        topo = paddle.Topology(cost)
        params = paddle.create_parameters(topo)
        from paddle_tpu.core.sequence import SequenceBatch
        import jax.numpy as jnp
        ids1 = np.zeros((2, T), np.int32)
        ids1[:, :4] = 7
        ids2 = ids1.copy()
        ids2[:, 4:] = 23                          # garbage past length 4
        lengths = np.array([4, 4], np.int32)
        outs = []
        for ids in (ids1, ids2):
            feed = {"ids": SequenceBatch(jnp.asarray(ids),
                                         jnp.asarray(lengths)),
                    "y": jnp.zeros((2,), jnp.int32)}
            o, _ = topo.forward(params.raw, {}, feed, mode="test",
                                output_names=["att_pool"])
            outs.append(np.asarray(o["att_pool"]))
        np.testing.assert_allclose(outs[0], outs[1], atol=1e-6)
