"""Topology construction, forward, serialization round-trip
(mirrors python/paddle/v2/tests/test_topology.py + golden-protostr
regression discipline)."""

import io

import jax
import jax.numpy as jnp
import numpy as np

import paddle_tpu as paddle
from paddle_tpu.core.sequence import pack_sequences
from paddle_tpu.core.topology import Topology


def _mlp():
    img = paddle.layer.data("pixel", paddle.data_type.dense_vector(16))
    h = paddle.layer.fc(img, size=8, act=paddle.activation.Relu(),
                        name="hidden")
    out = paddle.layer.fc(h, size=4, act=paddle.activation.Softmax(),
                          name="output")
    lbl = paddle.layer.data("label", paddle.data_type.integer_value(4))
    cost = paddle.layer.classification_cost(out, lbl, name="cost")
    return cost, out


class TestTopology:
    def test_build_and_forward(self, rng):
        cost, out = _mlp()
        topo = Topology(cost)
        assert set(topo.data_layers()) == {"pixel", "label"}
        params = topo.init_params(jax.random.PRNGKey(0))
        assert "_hidden.w0" in params and params["_hidden.w0"].shape == (16, 8)
        feed = {"pixel": jnp.asarray(rng.randn(5, 16).astype(np.float32)),
                "label": jnp.asarray(np.array([0, 1, 2, 3, 0]))}
        outs, _ = topo.forward(params, {}, feed, mode="test")
        assert outs["cost"].shape == (5,)

    def test_shared_params(self, rng):
        a = paddle.layer.data("a", paddle.data_type.dense_vector(6))
        shared = paddle.attr.Param(name="shared_w")
        h1 = paddle.layer.fc(a, size=6, param_attr=shared, bias_attr=False)
        h2 = paddle.layer.fc(h1, size=6, param_attr=shared, bias_attr=False)
        topo = Topology(h2)
        params = topo.init_params()
        assert list(params) == ["shared_w"]

    def test_serialize_roundtrip(self, rng):
        cost, _ = _mlp()
        topo = Topology(cost)
        blob = topo.serialize()
        topo2 = Topology.deserialize(blob)
        params = topo.init_params(jax.random.PRNGKey(1))
        feed = {"pixel": jnp.asarray(rng.randn(3, 16).astype(np.float32)),
                "label": jnp.asarray(np.array([1, 2, 3]))}
        o1, _ = topo.forward(params, {}, feed, mode="test")
        o2, _ = topo2.forward(params, {}, feed, mode="test")
        np.testing.assert_allclose(np.asarray(o1["cost"]),
                                   np.asarray(o2["cost"]), rtol=1e-6)
        # serialization is stable (golden-file regression discipline)
        assert topo2.serialize() == blob

    def test_parameters_tar_roundtrip(self, rng):
        cost, _ = _mlp()
        topo = Topology(cost)
        params = paddle.create_parameters(topo)
        buf = io.BytesIO()
        params.to_tar(buf)
        buf.seek(0)
        loaded = paddle.Parameters.from_tar(buf)
        for name in params.names():
            np.testing.assert_array_equal(params[name], loaded[name])

    def test_jit_forward(self, rng):
        """The whole topology forward must trace under jit."""
        cost, _ = _mlp()
        topo = Topology(cost)
        params = topo.init_params()

        @jax.jit
        def f(p, feed):
            outs, _ = topo.forward(p, {}, feed, mode="test")
            return outs["cost"]

        feed = {"pixel": jnp.asarray(rng.randn(4, 16).astype(np.float32)),
                "label": jnp.asarray(np.array([0, 1, 2, 3]))}
        v = f(params, feed)
        assert v.shape == (4,)

    def test_seq_model_forward(self, rng):
        toks = paddle.layer.data(
            "words", paddle.data_type.integer_value_sequence(50))
        emb = paddle.layer.embedding(toks, size=8)
        proj = paddle.layer.fc(emb, size=32, act=paddle.activation.Linear(),
                               bias_attr=False)
        lstm = paddle.layer.lstmemory(proj)
        pooled = paddle.layer.pooling(
            lstm, pooling_type=paddle.pooling.Max())
        out = paddle.layer.fc(pooled, size=2,
                              act=paddle.activation.Softmax())
        topo = Topology(out)
        params = topo.init_params()
        seqs = pack_sequences([np.array([1, 2, 3], np.int32),
                               np.array([4, 5], np.int32)])
        outs, _ = topo.forward(params, {}, {"words": seqs}, mode="test")
        assert outs[out.name].shape == (2, 2)
