"""Topology construction, forward, serialization round-trip
(mirrors python/paddle/v2/tests/test_topology.py + golden-protostr
regression discipline)."""

import io

import jax
import jax.numpy as jnp
import numpy as np

import paddle_tpu as paddle
from paddle_tpu.core.sequence import pack_sequences
from paddle_tpu.core.topology import Topology


def _mlp():
    img = paddle.layer.data("pixel", paddle.data_type.dense_vector(16))
    h = paddle.layer.fc(img, size=8, act=paddle.activation.Relu(),
                        name="hidden")
    out = paddle.layer.fc(h, size=4, act=paddle.activation.Softmax(),
                          name="output")
    lbl = paddle.layer.data("label", paddle.data_type.integer_value(4))
    cost = paddle.layer.classification_cost(out, lbl, name="cost")
    return cost, out


class TestTopology:
    def test_get_layer(self):
        # test_topology.py test_get_layer parity: lookup returns the very
        # node the DSL call produced; unknown names raise
        cost, out = _mlp()
        topo = Topology(cost)
        assert topo.get_layer("hidden") is topo.by_name["hidden"]
        assert topo.get_layer("output") is out
        import pytest
        with pytest.raises(ValueError):
            topo.get_layer("nope")

    def test_data_type_contract(self):
        # test_topology.py test_data_type parity: two data layers with
        # kind + dim preserved in feeding order
        cost, _ = _mlp()
        types = dict(Topology(cost).data_type())
        assert types["pixel"].kind == "dense" and types["pixel"].dim == 16
        assert types["label"].kind == "integer" and types["label"].dim == 4

    def test_build_and_forward(self, rng):
        cost, out = _mlp()
        topo = Topology(cost)
        assert set(topo.data_layers()) == {"pixel", "label"}
        params = topo.init_params(jax.random.PRNGKey(0))
        assert "_hidden.w0" in params and params["_hidden.w0"].shape == (16, 8)
        feed = {"pixel": jnp.asarray(rng.randn(5, 16).astype(np.float32)),
                "label": jnp.asarray(np.array([0, 1, 2, 3, 0]))}
        outs, _ = topo.forward(params, {}, feed, mode="test")
        assert outs["cost"].shape == (5,)

    def test_shared_params(self, rng):
        a = paddle.layer.data("a", paddle.data_type.dense_vector(6))
        shared = paddle.attr.Param(name="shared_w")
        h1 = paddle.layer.fc(a, size=6, param_attr=shared, bias_attr=False)
        h2 = paddle.layer.fc(h1, size=6, param_attr=shared, bias_attr=False)
        topo = Topology(h2)
        params = topo.init_params()
        assert list(params) == ["shared_w"]

    def test_serialize_roundtrip(self, rng):
        cost, _ = _mlp()
        topo = Topology(cost)
        blob = topo.serialize()
        topo2 = Topology.deserialize(blob)
        params = topo.init_params(jax.random.PRNGKey(1))
        feed = {"pixel": jnp.asarray(rng.randn(3, 16).astype(np.float32)),
                "label": jnp.asarray(np.array([1, 2, 3]))}
        o1, _ = topo.forward(params, {}, feed, mode="test")
        o2, _ = topo2.forward(params, {}, feed, mode="test")
        np.testing.assert_allclose(np.asarray(o1["cost"]),
                                   np.asarray(o2["cost"]), rtol=1e-6)
        # serialization is stable (golden-file regression discipline)
        assert topo2.serialize() == blob

    def test_parameters_tar_roundtrip(self, rng):
        cost, _ = _mlp()
        topo = Topology(cost)
        params = paddle.create_parameters(topo)
        buf = io.BytesIO()
        params.to_tar(buf)
        buf.seek(0)
        loaded = paddle.Parameters.from_tar(buf)
        for name in params.names():
            np.testing.assert_array_equal(params[name], loaded[name])

    def test_jit_forward(self, rng):
        """The whole topology forward must trace under jit."""
        cost, _ = _mlp()
        topo = Topology(cost)
        params = topo.init_params()

        @jax.jit
        def f(p, feed):
            outs, _ = topo.forward(p, {}, feed, mode="test")
            return outs["cost"]

        feed = {"pixel": jnp.asarray(rng.randn(4, 16).astype(np.float32)),
                "label": jnp.asarray(np.array([0, 1, 2, 3]))}
        v = f(params, feed)
        assert v.shape == (4,)

    def test_seq_model_forward(self, rng):
        toks = paddle.layer.data(
            "words", paddle.data_type.integer_value_sequence(50))
        emb = paddle.layer.embedding(toks, size=8)
        proj = paddle.layer.fc(emb, size=32, act=paddle.activation.Linear(),
                               bias_attr=False)
        lstm = paddle.layer.lstmemory(proj)
        pooled = paddle.layer.pooling(
            lstm, pooling_type=paddle.pooling.Max())
        out = paddle.layer.fc(pooled, size=2,
                              act=paddle.activation.Softmax())
        topo = Topology(out)
        params = topo.init_params()
        seqs = pack_sequences([np.array([1, 2, 3], np.int32),
                               np.array([4, 5], np.int32)])
        outs, _ = topo.forward(params, {}, {"words": seqs}, mode="test")
        assert outs[out.name].shape == (2, 2)


class TestDeclaredOutputWarning:
    def test_cost_graph_missing_declared_output_warns(self):
        """VERDICT r3 weak #6: Topology(spec.cost) must WARN when the
        ModelSpec's declared inference head is a side branch the cost
        graph excludes (the transformer's probs node)."""
        import warnings
        import paddle_tpu as paddle
        from paddle_tpu.core import registry, topology as topo_mod
        from paddle_tpu import models

        registry.reset_name_counters()
        spec = models.transformer_lm(vocab_size=32, d_model=16, n_heads=2,
                                     n_layers=1, d_ff=32, max_len=8)
        topo_mod._warned_orphan_outputs.clear()
        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            paddle.Topology(spec.cost)
        assert any("declared output" in str(x.message) for x in w), \
            [str(x.message) for x in w]
        # building WITH the output (the documented fix) does not warn
        topo_mod._warned_orphan_outputs.clear()
        with warnings.catch_warnings(record=True) as w2:
            warnings.simplefilter("always")
            paddle.Topology(spec.cost, extra_outputs=[spec.output])
        assert not any("declared output" in str(x.message) for x in w2)

    def test_contained_output_does_not_warn(self):
        import warnings
        import paddle_tpu as paddle
        from paddle_tpu.core import registry, topology as topo_mod
        from paddle_tpu import models

        registry.reset_name_counters()
        spec = models.smallnet(height=8, width=8, num_classes=4)
        topo_mod._warned_orphan_outputs.clear()
        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            paddle.Topology(spec.cost)
        assert not any("declared output" in str(x.message) for x in w)


class TestWarpCTCResolves:
    def test_warp_ctc_layer_type_registered(self):
        from paddle_tpu.core.registry import get_layer_impl
        impl = get_layer_impl("warp_ctc")
        assert impl is not None and "apply" in impl

    def test_warp_ctc_topology_roundtrip(self):
        """A serialized config naming warp_ctc must deserialize and run."""
        import numpy as np
        import paddle_tpu as paddle
        from paddle_tpu.core import registry
        from paddle_tpu.core.sequence import pack_sequences

        registry.reset_name_counters()
        x = paddle.layer.data(
            "x", paddle.data_type.dense_vector_sequence(6))
        acts = paddle.layer.fc(x, size=5, act=None, name="wc_fc")
        lbl = paddle.layer.data(
            "lab", paddle.data_type.integer_value_sequence(5))
        cost = paddle.layer.warp_ctc(acts, lbl, size=5, name="wc")
        topo = paddle.Topology(cost)
        assert topo.by_name["wc"].type == "warp_ctc"
        topo2 = paddle.Topology.deserialize(topo.serialize())
        params = topo2.init_params()
        feed = {"x": pack_sequences(
                    [np.random.RandomState(0).randn(5, 6).astype("f4"),
                     np.random.RandomState(1).randn(4, 6).astype("f4")]),
                "lab": pack_sequences(
                    [np.array([1, 2], np.int32),
                     np.array([3, 1, 2], np.int32)])}
        outs, _ = topo2.forward(params, topo2.init_state(), feed,
                                mode="train")
        v = outs["wc"]
        v = v.data if hasattr(v, "data") else v
        assert np.isfinite(np.asarray(v)).all()
