"""Memory-pressure chaos suite (docs/robustness.md "Memory pressure").

The acceptance contract (ISSUE 5): with ``oom_at(step=3, n=2)``
injected, a full training pass completes with ZERO lost samples and
final params equal (f32 tolerance) to an uninjected run at the same
effective batch size; a SIGKILL after the OOM resumes from checkpoint
meta with the adapted ``MemoryPlan`` — no re-probe, no re-discovery by
OOM. Plus: gradient-accumulation equivalence at k=1,2,4 (the
``lax.scan`` loop must not recompile per microbatch —
``@pytest.mark.recompile_budget``), the warmup probe's binary search
under deterministic allocation pressure, plan persistence via
``CheckpointManager.peek_meta``, and the serving-side shed path
(``Rejected(reason="resource_exhausted")`` without tripping the
circuit breaker).
"""

import os
import subprocess
import sys
import warnings

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.testing import FaultPlan
from paddle_tpu.trainer.memory import (MemoryPlan, is_resource_exhausted,
                                       plan_memory,
                                       resource_exhausted_error)
from paddle_tpu.utils.stats import global_counters

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module", autouse=True)
def _no_persistent_compile_cache():
    """OOM injection races the allocator against executables that the
    persistent compile cache (paddle_tpu/artifacts/cache.py, enabled
    by tests/conftest.py) would deserialize from disk; keep this
    module on freshly-compiled executables."""
    from paddle_tpu.artifacts import cache as compile_cache
    with compile_cache.disabled():
        yield


def _trainer(lr=0.05):
    from paddle_tpu.core import registry
    registry.reset_name_counters()     # identical auto-names per build
    paddle.init(seed=0)
    x = paddle.layer.data("x", paddle.data_type.dense_vector(8))
    y = paddle.layer.data("y", paddle.data_type.integer_value(2))
    out = paddle.layer.fc(x, size=4, act=paddle.activation.Relu())
    out = paddle.layer.fc(out, size=2, act=paddle.activation.Softmax())
    cost = paddle.layer.classification_cost(out, y, name="cost")
    params = paddle.create_parameters(paddle.Topology(cost))
    return paddle.SGD(cost=cost, parameters=params,
                      update_equation=paddle.optimizer.Momentum(
                          learning_rate=lr))


def _reader(rows=8, batches=6, seed=42):
    def reader():
        rng = np.random.RandomState(seed)
        for _ in range(batches):
            f = rng.randn(rows, 8).astype("float32")
            lbl = rng.randint(0, 2, rows)
            yield [(f[i], int(lbl[i])) for i in range(rows)]
    return reader


def _run(trainer, reader, collect=None, **kw):
    losses, ooms = [], []

    def handler(e):
        if isinstance(e, paddle.event.OOMEvent):
            ooms.append(e)
        elif isinstance(e, paddle.event.EndIteration):
            losses.append(e.cost)
        if collect is not None:
            collect(e)

    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        trainer.train(reader, num_passes=1, event_handler=handler, **kw)
    params = {k: np.asarray(v)
              for k, v in trainer.parameters.raw.items()}
    return losses, params, ooms


def _assert_params_close(a, b, rtol=2e-5, atol=2e-6):
    assert a.keys() == b.keys()
    for k in a:
        np.testing.assert_allclose(a[k], b[k], rtol=rtol, atol=atol,
                                   err_msg=k)


# ================================================================ plan
class TestMemoryPlan:
    def test_steps_for(self):
        assert MemoryPlan().steps_for(64) == 1
        assert MemoryPlan(microbatch=64).steps_for(64) == 1
        assert MemoryPlan(microbatch=16).steps_for(64) == 4
        assert MemoryPlan(microbatch=3).steps_for(8) == 3   # ceil

    def test_meta_roundtrip(self):
        assert MemoryPlan().to_meta() is None       # trivial: nothing
        p = MemoryPlan(microbatch=16, accum_steps=4,
                       provenance="adapted")
        m = p.to_meta()
        assert m == {"microbatch": 16, "accum_steps": 4,
                     "provenance": "adapted"}
        q = MemoryPlan.from_meta(m, provenance="resumed")
        assert (q.microbatch, q.accum_steps, q.provenance) == \
            (16, 4, "resumed")
        assert MemoryPlan.from_meta(None) is None
        assert MemoryPlan.from_meta({}) is None

    def test_is_resource_exhausted(self):
        assert is_resource_exhausted(resource_exhausted_error())
        assert is_resource_exhausted(
            RuntimeError("RESOURCE_EXHAUSTED: Out of memory"))
        assert is_resource_exhausted(MemoryError("Out of memory"))
        # the type gate: a ValueError carrying the magic string is NOT
        # a device allocation failure
        assert not is_resource_exhausted(
            ValueError("RESOURCE_EXHAUSTED"))
        assert not is_resource_exhausted(RuntimeError("NaN loss"))

    def test_realistic_error_is_jax_runtime_error(self):
        from jax.errors import JaxRuntimeError
        e = resource_exhausted_error(123456, where="test")
        assert isinstance(e, JaxRuntimeError)
        assert "RESOURCE_EXHAUSTED" in str(e) and "123456" in str(e)


# ====================================================== equivalence
class TestAccumEquivalence:
    """Microbatched step (k=1,2,4) == full-batch step: same per-step
    losses and same final params within f32 tolerance, and the
    accumulation loop compiles ONCE per k — not once per microbatch
    (the recompile budget would blow at 6 steps x k otherwise)."""

    @pytest.mark.recompile_budget(max_compiles=4)
    def test_k124_matches_full_batch(self):
        base_losses, base_params, _ = _run(_trainer(), _reader())
        for mb, k in ((8, 1), (4, 2), (2, 4)):
            losses, params, ooms = _run(_trainer(), _reader(),
                                        microbatch=mb)
            assert not ooms
            np.testing.assert_allclose(losses, base_losses, rtol=2e-5,
                                       atol=2e-6)
            _assert_params_close(base_params, params)

    def test_padded_tail_matches_full_batch(self):
        # 6-row batches at microbatch=4 -> k=2 with 2 zero-padded rows
        # past n_real: the mask must keep them out of loss and grads
        base_losses, base_params, _ = _run(_trainer(), _reader(rows=6))
        losses, params, _ = _run(_trainer(), _reader(rows=6),
                                 microbatch=4)
        np.testing.assert_allclose(losses, base_losses, rtol=2e-5,
                                   atol=2e-6)
        _assert_params_close(base_params, params)

    def test_composes_with_fault_policy(self):
        # guarded + microbatched: the guard folds around the
        # accumulation step; a healthy run matches the plain one
        from paddle_tpu.trainer.fault import FaultPolicy
        base_losses, base_params, _ = _run(_trainer(), _reader())
        losses, params, _ = _run(
            _trainer(), _reader(), microbatch=4,
            fault_policy=FaultPolicy(max_bad_steps=3))
        np.testing.assert_allclose(losses, base_losses, rtol=2e-5,
                                   atol=2e-6)
        _assert_params_close(base_params, params)


# ==================================================== chaos acceptance
class TestOOMChaos:
    def test_oom_at_step3_completes_with_identical_params(self):
        """THE acceptance test: oom_at(step=3, n=2) -> the pass
        completes, the failed batch is re-run microbatched (2 OOM
        events, 8 -> 4 -> 2 rows), zero samples lost, and the final
        params equal the uninjected run's at the same effective batch
        size."""
        base_losses, base_params, _ = _run(_trainer(), _reader())

        tr = _trainer()
        before = global_counters.value("trainer/oom_events")
        with FaultPlan.oom_at(tr, step=3, n=2) as stats:
            losses, params, ooms = _run(tr, _reader(),
                                        microbatch="auto")
        assert stats["injected"] == 2
        assert global_counters.value("trainer/oom_events") == before + 2
        assert [e.kind for e in ooms] == ["oom", "oom"]
        assert [(e.microbatch, e.accum_steps) for e in ooms] == \
            [(4, 2), (2, 4)]
        # zero lost samples: every batch stepped exactly once
        assert len(losses) == len(base_losses) == 6
        np.testing.assert_allclose(losses, base_losses, rtol=2e-5,
                                   atol=2e-6)
        _assert_params_close(base_params, params)
        assert tr._memory_exec.plan.provenance == "adapted"

    def test_oom_at_floor_reraises(self):
        # 1-row microbatch still OOMs: the model genuinely does not
        # fit — absorbing that would be a lie, so it must re-raise
        tr = _trainer()
        with FaultPlan.memory_pressure(tr, max_rows=0):
            with pytest.raises(Exception, match="RESOURCE_EXHAUSTED"):
                _run(tr, _reader(), microbatch="auto")

    def test_non_oom_errors_pass_through(self):
        # the executor absorbs RESOURCE_EXHAUSTED and ONLY that (the
        # R7 contract): an injected ValueError must surface unchanged
        tr = _trainer()

        def bad_interceptor(k, mb):
            raise ValueError("not an OOM")

        tr._step_interceptor = bad_interceptor
        with pytest.raises(ValueError, match="not an OOM"):
            _run(tr, _reader(), microbatch="auto")

    def test_fixed_microbatch_under_pressure(self):
        # microbatch=N (configured) starts shrunk: no OOM at all when
        # N already fits the pressured device
        tr = _trainer()
        with FaultPlan.memory_pressure(tr, max_rows=4) as stats:
            losses, _, ooms = _run(tr, _reader(), microbatch=4)
        assert not ooms and stats["injected"] == 0
        assert len(losses) == 6
        assert tr._memory_exec.plan.provenance == "configured"


# ============================================================= probe
class TestWarmupProbe:
    def test_oom_probe_binary_search_under_pressure(self):
        # device "fits" 3 rows: the probe must land on microbatch<=3
        # BEFORE the pass, so the pass itself sees zero OOM events
        tr = _trainer()
        with FaultPlan.memory_pressure(tr, max_rows=3):
            losses, _, ooms = _run(tr, _reader(), microbatch="auto",
                                   oom_probe=True)
        assert not ooms                     # probe pre-discovered
        assert len(losses) == 6
        plan = tr._memory_exec.plan
        assert plan.provenance == "probe"
        assert plan.microbatch is not None and plan.microbatch <= 3

    def test_plan_memory_direct_mutates_nothing(self):
        tr = _trainer()
        before = {k: np.asarray(v).copy()
                  for k, v in tr.parameters.raw.items()}
        batch = next(iter(_reader()()))
        with FaultPlan.memory_pressure(tr, max_rows=3):
            plan = plan_memory(tr, batch)
        assert plan.provenance == "probe"
        assert plan.microbatch is not None and plan.microbatch <= 3
        # the probe ran on COPIES: training state untouched
        for k in before:
            np.testing.assert_array_equal(
                before[k], np.asarray(tr.parameters.raw[k]))
        assert tr._step_count == 0

    def test_probe_when_everything_fits_returns_full(self):
        tr = _trainer()
        plan = plan_memory(tr, next(iter(_reader()())))
        assert plan.provenance == "probe"
        assert plan.microbatch is None      # whole batch fits


# ======================================================== persistence
class TestPlanPersistence:
    def test_plan_rides_checkpoint_meta_and_resume_adopts(self,
                                                          tmp_path):
        from paddle_tpu.trainer.checkpoint import CheckpointManager
        ckpt = str(tmp_path / "ckpt")

        tr1 = _trainer()
        with FaultPlan.memory_pressure(tr1, max_rows=4):
            _, _, ooms1 = _run(tr1, _reader(), microbatch="auto",
                               checkpoint_dir=ckpt, checkpoint_period=1)
        assert len(ooms1) == 1              # 8 -> 4, once

        # the plan is in meta, readable WITHOUT the state payload
        meta = CheckpointManager(ckpt).peek_meta()
        assert meta["memory_plan"] == {"microbatch": 4,
                                       "accum_steps": 2,
                                       "provenance": "adapted"}

        # a resumed run adopts the plan from meta: no re-probe, no
        # re-discovery by OOM — zero new OOM events under the same
        # pressure, provenance says where the plan came from
        tr2 = _trainer()
        probe_fails = global_counters.value(
            "trainer/oom_probe_failures")
        with FaultPlan.memory_pressure(tr2, max_rows=4):
            with warnings.catch_warnings():
                warnings.simplefilter("ignore")
                ooms2 = []
                tr2.train(_reader(), num_passes=2,
                          event_handler=lambda e: ooms2.append(e)
                          if isinstance(e, paddle.event.OOMEvent)
                          else None,
                          checkpoint_dir=ckpt, checkpoint_period=1,
                          auto_resume=True, microbatch="auto",
                          oom_probe=True)
        assert not ooms2
        assert tr2._memory_exec.plan.provenance == "resumed"
        assert tr2._memory_exec.plan.microbatch == 4
        # oom_probe=True did NOT re-probe: a resumed plan always wins
        assert global_counters.value(
            "trainer/oom_probe_failures") == probe_fails

    @pytest.mark.chaos(timeout=240)
    def test_sigkill_after_oom_resumes_with_adapted_plan(self,
                                                         tmp_path):
        """Subprocess acceptance: SIGKILL the trainer AFTER its OOM
        adaptation; the relaunched worker must resume from checkpoint
        meta with the adapted plan (provenance 'resumed', zero new
        OOMs) and finish with params bit-identical to an uninterrupted
        injected run."""
        env = dict(os.environ)
        env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
        env["JAX_PLATFORMS"] = "cpu"
        worker = os.path.join(REPO, "tests", "oom_worker.py")

        def spawn(d):
            return subprocess.Popen(
                [sys.executable, worker, d, "1", "4", "0.05"],
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                text=True, env=env)

        # uninterrupted run under the same pressure = the golden digest
        golden = spawn(str(tmp_path / "golden"))
        gold_out, _ = golden.communicate(timeout=180)
        assert golden.returncode == 0, gold_out[-2000:]
        gold_line = [l for l in gold_out.splitlines()
                     if l.startswith("WORKER DONE")][-1]
        assert "ooms=1" in gold_line       # the adaptation happened

        # killed mid-pass, after the OOM (which hits at step 1)
        ckpt = str(tmp_path / "ckpt")
        victim = spawn(ckpt)
        died_at = FaultPlan.kill_at_marker(victim, step=3)
        assert died_at >= 3

        resumed = spawn(ckpt)
        out, _ = resumed.communicate(timeout=180)
        assert resumed.returncode == 0, out[-2000:]
        done = [l for l in out.splitlines()
                if l.startswith("WORKER DONE")][-1]
        # no re-discovery: the resumed process absorbed ZERO OOMs and
        # its plan came from checkpoint meta
        assert "ooms=0" in done, done
        assert "plan=resumed:4" in done, done
        # bit-identical finish vs. the uninterrupted injected run
        assert done.split("digest=")[1] == \
            gold_line.split("digest=")[1], (done, gold_line)


# ============================================================ serving
class _OOMForward:
    """A model whose forward 'fits' at most max_rows rows — bigger
    batches die with a realistic RESOURCE_EXHAUSTED."""

    def __init__(self, max_rows):
        self.max_rows = max_rows
        self.calls = 0

    def forward_batch(self, samples):
        self.calls += 1
        if len(samples) > self.max_rows:
            raise resource_exhausted_error(
                len(samples) << 20, where="fake forward")
        return [np.zeros((len(samples), 2), np.float32)]


@pytest.mark.chaos
class TestServingOOM:
    def _server(self, max_rows=2, **kw):
        from paddle_tpu.serving import CircuitBreaker, InferenceServer
        kw.setdefault("breaker", CircuitBreaker(
            window=4, failure_threshold=0.5, cooldown=60.0))
        return InferenceServer(_OOMForward(max_rows), max_queue=8,
                               workers=1, **kw).start()

    def _sample_rows(self, n, dim=8):
        return [(np.zeros(dim, np.float32),) for _ in range(n)]

    def test_oom_sheds_with_retry_after_not_breaker(self):
        from paddle_tpu.serving import Rejected
        srv = self._server(max_rows=2)
        try:
            # repeated oversized requests: every one sheds typed, the
            # breaker NEVER opens (capacity != poisoned model)
            for _ in range(3):
                with pytest.raises(Rejected) as ei:
                    srv.infer(self._sample_rows(8))
                assert ei.value.reason == "resource_exhausted"
                assert ei.value.retry_after > 0
            assert srv.breaker.state == "closed"
            # small requests keep being served throughout
            out = srv.infer(self._sample_rows(2))
            assert np.asarray(out).shape == (2, 2)
            st = srv.stats()
            assert st["oom_events"] >= 1
            assert st["served"] == 1 and st["failed"] == 0
        finally:
            srv.shutdown()

    def test_adaptive_limit_rejects_at_admission(self):
        from paddle_tpu.serving import Rejected
        srv = self._server(max_rows=2)
        try:
            with pytest.raises(Rejected):
                srv.infer(self._sample_rows(8))      # worker-side OOM
            assert srv.stats()["batch_limit"] == 4   # 8 // 2
            fwd_calls = srv._inf.calls
            # the NEXT oversized request never reaches the device
            with pytest.raises(Rejected) as ei:
                srv.submit(self._sample_rows(6))
            assert ei.value.reason == "resource_exhausted"
            assert srv._inf.calls == fwd_calls
            assert srv.stats()["rejected_oom"] >= 1
        finally:
            srv.shutdown()

    def test_max_batch_memory_admission_budget(self):
        from paddle_tpu.serving import Rejected
        # 8 f32 per row = 32 bytes; budget 100 bytes -> 3 rows fit,
        # 4 rows (128 bytes) reject at admission
        srv = self._server(max_rows=64, max_batch_memory=100)
        try:
            out = srv.infer(self._sample_rows(3))
            assert np.asarray(out).shape == (3, 2)
            with pytest.raises(Rejected) as ei:
                srv.submit(self._sample_rows(4))
            assert ei.value.reason == "resource_exhausted"
            assert "max_batch_memory" in str(ei.value)
        finally:
            srv.shutdown()
