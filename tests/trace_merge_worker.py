"""Subprocess worker for the cross-process trace-merge acceptance
(tests/test_trace_merge.py).

Simulates one host of a multi-host job: connects to the test's
coordinator, measures its clock offset over the RPC channel
(sync_clock -> journaled clock_sync record), then emits step journal
records + tracer spans under an INJECTED wall-clock skew — the
deterministic stand-in for two machines whose clocks disagree.
`paddle_tpu trace merge` must put both workers back on the
coordinator's time base.

argv: <coordinator_port> <journal_path> <trace_path> <host_name>
      <skew_s> <n_steps> <run_id> <go_file>
"""

import json
import os
import sys
import time


def main():
    port = int(sys.argv[1])
    journal_path = sys.argv[2]
    trace_path = sys.argv[3]
    host = sys.argv[4]
    skew = float(sys.argv[5])
    steps = int(sys.argv[6])
    run_id = sys.argv[7]
    go_file = sys.argv[8]

    # the injected skew: this process's wall clock reads `skew` seconds
    # ahead of true time — journal ts, tracer epoch and sync_clock's
    # local samples all see it, exactly like a drifted host
    real_time = time.time
    if skew:
        time.time = lambda: real_time() + skew

    import jax
    jax.config.update("jax_platforms", "cpu")
    from paddle_tpu.obs import context as obs_context
    from paddle_tpu.obs.events import JOURNAL
    from paddle_tpu.obs.trace import TRACER
    from paddle_tpu.trainer.coordinator import connect, sync_clock

    obs_context.set_host(host)
    obs_context.set_run_id(run_id)
    JOURNAL.configure(journal_path)
    conn = connect("127.0.0.1", port)
    offset = sync_clock(conn)         # journals the clock_sync record
    assert int(conn.epoch()) >= 0     # plain coordinator RPC traffic
    JOURNAL.emit("trainer", "run_start", worker=host)
    print("READY", flush=True)

    # barrier: both workers start stepping together so the TRUE
    # timelines interleave (the raw skewed ones will not)
    deadline = real_time() + 60
    while not os.path.exists(go_file):
        if real_time() > deadline:
            print("go-file timeout", file=sys.stderr)
            return 2
        time.sleep(0.01)

    TRACER.start(capture_compiles=False)
    for i in range(steps):
        obs_context.set_step(i)
        with TRACER.span("worker_step"):
            time.sleep(0.12)
        JOURNAL.emit("trainer", "step", step=i)
    TRACER.stop()
    TRACER.save(trace_path)
    JOURNAL.emit("trainer", "run_end", worker=host)
    JOURNAL.configure(None)
    print(json.dumps({"host": host, "measured_offset": offset}),
          flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
