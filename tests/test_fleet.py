"""Serving fleet v1 — router over N in-process replicas (ISSUE 15).

The acceptance contract pinned here: prefix-affinity keeps >=90% of
same-prefix requests on one replica and the prefix-cache warm ratio
survives the router hop; fleet admission rejects typed
(``fleet_kv_capacity``) only when NO replica could ever hold the
request; drain redirects new work and re-admits on resume/rejoin;
lease expiry is an implicit drain and a rejoin re-admits; a replica
torn mid-stream fails over to a sibling with a TOKEN-EXACT resumed
continuation and exactly-once settle. Subprocess SIGKILL chaos lives
in tests/test_fleet_faults.py.
"""

import json
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

import jax
import paddle_tpu as paddle
from paddle_tpu import models
from paddle_tpu.fleet import (AffinityIndex, FleetBalancer,
                              ReplicaRegistration, ReplicaRegistry,
                              Router, build_router_http_server,
                              rendezvous_choose, stable_prefix_key)
from paddle_tpu.fleet.router import _HopTorn, _Reroute
from paddle_tpu.obs.events import JOURNAL
from paddle_tpu.serving import (DecodeEngine, InferenceServer, Rejected,
                                ServerClosed, build_http_server)
from paddle_tpu.testing import FaultPlan
from paddle_tpu.trainer.coordinator import Coordinator

pytestmark = pytest.mark.chaos

DEC_CFG = dict(vocab_size=40, d_model=16, n_heads=2, n_layers=2,
               d_ff=32, max_len=32)
PAGE = 4


def tiny_decoder(seed=7):
    paddle.init(use_tpu=False, seed=0)
    from paddle_tpu.core.registry import reset_name_counters
    reset_name_counters()
    spec = models.transformer_lm(**DEC_CFG)
    costs = spec.cost if isinstance(spec.cost, list) else [spec.cost]
    topo = paddle.Topology(costs, extra_outputs=[spec.output])
    params = topo.init_params(jax.random.PRNGKey(seed))
    return models.TransformerDecoder(params, n_layers=DEC_CFG["n_layers"],
                                     n_heads=DEC_CFG["n_heads"])


class Replica:
    """One in-process serving replica: decode engine + HTTP front.
    Same weights (same seed) on every replica — greedy decode is then
    deterministic across the fleet, which is what makes mid-stream
    failover token-exact."""

    def __init__(self, rid, decoder=None, **engine_kw):
        self.rid = rid
        self.dec = decoder or tiny_decoder()
        kw = dict(num_slots=2, page_size=PAGE,
                  max_seq_len=DEC_CFG["max_len"])
        kw.update(engine_kw)
        self.engine = DecodeEngine(self.dec, **kw)
        self.server = InferenceServer(None, max_queue=8, workers=1,
                                      breaker=False,
                                      engine=self.engine).start()
        self.httpd = build_http_server(self.server, "127.0.0.1", 0)
        self.port = self.httpd.server_address[1]
        self.endpoint = f"http://127.0.0.1:{self.port}"
        self._t = threading.Thread(target=self.httpd.serve_forever,
                                   daemon=True,
                                   name=f"pt-test-replica-{rid}")
        self._t.start()
        self._killed = False

    def kill(self):
        """In-process SIGKILL twin: tear every live connection."""
        self._killed = True
        self.httpd.kill()

    def stop(self):
        if not self._killed:
            self.httpd.shutdown()
            self.httpd.server_close()
        self.server.shutdown(drain=True, timeout=30)


def fleet(n=2, router_kw=None, **engine_kw):
    reps = {f"r{i}": Replica(f"r{i}", **engine_kw) for i in range(n)}
    kw = dict(affinity="prefix", page_size=PAGE, scrape_interval=0.1,
              queue_timeout=2.0, queue_poll=0.02, drain_timeout=5.0)
    kw.update(router_kw or {})
    router = Router(endpoints={r.rid: r.endpoint
                               for r in reps.values()}, **kw)
    return reps, router


def stop_fleet(reps, router):
    router.shutdown(drain=True, timeout=10)
    for r in reps.values():
        r.stop()


def http_json(url, body=None, timeout=30):
    data = json.dumps(body).encode() if body is not None else None
    req = urllib.request.Request(
        url, data=data,
        headers={"Content-Type": "application/json"} if data else {})
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return json.loads(r.read()), dict(r.headers)


class TestAffinityIndex:
    def test_keying_mirrors_prefix_trie(self):
        idx = AffinityIndex(page_size=4)
        # final token is always a query: a 4-token prompt has NO
        # cacheable page (limit = len-1 = 3 < page_size)
        assert idx.observe([1, 2, 3, 4], "r0") == 0
        # 9 tokens -> two aligned pages under the cap
        assert idx.observe(list(range(9)), "r0") == 2
        rid, depth = idx.match(list(range(9)))
        assert (rid, depth) == ("r0", 2)
        # shared first page, divergent second -> depth-1 match
        rid, depth = idx.match([0, 1, 2, 3, 9, 9, 9, 9, 9])
        assert (rid, depth) == ("r0", 1)
        # unknown path
        assert idx.match([7, 7, 7, 7, 7, 7, 7, 7, 7]) == (None, 0)

    def test_forget_and_lru_bound(self):
        idx = AffinityIndex(page_size=2, max_nodes=4)
        idx.observe([1, 1, 1, 1, 1], "r0")       # 2 nodes
        idx.observe([2, 2, 2, 2, 2], "r1")       # +2 nodes = cap
        idx.observe([3, 3, 3, 3, 3], "r2")       # evicts the oldest
        assert idx.stats()["nodes"] == 4
        assert idx.match([1, 1, 1, 1, 1])[0] is None   # evicted
        assert idx.match([3, 3, 3, 3, 3]) == ("r2", 2)
        assert idx.forget("r2") == 2
        assert idx.match([3, 3, 3, 3, 3]) == (None, 0)


class TestBalancer:
    def _scraped(self, bal, rid, total, free, ps=4):
        bal.upsert(rid, f"http://x/{rid}")
        bal.record_scrape(rid, kv_pages_total=total, kv_pages_free=free,
                          page_size=ps)

    def test_choose_by_headroom_and_exclude(self):
        bal = FleetBalancer(affinity="load", page_size=4)
        assert bal.choose([1, 2], 8) == (None, 0)
        self._scraped(bal, "a", total=16, free=2)
        self._scraped(bal, "b", total=16, free=10)
        assert bal.choose([1, 2], 8)[0] == "b"         # most free pages
        assert bal.choose([1, 2], 8, exclude={"b"})[0] == "a"
        # 20 tokens = 5 pages: only b has the free headroom NOW
        assert bal.choose([1, 2], 20)[0] == "b"
        assert bal.choose([1, 2], 20, exclude={"b"}) == (None, 0)
        bal.mark_draining("b", True)
        assert bal.choose([1, 2], 8)[0] == "a"
        bal.mark_dead("a")
        assert bal.choose([1, 2], 8) == (None, 0)

    def test_feasible_anywhere_gates_typed_reject(self):
        bal = FleetBalancer(affinity="load", page_size=4)
        bal.upsert("a", "http://x/a")
        assert bal.feasible_anywhere(10_000)    # unscraped: can't prove
        self._scraped(bal, "a", total=8, free=0)
        assert bal.feasible_anywhere(32)        # 8 pages fit... someday
        assert not bal.feasible_anywhere(33)    # 9 pages NEVER fit
        self._scraped(bal, "b", total=16, free=16)
        assert bal.feasible_anywhere(33)        # a sibling could

    def test_feasible_anywhere_ignores_dead_replicas(self):
        # mark_dead keeps the scraped pool size around; a request only
        # ever feasible on the DEAD replica must reject typed
        # (fleet_kv_capacity) immediately, not queue for the full
        # queue_timeout and bounce as retryable queue_full
        bal = FleetBalancer(affinity="load", page_size=4)
        self._scraped(bal, "a", total=8, free=8)
        self._scraped(bal, "b", total=16, free=16)
        assert bal.feasible_anywhere(33)        # b: 9 pages fit
        bal.mark_dead("b")
        assert not bal.feasible_anywhere(33)    # only b could, b is gone
        assert bal.feasible_anywhere(32)        # a still can, someday

    def test_scrape_adopts_fleet_page_size_into_affinity_index(self):
        # a router left at --page_size 16 fronting page-4 engines would
        # never cut an affinity key for short prompts; the scrape must
        # re-key the index at the size the fleet actually agrees on
        bal = FleetBalancer(affinity="prefix", page_size=16)
        self._scraped(bal, "a", total=16, free=16, ps=4)
        self._scraped(bal, "b", total=16, free=16, ps=4)
        assert bal.index.page_size == 4
        prompt = list(range(9))                 # 2 page-4 keys, 0 page-16
        bal.observe_served(prompt, "a")
        assert bal.choose(prompt, 4)[0] == "a"  # affinity now bites
        # disagreeing sizes: keep the current keying (no thrash)
        self._scraped(bal, "b", total=16, free=16, ps=8)
        assert bal.index.page_size == 4
        # ...until the fleet converges again
        self._scraped(bal, "a", total=16, free=16, ps=8)
        assert bal.index.page_size == 8

    def test_scrape_counts_reclaimable_trie_pages_as_headroom(self):
        # after a prefix-heavy burst the engine's free LIST is ~empty
        # (the trie holds evictable pages), but the ENGINE would still
        # admit by evicting on demand. A router gating on the bare
        # free gauge livelocks: the trie only yields pages under the
        # dispatch pressure the gate withholds. The scrape must count
        # engine_kv_pages_reclaimable as placeable headroom.
        router = Router(endpoints={"a": "http://127.0.0.1:1"},
                        page_size=4, scrape_interval=3600.0)
        router._http_get_text = lambda ep, path: (
            "paddle_tpu_serving_engine_kv_pages_total 16\n"
            "paddle_tpu_serving_engine_kv_pages_free 0\n"
            "paddle_tpu_serving_engine_kv_pages_reclaimable 14\n"
            "paddle_tpu_serving_engine_page_size 4\n")
        router.refresh()
        router._scrape("a")
        st = router.balancer.get("a")
        assert st.kv_pages_free == 14
        assert router.balancer.choose([1, 2, 3], 8)[0] == "a"

    def test_affinity_advice_never_overrides_health(self):
        bal = FleetBalancer(affinity="prefix", page_size=4)
        self._scraped(bal, "a", total=16, free=16)
        self._scraped(bal, "b", total=16, free=16)
        toks = list(range(9))
        bal.observe_served(toks, "a")
        assert bal.choose(toks, 12) == ("a", 2)
        bal.mark_draining("a", True)
        rid, depth = bal.choose(toks, 12)
        assert rid == "b" and depth == 0        # advice, not a pin


class TestFleetRouting:
    def test_prefix_affinity_pins_and_warm_ratio_survives_hop(self):
        reps, router = fleet(2)
        try:
            router.refresh()
            shared = [1, 2, 3, 4, 5, 6, 7, 8, 9, 10, 11, 12]
            results = []
            for i in range(10):
                res = router.generate(shared + [13 + i], 2)
                results.append(res)
            homes = [res.replica_chain[-1] for res in results]
            pin = max(homes.count(h) for h in set(homes))
            # acceptance: >=90% of same-prefix requests on ONE replica
            assert pin >= 9, homes
            assert sum(r.affinity_hit for r in results) >= 9
            # warm ratio survives the router hop: replaying an exact
            # earlier prompt hits the home replica's prefix cache and
            # the hit count rides the fleet response
            warm = router.generate(shared + [13], 2)
            assert warm.replica_chain[-1] == homes[0]
            assert warm.prefix_hit_pages >= 1
            st = router.stats()
            assert st["settled"] == 11 and st["failovers"] == 0
            assert st["affinity_hits"] >= 9
        finally:
            stop_fleet(reps, router)

    def test_generate_matches_direct_decode(self):
        reps, router = fleet(1)
        try:
            router.refresh()
            prompt = [3, 1, 4, 1, 5]
            want = reps["r0"].dec.generate(
                np.asarray(prompt, "int32")[None, :],
                max_len=len(prompt) + 6)[0]
            streamed = []
            res = router.generate(prompt, 6, on_token=streamed.append)
            assert res.tokens == [int(t) for t in want]
            assert streamed == res.tokens       # live relay, same order
            assert res.hops == 1 and res.replica_chain == ["r0"]
        finally:
            stop_fleet(reps, router)

    def test_fleet_kv_capacity_is_typed_and_journaled(self):
        reps, router = fleet(2)
        try:
            router.refresh()
            total_pages = max(
                st.kv_pages_total
                for st in router.balancer.replicas().values())
            assert total_pages > 0              # the scrape landed
            too_big = (total_pages + 1) * PAGE
            with pytest.raises(Rejected) as ei:
                router.generate([1] * (too_big - 1), 1)
            assert ei.value.reason == "fleet_kv_capacity"
            assert ei.value.retry_after == 0.0
            assert router.stats()["rejected_kv_capacity"] == 1
            # a merely-large request is NOT bounced: it fits total
            res = router.generate([1] * 8, 2)
            assert len(res.tokens) == 2
        finally:
            stop_fleet(reps, router)

    def test_drain_redirects_then_readmit(self):
        reps, router = fleet(2)
        try:
            router.refresh()
            out = router.drain("r0")
            assert out["draining"] and out["settled"]
            # the mark mirrored to the replica's own admission plane
            health, _ = http_json(reps["r0"].endpoint + "/health")
            assert health["status"] == "draining"
            for i in range(4):
                res = router.generate([20 + i, 1, 2], 2)
                assert res.replica_chain == ["r1"], i
            assert router.health()["replicas_draining"] == 1
            # re-admit, drain the sibling: traffic swings back
            router.undrain("r0")
            router.drain("r1")
            res = router.generate([30, 1, 2], 2)
            assert res.replica_chain == ["r0"]
            health, _ = http_json(reps["r0"].endpoint + "/health")
            assert health["status"] == "ok"
            assert router.stats()["drains"] == 2
        finally:
            stop_fleet(reps, router)

    def test_router_shutdown_is_typed(self):
        reps, router = fleet(1)
        try:
            router.refresh()
            router.shutdown(drain=True)
            with pytest.raises(ServerClosed):
                router.generate([1, 2, 3], 2)
        finally:
            for r in reps.values():
                r.stop()


class TestMidStreamFailover:
    # Both replicas share this process's journal, and the victim's
    # serve thread emits its serving/hop torn terminal asynchronously —
    # it can land AFTER the sibling's hop start for the same trace_id,
    # closing the sibling's witness machine and orphaning its settle
    # (timing-dependent). Exactly-once is proven by the settle
    # counter/audit below, not the live witness.
    @pytest.mark.protocol_violation_expected
    def test_failover_resumes_token_exact(self):
        """The tentpole invariant, in-process: the victim's transport
        is torn after 2 streamed tokens; the router replays prompt +
        streamed tokens on the sibling and the settled stream is
        token-identical to an undisturbed solo decode. Exactly-once:
        one return, one settle counter, original trace_id on both
        hops."""
        reps, router = fleet(2)
        try:
            router.refresh()
            prompt = [5, 6, 7, 8, 9, 10, 11, 12]
            # prime affinity so the victim is deterministic
            first = router.generate(prompt, 2)
            victim = first.replica_chain[-1]
            sibling = ("r0", "r1")[victim == "r0"]
            want = reps[victim].dec.generate(
                np.asarray(prompt, "int32")[None, :],
                max_len=len(prompt) + 10)[0]
            # throttle the victim so the kill genuinely lands
            # MID-stream (a tiny decoder otherwise finishes before the
            # router has read its second line)
            reps[victim].engine._step_interceptor = \
                lambda s: time.sleep(0.02)
            streamed = []
            with FaultPlan.kill_replica(router, victim,
                                        reps[victim].kill,
                                        at=2) as chaos:
                res = router.generate(prompt, 10,
                                      on_token=streamed.append)
            assert chaos["fired"] == 1
            assert chaos["victim_traces"] == [res.trace_id]
            assert res.hops == 2
            assert res.replica_chain == [victim, sibling]
            # token-exact resume: greedy determinism makes the
            # sibling's continuation exactly what the victim owed
            assert res.tokens == [int(t) for t in want]
            assert streamed == res.tokens
            st = router.stats()
            assert st["failovers"] == 1
            assert st["settled_failover"] == 1
            assert st["settled"] == 2           # prime + failover
            # the dead replica is out of the fleet; its affinity
            # entries died with it
            assert not router.balancer.get(victim).live
            # exactly-once on the survivor too: its pool balances
            acc = reps[sibling].engine.page_accounting()
            assert acc["leaked"] == 0
            assert reps[sibling].engine.stats()["kv_pages_leaked"] == 0
        finally:
            router.shutdown(drain=True)
            reps[victim].server.shutdown(drain=False, timeout=10)
            reps[sibling].stop()

    def test_pre_dispatch_kill_fails_over_with_zero_streamed(self):
        reps, router = fleet(2)
        try:
            router.refresh()
            prompt = [21, 22, 23, 24]
            first = router.generate(prompt, 2)
            victim = first.replica_chain[-1]
            sibling = ("r0", "r1")[victim == "r0"]
            with FaultPlan.kill_replica(router, victim,
                                        reps[victim].kill,
                                        mid_stream=False) as chaos:
                res = router.generate(prompt, 4)
            assert chaos["fired"] == 1 and chaos["at_tokens"] == 0
            assert res.hops == 2
            assert res.replica_chain == [victim, sibling]
            assert len(res.tokens) == 4
        finally:
            router.shutdown(drain=True)
            reps[victim].server.shutdown(drain=False, timeout=10)
            reps[sibling].stop()


class TestFailoverSettleEdges:
    """Torn-stream boundary cases, pinned on a stubbed dispatch (no
    HTTP): a tear AFTER the last owed token (or after EOS) but BEFORE
    the done record must settle with the tokens already held — a
    sibling replay would ask for max_new_tokens=0 or generate past
    EOS, neither of which an undisturbed run can produce — and a
    decline storm must respect the queue_timeout bound."""

    @staticmethod
    def _stub_router(**kw):
        kwargs = dict(page_size=4, scrape_interval=3600.0,
                      queue_timeout=1.0, queue_poll=0.01)
        kwargs.update(kw)
        router = Router(endpoints={"a": "http://127.0.0.1:1",
                                   "b": "http://127.0.0.1:2"}, **kwargs)
        for rid in ("a", "b"):
            router.balancer.record_scrape(
                rid, kv_pages_total=16, kv_pages_free=16, page_size=4)
        return router

    def test_tear_after_final_token_settles_without_redispatch(self):
        router = self._stub_router()
        calls = []

        def torn_dispatch(st, prompt, remaining, eos_id, deadline_s,
                          trace_id, on_token, base_count):
            calls.append(st.replica_id)
            for t in (101, 102, 103):
                on_token(t)
            raise _HopTorn([101, 102, 103], "eof before done record")

        router._dispatch_stream = torn_dispatch
        streamed = []
        res = router.generate([1, 2, 3, 4], 3,
                              on_token=streamed.append)
        # settled exactly once, on the torn hop — NOT replayed on the
        # sibling with an empty remainder, NOT failed after max_hops
        assert calls == res.replica_chain and len(calls) == 1
        assert res.tokens == [101, 102, 103] == streamed
        assert res.hops == 1
        st = router.stats()
        assert st["settled"] == 1 and st["settled_failover"] == 1
        assert st["failovers"] == 1
        # the sibling was never marked dead by a cascading 0-token
        # replay failure
        assert router.balancer.get(calls[0]).live is False
        other = ("a", "b")[calls[0] == "a"]
        assert router.balancer.get(other).live is True
        router.shutdown(drain=False)

    def test_tear_after_eos_settles_without_redispatch(self):
        router = self._stub_router()
        calls = []

        def torn_dispatch(st, prompt, remaining, eos_id, deadline_s,
                          trace_id, on_token, base_count):
            calls.append(st.replica_id)
            raise _HopTorn([55, 7], "read: torn")

        router._dispatch_stream = torn_dispatch
        res = router.generate([1, 2, 3, 4], 10, eos_id=7)
        # the replay prompt would END with EOS; a sibling would keep
        # generating past it (the engine only stops on GENERATED
        # tokens) and hand the client tokens a clean run never yields
        assert len(calls) == 1
        assert res.tokens == [55, 7]
        assert res.hops == 1
        router.shutdown(drain=False)

    def test_reroute_storm_respects_queue_timeout(self):
        router = self._stub_router(queue_timeout=0.3, queue_poll=0.01)

        def declining_dispatch(st, prompt, remaining, eos_id,
                               deadline_s, trace_id, on_token,
                               base_count):
            raise _Reroute("replica_queue_full", exclude=False,
                           draining=False)

        router._dispatch_stream = declining_dispatch
        t0 = time.monotonic()
        with pytest.raises(Rejected) as ei:
            router.generate([1, 2, 3, 4], 2)
        # a replica stuck answering 429 while its scraped headroom
        # looks fine must not spin generate() forever
        assert ei.value.reason == "queue_full"
        assert ei.value.retry_after > 0
        assert time.monotonic() - t0 < 5.0
        assert router.stats()["rejected_queue_full"] == 1
        router.shutdown(drain=False)


class TestCoordinatorDiscovery:
    def test_join_lease_lapse_rejoin(self):
        """Directory-driven fleet: replicas join the membership plane,
        a paused heartbeat lapses the lease (implicit drain), and the
        resumed heartbeat re-joins and re-admits — no router config
        changes anywhere."""
        coord = Coordinator([], worker_lease_s=0.6)
        reps = {f"r{i}": Replica(f"r{i}") for i in range(2)}
        regs = {rid: ReplicaRegistration(
                    coord, rid, rep.endpoint,
                    heartbeat_s=0.15).join()
                for rid, rep in reps.items()}
        router = Router(coordinator=coord, page_size=PAGE,
                        queue_timeout=2.0, queue_poll=0.02)
        try:
            router.refresh()
            assert router.health()["replicas_live"] == 2
            with FaultPlan.lease_lapse(regs["r0"], wait_s=0.9):
                router.refresh()
                assert router.health()["replicas_live"] == 1
                # traffic keeps flowing on the survivor
                res = router.generate([1, 2, 3], 2)
                assert res.replica_chain == ["r1"]
            # heartbeats resumed: the next tick re-joins and the
            # router's next poll re-admits
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline:
                router.refresh()
                if router.health()["replicas_live"] == 2:
                    break
                time.sleep(0.05)
            assert router.health()["replicas_live"] == 2
            assert regs["r0"].rejoins >= 1
            assert router.stats()["rejoins"] >= 1
        finally:
            router.shutdown(drain=True)
            for reg in regs.values():
                reg.stop(leave=True)
            for rep in reps.values():
                rep.stop()

    def test_registry_reports_restart_as_rejoin(self):
        coord = Coordinator([], worker_lease_s=30.0)
        events = []
        reg = ReplicaRegistry(
            coordinator=coord,
            on_join=lambda v: events.append(("join", v.replica_id)),
            on_leave=lambda rid: events.append(("leave", rid)),
            on_rejoin=lambda v: events.append(("rejoin", v.replica_id)))
        a = ReplicaRegistration(coord, "a", "http://h:1",
                                heartbeat_s=60).join()
        reg.poll()
        assert events == [("join", "a")]
        # a restart in place: same worker id, fresh boot_id
        a.stop(leave=False)
        a2 = ReplicaRegistration(coord, "a", "http://h:2",
                                 heartbeat_s=60).join()
        reg.poll()
        assert events[-1] == ("rejoin", "a")
        assert reg.view()["a"].endpoint == "http://h:2"
        a2.stop(leave=True)
        reg.poll()
        assert events[-1] == ("leave", "a")


class TestFleetHTTP:
    def test_router_endpoints_and_metrics(self):
        reps, router = fleet(1)
        httpd = build_router_http_server(router, "127.0.0.1", 0)
        port = httpd.server_address[1]
        t = threading.Thread(target=httpd.serve_forever, daemon=True,
                             name="pt-test-router-httpd")
        t.start()
        base = f"http://127.0.0.1:{port}"
        try:
            router.refresh()
            body, headers = http_json(
                base + "/generate",
                {"prompt": [1, 2, 3], "max_new_tokens": 3,
                 "trace_id": "fleet-http-1"})
            assert len(body["tokens"]) == 3
            assert body["trace_id"] == "fleet-http-1"
            assert headers["X-Trace-Id"] == "fleet-http-1"
            assert body["hops"] == 1 and body["replica_chain"] == ["r0"]
            health, _ = http_json(base + "/health")
            assert health["status"] == "ok"
            with urllib.request.urlopen(base + "/metrics",
                                        timeout=10) as r:
                text = r.read().decode()
            assert "# TYPE paddle_tpu_fleet_routed counter" in text
            assert "paddle_tpu_fleet_routed 1" in text
            assert "# TYPE paddle_tpu_fleet_replicas_live gauge" in text
            assert "paddle_tpu_fleet_kv_pages_total" in text
            # admin drain over HTTP, then 404 for a ghost replica
            out, _ = http_json(base + "/admin/drain", {"replica": "r0"})
            assert out["draining"] is True
            try:
                http_json(base + "/admin/drain", {"replica": "ghost"})
                assert False, "expected HTTPError"
            except urllib.error.HTTPError as e:
                assert e.code == 404
        finally:
            httpd.shutdown()
            httpd.server_close()
            stop_fleet(reps, router)

    def test_replica_identity_rides_health_and_metrics(self):
        rep = Replica("solo")
        try:
            health, _ = http_json(rep.endpoint + "/health")
            ident = health["replica"]
            assert ident["endpoint"].startswith("http://127.0.0.1:")
            assert ident["run_id"] and ident["host"]
            with urllib.request.urlopen(rep.endpoint + "/metrics",
                                        timeout=10) as r:
                text = r.read().decode()
            line = next(l for l in text.splitlines()
                        if l.startswith(
                            "paddle_tpu_serving_replica_info{"))
            assert f'run_id="{ident["run_id"]}"' in line
            assert f'endpoint="{ident["endpoint"]}"' in line
            assert f'host="{ident["host"]}"' in line
            assert line.endswith(" 1")
        finally:
            rep.stop()


class TestCoordinatorOutage:
    """ISSUE 16 satellite: coordinator unreachable is the ROUTER
    blind, not the replicas dead. The registry serves its last-known
    view (bounded by max_stale_s) and traffic keeps flowing."""

    def test_registry_keeps_last_known_view_no_mass_leave(self):
        coord = Coordinator([], worker_lease_s=30.0)
        leaves = []
        reg = ReplicaRegistry(coordinator=coord,
                              on_leave=leaves.append)
        a = ReplicaRegistration(coord, "a", "http://h:1",
                                heartbeat_s=60).join()
        reg.poll()
        assert set(reg.view()) == {"a"}
        seq0 = JOURNAL.last_seq
        with FaultPlan.coordinator_outage(reg):
            for _ in range(3):
                reg.poll()
            # the last-known view SURVIVES — no leave storm
            assert set(reg.view()) == {"a"}
            assert leaves == []
            assert reg.staleness() > 0.0
            assert reg.stale_polls >= 3
        stale = JOURNAL.tail(50, domain="fleet", kind="stale_view",
                             since_seq=seq0)
        assert len(stale) == 1     # once on entry, not per poll
        assert stale[0]["replicas"] == 1
        reg.poll()                 # coordinator is back
        assert reg.staleness() == 0.0
        rec = JOURNAL.tail(50, domain="fleet", kind="view_recovered",
                           since_seq=seq0)
        assert rec and rec[-1]["stale_s"] >= 0
        a.stop(leave=True)

    def test_staleness_bound_expires_view_and_fires_leaves(self):
        coord = Coordinator([], worker_lease_s=30.0)
        leaves = []
        reg = ReplicaRegistry(coordinator=coord,
                              on_leave=leaves.append,
                              max_stale_s=0.05)
        a = ReplicaRegistration(coord, "a", "http://h:1",
                                heartbeat_s=60).join()
        reg.poll()
        seq0 = JOURNAL.last_seq
        with FaultPlan.coordinator_outage(reg, for_s=0.12):
            reg.poll()             # enters staleness
            time.sleep(0.08)
            reg.poll()             # past the bound: the lie ends
            assert reg.view() == {}
            assert leaves == ["a"]
        exp = JOURNAL.tail(50, domain="fleet",
                           kind="stale_view_expired", since_seq=seq0)
        assert exp and exp[-1]["dropped"] == ["a"]
        a.stop(leave=True)

    def test_static_registry_rejects_the_fault(self):
        reg = ReplicaRegistry(endpoints={"r0": "http://h:1"})
        with pytest.raises(ValueError):
            with FaultPlan.coordinator_outage(reg):
                pass

    def test_router_serves_through_outage_with_zero_sheds(self):
        """The acceptance shape: coordinator dark for >= 2x the poll
        interval mid-burst — ZERO sheds, traffic flows on the stale
        view, and the staleness gauge is visible while dark."""
        coord = Coordinator([], worker_lease_s=30.0)
        reps = {f"r{i}": Replica(f"r{i}") for i in range(2)}
        regs = {rid: ReplicaRegistration(coord, rid, rep.endpoint,
                                         heartbeat_s=60).join()
                for rid, rep in reps.items()}
        router = Router(coordinator=coord, page_size=PAGE,
                        scrape_interval=0.1, queue_timeout=2.0,
                        queue_poll=0.02).start()
        try:
            deadline = time.monotonic() + 5
            while time.monotonic() < deadline and \
                    router.stats()["replicas_live"] < 2:
                time.sleep(0.05)
            before = router.stats()
            stale_seen = []
            with FaultPlan.coordinator_outage(router, for_s=0.25):

                def one(i):
                    res = router.generate([1 + i % 5, 2, 3], 3)
                    assert len(res.tokens) == 3
                    return res
                results, errors = FaultPlan.burst(one, n=8, threads=4,
                                                  timeout=60)
                assert [e for e in errors if e] == []
                assert sum(r is not None for r in results) == 8
                stale_seen.append(router.stats()["registry_stale_s"])
            assert stale_seen[0] > 0.0    # gauge visible while dark
            after = router.stats()
            for k in ("rejected_queue_full", "rejected_kv_capacity",
                      "rejected_no_replica"):
                assert after[k] == before[k], k   # ZERO sheds
            assert after["replicas_live"] == 2
            deadline = time.monotonic() + 5
            while time.monotonic() < deadline and \
                    router.stats()["registry_stale_s"] > 0:
                time.sleep(0.05)
            assert router.stats()["registry_stale_s"] == 0.0
        finally:
            router.shutdown(drain=True, timeout=10)
            for reg in regs.values():
                reg.stop(leave=True)
            for rep in reps.values():
                rep.stop()


class TestRendezvousHA:
    """ISSUE 16 tentpole leg (c): N routers agree on placement with no
    shared state — rendezvous hashing over the stable first-page key
    is a pure function of (prompt, live membership)."""

    def test_stable_prefix_key_is_deterministic_and_bounded(self):
        toks = list(range(1, 10))
        assert stable_prefix_key(toks, 4) == stable_prefix_key(toks, 4)
        # only the FIRST page matters — and a change inside it moves
        # the key; a change past it does not
        assert stable_prefix_key([99] + toks[1:], 4) != \
            stable_prefix_key(toks, 4)
        assert stable_prefix_key(toks[:5] + [99] + toks[6:], 4) == \
            stable_prefix_key(toks, 4)
        # final token is always a query: too-short prompts have no
        # cacheable first page, hence no stable key
        assert stable_prefix_key([1, 2, 3, 4], 4) is None
        assert stable_prefix_key([], 4) is None

    def test_rendezvous_choose_is_permutation_invariant(self):
        rids = ["r0", "r1", "r2", "r3"]
        for key in (f"k{i}".encode() for i in range(20)):
            a = rendezvous_choose(key, rids)
            b = rendezvous_choose(key, reversed(rids))
            assert a == b
        assert rendezvous_choose(b"k", []) is None
        # spreads: 50 keys should not all land on one replica
        homes = {rendezvous_choose(f"key-{i}".encode(), rids)
                 for i in range(50)}
        assert len(homes) >= 3

    def test_two_independent_routers_agree_on_placement(self):
        """Two balancer planes fed the same membership (but NO shared
        learned state) must route the same cold prompt to the same
        home — the property that lets a client retry on a sibling
        router without re-priming the prefix cache."""
        import random
        planes = []
        for _ in range(2):
            b = FleetBalancer(affinity="prefix", page_size=PAGE)
            for i in range(3):
                b.upsert(f"r{i}", f"http://h:{i}")
                b.record_scrape(f"r{i}", kv_pages_total=64,
                                kv_pages_free=64, page_size=PAGE)
            planes.append(b)
        rng = random.Random(11)
        agree = total = 0
        for _ in range(40):
            prompt = [rng.randrange(2, 40)
                      for _ in range(rng.randrange(6, 20))]
            picks = [b.choose(prompt, len(prompt) + 4)[0]
                     for b in planes]
            total += 1
            agree += int(picks[0] == picks[1])
        assert agree / total >= 0.9, (agree, total)
