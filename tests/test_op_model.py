"""paddle.v2.op operator sugar + paddle.v2.model save/load parity.

Reference: python/paddle/v2/op.py (unary math ops, LayerOutput operator
overloads) and python/paddle/v2/model.py (cloud-aware save_model with the
master's save election, local load_model).
"""

import os

import jax
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.core.topology import Topology

L = paddle.layer
op = paddle.op


def run(out, feed, seed=0):
    topo = Topology(out)
    params = topo.init_params(jax.random.PRNGKey(seed))
    outs, _ = topo.forward(params, topo.init_state(), feed, mode="test",
                           rng=jax.random.PRNGKey(seed + 1))
    return np.asarray(outs[out.name])


def dense(name, width):
    return L.data(name, paddle.data_type.dense_vector(width))


class TestUnaryMathOps:
    @pytest.mark.parametrize("fn,ref", [
        ("exp", np.exp), ("log", np.log), ("abs", np.abs),
        ("sigmoid", lambda v: 1 / (1 + np.exp(-v))),
        ("tanh", np.tanh), ("square", np.square), ("sqrt", np.sqrt),
        ("relu", lambda v: np.maximum(v, 0)),
        ("reciprocal", lambda v: 1 / v),
    ])
    def test_elementwise(self, fn, ref):
        v = np.array([[0.5, 1.0, 2.0, 3.5]], np.float32)
        got = run(getattr(op, fn)(dense("x", 4)), {"x": v})
        np.testing.assert_allclose(got, ref(v), rtol=1e-5, atol=1e-6)

    def test_softmax(self):
        v = np.array([[0.0, 1.0, 2.0, 3.0]], np.float32)
        got = run(op.softmax(dense("x", 4)), {"x": v})
        e = np.exp(v - v.max())
        np.testing.assert_allclose(got, e / e.sum(), rtol=1e-5)


class TestLayerOperators:
    def setup_method(self):
        self.av = np.array([[1.0, -2.0, 3.0]], np.float32)
        self.bv = np.array([[0.5, 4.0, -1.0]], np.float32)

    def test_add_layers(self):
        got = run(dense("a", 3) + dense("b", 3),
                  {"a": self.av, "b": self.bv})
        np.testing.assert_allclose(got, self.av + self.bv, rtol=1e-6)

    def test_add_scalar_both_sides(self):
        a = dense("a", 3)
        np.testing.assert_allclose(run(a + 2.5, {"a": self.av}),
                                   self.av + 2.5, rtol=1e-6)
        np.testing.assert_allclose(run(1.5 + dense("a2", 3),
                                       {"a2": self.av}),
                                   self.av + 1.5, rtol=1e-6)

    def test_sub_scalar_is_corrected(self):
        # the reference ADDS the constant here (op.py:89); we subtract
        got = run(dense("a", 3) - 2.0, {"a": self.av})
        np.testing.assert_allclose(got, self.av - 2.0, rtol=1e-6)

    def test_sub_and_rsub(self):
        np.testing.assert_allclose(
            run(dense("a", 3) - dense("b", 3), {"a": self.av, "b": self.bv}),
            self.av - self.bv, rtol=1e-6)
        np.testing.assert_allclose(
            run(3.0 - dense("a", 3), {"a": self.av}),
            3.0 - self.av, rtol=1e-6)

    def test_neg(self):
        np.testing.assert_allclose(run(-dense("a", 3), {"a": self.av}),
                                   -self.av, rtol=1e-6)

    def test_mul_scalar(self):
        np.testing.assert_allclose(run(dense("a", 3) * 0.5, {"a": self.av}),
                                   self.av * 0.5, rtol=1e-6)
        np.testing.assert_allclose(run(-2.0 * dense("a2", 3),
                                       {"a2": self.av}),
                                   self.av * -2.0, rtol=1e-6)

    def test_mul_by_size1_layer(self):
        w = np.array([[3.0]], np.float32)
        got = run(dense("a", 3) * dense("w", 1),
                  {"a": self.av, "w": w})
        np.testing.assert_allclose(got, self.av * 3.0, rtol=1e-6)

    def test_broadcast_add_size1(self):
        w = np.array([[0.25]], np.float32)
        got = run(dense("a", 3) + dense("w", 1),
                  {"a": self.av, "w": w})
        np.testing.assert_allclose(got, self.av + 0.25, rtol=1e-6)

    def test_mismatched_sizes_raise(self):
        with pytest.raises(TypeError):
            dense("a", 3) + dense("b", 4)
        with pytest.raises(TypeError):
            dense("a2", 3) * dense("b2", 4)
        with pytest.raises(TypeError):
            dense("a3", 3) + "nope"


class TestReferenceOpChain:
    def test_full_chain_builds_and_runs(self):
        """The reference's OpTest.test_op chain (v2/tests/test_op.py:22)
        — every unary op and operator form in one expression graph; the
        reference only parses it, here it also executes."""
        xv = np.array([[0.3, 1.2, 2.1, 0.7]], np.float32)
        zv = np.array([[2.0]], np.float32)
        x = dense("data", 4)
        for fn in (op.exp, op.sqrt, op.reciprocal, op.log, op.abs,
                   op.sigmoid, op.tanh, op.square, op.relu):
            x = fn(x)
        y = 1 + x
        y = y + 1
        y = x + y
        y = y - x
        y = y - 2
        y = 2 - y
        y = 2 * y
        y = y * 3
        z = dense("data_2", 1)
        y = y * z
        y = z * y
        y = y + z
        y = z + y
        got = run(y, {"data": xv, "data_2": zv})

        v = xv
        for f in (np.exp, np.sqrt, lambda a: 1 / a, np.log, np.abs,
                  lambda a: 1 / (1 + np.exp(-a)), np.tanh, np.square,
                  lambda a: np.maximum(a, 0)):
            v = f(v)
        w = 1 + v
        w = w + 1
        w = v + w
        w = w - v
        w = w - 2
        w = 2 - w
        w = 2 * w
        w = w * 3
        w = w * zv
        w = zv * w
        w = w + zv
        w = zv + w
        assert got.shape == (1, 4)
        np.testing.assert_allclose(got, w, rtol=1e-5, atol=1e-6)


def _tiny_params(seed=0):
    from paddle_tpu.core.registry import reset_name_counters
    reset_name_counters()
    x = dense("x", 4)
    out = L.fc(x, size=2, name="fc_out")
    topo = Topology(out)
    return paddle.Parameters(topo.init_params(jax.random.PRNGKey(seed)))


class TestModelSaveLoad:
    def test_local_round_trip(self, tmp_path):
        params = _tiny_params(seed=0)
        path = str(tmp_path / "sub" / "model.tar")
        assert paddle.model.save_model(params, path) is True
        fresh = _tiny_params(seed=7)   # different init: load must change it
        name = sorted(params.names())[0]
        before = np.asarray(fresh[name]).copy()
        paddle.model.load_model(fresh, path)
        assert not np.array_equal(before, np.asarray(fresh[name]))
        np.testing.assert_array_equal(np.asarray(fresh[name]),
                                      np.asarray(params[name]))
        assert sorted(fresh.names()) == sorted(params.names())

    def test_save_election_single_winner(self, tmp_path, monkeypatch):
        from paddle_tpu.trainer.coordinator import (Coordinator,
                                                    CoordinatorServer)
        coord = Coordinator(chunks=["c0"])
        server = CoordinatorServer(coord).start()
        monkeypatch.setenv("PADDLE_TPU_COORDINATOR",
                           f"127.0.0.1:{server.port}")
        try:
            params = _tiny_params()
            # three DISTINCT trainers race (save_model forwards the
            # process trainer_id; vary it to simulate three processes)
            wins = []
            for tid in ("tr-A", "tr-B", "tr-C"):
                monkeypatch.setattr(paddle.model, "trainer_id", tid)
                wins.append(paddle.model.save_model(params, str(tmp_path),
                                                    epoch=1))
            assert wins.count(True) == 1
            # the WINNER re-requesting is re-granted (service.go:474
            # TrainerID == savingTrainer), a loser stays denied
            winner = ("tr-A", "tr-B", "tr-C")[wins.index(True)]
            monkeypatch.setattr(paddle.model, "trainer_id", winner)
            assert paddle.model.save_model(params, str(tmp_path),
                                           epoch=1) is True
            # reference-style call with NO epoch: the server-side time
            # window (service.go RequestSaveModel duration) dedups —
            # still exactly one winner, resolved under the save lock
            wins = []
            for tid in ("tr-D", "tr-E", "tr-F"):
                monkeypatch.setattr(paddle.model, "trainer_id", tid)
                wins.append(paddle.model.save_model(params,
                                                    str(tmp_path / "w")))
            assert wins.count(True) == 1
            # each winner wrote under <path>/<trainer_id>/model.tar
            saved = [os.path.join(r, f) for r, _, fs in os.walk(tmp_path)
                     for f in fs]
            assert len(saved) == 2 and all(p.endswith("model.tar")
                                           for p in saved)
            fresh = _tiny_params()
            paddle.model.load_model(fresh, saved[0])
        finally:
            server.stop()
