"""Unified observability subsystem (paddle_tpu/obs) — acceptance suite.

Covers the ISSUE-7 contract: Prometheus exposition conformance
(HELP/TYPE lines, label escaping, histogram bucket monotonicity),
event-journal schema round-trip for every existing event class, the
trace-export smoke test (spans nest, compile events attach), the
standalone /metrics + /events endpoints, counter hygiene under
threads, and THE chaos acceptance: a run with injected data faults /
OOM / engine preemptions produces a schema-valid JSONL journal
capturing every injected fault.
"""

import json
import threading
import urllib.request
import warnings

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.obs import events as obs_events
from paddle_tpu.obs import metrics as obs_metrics
from paddle_tpu.obs import trace as obs_trace
from paddle_tpu.obs.events import (JOURNAL, EventJournal, read_journal,
                                   validate)
from paddle_tpu.obs.httpd import start_obs_server
from paddle_tpu.obs.metrics import (REGISTRY, MetricsRegistry,
                                    stats_families)
from paddle_tpu.trainer.event import (DataFaultEvent, FaultEvent,
                                      OOMEvent)
from paddle_tpu.utils.stats import global_counters, global_stat


# ------------------------------------------------------------- registry

class TestRegistry:
    def test_counter_gauge_histogram_semantics(self):
        r = MetricsRegistry()
        c = r.counter("t_total", "help")
        c.inc()
        c.inc(2)
        assert c.value() == 3
        with pytest.raises(ValueError):
            c.inc(-1)
        g = r.gauge("t_gauge")
        g.set(5)
        g.dec(2)
        assert g.value() == 3
        h = r.histogram("t_hist", buckets=(0.1, 1.0))
        h.observe(0.05)
        h.observe(0.5)
        h.observe(5.0)
        counts, total, n = h.labels().snapshot()
        assert counts == [1, 2] and n == 3
        assert total == pytest.approx(5.55)

    def test_labels_and_registration_conflicts(self):
        r = MetricsRegistry()
        c = r.counter("t_total", labelnames=("who",))
        c.labels(who="a").inc()
        c.labels(who="b").inc(4)
        assert c.value(who="b") == 4
        # idempotent re-registration returns the same family
        assert r.counter("t_total", labelnames=("who",)) is c
        with pytest.raises(ValueError):
            r.gauge("t_total")                # kind conflict
        with pytest.raises(ValueError):
            c.labels(nope="x")                # wrong label schema
        with pytest.raises(ValueError):
            r.counter("bad name")             # invalid metric name

    def test_counter_thread_safety_exact(self):
        r = MetricsRegistry()
        c = r.counter("t_total")
        n_threads, per = 8, 500

        def work():
            for _ in range(per):
                c.inc()

        ts = [threading.Thread(target=work, name=f"pt-test-m{i}")
              for i in range(n_threads)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        assert c.value() == n_threads * per

    def test_utils_stats_counterset_thread_safety(self):
        """The counter-hygiene satellite: global counters must count
        EXACTLY under the pt-serve/pt-data style worker pools."""
        n_threads, per = 8, 400

        def work():
            for _ in range(per):
                global_counters.bump("obs-test/bump")
                with warnings.catch_warnings():
                    warnings.simplefilter("ignore")
                    global_stat.get("obs-test/timer").add(0.001)

        ts = [threading.Thread(target=work, name=f"pt-test-s{i}")
              for i in range(n_threads)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        assert global_counters.value("obs-test/bump") == n_threads * per
        count, total, _ = global_stat.get("obs-test/timer").snapshot()
        assert count == n_threads * per
        assert total == pytest.approx(0.001 * n_threads * per)


# ----------------------------------------------- exposition conformance

def _parse_exposition(text):
    """{name: (kind, help)} for TYPE/HELP lines + [(name, labels-str,
    value)] samples, asserting basic line shape along the way."""
    types, helps, samples = {}, {}, []
    for line in text.strip().splitlines():
        if line.startswith("# TYPE "):
            _, _, name, kind = line.split(" ", 3)
            types[name] = kind
        elif line.startswith("# HELP "):
            _, _, name, h = line.split(" ", 3)
            helps[name] = h
        else:
            head, _, val = line.rpartition(" ")
            assert head, f"malformed sample line {line!r}"
            name, brace, labels = head.partition("{")
            samples.append((name, brace + labels, float(val)))
    return types, helps, samples


class TestExpositionConformance:
    def test_help_type_and_line_shape(self):
        r = MetricsRegistry()
        r.counter("t_total", "a counter").inc(2)
        r.gauge("t_gauge", "a gauge").set(1.5)
        r.histogram("t_hist", "a histogram", buckets=(0.1,)).observe(0.05)
        types, helps, samples = _parse_exposition(r.exposition())
        assert types == {"t_total": "counter", "t_gauge": "gauge",
                         "t_hist": "histogram"}
        assert helps["t_total"] == "a counter"
        names = [s[0] for s in samples]
        assert "t_hist_bucket" in names and "t_hist_sum" in names \
            and "t_hist_count" in names

    def test_label_escaping_round_trip(self):
        r = MetricsRegistry()
        nasty = 'quo"te\\slash\nnewline'
        r.counter("t_total", labelnames=("name",)) \
            .labels(name=nasty).inc()
        text = r.exposition()
        line = [l for l in text.splitlines()
                if l.startswith("t_total{")][0]
        assert '\\"' in line and "\\n" in line and "\\\\" in line
        assert "\n" not in line[:-1]          # literal newline escaped

    def test_histogram_bucket_monotonicity_and_inf(self):
        r = MetricsRegistry()
        h = r.histogram("t_hist", buckets=(0.01, 0.1, 1.0, 10.0))
        rng = np.random.RandomState(0)
        for v in rng.exponential(0.5, size=200):
            h.observe(float(v))
        _, _, samples = _parse_exposition(r.exposition())
        buckets = [(lab, v) for name, lab, v in samples
                   if name == "t_hist_bucket"]
        counts = [v for _, v in buckets]
        assert counts == sorted(counts), "buckets must be cumulative"
        assert buckets[-1][0] == '{le="+Inf"}'
        count = [v for name, _, v in samples if name == "t_hist_count"]
        assert count == [200.0] and buckets[-1][1] == 200.0

    def test_stats_families_pinned_serving_names(self):
        """The PR-6 flattening contract: nested dicts recurse with an
        underscored prefix, counter keys keep counter semantics,
        non-numeric leaves are skipped."""
        fams = stats_families(
            "paddle_tpu_serving",
            {"served": 3, "engine": {"kv_pages_free": 5},
             "breaker": None, "ok": True},
            counter_keys={"served"})
        flat = {f.name: (f.kind, f.samples()[0][2]) for f in fams}
        assert flat == {
            "paddle_tpu_serving_served": ("counter", 3.0),
            "paddle_tpu_serving_engine_kv_pages_free": ("gauge", 5.0)}

    def test_global_registry_bridges_stats_domains(self):
        """One scrape sees trainer, data-pipeline, fault and
        decode-engine domains through the utils/stats bridge."""
        for name in ("trainer/steps", "pipeline/quarantined",
                     "trainer/oom_events", "serving/decode_tokens"):
            global_counters.bump(name)
        text = REGISTRY.exposition()
        for name in ("trainer/steps", "pipeline/quarantined",
                     "trainer/oom_events", "serving/decode_tokens"):
            assert f'paddle_tpu_counter_total{{name="{name}"}} 1' \
                in text


# ----------------------------------------------------------- event journal

class TestEventJournal:
    def test_required_schema_fields(self):
        rec = obs_events.emit("test", "ping", detail=1)
        validate(rec)
        assert rec["v"] == obs_events.SCHEMA_VERSION
        assert rec["domain"] == "test" and rec["kind"] == "ping"
        with pytest.raises(ValueError):
            validate({"v": 1, "domain": "x"})
        with pytest.raises(ValueError):
            validate({**rec, "v": 99})
        with pytest.raises(ValueError):
            validate({**rec, "kind": ""})

    def test_round_trip_all_event_classes(self, tmp_path):
        """Every existing event class lands in the journal with its
        canonical (domain, kind) and survives the JSONL round trip."""
        path = str(tmp_path / "events.jsonl")
        j = EventJournal()
        j.configure(path)
        j.emit_event(FaultEvent(0, 3, "nonfinite", 2, None))
        j.emit_event(FaultEvent(0, 7, "rollback", 3, 40))
        j.emit_event(OOMEvent(1, 2, microbatch=4, accum_steps=2,
                              error=RuntimeError("RESOURCE_EXHAUSTED")))
        j.emit_event(DataFaultEvent("source_stall", 2, where="src"))
        j.emit_event(DataFaultEvent("worker_restart", 1,
                                    error=ValueError("boom")))
        j.emit("serving", "shed", reason="queue_full")
        j.emit("engine", "preemption", generated=5)
        j.emit("checkpoint", "save", step=10, path="/tmp/x")
        j.configure(None)
        recs = list(read_journal(path))
        assert len(recs) == 8
        assert [r["seq"] for r in recs] == list(range(1, 9))
        by_kind = {(r["domain"], r["kind"]): r for r in recs}
        assert by_kind[("trainer", "rollback")]["restored_step"] == 40
        assert by_kind[("trainer", "oom")]["microbatch"] == 4
        assert "RESOURCE_EXHAUSTED" in by_kind[("trainer",
                                                "oom")]["error"]
        assert by_kind[("data", "source_stall")]["count"] == 2
        assert by_kind[("data", "worker_restart")]["error"] \
            == repr(ValueError("boom"))
        assert by_kind[("serving", "shed")]["reason"] == "queue_full"
        assert by_kind[("engine", "preemption")]["generated"] == 5
        assert by_kind[("checkpoint", "save")]["step"] == 10

    def test_ring_tail_filters(self):
        j = EventJournal(ring_size=4)
        for i in range(6):
            j.emit("a" if i % 2 else "b", f"k{i}")
        recs = j.tail()
        assert len(recs) == 4                 # ring bound
        assert [r["seq"] for r in recs] == [3, 4, 5, 6]
        assert [r["kind"] for r in j.tail(domain="a")] == ["k3", "k5"]
        assert [r["kind"] for r in j.tail(kind="k4")] == ["k4"]

    def test_torn_final_line_skipped(self, tmp_path):
        path = str(tmp_path / "events.jsonl")
        j = EventJournal()
        j.configure(path)
        j.emit("test", "ok")
        j.configure(None)
        with open(path, "a") as f:
            f.write('{"v": 1, "truncat')       # the crash mid-write
        recs = list(read_journal(path))
        assert len(recs) == 1 and recs[0]["kind"] == "ok"
        # a malformed MIDDLE line is a real corruption -> strict raises
        with open(path, "a") as f:
            f.write('\ngarbage\n{"also": "bad"}\n')
        with pytest.raises(ValueError):
            list(read_journal(path))

    def test_non_serializable_fields_reprd(self):
        rec = obs_events.emit("test", "odd", obj=object())
        assert isinstance(rec["obj"], str) and "object" in rec["obj"]
        json.dumps(rec)                        # always serializable

    def test_since_seq_cursor_pages_forward(self):
        """ISSUE-8 satellite: ?since_seq= pages the ring without
        re-reading from the start — oldest-first after the cursor."""
        j = EventJournal(ring_size=100)
        for i in range(10):
            j.emit("t", f"k{i}")
        assert j.last_seq == 10
        page = j.tail(3, since_seq=4)
        assert [r["seq"] for r in page] == [5, 6, 7]   # oldest first
        page = j.tail(100, since_seq=page[-1]["seq"])
        assert [r["seq"] for r in page] == [8, 9, 10]
        assert j.tail(5, since_seq=10) == []           # caught up
        # filters compose with the cursor
        assert [r["seq"] for r in j.tail(100, kind="k8",
                                         since_seq=0)] == [9]

    def test_tail_and_read_journal_filter_parity(self, tmp_path):
        """ISSUE-8 satellite: the ring's tail() filters and the file's
        read_journal() filters agree — same records, same order — on
        the same domain/kind queries."""
        path = str(tmp_path / "parity.jsonl")
        j = EventJournal(ring_size=1000)
        j.configure(path)
        for i in range(30):
            j.emit("data" if i % 3 else "serving",
                   "shed" if i % 2 else "quarantine", i=i)
        j.configure(None)
        for q in ({}, {"domain": "data"}, {"kind": "shed"},
                  {"domain": "serving", "kind": "quarantine"}):
            ring = j.tail(1000, **q)
            file = list(read_journal(path, **q))
            assert [r["seq"] for r in ring] == \
                [r["seq"] for r in file], q
            assert ring == file, q


class TestJournalRotation:
    """ISSUE-11 satellite: size-based JSONL rotation — a long-running
    job's journal must not grow without bound, and every reader
    (read_journal, events tail --follow) must span the segment
    boundary losslessly."""

    def test_rotation_keeps_segments_and_read_spans_them(self, tmp_path):
        from paddle_tpu.obs.events import journal_segments
        path = str(tmp_path / "events.jsonl")
        j = EventJournal()
        # every record is ~190 bytes -> a 1 KiB cap rotates every ~5
        j.configure(path, max_bytes=1024, keep=3)
        for i in range(40):
            j.emit("test", "tick", i=i)
        j.configure(None)
        assert j.rotations > 0
        segs = journal_segments(path)
        assert segs[-1] == path and 2 <= len(segs) <= 4
        # oldest-first: path.N ... path.2, path.1, path
        import os
        assert [os.path.basename(s) for s in segs] == sorted(
            [os.path.basename(s) for s in segs],
            key=lambda n: -int(n.rsplit(".", 1)[-1])
            if n.rsplit(".", 1)[-1].isdigit() else 0)
        recs = list(read_journal(path))
        # the newest records are all present, in order, no duplicates
        idx = [r["i"] for r in recs]
        assert idx == sorted(idx) and len(set(idx)) == len(idx)
        assert idx[-1] == 39
        # keep=3 bounds what survives: pruning dropped the oldest
        assert 8 <= len(recs) < 40

    def test_keep_prunes_oldest(self, tmp_path):
        path = str(tmp_path / "events.jsonl")
        j = EventJournal()
        j.configure(path, max_bytes=256, keep=1)
        for i in range(50):
            j.emit("test", "tick", i=i)
        j.configure(None)
        import os
        assert os.path.exists(path + ".1")
        assert not os.path.exists(path + ".2")

    def test_follow_spans_rotation(self, tmp_path):
        """The tail -f loop must drain the rotated-away remainder of
        what is now ``path.1`` before restarting at the fresh active
        file — no record lost, none duplicated. Deterministic: the
        rotation happens between two polls of a single-threaded
        generator drive."""
        import os

        from paddle_tpu.cli import _iter_journal_follow
        path = str(tmp_path / "events.jsonl")
        j = EventJournal()
        j.configure(path, max_bytes=4096, keep=2)
        for _ in range(20):                     # preamble: pushes the
            j.emit("test", "tick", i=-1)        # follow-from cursor up
        start = os.path.getsize(path)
        # fill until ONE rotation lands: everything after `start` is
        # unread when the active file is swapped out to path.1
        n = 0
        while j.rotations == 0:
            j.emit("test", "tick", i=n)
            n += 1
            assert n < 200, "rotation never triggered"
        j.emit("test", "tick", i=n)             # one post-rotation
        n += 1
        j.configure(None)
        assert os.path.getsize(path) < start    # the detection window
        got = [rec["i"] for rec in _iter_journal_follow(
            path, poll=0.01, idle_timeout=0.3, from_pos=start)]
        assert got == list(range(n))


# ------------------------------------- profiler gauges + slo journal parity

PROFILE_GAUGES = (
    "paddle_tpu_profile_step_ms",
    "paddle_tpu_profile_phase_ms",
    "paddle_tpu_profile_mfu",
    "paddle_tpu_profile_roofline_frac",
    "paddle_tpu_profile_device_bytes_in_use",
    "paddle_tpu_profile_hbm_watermark_bytes",
    "paddle_tpu_profile_page_pool_occupancy",
    "paddle_tpu_profile_page_pool_occupancy_trend",
)


class TestProfileObservability:
    def test_profile_gauge_families_always_exported(self):
        """All eight profiler families register at import time, so one
        scrape carries their HELP/TYPE before the first sampled step."""
        text = REGISTRY.exposition()
        for fam in PROFILE_GAUGES:
            assert f"# HELP {fam} " in text, fam
            assert f"# TYPE {fam} gauge" in text, fam

    def test_sampled_steps_populate_live_gauges(self):
        """Driving the profiler through stat_timer scopes lands the
        step/phase/MFU/roofline gauges in the exposition with the same
        labels the docs pin."""
        import time

        from paddle_tpu.obs.profile import PROFILER
        from paddle_tpu.utils.stats import stat_timer
        PROFILER.configure(peak_flops=1e12, hbm_gbps=100.0,
                           assume_mxu=False)
        PROFILER.set_cost_source("train", lambda: (2.0e6, 1.0e6))
        PROFILER.enable(sample_every=2)
        try:
            for _ in range(6):
                with stat_timer("train_step"):
                    time.sleep(0.002)
                PROFILER.on_step("train")
        finally:
            PROFILER.disable()
        snap = PROFILER.snapshot()
        assert snap["kinds"]["train"]["phases"]["compute"] > 0
        assert snap["cost"]["train"] == {"flops": 2.0e6, "bytes": 1.0e6}
        text = REGISTRY.exposition()
        assert 'paddle_tpu_profile_step_ms{kind="train"} ' in text
        assert ('paddle_tpu_profile_phase_ms{kind="train",'
                'phase="compute"} ') in text
        assert 'paddle_tpu_profile_mfu{kind="train"} ' in text
        assert 'paddle_tpu_profile_roofline_frac{kind="train"} ' in text
        # snapshot reads the same numbers back from the gauges
        assert snap["mfu"]["train"] > 0
        assert snap["roofline_frac"]["train"] > 0

    def test_slo_domain_ring_file_filter_parity(self, tmp_path):
        """slo-domain breach records obey the same ring/file filter
        contract as every other domain — tail(domain=\"slo\") and
        read_journal(domain=\"slo\") agree record-for-record."""
        path = str(tmp_path / "slo.jsonl")
        j = EventJournal(ring_size=1000)
        j.configure(path)
        for i in range(12):
            if i % 3 == 0:
                j.emit("slo", "step_regression", step_kind="train",
                       phase="compute", step_ms=42.0, i=i)
            elif i % 3 == 1:
                j.emit("slo", "breach", objective="p99_ms<=5", i=i)
            else:
                j.emit("trainer", "step", i=i)
        j.configure(None)
        for q in ({"domain": "slo"},
                  {"domain": "slo", "kind": "step_regression"},
                  {"kind": "breach"}):
            ring = j.tail(1000, **q)
            file = list(read_journal(path, **q))
            assert ring == file and ring, q
        regs = j.tail(1000, domain="slo", kind="step_regression")
        assert all(r["phase"] == "compute" for r in regs)


# --------------------------------------------------- lockdep telemetry

class TestLockdepObservability:
    def test_lockdep_gauges_always_in_exposition(self):
        """The witness bridge (obs/metrics._lockdep_bridge) exports the
        graph size and inversion count on every scrape, even idle."""
        text = REGISTRY.exposition()
        assert "# TYPE paddle_tpu_lockdep_edges gauge" in text
        assert ("# TYPE paddle_tpu_lockdep_inversions_total "
                "counter") in text
        assert "paddle_tpu_lockdep_inversions_total 0" in text

    def test_contention_and_hold_time_reach_metrics_and_reset(self):
        """Driving real contention on a named lock lands the per-name
        contention/hold-time samples in /metrics exposition, and
        obs.reset_all (the per-test conftest reset) zeroes them."""
        import time

        from paddle_tpu.analysis.lockdep import named_lock
        from paddle_tpu.obs import reset_all
        lk = named_lock("obs.test.lk")
        entered = threading.Event()

        def holder():
            with lk:
                entered.set()
                # ptlint: disable=R9(deliberate hold: this thread exists to create the contention under test)
                time.sleep(0.05)

        t = threading.Thread(target=holder, name="pt-test-obs-holder")
        t.start()
        assert entered.wait(2.0)
        with lk:
            pass
        t.join(timeout=2.0)

        text = REGISTRY.exposition()
        assert ('paddle_tpu_lockdep_contentions_total'
                '{name="obs.test.lk"} ') in text
        assert ('paddle_tpu_lockdep_hold_time_ms'
                '{name="obs.test.lk"} ') in text
        assert ('paddle_tpu_lockdep_acquisitions_total'
                '{name="obs.test.lk"} ') in text

        reset_all()
        # snapshot BEFORE scraping: exposition() itself nests the
        # registry and family locks, legitimately re-growing the graph
        from paddle_tpu.analysis.lockdep import LOCKDEP
        snap = LOCKDEP.metrics_snapshot()
        assert snap["edges"] == 0 and snap["inversions"] == 0
        assert "obs.test.lk" not in snap["contentions"]
        text = REGISTRY.exposition()
        assert "obs.test.lk" not in text


# ------------------------------------------------------------ step tracing

class TestTracing:
    def test_spans_nest_and_compile_events_attach(self):
        """ISSUE acceptance: spans nest, xla-compile instants attach,
        and stat_timer scopes become spans with no per-site wiring."""
        import jax

        from paddle_tpu.utils.stats import stat_timer
        tracer = obs_trace.TRACER
        tracer.start(capture_compiles=True)
        try:
            with tracer.span("outer"):
                with tracer.span("inner"):
                    jax.jit(lambda x: x * 2 + 1)(
                        np.float32(3.0)).block_until_ready()
                with stat_timer("train_step"):
                    pass
        finally:
            tracer.stop()
        spans = {s["name"]: s for s in tracer.spans()}
        assert set(spans) >= {"outer", "inner", "train_step"}
        out, inn = spans["outer"], spans["inner"]
        assert inn["parent"] == "outer"
        assert spans["train_step"]["parent"] == "outer"
        assert out["t0"] <= inn["t0"] and inn["t1"] <= out["t1"]
        compiles = [i for i in tracer.instants()
                    if i["name"] == "xla_compile"]
        assert compiles, "the jit compile must appear as an instant"
        assert any(out["t0"] <= i["t"] <= out["t1"] and
                   i["parent"] == "inner" for i in compiles)

    def test_chrome_trace_export(self, tmp_path):
        tracer = obs_trace.TRACER
        tracer.start(capture_compiles=False)
        with tracer.span("step", batch=3):
            with tracer.span("data_wait"):
                pass
        tracer.stop()
        path = str(tmp_path / "trace.json")
        tracer.save(path)
        with open(path) as f:
            blob = json.load(f)
        evs = blob["traceEvents"]
        assert all(e["ph"] in ("X", "i", "M") for e in evs)
        # cross-process merge keys (obs/merge.py): process metadata +
        # run/host identity ride the export
        meta = blob["metadata"]
        assert meta["run_id"] and meta["host"] and meta["pid"] == \
            __import__("os").getpid()
        assert any(e["ph"] == "M" and e["name"] == "process_name"
                   for e in evs)
        step = [e for e in evs if e["name"] == "step"][0]
        wait = [e for e in evs if e["name"] == "data_wait"][0]
        assert step["ts"] <= wait["ts"]
        assert wait["ts"] + wait["dur"] <= step["ts"] + step["dur"]
        assert step["args"]["batch"] == 3
        assert wait["args"]["parent"] == "step"

    def test_disabled_tracer_records_nothing(self):
        tracer = obs_trace.TRACER
        from paddle_tpu.obs.flight import FLIGHT
        FLIGHT.configure(enabled=False)
        try:
            assert tracer.span("ghost") is \
                tracer.span("ghost")          # the shared no-op object
            with tracer.span("ghost"):
                pass
            assert tracer.spans() == []
        finally:
            FLIGHT.configure(enabled=True)

    def test_span_ring_is_bounded_and_drops_are_counted(self):
        """ISSUE-8 satellite: Tracer memory is a ring (max_spans) and
        overflow shows up as paddle_tpu_trace_dropped_total."""
        tracer = obs_trace.Tracer(max_spans=4)
        tracer._flight = obs_trace.TRACER._flight_recorder()
        before = REGISTRY.counter(
            "paddle_tpu_trace_dropped_total").value()
        tracer.start(capture_compiles=False)
        for i in range(10):
            with tracer.span(f"s{i}"):
                pass
        tracer.stop()
        spans = tracer.spans()
        assert len(spans) == 4                 # fixed memory
        assert [s["name"] for s in spans] == ["s6", "s7", "s8", "s9"]
        assert tracer.dropped == 6
        assert REGISTRY.counter(
            "paddle_tpu_trace_dropped_total").value() - before == 6

    def test_spans_carry_bound_trace_context(self):
        from paddle_tpu.obs import context as obs_context
        tracer = obs_trace.TRACER
        tracer.start(capture_compiles=False)
        try:
            with obs_context.bind(trace_id="tid-x", step=12):
                with tracer.span("ctx_span"):
                    pass
        finally:
            tracer.stop()
        (s,) = [x for x in tracer.spans() if x["name"] == "ctx_span"]
        assert s["trace_id"] == "tid-x" and s["step"] == 12
        ev = [e for e in tracer.chrome_trace()["traceEvents"]
              if e.get("name") == "ctx_span"][0]
        assert ev["args"]["trace_id"] == "tid-x"


# ------------------------------------------------- standalone obs endpoint

class TestObsEndpoint:
    def test_metrics_and_events_over_http(self):
        global_counters.bump("trainer/steps", 5)
        obs_events.emit("test", "ping", detail="x")
        httpd = start_obs_server()
        port = httpd.server_address[1]
        base = f"http://127.0.0.1:{port}"
        try:
            with urllib.request.urlopen(base + "/metrics",
                                        timeout=10) as r:
                assert r.headers["Content-Type"].startswith("text/plain")
                text = r.read().decode()
            assert 'paddle_tpu_counter_total{name="trainer/steps"} 5' \
                in text
            with urllib.request.urlopen(
                    base + "/events?n=5&domain=test", timeout=10) as r:
                evs = json.loads(r.read())["events"]
            assert evs and evs[-1]["kind"] == "ping"
            with urllib.request.urlopen(base + "/health",
                                        timeout=10) as r:
                assert json.loads(r.read())["status"] == "ok"
            # the since_seq cursor pages the scrape (ISSUE-8
            # satellite): page 1 returns a resume point, page 2 is
            # empty once caught up
            with urllib.request.urlopen(
                    base + "/events?since_seq=0&n=100",
                    timeout=10) as r:
                blob = json.loads(r.read())
            assert blob["events"] and blob["last_seq"] >= \
                blob["events"][-1]["seq"]
            cursor = blob["last_seq"]
            with urllib.request.urlopen(
                    base + f"/events?since_seq={cursor}",
                    timeout=10) as r:
                assert json.loads(r.read())["events"] == []
            # and the flight bundle is served on demand
            with urllib.request.urlopen(base + "/flight",
                                        timeout=10) as r:
                bundle = json.loads(r.read())
            assert bundle["v"] == 1 and "ring" in bundle \
                and "metrics" in bundle
        finally:
            httpd.shutdown()
            httpd.server_close()

    def test_serving_front_events_route(self):
        """serve's transport gains /events (ISSUE satellite)."""
        from paddle_tpu.serving import InferenceServer, build_http_server
        from paddle_tpu.trainer.inference import Inference
        x = paddle.layer.data("ox", paddle.data_type.dense_vector(4))
        o = paddle.layer.fc(x, size=2, act=paddle.activation.Softmax())
        inf = Inference(output_layer=o,
                        parameters=paddle.create_parameters(
                            paddle.Topology(o)))
        srv = InferenceServer(inf, workers=1, breaker=False).start()
        httpd = build_http_server(srv, "127.0.0.1", 0)
        port = httpd.server_address[1]
        t = threading.Thread(target=httpd.serve_forever, daemon=True,
                             name="pt-test-obs-httpd")
        t.start()
        try:
            obs_events.emit("serving", "shed", reason="test")
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/events?kind=shed",
                    timeout=10) as r:
                evs = json.loads(r.read())["events"]
            assert evs and evs[-1]["reason"] == "test"
        finally:
            httpd.shutdown()
            srv.shutdown(drain=True)


# --------------------------------------------------- chaos: one journal

class TestChaosJournal:
    """THE acceptance criterion: chaos runs produce a schema-valid
    JSONL journal capturing every injected fault."""

    @pytest.mark.chaos
    def test_data_oom_and_preemption_faults_all_journaled(self, tmp_path):
        from paddle_tpu.reader import ErrorBudget, supervised
        from paddle_tpu.serving import DecodeEngine
        from paddle_tpu.testing.faults import FaultPlan
        from tests.test_serving_faults import tiny_decoder

        path = str(tmp_path / "chaos.jsonl")
        JOURNAL.configure(path)

        # (1) data faults: 3 raising-mapper samples quarantined, budget
        # of 1 blown -> 3 quarantine + 1 data_budget records
        plan = FaultPlan()
        eb = ErrorBudget(max_bad=1, on_bad="log")
        sr = supervised(lambda: iter(range(20)),
                        mapper=plan.raising_mapper(lambda v: v,
                                                   [2, 5, 9]),
                        num_workers=2, order=True, error_budget=eb)
        assert len(list(sr())) == 17

        # (2) trainer OOM: oom_at(step=1) -> adaptive microbatching
        from tests.test_oom import _reader, _trainer
        tr = _trainer()
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            with FaultPlan.oom_at(tr, step=1, n=1) as stats:
                tr.train(_reader(batches=3), num_passes=1,
                         event_handler=lambda e: None,
                         microbatch="auto")
        assert stats["injected"] == 1

        # (3) engine preemption, forced deterministically: two
        # requests of 5 pages each against a 5-usable-page pool — the
        # younger MUST be evicted at least once while the elder grows
        dec = tiny_decoder()
        rng = np.random.RandomState(1)
        p1 = rng.randint(0, 40, (4,)).astype("int32")
        p2 = rng.randint(0, 40, (4,)).astype("int32")
        eng = DecodeEngine(dec, num_slots=2, page_size=4,
                           max_seq_len=20, num_pages=6)
        r1, r2 = eng.submit(p1, 14), eng.submit(p2, 14)
        eng.run(timeout=300)
        assert len(r1.get(timeout=1)) == 14
        assert len(r2.get(timeout=1)) == 14
        preempts = eng.stats()["preemptions"]
        assert preempts > 0

        JOURNAL.configure(None)
        recs = [validate(r) for r in read_journal(path)]
        kinds = {}
        for r in recs:
            kinds[(r["domain"], r["kind"])] = \
                kinds.get((r["domain"], r["kind"]), 0) + 1
        assert kinds[("data", "quarantine")] == 3
        assert kinds[("data", "data_budget")] == 1
        assert kinds[("trainer", "oom")] == 1
        assert kinds[("engine", "preemption")] == preempts
