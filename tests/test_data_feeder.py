"""DataFeeder parity — mirrors python/paddle/v2/tests/test_data_feeder.py
case for case: dense, sparse_binary, sparse (float), integer, integer
sequence, multiple features, and the `feeding` column remap. The reference
checks the produced Arguments matrices; here the targets are arrays and
SequenceBatch.
"""

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.core.data_type import (dense_vector, integer_value,
                                       integer_value_sequence,
                                       sparse_binary_vector,
                                       sparse_float_vector)
from paddle_tpu.core.sequence import SequenceBatch
from paddle_tpu.trainer.data_feeder import DataFeeder


class TestDense:
    def test_dense(self):
        # test_data_feeder.py:35 — batches of float vectors, several sizes
        for batch_size in (1, 3, 10):
            rows = [(np.random.rand(8).astype(np.float32),)
                    for _ in range(batch_size)]
            feed = DataFeeder([("image", dense_vector(8))]).convert(rows)
            got = np.asarray(feed["image"])
            assert got.shape == (batch_size, 8)
            np.testing.assert_allclose(got[0], rows[0][0], rtol=1e-6)

    def test_dense_accepts_lists(self):
        feed = DataFeeder([("x", dense_vector(3))]).convert(
            [([0.0, 1.0, 2.0],), ([3.0, 4.0, 5.0],)])
        np.testing.assert_array_equal(np.asarray(feed["x"]),
                                      [[0, 1, 2], [3, 4, 5]])


class TestSparse:
    def test_sparse_binary(self):
        # test_data_feeder.py:69 — index lists become 1.0 at each index
        rows = [([1, 3],), ([0,],), ([2, 4, 5],)]
        feed = DataFeeder([("w", sparse_binary_vector(6))]).convert(rows)
        got = np.asarray(feed["w"])
        assert got.shape == (3, 6)
        for i, (idxs,) in enumerate(rows):
            want = np.zeros(6); want[idxs] = 1.0
            np.testing.assert_array_equal(got[i], want)

    def test_sparse_float(self):
        # test_data_feeder.py:85 — (indices, values) pairs
        rows = [(([1, 3], [0.5, 2.0]),), (([0, 5], [1.0, -1.0]),)]
        feed = DataFeeder([("w", sparse_float_vector(6))]).convert(rows)
        got = np.asarray(feed["w"])
        assert got[0, 1] == 0.5 and got[0, 3] == 2.0
        assert got[1, 0] == 1.0 and got[1, 5] == -1.0
        assert got.sum() == pytest.approx(2.5)


class TestInteger:
    def test_integer(self):
        # test_data_feeder.py:112
        feed = DataFeeder([("label", integer_value(10))]).convert(
            [(3,), (7,), (0,)])
        got = np.asarray(feed["label"])
        assert got.dtype == np.int32
        np.testing.assert_array_equal(got, [3, 7, 0])

    def test_integer_sequence(self):
        # test_data_feeder.py:127 — ragged id lists -> SequenceBatch
        rows = [([1, 2, 3],), ([4, 5],), ([6],)]
        feed = DataFeeder(
            [("sent", integer_value_sequence(100))]).convert(rows)
        sb = feed["sent"]
        assert isinstance(sb, SequenceBatch)
        np.testing.assert_array_equal(np.asarray(sb.lengths), [3, 2, 1])
        np.testing.assert_array_equal(np.asarray(sb.data)[0, :3], [1, 2, 3])


class TestMultipleFeatures:
    TYPES = [("image", dense_vector(4)), ("label", integer_value(5))]

    def test_positional(self):
        # test_data_feeder.py:154 — sample columns in data_types order
        rows = [(np.ones(4, np.float32), 2), (np.zeros(4, np.float32), 4)]
        feed = DataFeeder(self.TYPES).convert(rows)
        np.testing.assert_array_equal(np.asarray(feed["label"]), [2, 4])
        assert np.asarray(feed["image"]).shape == (2, 4)

    def test_feeding_remap(self):
        # test_data_feeder.py:212 — `feeding` maps name -> column index,
        # so samples can carry columns in any order
        rows = [(2, np.ones(4, np.float32)), (4, np.zeros(4, np.float32))]
        feed = DataFeeder(self.TYPES,
                          feeding={"image": 1, "label": 0}).convert(rows)
        np.testing.assert_array_equal(np.asarray(feed["label"]), [2, 4])
        np.testing.assert_array_equal(np.asarray(feed["image"])[0],
                                      np.ones(4))

    def test_batch_size_recorded(self):
        rows = [(np.ones(4, np.float32), 1)]
        feed = DataFeeder(self.TYPES).convert(rows)
        assert feed["__batch_size__"] == 1


class TestFixedBatchPadding:
    def test_pad_to_fixed_and_zero_lengths(self):
        # TPU shape discipline: short batches pad to fixed_batch_size,
        # sequence fillers get length 0 (no reference twin — this is the
        # static-shape replacement for fully-dynamic batching)
        f = DataFeeder([("sent", integer_value_sequence(50))],
                       fixed_batch_size=4)
        sb = f.convert([([1, 2],), ([3],)])["sent"]
        assert np.asarray(sb.data).shape[0] == 4
        np.testing.assert_array_equal(np.asarray(sb.lengths), [2, 1, 0, 0])
