"""Golden-topology regression corpus.

Reference: trainer_config_helpers/tests/configs/ + protostr/ — every DSL
config's serialized form is committed, and CI diffs a fresh parse against
it (generate_protostr.sh / run_tests.sh). Any change to shape inference,
auto-naming, parameter layout, or serialization shows up as a diff here
and must be intentional (regenerate with UPDATE_GOLDEN=1).

    UPDATE_GOLDEN=1 python -m pytest tests/test_golden_topology.py

Besides the byte diff, each config must deserialize back and rebuild the
same serialized form (round-trip closure).
"""

import json
import os
import pathlib

import pytest

from paddle_tpu.core.topology import Topology
from tests.golden_configs import CONFIGS

GOLDEN_DIR = pathlib.Path(__file__).parent / "golden"


def build_serialized(name: str) -> str:
    out = CONFIGS[name]()
    outs = out if isinstance(out, (list, tuple)) else [out]
    return Topology(outs).serialize()


@pytest.mark.parametrize("name", sorted(CONFIGS))
def test_golden_topology(name):
    blob = build_serialized(name)
    path = GOLDEN_DIR / f"{name}.json"
    if os.environ.get("UPDATE_GOLDEN"):
        GOLDEN_DIR.mkdir(exist_ok=True)
        path.write_text(blob)
    assert path.exists(), (
        f"no golden topology for {name!r}; run with UPDATE_GOLDEN=1 to "
        "create it")
    golden = path.read_text()
    if blob != golden:
        # structured diff makes the failure actionable
        a = json.loads(golden)
        b = json.loads(blob)
        ga = {l["name"]: l for l in a["layers"]}
        gb = {l["name"]: l for l in b["layers"]}
        only_a = sorted(set(ga) - set(gb))
        only_b = sorted(set(gb) - set(ga))
        changed = [n for n in ga if n in gb and ga[n] != gb[n]]
        pytest.fail(
            f"topology drift for {name!r}: removed={only_a} added={only_b} "
            f"changed={changed[:10]} — if intentional, regenerate with "
            "UPDATE_GOLDEN=1")


@pytest.mark.parametrize("name", sorted(CONFIGS))
def test_golden_roundtrip(name):
    blob = build_serialized(name)
    topo = Topology.deserialize(blob)
    assert topo.serialize() == blob
