"""Shim: the finite-difference harness moved into the package so the
CLI's --job=checkgrad (Trainer.h:43 checkGradient parity) can use it;
tests keep importing it from here."""

from paddle_tpu.trainer.grad_check import check_topology_grads  # noqa: F401
