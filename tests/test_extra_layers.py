"""Forward-semantics tests for the round-3 layer additions: bilinear tensor
product, conv_shift circular correlation, linear_comb, prelu, row_l2_norm,
switch_order, crf_error, and cross_entropy_over_beam.

Values are pinned against hand-computed numpy expectations, mirroring the
reference's dedicated unit tests (test_LayerGrad.cpp cases for tensor /
conv_shift / convex_comb / prelu, test_CrossEntropyOverBeamGrad.cpp for the
beam cost).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.core.sequence import pack_nested_sequences, pack_sequences
from paddle_tpu.core.topology import Topology

L = paddle.layer


def run(out, feed, mode="test", seed=0):
    topo = Topology(out)
    params = topo.init_params(jax.random.PRNGKey(seed))
    outs, _ = topo.forward(params, topo.init_state(), feed, mode=mode,
                           rng=jax.random.PRNGKey(seed + 1))
    return outs[out.name], params


class TestTensorLayer:
    def test_bilinear_product(self):
        rng = np.random.RandomState(0)
        av = rng.randn(3, 4).astype(np.float32)
        bv = rng.randn(3, 5).astype(np.float32)
        a = L.data("a", paddle.data_type.dense_vector(4))
        b = L.data("b", paddle.data_type.dense_vector(5))
        out = L.tensor(a, b, size=2)
        got, params = run(out, {"a": jnp.asarray(av), "b": jnp.asarray(bv)})
        w = np.asarray(params[out.name and f"_{out.name}.w0"])
        bias = np.asarray(params[f"_{out.name}.wbias"])
        want = np.einsum("bi,kij,bj->bk", av, w, bv) + bias
        np.testing.assert_allclose(np.asarray(got), want, rtol=1e-5,
                                   atol=1e-5)


class TestConvShift:
    def test_circular_correlation(self):
        # hand example: M=4, N=3 window; c[i] = sum_j a[(i+j) mod 4] * w[j]
        av = np.array([[1.0, 2.0, 3.0, 4.0]], np.float32)
        wv = np.array([[0.5, 1.0, -1.0]], np.float32)   # j = -1, 0, +1
        a = L.data("a", paddle.data_type.dense_vector(4))
        w = L.data("w", paddle.data_type.dense_vector(3))
        got, _ = run(L.conv_shift(a, w),
                     {"a": jnp.asarray(av), "w": jnp.asarray(wv)})
        want = np.zeros((1, 4), np.float32)
        for i in range(4):
            for j in (-1, 0, 1):
                want[0, i] += av[0, (i + j) % 4] * wv[0, j + 1]
        np.testing.assert_allclose(np.asarray(got), want, rtol=1e-6)


class TestLinearComb:
    def test_weighted_block_sum(self):
        wv = np.array([[2.0, -1.0]], np.float32)                 # m=2
        vv = np.array([[1.0, 2.0, 3.0, 10.0, 20.0, 30.0]], np.float32)
        w = L.data("w", paddle.data_type.dense_vector(2))
        v = L.data("v", paddle.data_type.dense_vector(6))
        got, _ = run(L.linear_comb(w, v),
                     {"w": jnp.asarray(wv), "v": jnp.asarray(vv)})
        want = 2.0 * vv[:, :3] - 1.0 * vv[:, 3:]
        np.testing.assert_allclose(np.asarray(got), want, rtol=1e-6)


class TestPRelu:
    def test_negative_slope_init(self):
        xv = np.array([[-2.0, -1.0, 1.0, 2.0]], np.float32)
        x = L.data("x", paddle.data_type.dense_vector(4))
        got, _ = run(L.prelu(x), {"x": jnp.asarray(xv)})
        want = np.where(xv > 0, xv, 0.25 * xv)
        np.testing.assert_allclose(np.asarray(got), want, rtol=1e-6)

    def test_partial_sum_groups(self):
        x = L.data("x", paddle.data_type.dense_vector(6))
        out = L.prelu(x, partial_sum=3)
        topo = Topology(out)
        (pname, spec), = topo.param_specs.items()
        assert spec.shape == (2,)   # 6 / partial_sum 3


class TestRowL2Norm:
    def test_unit_rows(self):
        rng = np.random.RandomState(1)
        xv = rng.randn(3, 5).astype(np.float32)
        x = L.data("x", paddle.data_type.dense_vector(5))
        got, _ = run(L.row_l2_norm(x), {"x": jnp.asarray(xv)})
        want = xv / np.linalg.norm(xv, axis=1, keepdims=True)
        np.testing.assert_allclose(np.asarray(got), want, rtol=1e-5)


class TestSwitchOrder:
    def test_nchw_to_nhwc(self):
        c, h, w = 2, 2, 3
        xv = np.arange(1 * c * h * w, dtype=np.float32).reshape(1, -1)
        x = L.data("x", paddle.data_type.dense_vector(c * h * w),
                   height=h, width=w)
        got, _ = run(L.switch_order(x), {"x": jnp.asarray(xv)})
        want = xv.reshape(1, c, h, w).transpose(0, 2, 3, 1).reshape(1, -1)
        np.testing.assert_allclose(np.asarray(got), want)


class TestCrfError:
    def test_zero_one_disagreement(self):
        rng = np.random.RandomState(2)
        rows = [rng.randn(4, 3).astype(np.float32)]
        x = L.data("x", paddle.data_type.dense_vector_sequence(3))
        lbl = L.data("lbl", paddle.data_type.integer_value_sequence(3))
        feed = {"x": pack_sequences(rows),
                "lbl": pack_sequences(
                    [rng.randint(0, 3, 4).astype(np.int32)])}
        err, _ = run(L.crf_error(x, lbl), feed)
        dec, _ = run(L.crf_decoding(x), feed)
        want = (np.asarray(dec.data)[0, :4] !=
                np.asarray(feed["lbl"].data)[0, :4]).astype(np.float32)
        np.testing.assert_allclose(np.asarray(err.data)[0, :4], want)


def _beam_nodes(scores1, beam1):
    s1 = L.data("s1", paddle.data_type.dense_vector_sequence(1))
    g1 = L.data("g1", paddle.data_type.integer_value(100))
    sel1 = L.kmax_seq_score(s1, beam_size=beam1)
    return s1, sel1, g1


class TestCrossEntropyOverBeam:
    def test_single_expansion_gold_in_beam(self):
        # 1 sequence, 4 candidates, top-2 beam; gold is the best candidate
        sc = np.array([0.1, 2.0, 0.3, 1.0], np.float32)
        s1, sel1, g1 = _beam_nodes(sc, 2)
        cost = L.cross_entropy_over_beam(L.BeamInput(s1, sel1, g1))
        feed = {"s1": pack_sequences([sc[:, None]]),
                "g1": jnp.asarray([1])}
        got, _ = run(cost, feed)
        # top-2 = ids {1, 3}; softmax over their scores, gold at id 1
        sel = np.array([2.0, 1.0])
        want = -(sel[0] - np.log(np.exp(sel).sum()))
        np.testing.assert_allclose(float(np.asarray(got)[0]), want,
                                   rtol=1e-5)

    def test_single_expansion_gold_off_beam(self):
        # gold (id 0) falls off the top-2 beam -> appended as extra path
        sc = np.array([0.1, 2.0, 0.3, 1.0], np.float32)
        s1, sel1, g1 = _beam_nodes(sc, 2)
        cost = L.cross_entropy_over_beam(L.BeamInput(s1, sel1, g1))
        feed = {"s1": pack_sequences([sc[:, None]]),
                "g1": jnp.asarray([0])}
        got, _ = run(cost, feed)
        paths = np.array([2.0, 1.0, 0.1])   # beam paths + gold as extra
        want = -(paths[2] - np.log(np.exp(paths).sum()))
        np.testing.assert_allclose(float(np.asarray(got)[0]), want,
                                   rtol=1e-5)

    def test_two_expansions_path_scores(self):
        # expansion 0: 4 candidates, top-2 selected (ids 1 then 3).
        # expansion 1: one subsequence per selected candidate, 3 candidates
        # each, top-2 per subsequence. Paths = 4; score = sum along chain.
        sc1 = np.array([0.1, 2.0, 0.3, 1.0], np.float32)
        sc2_rows = [np.array([[0.5], [1.5], [0.2]], np.float32),   # for id 1
                    np.array([[0.9], [0.4], [1.1]], np.float32)]   # for id 3
        s1 = L.data("s1", paddle.data_type.dense_vector_sequence(1))
        s2 = L.data("s2", paddle.data_type.dense_vector_sub_sequence(1))
        sel1 = L.kmax_seq_score(s1, beam_size=2)
        sel2 = L.kmax_seq_score(s2, beam_size=2)
        g1 = L.data("g1", paddle.data_type.integer_value(100))
        g2 = L.data("g2", paddle.data_type.integer_value(100))
        cost = L.cross_entropy_over_beam([
            L.BeamInput(s1, sel1, g1), L.BeamInput(s2, sel2, g2)])
        feed = {"s1": pack_sequences([sc1[:, None]]),
                "s2": pack_nested_sequences([sc2_rows]),
                "g1": jnp.asarray([1]),      # gold candidate: id 1 (col 0)
                "g2": jnp.asarray([1])}      # gold within subsequence 0
        got, _ = run(cost, feed)
        # kmax rows: exp0 -> [1, 3]; exp1 row0 -> [1, 0], row1 -> [2, 0]
        # paths (flat order): (row0,[1,0]) then (row1,[2,0])
        path_scores = np.array([
            sc1[1] + 1.5,   # row 0, inner id 1  <- gold path
            sc1[1] + 0.5,   # row 0, inner id 0
            sc1[3] + 1.1,   # row 1, inner id 2
            sc1[3] + 0.9,   # row 1, inner id 0
        ])
        want = -(path_scores[0] - np.log(np.exp(path_scores).sum()))
        np.testing.assert_allclose(float(np.asarray(got)[0]), want,
                                   rtol=1e-5)
