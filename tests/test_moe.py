"""Mixture-of-experts: routing semantics, dense-FFN equivalence, the
layer/cost pair, and expert parallelism over a dp x ep mesh.

The ep leg has no 2017 reference counterpart (like ring attention, it is
a beyond-parity TPU extra); the test discipline mirrors the repo's other
parallel legs: sharded run must reproduce single-device numerics exactly
(GSPMD routing runs on the global batch, so there is no tolerance game).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import models
from paddle_tpu.core.sequence import SequenceBatch
from paddle_tpu.ops import moe as moe_ops
from paddle_tpu.parallel.mesh import create_mesh

L = paddle.layer


class TestDispatch:
    def test_uniform_router_aux_is_one(self):
        d, c, aux = moe_ops.moe_dispatch(jnp.zeros((8, 4)), None, k=2,
                                         capacity=8)
        assert abs(float(aux) - 1.0) < 1e-6

    def test_topk_dispatch_and_combine(self):
        # 3 tokens, 3 experts; distinct logits so routing is unambiguous
        logits = jnp.asarray([[3.0, 1.0, 0.0],
                              [0.0, 3.0, 1.0],
                              [1.0, 0.0, 3.0]])
        d, c, _ = moe_ops.moe_dispatch(logits, None, k=2, capacity=2)
        d = np.asarray(d)
        probs = np.asarray(jax.nn.softmax(logits, axis=-1))
        # token 0 -> experts 0,1; token 1 -> 1,2; token 2 -> 2,0
        for tok, (e1, e2) in enumerate([(0, 1), (1, 2), (2, 0)]):
            assert d[tok, e1].sum() == 1 and d[tok, e2].sum() == 1
            assert d[tok].sum() == 2
        # combine weights = top-2 probs renormalized per token
        cs = np.asarray(c).sum(axis=2)
        for tok, (e1, e2) in enumerate([(0, 1), (1, 2), (2, 0)]):
            tot = probs[tok, e1] + probs[tok, e2]
            np.testing.assert_allclose(cs[tok, e1], probs[tok, e1] / tot,
                                       rtol=1e-5)

    def test_capacity_drops_overflow(self):
        # all tokens prefer expert 0; capacity 2 keeps the first 2 only
        logits = jnp.asarray([[5.0, 0.0]] * 4)
        d, _, _ = moe_ops.moe_dispatch(logits, None, k=1, capacity=2)
        d = np.asarray(d)
        assert d[:2, 0].sum() == 2 and d[2:, 0].sum() == 0

    def test_invalid_tokens_eat_no_capacity(self):
        logits = jnp.asarray([[5.0, 0.0]] * 4)
        valid = jnp.asarray([0.0, 0.0, 1.0, 1.0])
        d, _, _ = moe_ops.moe_dispatch(logits, valid, k=1, capacity=2)
        d = np.asarray(d)
        assert d[:2].sum() == 0          # masked tokens dispatch nowhere
        assert d[2:, 0].sum() == 2       # real tokens still fit

    def test_single_expert_is_dense_ffn(self):
        rng = np.random.RandomState(0)
        x = jnp.asarray(rng.randn(6, 5).astype(np.float32))
        gw = jnp.asarray(rng.randn(5, 1).astype(np.float32))
        wu = jnp.asarray(rng.randn(1, 5, 7).astype(np.float32))
        wd = jnp.asarray(rng.randn(1, 7, 5).astype(np.float32))
        y, _ = moe_ops.moe_ffn(x, None, gw, wu, wd, k=1,
                               capacity_factor=2.0)
        want = jnp.maximum(x @ wu[0], 0) @ wd[0]
        np.testing.assert_allclose(np.asarray(y), np.asarray(want),
                                   rtol=1e-5, atol=1e-5)


class TestRowCoupling:
    def test_k_beyond_experts_rejected(self):
        with pytest.raises(AssertionError):
            moe_ops.moe_dispatch(jnp.zeros((4, 2)), None, k=3, capacity=4)
        with pytest.raises(AssertionError):
            paddle.init(use_tpu=False, seed=0)
            x = L.data("x", paddle.data_type.dense_vector(6))
            L.moe(x, expert_num=2, k=3)

    def test_masked_rows_match_trimmed_batch(self):
        """At fixed capacity, dispatch/combine/aux over (real + masked
        pad) rows must equal the trimmed-batch result row for row — pad
        rows eat no capacity and don't skew the aux statistics."""
        rng = np.random.RandomState(0)
        logits6 = jnp.asarray(rng.randn(6, 2).astype(np.float32))
        logits8 = jnp.concatenate([logits6, jnp.zeros((2, 2))], axis=0)
        valid = jnp.asarray([1.0] * 6 + [0.0] * 2)
        d6, c6, a6 = moe_ops.moe_dispatch(logits6, None, k=2, capacity=3)
        d8, c8, a8 = moe_ops.moe_dispatch(logits8, valid, k=2, capacity=3)
        np.testing.assert_allclose(np.asarray(d8)[:6], np.asarray(d6))
        np.testing.assert_allclose(np.asarray(c8)[:6], np.asarray(c6),
                                   rtol=1e-6)
        assert float(np.asarray(d8)[6:].sum()) == 0.0
        np.testing.assert_allclose(float(a8), float(a6), rtol=1e-6)

    def test_trainer_n_real_reaches_dense_routing(self):
        """The forward ctx's n_real must mask feeder pad rows for DENSE
        (non-sequence) moe inputs: with it, the aux statistics see 6
        rows; without it, the 2 zero pad rows join the router and move
        the aux value."""
        paddle.init(use_tpu=False, seed=0)
        from paddle_tpu.core.registry import reset_name_counters
        reset_name_counters()
        rng = np.random.RandomState(0)
        xv = rng.randn(6, 6).astype(np.float32)
        xpad = np.concatenate([xv, np.zeros((2, 6), np.float32)])
        x = L.data("x", paddle.data_type.dense_vector(6))
        node = L.moe(x, expert_num=2, expert_hidden=5, k=2, name="m")
        aux = L.moe_aux_cost(x, node, coeff=1.0, name="aux")
        topo = paddle.Topology(aux)
        params = topo.init_params()
        state = topo.init_state()

        def run(feed_x, n_real):
            outs, _ = topo.forward(params, state, {"x": jnp.asarray(feed_x)},
                                   mode="test", n_real=n_real)
            return float(np.asarray(outs["aux"])[0])

        full = run(xv, jnp.asarray(6))
        masked = run(xpad, jnp.asarray(6))
        unmasked = run(xpad, None)
        np.testing.assert_allclose(masked, full, rtol=1e-5)
        assert abs(unmasked - full) > 1e-4   # the mask actually bites


def _lm_batch(rng, b=8, T=8, vocab=50):
    ids = rng.randint(0, vocab, (b, T)).astype("int32")
    return [(ids[i], np.arange(T, dtype="int32"), ids[i]) for i in range(b)]


def _train(mesh, steps=3, experts=4):
    paddle.init(use_tpu=False, seed=0)
    spec = models.transformer_lm(vocab_size=50, d_model=16, n_heads=2,
                                 n_layers=2, d_ff=32, max_len=32,
                                 moe_experts=experts)
    params = paddle.create_parameters(
        paddle.Topology(spec.cost, extra_outputs=[spec.output]))
    tr = paddle.SGD(cost=spec.cost, parameters=params,
                    extra_layers=[spec.output],
                    update_equation=paddle.optimizer.Adam(
                        learning_rate=1e-3),
                    mesh=mesh)
    rng = np.random.RandomState(0)
    batch = _lm_batch(rng)
    return [float(tr.train_batch(batch)[0]) for _ in range(steps)]


class TestMoETransformer:
    def test_moe_lm_trains_and_loss_falls(self):
        costs = _train(None)
        assert all(np.isfinite(c) for c in costs)
        assert costs[-1] < costs[0]

    def test_aux_cost_joins_total(self):
        paddle.init(use_tpu=False, seed=0)
        spec = models.transformer_lm(vocab_size=50, d_model=16, n_heads=2,
                                     n_layers=1, d_ff=32, max_len=32,
                                     moe_experts=4, moe_aux_coeff=0.5)
        assert isinstance(spec.cost, list) and len(spec.cost) == 2
        params = paddle.create_parameters(
            paddle.Topology(spec.cost, extra_outputs=[spec.output]))
        tr = paddle.SGD(cost=spec.cost, parameters=params,
                        extra_layers=[spec.output],
                        update_equation=paddle.optimizer.Adam(
                            learning_rate=1e-3))
        loss, metrics = tr.train_batch(_lm_batch(np.random.RandomState(0)))
        aux = metrics["tfm_l0_aux"]
        assert float(aux) > 0.4          # coeff 0.5 x (aux >= ~1)
        np.testing.assert_allclose(float(loss),
                                   float(metrics["tfm_cost"]) + float(aux),
                                   rtol=1e-5)

    def test_ep_mesh_matches_single_device(self):
        single = _train(None)
        meshed = _train(create_mesh([("dp", 2), ("ep", 4)]))
        np.testing.assert_allclose(single, meshed, rtol=1e-4)

    def test_trainer_shards_experts_on_ep_mesh(self):
        """The TRAINER path must place expert tables on the ep axis (not
        just spec_for): after a sharded step, the live param arrays carry
        the P('ep', None, None) sharding."""
        paddle.init(use_tpu=False, seed=0)
        spec = models.transformer_lm(vocab_size=50, d_model=16, n_heads=2,
                                     n_layers=1, d_ff=32, max_len=32,
                                     moe_experts=4)
        params = paddle.create_parameters(
            paddle.Topology(spec.cost, extra_outputs=[spec.output]))
        tr = paddle.SGD(cost=spec.cost, parameters=params,
                        extra_layers=[spec.output],
                        update_equation=paddle.optimizer.Adam(
                            learning_rate=1e-3),
                        mesh=create_mesh([("dp", 2), ("ep", 4)]))
        tr.train_batch(_lm_batch(np.random.RandomState(0)))
        up = tr.parameters.raw["_tfm_l0_moe.moe_up"]
        assert up.sharding.spec == jax.sharding.PartitionSpec(
            "ep", None, None), up.sharding

    def test_expert_tables_shard_over_ep(self):
        from paddle_tpu.parallel.tensor_parallel import spec_for
        mesh = create_mesh([("dp", 2), ("ep", 4)])
        spec = spec_for("_tfm_l0_moe.moe_up", (4, 16, 32), mesh)
        assert spec == jax.sharding.PartitionSpec("ep", None, None)
        # gate stays replicated
        assert spec_for("_tfm_l0_moe.gate", (16, 4), mesh) == \
            jax.sharding.PartitionSpec()


class TestSortedDispatch:
    """moe_sorted_ffn must reproduce the einsum path's numerics exactly:
    same keep decisions, same slots, same combine weights — argsort
    ranking in choice-major token order IS the einsum fill discipline."""

    def _both(self, n, d, E, f, k, capacity, seed, valid=None):
        rng = np.random.RandomState(seed)
        x = jnp.asarray(rng.randn(n, d).astype(np.float32))
        gate_w = jnp.asarray(rng.randn(d, E).astype(np.float32))
        w_up = jnp.asarray(0.1 * rng.randn(E, d, f).astype(np.float32))
        w_down = jnp.asarray(0.1 * rng.randn(E, f, d).astype(np.float32))
        ein = moe_ops.moe_ffn(x, valid, gate_w, w_up, w_down, k=k,
                              capacity=capacity)
        srt = moe_ops.moe_ffn(x, valid, gate_w, w_up, w_down, k=k,
                              capacity=capacity, dispatch_mode="sort")
        return ein, srt

    @pytest.mark.parametrize("k", [1, 2])
    def test_matches_einsum_no_overflow(self, k):
        (y0, a0), (y1, a1) = self._both(24, 8, 4, 16, k,
                                        capacity=24, seed=0)
        np.testing.assert_allclose(np.asarray(y1), np.asarray(y0),
                                   rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(float(a1), float(a0), rtol=1e-6)

    @pytest.mark.parametrize("k", [1, 2, 3])
    def test_matches_einsum_with_overflow_drops(self, k):
        # capacity 3 over 24 tokens / 4 experts forces real drops; the
        # two paths must drop the SAME (token, choice) pairs
        (y0, a0), (y1, a1) = self._both(24, 8, 4, 16, k,
                                        capacity=3, seed=1)
        np.testing.assert_allclose(np.asarray(y1), np.asarray(y0),
                                   rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(float(a1), float(a0), rtol=1e-6)

    def test_matches_einsum_with_invalid_rows(self):
        valid = jnp.asarray(
            np.array([1] * 10 + [0] * 6, np.float32))
        (y0, a0), (y1, a1) = self._both(16, 8, 4, 16, 2, capacity=4,
                                        seed=2, valid=valid)
        np.testing.assert_allclose(np.asarray(y1), np.asarray(y0),
                                   rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(float(a1), float(a0), rtol=1e-6)
        # padding rows produce zero output on both paths
        assert np.abs(np.asarray(y1)[10:]).max() == 0.0

    def test_grads_match_einsum(self):
        rng = np.random.RandomState(3)
        n, d, E, f, k = 16, 6, 4, 12, 2
        x = jnp.asarray(rng.randn(n, d).astype(np.float32))
        gate_w = jnp.asarray(rng.randn(d, E).astype(np.float32))
        w_up = jnp.asarray(0.1 * rng.randn(E, d, f).astype(np.float32))
        w_down = jnp.asarray(0.1 * rng.randn(E, f, d).astype(np.float32))

        def loss(mode, gw, wu, wd):
            y, aux = moe_ops.moe_ffn(x, None, gw, wu, wd, k=k,
                                     capacity=5, dispatch_mode=mode)
            return jnp.sum(y * y) + 0.01 * aux

        g0 = jax.grad(lambda *a: loss("einsum", *a),
                      argnums=(0, 1, 2))(gate_w, w_up, w_down)
        g1 = jax.grad(lambda *a: loss("sort", *a),
                      argnums=(0, 1, 2))(gate_w, w_up, w_down)
        for a, b in zip(g0, g1):
            np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                       rtol=1e-4, atol=1e-5)

    def test_layer_flag_reaches_op(self):
        from paddle_tpu.core import registry
        registry.reset_name_counters()
        paddle.init(use_tpu=False, seed=0)
        x = L.data("x", paddle.data_type.dense_vector(8))
        m = L.moe(x, expert_num=4, expert_hidden=16,
                  dispatch_mode="sort", name="m")
        assert m.config["dispatch_mode"] == "sort"
        topo = paddle.Topology(m)
        params = topo.init_params(jax.random.PRNGKey(0))
        xs = np.random.RandomState(0).randn(8, 8).astype("float32")
        outs, _ = topo.forward(params, {}, {"x": xs}, mode="test")
        assert np.isfinite(np.asarray(outs["m"])).all()

    def test_sort_rejects_ep_mesh(self):
        devs = jax.devices()[:2]
        mesh = create_mesh([("ep", 2)], devs)
        rng = np.random.RandomState(0)
        x = jnp.asarray(rng.randn(8, 4).astype(np.float32))
        with pytest.raises(AssertionError, match="single-host"):
            moe_ops.moe_ffn(
                x, None, jnp.zeros((4, 2)), jnp.zeros((2, 4, 8)),
                jnp.zeros((2, 8, 4)), k=1, dispatch_mode="sort",
                mesh=mesh)
