"""Parity tests for the fused conv_bn layer (ops/fused.conv_bn_train):
forward values, state updates, and end-to-end training gradients must
match the two-layer img_conv(bias_attr=False) -> batch_norm composition
exactly (f32 CPU) — the fusion is a schedule change, not a math change.
"""

import jax
import jax.numpy as jnp
import numpy as np

import paddle_tpu as paddle
from paddle_tpu.core import registry
from paddle_tpu.core.topology import Topology


def _build(fused, k=1, stride=1, padding=0, act=None):
    registry.reset_name_counters()
    h = w = 6
    c = 4
    img = paddle.layer.data("img",
                            paddle.data_type.dense_vector(c * h * w),
                            height=h, width=w)
    if fused:
        out = paddle.layer.conv_bn(img, filter_size=k, num_filters=5,
                                   stride=stride, padding=padding, act=act,
                                   num_channels=c, fuse_stats=True,
                                   name="cb")
    else:
        conv = paddle.layer.img_conv(img, filter_size=k, num_filters=5,
                                     stride=stride, padding=padding,
                                     bias_attr=False, act=None,
                                     num_channels=c, name="cb_conv")
        out = paddle.layer.batch_norm(conv, act=act, name="cb_bn")
    lbl = paddle.layer.data("y", paddle.data_type.integer_value(5))
    pool = paddle.layer.img_pool(out, pool_size=out.meta.height, stride=1,
                                 pool_type=paddle.pooling.Avg(),
                                 name="cb_gap")
    fc = paddle.layer.fc(pool, size=5, act=paddle.activation.Softmax(),
                         name="cb_fc")
    cost = paddle.layer.classification_cost(fc, lbl, name="cb_cost")
    return cost


def _train_once(fused, k=1, stride=1, padding=0, act=None):
    paddle.init(seed=0)
    cost = _build(fused, k, stride, padding, act)
    topo = Topology(cost)
    params = topo.init_params(jax.random.PRNGKey(7))

    # canonical name shared by the fused and two-layer builds
    def canon(name):
        return {"_cb.w0": "conv_w", "_cb_conv.w0": "conv_w",
                "_cb.wgamma": "gamma", "_cb_bn.w0": "gamma",
                "_cb.wbeta": "beta", "_cb_bn.wbias": "beta"}.get(name, name)

    aligned, vals = {}, {}
    for name, v in sorted(params.items()):
        key = canon(name)
        aligned[name] = key, v.shape
        if key not in vals:
            # value depends only on the canonical key, never on the
            # draw order (which differs between the two builds)
            rng = np.random.RandomState(abs(hash(key)) % 100000)
            if key == "gamma":
                vals[key] = (np.ones(v.shape)
                             + 0.1 * rng.randn(*v.shape)).astype(np.float32)
            else:
                vals[key] = rng.randn(*v.shape).astype(np.float32) * 0.3
        assert vals[key].shape == v.shape, (name, key)
        params[name] = jnp.asarray(vals[key])
    state = topo.init_state()

    feed_rng = np.random.RandomState(11)   # independent of param draws
    x = feed_rng.randn(8, 4 * 6 * 6).astype(np.float32)
    y = feed_rng.randint(0, 5, (8,)).astype(np.int32)
    feed = {"img": jnp.asarray(x), "y": jnp.asarray(y)}

    def loss_fn(p):
        outs, new_state = topo.forward(p, state, feed, mode="train",
                                       rng=jax.random.PRNGKey(0))
        return jnp.mean(outs[cost.name]), new_state

    (loss, new_state), grads = jax.value_and_grad(
        loss_fn, has_aux=True)(params)
    return loss, grads, new_state, aligned


class TestFusedConvBN:
    def _compare(self, k=1, stride=1, padding=0, act=None):
        loss_f, grads_f, state_f, names_f = _train_once(
            True, k, stride, padding, act)
        loss_r, grads_r, state_r, names_r = _train_once(
            False, k, stride, padding, act)
        np.testing.assert_allclose(float(loss_f), float(loss_r),
                                   rtol=1e-5, atol=1e-6)
        # grads keyed by the alignment key
        by_key_f = {names_f[n][0]: g for n, g in grads_f.items()}
        by_key_r = {names_r[n][0]: g for n, g in grads_r.items()}
        assert set(by_key_f) == set(by_key_r)
        for key in by_key_f:
            np.testing.assert_allclose(
                np.asarray(by_key_f[key]), np.asarray(by_key_r[key]),
                rtol=2e-4, atol=1e-5, err_msg=key)
        # moving-stat state updates match
        sf = {n.split(".")[-1]: v for n, v in state_f.items()
              if "moving" in n}
        sr = {n.split(".")[-1]: v for n, v in state_r.items()
              if "moving" in n}
        for kk in sf:
            np.testing.assert_allclose(np.asarray(sf[kk]),
                                       np.asarray(sr[kk]),
                                       rtol=1e-5, atol=1e-6, err_msg=kk)

    def test_1x1_fused_path_matches_two_layers(self):
        self._compare(k=1)

    def test_1x1_with_relu(self):
        self._compare(k=1, act=paddle.activation.Relu())

    def test_3x3_fallback_path_matches_two_layers(self):
        self._compare(k=3, stride=1, padding=1)

    def test_strided_fallback(self):
        self._compare(k=1, stride=2)

    def test_infer_uses_moving_stats(self):
        paddle.init(seed=0)
        cost = _build(True)
        topo = Topology(cost)
        params = topo.init_params(jax.random.PRNGKey(0))
        state = topo.init_state()
        rng = np.random.RandomState(0)
        feed = {"img": jnp.asarray(rng.randn(4, 4 * 6 * 6), jnp.float32),
                "y": jnp.asarray(np.zeros(4, np.int32))}
        outs_a, st_a = topo.forward(params, state, feed, mode="test")
        # test mode must not touch the moving stats
        for n, v in st_a.items():
            np.testing.assert_allclose(np.asarray(v),
                                       np.asarray(state[n]), err_msg=n)

    def test_zero_gamma_gradient_matches_unfused(self):
        """A pruned (exactly-zero) gamma channel must still get the TRUE
        dgamma (so it can un-prune) — the gradients of the fused op must
        match the unfused conv+BN composition even at gamma == 0."""
        from paddle_tpu.ops import conv as conv_ops
        from paddle_tpu.ops import fused as fused_ops
        from paddle_tpu.ops import norm as norm_ops
        rng = np.random.RandomState(0)
        x = jnp.asarray(rng.randn(2, 3, 3, 4), jnp.float32)
        w = jnp.asarray(rng.randn(1, 1, 4, 3), jnp.float32)
        gamma = jnp.asarray([1.0, 0.0, -0.5], jnp.float32)
        beta = jnp.asarray([0.1, 0.2, 0.3], jnp.float32)

        def loss_fused(x, w, gamma, beta):
            z, m, v = fused_ops.conv_bn_train(x, w, gamma, beta, 1e-5)
            return jnp.sum(z ** 2) + jnp.sum(m) + jnp.sum(v)

        def loss_ref(x, w, gamma, beta):
            c = conv_ops.conv2d(x, w, stride=1, padding=0)
            z, nm, nv = norm_ops.batch_norm_train(
                c, gamma, beta, jnp.zeros_like(gamma),
                jnp.ones_like(gamma), momentum=0.0, eps=1e-5)
            return jnp.sum(z ** 2) + jnp.sum(nm) + jnp.sum(nv)

        gf = jax.grad(loss_fused, argnums=(0, 1, 2, 3))(x, w, gamma, beta)
        gr = jax.grad(loss_ref, argnums=(0, 1, 2, 3))(x, w, gamma, beta)
        for a, b, nm in zip(gf, gr, ("dx", "dw", "dgamma", "dbeta")):
            assert np.isfinite(np.asarray(a)).all(), nm
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-4, atol=1e-5, err_msg=nm)
