"""Cross-process trace/journal merge (paddle_tpu/obs/merge.py,
`paddle_tpu trace merge`, tools/trace_merge.py) — acceptance suite.

Unit tier: offset resolution (explicit > clock_sync > 0), monotone
merged seq, trace fusion mechanics over hand-built inputs. Chaos tier
(THE ISSUE-8 acceptance): two subprocess coordinator workers — one
with an injected 2.5 s clock skew — merge into ONE journal whose step
records interleave in true order with strictly monotone mseq, and one
Perfetto trace containing both hosts.
"""

import json
import os
import subprocess
import sys
import time

import pytest

from paddle_tpu.obs.merge import (journal_clock_offset, merge_journals,
                                  merge_traces)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _write_journal(path, host, base_ts, kinds, offset_s=None):
    """Hand-built schema-valid journal: full control over ts/host."""
    recs = []
    seq = 0
    if offset_s is not None:
        seq += 1
        recs.append({"v": 1, "ts": base_ts, "seq": seq, "pid": 1,
                     "domain": "coordinator", "kind": "clock_sync",
                     "host": host, "run_id": "r", "offset_s": offset_s})
    for i, kind in enumerate(kinds):
        seq += 1
        recs.append({"v": 1, "ts": base_ts + 0.1 * i, "seq": seq,
                     "pid": 1, "domain": "trainer", "kind": kind,
                     "host": host, "run_id": "r", "step": i})
    with open(path, "w") as f:
        for r in recs:
            f.write(json.dumps(r) + "\n")
    return recs


class TestMergeUnit:
    def test_offsets_from_clock_sync_and_monotone_mseq(self, tmp_path):
        a = str(tmp_path / "a.jsonl")
        b = str(tmp_path / "b.jsonl")
        # host-b's clock reads 5 s ahead; unadjusted, ALL its records
        # sort after host-a's — the clock_sync record must fix that
        _write_journal(a, "host-a", 100.0, ["s0", "s1", "s2"])
        _write_journal(b, "host-b", 105.05, ["s0", "s1", "s2"],
                       offset_s=5.0)
        assert journal_clock_offset(b) == 5.0
        assert journal_clock_offset(a) is None
        merged = merge_journals([a, b])
        assert [r["mseq"] for r in merged] == \
            list(range(1, len(merged) + 1))
        order = [(r["host"], r["kind"]) for r in merged
                 if r["kind"].startswith("s")]
        # true order interleaves: a/s0, b/s0(=100.05), a/s1, b/s1, ...
        assert order == [("host-a", "s0"), ("host-b", "s0"),
                         ("host-a", "s1"), ("host-b", "s1"),
                         ("host-a", "s2"), ("host-b", "s2")]
        ts_adj = [r["ts_adj"] for r in merged]
        assert ts_adj == sorted(ts_adj)
        # per-process seq survives untouched for provenance
        assert all("seq" in r for r in merged)

    def test_explicit_offset_beats_clock_sync(self, tmp_path):
        a = str(tmp_path / "a.jsonl")
        _write_journal(a, "host-a", 100.0, ["s0"], offset_s=5.0)
        (rec,) = [r for r in merge_journals(
            [a], offsets={"host-a": 50.0}) if r["kind"] == "s0"]
        assert rec["ts_adj"] == pytest.approx(50.0)

    def test_merged_journal_file_round_trips(self, tmp_path):
        a = str(tmp_path / "a.jsonl")
        _write_journal(a, "host-a", 100.0, ["s0", "s1"])
        out = str(tmp_path / "merged.jsonl")
        merge_journals([a], out=out)
        from paddle_tpu.obs.events import read_journal
        recs = list(read_journal(out))
        assert [r["mseq"] for r in recs] == [1, 2]

    def test_trace_fusion_relabels_processes(self, tmp_path):
        def trace(path, host, pid, ts0):
            blob = {"traceEvents": [
                {"ph": "M", "name": "process_name", "pid": pid,
                 "tid": 0, "args": {"name": "old"}},
                {"ph": "X", "name": "step", "pid": pid, "tid": 1,
                 "ts": ts0, "dur": 50.0, "args": {}}],
                "metadata": {"host": host, "pid": pid, "run_id": "r"}}
            with open(path, "w") as f:
                json.dump(blob, f)
            return path

        # both exports claim pid 7 — the merge must give them lanes
        t1 = trace(str(tmp_path / "t1.json"), "host-a", 7, 1000.0)
        t2 = trace(str(tmp_path / "t2.json"), "host-b", 7, 3_500_000.0)
        merged = merge_traces([t1, t2],
                              offsets={"host-b": 2.5})  # 2.5 s skew
        evs = merged["traceEvents"]
        names = {e["args"]["name"] for e in evs if e["ph"] == "M"}
        assert names == {"host-a pid=7", "host-b pid=7"}
        xs = {e["args"]["host"]: e for e in evs if e["ph"] == "X"}
        assert len({e["pid"] for e in xs.values()}) == 2  # distinct lanes
        # host-b's 3.5 s came back to 1.0 s on the reference clock
        assert xs["host-b"]["ts"] == pytest.approx(1_000_000.0)


class TestTwoWorkerAcceptance:
    """THE acceptance: trace merge over two subprocess coordinator
    workers yields one timeline containing both hosts' steps with
    monotone merged seq — clock skew adjusted via the coordinator
    heartbeat-channel offsets."""

    @pytest.mark.chaos(timeout=300)
    def test_two_skewed_workers_one_timeline(self, tmp_path):
        from paddle_tpu.trainer.coordinator import (Coordinator,
                                                    CoordinatorServer)
        coord = Coordinator(list(range(4)))
        server = CoordinatorServer(coord, port=0).start()
        worker = os.path.join(REPO, "tests", "trace_merge_worker.py")
        go = str(tmp_path / "go")
        env = {**os.environ, "JAX_PLATFORMS": "cpu"}
        env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH",
                                                        "")
        procs, journals, traces = [], [], []
        try:
            for i, skew in ((0, 0.0), (1, 2.5)):
                jp = str(tmp_path / f"w{i}.jsonl")
                tp = str(tmp_path / f"w{i}_trace.json")
                journals.append(jp)
                traces.append(tp)
                procs.append(subprocess.Popen(
                    [sys.executable, worker, str(server.port), jp, tp,
                     f"worker-{i}", str(skew), "6", "run-merge", go],
                    env=env, cwd=REPO, stdout=subprocess.PIPE,
                    stderr=subprocess.PIPE, text=True))
            # both workers up + clock-synced, then step together
            for p in procs:
                assert p.stdout.readline().strip() == "READY", \
                    p.stderr.read()
            with open(go, "w") as f:
                f.write("go")
            for p in procs:
                assert p.wait(timeout=240) == 0, p.stderr.read()
        finally:
            for p in procs:
                if p.poll() is None:
                    p.kill()
            server.stop()

        # worker-1's measured offset is its injected skew (the RPC
        # round trip adds only noise)
        off1 = journal_clock_offset(journals[1])
        assert off1 == pytest.approx(2.5, abs=0.5)
        assert abs(journal_clock_offset(journals[0])) < 0.5

        # ---- the merged journal, via the CLI verb
        out_j = str(tmp_path / "merged.jsonl")
        out_t = str(tmp_path / "merged_trace.json")
        from paddle_tpu.cli import main as cli_main
        rc = cli_main(["trace", "merge",
                       "--journal", journals[0], journals[1],
                       "--trace", traces[0], traces[1],
                       "--out-journal", out_j, "--out-trace", out_t])
        assert rc == 0
        from paddle_tpu.obs.events import read_journal
        merged = list(read_journal(out_j))
        assert {r["host"] for r in merged} == {"worker-0", "worker-1"}
        assert {r["run_id"] for r in merged} == {"run-merge"}
        assert [r["mseq"] for r in merged] == \
            list(range(1, len(merged) + 1))
        ts_adj = [r["ts_adj"] for r in merged]
        assert ts_adj == sorted(ts_adj)
        steps = [(r["host"], r["step"]) for r in merged
                 if r["kind"] == "step"]
        assert len(steps) == 12
        # RAW ordering is disjoint (2.5 s skew > the 0.7 s step
        # window): every worker-1 ts is later than every worker-0 ts
        raw1 = [r["ts"] for r in read_journal(journals[1],
                                              kind="step")]
        raw0 = [r["ts"] for r in read_journal(journals[0],
                                              kind="step")]
        assert min(raw1) > max(raw0)
        # ...but the MERGED timeline interleaves them back: worker-1
        # steps appear before worker-0's last step
        hosts_in_order = [h for h, _ in steps]
        first_w1 = hosts_in_order.index("worker-1")
        last_w0 = len(hosts_in_order) - 1 - \
            hosts_in_order[::-1].index("worker-0")
        assert first_w1 < last_w0, \
            "skew adjustment failed: the merged timeline kept the " \
            "raw (disjoint) ordering"
        # per-host step numbering stays monotone after the merge
        for h in ("worker-0", "worker-1"):
            seq = [s for hh, s in steps if hh == h]
            assert seq == sorted(seq)

        # ---- the merged Perfetto trace
        with open(out_t) as f:
            mt = json.load(f)
        evs = mt["traceEvents"]
        lanes = {e["args"]["name"] for e in evs if e["ph"] == "M"}
        assert len(lanes) == 2 and \
            {n.split(" ")[0] for n in lanes} == {"worker-0", "worker-1"}
        spans = [e for e in evs if e["ph"] == "X"
                 and e["name"] == "worker_step"]
        assert len(spans) == 12
        by_host = {}
        for e in spans:
            by_host.setdefault(e["args"]["host"], []).append(e["ts"])
        # adjusted span windows overlap (they ran simultaneously)
        assert min(by_host["worker-1"]) < max(by_host["worker-0"])
        assert min(by_host["worker-0"]) < max(by_host["worker-1"])
