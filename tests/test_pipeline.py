"""Pipeline parallelism vs sequential stage application (the
ParallelNeuralNetwork equivalence check: same math, pipelined)."""

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from paddle_tpu.parallel import create_mesh, PP_AXIS
from paddle_tpu.parallel.pipeline import pipeline


def _stage(params, x):
    return jnp.tanh(x @ params["w"] + params["b"])


def _sequential(stage_params, x):
    n = stage_params["w"].shape[0]
    for i in range(n):
        x = _stage({"w": stage_params["w"][i],
                    "b": stage_params["b"][i]}, x)
    return x


@pytest.fixture(scope="module")
def mesh():
    return create_mesh([(PP_AXIS, 4)])


def _params(n=4, d=16, seed=0):
    rng = np.random.RandomState(seed)
    return {"w": jnp.asarray(rng.randn(n, d, d).astype("float32") * 0.3),
            "b": jnp.asarray(rng.randn(n, d).astype("float32") * 0.1)}


class TestPipeline:
    def test_matches_sequential(self, mesh):
        params = _params()
        x = jnp.asarray(np.random.RandomState(1).randn(8, 16)
                        .astype("float32"))
        ref = _sequential(params, x)
        out = pipeline(_stage, params, x, mesh)
        np.testing.assert_allclose(np.asarray(ref), np.asarray(out),
                                   rtol=1e-5, atol=1e-6)

    def test_more_microbatches(self, mesh):
        params = _params(seed=2)
        x = jnp.asarray(np.random.RandomState(3).randn(16, 16)
                        .astype("float32"))
        ref = _sequential(params, x)
        out = pipeline(_stage, params, x, mesh, num_microbatches=8)
        np.testing.assert_allclose(np.asarray(ref), np.asarray(out),
                                   rtol=1e-5, atol=1e-6)

    def test_differentiable(self, mesh):
        params = _params(seed=4)
        x = jnp.asarray(np.random.RandomState(5).randn(8, 16)
                        .astype("float32"))

        def loss_pipe(p):
            return jnp.sum(pipeline(_stage, p, x, mesh) ** 2)

        def loss_seq(p):
            return jnp.sum(_sequential(p, x) ** 2)

        g_pipe = jax.grad(loss_pipe)(params)
        g_seq = jax.grad(loss_seq)(params)
        for k in g_seq:
            np.testing.assert_allclose(np.asarray(g_pipe[k]),
                                       np.asarray(g_seq[k]),
                                       rtol=1e-4, atol=1e-5)

    def test_inside_jit(self, mesh):
        params = _params(seed=6)
        x = jnp.asarray(np.random.RandomState(7).randn(8, 16)
                        .astype("float32"))

        @jax.jit
        def f(p, x):
            return pipeline(_stage, p, x, mesh)

        np.testing.assert_allclose(np.asarray(f(params, x)),
                                   np.asarray(_sequential(params, x)),
                                   rtol=1e-5, atol=1e-6)
