"""ptproto runtime-witness acceptance (paddle_tpu/obs/protocol.py;
docs/observability.md "Protocol contracts").

Three planes under test:

- the **witness machines**: declared protocols advance per correlation
  key off the REAL journal observer seam (not a private API) —
  completion, supersede-vs-extend on restart, orphan-terminal
  violations, explicit ``finalize()`` for unterminated machines;
- the **chaos acceptance**: a deliberately torn hop yields exactly one
  ``protocol/violation`` record whose chain reconstructs the machine's
  history, and the flight recorder auto-dumps a bundle naming the key;
- the **one-definition pin**: the soak verdict and the witness consume
  the SAME ``obs.catalog`` declaration objects, so they cannot drift.

Plus the EventJournal.emit argument-validation regression (reserved
envelope fields, empty domain/kind).
"""

import glob
import json
import os

import pytest

from paddle_tpu import obs
from paddle_tpu.obs import catalog
from paddle_tpu.obs.events import RESERVED_FIELDS, emit
from paddle_tpu.obs.flight import FLIGHT


class TestWitnessMachines:
    def test_start_then_terminal_completes(self):
        emit("serving", "hop", trace_id="t-ok", phase="start")
        assert obs.WITNESS.counts()["tracked"] == {"serving_hop": 1}
        emit("serving", "hop", trace_id="t-ok", phase="settle",
             tokens=3)
        c = obs.WITNESS.counts()
        assert c["tracked"] == {}
        assert c["completed"] == {"serving_hop": 1}
        assert c["violations"] == 0

    def test_keys_are_independent_machines(self):
        emit("serving", "hop", trace_id="t-a", phase="start")
        emit("serving", "hop", trace_id="t-b", phase="start")
        emit("serving", "hop", trace_id="t-a", phase="error",
             reason="boom")
        c = obs.WITNESS.counts()
        assert c["tracked"] == {"serving_hop": 1}
        assert c["completed"] == {"serving_hop": 1}

    def test_restart_supersedes_serving_hop(self):
        emit("serving", "hop", trace_id="t-s", phase="start")
        emit("serving", "hop", trace_id="t-s", phase="start")
        c = obs.WITNESS.counts()
        assert c["tracked"] == {"serving_hop": 1}
        assert c["superseded"] == {"serving_hop": 1}
        emit("serving", "hop", trace_id="t-s", phase="settle")
        assert obs.WITNESS.counts()["completed"] == {"serving_hop": 1}

    def test_restart_extends_fleet_request(self):
        # a re-route after failover CONTINUES the same request machine
        # (catalog: fleet_request.on_restart == "extend"), and the
        # failover intermediate lands in the chain
        emit("fleet", "route", trace_id="t-f", replica="r0", hop=1)
        emit("fleet", "failover", trace_id="t-f", victim="r0",
             next="r1")
        emit("fleet", "route", trace_id="t-f", replica="r1", hop=2)
        c = obs.WITNESS.counts()
        assert c["tracked"] == {"fleet_request": 1}
        assert c["superseded"] == {}
        [machine] = obs.WITNESS.open_machines()
        kinds = [r["kind"] for r in machine["chain"]]
        assert kinds == ["route", "failover", "route"]
        emit("fleet", "settle", trace_id="t-f", replica="r1",
             hops=2, failovers=1)
        assert obs.WITNESS.counts()["completed"] == {"fleet_request": 1}

    @pytest.mark.protocol_violation_expected
    def test_orphan_terminal_is_live_violation(self):
        # settle for a trace never started — exactly-once broken
        emit("fleet", "settle", trace_id="t-orphan", replica="r0",
             hops=1, failovers=0)
        [v] = obs.WITNESS.violations()
        assert v["protocol"] == "fleet_request"
        assert v["key"] == "t-orphan"
        assert v["reason"] == "orphan_terminal"

    def test_orphan_reject_is_not_violation(self):
        # reject is a declared terminal with orphan_violates=False: a
        # router can reject before ever routing (queue_full at admission)
        emit("fleet", "reject", trace_id="t-rej", reason="queue_full")
        assert obs.WITNESS.violation_count == 0

    def test_unterminated_only_on_finalize(self):
        # a hop that never settles is NOT a live violation — a
        # SIGKILL'd replica legitimately leaves one
        # (tests/test_fleet_faults.py pins that shape)
        emit("serving", "hop", trace_id="t-open", phase="start")
        assert obs.WITNESS.violation_count == 0

    def test_gauges_ride_the_registry_collector(self):
        from paddle_tpu.obs.metrics import REGISTRY
        emit("serving", "hop", trace_id="t-m1", phase="start")
        emit("serving", "hop", trace_id="t-m1", phase="settle")
        emit("serving", "hop", trace_id="t-m2", phase="start")
        text = REGISTRY.exposition()
        assert ('paddle_tpu_protocol_completed{protocol="serving_hop"}'
                ' 1') in text
        assert ('paddle_tpu_protocol_tracked{protocol="serving_hop"}'
                ' 1') in text


class TestChaosAcceptance:
    """The ISSUE acceptance: a deliberately torn hop -> exactly one
    protocol/violation with a reconstructible chain, and the flight
    recorder's auto-dumped bundle names the key."""

    @pytest.mark.protocol_violation_expected
    def test_torn_hop_journals_one_violation_with_chain(self):
        emit("serving", "hop", trace_id="t-torn", phase="start")
        out = obs.WITNESS.finalize()
        assert len(out) == 1
        v = out[0]
        assert v["protocol"] == "serving_hop"
        assert v["key"] == "t-torn"
        assert v["reason"] == "unterminated"
        # the chain reconstructs the machine's history by seq
        assert [r["kind"] for r in v["chain"]] == ["hop"]
        assert v["chain"][0]["phase"] == "start"
        assert v["chain"][0]["trace_id"] == "t-torn"
        assert isinstance(v["chain"][0]["seq"], int)
        # exactly one protocol/violation record in the journal ring
        recs = obs.JOURNAL.tail(50, domain="protocol")
        assert len(recs) == 1
        assert recs[0]["kind"] == "violation"
        assert recs[0]["key"] == "t-torn"
        assert recs[0]["reason"] == "unterminated"
        # finalize is idempotent once machines are drained
        assert obs.WITNESS.finalize() == []

    @pytest.mark.protocol_violation_expected
    def test_violation_autodumps_bundle_naming_the_key(self, tmp_path):
        FLIGHT.configure(dump_dir=str(tmp_path))
        emit("serving", "hop", trace_id="t-dump", phase="settle")
        bundles = glob.glob(os.path.join(str(tmp_path), "flight-*.json"))
        assert len(bundles) == 1
        assert "protocol_violation" in os.path.basename(bundles[0])
        with open(bundles[0], encoding="utf-8") as f:
            b = json.load(f)
        assert b["reason"] == "protocol_violation"
        tail = b["journal"]["tail"]
        viol = [r for r in tail if r.get("domain") == "protocol"]
        assert len(viol) == 1 and viol[0]["key"] == "t-dump"


class TestOneDefinition:
    """Verdict, witness, and R13 all consume obs.catalog.PROTOCOLS —
    one declaration, pinned here so a fork can never drift."""

    def test_verdict_imports_the_same_objects(self):
        from paddle_tpu.loadgen import verdict
        assert verdict.PROTOCOLS is catalog.PROTOCOLS
        assert verdict.FAULT_FAMILIES is catalog.FAULT_FAMILIES

    def test_witness_consumes_the_same_objects(self):
        assert obs.WITNESS._protocols == catalog.PROTOCOLS

    def test_every_fault_family_maps_to_a_protocol(self):
        for fam, spec in catalog.FAULT_FAMILIES.items():
            proto = catalog.PROTOCOLS[spec.protocol]
            assert proto.terminals, fam
            if spec.fault_key is not None:
                assert proto.key is not None, fam

    def test_every_protocol_event_is_a_catalogued_journal_kind(self):
        for p in catalog.PROTOCOLS.values():
            matches = [p.start] + list(p.intermediates) + \
                [t.match for t in p.terminals]
            for m in matches:
                assert (m.domain, m.kind) in catalog.JOURNALS, \
                    f"{p.name}: ({m.domain}/{m.kind}) not catalogued"

    def test_verdict_chain_reconstruction_from_declarations(self):
        # family k through the declared fleet_lease machine
        from paddle_tpu.loadgen.verdict import _fault_chain
        records = [
            {"domain": "fleet", "kind": "lease_lapse", "replica": "r1"},
            {"domain": "fleet", "kind": "rejoin", "replica": "r1"},
        ]
        out = _fault_chain(records, {"family": "k", "replica": "r1"})
        assert out["ok"] and out["lapses"] == 1 and out["rejoins"] == 1
        out2 = _fault_chain(list(reversed(records)),
                            {"family": "k", "replica": "r1"})
        assert not out2["ok"]


class TestEmitValidation:
    """EventJournal.emit argument validation (satellite 6): empty or
    non-str domain/kind and envelope-reserved fields are rejected at
    the emit site, not discovered downstream by a reader."""

    def test_rejects_empty_or_nonstr_domain_kind(self):
        with pytest.raises(ValueError, match="domain"):
            emit("", "kind")
        with pytest.raises(ValueError, match="domain"):
            emit(None, "kind")
        with pytest.raises(ValueError, match="kind"):
            emit("obs", "")
        with pytest.raises(ValueError, match="kind"):
            emit("obs", 7)

    def test_rejects_reserved_envelope_fields(self):
        for bad in sorted(RESERVED_FIELDS):
            with pytest.raises(ValueError, match="reserved"):
                emit("obs", "selfcheck", **{bad: "x", "probe": 1})

    def test_caller_trace_id_and_step_still_allowed(self):
        # trace_id/step are context-stamped but caller-overridable by
        # design — they are NOT reserved
        emit("serving", "hop", trace_id="t-v", phase="start")
        emit("serving", "hop", trace_id="t-v", phase="settle", step=4)
        rec = obs.JOURNAL.tail(1)[0]
        assert rec["trace_id"] == "t-v" and rec["step"] == 4
