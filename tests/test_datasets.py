"""Dataset loader tests — python/paddle/v2/dataset parity (14 loaders).

Each loader yields the documented sample layout both from the synthetic
fallback and (where a text format exists) from real files parsed out of a
temp DATA_HOME — so the real-data path is exercised hermetically."""

import os

import numpy as np
import pytest

import paddle_tpu.dataset as D
from paddle_tpu.dataset import common


@pytest.fixture
def data_home(tmp_path, monkeypatch):
    monkeypatch.setattr(common, "DATA_HOME", str(tmp_path))
    yield str(tmp_path)


def _first(reader, n=3):
    out = []
    for s in reader():
        out.append(s)
        if len(out) >= n:
            break
    return out


class TestSyntheticFallbacks:
    def test_movielens_layout(self):
        s = _first(D.movielens.train())[0]
        uid, g, age, job, mid, cats, tids, score = s
        assert 1 <= uid <= D.movielens.max_user_id()
        assert g in (0, 1) and 0 <= age < len(D.movielens.age_table())
        assert isinstance(cats, list) and isinstance(tids, list)
        assert 1.0 <= score <= 5.0

    def test_conll05_layout(self):
        s = _first(D.conll05.train())[0]
        assert len(s) == 9
        n = len(s[0])
        assert all(len(f) == n for f in s)
        assert set(s[7]) <= {0, 1}                      # mark is binary

    def test_wmt14_layout(self):
        src, trg, trg_next = _first(D.wmt14.train())[0]
        assert trg[0] == D.wmt14.START
        assert trg_next[-1] == D.wmt14.END
        assert trg[1:] == trg_next[:-1]

    def test_sentiment_layout(self):
        ids, label = _first(D.sentiment.train())[0]
        assert label in (0, 1) and all(
            0 <= i < D.sentiment.WORD_DICT_LEN for i in ids)

    def test_mq2007_formats(self):
        f, rel = _first(D.mq2007.train("pointwise"))[0]
        assert f.shape == (D.mq2007.FEATURE_DIM,)
        hi, lo = _first(D.mq2007.train("pairwise"))[0]
        assert hi.shape == lo.shape == (D.mq2007.FEATURE_DIM,)
        qid, feats, labels = _first(D.mq2007.train("listwise"))[0]
        assert len(feats) == len(labels) > 0

    def test_flowers_and_voc(self):
        img, lbl = _first(D.flowers.train())[0]
        assert img.shape == (3 * 32 * 32,) and 0 <= lbl < 102
        img, mask = _first(D.voc2012.train())[0]
        assert img.shape == (3 * 32 * 32,) and mask.shape == (32 * 32,)
        assert mask.max() < D.voc2012.N_CLASSES

    def test_deterministic(self):
        a = _first(D.sentiment.train(), 5)
        b = _first(D.sentiment.train(), 5)
        assert a == b

    def test_fourteen_loaders_present(self):
        names = ["mnist", "imdb", "imikolov", "uci_housing",
                 "conll05", "movielens", "wmt14", "flowers", "voc2012",
                 "sentiment", "mq2007"]
        for n in names:
            mod = getattr(D, n)
            assert callable(mod.train)
        assert callable(D.cifar.train10) and callable(D.cifar.train100)


class TestRealFileParsing:
    def test_movielens_dat(self, data_home):
        d = os.path.join(data_home, "movielens")
        os.makedirs(d)
        with open(os.path.join(d, "users.dat"), "w") as f:
            f.write("1::F::18::4::12345\n2::M::25::7::54321\n")
        with open(os.path.join(d, "movies.dat"), "w") as f:
            f.write("10::Toy Story (1995)::Animation|Comedy\n"
                    "20::Heat (1995)::Action\n")
        with open(os.path.join(d, "ratings.dat"), "w") as f:
            f.write("1::10::5::978300760\n2::20::3::978302109\n"
                    "1::20::4::978301968\n")
        samples = list(D.movielens.train()()) + list(D.movielens.test()())
        assert len(samples) == 3
        uid, g, age, job, mid, cats, tids, score = samples[0]
        assert (uid, g, age, job, mid) == (1, 0, 1, 4, 10)
        assert score == 5.0 and len(cats) == 2

    def test_mq2007_letor(self, data_home):
        d = os.path.join(data_home, "mq2007")
        os.makedirs(d)
        with open(os.path.join(d, "train.txt"), "w") as f:
            f.write("2 qid:1 1:0.5 2:0.25 # doc1\n"
                    "0 qid:1 1:0.1 2:0.9 # doc2\n"
                    "1 qid:2 1:0.7 # doc3\n")
        pts = list(D.mq2007.train("pointwise")())
        assert len(pts) == 3
        np.testing.assert_allclose(pts[0][0][:2], [0.5, 0.25])
        assert pts[0][1] == 2.0
        pairs = list(D.mq2007.train("pairwise")())
        assert len(pairs) == 1                     # only qid:1 has a pair
        lists = list(D.mq2007.train("listwise")())
        assert [len(l[1]) for l in lists] == [2, 1]

    def test_conll05_tsv(self, data_home):
        d = os.path.join(data_home, "conll05")
        os.makedirs(d)
        with open(os.path.join(d, "train.txt"), "w") as f:
            f.write("The\t-\tB-A0\nsaw\tsaw\tB-V\nend\t-\tO\n\n"
                    "Go\tgo\tB-V\n")
        samples = list(D.conll05.train()())
        assert len(samples) == 2
        words = samples[0][0]
        assert len(words) == 3 and samples[0][7] == [0, 1, 0]

    def test_sentiment_tsv(self, data_home):
        d = os.path.join(data_home, "sentiment")
        os.makedirs(d)
        with open(os.path.join(d, "train.txt"), "w") as f:
            f.write("1\tgreat movie\n0\tterrible plot\n")
        samples = list(D.sentiment.train()())
        assert [s[1] for s in samples] == [1, 0]

    def test_wmt14_parallel(self, data_home):
        d = os.path.join(data_home, "wmt14")
        os.makedirs(d)
        with open(os.path.join(d, "train.src"), "w") as f:
            f.write("5 6 7\n8 9\n")
        with open(os.path.join(d, "train.trg"), "w") as f:
            f.write("10 11\n12\n")
        samples = list(D.wmt14.train()())
        assert samples[0][0] == [5, 6, 7]
        assert samples[0][1] == [D.wmt14.START, 10, 11]
        assert samples[0][2] == [10, 11, D.wmt14.END]

    def test_flowers_npz(self, data_home):
        d = os.path.join(data_home, "flowers")
        os.makedirs(d)
        imgs = (np.arange(2 * 3 * 8 * 8) % 255).reshape(2, 3, 8, 8)
        np.savez(os.path.join(d, "train.npz"),
                 images=imgs.astype(np.uint8),
                 labels=np.array([3, 99]))
        samples = list(D.flowers.train()())
        assert len(samples) == 2
        assert samples[0][0].shape == (3 * 8 * 8,)
        assert samples[1][1] == 99


class TestMd5Manifest:
    """Satellite (docs/robustness.md): an optional MD5SUMS manifest in a
    module's DATA_HOME dir verifies real drop-ins; a mismatch warns and
    falls back to the synthetic generator instead of training on
    corrupt data."""

    def _write(self, data_home, module, filename, payload):
        d = os.path.join(data_home, module)
        os.makedirs(d, exist_ok=True)
        with open(os.path.join(d, filename), "wb") as f:
            f.write(payload)
        return os.path.join(d, filename)

    def test_no_manifest_passes(self, data_home):
        self._write(data_home, "m", "f.bin", b"payload")
        assert common.has_cached("m", "f.bin")

    def test_matching_manifest_passes(self, data_home):
        p = self._write(data_home, "m", "f.bin", b"payload")
        digest = common.file_md5(p)
        with open(os.path.join(data_home, "m", common.MANIFEST_NAME),
                  "w") as f:
            f.write(f"{digest}  f.bin\nsomethingelse  other.bin\n")
        assert common.has_cached("m", "f.bin")

    def test_mismatch_warns_and_rejects(self, data_home):
        self._write(data_home, "m", "f.bin", b"CORRUPTED")
        with open(os.path.join(data_home, "m", common.MANIFEST_NAME),
                  "w") as f:
            f.write("0" * 32 + "  f.bin\n")
        with pytest.warns(UserWarning, match="md5 mismatch"):
            assert not common.has_cached("m", "f.bin")

    def test_explicit_md5_arg(self, data_home):
        p = self._write(data_home, "m", "f.bin", b"payload")
        assert common.has_cached("m", "f.bin", md5=common.file_md5(p))
        with pytest.warns(UserWarning, match="md5 mismatch"):
            assert not common.has_cached("m", "f.bin", md5="0" * 32)

    def test_corrupt_mnist_falls_back_to_synthetic(self, data_home):
        # garbage gz files + a manifest that disowns them: the loader
        # must warn and serve the synthetic set instead of crashing
        for name in ("train-images-idx3-ubyte.gz",
                     "train-labels-idx1-ubyte.gz"):
            self._write(data_home, "mnist", name, b"not a gzip")
        with open(os.path.join(data_home, "mnist", common.MANIFEST_NAME),
                  "w") as f:
            f.write("1" * 32 + "  train-images-idx3-ubyte.gz\n")
        with pytest.warns(UserWarning, match="md5 mismatch"):
            samples = _first(D.mnist.train(), 2)
        assert samples[0][0].shape == (784,)
