"""Subprocess trainer for the SIGKILL auto-resume chaos test
(tests/test_faults.py): a plain (non-coordinator) run with
--checkpoint_dir/--checkpoint_period/--auto_resume semantics, printing a
'STEP n' marker per completed batch so FaultPlan.kill_at_marker can
SIGKILL it at an exact step, and a params digest at the end so the
resumed run can be compared bit-for-bit with an uninterrupted one.

argv: <ckpt_dir> <num_passes> <per_step_delay_s>
"""

import hashlib
import sys
import time


def main():
    ckpt_dir = sys.argv[1]
    num_passes = int(sys.argv[2])
    delay = float(sys.argv[3])

    import jax
    jax.config.update("jax_platforms", "cpu")
    import numpy as np
    import paddle_tpu as paddle

    paddle.init(seed=0)
    x = paddle.layer.data("x", paddle.data_type.dense_vector(8))
    y = paddle.layer.data("y", paddle.data_type.integer_value(2))
    out = paddle.layer.fc(x, size=2, act=paddle.activation.Softmax(),
                          name="out")
    cost = paddle.layer.classification_cost(out, y, name="cost")
    params = paddle.create_parameters(paddle.Topology(cost))
    tr = paddle.SGD(cost=cost, parameters=params,
                    update_equation=paddle.optimizer.Momentum(
                        learning_rate=0.05))

    def reader():
        rng = np.random.RandomState(42)
        for _ in range(6):
            f = rng.randn(4, 8).astype("float32")
            lbl = rng.randint(0, 2, 4)
            yield [(f[i], int(lbl[i])) for i in range(4)]

    def handler(e):
        if isinstance(e, paddle.event.EndIteration):
            print(f"STEP {tr._step_count}", flush=True)
            if delay:
                time.sleep(delay)

    tr.train(reader, num_passes=num_passes, event_handler=handler,
             checkpoint_dir=ckpt_dir, checkpoint_period=1,
             auto_resume=True)

    h = hashlib.md5()
    for k in sorted(tr.parameters.raw):
        h.update(k.encode())
        h.update(np.ascontiguousarray(
            np.asarray(tr.parameters.raw[k])).tobytes())
    print(f"WORKER DONE steps={tr._step_count} digest={h.hexdigest()}",
          flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
