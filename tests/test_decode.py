"""KV-cache transformer decoding vs the training graph.

The decoder (models/decode.py) re-derives the forward functionally from
the DSL's parameter table; these tests pin it against the training
graph token for token (greedy decode must follow the graph's argmax
chain exactly), plus cache-correctness and sampling behavior.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import models
from paddle_tpu.core.sequence import SequenceBatch

CFG = dict(vocab_size=40, d_model=16, n_heads=2, n_layers=2, d_ff=32,
           max_len=32)


def _model(seed=7, **overrides):
    paddle.init(use_tpu=False, seed=0)
    from paddle_tpu.core.registry import reset_name_counters
    reset_name_counters()
    spec = models.transformer_lm(**{**CFG, **overrides})
    costs = spec.cost if isinstance(spec.cost, list) else [spec.cost]
    # include the (paramless) probs node so _graph_argmax can read it
    topo = paddle.Topology(costs, extra_outputs=[spec.output])
    params = topo.init_params(jax.random.PRNGKey(seed))
    return spec, topo, params


def _graph_argmax(topo, spec, params, prefix):
    """Training-graph next-token argmax for each row of `prefix` [b, t]."""
    b, t = prefix.shape
    lens = jnp.full((b,), t, jnp.int32)
    sb = lambda a: SequenceBatch(jnp.asarray(a), lens)
    pos = np.tile(np.arange(t, dtype="int32"), (b, 1))
    feed = {spec.data.name: sb(prefix), spec.positions.name: sb(pos),
            spec.label.name: sb(prefix)}
    outs, _ = topo.forward(params, topo.init_state(), feed, mode="test",
                           output_names=[spec.output.name])
    probs = outs[spec.output.name].data      # [b, t, V] softmax
    return np.asarray(jnp.argmax(probs[:, -1], axis=-1))


class TestGreedyParity:
    def test_decode_follows_graph_argmax_chain(self):
        spec, topo, params = _model()
        dec = models.TransformerDecoder(params, n_layers=CFG["n_layers"],
                                        n_heads=CFG["n_heads"])
        rng = np.random.RandomState(0)
        b, plen, max_len = 3, 4, 10
        prompt = rng.randint(0, CFG["vocab_size"], (b, plen)).astype("int32")
        got = dec.generate(prompt, max_len=max_len)   # greedy
        assert len(got) == b and all(len(r) == max_len - plen for r in got)

        prefix = prompt.copy()
        for step in range(max_len - plen):
            want = _graph_argmax(topo, spec, params, prefix)
            for row in range(b):
                assert got[row][step] == int(want[row]), (
                    f"step {step} row {row}: decode {got[row][step]} "
                    f"!= graph {int(want[row])}")
            prefix = np.concatenate(
                [prefix, want[:, None].astype("int32")], axis=1)

    def test_prefill_matches_stepwise(self):
        """Prefilling the prompt in one batched pass must produce the
        same logits and caches as feeding it token by token (the cache
        position/mask arithmetic lines up between the two modes)."""
        spec, topo, params = _model()
        dec = models.TransformerDecoder(params, n_layers=CFG["n_layers"],
                                        n_heads=CFG["n_heads"])
        rng = np.random.RandomState(1)
        b, plen, max_len = 2, 5, 8
        prompt = jnp.asarray(
            rng.randint(0, CFG["vocab_size"], (b, plen)).astype("int32"))
        d = dec.p["_tfm_tok_emb.w0"].shape[1]
        h = CFG["n_heads"]

        def fresh():
            return [(jnp.zeros((b, max_len, h, d // h), jnp.float32),
                     jnp.zeros((b, max_len, h, d // h), jnp.float32))
                    for _ in range(CFG["n_layers"])]

        pos = jnp.arange(plen)[None, :].repeat(b, 0)
        lg_pre, caches_pre = dec._forward(dec.p, prompt, pos, fresh(),
                                          0, plen)
        caches_step = fresh()
        for t in range(plen):
            lg_step, caches_step = dec._forward(
                dec.p, prompt[:, t:t + 1],
                jnp.full((b, 1), t, jnp.int32), caches_step, t, t + 1)
        np.testing.assert_allclose(np.asarray(lg_pre[:, -1]),
                                   np.asarray(lg_step[:, -1]),
                                   rtol=1e-5, atol=1e-5)
        for (kp, vp), (ks, vs) in zip(caches_pre, caches_step):
            np.testing.assert_allclose(np.asarray(kp), np.asarray(ks),
                                       rtol=1e-5, atol=1e-6)
            np.testing.assert_allclose(np.asarray(vp), np.asarray(vs),
                                       rtol=1e-5, atol=1e-6)

    def test_max_len_beyond_position_table_rejected(self):
        spec, topo, params = _model()
        dec = models.TransformerDecoder(params, n_layers=CFG["n_layers"],
                                        n_heads=CFG["n_heads"])
        with pytest.raises(AssertionError):
            dec.generate(np.zeros((1, 2), "int32"),
                         max_len=CFG["max_len"] + 1)

    def test_eos_trimming(self):
        spec, topo, params = _model()
        dec = models.TransformerDecoder(params, n_layers=CFG["n_layers"],
                                        n_heads=CFG["n_heads"])
        prompt = np.zeros((1, 2), "int32")
        rows = dec.generate(prompt, max_len=12, eos_id=None)
        eid = rows[0][1] if len(set(rows[0])) > 1 else rows[0][0]
        trimmed = dec.generate(prompt, max_len=12, eos_id=eid)
        assert trimmed[0] == rows[0][:rows[0].index(eid) + 1]

    def test_moe_decode_follows_graph_in_no_drop_regime(self):
        """MoE blocks are auto-detected from the param table. Capacity
        derives from each call's token count, so graph parity is only
        guaranteed when nothing drops — pin it there (ample factor)."""
        spec, topo, params = _model(seed=3, moe_experts=4,
                                    moe_capacity_factor=8.0)
        dec = models.TransformerDecoder(params, n_layers=CFG["n_layers"],
                                        n_heads=CFG["n_heads"],
                                        moe_capacity_factor=8.0)
        rng = np.random.RandomState(2)
        b, plen, max_len = 2, 3, 8
        prompt = rng.randint(0, CFG["vocab_size"], (b, plen)).astype("int32")
        got = dec.generate(prompt, max_len=max_len)

        # graph side: same no-drop regime needs a high factor too — the
        # graph's capacity covers b*T tokens, which is already ample
        prefix = prompt.copy()
        for step in range(max_len - plen):
            want = _graph_argmax(topo, spec, params, prefix)
            for row in range(b):
                assert got[row][step] == int(want[row]), (step, row)
            prefix = np.concatenate(
                [prefix, want[:, None].astype("int32")], axis=1)

    def test_temperature_sampling_varies(self):
        spec, topo, params = _model()
        dec = models.TransformerDecoder(params, n_layers=CFG["n_layers"],
                                        n_heads=CFG["n_heads"])
        prompt = np.zeros((4, 2), "int32")
        a = dec.generate(prompt, max_len=16, temperature=2.0,
                         rng=jax.random.PRNGKey(0))
        bb = dec.generate(prompt, max_len=16, temperature=2.0,
                          rng=jax.random.PRNGKey(1))
        assert a != bb          # different keys explore different paths
        g = dec.generate(prompt, max_len=16)
        assert g == dec.generate(prompt, max_len=16)   # greedy is stable


class TestBeamSearch:
    def test_beam1_equals_greedy(self):
        spec, topo, params = _model()
        dec = models.TransformerDecoder(params, n_layers=CFG["n_layers"],
                                        n_heads=CFG["n_heads"])
        rng = np.random.RandomState(4)
        prompt = rng.randint(0, CFG["vocab_size"], (2, 3)).astype("int32")
        eid = CFG["vocab_size"] - 1
        greedy = dec.generate(prompt, max_len=10, eos_id=eid)
        beam = dec.beam_search(prompt, max_len=10, beam_size=1, eos_id=eid)
        for row in range(2):
            assert beam[row][0][1] == greedy[row]

    def test_nbest_sorted_and_scores_match_graph(self):
        """Beam scores must equal the training graph's summed token
        log-probs for the returned sequence (teacher-forced recompute)."""
        spec, topo, params = _model()
        dec = models.TransformerDecoder(params, n_layers=CFG["n_layers"],
                                        n_heads=CFG["n_heads"])
        rng = np.random.RandomState(5)
        b, plen, max_len, K = 2, 3, 9, 3
        prompt = rng.randint(0, CFG["vocab_size"], (b, plen)).astype("int32")
        eid = CFG["vocab_size"] - 1
        results = dec.beam_search(prompt, max_len=max_len, beam_size=K,
                                  eos_id=eid)
        for bi in range(b):
            scores = [s for s, _ in results[bi]]
            assert scores == sorted(scores, reverse=True)
            # recompute the best row's score through the graph
            score, row = results[bi][0]
            full = np.concatenate([prompt[bi], np.array(row, "int32")])
            want = 0.0
            for t in range(len(row)):
                pre = full[None, :plen + t]
                lens = jnp.full((1,), pre.shape[1], jnp.int32)
                sb = lambda a: SequenceBatch(jnp.asarray(a), lens)
                pos = np.arange(pre.shape[1], dtype="int32")[None]
                feed = {spec.data.name: sb(pre),
                        spec.positions.name: sb(pos),
                        spec.label.name: sb(pre)}
                outs, _ = topo.forward(params, topo.init_state(), feed,
                                       mode="test",
                                       output_names=[spec.output.name])
                probs = np.asarray(outs[spec.output.name].data[0, -1])
                want += float(np.log(max(probs[row[t]], 1e-30)))
                if row[t] == eid:
                    break
            np.testing.assert_allclose(score, want, rtol=1e-3, atol=1e-3)

    def test_beams_are_distinct(self):
        spec, topo, params = _model()
        dec = models.TransformerDecoder(params, n_layers=CFG["n_layers"],
                                        n_heads=CFG["n_heads"])
        prompt = np.zeros((1, 2), "int32")
        res = dec.beam_search(prompt, max_len=8, beam_size=4,
                              eos_id=CFG["vocab_size"] - 1)
        rows = [tuple(r) for _, r in res[0]]
        assert len(set(rows)) == len(rows)


def _teacher_forced_logprob(spec, topo, params, prompt_row, row, eid):
    """Raw summed log-prob of `row` continuing `prompt_row`, through the
    training graph (stops after eos)."""
    full = np.concatenate([prompt_row, np.array(row, "int32")])
    plen = len(prompt_row)
    want = 0.0
    for t in range(len(row)):
        pre = full[None, :plen + t]
        lens = jnp.full((1,), pre.shape[1], jnp.int32)
        sb = lambda a: SequenceBatch(jnp.asarray(a), lens)
        pos = np.arange(pre.shape[1], dtype="int32")[None]
        feed = {spec.data.name: sb(pre), spec.positions.name: sb(pos),
                spec.label.name: sb(pre)}
        outs, _ = topo.forward(params, topo.init_state(), feed,
                               mode="test",
                               output_names=[spec.output.name])
        probs = np.asarray(outs[spec.output.name].data[0, -1])
        want += float(np.log(max(probs[row[t]], 1e-30)))
        if row[t] == eid:
            break
    return want


class TestLengthPenalty:
    def test_gnmt_scores_match_graph(self):
        """length_penalty > 0 runs the in-scan GNMT bank: every returned
        score must equal the teacher-forced raw log-prob / len^alpha,
        and results arrive sorted. (No superiority assertion vs the
        raw-sum search: both beams are greedy approximations exploring
        different live sets, so neither dominates in general.)"""
        spec, topo, params = _model()
        dec = models.TransformerDecoder(params, n_layers=CFG["n_layers"],
                                        n_heads=CFG["n_heads"])
        prompt = np.zeros((1, 2), "int32")
        eid = CFG["vocab_size"] - 1
        alpha = 1.0
        norm = dec.beam_search(prompt, max_len=9, beam_size=4, eos_id=eid,
                               length_penalty=alpha)
        scores = [s for s, _ in norm[0]]
        assert scores == sorted(scores, reverse=True)
        for s, r in norm[0]:
            want = _teacher_forced_logprob(spec, topo, params, prompt[0],
                                           r, eid)
            np.testing.assert_allclose(
                s, want / max(len(r), 1) ** alpha, rtol=1e-3, atol=1e-3)

    def test_gnmt_results_distinct_and_trimmed(self):
        spec, topo, params = _model()
        dec = models.TransformerDecoder(params, n_layers=CFG["n_layers"],
                                        n_heads=CFG["n_heads"])
        prompt = np.zeros((2, 2), "int32")
        eid = CFG["vocab_size"] - 1
        res = dec.beam_search(prompt, max_len=8, beam_size=4, eos_id=eid,
                              length_penalty=0.6)
        for bi in range(2):
            rows = [tuple(r) for _, r in res[bi]]
            assert len(set(rows)) == len(rows)
            for _, r in res[bi]:
                assert eid not in r[:-1]   # trimmed at first eos


class TestTiedEmbeddings:
    def test_tied_lm_trains_and_decodes_in_parity(self):
        """tie_embeddings=True: one vocab-sized table serves both the
        embedding and the (transposed) head; training works and greedy
        decode still follows the training graph's argmax chain."""
        spec, topo, params = _model(tie_embeddings=True)
        assert "_tfm_head.w0" not in params        # the table is shared
        assert "_tfm_tok_emb.w0" in params

        dec = models.TransformerDecoder(params, n_layers=CFG["n_layers"],
                                        n_heads=CFG["n_heads"])
        rng = np.random.RandomState(0)
        b, plen, max_len = 2, 3, 8
        prompt = rng.randint(0, CFG["vocab_size"],
                             (b, plen)).astype("int32")
        got = dec.generate(prompt, max_len=max_len)
        prefix = prompt.copy()
        for step in range(max_len - plen):
            want = _graph_argmax(topo, spec, params, prefix)
            for row in range(b):
                assert got[row][step] == int(want[row])
            prefix = np.concatenate(
                [prefix, want[:, None].astype("int32")], axis=1)

        # one SGD step moves the shared table with grads from BOTH uses
        ps = paddle.create_parameters(
            paddle.Topology(spec.cost, extra_outputs=[spec.output]))
        tr = paddle.SGD(cost=spec.cost, parameters=ps,
                        extra_layers=[spec.output],
                        update_equation=paddle.optimizer.Adam(
                            learning_rate=1e-3))
        T = 8
        rows = []
        for _ in range(4):
            ids = rng.randint(0, CFG["vocab_size"], T + 1)
            rows.append(([int(v) for v in ids[:T]], list(range(T)),
                         [int(v) for v in ids[1:]]))
        w0 = np.asarray(ps.raw["_tfm_tok_emb.w0"]).copy()
        losses = []
        tr.train(lambda: iter([rows]), num_passes=2,
                 event_handler=lambda e: losses.append(e.cost)
                 if isinstance(e, paddle.event.EndIteration) else None)
        assert np.isfinite(losses).all() and losses[-1] < losses[0]
        assert np.abs(np.asarray(
            tr.parameters.raw["_tfm_tok_emb.w0"]) - w0).max() > 0


class TestGroupedQueryAttention:
    def test_gqa_decode_follows_graph_argmax_chain(self):
        """n_kv_heads < n_heads: the decoder's grouped einsums over the
        kv_h-sized caches must match the training graph token for
        token (which repeats kv heads to full width)."""
        spec, topo, params = _model(n_kv_heads=1)   # MQA, 2 q heads
        assert params["_tfm_l0_k.w0"].shape[1] == \
            CFG["d_model"] // CFG["n_heads"]        # kv width = one head
        dec = models.TransformerDecoder(params, n_layers=CFG["n_layers"],
                                        n_heads=CFG["n_heads"])
        rng = np.random.RandomState(1)
        b, plen, max_len = 3, 4, 10
        prompt = rng.randint(0, CFG["vocab_size"],
                             (b, plen)).astype("int32")
        got = dec.generate(prompt, max_len=max_len)
        prefix = prompt.copy()
        for step in range(max_len - plen):
            want = _graph_argmax(topo, spec, params, prefix)
            for row in range(b):
                assert got[row][step] == int(want[row]), (step, row)
            prefix = np.concatenate(
                [prefix, want[:, None].astype("int32")], axis=1)

    def test_gqa_trains(self):
        spec, topo, params = _model(n_kv_heads=1)
        ps = paddle.create_parameters(
            paddle.Topology(spec.cost, extra_outputs=[spec.output]))
        tr = paddle.SGD(cost=spec.cost, parameters=ps,
                        extra_layers=[spec.output],
                        update_equation=paddle.optimizer.Adam(
                            learning_rate=1e-3))
        rng = np.random.RandomState(0)
        T = 8
        rows = []
        for _ in range(8):
            ids = rng.randint(0, CFG["vocab_size"], T + 1)
            rows.append(([int(v) for v in ids[:T]], list(range(T)),
                         [int(v) for v in ids[1:]]))
        losses = []
        tr.train(lambda: iter([rows]), num_passes=3,
                 event_handler=lambda e: losses.append(e.cost)
                 if isinstance(e, paddle.event.EndIteration) else None)
        assert np.isfinite(losses).all() and losses[-1] < losses[0]

    def test_gqa_grouping_order_parity(self):
        """rep>1 AND kv_h>1 (4 q heads over 2 kv heads): detects a
        consecutive-vs-interleaved mismatch between the training path's
        jnp.repeat and the decoder's grouped q reshape, which the MQA
        case structurally cannot."""
        spec, topo, params = _model(n_heads=4, n_kv_heads=2)
        dec = models.TransformerDecoder(params, n_layers=CFG["n_layers"],
                                        n_heads=4)
        rng = np.random.RandomState(5)
        b, plen, max_len = 2, 4, 9
        prompt = rng.randint(0, CFG["vocab_size"],
                             (b, plen)).astype("int32")
        got = dec.generate(prompt, max_len=max_len)
        prefix = prompt.copy()
        for step in range(max_len - plen):
            want = _graph_argmax(topo, spec, params, prefix)
            for row in range(b):
                assert got[row][step] == int(want[row]), (step, row)
            prefix = np.concatenate(
                [prefix, want[:, None].astype("int32")], axis=1)


class TestPallasDecodeAttention:
    """ops/pallas_decode kernel vs the einsum reference, incl. GQA."""

    @pytest.mark.parametrize("h,g", [(8, 8), (8, 2)])
    def test_matches_einsum(self, h, g):
        import jax.numpy as jnp
        from paddle_tpu.ops.pallas_decode import decode_attention
        rng = np.random.RandomState(0)
        b, dh, T, kv_len = 4, 16, 64, 37
        q = jnp.asarray(rng.randn(b, h, dh).astype(np.float32))
        kc = jnp.asarray(rng.randn(b, g, dh, T).astype(np.float32))
        vc = jnp.asarray(rng.randn(b, g, dh, T).astype(np.float32))

        got = decode_attention(q, kc, vc, kv_len, interpret=True)

        rep = h // g
        q5 = q.reshape(b, g, rep, dh)
        logits = jnp.einsum("bgrd,bgdk->bgrk", q5, kc) * dh ** -0.5
        mask = jnp.arange(T) < kv_len
        logits = jnp.where(mask[None, None, None], logits, -1e30)
        w = jax.nn.softmax(logits, axis=-1)
        want = jnp.einsum("bgrk,bgdk->bgrd", w, vc).reshape(b, h, dh)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-4, atol=2e-5)


class TestFlashPrefill:
    """Long-prompt prefill through the flash kernel must match the
    quadratic einsum path (models/decode.py _use_flash_prefill gate)."""

    def test_prefill_logits_match_einsum(self, monkeypatch):
        import jax.numpy as jnp
        from paddle_tpu import models
        paddle.init(seed=0)
        plen, max_len, d, L = 256, 272, 64, 2
        spec = models.transformer_lm(vocab_size=97, d_model=d, n_heads=4,
                                     n_layers=L, d_ff=2 * d,
                                     max_len=max_len)
        topo = paddle.Topology(spec.cost, extra_outputs=[spec.output])
        params = topo.init_params(jax.random.PRNGKey(0))
        prompt = jnp.asarray(np.random.RandomState(0).randint(
            0, 97, (2, plen)).astype("int32"))

        dec = models.TransformerDecoder(params, n_layers=L, n_heads=4)
        lg_e, _ = dec._prefill(dec.p, prompt, plen, max_len)

        # force the flash gate on (CPU runs the kernel in interpret mode)
        monkeypatch.setattr(models.TransformerDecoder,
                            "_use_flash_prefill",
                            staticmethod(lambda t, pos, dh:
                                         isinstance(pos, int) and pos == 0
                                         and t > 1))
        lg_f, _ = dec._prefill(dec.p, prompt, plen, max_len)
        np.testing.assert_allclose(np.asarray(lg_f), np.asarray(lg_e),
                                   rtol=2e-4, atol=2e-4)

    def test_gqa_prefill_logits_match_einsum(self, monkeypatch):
        import jax.numpy as jnp
        from paddle_tpu import models
        paddle.init(seed=0)
        plen, max_len, d, L = 256, 272, 64, 1
        spec = models.transformer_lm(vocab_size=61, d_model=d, n_heads=4,
                                     n_layers=L, d_ff=2 * d,
                                     max_len=max_len, n_kv_heads=2)
        topo = paddle.Topology(spec.cost, extra_outputs=[spec.output])
        params = topo.init_params(jax.random.PRNGKey(1))
        prompt = jnp.asarray(np.random.RandomState(1).randint(
            0, 61, (2, plen)).astype("int32"))
        dec = models.TransformerDecoder(params, n_layers=L, n_heads=4)
        lg_e, _ = dec._prefill(dec.p, prompt, plen, max_len)
        monkeypatch.setattr(models.TransformerDecoder,
                            "_use_flash_prefill",
                            staticmethod(lambda t, pos, dh:
                                         isinstance(pos, int) and pos == 0
                                         and t > 1))
        lg_f, _ = dec._prefill(dec.p, prompt, plen, max_len)
        np.testing.assert_allclose(np.asarray(lg_f), np.asarray(lg_e),
                                   rtol=2e-4, atol=2e-4)
