"""Nested recurrent_group (SubsequenceInput) — the level-2 unroll of
RecurrentGradientMachine (RecurrentGradientMachine.h:32 hasSubseq path,
gserver/tests/test_RecurrentGradientMachine.cpp's hierarchical configs).

The outer group iterates over subsequences; each outer step hands the step
function a level-1 SequenceBatch. Covers: per-subsequence reduction to a
level-1 output, per-position inner-sequence outputs re-flattened to the
nested layout, memory carried across subsequences, and gradients through
the whole two-level unroll.
"""

import jax
import jax.numpy as jnp
import numpy as np

import paddle_tpu as paddle
from paddle_tpu.core.sequence import SequenceBatch, pack_nested_sequences
from paddle_tpu.core.topology import Topology
from paddle_tpu.ops import sequence_ops as seq_ops
from tests.grad_check import check_topology_grads

L = paddle.layer


def nested_feed(d=3):
    rows = [[np.arange(2 * d, dtype=np.float32).reshape(2, d),
             10 + np.arange(3 * d, dtype=np.float32).reshape(3, d)],
            [20 + np.arange(1 * d, dtype=np.float32).reshape(1, d),
             30 + np.arange(2 * d, dtype=np.float32).reshape(2, d),
             40 + np.arange(2 * d, dtype=np.float32).reshape(2, d)]]
    return rows, pack_nested_sequences(rows)


class TestNestedRestructure:
    def test_nested_to_padded_roundtrip(self):
        rows, seq = nested_feed()
        data, ilen = seq_ops.nested_to_padded(seq)
        # row 0: segments of length 2 and 3
        np.testing.assert_array_equal(np.asarray(ilen[0])[:3], [2, 3, 0])
        np.testing.assert_allclose(np.asarray(data[0, 0, :2]), rows[0][0])
        np.testing.assert_allclose(np.asarray(data[0, 1, :3]), rows[0][1])
        back = seq_ops.padded_to_nested(data, ilen, seq.num_segments,
                                        seq.max_len)
        np.testing.assert_allclose(np.asarray(back.data),
                                   np.asarray(seq.data))
        np.testing.assert_array_equal(np.asarray(back.segment_ids),
                                      np.asarray(seq.segment_ids))
        np.testing.assert_array_equal(np.asarray(back.lengths),
                                      np.asarray(seq.lengths))


def run(out, feed, mode="test"):
    topo = Topology(out)
    params = topo.init_params(jax.random.PRNGKey(0))
    outs, _ = topo.forward(params, topo.init_state(), feed, mode=mode,
                           rng=jax.random.PRNGKey(1))
    return outs[out.name], params


class TestNestedGroup:
    def test_subsequence_pooling_step(self):
        """step reduces each subsequence -> level-1 sequence of vectors."""
        rows, seq = nested_feed()
        ns = L.data("ns", paddle.data_type.dense_vector_sub_sequence(3))

        def step(sub):
            return L.pooling(sub, pooling_type=paddle.pooling.Avg())

        g = L.recurrent_group(step=step, input=L.SubsequenceInput(ns))
        assert g.meta.seq_level == 1
        got, _ = run(g, {"ns": seq})
        assert isinstance(got, SequenceBatch) and not got.is_nested
        np.testing.assert_array_equal(np.asarray(got.lengths), [2, 3])
        np.testing.assert_allclose(np.asarray(got.data[0, 0]),
                                   rows[0][0].mean(0), rtol=1e-6)
        np.testing.assert_allclose(np.asarray(got.data[1, 2]),
                                   rows[1][2].mean(0), rtol=1e-6)

    def test_inner_seq_output_stays_nested(self):
        """step returns a per-position output -> nested output, same
        raggedness as the input."""
        rows, seq = nested_feed()
        ns = L.data("ns", paddle.data_type.dense_vector_sub_sequence(3))

        def step(sub):
            return L.fc(sub, size=4, act=paddle.activation.Tanh())

        g = L.recurrent_group(step=step, input=L.SubsequenceInput(ns))
        assert g.meta.seq_level == 2
        got, _ = run(g, {"ns": seq})
        assert got.is_nested
        np.testing.assert_array_equal(np.asarray(got.lengths),
                                      np.asarray(seq.lengths))
        np.testing.assert_array_equal(np.asarray(got.segment_ids),
                                      np.asarray(seq.segment_ids))

    def test_memory_across_subsequences(self):
        """A memory linked across outer steps accumulates subsequence
        summaries — the hierarchical-RNN pattern."""
        rows, seq = nested_feed()
        ns = L.data("ns", paddle.data_type.dense_vector_sub_sequence(3))

        def step(sub):
            mem = L.memory(name="acc", size=3)
            pooled = L.pooling(sub, pooling_type=paddle.pooling.Sum())
            return L.addto([pooled, mem], name="acc")

        g = L.recurrent_group(step=step, input=L.SubsequenceInput(ns))
        got, _ = run(g, {"ns": seq})
        # outer step s output = sum of pooled sums up to s
        np.testing.assert_allclose(np.asarray(got.data[0, 0]),
                                   rows[0][0].sum(0), rtol=1e-5)
        np.testing.assert_allclose(np.asarray(got.data[0, 1]),
                                   rows[0][0].sum(0) + rows[0][1].sum(0),
                                   rtol=1e-5)
        np.testing.assert_allclose(
            np.asarray(got.data[1, 2]),
            rows[1][0].sum(0) + rows[1][1].sum(0) + rows[1][2].sum(0),
            rtol=1e-5)

    def test_inner_recurrent_group_two_level(self):
        """Full two-level unroll: an inner recurrent_group inside the outer
        step (the configuration test_RecurrentGradientMachine exercises)."""
        rows, seq = nested_feed()
        ns = L.data("ns", paddle.data_type.dense_vector_sub_sequence(3))

        def inner_step(x):
            m = L.memory(name="ih", size=4)
            return L.fc([x, m], size=4, act=paddle.activation.Tanh(),
                        name="ih")

        def outer_step(sub):
            h = L.recurrent_group(step=inner_step, input=sub,
                                  name="inner_rg")
            return L.last_seq(h)

        g = L.recurrent_group(step=outer_step, input=L.SubsequenceInput(ns),
                              name="outer_rg")
        got, _ = run(g, {"ns": seq})
        assert isinstance(got, SequenceBatch)
        np.testing.assert_array_equal(np.asarray(got.lengths), [2, 3])
        assert np.all(np.isfinite(np.asarray(got.data)))

    def test_reverse_walks_subsequences_backward(self):
        rows, seq = nested_feed()
        ns = L.data("ns", paddle.data_type.dense_vector_sub_sequence(3))

        def step(sub):
            mem = L.memory(name="racc", size=3)
            pooled = L.pooling(sub, pooling_type=paddle.pooling.Sum())
            return L.addto([pooled, mem], name="racc")

        g = L.recurrent_group(step=step, input=L.SubsequenceInput(ns),
                              reverse=True)
        got, _ = run(g, {"ns": seq})
        # reverse: step 0 sees the LAST subsequence; outputs are delivered
        # back in forward segment order, so segment 0 carries the full sum
        np.testing.assert_allclose(np.asarray(got.data[0, 0]),
                                   rows[0][0].sum(0) + rows[0][1].sum(0),
                                   rtol=1e-5)
        np.testing.assert_allclose(np.asarray(got.data[0, 1]),
                                   rows[0][1].sum(0), rtol=1e-5)

    def test_bounded_view_truncates_consistently(self):
        # max_segments / max_sub_len clip data AND lengths together
        rows = [[np.ones((2, 2), np.float32), 2 * np.ones((1, 2), np.float32),
                 3 * np.ones((2, 2), np.float32)]]
        seq = pack_nested_sequences(rows)
        data, ilen = seq_ops.nested_to_padded(seq, max_segments=2,
                                              max_sub_len=1)
        np.testing.assert_array_equal(np.asarray(ilen[0])[:2], [1, 1])
        assert np.all(np.asarray(ilen) <= 1)

    def test_nested_group_gradients(self, rng):
        rows = [[rng.randn(2, 3).astype(np.float32),
                 rng.randn(3, 3).astype(np.float32)],
                [rng.randn(2, 3).astype(np.float32)]]
        seq = pack_nested_sequences(rows)
        ns = L.data("ns", paddle.data_type.dense_vector_sub_sequence(3))

        def step(sub):
            mem = L.memory(name="h2", size=4)
            pooled = L.pooling(L.fc(sub, size=4,
                                    act=paddle.activation.Tanh()),
                               pooling_type=paddle.pooling.Avg())
            return L.fc([pooled, mem], size=4,
                        act=paddle.activation.Tanh(), name="h2")

        g = L.recurrent_group(step=step, input=L.SubsequenceInput(ns))
        cost = L.sum_cost(L.last_seq(g))
        check_topology_grads(Topology(cost), {"ns": seq}, n_coords=4)

    def test_serialization_roundtrip(self):
        rows, seq = nested_feed()
        ns = L.data("ns", paddle.data_type.dense_vector_sub_sequence(3))

        def step(sub):
            return L.pooling(sub, pooling_type=paddle.pooling.Avg())

        g = L.recurrent_group(step=step, input=L.SubsequenceInput(ns))
        topo = Topology(g)
        topo2 = Topology.deserialize(topo.serialize())
        params = topo2.init_params(jax.random.PRNGKey(0))
        outs, _ = topo2.forward(params, topo2.init_state(), {"ns": seq},
                                mode="test", rng=jax.random.PRNGKey(1))
        got = outs[g.name]
        np.testing.assert_array_equal(np.asarray(got.lengths), [2, 3])


class TestNestedGroupRemat:
    def test_remat_nested_group_identical(self):
        """remat=True on a NESTED group must checkpoint its scan body too
        (not just the flat path) — outputs and grads bit-identical."""
        rows, seq = nested_feed()

        def build(remat):
            from paddle_tpu.core import registry
            registry.reset_name_counters()
            ns = L.data("ns", paddle.data_type.dense_vector_sub_sequence(3))

            def step(sub):
                return L.pooling(L.fc(sub, size=4, name="nf",
                                      act=paddle.activation.Tanh()),
                                 pooling_type=paddle.pooling.Avg())

            g = L.recurrent_group(step=step, input=L.SubsequenceInput(ns),
                                  remat=remat, name="nrg")
            pooled = L.pooling(g, pooling_type=paddle.pooling.Sum())
            return Topology(L.fc(pooled, size=1, name="no"))

        vals = []
        for remat in (False, True):
            topo = build(remat)
            params = topo.init_params(jax.random.PRNGKey(5))

            def loss(p):
                outs, _ = topo.forward(p, topo.init_state(), {"ns": seq},
                                       mode="train",
                                       rng=jax.random.PRNGKey(6))
                return jnp.sum(outs["no"] ** 2)

            # ptlint: disable=R2(two intentionally different graphs — remat off/on — compiled once each)
            val, grads = jax.jit(jax.value_and_grad(loss))(params)
            vals.append((float(val),
                         {k: np.asarray(v) for k, v in grads.items()}))
        (v0, g0), (v1, g1) = vals
        assert v0 == v1
        for k in g0:
            np.testing.assert_array_equal(g0[k], g1[k], err_msg=k)
