"""Fleet chaos acceptance (ISSUE 15): SIGKILL a serve replica
MID-STREAM under burst load and hold the tentpole invariants:

- every burst request settles EXACTLY ONCE at the router boundary —
  a result or one typed error, never both, never neither, and the
  router journal carries exactly one fleet/settle per trace_id;
- zero KV-page leaks on the surviving replicas (their own
  ``kv_pages_leaked`` gauge over GET /stats);
- ``merge_journals`` over the router's + all replicas' journals
  reconstructs each failover's hop chain from the trace_id ALONE —
  the victim's journal shows a hop that starts and never settles
  (the process died mid-stream), the router shows
  route(victim) -> failover -> route(sibling) -> settle in order.

Faults come from testing/faults.py family (p): ``kill_replica`` (the
SIGKILL trigger riding the router's stream-interceptor seam) and
``drain_during_burst`` (deploy-drain while requests are in flight).
``lease_lapse`` is covered in tests/test_fleet.py.
"""

import json
import os
import subprocess
import sys
import time
import urllib.request

import pytest

from paddle_tpu.fleet import Router
from paddle_tpu.obs.events import JOURNAL
from paddle_tpu.obs.merge import merge_journals
from paddle_tpu.serving import (Expired, Rejected, ServerClosed,
                                ServingError)
from paddle_tpu.testing import FaultPlan, assert_exactly_once
from paddle_tpu.trainer.coordinator import connect

pytestmark = pytest.mark.chaos

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# same tiny decoder on every replica (same seed): greedy decode is
# deterministic across the fleet, which is what makes a mid-stream
# failover's resumed continuation token-exact
DEC_SRC = (
    "import jax\n"
    "import paddle_tpu as paddle\n"
    "from paddle_tpu import models\n"
    "from paddle_tpu.core.registry import reset_name_counters\n"
    "paddle.init(use_tpu=False, seed=0)\n"
    "reset_name_counters()\n"
    "spec = models.transformer_lm(vocab_size=40, d_model=16,\n"
    "                             n_heads=2, n_layers=2, d_ff=32,\n"
    "                             max_len=32)\n"
    "costs = (spec.cost if isinstance(spec.cost, list)\n"
    "         else [spec.cost])\n"
    "topo = paddle.Topology(costs, extra_outputs=[spec.output])\n"
    "params = topo.init_params(jax.random.PRNGKey(7))\n"
    "decoder = models.TransformerDecoder(params, n_layers=2,\n"
    "                                    n_heads=2)\n")

TYPED = (Rejected, Expired, ServerClosed, ServingError)


def _env(host_tag):
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"
    env["PADDLE_TPU_HOST"] = host_tag
    return env


def _http_json(url, body=None, timeout=60):
    data = json.dumps(body).encode() if body is not None else None
    req = urllib.request.Request(
        url, data=data,
        headers={"Content-Type": "application/json"} if data else {})
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return json.loads(r.read())


class TestSigkillMidStreamUnderBurst:
    def test_chaos_acceptance(self, tmp_path):
        dec_cfg = tmp_path / "dec.py"
        dec_cfg.write_text(DEC_SRC)
        data = str(tmp_path / "seed.ptr")
        from paddle_tpu.reader import recordio as rio
        rio.write_records(data, [b"r0", b"r1"], max_chunk_bytes=64)

        procs = {}
        router = None
        coord_proc = subprocess.Popen(
            [sys.executable, "-m", "paddle_tpu.cli", "coordinator",
             "--data", data, "--worker_lease", "2.5"],
            stdout=subprocess.PIPE, text=True, env=_env("coord"))
        try:
            cport = json.loads(coord_proc.stdout.readline())["port"]
            journals = {"router": str(tmp_path / "router.jsonl")}
            for rid in ("rA", "rB"):
                journals[rid] = str(tmp_path / f"{rid}.jsonl")
                procs[rid] = subprocess.Popen(
                    [sys.executable, "-m", "paddle_tpu.cli", "serve",
                     "--decode_config", str(dec_cfg),
                     "--gen_slots", "2", "--gen_page_size", "4",
                     "--workers", "1",
                     "--coordinator", f"127.0.0.1:{cport}",
                     "--replica_id", rid, "--heartbeat", "0.5",
                     "--event_log", journals[rid]],
                    stdout=subprocess.PIPE, text=True, env=_env(rid))
            endpoints = {}
            for rid, p in procs.items():
                rec = json.loads(p.stdout.readline())
                assert rec["status"] == "serving"
                assert rec["replica_id"] == rid
                endpoints[rid] = f"http://127.0.0.1:{rec['port']}"
            # warm each replica's jit cache OUTSIDE the chaos window
            for rid, ep in endpoints.items():
                out = _http_json(ep + "/generate",
                                 {"prompt": [1, 2], "max_new_tokens": 1,
                                  "deadline_ms": 120000})
                assert len(out["tokens"]) == 1, rid

            JOURNAL.configure(journals["router"])
            router = Router(coordinator=connect("127.0.0.1", cport),
                            page_size=4, scrape_interval=0.2,
                            queue_timeout=10.0, queue_poll=0.05).start()
            deadline = time.monotonic() + 30
            while time.monotonic() < deadline:
                router.refresh()
                if router.health()["replicas_live"] == 2:
                    break
                time.sleep(0.1)
            assert router.health()["replicas_live"] == 2

            # prime prefix affinity: the shared-prefix burst will all
            # steer to ONE replica — the victim
            shared = [1, 2, 3, 4, 5, 6, 7, 8, 9, 10, 11, 12]
            prime = router.generate(shared + [39], 2)
            victim = prime.replica_chain[-1]
            sibling = ("rA", "rB")[victim == "rA"]

            def one(i):
                return router.generate(shared + [20 + i], 8)

            with FaultPlan.kill_replica(
                    router, victim, procs[victim].kill,
                    at=2) as chaos:
                results, errors = FaultPlan.burst(one, 8, threads=8,
                                                  timeout=180)
            assert chaos["fired"] == 1
            procs[victim].wait(timeout=30)      # SIGKILL landed

            # exactly-once at the caller: every request is a result
            # XOR one typed error — no untyped escapes, no losses
            untyped = [e for e in errors
                       if e is not None and not isinstance(e, TYPED)]
            assert untyped == []
            settled = [r for r in results if r is not None]
            assert len(settled) + sum(
                e is not None for e in errors) == 8
            assert len(settled) >= 4            # the fleet kept serving

            failed_over = [r for r in settled if r.hops >= 2]
            assert failed_over, [r.replica_chain for r in settled]
            for r in failed_over:
                assert r.replica_chain[0] == victim
                assert r.replica_chain[-1] == sibling
                assert len(r.tokens) == 8
            st = router.stats()
            assert st["failovers"] >= 1
            assert st["settled_failover"] >= len(failed_over)
            assert st["settled"] == len(settled) + 1    # + the prime

            # token-exact resume: greedy decode replayed on the
            # sibling produces what the victim would have — re-asking
            # the (identically seeded) survivor must agree
            probe = failed_over[0]
            idx = results.index(probe)
            again = router.generate(shared + [20 + idx], 8)
            assert again.tokens == probe.tokens

            # zero page leaks on the survivor, via its own gauge
            stats = _http_json(endpoints[sibling] + "/stats")
            assert stats["engine"]["kv_pages_leaked"] == 0
            assert _http_json(endpoints[sibling] + "/health")[
                "status"] == "ok"

            # exactly-once settle per trace_id in the router journal
            # (shared audit — paddle_tpu/testing/audit.py; the prime
            # request is a legitimate stray)
            JOURNAL.configure(None)
            assert_exactly_once(journals["router"],
                                [r.trace_id for r in settled])

            # the merged trace reconstructs the victim hop chain from
            # the trace_id alone, across all three processes' journals
            merged = merge_journals([journals["router"],
                                     journals["rA"], journals["rB"]])
            tid = probe.trace_id
            chain = [r for r in merged if r.get("trace_id") == tid]
            routes = [r for r in chain if r["domain"] == "fleet"
                      and r["kind"] == "route"]
            assert [r["replica"] for r in routes][:1] == [victim]
            assert routes[-1]["replica"] == sibling
            fails = [r for r in chain if r["domain"] == "fleet"
                     and r["kind"] == "failover"]
            assert fails and fails[0]["victim"] == victim
            # mseq order: dispatch to victim, then the failover, then
            # the re-dispatch, then the settle
            order = [r["mseq"] for r in (routes[0], fails[0],
                                         routes[-1])]
            assert order == sorted(order)
            settle_rec = [r for r in chain if r["kind"] == "settle"]
            assert settle_rec and settle_rec[0]["mseq"] > order[-1]
            # the victim's OWN journal shows the hop that started and
            # never settled (the process died mid-stream); the
            # sibling's shows start + settle
            victim_hops = [r for r in chain if r["kind"] == "hop"
                           and r.get("host") == victim]
            assert [r["phase"] for r in victim_hops] == ["start"]
            sibling_hops = [r for r in chain if r["kind"] == "hop"
                            and r.get("host") == sibling]
            assert [r["phase"] for r in sibling_hops] == \
                ["start", "settle"]
        finally:
            JOURNAL.configure(None)
            if router is not None:
                router.shutdown(drain=True, timeout=10)
            for p in procs.values():
                if p.poll() is None:
                    p.terminate()
                    try:
                        p.wait(timeout=30)
                    except subprocess.TimeoutExpired:
                        p.kill()
            coord_proc.terminate()
            try:
                coord_proc.wait(timeout=30)
            except subprocess.TimeoutExpired:
                coord_proc.kill()
                raise


class TestDrainDuringBurst:
    def test_drain_under_load_redirects_and_settles_all(self):
        """Deploy-drain mid-burst (family (p) ``drain_during_burst``):
        once 3 requests have dispatched, a side thread drains one
        replica; everything in flight still settles exactly once,
        post-drain admissions all land on the sibling, and the drained
        replica's own admission plane answers 'draining'."""
        from test_fleet import fleet, http_json, stop_fleet

        reps, router = fleet(2)
        try:
            router.refresh()
            # pin the burst's prefix to r-target so the drain bites a
            # replica that actually has traffic
            shared = [2, 4, 6, 8, 10, 12, 14, 16]
            prime = router.generate(shared + [30], 2)
            target = prime.replica_chain[-1]
            other = ("r0", "r1")[target == "r0"]
            # slow the target a little so the drain lands mid-burst
            reps[target].engine._step_interceptor = \
                lambda s: time.sleep(0.01)

            def one(i):
                return router.generate(shared + [31 + i], 4)

            with FaultPlan.drain_during_burst(
                    router, target, after=3) as chaos:
                results, errors = FaultPlan.burst(one, 8, threads=4,
                                                  timeout=120)
            reps[target].engine._step_interceptor = None
            assert chaos["drained"] is not None
            assert chaos["drained"]["draining"] is True
            assert chaos["dispatches"] >= 3
            # exactly-once: every burst request settled with tokens
            # (a drain sheds nothing — it redirects)
            untyped = [e for e in errors
                       if e is not None and not isinstance(e, TYPED)]
            assert untyped == []
            settled = [r for r in results if r is not None]
            assert len(settled) + sum(
                e is not None for e in errors) == 8
            assert all(len(r.tokens) == 4 for r in settled)
            # the replica's own admission plane took the mark
            health, _ = http_json(reps[target].endpoint + "/health")
            assert health["status"] == "draining"
            # post-drain traffic all lands on the sibling
            after = router.generate(shared + [99], 2)
            assert after.replica_chain == [other]
            assert router.stats()["drains"] == 1
            # no pages stuck anywhere
            for rep in reps.values():
                assert rep.engine.stats()["kv_pages_leaked"] == 0
        finally:
            stop_fleet(reps, router)


# family (q) control-plane chaos (ISSUE 16): the ROUTER is the victim.
# A slowed decode step keeps streams open long enough that a kill is
# genuinely MID-stream (the subprocess serve CLI has no throttle flag,
# so the decode_config script wraps the paged-decode step itself).
THROTTLED_DEC_SRC = DEC_SRC + (
    "import time as _time\n"
    "_orig_paged = decoder.paged\n"
    "def _slow_paged(**kw):\n"
    "    pd = _orig_paged(**kw)\n"
    "    _step = pd.step\n"
    "    def _throttled(*a, **k):\n"
    "        _time.sleep(0.05)\n"
    "        return _step(*a, **k)\n"
    "    pd.step = _throttled\n"
    "    return pd\n"
    "decoder.paged = _slow_paged\n")


def _read_stream(resp, stop_after=None):
    """Read NDJSON records off a streaming /generate response until
    the terminal record, EOF, or ``stop_after`` token records.
    Returns (token_records, done_record_or_None, torn)."""
    tokens, done, torn = [], None, False
    try:
        while True:
            line = resp.readline()
            if not line:
                torn = done is None
                break
            rec = json.loads(line)
            if rec.get("done"):
                done = rec
                break
            if "token" in rec:
                tokens.append(rec["token"])
                if stop_after is not None and \
                        len(tokens) >= stop_after:
                    break
    except (OSError, json.JSONDecodeError):
        torn = True
    return tokens, done, torn


def _stream_open(base, body, timeout=120):
    req = urllib.request.Request(
        base + "/generate", data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"})
    return urllib.request.urlopen(req, timeout=timeout)


class TestRouterKillSeam:
    # Both router planes and both replicas share ONE in-process journal,
    # and the client retries the SAME trace_id after the tear — so the
    # torn attempt's late terminals (serving/hop torn, fleet/reject
    # router_error) close the per-key witness machines that the retry's
    # second start opened, and the retry's own settle then lands as an
    # orphan terminal. Exactly-once here is proven by the journal audit
    # below, not the live witness (docs/observability.md "Protocol
    # contracts").
    @pytest.mark.protocol_violation_expected
    def test_kill_router_fires_once_mid_stream_and_client_retries(
            self):
        """In-process family (q): the seam tears the ROUTER's client
        connections the moment any stream has relayed ``at`` tokens.
        The client sees a torn NDJSON stream (no terminal record) and
        retries the SAME trace_id on a sibling router plane — landing
        token-exact, with both routers agreeing on the home replica
        (rendezvous over the prompt's first page, no shared state)."""
        import threading

        from test_fleet import Replica, stop_fleet

        reps = {f"r{i}": Replica(f"r{i}") for i in range(2)}
        endpoints = {rid: r.endpoint for rid, r in reps.items()}
        routers, httpds = [], []
        from paddle_tpu.fleet import build_router_http_server
        for i in range(2):
            router = Router(endpoints=dict(endpoints),
                            affinity="prefix", page_size=4,
                            scrape_interval=0.1, queue_timeout=5.0,
                            queue_poll=0.02,
                            drain_timeout=5.0).start()
            httpd = build_router_http_server(router, "127.0.0.1", 0)
            threading.Thread(target=httpd.serve_forever, daemon=True,
                             name=f"pt-test-ha-router-{i}").start()
            routers.append(router)
            httpds.append(httpd)
        bases = [f"http://127.0.0.1:{h.server_address[1]}"
                 for h in httpds]
        # slow the replicas so the tear is mid-stream, not post-stream
        for r in reps.values():
            r.engine._step_interceptor = lambda s: time.sleep(0.02)
        tid = "q-inproc-1"
        shared = [3, 1, 4, 1, 5, 9, 2, 6, 5, 3, 5]
        try:
            deadline = time.monotonic() + 5
            while time.monotonic() < deadline and any(
                    r.stats()["replicas_live"] < 2 for r in routers):
                time.sleep(0.05)
            with FaultPlan.kill_router(routers[0], httpds[0].kill,
                                       at=2) as chaos:
                resp = _stream_open(bases[0],
                                    {"prompt": shared,
                                     "max_new_tokens": 10,
                                     "stream": True, "trace_id": tid})
                tokens1, done1, torn1 = _read_stream(resp)
            assert chaos["fired"] == 1
            assert chaos["at_tokens"] >= 2
            assert chaos["victim_traces"] == [tid]
            assert torn1 and done1 is None     # no terminal record
            # seam restored: no interceptors left armed
            assert routers[0]._stream_interceptor is None
            assert routers[0]._route_interceptor is None
            # the client's contract: retry the same trace_id on the
            # sibling router — token-exact (greedy decode, same fleet)
            resp2 = _stream_open(bases[1],
                                 {"prompt": shared,
                                  "max_new_tokens": 10,
                                  "stream": True, "trace_id": tid})
            tokens2, done2, torn2 = _read_stream(resp2)
            assert not torn2 and done2 is not None
            assert len(tokens2) == 10
            assert done2["tokens"] == tokens2
            assert done2["trace_id"] == tid
            assert tokens2[:len(tokens1)] == tokens1   # token-exact
            # both planes agree on the home replica for this prompt
            picks = {r.balancer.choose(shared, len(shared) + 10)[0]
                     for r in routers}
            assert len(picks) == 1
            for rep in reps.values():
                rep.engine._step_interceptor = None
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline and any(
                    rep.engine.stats()["kv_pages_leaked"] > 0
                    or rep.engine.stats()["active_slots"] > 0
                    for rep in reps.values()):
                time.sleep(0.1)
            for rep in reps.values():
                assert rep.engine.stats()["kv_pages_leaked"] == 0
        finally:
            for h in httpds:
                try:
                    h.shutdown()
                    h.server_close()
                except OSError:
                    pass
            stop_fleet(reps, routers[1])
            routers[0].shutdown(drain=False, timeout=5)


class TestRouterSigkillMidStream:
    def test_family_q_acceptance(self, tmp_path):
        """The ISSUE 16 family (q) proof, full subprocess topology:
        coordinator + 2 replicas + 2 INDEPENDENT router daemons.
        SIGKILL router 1 while it is relaying a stream; the client
        retries the same trace_id on router 2 and lands token-exact.
        Across the merged ROUTER journals the trace settles EXACTLY
        once (the dead router never wrote its settle), the replica-side
        hop journal is the dedupe witness (start -> torn -> start ->
        settle on ONE home replica), and no KV page leaks anywhere."""
        import signal

        dec_cfg = tmp_path / "dec.py"
        dec_cfg.write_text(THROTTLED_DEC_SRC)
        data = str(tmp_path / "seed.ptr")
        from paddle_tpu.reader import recordio as rio
        rio.write_records(data, [b"r0", b"r1"], max_chunk_bytes=64)

        procs = {}
        journals = {}
        coord_proc = subprocess.Popen(
            [sys.executable, "-m", "paddle_tpu.cli", "coordinator",
             "--data", data, "--worker_lease", "5.0"],
            stdout=subprocess.PIPE, text=True, env=_env("coord"))
        try:
            cport = json.loads(coord_proc.stdout.readline())["port"]
            for rid in ("rA", "rB"):
                journals[rid] = str(tmp_path / f"{rid}.jsonl")
                procs[rid] = subprocess.Popen(
                    [sys.executable, "-m", "paddle_tpu.cli", "serve",
                     "--decode_config", str(dec_cfg),
                     "--gen_slots", "2", "--gen_page_size", "4",
                     "--workers", "1",
                     "--coordinator", f"127.0.0.1:{cport}",
                     "--replica_id", rid, "--heartbeat", "0.5",
                     "--event_log", journals[rid]],
                    stdout=subprocess.PIPE, text=True, env=_env(rid))
            endpoints = {}
            for rid in ("rA", "rB"):
                rec = json.loads(procs[rid].stdout.readline())
                assert rec["status"] == "serving"
                endpoints[rid] = f"http://127.0.0.1:{rec['port']}"
            bases = {}
            for rname in ("router1", "router2"):
                journals[rname] = str(tmp_path / f"{rname}.jsonl")
                procs[rname] = subprocess.Popen(
                    [sys.executable, "-m", "paddle_tpu.cli", "router",
                     "--coordinator", f"127.0.0.1:{cport}",
                     "--page_size", "4", "--scrape_interval", "0.2",
                     "--queue_timeout", "10.0",
                     "--event_log", journals[rname]],
                    stdout=subprocess.PIPE, text=True,
                    env=_env(rname))
                rec = json.loads(procs[rname].stdout.readline())
                assert rec["status"] == "serving"
                bases[rname] = f"http://127.0.0.1:{rec['port']}"
            # both router planes must see the full fleet before chaos
            for rname, base in bases.items():
                deadline = time.monotonic() + 30
                while time.monotonic() < deadline:
                    if _http_json(base + "/stats")[
                            "replicas_live"] == 2:
                        break
                    time.sleep(0.2)
                assert _http_json(base + "/stats")[
                    "replicas_live"] == 2, rname
            # warm the jit caches outside the chaos window
            for base in bases.values():
                out = _http_json(base + "/generate",
                                 {"prompt": [1, 2], "max_new_tokens": 1})
                assert len(out["tokens"]) == 1

            tid = "q-sigkill-1"
            shared = [3, 1, 4, 1, 5, 9, 2, 6, 5, 3, 5]
            resp = _stream_open(bases["router1"],
                                {"prompt": shared,
                                 "max_new_tokens": 12,
                                 "stream": True, "trace_id": tid})
            tokens1, done1, _ = _read_stream(resp, stop_after=2)
            assert len(tokens1) == 2 and done1 is None
            # SIGKILL the router RELAYING the stream — family (q)
            os.kill(procs["router1"].pid, signal.SIGKILL)
            procs["router1"].wait(timeout=30)
            _, done_post, torn = _read_stream(resp)
            assert done_post is None and torn   # no terminal record

            # the client's retry: SAME trace_id, sibling router
            resp2 = _stream_open(bases["router2"],
                                 {"prompt": shared,
                                  "max_new_tokens": 12,
                                  "stream": True, "trace_id": tid})
            tokens2, done2, torn2 = _read_stream(resp2)
            assert not torn2 and done2 is not None
            assert len(tokens2) == 12
            assert done2["trace_id"] == tid
            assert tokens2[:2] == tokens1       # token-exact resume
            # greedy decode is deterministic: a control request agrees
            control = _http_json(bases["router2"] + "/generate",
                                 {"prompt": shared,
                                  "max_new_tokens": 12,
                                  "trace_id": "q-control"})
            assert control["tokens"] == tokens2

            # zero KV page leaks once the torn stream is reaped
            for rid in ("rA", "rB"):
                deadline = time.monotonic() + 20
                while time.monotonic() < deadline:
                    st = _http_json(endpoints[rid] + "/stats")
                    if st["engine"]["kv_pages_leaked"] == 0 and \
                            st["engine"]["active_slots"] == 0:
                        break
                    time.sleep(0.2)
                st = _http_json(endpoints[rid] + "/stats")
                assert st["engine"]["kv_pages_leaked"] == 0, rid

            # stop router2 cleanly so its journal is flushed
            procs["router2"].terminate()
            procs["router2"].wait(timeout=30)

            # EXACTLY-ONCE settle across the merged router journals:
            # the SIGKILLed router never wrote one
            merged = merge_journals([journals["router1"],
                                     journals["router2"],
                                     journals["rA"], journals["rB"]])
            assert_exactly_once(merged, [tid])
            chain = [r for r in merged if r.get("trace_id") == tid]
            settles = [r for r in chain if r["domain"] == "fleet"
                       and r["kind"] == "settle"]
            assert settles[0]["host"] == "router2"
            # router1's journal shows the route that never settled
            r1 = [r for r in chain if r.get("host") == "router1"]
            assert any(r["kind"] == "route" for r in r1)
            assert not any(r["kind"] == "settle" for r in r1)
            # the replica-side hop journal is the dedupe witness:
            # start -> torn (router died) -> start -> settle, all on
            # the ONE home replica both planes agree on
            hops = [r for r in chain if r["domain"] == "serving"
                    and r["kind"] == "hop"]
            assert len({r["host"] for r in hops}) == 1
            phases = [r["phase"] for r in hops]
            # two dispatches; the victim's tore, the retry's settled.
            # (torn may journal AFTER the retry's start — the write
            # failure only surfaces one throttled token later)
            assert sorted(phases) == ["settle", "start", "start",
                                      "torn"]
            assert phases[0] == "start" and phases[-1] == "settle"
            torn_rec = next(r for r in hops if r["phase"] == "torn")
            assert torn_rec["streamed"] >= 2
            settle_rec = next(r for r in hops
                              if r["phase"] == "settle")
            assert settle_rec["tokens"] == 12
        finally:
            for p in procs.values():
                if p.poll() is None:
                    p.terminate()
                    try:
                        p.wait(timeout=30)
                    except subprocess.TimeoutExpired:
                        p.kill()
            coord_proc.terminate()
            try:
                coord_proc.wait(timeout=30)
            except subprocess.TimeoutExpired:
                coord_proc.kill()
                raise
