"""Flash attention kernel (ops/pallas_attention.py) — parity against the
XLA reference in interpret mode, exactly as test_pallas_rnn.py pins the
fused RNN kernels. Covers ragged kv lengths, q lengths, causal masking,
block padding, gradients (custom_vjp), and the attention-layer dispatch
gate."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.ops.pallas_attention import (_lens_mask, _reference,
                                             flash_attention,
                                             flash_supported)


def make_qkv(rng, b=2, tq=24, tk=40, h=2, d=16, dtype=jnp.float32):
    q = jnp.asarray(rng.randn(b, tq, h, d)).astype(dtype)
    k = jnp.asarray(rng.randn(b, tk, h, d)).astype(dtype)
    v = jnp.asarray(rng.randn(b, tk, h, d)).astype(dtype)
    return q, k, v


def ref(q, k, v, q_lens=None, kv_lens=None, causal=False):
    b, tq = q.shape[0], q.shape[1]
    tk = k.shape[1]
    ql = q_lens if q_lens is not None else jnp.full((b,), tq, jnp.int32)
    kl = kv_lens if kv_lens is not None else jnp.full((b,), tk, jnp.int32)
    return _reference(q, k, v, _lens_mask(ql, kl, tq, tk, causal),
                      q.shape[-1] ** -0.5)


class TestFlashParity:
    def test_full_attention(self, rng):
        q, k, v = make_qkv(rng)
        out = flash_attention(q, k, v, interpret=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref(q, k, v)),
                                   atol=2e-5)

    def test_ragged_kv_lengths(self, rng):
        q, k, v = make_qkv(rng)
        kl = jnp.asarray([17, 40], jnp.int32)
        out = flash_attention(q, k, v, kv_lens=kl, interpret=True)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref(q, k, v, kv_lens=kl)), atol=2e-5)

    def test_q_lengths_zero_invalid_rows(self, rng):
        q, k, v = make_qkv(rng)
        ql = jnp.asarray([10, 24], jnp.int32)
        out = flash_attention(q, k, v, q_lens=ql, interpret=True)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref(q, k, v, q_lens=ql)), atol=2e-5)
        assert np.all(np.asarray(out)[0, 10:] == 0.0)

    def test_causal(self, rng):
        q, k, v = make_qkv(rng, tq=32, tk=32)
        kl = jnp.asarray([32, 20], jnp.int32)
        out = flash_attention(q, k, v, kv_lens=kl, causal=True,
                              interpret=True)
        np.testing.assert_allclose(
            np.asarray(out),
            np.asarray(ref(q, k, v, kv_lens=kl, causal=True)), atol=2e-5)

    def test_multi_block_online_softmax(self, rng):
        # forces several K blocks + padding (block 16 on T=70/90)
        q, k, v = make_qkv(rng, tq=70, tk=90)
        kl = jnp.asarray([90, 33], jnp.int32)
        out = flash_attention(q, k, v, kv_lens=kl, block_q=16, block_k=16,
                              interpret=True)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref(q, k, v, kv_lens=kl)), atol=2e-5)

    def test_fully_masked_row_returns_zero(self, rng):
        q, k, v = make_qkv(rng)
        kl = jnp.asarray([0, 5], jnp.int32)
        out = flash_attention(q, k, v, kv_lens=kl, interpret=True)
        assert np.all(np.asarray(out)[0] == 0.0)
        assert np.all(np.isfinite(np.asarray(out)))

    def test_gradients_match_reference(self, rng):
        q, k, v = make_qkv(rng, tq=16, tk=24)
        kl = jnp.asarray([24, 12], jnp.int32)

        def f_flash(q_, k_, v_):
            return flash_attention(q_, k_, v_, kv_lens=kl, causal=True,
                                   interpret=True).sum()

        def f_ref(q_, k_, v_):
            return ref(q_, k_, v_, kv_lens=kl, causal=True).sum()

        gf = jax.grad(f_flash, argnums=(0, 1, 2))(q, k, v)
        gr = jax.grad(f_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(gf, gr):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=2e-5)

    def test_supported_gate(self, rng):
        q, k, _ = make_qkv(rng)
        assert flash_supported(q, k)
        q3 = jnp.zeros((2, 24, 2, 15))      # d % 8 != 0
        assert not flash_supported(q3, k)


class TestLayerDispatch:
    def test_layer_uses_reference_on_cpu_and_flash_flag(self, rng):
        """On the CPU test backend the layer must take the XLA path; the
        flash gate is TPU-only. Semantics are identical either way."""
        from paddle_tpu.core.sequence import pack_sequences
        from paddle_tpu.core.topology import Topology
        s_rows = [rng.randn(5, 8).astype(np.float32),
                  rng.randn(7, 8).astype(np.float32)]
        s = paddle.layer.data("s", paddle.data_type.dense_vector_sequence(8))
        att = paddle.layer.dot_product_attention(s, num_heads=2)
        topo = Topology(att)
        params = topo.init_params(jax.random.PRNGKey(0))
        outs, _ = topo.forward(params, topo.init_state(),
                               {"s": pack_sequences(s_rows)}, mode="test",
                               rng=jax.random.PRNGKey(1))
        out = outs[att.name]
        assert np.all(np.isfinite(np.asarray(out.data)))
        assert paddle.config.global_config().use_flash_attention
