"""Real-TPU smoke tests for the compiled Mosaic kernel paths.

The regular suite runs every Pallas kernel in interpret mode on CPU;
these tests exercise the COMPILED path on actual TPU hardware (the gap
ADVICE round 2 flagged: interpret-only coverage can hide Mosaic
compile/tiling failures). They self-skip off-TPU, so the CPU CI lane is
unaffected; run the TPU lane with:

    PADDLE_TPU_SMOKE=1 python -m pytest tests/test_tpu_smoke.py -q

(the env var tells conftest.py to keep the real backend instead of the
virtual 8-device CPU mesh).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp


def _on_tpu():
    try:
        return jax.default_backend() == "tpu"
    except Exception:
        return False


pytestmark = pytest.mark.skipif(not _on_tpu(),
                                reason="needs real TPU hardware")


class TestFlashAttentionCompiled:
    @pytest.mark.parametrize("tq,tk,d", [
        (512, 512, 128),
        (100, 100, 64),        # ragged T -> exercises block rounding/pad
        (1024, 256, 128),      # cross lengths
    ])
    def test_forward_matches_reference(self, tq, tk, d):
        from paddle_tpu.ops.pallas_attention import (_lens_mask, _reference,
                                                     flash_attention)
        rng = np.random.RandomState(0)
        b, h = 2, 4
        q = jnp.asarray(rng.randn(b, tq, h, d).astype(np.float32))
        k = jnp.asarray(rng.randn(b, tk, h, d).astype(np.float32))
        v = jnp.asarray(rng.randn(b, tk, h, d).astype(np.float32))
        lens_q = jnp.asarray([tq, max(tq // 2, 1)], jnp.int32)
        lens_k = jnp.asarray([tk, max(tk // 3, 1)], jnp.int32)
        out = flash_attention(q, k, v, q_lens=lens_q, kv_lens=lens_k,
                              causal=False)
        mask = _lens_mask(lens_q, lens_k, tq, tk, False)
        want = _reference(q, k, v, mask, d ** -0.5)
        np.testing.assert_allclose(np.asarray(out), np.asarray(want),
                                   rtol=2e-2, atol=2e-2)

    def test_backward_matches_reference(self):
        from paddle_tpu.ops.pallas_attention import (_lens_mask, _reference,
                                                     flash_attention)
        rng = np.random.RandomState(1)
        b, t, h, d = 2, 256, 4, 128
        q = jnp.asarray(rng.randn(b, t, h, d).astype(np.float32))
        k = jnp.asarray(rng.randn(b, t, h, d).astype(np.float32))
        v = jnp.asarray(rng.randn(b, t, h, d).astype(np.float32))
        lens = jnp.asarray([t, t // 2], jnp.int32)

        def f(q, k, v):
            return jnp.sum(flash_attention(q, k, v, kv_lens=lens,
                                           q_lens=lens, causal=True) ** 2)

        mask = _lens_mask(lens, lens, t, t, True)

        def r(q, k, v):
            return jnp.sum(_reference(q, k, v, mask, d ** -0.5)
                           .astype(jnp.float32) ** 2)

        gf = jax.jit(jax.grad(f, argnums=(0, 1, 2)))(q, k, v)
        gr = jax.jit(jax.grad(r, argnums=(0, 1, 2)))(q, k, v)
        for a, b_ in zip(gf, gr):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                       rtol=5e-2, atol=5e-2)


class TestLstmCompiled:
    def test_train_step_matches_lax(self):
        from paddle_tpu.ops import pallas_rnn
        rng = np.random.RandomState(2)
        b, T, h = 16, 12, 128
        x4 = jnp.asarray(rng.randn(b, T, 4 * h).astype(np.float32) * 0.1)
        w = jnp.asarray(rng.randn(h, 4 * h).astype(np.float32) * 0.1)
        bias = jnp.asarray(rng.randn(4 * h).astype(np.float32) * 0.1)
        lens = jnp.asarray(rng.randint(3, T + 1, b), jnp.int32)

        def f(x4, w, bias):
            out, hT, cT = pallas_rnn.lstm_sequence(x4, lens, w, bias, None)
            return jnp.sum(out ** 2) + jnp.sum(hT) + jnp.sum(cT)

        def r(x4, w, bias):
            out, hT, cT = pallas_rnn._lstm_ref(
                x4, lens.reshape(b, 1), w, bias.reshape(1, -1),
                jnp.zeros((3, h)))
            return jnp.sum(out ** 2) + jnp.sum(hT) + jnp.sum(cT)

        vf, gf = jax.jit(jax.value_and_grad(f, argnums=(0, 1, 2)))(
            x4, w, bias)
        vr, gr = jax.jit(jax.value_and_grad(r, argnums=(0, 1, 2)))(
            x4, w, bias)
        np.testing.assert_allclose(float(vf), float(vr), rtol=1e-3)
        for a, b_ in zip(gf, gr):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                       rtol=1e-2, atol=1e-3)


class TestCpuTpuParity:
    """The reference's CPU<->GPU parity discipline (test_matrixCompare.cpp,
    test_CpuGpuVector.cpp) applied for real: the SAME jitted computation
    on the TPU backend vs the in-process CPU backend, asserted allclose.
    JAX always carries a CPU backend, so this needs no process tricks."""

    def _both(self, fn, *args):
        # placement follows the committed inputs (jit's device= kwarg is
        # deprecated): default device_put -> TPU, explicit put -> CPU
        cpu = jax.devices("cpu")[0]
        on_t = jax.jit(fn)(*args)
        on_c = jax.jit(fn)(
            *jax.tree_util.tree_map(lambda a: jax.device_put(a, cpu), args))
        return (jax.tree_util.tree_map(np.asarray, on_t),
                jax.tree_util.tree_map(np.asarray, on_c))

    def test_fc_train_grads(self):
        rng = np.random.RandomState(0)
        x = jnp.asarray(rng.randn(32, 64).astype(np.float32))
        w = jnp.asarray(rng.randn(64, 16).astype(np.float32))

        def loss(x, w):
            from paddle_tpu.ops import linear
            return jnp.sum(jax.nn.softmax(linear.matmul(x, w)) ** 2)

        t, c = self._both(jax.grad(loss, argnums=(0, 1)), x, w)
        for a, b in zip(t, c):
            np.testing.assert_allclose(a, b, rtol=2e-3, atol=2e-4)

    def test_conv_bn_forward(self):
        rng = np.random.RandomState(1)
        x = jnp.asarray(rng.randn(4, 16, 16, 8).astype(np.float32))
        k = jnp.asarray(rng.randn(3, 3, 8, 16).astype(np.float32) * 0.1)

        def f(x, k):
            from paddle_tpu.ops import conv as conv_ops
            from paddle_tpu.ops import norm as norm_ops
            y = conv_ops.conv2d(x, k, stride=1, padding=1)
            g = jnp.ones((16,), jnp.float32)
            b = jnp.zeros((16,), jnp.float32)
            out, _, _ = norm_ops.batch_norm_train(
                y, g, b, jnp.zeros((16,)), jnp.ones((16,)))
            return out

        t, c = self._both(f, x, k)
        np.testing.assert_allclose(t, c, rtol=2e-3, atol=2e-3)

    def test_seqpool_embedding_path(self):
        rng = np.random.RandomState(2)
        ids = jnp.asarray(rng.randint(0, 50, (8, 12)).astype(np.int32))
        table = jnp.asarray(rng.randn(50, 24).astype(np.float32))
        lens = jnp.asarray(rng.randint(1, 13, (8,)), jnp.int32)

        def f(table, ids):
            e = table[ids]                              # [b, T, d]
            m = (jnp.arange(12)[None, :] < lens[:, None]).astype(e.dtype)
            s = jnp.sum(e * m[:, :, None], axis=1)
            return s / jnp.maximum(lens[:, None].astype(e.dtype), 1.0)

        t, c = self._both(f, table, ids)
        np.testing.assert_allclose(t, c, rtol=1e-4, atol=1e-5)
