"""Real-TPU smoke tests for the compiled Mosaic kernel paths.

The regular suite runs every Pallas kernel in interpret mode on CPU;
these tests exercise the COMPILED path on actual TPU hardware (the gap
ADVICE round 2 flagged: interpret-only coverage can hide Mosaic
compile/tiling failures). They self-skip off-TPU, so the CPU CI lane is
unaffected; run the TPU lane with:

    PADDLE_TPU_SMOKE=1 python -m pytest tests/test_tpu_smoke.py -q

(the env var tells conftest.py to keep the real backend instead of the
virtual 8-device CPU mesh).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp


def _on_tpu():
    try:
        return jax.default_backend() == "tpu"
    except Exception:
        return False


pytestmark = pytest.mark.skipif(not _on_tpu(),
                                reason="needs real TPU hardware")


class TestFlashAttentionCompiled:
    @pytest.mark.parametrize("tq,tk,d", [
        (512, 512, 128),
        (100, 100, 64),        # ragged T -> exercises block rounding/pad
        (1024, 256, 128),      # cross lengths
    ])
    def test_forward_matches_reference(self, tq, tk, d):
        from paddle_tpu.ops.pallas_attention import (_lens_mask, _reference,
                                                     flash_attention)
        rng = np.random.RandomState(0)
        b, h = 2, 4
        q = jnp.asarray(rng.randn(b, tq, h, d).astype(np.float32))
        k = jnp.asarray(rng.randn(b, tk, h, d).astype(np.float32))
        v = jnp.asarray(rng.randn(b, tk, h, d).astype(np.float32))
        lens_q = jnp.asarray([tq, max(tq // 2, 1)], jnp.int32)
        lens_k = jnp.asarray([tk, max(tk // 3, 1)], jnp.int32)
        out = flash_attention(q, k, v, q_lens=lens_q, kv_lens=lens_k,
                              causal=False)
        mask = _lens_mask(lens_q, lens_k, tq, tk, False)
        want = _reference(q, k, v, mask, d ** -0.5)
        np.testing.assert_allclose(np.asarray(out), np.asarray(want),
                                   rtol=2e-2, atol=2e-2)

    def test_backward_matches_reference(self):
        from paddle_tpu.ops.pallas_attention import (_lens_mask, _reference,
                                                     flash_attention)
        rng = np.random.RandomState(1)
        b, t, h, d = 2, 256, 4, 128
        q = jnp.asarray(rng.randn(b, t, h, d).astype(np.float32))
        k = jnp.asarray(rng.randn(b, t, h, d).astype(np.float32))
        v = jnp.asarray(rng.randn(b, t, h, d).astype(np.float32))
        lens = jnp.asarray([t, t // 2], jnp.int32)

        def f(q, k, v):
            return jnp.sum(flash_attention(q, k, v, kv_lens=lens,
                                           q_lens=lens, causal=True) ** 2)

        mask = _lens_mask(lens, lens, t, t, True)

        def r(q, k, v):
            return jnp.sum(_reference(q, k, v, mask, d ** -0.5)
                           .astype(jnp.float32) ** 2)

        gf = jax.jit(jax.grad(f, argnums=(0, 1, 2)))(q, k, v)
        gr = jax.jit(jax.grad(r, argnums=(0, 1, 2)))(q, k, v)
        for a, b_ in zip(gf, gr):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                       rtol=5e-2, atol=5e-2)


class TestLstmCompiled:
    def test_train_step_matches_lax(self):
        from paddle_tpu.ops import pallas_rnn
        rng = np.random.RandomState(2)
        b, T, h = 16, 12, 128
        x4 = jnp.asarray(rng.randn(b, T, 4 * h).astype(np.float32) * 0.1)
        w = jnp.asarray(rng.randn(h, 4 * h).astype(np.float32) * 0.1)
        bias = jnp.asarray(rng.randn(4 * h).astype(np.float32) * 0.1)
        lens = jnp.asarray(rng.randint(3, T + 1, b), jnp.int32)

        def f(x4, w, bias):
            out, hT, cT = pallas_rnn.lstm_sequence(x4, lens, w, bias, None)
            return jnp.sum(out ** 2) + jnp.sum(hT) + jnp.sum(cT)

        def r(x4, w, bias):
            out, hT, cT = pallas_rnn._lstm_ref(
                x4, lens.reshape(b, 1), w, bias.reshape(1, -1),
                jnp.zeros((3, h)))
            return jnp.sum(out ** 2) + jnp.sum(hT) + jnp.sum(cT)

        vf, gf = jax.jit(jax.value_and_grad(f, argnums=(0, 1, 2)))(
            x4, w, bias)
        vr, gr = jax.jit(jax.value_and_grad(r, argnums=(0, 1, 2)))(
            x4, w, bias)
        np.testing.assert_allclose(float(vf), float(vr), rtol=1e-3)
        for a, b_ in zip(gf, gr):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                       rtol=1e-2, atol=1e-3)
