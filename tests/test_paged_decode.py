"""Paged-KV continuous-batching decode vs the dense-cache reference.

The engine contract (ISSUE 6): greedy decode through the paged KV
cache + fixed-shape slot batch must be TOKEN-IDENTICAL to
``TransformerDecoder.generate`` (the dense path test_decode.py already
pins against the training graph) — on ragged batches, across page
boundaries, under GQA, and through preemption/eviction replays. The
decode step must compile exactly once no matter how requests join and
leave (@recompile_budget); KV pages must always return to the pool.
"""

import numpy as np
import pytest

import jax
import paddle_tpu as paddle
from paddle_tpu import models
from paddle_tpu.serving import DecodeEngine, PagePool, Rejected
from paddle_tpu.serving.engine import GenRequest  # noqa: F401 (re-export)

CFG = dict(vocab_size=40, d_model=16, n_heads=2, n_layers=2, d_ff=32,
           max_len=32)


def _model(seed=7, **overrides):
    paddle.init(use_tpu=False, seed=0)
    from paddle_tpu.core.registry import reset_name_counters
    reset_name_counters()
    spec = models.transformer_lm(**{**CFG, **overrides})
    costs = spec.cost if isinstance(spec.cost, list) else [spec.cost]
    topo = paddle.Topology(costs, extra_outputs=[spec.output])
    params = topo.init_params(jax.random.PRNGKey(seed))
    return params


def _decoder(params, n_heads=None):
    return models.TransformerDecoder(params, n_layers=CFG["n_layers"],
                                     n_heads=n_heads or CFG["n_heads"])


def _dense_rows(dec, prompts, max_news):
    """Reference: the dense-cache decoder, one request at a time (the
    per-request path the engine replaces)."""
    return [dec.generate(p[None, :], max_len=len(p) + mn)[0]
            for p, mn in zip(prompts, max_news)]


def _ragged(rng, n, lo=3, hi=9):
    return [rng.randint(0, CFG["vocab_size"],
                        (int(rng.randint(lo, hi)),)).astype("int32")
            for _ in range(n)]


def _balanced(eng):
    """Zero leaks, zero refcount drift. With the prefix cache on (the
    default) a drained engine parks finished pages in the trie, so the
    balance is free + trie-held == usable and refs == slots + trie."""
    acc = eng.page_accounting()
    assert acc["leaked"] == 0
    assert acc["free"] + acc["held_by_trie"] == acc["total_usable"]
    assert acc["refs_total"] == \
        acc["held_by_slots"] + acc["held_by_trie"]
    return acc


class TestPagedAttentionUnit:
    """ops/pallas_decode.paged_attention vs a straight dense reference,
    including GQA widths, per-row ragged lengths, and the composition
    with the recorded-experiment Pallas kernel."""

    def _reference(self, q, k, v, lens):
        b, h, dh = q.shape
        g = k.shape[2]
        rep = h // g
        t = k.shape[1]
        q5 = q.reshape(b, 1, g, rep, dh)
        logits = np.einsum("bqgrd,bkgd->bgrqk", q5, k) * dh ** -0.5
        mask = np.arange(t)[None, :] < np.asarray(lens)[:, None]
        logits = np.where(mask[:, None, None, None], logits, -1e30)
        w = np.exp(logits - logits.max(-1, keepdims=True))
        w = w / w.sum(-1, keepdims=True)
        return np.einsum("bgrqk,bkgd->bqgrd", w, v).reshape(b, h, dh)

    @pytest.mark.parametrize("h,g", [(4, 4), (4, 2), (4, 1)])
    def test_matches_dense_reference(self, h, g):
        from paddle_tpu.ops.pallas_decode import paged_attention
        rng = np.random.RandomState(0)
        b, dh, ps, npages, P = 3, 8, 4, 16, 5
        k_pages = rng.randn(npages, ps, g, dh).astype(np.float32)
        v_pages = rng.randn(npages, ps, g, dh).astype(np.float32)
        q = rng.randn(b, h, dh).astype(np.float32)
        # distinct physical pages per row, deliberately out of order
        table = np.array([[3, 1, 7, 0, 0],
                          [2, 9, 4, 11, 0],
                          [5, 6, 0, 0, 0]], np.int32)
        lens = np.array([9, 17, 5], np.int32)   # ragged, straddling
        got = np.asarray(paged_attention(
            jax.numpy.asarray(q), jax.numpy.asarray(k_pages),
            jax.numpy.asarray(v_pages), jax.numpy.asarray(table),
            jax.numpy.asarray(lens)))
        k = k_pages[table].reshape(b, P * ps, g, dh)
        v = v_pages[table].reshape(b, P * ps, g, dh)
        want = self._reference(q, k, v, lens)
        np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-6)

    def test_kernel_composition_matches_einsum_path(self):
        """use_kernel=True gathers the same pages and runs the GQA
        decode kernel — same numbers (interpret mode on CPU)."""
        from paddle_tpu.ops.pallas_decode import paged_attention
        rng = np.random.RandomState(1)
        b, h, g, dh, ps, npages, P = 2, 4, 2, 8, 4, 8, 4
        k_pages = jax.numpy.asarray(
            rng.randn(npages, ps, g, dh).astype(np.float32))
        v_pages = jax.numpy.asarray(
            rng.randn(npages, ps, g, dh).astype(np.float32))
        q = jax.numpy.asarray(rng.randn(b, h, dh).astype(np.float32))
        table = jax.numpy.asarray(
            np.array([[1, 4, 2, 0], [3, 5, 0, 0]], np.int32))
        lens = jax.numpy.asarray(np.array([10, 7], np.int32))
        ein = paged_attention(q, k_pages, v_pages, table, lens)
        ker = paged_attention(q, k_pages, v_pages, table, lens,
                              use_kernel=True, interpret=True)
        np.testing.assert_allclose(np.asarray(ker), np.asarray(ein),
                                   rtol=2e-4, atol=2e-5)

    def test_decode_attention_per_row_lens(self):
        """The dense-layout kernel now takes per-row kv lengths: each
        row must mask at ITS length (scalar path unchanged)."""
        from paddle_tpu.ops.pallas_decode import decode_attention
        rng = np.random.RandomState(2)
        b, h, g, dh, T = 3, 4, 2, 8, 16
        q = jax.numpy.asarray(rng.randn(b, h, dh).astype(np.float32))
        kc = jax.numpy.asarray(
            rng.randn(b, g, dh, T).astype(np.float32))
        vc = jax.numpy.asarray(
            rng.randn(b, g, dh, T).astype(np.float32))
        lens = np.array([5, 16, 11], np.int32)
        got = np.asarray(decode_attention(
            q, kc, vc, jax.numpy.asarray(lens), interpret=True))
        for i, ln in enumerate(lens):
            one = np.asarray(decode_attention(
                q[i:i + 1], kc[i:i + 1], vc[i:i + 1], int(ln),
                interpret=True))
            np.testing.assert_allclose(got[i:i + 1], one,
                                       rtol=2e-5, atol=2e-6)


class TestPagedWindowKernel:
    """Round 9 allocated-pages kernel (ops/pallas_decode.py
    paged_window_attention) vs the gather/einsum reference: W-token
    verify windows, GQA/MQA widths, ragged lengths whose trailing
    page-table entries the clamped index map must never read."""

    @pytest.mark.parametrize("h,g", [(4, 4), (4, 2), (4, 1)])
    def test_window_parity_gqa(self, h, g):
        from paddle_tpu.ops.pallas_decode import paged_window_attention
        rng = np.random.RandomState(3)
        S, W, dh, ps, npages = 3, 3, 8, 4, 12
        k_pages = rng.randn(npages, ps, g, dh).astype(np.float32)
        v_pages = rng.randn(npages, ps, g, dh).astype(np.float32)
        q = rng.randn(S, W, h, dh).astype(np.float32)
        # out-of-order physical pages; rows past the allocation point
        # are the null page and must be SKIPPED, not gathered
        tables = np.array([[3, 1, 7, 0, 0],
                           [2, 9, 4, 11, 8],
                           [5, 6, 0, 0, 0]], np.int32)
        base = np.array([9, 15, 5], np.int32)     # ragged, mid-page
        lens = (base[:, None] + np.arange(W)[None, :]).astype(np.int32)
        args = [jax.numpy.asarray(a) for a in
                (q, k_pages, v_pages, tables, lens)]
        want = np.asarray(paged_window_attention(*args))
        got = np.asarray(paged_window_attention(
            *args, use_kernel=True, interpret=True))
        np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-5)

    def test_w1_matches_paged_attention(self):
        """W = 1 is the classic one-token step — same numbers as the
        round-6 paged_attention path."""
        from paddle_tpu.ops.pallas_decode import (paged_attention,
                                                  paged_window_attention)
        rng = np.random.RandomState(4)
        S, h, g, dh, ps, npages = 2, 4, 2, 8, 4, 8
        k_pages = jax.numpy.asarray(
            rng.randn(npages, ps, g, dh).astype(np.float32))
        v_pages = jax.numpy.asarray(
            rng.randn(npages, ps, g, dh).astype(np.float32))
        q = jax.numpy.asarray(rng.randn(S, h, dh).astype(np.float32))
        tables = jax.numpy.asarray(
            np.array([[1, 4, 2, 0], [3, 5, 0, 0]], np.int32))
        lens = jax.numpy.asarray(np.array([10, 7], np.int32))
        want = np.asarray(
            paged_attention(q, k_pages, v_pages, tables, lens))
        got = np.asarray(paged_window_attention(
            q[:, None], k_pages, v_pages, tables, lens[:, None],
            use_kernel=True, interpret=True))[:, 0]
        np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-5)

    def test_kernel_gate(self):
        from paddle_tpu.ops.pallas_decode import paged_kernel_supported
        q = jax.numpy.zeros((2, 2, 4, 8), np.float32)
        k = jax.numpy.zeros((8, 4, 2, 8), np.float32)
        assert paged_kernel_supported(q, k)
        # head dim off the sublane multiple -> fall back to XLA
        k_odd = jax.numpy.zeros((8, 4, 2, 6), np.float32)
        assert not paged_kernel_supported(q, k_odd)


class TestDequantWindowKernel:
    """ISSUE 20: the dequant-fused variant of the allocated-pages
    kernel over INT8 pools (quantize_kv rows + per-(row, kv-head)
    float32 scales). Three pins: the fused kernel matches the
    dequantizing gather/einsum path bit-for-tolerance, both int8 paths
    stay within the pinned INT8_KV_RTOL/ATOL contract of the exact
    float32 attention, and the VMEM gate accounts for the scale
    blocks."""

    def _quant_pools(self, rng, npages, ps, g, dh):
        from paddle_tpu.ops.pallas_decode import quantize_kv
        k = rng.randn(npages, ps, g, dh).astype(np.float32)
        v = rng.randn(npages, ps, g, dh).astype(np.float32)
        kq, ks = quantize_kv(jax.numpy.asarray(k))
        vq, vs = quantize_kv(jax.numpy.asarray(v))
        return k, v, kq, ks, vq, vs

    @pytest.mark.parametrize("h,g", [(4, 4), (4, 2), (4, 1)])
    def test_dequant_kernel_matches_gather_path(self, h, g):
        """GQA/MQA widths, out-of-order physical pages, ragged mid-page
        lengths: the fused kernel (interpret mode) vs the dequantizing
        gather + exact einsum — same int8 inputs, same numbers."""
        from paddle_tpu.ops.pallas_decode import paged_window_attention
        rng = np.random.RandomState(13)
        S, W, dh, ps, npages = 3, 3, 8, 4, 12
        _, _, kq, ks, vq, vs = self._quant_pools(rng, npages, ps, g, dh)
        q = jax.numpy.asarray(
            rng.randn(S, W, h, dh).astype(np.float32))
        tables = jax.numpy.asarray(
            np.array([[3, 1, 7, 0, 0],
                      [2, 9, 4, 11, 8],
                      [5, 6, 0, 0, 0]], np.int32))
        base = np.array([9, 15, 5], np.int32)
        lens = jax.numpy.asarray(
            (base[:, None] + np.arange(W)[None, :]).astype(np.int32))
        want = np.asarray(paged_window_attention(
            q, kq, vq, tables, lens, k_scales=ks, v_scales=vs))
        got = np.asarray(paged_window_attention(
            q, kq, vq, tables, lens, k_scales=ks, v_scales=vs,
            use_kernel=True, interpret=True))
        np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-5)

    def test_int8_within_pinned_contract_of_fp32(self):
        """The token-identity tolerance contract: int8 attention
        outputs (gather AND fused kernel) sit within INT8_KV_RTOL/ATOL
        of the exact float32 attention over the same pre-quantization
        pages — the bound under which tiny-model greedy argmax stays
        stable (TestTwoTierChaos pins the end-to-end identity)."""
        from paddle_tpu.ops.pallas_decode import (
            INT8_KV_ATOL, INT8_KV_RTOL, paged_window_attention)
        rng = np.random.RandomState(14)
        S, W, h, g, dh, ps, npages = 2, 2, 4, 2, 8, 4, 10
        k, v, kq, ks, vq, vs = self._quant_pools(rng, npages, ps, g, dh)
        q = jax.numpy.asarray(
            rng.randn(S, W, h, dh).astype(np.float32))
        tables = jax.numpy.asarray(
            np.array([[1, 4, 2, 0], [3, 5, 7, 0]], np.int32))
        base = np.array([10, 7], np.int32)
        lens = jax.numpy.asarray(
            (base[:, None] + np.arange(W)[None, :]).astype(np.int32))
        exact = np.asarray(paged_window_attention(
            q, jax.numpy.asarray(k), jax.numpy.asarray(v),
            tables, lens))
        for use_kernel in (False, True):
            got = np.asarray(paged_window_attention(
                q, kq, vq, tables, lens, k_scales=ks, v_scales=vs,
                use_kernel=use_kernel, interpret=use_kernel))
            np.testing.assert_allclose(got, exact, rtol=INT8_KV_RTOL,
                                       atol=INT8_KV_ATOL)

    def test_quantize_roundtrip_properties(self):
        """quantize_kv is a pure per-row function (token identity
        across prefix reuse needs the same row to quantize the same
        way in any batch) and all-zero rows — the null page — stay
        exactly zero after dequant."""
        from paddle_tpu.ops.pallas_decode import (dequantize_kv,
                                                  quantize_kv)
        rng = np.random.RandomState(15)
        rows = jax.numpy.asarray(rng.randn(6, 4, 2, 8)
                                 .astype(np.float32))
        q1, s1 = quantize_kv(rows)
        q2, s2 = quantize_kv(rows[2:5])      # different batch context
        np.testing.assert_array_equal(np.asarray(q1)[2:5],
                                      np.asarray(q2))
        np.testing.assert_array_equal(np.asarray(s1)[2:5],
                                      np.asarray(s2))
        zq, zs = quantize_kv(jax.numpy.zeros((1, 4, 2, 8), np.float32))
        np.testing.assert_array_equal(
            np.asarray(dequantize_kv(zq, zs)), 0.0)
        # max quantization error bounded by scale/2 per element
        back = np.asarray(dequantize_kv(q1, s1))
        err = np.abs(back - np.asarray(rows))
        bound = np.asarray(s1)[..., None] * 0.5 + 1e-7
        assert (err <= bound).all()

    def test_gate_counts_scale_blocks(self):
        from paddle_tpu.ops.pallas_decode import paged_kernel_supported
        q = jax.numpy.zeros((2, 2, 4, 8), np.float32)
        k8 = jax.numpy.zeros((8, 4, 2, 8), jax.numpy.int8)
        sc = jax.numpy.zeros((8, 4, 2), np.float32)
        assert paged_kernel_supported(q, k8, sc)
        # odd head dim still falls back, scales or not
        k_odd = jax.numpy.zeros((8, 4, 2, 6), jax.numpy.int8)
        assert not paged_kernel_supported(
            q, k_odd, jax.numpy.zeros((8, 4, 2), np.float32))


class TestPagePool:
    def test_alloc_free_accounting(self):
        pool = PagePool(8)              # 7 usable, page 0 reserved
        assert pool.usable == 7
        pages = [pool.alloc() for _ in range(7)]
        assert 0 not in pages           # the null page is never issued
        assert pool.alloc() is None     # exhausted, not an exception
        assert pool.accounting()["leaked"] == 0
        pool.free(pages[:3])
        assert pool.free_pages == 3 and pool.used_pages == 4
        assert pool.high_water == 7
        pool.free(pages[3:])
        assert pool.accounting() == {
            "total_usable": 7, "free": 7, "allocated": 0, "leaked": 0,
            "refs_total": 0, "shared": 0, "high_water": 7, }

    def test_double_free_is_loud(self):
        pool = PagePool(4)
        p = pool.alloc()
        pool.free([p])
        with pytest.raises(ValueError, match="double free|foreign"):
            pool.free([p])
        with pytest.raises(ValueError):
            pool.free([99])

    def test_refcounted_sharing(self):
        """Round 9: alloc() hands a page out at refcount 1, ref() adds
        holders (shared-prefix attach / trie indexing), and free() only
        returns the page to the free list at zero."""
        pool = PagePool(5)
        p = pool.alloc()
        assert pool.refcount(p) == 1 and pool.shared_pages == 0
        pool.ref(p)
        pool.ref(p)
        assert pool.refcount(p) == 3 and pool.shared_pages == 1
        pool.free([p])                      # one holder lets go
        assert pool.refcount(p) == 2
        assert pool.used_pages == 1         # still allocated
        pool.free([p, p])                   # last holders release
        assert pool.refcount(p) == 0
        assert pool.free_pages == pool.usable
        acc = pool.accounting()
        assert acc["leaked"] == 0 and acc["refs_total"] == 0

    def test_refcount_underflow_is_loud(self):
        """Freeing past zero is indistinguishable from a lost page —
        both raise rather than silently corrupting shared KV."""
        pool = PagePool(5)
        p = pool.alloc()
        pool.ref(p)
        pool.free([p, p])
        with pytest.raises(ValueError, match="underflow|double free"):
            pool.free([p])
        with pytest.raises(ValueError, match="not allocated"):
            pool.ref(p)                     # ref after full release

    def test_refcount_histogram(self):
        pool = PagePool(8)
        a, b, c = pool.alloc(), pool.alloc(), pool.alloc()
        pool.ref(b)
        pool.ref(c)
        pool.ref(c)
        assert pool.refcount_histogram() == {1: 1, 2: 1, 3: 1}
        assert pool.accounting()["refs_total"] == 6
        assert pool.accounting()["shared"] == 2
        pool.free([b, c, c])
        assert pool.refcount_histogram() == {1: 3}
        pool.free([a, b, c])
        assert pool.refcount_histogram() == {}


class TestTokenIdentity:
    """THE acceptance test: greedy paged decode == greedy dense decode,
    token for token, on ragged batches whose sequences straddle page
    boundaries — and the engine step compiles exactly once even though
    requests join and leave mid-flight."""

    def test_ragged_batch_token_identical(self):
        params = _model()
        dec = _decoder(params)
        rng = np.random.RandomState(0)
        # lengths 3..8 against page_size 4: sequences start mid-page,
        # end mid-page, and cross 1-3 page boundaries while growing
        prompts = _ragged(rng, 6, lo=3, hi=9)
        max_news = [int(rng.randint(4, 12)) for _ in prompts]
        want = _dense_rows(dec, prompts, max_news)

        eng = DecodeEngine(dec, num_slots=3, page_size=4,
                           max_seq_len=CFG["max_len"])
        # more requests than slots: joins happen mid-flight as earlier
        # sequences finish — continuous batching, not static batching
        reqs = [eng.submit(p, mn) for p, mn in zip(prompts, max_news)]
        eng.run(timeout=300)
        for i, r in enumerate(reqs):
            assert r.get(timeout=1) == [int(t) for t in want[i]], i
        _balanced(eng)
        st = eng.stats()
        assert st["finished"] == len(prompts)
        assert st["tokens_out"] == sum(max_news)

    def test_gqa_token_identical(self):
        params = _model(seed=3, n_kv_heads=1)   # MQA: cache narrower
        dec = _decoder(params)
        rng = np.random.RandomState(1)
        prompts = _ragged(rng, 4, lo=3, hi=8)
        max_news = [6, 9, 5, 8]
        want = _dense_rows(dec, prompts, max_news)
        eng = DecodeEngine(dec, num_slots=4, page_size=4,
                           max_seq_len=CFG["max_len"])
        reqs = [eng.submit(p, mn) for p, mn in zip(prompts, max_news)]
        eng.run(timeout=300)
        for i, r in enumerate(reqs):
            assert r.get(timeout=1) == [int(t) for t in want[i]], i
        assert eng.page_accounting()["leaked"] == 0

    @pytest.mark.recompile_budget(max_compiles=8)
    def test_churn_causes_zero_recompiles(self):
        """THE shape-stability pin: with the engine warm, a storm of
        mid-flight joins, a cancellation, and a pool-pressure eviction
        cause ZERO XLA compilations — the continuous-batching loop
        never retraces (the fixed-shape slot-batch contract). The
        marker budget (8) is headroom for param-init/jit of the warmup
        phase, which legitimately compiles several shape families; the
        churn phase itself is held to an exact total of 0 by the inner
        watch."""
        from paddle_tpu.analysis.sanitizer import compile_watch
        from paddle_tpu.testing import FaultPlan
        params = _model()
        dec = _decoder(params)
        eng = DecodeEngine(dec, num_slots=2, page_size=4,
                           max_seq_len=20, num_pages=8)
        warm = eng.submit(np.zeros((3,), "int32"), 1)
        eng.run(timeout=120)                  # compiles the step once
        assert warm.get(timeout=1)
        r0 = eng.submit(np.zeros((4,), "int32"), 10)
        joined = []
        with compile_watch() as watch:
            with FaultPlan.decode_script(eng, {
                    2: lambda: joined.append(
                        eng.submit(np.ones((6,), "int32"), 9)),
                    4: lambda: joined.append(
                        eng.submit(np.full((5,), 2, "int32"), 8)),
                    7: lambda: joined[0].cancel()}) as script:
                eng.run(timeout=300)
            assert script["fired"] == [2, 4, 7]
        assert watch.total == 0, (
            f"join/evict/cancel churn recompiled: {watch.per_function}")
        assert len(r0.get(timeout=1)) == 10
        assert joined[0].state == "cancelled"
        assert len(joined[1].get(timeout=1)) == 8
        assert eng.page_accounting()["leaked"] == 0

    def test_eos_frees_slot_early(self):
        """A request that hits its eos mid-flight finishes, frees its
        pages, and its tokens still match the dense path's trim."""
        params = _model()
        dec = _decoder(params)
        prompt = np.zeros((2,), "int32")
        dense = dec.generate(prompt[None, :], max_len=14)[0]
        eos = dense[1] if len(set(dense)) > 1 else dense[0]
        dense_trim = dec.generate(prompt[None, :], max_len=14,
                                  eos_id=int(eos))[0]
        eng = DecodeEngine(dec, num_slots=2, page_size=4,
                           max_seq_len=CFG["max_len"])
        req = eng.submit(prompt, 12, eos_id=int(eos))
        eng.run(timeout=120)
        assert req.get(timeout=1) == [int(t) for t in dense_trim]
        _balanced(eng)


class TestScheduling:
    def test_preemption_under_tiny_pool_is_output_invariant(self):
        """A pool too small for both requests forces preemption: the
        youngest is evicted, its pages return, and on re-admission it
        replays prompt + generated tokens — BOTH outputs stay identical
        to undisturbed solo runs (greedy determinism survives
        eviction)."""
        params = _model()
        dec = _decoder(params)
        rng = np.random.RandomState(2)
        p1 = rng.randint(0, 40, (5,)).astype("int32")
        p2 = rng.randint(0, 40, (6,)).astype("int32")
        want1 = dec.generate(p1[None, :], max_len=5 + 12)[0]
        want2 = dec.generate(p2[None, :], max_len=6 + 12)[0]
        # each needs ceil(17/4)=5 / ceil(18/4)=5 pages; give the pool 7
        # usable so concurrent growth MUST preempt at some point
        eng = DecodeEngine(dec, num_slots=2, page_size=4,
                           max_seq_len=CFG["max_len"], num_pages=8)
        r1 = eng.submit(p1, 12)
        r2 = eng.submit(p2, 12)
        eng.run(timeout=300)
        assert r1.get(timeout=1) == [int(t) for t in want1]
        assert r2.get(timeout=1) == [int(t) for t in want2]
        st = eng.stats()
        assert st["preemptions"] >= 1, \
            "pool was sized to force at least one preemption"
        assert (r1.evictions + r2.evictions) == st["preemptions"]
        assert eng.page_accounting()["leaked"] == 0

    def test_admission_rejects_never_satisfiable(self):
        params = _model()
        eng = DecodeEngine(_decoder(params), num_slots=2, page_size=4,
                           max_seq_len=16)
        with pytest.raises(Rejected) as ei:
            eng.submit(np.zeros((8,), "int32"), 20)   # 28 > 16
        assert ei.value.reason == "kv_capacity"
        # pool smaller than the sequence cap: page check also rejects
        eng2 = DecodeEngine(_decoder(params), num_slots=2, page_size=4,
                            max_seq_len=16, num_pages=3)
        with pytest.raises(Rejected) as ei2:
            eng2.submit(np.zeros((8,), "int32"), 6)   # 4 pages > 2
        assert ei2.value.reason == "kv_capacity"

    def test_wait_queue_bound(self):
        params = _model()
        eng = DecodeEngine(_decoder(params), num_slots=1, page_size=4,
                           max_seq_len=16, max_waiting=2)
        reqs = [eng.submit(np.zeros((3,), "int32"), 2)
                for _ in range(2)]
        with pytest.raises(Rejected) as ei:
            eng.submit(np.zeros((3,), "int32"), 2)
        assert ei.value.reason == "queue_full"
        assert ei.value.retry_after > 0
        eng.run(timeout=120)
        for r in reqs:
            assert len(r.get(timeout=1)) == 2

    def test_page_aware_admission_head_waits_for_pages(self):
        """A free SLOT is not enough: the queue head only joins when
        the pool can reach its first new token — admission is scheduled
        by free KV pages, not queue depth."""
        params = _model()
        dec = _decoder(params)
        eng = DecodeEngine(dec, num_slots=2, page_size=4,
                           max_seq_len=16, num_pages=5)  # 4 usable
        big = eng.submit(np.zeros((8,), "int32"), 4)     # 3 pages total
        # pages allocate lazily: march until big actually holds 3 of
        # the 4 usable pages (it is still mid-generation then)
        for _ in range(40):
            eng.step()
            if eng.page_accounting()["free"] == 1:
                break
        assert eng.page_accounting()["free"] == 1
        assert big.state == "running"
        rival = eng.submit(np.zeros((8,), "int32"), 4)
        eng.step()
        # a slot is FREE, but the head needs ceil(9/4)=3 pages and only
        # 1 is — admission waits on pages, not on queue depth
        assert eng.stats()["active_slots"] == 1
        assert eng.stats()["waiting"] == 1
        eng.run(timeout=300)
        assert len(big.get(timeout=1)) == 4
        assert len(rival.get(timeout=1)) == 4
        assert eng.page_accounting()["leaked"] == 0


class TestSpeculativeDecoding:
    """ISSUE 13 tentpole (b): a draft model proposes spec_k tokens per
    round and the target verifies them in ONE fixed-shape [S, W] paged
    step. Greedy token-identity acceptance means the OUTPUT never
    depends on the draft — only the step count does."""

    def test_same_weights_draft_multi_token_commits(self):
        params = _model()
        dec = _decoder(params)
        rng = np.random.RandomState(5)
        prompts = _ragged(rng, 3, lo=3, hi=8)
        max_news = [10, 8, 12]
        want = _dense_rows(dec, prompts, max_news)
        eng = DecodeEngine(dec, num_slots=3, page_size=4,
                           max_seq_len=CFG["max_len"],
                           draft=_decoder(_model()), spec_k=2)
        reqs = [eng.submit(p, mn) for p, mn in zip(prompts, max_news)]
        eng.run(timeout=300)
        for i, r in enumerate(reqs):
            assert r.get(timeout=1) == [int(t) for t in want[i]], i
        st = eng.stats()
        assert st["window"] == 3 and st["spec_k"] == 2
        assert st["spec_proposed_tokens"] > 0
        assert st["spec_accepted_tokens"] > 0
        # a perfect draft makes multi-token commits the norm: strictly
        # more tokens out than target dispatches (accepted/step > 1)
        assert st["tokens_out"] > st["steps"]
        assert sum(r.accepted_tokens for r in reqs) == \
            st["spec_accepted_tokens"]
        _balanced(eng)

    def test_disagreeing_draft_still_token_identical(self):
        """A draft with different weights proposes mostly-wrong tokens:
        acceptance filters them; rejected speculation rows are masked
        by kv_len and overwritten before they can be read."""
        params = _model()
        dec = _decoder(params)
        rng = np.random.RandomState(6)
        prompts = _ragged(rng, 3, lo=3, hi=8)
        max_news = [8, 10, 6]
        want = _dense_rows(dec, prompts, max_news)
        eng = DecodeEngine(dec, num_slots=2, page_size=4,
                           max_seq_len=CFG["max_len"],
                           draft=_decoder(_model(seed=11)), spec_k=2)
        reqs = [eng.submit(p, mn) for p, mn in zip(prompts, max_news)]
        eng.run(timeout=300)
        for i, r in enumerate(reqs):
            assert r.get(timeout=1) == [int(t) for t in want[i]], i
        st = eng.stats()
        assert st["spec_proposed_tokens"] >= st["spec_accepted_tokens"]
        _balanced(eng)

    def test_mqa_spec_identity(self):
        """Speculation over the narrow MQA cache: the [S, W] verify
        window reads the cache at stored width."""
        params = _model(seed=3, n_kv_heads=1)
        dec = _decoder(params)
        draft = _decoder(_model(seed=3, n_kv_heads=1))
        rng = np.random.RandomState(7)
        prompts = _ragged(rng, 2, lo=3, hi=7)
        want = _dense_rows(dec, prompts, [9, 7])
        eng = DecodeEngine(dec, num_slots=2, page_size=4,
                           max_seq_len=CFG["max_len"], draft=draft,
                           spec_k=2)
        reqs = [eng.submit(p, mn) for p, mn in zip(prompts, [9, 7])]
        eng.run(timeout=300)
        for i, r in enumerate(reqs):
            assert r.get(timeout=1) == [int(t) for t in want[i]], i
        assert eng.stats()["spec_accepted_tokens"] > 0
        _balanced(eng)

    def test_speculation_requires_greedy(self):
        params = _model()
        with pytest.raises(ValueError, match="greedy|temperature"):
            DecodeEngine(_decoder(params), draft=_decoder(params),
                         spec_k=2, temperature=0.8, max_seq_len=16)


class TestPrefixReuse:
    """ISSUE 13 tentpole (a): radix-indexed shared-prefix KV attach
    with per-page refcounts and copy-on-write on divergence."""

    def test_warm_prefix_attaches_pages_and_skips_prefill(self):
        params = _model()
        dec = _decoder(params)
        rng = np.random.RandomState(8)
        prompt = rng.randint(0, 40, (13,)).astype("int32")
        want = dec.generate(prompt[None, :], max_len=13 + 5)[0]
        eng = DecodeEngine(dec, num_slots=2, page_size=4,
                           max_seq_len=CFG["max_len"])
        cold = eng.submit(prompt, 5)
        eng.run(timeout=120)
        steps_cold = eng.stats()["steps"]
        assert cold.get(timeout=1) == [int(t) for t in want]
        assert cold.prefix_hit_pages == 0
        warm = eng.submit(prompt, 5)
        eng.run(timeout=120)
        steps_warm = eng.stats()["steps"] - steps_cold
        # same tokens, but the shared prefill never re-runs: the warm
        # request attaches the cached pages and feeds only the tail
        assert warm.get(timeout=1) == [int(t) for t in want]
        assert warm.prefix_hit_pages >= 2
        assert steps_warm < steps_cold
        st = eng.stats()
        assert st["prefix_hit_pages"] >= 2
        assert st["kv_pages_shared"] >= 0
        _balanced(eng)

    def test_page_straddling_divergence_cow_identity(self):
        """Divergence INSIDE a shared page forces a copy-on-write: the
        matched rows are copied into a private page, the source page
        keeps its other holders, and both outputs stay exact."""
        params = _model()
        dec = _decoder(params)
        rng = np.random.RandomState(9)
        shared = rng.randint(0, 40, (6,)).astype("int32")
        a = np.concatenate([shared, rng.randint(0, 40, (4,))]) \
            .astype("int32")
        b = np.concatenate([shared, rng.randint(0, 40, (4,))]) \
            .astype("int32")
        b[6] = (a[6] + 1) % 40          # diverge mid-page-1, always
        want_a = dec.generate(a[None, :], max_len=len(a) + 6)[0]
        want_b = dec.generate(b[None, :], max_len=len(b) + 6)[0]
        eng = DecodeEngine(dec, num_slots=1, page_size=4,
                           max_seq_len=CFG["max_len"])
        ra = eng.submit(a, 6)
        eng.run(timeout=120)
        rb = eng.submit(b, 6)
        eng.run(timeout=120)
        assert ra.get(timeout=1) == [int(t) for t in want_a]
        assert rb.get(timeout=1) == [int(t) for t in want_b]
        st = eng.stats()
        assert rb.prefix_hit_pages >= 1     # page 0 attached whole
        assert st["prefix_cow_copies"] >= 1  # page 1 copied on write
        _balanced(eng)

    def test_prefix_cache_off_frees_everything(self):
        params = _model()
        dec = _decoder(params)
        eng = DecodeEngine(dec, num_slots=1, page_size=4,
                           max_seq_len=16, prefix_cache=False)
        r = eng.submit(np.zeros((5,), "int32"), 4)
        eng.run(timeout=120)
        assert len(r.get(timeout=1)) == 4
        r2 = eng.submit(np.zeros((5,), "int32"), 4)
        eng.run(timeout=120)
        assert len(r2.get(timeout=1)) == 4
        acc = eng.page_accounting()
        assert acc["held_by_trie"] == 0
        assert acc["free"] == acc["total_usable"]
        assert eng.stats()["prefix_hit_pages"] == 0

    @pytest.mark.recompile_budget(max_compiles=12)
    def test_spec_prefix_churn_zero_recompiles(self):
        """Round-9 zero-recompile pin: with the [S, W] verify step, the
        draft step AND the CoW page copy warmed, a storm of
        shared-prefix joins (each walking the radix index and copying
        on write) plus a cancel cause ZERO XLA compilations."""
        from paddle_tpu.analysis.sanitizer import compile_watch
        from paddle_tpu.testing import FaultPlan
        params = _model()
        dec = _decoder(params)
        eng = DecodeEngine(dec, num_slots=2, page_size=4,
                           max_seq_len=20, draft=_decoder(_model()),
                           spec_k=2)
        rng = np.random.RandomState(10)
        base = rng.randint(0, 40, (9,)).astype("int32")

        def twin():
            t = np.concatenate([base[:6], rng.randint(0, 40, (3,))]) \
                .astype("int32")
            t[6] = (base[6] + 1 + int(rng.randint(38))) % 40
            return t

        warm = eng.submit(base, 3)
        eng.run(timeout=120)             # target + draft steps compile
        warm2 = eng.submit(twin(), 3)    # CoW warms the page copy
        eng.run(timeout=120)
        assert warm.get(timeout=1) and warm2.get(timeout=1)
        assert eng.stats()["prefix_cow_copies"] >= 1
        joined = []
        r0 = eng.submit(twin(), 8)
        with compile_watch() as watch:
            with FaultPlan.decode_script(eng, {
                    2: lambda: joined.append(eng.submit(twin(), 6)),
                    4: lambda: joined.append(eng.submit(twin(), 6)),
                    6: lambda: joined[0].cancel()}) as script:
                eng.run(timeout=300)
            assert script["fired"] == [2, 4, 6]
        assert watch.total == 0, (
            f"prefix/spec churn recompiled: {watch.per_function}")
        assert len(r0.get(timeout=1)) == 8
        assert joined[0].state in ("cancelled", "done")
        assert len(joined[1].get(timeout=1)) == 6
        _balanced(eng)


class TestBenchSmoke:
    """The CPU smoke slice of the decode_continuous_* bench rows: the
    same driver code bench.py runs on TPU, at toy shape, so a harness
    regression (row stops producing tokens / latency fields vanish)
    surfaces in tier-1 rather than in the next driver capture."""

    def test_decode_continuous_row_smoke(self):
        import bench
        row = bench.bench_decode_continuous(
            num_slots=4, n_requests=6, page_size=4, d_model=16,
            n_layers=2, n_heads=2, vocab_size=40, max_len=32,
            prompt_lens=(3, 8), new_tokens=(4, 10), seed=0)
        assert row["new_tokens"] == row["tokens_out"] > 0
        assert row["tokens_per_sec"] > 0
        assert row["ms"] > 0                     # per-token p50
        assert row["p99_ms"] >= row["ms"]
        assert 0 < row["slot_utilization"] <= 1
        assert row["kv_page_high_water"] > 0
        assert row["preemptions"] == 0
        assert row["roofline_frac"] > 0
