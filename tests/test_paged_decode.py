"""Paged-KV continuous-batching decode vs the dense-cache reference.

The engine contract (ISSUE 6): greedy decode through the paged KV
cache + fixed-shape slot batch must be TOKEN-IDENTICAL to
``TransformerDecoder.generate`` (the dense path test_decode.py already
pins against the training graph) — on ragged batches, across page
boundaries, under GQA, and through preemption/eviction replays. The
decode step must compile exactly once no matter how requests join and
leave (@recompile_budget); KV pages must always return to the pool.
"""

import numpy as np
import pytest

import jax
import paddle_tpu as paddle
from paddle_tpu import models
from paddle_tpu.serving import DecodeEngine, PagePool, Rejected
from paddle_tpu.serving.engine import GenRequest  # noqa: F401 (re-export)

CFG = dict(vocab_size=40, d_model=16, n_heads=2, n_layers=2, d_ff=32,
           max_len=32)


def _model(seed=7, **overrides):
    paddle.init(use_tpu=False, seed=0)
    from paddle_tpu.core.registry import reset_name_counters
    reset_name_counters()
    spec = models.transformer_lm(**{**CFG, **overrides})
    costs = spec.cost if isinstance(spec.cost, list) else [spec.cost]
    topo = paddle.Topology(costs, extra_outputs=[spec.output])
    params = topo.init_params(jax.random.PRNGKey(seed))
    return params


def _decoder(params, n_heads=None):
    return models.TransformerDecoder(params, n_layers=CFG["n_layers"],
                                     n_heads=n_heads or CFG["n_heads"])


def _dense_rows(dec, prompts, max_news):
    """Reference: the dense-cache decoder, one request at a time (the
    per-request path the engine replaces)."""
    return [dec.generate(p[None, :], max_len=len(p) + mn)[0]
            for p, mn in zip(prompts, max_news)]


def _ragged(rng, n, lo=3, hi=9):
    return [rng.randint(0, CFG["vocab_size"],
                        (int(rng.randint(lo, hi)),)).astype("int32")
            for _ in range(n)]


class TestPagedAttentionUnit:
    """ops/pallas_decode.paged_attention vs a straight dense reference,
    including GQA widths, per-row ragged lengths, and the composition
    with the recorded-experiment Pallas kernel."""

    def _reference(self, q, k, v, lens):
        b, h, dh = q.shape
        g = k.shape[2]
        rep = h // g
        t = k.shape[1]
        q5 = q.reshape(b, 1, g, rep, dh)
        logits = np.einsum("bqgrd,bkgd->bgrqk", q5, k) * dh ** -0.5
        mask = np.arange(t)[None, :] < np.asarray(lens)[:, None]
        logits = np.where(mask[:, None, None, None], logits, -1e30)
        w = np.exp(logits - logits.max(-1, keepdims=True))
        w = w / w.sum(-1, keepdims=True)
        return np.einsum("bgrqk,bkgd->bqgrd", w, v).reshape(b, h, dh)

    @pytest.mark.parametrize("h,g", [(4, 4), (4, 2), (4, 1)])
    def test_matches_dense_reference(self, h, g):
        from paddle_tpu.ops.pallas_decode import paged_attention
        rng = np.random.RandomState(0)
        b, dh, ps, npages, P = 3, 8, 4, 16, 5
        k_pages = rng.randn(npages, ps, g, dh).astype(np.float32)
        v_pages = rng.randn(npages, ps, g, dh).astype(np.float32)
        q = rng.randn(b, h, dh).astype(np.float32)
        # distinct physical pages per row, deliberately out of order
        table = np.array([[3, 1, 7, 0, 0],
                          [2, 9, 4, 11, 0],
                          [5, 6, 0, 0, 0]], np.int32)
        lens = np.array([9, 17, 5], np.int32)   # ragged, straddling
        got = np.asarray(paged_attention(
            jax.numpy.asarray(q), jax.numpy.asarray(k_pages),
            jax.numpy.asarray(v_pages), jax.numpy.asarray(table),
            jax.numpy.asarray(lens)))
        k = k_pages[table].reshape(b, P * ps, g, dh)
        v = v_pages[table].reshape(b, P * ps, g, dh)
        want = self._reference(q, k, v, lens)
        np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-6)

    def test_kernel_composition_matches_einsum_path(self):
        """use_kernel=True gathers the same pages and runs the GQA
        decode kernel — same numbers (interpret mode on CPU)."""
        from paddle_tpu.ops.pallas_decode import paged_attention
        rng = np.random.RandomState(1)
        b, h, g, dh, ps, npages, P = 2, 4, 2, 8, 4, 8, 4
        k_pages = jax.numpy.asarray(
            rng.randn(npages, ps, g, dh).astype(np.float32))
        v_pages = jax.numpy.asarray(
            rng.randn(npages, ps, g, dh).astype(np.float32))
        q = jax.numpy.asarray(rng.randn(b, h, dh).astype(np.float32))
        table = jax.numpy.asarray(
            np.array([[1, 4, 2, 0], [3, 5, 0, 0]], np.int32))
        lens = jax.numpy.asarray(np.array([10, 7], np.int32))
        ein = paged_attention(q, k_pages, v_pages, table, lens)
        ker = paged_attention(q, k_pages, v_pages, table, lens,
                              use_kernel=True, interpret=True)
        np.testing.assert_allclose(np.asarray(ker), np.asarray(ein),
                                   rtol=2e-4, atol=2e-5)

    def test_decode_attention_per_row_lens(self):
        """The dense-layout kernel now takes per-row kv lengths: each
        row must mask at ITS length (scalar path unchanged)."""
        from paddle_tpu.ops.pallas_decode import decode_attention
        rng = np.random.RandomState(2)
        b, h, g, dh, T = 3, 4, 2, 8, 16
        q = jax.numpy.asarray(rng.randn(b, h, dh).astype(np.float32))
        kc = jax.numpy.asarray(
            rng.randn(b, g, dh, T).astype(np.float32))
        vc = jax.numpy.asarray(
            rng.randn(b, g, dh, T).astype(np.float32))
        lens = np.array([5, 16, 11], np.int32)
        got = np.asarray(decode_attention(
            q, kc, vc, jax.numpy.asarray(lens), interpret=True))
        for i, ln in enumerate(lens):
            one = np.asarray(decode_attention(
                q[i:i + 1], kc[i:i + 1], vc[i:i + 1], int(ln),
                interpret=True))
            np.testing.assert_allclose(got[i:i + 1], one,
                                       rtol=2e-5, atol=2e-6)


class TestPagePool:
    def test_alloc_free_accounting(self):
        pool = PagePool(8)              # 7 usable, page 0 reserved
        assert pool.usable == 7
        pages = [pool.alloc() for _ in range(7)]
        assert 0 not in pages           # the null page is never issued
        assert pool.alloc() is None     # exhausted, not an exception
        assert pool.accounting()["leaked"] == 0
        pool.free(pages[:3])
        assert pool.free_pages == 3 and pool.used_pages == 4
        assert pool.high_water == 7
        pool.free(pages[3:])
        assert pool.accounting() == {
            "total_usable": 7, "free": 7, "allocated": 0, "leaked": 0,
            "high_water": 7, }

    def test_double_free_is_loud(self):
        pool = PagePool(4)
        p = pool.alloc()
        pool.free([p])
        with pytest.raises(ValueError, match="double free|foreign"):
            pool.free([p])
        with pytest.raises(ValueError):
            pool.free([99])


class TestTokenIdentity:
    """THE acceptance test: greedy paged decode == greedy dense decode,
    token for token, on ragged batches whose sequences straddle page
    boundaries — and the engine step compiles exactly once even though
    requests join and leave mid-flight."""

    def test_ragged_batch_token_identical(self):
        params = _model()
        dec = _decoder(params)
        rng = np.random.RandomState(0)
        # lengths 3..8 against page_size 4: sequences start mid-page,
        # end mid-page, and cross 1-3 page boundaries while growing
        prompts = _ragged(rng, 6, lo=3, hi=9)
        max_news = [int(rng.randint(4, 12)) for _ in prompts]
        want = _dense_rows(dec, prompts, max_news)

        eng = DecodeEngine(dec, num_slots=3, page_size=4,
                           max_seq_len=CFG["max_len"])
        # more requests than slots: joins happen mid-flight as earlier
        # sequences finish — continuous batching, not static batching
        reqs = [eng.submit(p, mn) for p, mn in zip(prompts, max_news)]
        eng.run(timeout=300)
        for i, r in enumerate(reqs):
            assert r.get(timeout=1) == [int(t) for t in want[i]], i
        acc = eng.page_accounting()
        assert acc["leaked"] == 0 and acc["free"] == acc["total_usable"]
        st = eng.stats()
        assert st["finished"] == len(prompts)
        assert st["tokens_out"] == sum(max_news)

    def test_gqa_token_identical(self):
        params = _model(seed=3, n_kv_heads=1)   # MQA: cache narrower
        dec = _decoder(params)
        rng = np.random.RandomState(1)
        prompts = _ragged(rng, 4, lo=3, hi=8)
        max_news = [6, 9, 5, 8]
        want = _dense_rows(dec, prompts, max_news)
        eng = DecodeEngine(dec, num_slots=4, page_size=4,
                           max_seq_len=CFG["max_len"])
        reqs = [eng.submit(p, mn) for p, mn in zip(prompts, max_news)]
        eng.run(timeout=300)
        for i, r in enumerate(reqs):
            assert r.get(timeout=1) == [int(t) for t in want[i]], i
        assert eng.page_accounting()["leaked"] == 0

    @pytest.mark.recompile_budget(max_compiles=8)
    def test_churn_causes_zero_recompiles(self):
        """THE shape-stability pin: with the engine warm, a storm of
        mid-flight joins, a cancellation, and a pool-pressure eviction
        cause ZERO XLA compilations — the continuous-batching loop
        never retraces (the fixed-shape slot-batch contract). The
        marker budget (8) is headroom for param-init/jit of the warmup
        phase, which legitimately compiles several shape families; the
        churn phase itself is held to an exact total of 0 by the inner
        watch."""
        from paddle_tpu.analysis.sanitizer import compile_watch
        from paddle_tpu.testing import FaultPlan
        params = _model()
        dec = _decoder(params)
        eng = DecodeEngine(dec, num_slots=2, page_size=4,
                           max_seq_len=20, num_pages=8)
        warm = eng.submit(np.zeros((3,), "int32"), 1)
        eng.run(timeout=120)                  # compiles the step once
        assert warm.get(timeout=1)
        r0 = eng.submit(np.zeros((4,), "int32"), 10)
        joined = []
        with compile_watch() as watch:
            with FaultPlan.decode_script(eng, {
                    2: lambda: joined.append(
                        eng.submit(np.ones((6,), "int32"), 9)),
                    4: lambda: joined.append(
                        eng.submit(np.full((5,), 2, "int32"), 8)),
                    7: lambda: joined[0].cancel()}) as script:
                eng.run(timeout=300)
            assert script["fired"] == [2, 4, 7]
        assert watch.total == 0, (
            f"join/evict/cancel churn recompiled: {watch.per_function}")
        assert len(r0.get(timeout=1)) == 10
        assert joined[0].state == "cancelled"
        assert len(joined[1].get(timeout=1)) == 8
        assert eng.page_accounting()["leaked"] == 0

    def test_eos_frees_slot_early(self):
        """A request that hits its eos mid-flight finishes, frees its
        pages, and its tokens still match the dense path's trim."""
        params = _model()
        dec = _decoder(params)
        prompt = np.zeros((2,), "int32")
        dense = dec.generate(prompt[None, :], max_len=14)[0]
        eos = dense[1] if len(set(dense)) > 1 else dense[0]
        dense_trim = dec.generate(prompt[None, :], max_len=14,
                                  eos_id=int(eos))[0]
        eng = DecodeEngine(dec, num_slots=2, page_size=4,
                           max_seq_len=CFG["max_len"])
        req = eng.submit(prompt, 12, eos_id=int(eos))
        eng.run(timeout=120)
        assert req.get(timeout=1) == [int(t) for t in dense_trim]
        assert eng.page_accounting()["free"] == \
            eng.page_accounting()["total_usable"]


class TestScheduling:
    def test_preemption_under_tiny_pool_is_output_invariant(self):
        """A pool too small for both requests forces preemption: the
        youngest is evicted, its pages return, and on re-admission it
        replays prompt + generated tokens — BOTH outputs stay identical
        to undisturbed solo runs (greedy determinism survives
        eviction)."""
        params = _model()
        dec = _decoder(params)
        rng = np.random.RandomState(2)
        p1 = rng.randint(0, 40, (5,)).astype("int32")
        p2 = rng.randint(0, 40, (6,)).astype("int32")
        want1 = dec.generate(p1[None, :], max_len=5 + 12)[0]
        want2 = dec.generate(p2[None, :], max_len=6 + 12)[0]
        # each needs ceil(17/4)=5 / ceil(18/4)=5 pages; give the pool 7
        # usable so concurrent growth MUST preempt at some point
        eng = DecodeEngine(dec, num_slots=2, page_size=4,
                           max_seq_len=CFG["max_len"], num_pages=8)
        r1 = eng.submit(p1, 12)
        r2 = eng.submit(p2, 12)
        eng.run(timeout=300)
        assert r1.get(timeout=1) == [int(t) for t in want1]
        assert r2.get(timeout=1) == [int(t) for t in want2]
        st = eng.stats()
        assert st["preemptions"] >= 1, \
            "pool was sized to force at least one preemption"
        assert (r1.evictions + r2.evictions) == st["preemptions"]
        assert eng.page_accounting()["leaked"] == 0

    def test_admission_rejects_never_satisfiable(self):
        params = _model()
        eng = DecodeEngine(_decoder(params), num_slots=2, page_size=4,
                           max_seq_len=16)
        with pytest.raises(Rejected) as ei:
            eng.submit(np.zeros((8,), "int32"), 20)   # 28 > 16
        assert ei.value.reason == "kv_capacity"
        # pool smaller than the sequence cap: page check also rejects
        eng2 = DecodeEngine(_decoder(params), num_slots=2, page_size=4,
                            max_seq_len=16, num_pages=3)
        with pytest.raises(Rejected) as ei2:
            eng2.submit(np.zeros((8,), "int32"), 6)   # 4 pages > 2
        assert ei2.value.reason == "kv_capacity"

    def test_wait_queue_bound(self):
        params = _model()
        eng = DecodeEngine(_decoder(params), num_slots=1, page_size=4,
                           max_seq_len=16, max_waiting=2)
        reqs = [eng.submit(np.zeros((3,), "int32"), 2)
                for _ in range(2)]
        with pytest.raises(Rejected) as ei:
            eng.submit(np.zeros((3,), "int32"), 2)
        assert ei.value.reason == "queue_full"
        assert ei.value.retry_after > 0
        eng.run(timeout=120)
        for r in reqs:
            assert len(r.get(timeout=1)) == 2

    def test_page_aware_admission_head_waits_for_pages(self):
        """A free SLOT is not enough: the queue head only joins when
        the pool can reach its first new token — admission is scheduled
        by free KV pages, not queue depth."""
        params = _model()
        dec = _decoder(params)
        eng = DecodeEngine(dec, num_slots=2, page_size=4,
                           max_seq_len=16, num_pages=5)  # 4 usable
        big = eng.submit(np.zeros((8,), "int32"), 4)     # 3 pages total
        # pages allocate lazily: march until big actually holds 3 of
        # the 4 usable pages (it is still mid-generation then)
        for _ in range(40):
            eng.step()
            if eng.page_accounting()["free"] == 1:
                break
        assert eng.page_accounting()["free"] == 1
        assert big.state == "running"
        rival = eng.submit(np.zeros((8,), "int32"), 4)
        eng.step()
        # a slot is FREE, but the head needs ceil(9/4)=3 pages and only
        # 1 is — admission waits on pages, not on queue depth
        assert eng.stats()["active_slots"] == 1
        assert eng.stats()["waiting"] == 1
        eng.run(timeout=300)
        assert len(big.get(timeout=1)) == 4
        assert len(rival.get(timeout=1)) == 4
        assert eng.page_accounting()["leaked"] == 0


class TestBenchSmoke:
    """The CPU smoke slice of the decode_continuous_* bench rows: the
    same driver code bench.py runs on TPU, at toy shape, so a harness
    regression (row stops producing tokens / latency fields vanish)
    surfaces in tier-1 rather than in the next driver capture."""

    def test_decode_continuous_row_smoke(self):
        import bench
        row = bench.bench_decode_continuous(
            num_slots=4, n_requests=6, page_size=4, d_model=16,
            n_layers=2, n_heads=2, vocab_size=40, max_len=32,
            prompt_lens=(3, 8), new_tokens=(4, 10), seed=0)
        assert row["new_tokens"] == row["tokens_out"] > 0
        assert row["tokens_per_sec"] > 0
        assert row["ms"] > 0                     # per-token p50
        assert row["p99_ms"] >= row["ms"]
        assert 0 < row["slot_utilization"] <= 1
        assert row["kv_page_high_water"] > 0
        assert row["preemptions"] == 0
        assert row["roofline_frac"] > 0
