"""Elastic runtime tests — coordinator task dispatch/timeout/snapshot
(go/master service_internal_test parity) and full-state checkpoint/resume
(kill-a-host test of SURVEY.md §7 stage 8)."""

import os
import threading
import time

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.trainer.checkpoint import CheckpointManager
from paddle_tpu.trainer.coordinator import (Coordinator, CoordinatorServer,
                                            FileStore, InMemStore, connect,
                                            task_reader)


class TestCoordinator:
    def test_dispatch_and_finish_turns_epoch(self):
        c = Coordinator(chunks=list(range(6)), chunks_per_task=2)
        seen = []
        for _ in range(3):
            t = c.get_task()
            seen.extend(t["chunks"])
            assert c.task_finished(t["task_id"])
        assert sorted(seen) == list(range(6))
        assert c.epoch == 1                  # all done -> next pass
        assert c.get_task() is not None      # epoch 1 re-serves tasks

    def test_timeout_requeues(self):
        c = Coordinator(chunks=[1, 2], chunks_per_task=1, timeout_s=0.05)
        t1 = c.get_task()
        t2 = c.get_task()
        assert c.get_task() is None
        time.sleep(0.08)                     # both time out
        t3 = c.get_task()
        assert t3 is not None                # re-served
        assert t3["task_id"] in (t1["task_id"], t2["task_id"])

    def test_timeout_drain_turns_epoch(self):
        # Regression: the last outstanding task dying by TIMEOUT (trainer
        # crash) must turn the pass over like task_failed does, or the
        # queue drains forever.
        c = Coordinator(chunks=[1], chunks_per_task=1, timeout_s=0.03,
                        failure_max=1)
        t = c.get_task()
        assert t is not None
        time.sleep(0.05)                     # times out -> dropped
        t2 = c.get_task()                    # triggers requeue scan
        assert c.epoch == 1                  # pass turned over
        assert t2 is not None                # epoch-1 queue re-serves

    def test_task_reader_over_rpc(self):
        # task_reader must work against the RPC proxy, where `epoch` is a
        # callable, not an attribute.
        c = Coordinator(chunks=["a", "b"], chunks_per_task=1)
        srv = CoordinatorServer(c).start()
        try:
            client = connect("127.0.0.1", srv.port)
            recs = list(task_reader(client, lambda ch: [ch + "0"])())
            assert sorted(recs) == ["a0", "b0"]
        finally:
            srv.stop()

    def test_failure_max_drops_task(self):
        c = Coordinator(chunks=[1], chunks_per_task=1, failure_max=2)
        t = c.get_task()
        assert c.task_failed(t["task_id"])   # 1st failure: re-queued
        t = c.get_task()
        assert c.task_failed(t["task_id"])   # 2nd: dropped, epoch turns
        assert c.epoch == 1

    def test_snapshot_recover(self, tmp_path):
        store = FileStore(str(tmp_path))
        c1 = Coordinator(chunks=list(range(4)), chunks_per_task=1,
                         store=store)
        t = c1.get_task()                    # leaves one task pending
        # master "crashes"; new master recovers from the store
        c2 = Coordinator(chunks=[], store=store)
        served = []
        while True:
            t2 = c2.get_task(c2.epoch if not served else epoch0)
            if t2 is None:
                break
            if not served:
                epoch0 = c2.epoch
            served.append(t2["task_id"])
            c2.task_finished(t2["task_id"])
        # the pending task was re-served by the recovered master
        assert t["task_id"] in served
        assert len(served) == 4

    def test_save_election(self):
        c = Coordinator(chunks=[1])
        grants = [c.request_save_model(0) for _ in range(5)]
        assert grants.count(True) == 1
        assert c.request_save_model(1) is True

    def test_save_election_regrants_current_trainer(self):
        # RequestSaveModel parity: the CURRENT saving trainer re-asking
        # is re-granted (service.go TrainerID == savingTrainer); others
        # stay denied — per epoch and per window alike
        c = Coordinator(chunks=[1])
        assert c.request_save_model(0, 30.0, "tr-A") is True
        assert c.request_save_model(0, 30.0, "tr-A") is True
        assert c.request_save_model(0, 30.0, "tr-B") is False
        c2 = Coordinator(chunks=[1])
        assert c2.request_save_model(None, 30.0, "tr-A") is True
        assert c2.request_save_model(None, 30.0, "tr-A") is True
        assert c2.request_save_model(None, 30.0, "tr-B") is False
        # anonymous callers are never re-granted within the window
        c3 = Coordinator(chunks=[1])
        assert c3.request_save_model() is True
        assert c3.request_save_model() is False

    def test_public_status_properties(self, tmp_path):
        store = FileStore(str(tmp_path))
        c1 = Coordinator(chunks=list(range(4)), chunks_per_task=2,
                         store=store)
        assert c1.chunks == tuple(range(4))
        assert c1.chunks_per_task == 2
        assert c1.recovered is False
        # a coordinator recovering from the snapshot reports it and
        # serves the RECOVERED chunk list, not its constructor args
        c2 = Coordinator(chunks=[], store=store)
        assert c2.recovered is True
        assert c2.chunks == tuple(range(4))
        assert c2.chunks_per_task == 2

    def test_task_reader_skips_bad_chunk(self):
        c = Coordinator(chunks=["a", "bad", "b"], chunks_per_task=1,
                        failure_max=2)

        def chunk_reader(chunk):
            if chunk == "bad":
                raise IOError("corrupt chunk")
            yield from [f"{chunk}{i}" for i in range(2)]

        recs = list(task_reader(c, chunk_reader)())
        assert sorted(recs) == ["a0", "a1", "b0", "b1"]
        assert c.num_dropped() in (0, 1)     # dropped or epoch turned

    def test_rpc_server(self):
        c = Coordinator(chunks=list(range(4)), chunks_per_task=2)
        srv = CoordinatorServer(c).start()
        try:
            client = connect("127.0.0.1", srv.port)
            t = client.get_task()
            assert t is not None and len(t["chunks"]) == 2
            assert client.task_finished(t["task_id"])
            t2 = client.get_task()
            assert client.task_failed(t2["task_id"])
        finally:
            srv.stop()


def _cpu_env():
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"
    return env


def _trainer(seed=0):
    from paddle_tpu.core import registry
    registry.reset_name_counters()
    paddle.init(use_tpu=False, seed=seed)
    img = paddle.layer.data("x", paddle.data_type.dense_vector(16))
    out = paddle.layer.fc(img, size=4, act=paddle.activation.Softmax(),
                          name="out")
    lbl = paddle.layer.data("y", paddle.data_type.integer_value(4))
    cost = paddle.layer.classification_cost(out, lbl, name="cost")
    params = paddle.create_parameters(paddle.Topology(cost))
    return paddle.SGD(cost=cost, parameters=params,
                      update_equation=paddle.optimizer.Adam(
                          learning_rate=1e-2))


class TestFileStoreTornFiles:
    """FileStore durability contract: atomic framed writes, and a torn
    or bit-rotted snapshot degrades to 'absent' (warn + None) instead
    of killing the recovering coordinator."""

    def test_put_get_roundtrip(self, tmp_path):
        store = FileStore(str(tmp_path))
        store.put("coordinator/state", b"hello \x00 world")
        assert store.get("coordinator/state") == b"hello \x00 world"
        assert store.get("missing") is None
        # atomic: no .tmp litter after a successful put
        assert not [p for p in os.listdir(tmp_path)
                    if p.endswith(".tmp")]

    def test_torn_file_returns_none_with_warning(self, tmp_path):
        store = FileStore(str(tmp_path))
        store.put("k", b"x" * 256)
        path = store._path("k")
        blob = open(path, "rb").read()
        with open(path, "wb") as f:          # crash mid-write tear
            f.write(blob[:len(blob) // 2])
        with pytest.warns(UserWarning, match="torn"):
            assert store.get("k") is None

    def test_corrupt_payload_returns_none_with_warning(self, tmp_path):
        store = FileStore(str(tmp_path))
        store.put("k", b"y" * 64)
        path = store._path("k")
        blob = bytearray(open(path, "rb").read())
        blob[-1] ^= 0xFF                     # bit rot inside the value
        open(path, "wb").write(bytes(blob))
        with pytest.warns(UserWarning, match="torn or corrupt"):
            assert store.get("k") is None

    def test_legacy_unframed_value_passes_through(self, tmp_path):
        store = FileStore(str(tmp_path))
        with open(store._path("legacy"), "wb") as f:
            f.write(b'{"old": "snapshot"}')  # pre-framing writer
        assert store.get("legacy") == b'{"old": "snapshot"}'

    def test_chunked_rpcstore_roundtrip_and_torn_chunk(self):
        """RpcStore chunked path: a multi-chunk value survives the
        round trip; a torn chunk set (lost chunk, crc mismatch) reads
        as absent with a warning — FileStore torn-frame parity over
        the RPC plane."""
        from paddle_tpu.trainer.coordinator import KVStoreServer, RpcStore
        backing = InMemStore()
        srv = KVStoreServer(backing, max_value_bytes=256 * 1024).start()
        try:
            store = RpcStore("127.0.0.1", srv.port, chunk_bytes=8 * 1024)
            big = bytes(np.random.default_rng(0).integers(
                0, 256, size=50 * 1024, dtype=np.uint8))
            store.put("embed/snap", big)
            assert store.get("embed/snap") == big
            assert store.get("embed/snap.chunk.0") is not None  # chunks real
            store.put("tiny", b"t")              # small values stay direct
            assert store.get("tiny") == b"t"
            # torn: one chunk vanishes server-side (partial overwrite)
            backing.put("embed/snap.chunk.2", b"")
            with pytest.warns(UserWarning, match="torn or corrupt"):
                assert store.get("embed/snap") is None
            backing._data.pop("embed/snap.chunk.2")
            with pytest.warns(UserWarning, match="missing"):
                assert store.get("embed/snap") is None
            # server size guard: oversized single value is refused
            import xmlrpc.client
            with pytest.raises(xmlrpc.client.Fault):
                store._rpc_put("bomb", b"\x00" * (300 * 1024))
        finally:
            srv.stop()

    def test_coordinator_recovers_fresh_from_torn_snapshot(self,
                                                           tmp_path):
        store = FileStore(str(tmp_path))
        c1 = Coordinator(chunks=list(range(4)), chunks_per_task=1,
                         store=store)
        del c1
        path = store._path("coordinator/state")
        blob = open(path, "rb").read()
        with open(path, "wb") as f:
            f.write(blob[:len(blob) - 7])    # torn framed snapshot
        with pytest.warns(UserWarning):
            c2 = Coordinator(chunks=list(range(2)), chunks_per_task=1,
                             store=store)
        # degraded to a FRESH partition of the constructor chunks
        assert c2.recovered is False
        assert c2.chunks == (0, 1)

    def test_coordinator_recovers_fresh_from_legacy_garbage(self,
                                                            tmp_path):
        store = FileStore(str(tmp_path))
        # a legacy unframed snapshot torn mid-JSON reaches json.loads —
        # the recovery path itself must tolerate it
        with open(store._path("coordinator/state"), "wb") as f:
            f.write(b'{"epoch": 0, "todo": [{"task')
        with pytest.warns(UserWarning, match="torn or corrupt"):
            c = Coordinator(chunks=[9], chunks_per_task=1, store=store)
        assert c.recovered is False
        assert c.chunks == (9,)


def _reader(seed):
    rng = np.random.RandomState(seed)
    feats = rng.randn(32, 16).astype("float32")
    labels = rng.randint(0, 4, 32)

    def reader():
        yield [(feats[i], int(labels[i])) for i in range(32)]
    return reader


class TestKillResume:
    """SURVEY §7.8 exit criterion: a REAL subprocess trainer SIGKILLed
    mid-pass; a replacement restores the full-state checkpoint, the
    coordinator re-queues the dead trainer's task on timeout, and the run
    completes within the pass it died in."""

    def test_sigkill_mid_pass_resumes(self, tmp_path):
        import signal
        import subprocess
        import sys as _sys

        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        worker = os.path.join(repo, "tests", "elastic_worker.py")
        ckpt = str(tmp_path / "ckpt")
        coord = Coordinator(chunks=list(range(6)), chunks_per_task=1,
                            timeout_s=1.5, failure_max=10)
        srv = CoordinatorServer(coord).start()
        env = dict(os.environ)
        env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
        env["JAX_PLATFORMS"] = "cpu"
        try:
            # slow worker: ~0.8s per chunk; kill it mid-pass
            p1 = subprocess.Popen(
                [_sys.executable, worker, str(srv.port), ckpt, "0.2"],
                env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE)
            deadline = time.time() + 60
            while coord.epoch == 0 and not coord._done and \
                    time.time() < deadline:
                time.sleep(0.1)          # wait until it finished >=1 task
            assert time.time() < deadline, "worker never started tasks"
            p1.send_signal(signal.SIGKILL)
            p1.wait()
            assert coord.epoch == 0      # died mid-pass

            # replacement worker: restores checkpoint, finishes the run
            p2 = subprocess.Popen(
                [_sys.executable, worker, str(srv.port), ckpt, "0.0"],
                env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                text=True)
            out, err = p2.communicate(timeout=180)
            assert p2.returncode == 0, err[-2000:]
            assert "WORKER DONE" in out
            assert coord.epoch >= 2      # both passes completed
            # the replacement resumed from the kill-point checkpoint, not
            # from scratch: its total step count exceeds what a fresh run
            # of the remaining work alone would reach
            from paddle_tpu.trainer.checkpoint import CheckpointManager
            mgr = CheckpointManager(ckpt)
            assert mgr.latest_step() is not None
        finally:
            srv.stop()


class TestCheckpointResume:
    def test_full_state_roundtrip(self, tmp_path):
        mgr = CheckpointManager(str(tmp_path), keep=2)
        tr = _trainer()
        tr.train(_reader(0), num_passes=2)
        tr.save_checkpoint(mgr, meta={"pass": 2})

        tr2 = _trainer()
        assert tr2.restore_checkpoint(mgr)
        for k, v in tr.parameters.raw.items():
            np.testing.assert_array_equal(np.asarray(v),
                                          np.asarray(tr2.parameters.raw[k]))
        # optimizer slots (Adam moments) restored too
        assert int(tr2.opt_state["step"]) == int(tr.opt_state["step"])

    def test_resume_matches_uninterrupted(self, tmp_path):
        # uninterrupted: 4 passes
        tr_full = _trainer()
        tr_full.train(_reader(0), num_passes=4)

        # interrupted: 2 passes, checkpoint, "crash", restore, 2 more
        mgr = CheckpointManager(str(tmp_path))
        tr_a = _trainer()
        tr_a.train(_reader(0), num_passes=2)
        tr_a.save_checkpoint(mgr)
        tr_b = _trainer()
        assert tr_b.restore_checkpoint(mgr)
        tr_b.train(_reader(0), num_passes=2)

        for k in tr_full.parameters.raw:
            np.testing.assert_allclose(
                np.asarray(tr_full.parameters.raw[k]),
                np.asarray(tr_b.parameters.raw[k]), rtol=1e-5, atol=1e-6)

    def test_async_write_and_corruption_fallback(self, tmp_path):
        mgr = CheckpointManager(str(tmp_path), keep=3, async_write=True)
        tr = _trainer()
        tr.train(_reader(0), num_passes=1)
        tr.save_checkpoint(mgr)
        tr.train(_reader(0), num_passes=1)
        tr.save_checkpoint(mgr)
        mgr.wait()
        steps = mgr.all_steps()
        assert len(steps) == 2
        # corrupt the newest -> restore falls back to the previous one
        import os
        newest = os.path.join(str(tmp_path), f"ckpt-{steps[-1]:010d}",
                              "state.npz")
        with open(newest, "wb") as f:
            f.write(b"garbage")
        assert mgr.latest_step() == steps[0]
        tr2 = _trainer()
        assert tr2.restore_checkpoint(mgr)

    def test_keep_last_n(self, tmp_path):
        mgr = CheckpointManager(str(tmp_path), keep=2)
        tr = _trainer()
        for i in range(4):
            tr.train(_reader(0), num_passes=1)
            tr.save_checkpoint(mgr)
        mgr.wait()
        assert len(mgr.all_steps()) == 2

    def test_save_does_not_block_steps(self, tmp_path, monkeypatch):
        """The write must run OFF the step path: hold the writer thread
        open and prove (a) save() returns immediately, (b) training
        steps complete while the write is still in flight."""
        import threading
        import paddle_tpu.trainer.checkpoint as ck

        gate = threading.Event()
        real_savez = ck.np.savez

        def slow_savez(f, **kw):
            real_savez(f, **kw)
            gate.wait(timeout=60)  # pin the writer thread open

        monkeypatch.setattr(ck.np, "savez", slow_savez)
        mgr = CheckpointManager(str(tmp_path), keep=3)
        tr = _trainer()
        tr.train(_reader(0), num_passes=1)
        tr.save_checkpoint(mgr)
        # save returned while the writer is still held open
        assert mgr._writer is not None and mgr._writer.is_alive()
        # a full training pass completes with the write in flight
        tr.train(_reader(0), num_passes=1)
        assert mgr._writer.is_alive()
        gate.set()
        mgr.wait()
        assert mgr.latest_step() is not None

    def test_background_write_failure_surfaces(self, tmp_path,
                                               monkeypatch):
        """An async write that fails (ENOSPC, permissions) must raise at
        wait()/next-save — not vanish into the writer thread."""
        import paddle_tpu.trainer.checkpoint as ck

        def boom(f, **kw):
            raise OSError(28, "No space left on device")

        monkeypatch.setattr(ck.np, "savez", boom)
        mgr = CheckpointManager(str(tmp_path))
        mgr.save(1, {"w": np.ones((2, 2), np.float32)})
        with pytest.raises(RuntimeError, match="checkpoint write failed"):
            mgr.wait()
        # the manager recovers: the error does not re-raise forever
        mgr.wait()

    def test_kill_during_write_leaves_no_torn_checkpoint(self, tmp_path):
        """SIGKILL the process while a (large) checkpoint write is in
        flight: the newest INTACT checkpoint must be the previous one —
        atomic rename means a torn artifact can never be selected."""
        import signal
        import subprocess
        import sys
        import time

        code = (
            "import sys, numpy as np\n"
            "from paddle_tpu.trainer.checkpoint import CheckpointManager\n"
            "mgr = CheckpointManager(sys.argv[1], keep=5,"
            " async_write=False)\n"
            "mgr.save(1, {'w': np.ones((8, 8), np.float32)})\n"
            "print('SAVED1', flush=True)\n"
            "big = {'w': np.random.RandomState(0).randn(96, 1 << 20)"
            ".astype(np.float32)}\n"
            "mgr.save(2, big)\n"
            "print('SAVED2', flush=True)\n")
        p = subprocess.Popen([sys.executable, "-c", code, str(tmp_path)],
                             stdout=subprocess.PIPE, text=True,
                             env=_cpu_env())
        assert p.stdout.readline().strip() == "SAVED1"
        # kill the moment the step-2 write directory appears
        tmp_dir = tmp_path / "ckpt-0000000002.tmp"
        deadline = time.time() + 120
        while time.time() < deadline and not tmp_dir.exists() \
                and p.poll() is None:
            time.sleep(0.001)
        p.send_signal(signal.SIGKILL)
        p.wait(timeout=60)

        mgr = CheckpointManager(str(tmp_path))
        latest = mgr.latest_step()
        # the kill races the write's completion: either the old intact
        # checkpoint or a FULLY completed new one — never torn, never None
        assert latest in (1, 2)
        step, tree = mgr.restore(latest)
        assert step == latest
        w = tree["params"]["w"]
        assert np.isfinite(np.asarray(w)).all()
