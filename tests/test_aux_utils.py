"""Aux-surface parity tests: pruning hook (ParameterUpdaterHook.cpp),
detection mAP evaluator, Ploter (v2/plot), image transforms (v2/image.py),
and glog-style logging (paddle/utils/Logging.h)."""

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import evaluator as E
from paddle_tpu.core.sequence import pack_sequences


class TestPruningHook:
    def test_static_pruning_masks_updates(self):
        import jax.numpy as jnp
        from paddle_tpu.attr import HookAttribute, Param
        from paddle_tpu.optimizer.optimizers import Momentum

        x = paddle.layer.data("x", paddle.data_type.dense_vector(8))
        out = paddle.layer.fc(
            x, size=6, act=paddle.activation.Tanh(),
            param_attr=Param(name="pruned_w",
                             update_hooks=HookAttribute("pruning",
                                                        sparsity_ratio=0.5)),
            bias_attr=False)
        cost = paddle.layer.sum_cost(out)
        topo = paddle.Topology(cost)
        params = paddle.create_parameters(topo)
        opt = Momentum(learning_rate=0.1, momentum=0.9).bind(topo.param_specs)
        state = opt.init_state(params.raw)
        mask = np.asarray(state["slots"]["pruned_w"]["_mask"])
        assert 0.3 <= mask.mean() <= 0.7      # ~half pruned
        grads = {"pruned_w": jnp.ones_like(params.raw["pruned_w"])}
        new_params, new_state = opt.update(params.raw, grads, state, 4)
        w = np.asarray(new_params["pruned_w"])
        assert np.all(w[mask == 0] == 0.0)    # pruned slots stay dead
        assert np.any(w[mask == 1] != 0.0)
        # mask persists in the new state
        np.testing.assert_array_equal(
            np.asarray(new_state["slots"]["pruned_w"]["_mask"]), mask)


    def test_update_hooks_survive_serialization(self):
        from paddle_tpu.attr import HookAttribute, Param
        x = paddle.layer.data("x", paddle.data_type.dense_vector(4))
        out = paddle.layer.fc(
            x, size=3, bias_attr=False,
            param_attr=Param(name="w",
                             update_hooks=HookAttribute("pruning", 0.7)))
        topo = paddle.Topology(out)
        topo2 = paddle.Topology.deserialize(topo.serialize())
        h = topo2.param_specs["w"].attr.update_hooks[0]
        assert h.type == "pruning" and h.sparsity_ratio == 0.7

    def test_pruning_rejects_sparse_params(self):
        from paddle_tpu.attr import HookAttribute, Param
        from paddle_tpu.optimizer.optimizers import Momentum
        ids = paddle.layer.data("ids", paddle.data_type.integer_value(100))
        emb = paddle.layer.embedding(
            ids, size=8,
            param_attr=Param(name="tbl", sparse_update=True,
                             update_hooks=HookAttribute("pruning", 0.5)))
        cost = paddle.layer.sum_cost(emb)
        topo = paddle.Topology(cost)
        params = paddle.create_parameters(topo)
        opt = Momentum(learning_rate=0.1).bind(topo.param_specs,
                                               sparse_params=["tbl"])
        with pytest.raises(ValueError, match="pruning hook"):
            opt.init_state(params.raw)


class _FakeLayer:
    def __init__(self, name):
        self.name = name


class TestDetectionMAP:
    def test_perfect_predictions_map_1(self):
        ev = E.detection_map(_FakeLayer("det"), _FakeLayer("gt"))
        # one image, two gt boxes of classes 1 and 2, detections match
        det = np.zeros((1, 2, 7), np.float32)
        det[0, 0] = [0, 1, 0.9, 0.1, 0.1, 0.4, 0.4]
        det[0, 1] = [0, 2, 0.8, 0.5, 0.5, 0.9, 0.9]
        gt = pack_sequences([np.array([[1, .1, .1, .4, .4, 0],
                                       [2, .5, .5, .9, .9, 0]], np.float32)])
        ev.eval_batch([det.reshape(1, -1), gt], 1)
        assert ev.result()["detection_map"] == pytest.approx(1.0)

    def test_wrong_boxes_map_0(self):
        ev = E.detection_map(_FakeLayer("det"), _FakeLayer("gt"))
        det = np.zeros((1, 1, 7), np.float32)
        det[0, 0] = [0, 1, 0.9, 0.6, 0.6, 0.9, 0.9]   # misses the gt
        gt = pack_sequences([np.array([[1, .1, .1, .3, .3, 0]], np.float32)])
        ev.eval_batch([det.reshape(1, -1), gt], 1)
        assert ev.result()["detection_map"] == pytest.approx(0.0)

    def test_duplicate_detection_is_fp(self):
        ev = E.detection_map(_FakeLayer("det"), _FakeLayer("gt"))
        det = np.zeros((1, 2, 7), np.float32)
        det[0, 0] = [0, 1, 0.9, 0.1, 0.1, 0.4, 0.4]
        det[0, 1] = [0, 1, 0.8, 0.1, 0.1, 0.4, 0.4]   # duplicate
        gt = pack_sequences([np.array([[1, .1, .1, .4, .4, 0]], np.float32)])
        ev.eval_batch([det.reshape(1, -1), gt], 1)
        m = ev.result()["detection_map"]
        assert 0.9 < m <= 1.0                  # AP still ~1 (dup ranks after)


class TestPloter:
    def test_collects_and_resets(self):
        from paddle_tpu.plot import Ploter
        p = Ploter("train", "test")
        p.append("train", 0, 1.0)
        p.append("train", 1, 0.5)
        assert p.data("train").value == [1.0, 0.5]
        p.plot()                               # headless-safe
        p.reset()
        assert p.data("train").value == []


class TestImage:
    def test_resize_short_and_center_crop(self):
        from paddle_tpu import image as img
        im = np.arange(20 * 10 * 3, dtype=np.uint8).reshape(20, 10, 3)
        r = img.resize_short(im, 8)
        assert min(r.shape[:2]) == 8 and r.shape[0] == 16
        c = img.center_crop(r, 8)
        assert c.shape[:2] == (8, 8)

    def test_simple_transform_chw(self):
        from paddle_tpu import image as img
        im = np.random.RandomState(0).randint(
            0, 255, (32, 24, 3)).astype(np.uint8)
        out = img.simple_transform(im, 16, 12, is_train=False,
                                   mean=[1.0, 2.0, 3.0])
        assert out.shape == (3, 12, 12)
        assert out.dtype == np.float32

    def test_flip(self):
        from paddle_tpu import image as img
        im = np.arange(12, dtype=np.float32).reshape(2, 2, 3)
        np.testing.assert_allclose(img.left_right_flip(im)[:, 0], im[:, 1])

    def test_batch_images_from_tar(self, tmp_path):
        """python/paddle/v2/image.py:33 parity: tar -> pickled shards of
        num_per_batch samples + a meta file listing shard paths."""
        import pickle
        import tarfile

        from paddle_tpu import image as img
        tar_path = str(tmp_path / "imgs.tar")
        with tarfile.open(tar_path, "w") as tf:
            for i in range(5):
                p = tmp_path / f"im{i}.bin"
                p.write_bytes(bytes([i]) * 8)
                tf.add(str(p), arcname=f"im{i}.bin")
        img2label = {f"im{i}.bin": i % 2 for i in range(5)}

        meta = img.batch_images_from_tar(tar_path, "train", img2label,
                                         num_per_batch=2)
        shards = [l.strip() for l in open(meta) if l.strip()]
        assert len(shards) == 3  # 2+2+1
        seen = {}
        for s in shards:
            with open(s, "rb") as f:
                d = pickle.load(f)
            assert len(d["label"]) == len(d["data"]) <= 2
            for lbl, raw in zip(d["label"], d["data"]):
                seen[raw[0]] = lbl
        assert seen == {i: i % 2 for i in range(5)}
        # idempotent: existing batch dir short-circuits
        assert img.batch_images_from_tar(tar_path, "train",
                                         img2label) == meta


class TestLogging:
    def test_glog_format_and_version(self, capsys):
        from paddle_tpu.utils import logging as plog
        lg = plog.get_logger()
        plog.set_min_log_level(0)
        lg.info("hello")
        err = capsys.readouterr().err
        assert "hello" in err and err.startswith("[I ")
        assert "paddle_tpu" in plog.version()
