"""Dynamic-engine equivalence: a recurrent_group built from step
primitives must compute EXACTLY what the fused sequence layer computes
when they share weights.

Reference discipline: paddle/gserver/tests/test_RecurrentGradientMachine
+ paired configs (sequence_rnn.conf vs sequence_layer_group.conf) assert
the hand-built group equals the fused machine. Here both versions live
in ONE topology sharing parameters by explicit name, so a single forward
compares them with zero tolerance games.
"""

import jax
import jax.numpy as jnp
import numpy as np

import paddle_tpu as paddle
from paddle_tpu.core.sequence import pack_sequences
from paddle_tpu.core.topology import Topology

L = paddle.layer


def _forward(outputs, feed, seed=0):
    topo = Topology(outputs)
    params = topo.init_params(jax.random.PRNGKey(seed))
    outs, _ = topo.forward(params, topo.init_state(), feed, mode="test",
                           rng=jax.random.PRNGKey(1))
    return outs, params


class TestGroupEquivalence:
    def test_simple_rnn_group_matches_fused(self):
        """tanh(x_t + h_{t-1} @ W + b): fused `recurrent` layer vs a
        recurrent_group of memory + fc + addto, sharing W and b."""
        rng = np.random.RandomState(0)
        d = 6
        rows = [rng.randn(4, d).astype(np.float32),
                rng.randn(2, d).astype(np.float32)]
        x = L.data("x", paddle.data_type.dense_vector_sequence(d))
        feed = {"x": pack_sequences(rows)}

        fused = L.recurrent(
            x, act=paddle.activation.Tanh(),
            param_attr=paddle.attr.Param(name="shared_W"),
            bias_attr=paddle.attr.Param(name="shared_b"), name="fused_rnn")

        def step(inp):
            mem = L.memory(name="grp_h", size=d)
            rec = L.fc(mem, size=d, bias_attr=False, act=None,
                       param_attr=paddle.attr.Param(name="shared_W"))
            return L.addto([inp, rec], act=paddle.activation.Tanh(),
                           bias_attr=paddle.attr.Param(name="shared_b"),
                           name="grp_h")

        grouped = L.recurrent_group(step=step, input=x, name="rnn_grp")

        outs, _ = _forward([fused, grouped], feed)
        a = np.asarray(outs[fused.name].data)
        b = np.asarray(outs[grouped.name].data)
        np.testing.assert_allclose(a, b, rtol=1e-6, atol=1e-6)

    def test_simple_rnn_group_matches_fused_gradients(self):
        """The backward halves must agree too (the group's scan-of-steps
        vs the fused scan)."""
        rng = np.random.RandomState(1)
        d = 5
        rows = [rng.randn(3, d).astype(np.float32),
                rng.randn(4, d).astype(np.float32)]
        x = L.data("x", paddle.data_type.dense_vector_sequence(d))
        feed = {"x": pack_sequences(rows)}

        fused = L.recurrent(
            x, act=paddle.activation.Tanh(),
            param_attr=paddle.attr.Param(name="eqW"),
            bias_attr=paddle.attr.Param(name="eqb"), name="f_rnn")

        def step(inp):
            mem = L.memory(name="g_h", size=d)
            rec = L.fc(mem, size=d, bias_attr=False, act=None,
                       param_attr=paddle.attr.Param(name="eqW"))
            return L.addto([inp, rec], act=paddle.activation.Tanh(),
                           bias_attr=paddle.attr.Param(name="eqb"),
                           name="g_h")

        grouped = L.recurrent_group(step=step, input=x, name="g_grp")

        topo = Topology([fused, grouped])
        params = topo.init_params(jax.random.PRNGKey(2))
        state = topo.init_state()

        def loss_of(name):
            def f(p):
                outs, _ = topo.forward(p, state, feed, mode="test",
                                       rng=jax.random.PRNGKey(3))
                v = outs[name]
                return jnp.sum(v.data ** 2)
            return f

        gf = jax.grad(loss_of(fused.name))(params)
        gg = jax.grad(loss_of(grouped.name))(params)
        np.testing.assert_allclose(np.asarray(gf["eqW"]),
                                   np.asarray(gg["eqW"]),
                                   rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(np.asarray(gf["eqb"]),
                                   np.asarray(gg["eqb"]),
                                   rtol=1e-5, atol=1e-6)


class TestGruGroupEquivalence:
    def test_gru_step_group_matches_grumemory(self):
        """A recurrent_group of gru_step must equal the fused grumemory
        when they share the [h, 3h] recurrent weight and gate bias
        (sequence_layer_group.conf discipline for GRU)."""
        rng = np.random.RandomState(3)
        h = 4
        rows = [rng.randn(3, 3 * h).astype(np.float32),
                rng.randn(5, 3 * h).astype(np.float32)]
        x3 = L.data("x3", paddle.data_type.dense_vector_sequence(3 * h))
        feed = {"x3": pack_sequences(rows)}

        fused = L.grumemory(
            x3, param_attr=paddle.attr.Param(name="gru_W"),
            bias_attr=paddle.attr.Param(name="gru_b"), name="f_gru")

        def step(inp):
            mem = L.memory(name="g_gru", size=h)
            return L.gru_step(inp, mem, size=h,
                              param_attr=paddle.attr.Param(name="gru_W"),
                              bias_attr=paddle.attr.Param(name="gru_b"),
                              name="g_gru")

        grouped = L.recurrent_group(step=step, input=x3, name="gru_grp")
        outs, _ = _forward([fused, grouped], feed, seed=4)
        np.testing.assert_allclose(np.asarray(outs[fused.name].data),
                                   np.asarray(outs[grouped.name].data),
                                   rtol=1e-6, atol=1e-6)


class TestGroupRemat:
    def test_remat_group_identical_grads(self):
        """recurrent_group(remat=True) must produce bit-identical loss and
        gradients — jax.checkpoint changes only what the backward stores."""
        from paddle_tpu.core import registry
        from paddle_tpu.core.sequence import pack_sequences
        from paddle_tpu.core.topology import Topology

        def build(remat):
            registry.reset_name_counters()
            paddle.init(use_tpu=False, seed=0)
            x = paddle.layer.data(
                "x", paddle.data_type.dense_vector_sequence(6))

            def step(xt):
                prev = paddle.layer.memory(name="h", size=8)
                return paddle.layer.fc(
                    paddle.layer.concat([xt, prev]), size=8,
                    act=paddle.activation.Tanh(), name="h")

            out = paddle.layer.recurrent_group(step, x, remat=remat,
                                               name="rg")
            pooled = paddle.layer.pooling(out, paddle.pooling.Sum())
            return Topology(paddle.layer.fc(pooled, size=1, name="o"))

        rng = np.random.RandomState(0)
        rows = [rng.randn(t, 6).astype("float32") for t in (3, 5)]
        feed = {"x": pack_sequences(rows)}

        results = []
        for remat in (False, True):
            topo = build(remat)
            params = topo.init_params(jax.random.PRNGKey(1))

            def loss(p):
                outs, _ = topo.forward(p, topo.init_state(), feed,
                                       mode="train",
                                       rng=jax.random.PRNGKey(2))
                return jnp.sum(outs["o"] ** 2)

            # ptlint: disable=R2(two intentionally different graphs — remat off/on — compiled once each)
            val, grads = jax.jit(jax.value_and_grad(loss))(params)
            results.append((float(val),
                            {k: np.asarray(v) for k, v in grads.items()}))

        (v0, g0), (v1, g1) = results
        assert v0 == v1
        for k in g0:
            np.testing.assert_array_equal(g0[k], g1[k], err_msg=k)
