"""Chaos tests — the fault-injection harness (paddle_tpu/testing/faults)
driven against the real training loop: checkpoint write faults, NaN
steps under the guarded train step, coordinator RPC drops/delays and
lease expiry, and a SIGKILL'd subprocess trainer auto-resuming
(docs/robustness.md)."""

import os
import time

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.testing.faults import FaultPlan
from paddle_tpu.trainer.checkpoint import CheckpointManager
from paddle_tpu.trainer.coordinator import (Coordinator, CoordinatorServer,
                                            RetryPolicy, call_with_retry,
                                            connect, task_reader)
from paddle_tpu.trainer.fault import FaultPolicy


def _trainer(seed=0):
    from paddle_tpu.core import registry
    registry.reset_name_counters()
    paddle.init(use_tpu=False, seed=seed)
    x = paddle.layer.data("x", paddle.data_type.dense_vector(16))
    out = paddle.layer.fc(x, size=4, act=paddle.activation.Softmax(),
                          name="out")
    y = paddle.layer.data("y", paddle.data_type.integer_value(4))
    cost = paddle.layer.classification_cost(out, y, name="cost")
    params = paddle.create_parameters(paddle.Topology(cost))
    return paddle.SGD(cost=cost, parameters=params,
                      update_equation=paddle.optimizer.Adam(
                          learning_rate=1e-2))


def _reader(n_batches=8, batch=16):
    rng = np.random.RandomState(3)
    feats = rng.randn(n_batches, batch, 16).astype("float32")
    labels = rng.randint(0, 4, (n_batches, batch))

    def reader():
        for b in range(n_batches):
            yield [(feats[b, i], int(labels[b, i])) for i in range(batch)]
    return reader


# ---------------------------------------------------------------- (a) disk

class TestCheckpointFaults:
    def test_enospc_surfaces_and_previous_survives(self, tmp_path):
        mgr = CheckpointManager(str(tmp_path), async_write=False)
        plan = FaultPlan()
        w = {"w": np.ones((4, 4), np.float32)}
        with plan.checkpoint_write_failure(at_save=1):
            mgr.save(1, w)
            with pytest.raises(OSError):
                mgr.save(2, w)
        assert mgr.latest_step() == 1

    def test_torn_write_recovery(self, tmp_path):
        """Satellite: a write that dies mid-file (ENOSPC at a chosen
        byte) leaves a torn artifact — in the .tmp staging dir, never
        renamed — and latest_step() returns the previous INTACT one."""
        mgr = CheckpointManager(str(tmp_path), async_write=False)
        plan = FaultPlan()
        w = {"w": np.arange(256, dtype=np.float32).reshape(16, 16)}
        mgr.save(1, w)
        with plan.checkpoint_write_failure(at_save=0, at_byte=128):
            with pytest.raises(OSError):
                mgr.save(2, w)
        # the torn bytes exist on disk, but only in staging
        torn = tmp_path / "ckpt-0000000002.tmp" / "state.npz"
        assert torn.exists() and torn.stat().st_size <= 128
        assert mgr.latest_step() == 1
        step, tree = mgr.restore()
        assert step == 1
        np.testing.assert_array_equal(tree["params"]["w"], w["w"])

    def test_async_write_failure_surfaces_at_wait(self, tmp_path):
        mgr = CheckpointManager(str(tmp_path), async_write=True)
        plan = FaultPlan()
        with plan.checkpoint_write_failure(at_save=0):
            mgr.save(1, {"w": np.ones((2, 2), np.float32)})
            with pytest.raises(RuntimeError, match="checkpoint"):
                mgr.wait()
        assert mgr.latest_step() is None

    def test_md5_corruption_falls_back(self, tmp_path):
        """Satellite: bit-rot on the NEWEST checkpoint -> restore uses
        the one before it."""
        mgr = CheckpointManager(str(tmp_path), async_write=False)
        mgr.save(1, {"w": np.full((2, 2), 1.0, np.float32)})
        mgr.save(2, {"w": np.full((2, 2), 2.0, np.float32)})
        corrupted = FaultPlan.corrupt_newest_checkpoint(str(tmp_path))
        assert corrupted == 2
        assert mgr.latest_step() == 1
        step, tree = mgr.restore()
        assert step == 1
        np.testing.assert_array_equal(tree["params"]["w"],
                                      np.full((2, 2), 1.0, np.float32))

    def test_explicit_step_restore_verifies_too(self, tmp_path):
        """Satellite: restore(step=N) must run the same md5 check
        latest_step() does — an explicitly-named corrupt checkpoint
        raises a clear error instead of loading garbage."""
        mgr = CheckpointManager(str(tmp_path), async_write=False)
        mgr.save(1, {"w": np.full((2, 2), 1.0, np.float32)})
        mgr.save(2, {"w": np.full((2, 2), 2.0, np.float32)})
        corrupted = FaultPlan.corrupt_newest_checkpoint(str(tmp_path))
        with pytest.raises(RuntimeError,
                           match=f"ckpt-{corrupted:010d}"):
            mgr.restore(step=corrupted)
        # an intact explicit step still loads
        step, tree = mgr.restore(step=1)
        assert step == 1
        np.testing.assert_array_equal(tree["params"]["w"],
                                      np.full((2, 2), 1.0, np.float32))


# ----------------------------------------------------------- (c) numerics

class TestGuardedStep:
    def test_nan_steps_never_reach_params(self):
        """Injected non-finite losses at chosen steps leave the params
        finite and BIT-identical to a run that skipped those batches."""
        plan = FaultPlan()
        bad = {2, 5}
        events = []
        tr = _trainer()
        tr.train(plan.poison_batches(_reader(), bad), num_passes=1,
                 fault_policy=FaultPolicy(max_bad_steps=3),
                 event_handler=events.append)

        tr2 = _trainer()

        def skipping():
            for b, batch in enumerate(_reader()()):
                if b not in bad:
                    yield batch
        tr2.train(skipping, num_passes=1,
                  fault_policy=FaultPolicy(max_bad_steps=3))

        for k in tr.parameters.raw:
            a = np.asarray(tr.parameters.raw[k])
            b = np.asarray(tr2.parameters.raw[k])
            assert np.isfinite(a).all()
            np.testing.assert_array_equal(a, b)
        faults = [e for e in events
                  if isinstance(e, paddle.event.FaultEvent)]
        assert faults and all(f.kind == "nonfinite" for f in faults)
        done = [e for e in events if isinstance(e, paddle.event.EndPass)]
        # skipped steps are excluded from pass averages; the good-step
        # fraction is surfaced
        assert done[0].metrics["fault_ok"] == pytest.approx(0.75)
        assert np.isfinite(done[0].metrics["cost"])

    def test_inf_injection_also_guarded(self):
        plan = FaultPlan()
        tr = _trainer()
        tr.train(plan.poison_batches(_reader(), {1}, value=float("inf")),
                 num_passes=1, fault_policy=FaultPolicy(max_bad_steps=2))
        for k, v in tr.parameters.raw.items():
            assert np.isfinite(np.asarray(v)).all(), k

    def test_k_bad_steps_roll_back(self, tmp_path):
        """K consecutive bad steps -> restore from the newest intact
        checkpoint + a FaultEvent(kind='rollback')."""
        plan = FaultPlan()
        mgr = CheckpointManager(str(tmp_path))
        events = []
        tr = _trainer()
        tr.train(plan.poison_batches(_reader(), {3, 4, 5}), num_passes=1,
                 fault_policy=FaultPolicy(max_bad_steps=3),
                 checkpoint_manager=mgr, checkpoint_period=2,
                 event_handler=events.append)
        rb = [e for e in events if isinstance(e, paddle.event.FaultEvent)
              and e.kind == "rollback"]
        assert len(rb) == 1
        assert rb[0].bad_streak == 3
        assert rb[0].restored_step is not None     # a checkpoint existed
        for k, v in tr.parameters.raw.items():
            assert np.isfinite(np.asarray(v)).all(), k

    def test_streak_detected_between_checks(self):
        """A K-streak that ENDS between host checks is still caught (the
        device-side peak counter is sticky): bad steps 1-3 with
        check_period=4 must roll up a rollback event at the step-4
        check."""
        plan = FaultPlan()
        events = []
        tr = _trainer()
        tr.train(plan.poison_batches(_reader(), {1, 2, 3}), num_passes=1,
                 fault_policy=FaultPolicy(max_bad_steps=3, check_period=4),
                 event_handler=events.append)
        kinds = [e.kind for e in events
                 if isinstance(e, paddle.event.FaultEvent)]
        assert "rollback" in kinds


# ---------------------------------------------------------------- (b) rpc

class TestCoordinatorChaos:
    def test_retry_survives_drops_and_delays(self):
        """Injected RPC drops/delays: task_reader retries with backoff
        and still completes the epoch."""
        plan = FaultPlan(seed=7)
        c = Coordinator(chunks=["a", "b", "c"], chunks_per_task=1)
        flaky = plan.flaky_coordinator(
            c,
            drop={"get_task": [0, 2], "task_finished": [0]},
            delay={"get_task": {1: 0.05}})
        retry = RetryPolicy(base_delay=0.01, deadline=5.0)
        recs = list(task_reader(flaky, lambda ch: [ch + "0"],
                                retry=retry)())
        assert sorted(recs) == ["a0", "b0", "c0"]
        assert c.epoch == 1
        assert flaky.faults_injected >= 3

    def test_deadline_exhaustion_raises(self):
        c = Coordinator(chunks=["a"], chunks_per_task=1)
        plan = FaultPlan()
        flaky = plan.flaky_coordinator(c, drop_rate=1.0)
        with pytest.raises(TimeoutError):
            call_with_retry(flaky.get_task, 0,
                            policy=RetryPolicy(base_delay=0.01,
                                               deadline=0.2))

    def test_unreachable_coordinator_times_out_cleanly(self):
        """Startup degradation: nothing listening -> bounded backoff,
        then a clear TimeoutError (not a raw socket error)."""
        dead = connect("127.0.0.1", 1)       # nothing listens there
        with pytest.raises(TimeoutError):
            call_with_retry(dead.get_task, 0,
                            policy=RetryPolicy(base_delay=0.01,
                                               deadline=0.3))

    def test_heartbeat_keeps_slow_trainer_alive(self):
        c = Coordinator(chunks=[1, 2], chunks_per_task=1, timeout_s=0.3)
        t = c.get_task()
        for _ in range(5):                    # hold it well past the lease
            time.sleep(0.1)
            assert c.heartbeat(t["task_id"])
        assert c.task_finished(t["task_id"])  # still ours

    def test_expired_lease_requeues_and_heartbeat_refuses(self):
        c = Coordinator(chunks=[1], chunks_per_task=1, timeout_s=0.05,
                        failure_max=10)
        t = c.get_task()
        time.sleep(0.1)
        assert c.heartbeat(t["task_id"]) is False   # lease lapsed
        t2 = c.get_task()                           # re-served
        assert t2 is not None and t2["task_id"] == t["task_id"]

    def test_lease_expiry_hands_task_to_other_trainer(self):
        """Acceptance: trainer A takes a task over RPC and dies silently
        (no heartbeat); its lease expires and trainer B — heartbeating
        through the same server — finishes the whole epoch."""
        c = Coordinator(chunks=["a", "b", "c"], chunks_per_task=1,
                        timeout_s=0.4, failure_max=10)
        srv = CoordinatorServer(c).start()
        try:
            dead = connect("127.0.0.1", srv.port)
            taken = dead.get_task()              # trainer A: takes + dies
            assert taken is not None

            live = connect("127.0.0.1", srv.port)
            recs = []

            def slow_chunks(ch):
                # slower than the lease: only survivable via heartbeat
                time.sleep(0.5)
                yield ch + "0"

            rdr = task_reader(live, slow_chunks,
                              retry=RetryPolicy(base_delay=0.01,
                                                deadline=10.0),
                              heartbeat_interval=0.1)
            for r in rdr():
                recs.append(r)
            assert sorted(recs) == ["a0", "b0", "c0"]
            assert c.epoch == 1
        finally:
            srv.stop()


# ------------------------------------------------------------- (d) murder

def _cpu_env():
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"
    return env


class TestSigkillAutoResume:
    def test_sigkill_then_auto_resume_matches_uninterrupted(self, tmp_path):
        """Acceptance: a subprocess trainer SIGKILL'd mid-pass and
        relaunched with the same --checkpoint_dir/--auto_resume flags
        finishes with the SAME step count and bit-identical params as an
        uninterrupted run (checkpoint_period=1: no step lost)."""
        import subprocess
        import sys as _sys

        worker = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                              "fault_worker.py")

        def launch(ckpt, delay):
            return subprocess.Popen(
                [_sys.executable, worker, ckpt, "2", str(delay)],
                env=_cpu_env(), stdout=subprocess.PIPE,
                stderr=subprocess.PIPE, text=True)

        # reference: uninterrupted run
        ref = launch(str(tmp_path / "ref"), 0.0)
        out, err = ref.communicate(timeout=180)
        assert ref.returncode == 0, err[-2000:]
        ref_line = [l for l in out.splitlines()
                    if l.startswith("WORKER DONE")][0]

        # chaos: kill mid-pass at step 4, then relaunch with same flags
        ckpt = str(tmp_path / "chaos")
        victim = launch(ckpt, 0.15)
        died_at = FaultPlan.kill_at_marker(victim, step=4)
        assert died_at >= 4 and victim.returncode != 0
        mgr = CheckpointManager(ckpt)
        assert mgr.latest_step() is not None     # an intact ckpt survives

        resumed = launch(ckpt, 0.0)
        out2, err2 = resumed.communicate(timeout=180)
        assert resumed.returncode == 0, err2[-2000:]
        res_line = [l for l in out2.splitlines()
                    if l.startswith("WORKER DONE")][0]
        # same step count AND same params digest as never having died
        assert res_line == ref_line
        # and the resumed run really did skip completed work: fewer than
        # a full run's worth of fresh STEP markers
        steps2 = [l for l in out2.splitlines() if l.startswith("STEP")]
        assert len(steps2) < 12
