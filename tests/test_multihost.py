"""Multi-host glue (parallel/multihost.py) — single-process semantics of
the jax.distributed path (Flags.cpp:55-60 trainer_id/num_gradient_servers
equivalent). Real multi-process formation needs multiple hosts; here we
pin the process-local contracts the cluster path builds on."""

import jax
import numpy as np

from paddle_tpu import config as cfg
from paddle_tpu.parallel import (global_batch, init_distributed,
                                 is_coordinator, process_reader)
from paddle_tpu.parallel.mesh import batch_sharding, data_parallel_mesh


def test_init_distributed_single_process_noop():
    pi, pc = init_distributed()
    assert (pi, pc) == (0, 1)
    assert cfg.global_config().process_index == 0
    assert cfg.global_config().process_count == 1
    assert is_coordinator()


def test_process_reader_deals_round_robin():
    def reader():
        yield from range(10)

    r0 = list(process_reader(reader, process_index=0, process_count=3)())
    r1 = list(process_reader(reader, process_index=1, process_count=3)())
    r2 = list(process_reader(reader, process_index=2, process_count=3)())
    assert r0 == [0, 3, 6, 9]
    assert r1 == [1, 4, 7]
    assert r2 == [2, 5, 8]
    assert sorted(r0 + r1 + r2) == list(range(10))


def test_global_batch_shards_over_mesh():
    mesh = data_parallel_mesh(8)
    sharding = batch_sharding(mesh)
    x = np.arange(16 * 3, dtype=np.float32).reshape(16, 3)
    arr = global_batch(x, mesh, sharding.spec)
    assert arr.shape == (16, 3)
    assert len(arr.sharding.device_set) == 8
    np.testing.assert_allclose(np.asarray(arr), x)
