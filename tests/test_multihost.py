"""Multi-host glue (parallel/multihost.py): single-process semantics of
the jax.distributed path (Flags.cpp:55-60 trainer_id/num_gradient_servers
equivalent), plus a REAL two-OS-process group formed over localhost."""

import os

import jax
import numpy as np

from paddle_tpu import config as cfg
from paddle_tpu.parallel import (global_batch, init_distributed,
                                 is_coordinator, process_reader)
from paddle_tpu.parallel.mesh import batch_sharding, data_parallel_mesh


def test_init_distributed_single_process_noop():
    pi, pc = init_distributed()
    assert (pi, pc) == (0, 1)
    assert cfg.global_config().process_index == 0
    assert cfg.global_config().process_count == 1
    assert is_coordinator()


def test_process_reader_deals_round_robin():
    def reader():
        yield from range(10)

    r0 = list(process_reader(reader, process_index=0, process_count=3)())
    r1 = list(process_reader(reader, process_index=1, process_count=3)())
    r2 = list(process_reader(reader, process_index=2, process_count=3)())
    assert r0 == [0, 3, 6, 9]
    assert r1 == [1, 4, 7]
    assert r2 == [2, 5, 8]
    assert sorted(r0 + r1 + r2) == list(range(10))


def test_global_batch_shards_over_mesh():
    mesh = data_parallel_mesh(8)
    sharding = batch_sharding(mesh)
    x = np.arange(16 * 3, dtype=np.float32).reshape(16, 3)
    arr = global_batch(x, mesh, sharding.spec)
    assert arr.shape == (16, 3)
    assert len(arr.sharding.device_set) == 8
    np.testing.assert_allclose(np.asarray(arr), x)


def test_two_process_group_agrees_on_loss(tmp_path):
    """REAL multi-host: two OS processes form a jax.distributed group over
    localhost (4 virtual CPU devices each -> one 8-device dp mesh), run
    two dp training steps with per-process data shards, and must print
    identical losses (test_CompareSparse.cpp's in-process-cluster
    discipline, with actual processes)."""
    import socket
    import subprocess
    import sys

    with socket.socket() as s:
        s.bind(("localhost", 0))
        port = s.getsockname()[1]

    worker = os.path.join(os.path.dirname(__file__), "multihost_worker.py")
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = {**os.environ}
    env.pop("XLA_FLAGS", None)
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    procs = [subprocess.Popen([sys.executable, worker, str(port), str(i)],
                              stdout=subprocess.PIPE,
                              stderr=subprocess.PIPE, text=True, env=env)
             for i in range(2)]
    outs = []
    for p in procs:
        out, err = p.communicate(timeout=300)
        assert p.returncode == 0, f"worker failed:\n{out}\n{err}"
        outs.append([l for l in out.splitlines() if l.startswith("STEP")])
    assert len(outs[0]) == 2 and outs[0] == outs[1], outs
    losses = [float(l.split()[2]) for l in outs[0]]
    assert np.isfinite(losses).all() and losses[1] < losses[0]


class TestAsyncSGD:
    """Local-SGD islands — the async-DP capability
    (ParameterServer2::asyncSGD parity by redesign; see
    parallel/async_sgd.py)."""

    def _island(self, seed, lr=3e-2):
        import paddle_tpu as paddle
        from paddle_tpu.core import registry
        registry.reset_name_counters()
        paddle.init(use_tpu=False, seed=seed)
        x = paddle.layer.data("x", paddle.data_type.dense_vector(8))
        y = paddle.layer.data("y", paddle.data_type.dense_vector(1))
        cost = paddle.layer.mse_cost(paddle.layer.fc(x, size=1), y)
        params = paddle.create_parameters(paddle.Topology(cost))
        tr = paddle.SGD(cost=cost, parameters=params,
                        update_equation=paddle.optimizer.Adam(
                            learning_rate=lr))
        return tr, params

    def test_islands_drift_then_reconcile(self):
        from paddle_tpu.parallel import AsyncSGDIsland
        rng = np.random.RandomState(0)
        w_true = rng.randn(8, 1).astype("float32")
        tr_a, pa = self._island(0)
        tr_b, pb = self._island(0)
        isl_a = AsyncSGDIsland(tr_a, sync_period=4, sync_group=[pa, pb])
        isl_b = AsyncSGDIsland(tr_b, sync_period=4, sync_group=[pa, pb])

        def batch(r):
            xs = r.randn(32, 8).astype("float32")
            ys = (xs @ w_true).astype("float32")
            return [(xs[i], ys[i]) for i in range(32)]

        ra, rb = np.random.RandomState(1), np.random.RandomState(2)
        drifted = False
        for it in range(24):
            isl_a.train_batch(batch(ra))          # different shards
            la, _ = 0, 0
            isl_b.train_batch(batch(rb))
            wa = np.asarray(pa.raw["___fc_0__.w0"])
            wb = np.asarray(pb.raw["___fc_0__.w0"])
            if (it + 1) % 4 == 0:
                # reconciliation just ran: islands agree exactly
                np.testing.assert_array_equal(wa, wb)
            elif not np.array_equal(wa, wb):
                drifted = True                     # async drift is real
        assert drifted, "islands never drifted -> test is vacuous"
        loss, _ = isl_a.train_batch(batch(ra))
        assert np.isfinite(loss)

    def test_local_sgd_converges_like_sync(self):
        from paddle_tpu.parallel import AsyncSGDIsland
        rng = np.random.RandomState(3)
        w_true = rng.randn(8, 1).astype("float32")

        def batch(r, n=64):
            xs = r.randn(n, 8).astype("float32")
            ys = (xs @ w_true).astype("float32")
            return [(xs[i], ys[i]) for i in range(n)]

        # Adam at 3e-2 leaves |w - w_true| ~0.54 after 60 local-SGD
        # iterations on this seed; 6e-2 converges to ~0.09 with the
        # same dynamics — the assertion tests RECONCILED convergence,
        # not the optimizer's step-size schedule
        tr_a, pa = self._island(0, lr=6e-2)
        tr_b, pb = self._island(0, lr=6e-2)
        isl_a = AsyncSGDIsland(tr_a, sync_period=5, sync_group=[pa, pb])
        isl_b = AsyncSGDIsland(tr_b, sync_period=5, sync_group=[pa, pb])
        ra, rb = np.random.RandomState(4), np.random.RandomState(5)
        for _ in range(60):
            isl_a.train_batch(batch(ra))
            loss_b, _ = isl_b.train_batch(batch(rb))
        isl_a.reconcile()
        w = np.asarray(pa.raw["___fc_0__.w0"])
        assert np.abs(w - w_true).max() < 0.15, (w - w_true)


def test_two_process_async_islands_reconcile(tmp_path):
    """REAL cross-process async DP (local SGD): two processes train on
    DIFFERENT data without a barrier per step, reconciling by parameter
    averaging every 4 steps — both must hold identical weights after each
    reconciliation and each island's loss must fall."""
    import socket
    import subprocess
    import sys

    with socket.socket() as s:
        s.bind(("localhost", 0))
        port = s.getsockname()[1]

    worker = os.path.join(os.path.dirname(__file__), "multihost_worker.py")
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = {**os.environ}
    env.pop("XLA_FLAGS", None)
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    procs = [subprocess.Popen(
        [sys.executable, worker, str(port), str(i), "async"],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True, env=env)
        for i in range(2)]
    outs = []
    for p in procs:
        out, err = p.communicate(timeout=300)
        assert p.returncode == 0, f"worker failed:\n{out}\n{err}"
        outs.append(out.splitlines())
    syncs = [[l for l in o if l.startswith("SYNCW")] for o in outs]
    assert len(syncs[0]) == 3 and syncs[0] == syncs[1], syncs
    for o in outs:
        steps = [float(l.split()[2]) for l in o if l.startswith("STEP")]
        assert steps[1] < steps[0]
