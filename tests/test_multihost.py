"""Multi-host glue (parallel/multihost.py): single-process semantics of
the jax.distributed path (Flags.cpp:55-60 trainer_id/num_gradient_servers
equivalent), plus a REAL two-OS-process group formed over localhost."""

import os

import jax
import numpy as np

from paddle_tpu import config as cfg
from paddle_tpu.parallel import (global_batch, init_distributed,
                                 is_coordinator, process_reader)
from paddle_tpu.parallel.mesh import batch_sharding, data_parallel_mesh


def test_init_distributed_single_process_noop():
    pi, pc = init_distributed()
    assert (pi, pc) == (0, 1)
    assert cfg.global_config().process_index == 0
    assert cfg.global_config().process_count == 1
    assert is_coordinator()


def test_process_reader_deals_round_robin():
    def reader():
        yield from range(10)

    r0 = list(process_reader(reader, process_index=0, process_count=3)())
    r1 = list(process_reader(reader, process_index=1, process_count=3)())
    r2 = list(process_reader(reader, process_index=2, process_count=3)())
    assert r0 == [0, 3, 6, 9]
    assert r1 == [1, 4, 7]
    assert r2 == [2, 5, 8]
    assert sorted(r0 + r1 + r2) == list(range(10))


def test_global_batch_shards_over_mesh():
    mesh = data_parallel_mesh(8)
    sharding = batch_sharding(mesh)
    x = np.arange(16 * 3, dtype=np.float32).reshape(16, 3)
    arr = global_batch(x, mesh, sharding.spec)
    assert arr.shape == (16, 3)
    assert len(arr.sharding.device_set) == 8
    np.testing.assert_allclose(np.asarray(arr), x)


def test_two_process_group_agrees_on_loss(tmp_path):
    """REAL multi-host: two OS processes form a jax.distributed group over
    localhost (4 virtual CPU devices each -> one 8-device dp mesh), run
    two dp training steps with per-process data shards, and must print
    identical losses (test_CompareSparse.cpp's in-process-cluster
    discipline, with actual processes)."""
    import socket
    import subprocess
    import sys

    with socket.socket() as s:
        s.bind(("localhost", 0))
        port = s.getsockname()[1]

    worker = os.path.join(os.path.dirname(__file__), "multihost_worker.py")
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = {**os.environ}
    env.pop("XLA_FLAGS", None)
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    procs = [subprocess.Popen([sys.executable, worker, str(port), str(i)],
                              stdout=subprocess.PIPE,
                              stderr=subprocess.PIPE, text=True, env=env)
             for i in range(2)]
    outs = []
    for p in procs:
        out, err = p.communicate(timeout=300)
        assert p.returncode == 0, f"worker failed:\n{out}\n{err}"
        outs.append([l for l in out.splitlines() if l.startswith("STEP")])
    assert len(outs[0]) == 2 and outs[0] == outs[1], outs
    losses = [float(l.split()[2]) for l in outs[0]]
    assert np.isfinite(losses).all() and losses[1] < losses[0]
