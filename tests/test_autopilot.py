"""Fleet autopilot (ISSUE 16): the hysteresis policy replayed over
the seeded bursty trace, the autoscaler against a REAL mini-fleet
(scales up on the shed spike, down once after cooldown, never flaps,
every decision journaled with evidence), and the SLO-gated rolling
deploy (zero failed requests under an open-loop burst; an injected
breach pauses the rollout)."""

import json
import threading
import time
import urllib.error
import urllib.request

import pytest

import jax
import paddle_tpu as paddle
from paddle_tpu import models
from paddle_tpu.fleet import Router
from paddle_tpu.fleet.autopilot import (Autopilot, AutopilotPolicy,
                                        CallbackProvisioner,
                                        ReplicaProvisioner, RollingDeploy)
from paddle_tpu.obs.events import JOURNAL
from paddle_tpu.serving import (DecodeEngine, InferenceServer, Rejected,
                                build_http_server)
from paddle_tpu.testing import FaultPlan

pytestmark = pytest.mark.chaos

DEC_CFG = dict(vocab_size=40, d_model=16, n_heads=2, n_layers=2,
               d_ff=32, max_len=32)
PAGE = 4


def tiny_decoder(seed=7):
    paddle.init(use_tpu=False, seed=0)
    from paddle_tpu.core.registry import reset_name_counters
    reset_name_counters()
    spec = models.transformer_lm(**DEC_CFG)
    costs = spec.cost if isinstance(spec.cost, list) else [spec.cost]
    topo = paddle.Topology(costs, extra_outputs=[spec.output])
    params = topo.init_params(jax.random.PRNGKey(seed))
    return models.TransformerDecoder(params, n_layers=DEC_CFG["n_layers"],
                                     n_heads=DEC_CFG["n_heads"])


@pytest.fixture(scope="module")
def decoder():
    return tiny_decoder()


class Replica:
    """One in-process serving replica (tests/test_fleet.py's shape,
    with a configurable page pool so the autoscaler tests can make
    KV headroom genuinely scarce)."""

    def __init__(self, rid, decoder, max_queue=16, **engine_kw):
        self.rid = rid
        kw = dict(num_slots=2, page_size=PAGE,
                  max_seq_len=DEC_CFG["max_len"])
        kw.update(engine_kw)
        self.engine = DecodeEngine(decoder, **kw)
        self.server = InferenceServer(None, max_queue=max_queue,
                                      workers=1, breaker=False,
                                      engine=self.engine).start()
        self.httpd = build_http_server(self.server, "127.0.0.1", 0)
        self.endpoint = \
            f"http://127.0.0.1:{self.httpd.server_address[1]}"
        self._t = threading.Thread(target=self.httpd.serve_forever,
                                   daemon=True,
                                   name=f"pt-test-ap-replica-{rid}")
        self._t.start()

    def stop(self):
        self.httpd.shutdown()
        self.httpd.server_close()
        self.server.shutdown(drain=True, timeout=30)


def _journal_since(seq, kind=None):
    return JOURNAL.tail(500, domain="autopilot", kind=kind,
                        since_seq=seq)


class _SIG:
    """Signal-dict factory for pure policy tests."""

    @staticmethod
    def make(**kw):
        sig = dict(replicas_live=1, shed_rate=0.0, headroom_frac=1.0,
                   headroom_trend_per_s=0.0, slo_breaches=0)
        sig.update(kw)
        return sig


class TestAutopilotPolicy:
    def test_scale_up_on_shed_respects_cooldown_and_ceiling(self):
        p = AutopilotPolicy(min_replicas=1, max_replicas=3,
                            up_cooldown_s=5.0)
        d = p.decide(_SIG.make(shed_rate=2.0), 100.0)
        assert d["action"] == "scale_up"
        assert "shed_rate" in d["reason"]
        assert d["evidence"]["shed_rate"] == 2.0
        # a spawn is already in flight: hold through the cooldown
        assert p.decide(_SIG.make(shed_rate=2.0, replicas_live=2),
                        102.0) is None
        d2 = p.decide(_SIG.make(shed_rate=2.0, replicas_live=2), 106.0)
        assert d2["action"] == "scale_up"
        # pinned at the ceiling: pressure no longer scales
        assert p.decide(_SIG.make(shed_rate=9.0, replicas_live=3),
                        120.0) is None

    def test_scale_up_on_low_headroom_and_slo_breach(self):
        p = AutopilotPolicy(headroom_low=0.15)
        d = p.decide(_SIG.make(headroom_frac=0.10), 0.0)
        assert d["action"] == "scale_up" and "headroom" in d["reason"]
        p2 = AutopilotPolicy()
        d2 = p2.decide(_SIG.make(slo_breaches=2), 0.0)
        assert d2["action"] == "scale_up"
        assert "slo_breaches" in d2["reason"]

    def test_scale_down_needs_sustained_calm_and_floor(self):
        p = AutopilotPolicy(min_replicas=1, max_replicas=4,
                            down_stable_s=5.0, down_cooldown_s=0.0)
        calm = _SIG.make(replicas_live=2, headroom_frac=0.9)
        assert p.decide(calm, 0.0) is None     # calm clock starts
        assert p.decide(calm, 3.0) is None     # not stable yet
        d = p.decide(calm, 6.0)
        assert d["action"] == "scale_down"
        # ONE down per stability window — no flap
        assert p.decide(calm, 6.5) is None
        assert p.decide(calm, 12.0)["action"] == "scale_down"
        # the floor: never drain below min_replicas
        at_floor = _SIG.make(replicas_live=1, headroom_frac=0.9)
        p2 = AutopilotPolicy(min_replicas=1, down_stable_s=0.0,
                             down_cooldown_s=0.0)
        p2.decide(at_floor, 0.0)
        assert p2.decide(at_floor, 1.0) is None

    def test_pressure_resets_the_calm_clock(self):
        p = AutopilotPolicy(max_replicas=8, down_stable_s=2.0,
                            down_cooldown_s=0.0)
        calm = _SIG.make(replicas_live=2, headroom_frac=0.9)
        assert p.decide(calm, 0.0) is None
        p.decide(_SIG.make(replicas_live=2, shed_rate=1.0), 1.0)
        # calm must restart from scratch after the pressure blip
        assert p.decide(calm, 2.5) is None
        assert p.decide(calm, 4.0) is None     # 1.5s < 2s stable
        assert p.decide(calm, 5.0)["action"] == "scale_down"

    def test_bursty_trace_replay_is_bounded_and_converges(self):
        """The acceptance shape: the seeded trace scales up ON the
        burst edge, down ONCE after the quiet tail, total decisions
        bounded — hysteresis, not flapping."""
        trace = FaultPlan.bursty_trace(seed=0, ticks=30)
        p = AutopilotPolicy(min_replicas=1, max_replicas=2,
                            up_cooldown_s=2.0, down_cooldown_s=3.0,
                            down_stable_s=2.0)
        live, decisions = 1, []
        for t, load in enumerate(trace):
            # toy capacity model: ~4 concurrent requests per replica
            shed = max(0, load - 4 * live)
            sig = _SIG.make(replicas_live=live, shed_rate=float(shed),
                            headroom_frac=0.9 if shed == 0 else 0.2)
            d = p.decide(sig, float(t))
            if d is None:
                continue
            decisions.append((t, d["action"]))
            live += 1 if d["action"] == "scale_up" else -1
        ups = [t for t, a in decisions if a == "scale_up"]
        downs = [t for t, a in decisions if a == "scale_down"]
        assert ups and downs
        assert 8 <= ups[0] <= 10      # the burst edge (burst_start=8)
        assert downs[0] > max(ups)    # down only after the burst
        assert len(decisions) <= 4    # bounded: never flaps
        assert live == 1              # back to the floor

    def test_bursty_trace_is_seed_deterministic(self):
        a = FaultPlan.bursty_trace(seed=3)
        assert a == FaultPlan.bursty_trace(seed=3)
        assert a != FaultPlan.bursty_trace(seed=4)
        assert max(a[8:16]) >= 10 and max(a[:8] + a[16:]) <= 2


class TestAutoscalerChaos:
    def test_bursty_load_scales_up_then_down_with_journaled_evidence(
            self, decoder):
        """The tentpole acceptance: a REAL router + replica under the
        seeded bursty trace. The shed spike triggers ONE spawn (live
        provisioner, admitted mid-run), the quiet tail ONE drain after
        cooldown, decision count stays bounded, and every decision
        carries its evidence in the journal."""
        def slow_replica(rid):
            # tiny waiting queue + throttled decode: a 10-wide burst
            # makes the replica decline (429) past the router's
            # queue_timeout — genuine SHEDS, the autoscaler's trigger
            r = Replica(rid, decoder, max_waiting=1,
                        prefix_cache=False)
            r.engine._step_interceptor = lambda n: time.sleep(0.04)
            return r

        reps = {"r0": slow_replica("r0")}
        router = Router(endpoints={"r0": reps["r0"].endpoint},
                        affinity="load", page_size=PAGE,
                        scrape_interval=0.1, queue_timeout=0.35,
                        queue_poll=0.02, drain_timeout=5.0).start()
        time.sleep(0.3)                # first scrape lands

        def spawn(rid):
            reps[rid] = slow_replica(rid)
            return {"endpoint": reps[rid].endpoint}

        def stop(rid):
            reps.pop(rid).stop()

        ap = Autopilot(
            router, CallbackProvisioner(spawn=spawn, stop=stop),
            policy=AutopilotPolicy(min_replicas=1, max_replicas=2,
                                   up_cooldown_s=1.0,
                                   down_cooldown_s=1.0,
                                   down_stable_s=0.8),
            interval=0.2)
        seq0 = JOURNAL.last_seq
        trace = FaultPlan.bursty_trace(seed=0, ticks=16, base=0,
                                       peak=10, burst_start=3,
                                       burst_len=4)
        try:
            for load in trace:
                if load:
                    def one(i):
                        try:
                            router.generate([2 + i % 7, 3, 5, 7, 11], 8)
                        except Rejected:
                            pass
                    FaultPlan.burst(one, n=load,
                                    threads=min(load, 10), timeout=30)
                ap.tick()
                time.sleep(0.15)
            # quiet tail: let the calm window + cooldown elapse
            deadline = time.monotonic() + 8.0
            while time.monotonic() < deadline and \
                    ap.stats()["scale_downs"] == 0:
                ap.tick()
                time.sleep(0.2)
            st = ap.stats()
            assert st["scale_ups"] >= 1, st
            assert st["scale_downs"] >= 1, st
            # hysteresis: bounded decision count, no flapping
            assert st["scale_ups"] + st["scale_downs"] <= 4, st
            assert st["spawn_failures"] == 0
            # the fleet converged back to the floor
            live = [s for s in router.balancer.replicas().values()
                    if s.live and not s.draining]
            assert len(live) == 1
            # every decision journaled WITH its triggering evidence
            ups = _journal_since(seq0, kind="scale_up")
            downs = _journal_since(seq0, kind="scale_down")
            assert len(ups) == st["scale_ups"]
            assert len(downs) == st["scale_downs"]
            for rec in ups:
                ev = rec["evidence"]
                assert rec["reason"]
                assert ev["shed_rate"] > 0 or \
                    ev["headroom_frac"] < 0.15 or ev["slo_breaches"]
            for rec in downs:
                assert rec["evidence"]["shed_rate"] == 0
                assert rec["replica"].startswith("auto-")
        finally:
            ap.stop()
            router.shutdown(drain=True, timeout=10)
            for r in list(reps.values()):
                r.stop()

    def test_scale_to_is_bounded_by_policy(self, decoder):
        """`fleet scale` clamps to [min, max] and journals each
        action."""
        reps = {"r0": Replica("r0", decoder)}
        router = Router(endpoints={"r0": reps["r0"].endpoint},
                        affinity="load", page_size=PAGE,
                        scrape_interval=0.1, queue_timeout=1.0).start()
        time.sleep(0.25)

        def spawn(rid):
            reps[rid] = Replica(rid, decoder)
            return {"endpoint": reps[rid].endpoint}

        ap = Autopilot(
            router,
            CallbackProvisioner(spawn=spawn,
                                stop=lambda rid: reps.pop(rid).stop()),
            policy=AutopilotPolicy(min_replicas=1, max_replicas=3))
        try:
            acts = ap.scale_to(99)     # clamped to max_replicas=3
            assert [a["action"] for a in acts] == ["scale_up"] * 2
            assert router.stats()["replicas_live"] == 3
            acts = ap.scale_to(0)      # clamped to min_replicas=1
            assert [a["action"] for a in acts] == ["scale_down"] * 2
            deadline = time.monotonic() + 5.0
            while time.monotonic() < deadline and \
                    router.stats()["replicas_live"] != 1:
                time.sleep(0.05)
            assert router.stats()["replicas_live"] == 1
        finally:
            ap.stop()
            router.shutdown(drain=True, timeout=10)
            for r in list(reps.values()):
                r.stop()

    def test_scale_to_arms_the_hysteresis_clocks(self):
        """Operator scale-up on an IDLE fleet must not be reverted by
        the very next policy tick: before the fix, `_calm_since`
        predated the spawn (the fleet was calm all along) and
        `_last_action_t` was None, so decide() fired scale_down one
        tick after `fleet scale` returned."""
        pol = AutopilotPolicy(min_replicas=1, max_replicas=4,
                              down_cooldown_s=10.0, down_stable_s=5.0)
        # fleet idle since t=0: calm clock armed at 100, stable by 106
        assert pol.decide(_SIG.make(replicas_live=1), 100.0) is None
        assert pol.decide(_SIG.make(replicas_live=1), 106.0) is None
        # operator scales to 2 at t=107 (scale_to calls this)
        pol.note_external_action(107.0)
        # next ticks: calm again, but stability + cooldown restart at
        # 107 — no scale_down until BOTH have re-elapsed
        assert pol.decide(_SIG.make(replicas_live=2), 107.5) is None
        assert pol.decide(_SIG.make(replicas_live=2), 111.0) is None
        d = pol.decide(_SIG.make(replicas_live=2), 117.5)
        assert d is not None and d["action"] == "scale_down"


class FakeWatchdog:
    """SLO watchdog stand-in: .breaches is all RollingDeploy reads."""

    def __init__(self):
        self.breaches = 0


class TestRollingDeploy:
    def _fleet(self, decoder, n=2):
        reps = {f"r{i}": Replica(f"r{i}", decoder) for i in range(n)}
        router = Router(endpoints={rid: r.endpoint
                                   for rid, r in reps.items()},
                        affinity="prefix", page_size=PAGE,
                        scrape_interval=0.1, queue_timeout=10.0,
                        queue_poll=0.02, drain_timeout=5.0).start()
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline and any(
                s.last_scrape == 0
                for s in router.balancer.replicas().values()):
            time.sleep(0.05)
        return reps, router

    def test_zero_failed_requests_under_open_loop_burst(self, decoder):
        """The deploy acceptance: every replica restarts (new port =
        new endpoint) one at a time while an open-loop burst keeps
        arriving — and NOT ONE request fails."""
        reps, router = self._fleet(decoder)
        old_endpoints = {rid: r.endpoint for rid, r in reps.items()}
        cycled = []

        def restart(rid):
            reps[rid].stop()
            reps[rid] = Replica(rid, decoder)
            cycled.append(rid)
            return {"endpoint": reps[rid].endpoint}

        roll = RollingDeploy(router, restart,
                             watchdog=FakeWatchdog(),
                             settle_timeout=30.0)
        seq0 = JOURNAL.last_seq
        out = {}

        def run_deploy():
            out.update(roll.run())

        try:
            dt = threading.Thread(target=run_deploy, daemon=True,
                                  name="pt-test-deploy")
            dt.start()

            def one(i):
                r = router.generate([1 + i % 5, 2, 3, 4], 6)
                assert len(r.tokens) == 6
                return r
            results, errors = FaultPlan.burst(one, n=40, threads=4,
                                             timeout=120)
            dt.join(timeout=60)
            assert not dt.is_alive()
            failed = [e for e in errors if e is not None]
            assert failed == []        # ZERO failed requests
            assert sum(r is not None for r in results) == 40
            assert out["status"] == "complete", out
            assert cycled == ["r0", "r1"]
            assert all(s["ready"] for s in out["steps"])
            # both replicas really moved (restart = new port)
            for rid, r in reps.items():
                assert r.endpoint != old_endpoints[rid]
            steps = _journal_since(seq0, kind="deploy_step")
            assert [s["replica"] for s in steps] == ["r0", "r1"]
            assert _journal_since(seq0, kind="deploy_done")
        finally:
            router.shutdown(drain=True, timeout=10)
            for r in reps.values():
                r.stop()

    def test_slo_breach_pauses_rollout_and_force_overrides(
            self, decoder):
        reps, router = self._fleet(decoder)
        wd = FakeWatchdog()

        def restart(rid):
            reps[rid].stop()
            reps[rid] = Replica(rid, decoder)
            wd.breaches += 1           # regression surfaces AFTER r0
            return {"endpoint": reps[rid].endpoint}

        seq0 = JOURNAL.last_seq
        try:
            out = RollingDeploy(router, restart, watchdog=wd,
                                settle_timeout=30.0).run()
            assert out["status"] == "paused", out
            assert out["reason"] == "slo_breach"
            assert [s["replica"] for s in out["steps"]] == ["r0"]
            assert out["remaining"] == ["r1"]
            paused = _journal_since(seq0, kind="deploy_paused")
            assert paused and paused[-1]["replica"] == "r1"
            assert paused[-1]["breaches"] == 1
            # --force marches through the breach (journal still has it)
            out2 = RollingDeploy(router, restart, watchdog=wd,
                                 force=True,
                                 settle_timeout=30.0).run(["r1"])
            assert out2["status"] == "complete"
        finally:
            router.shutdown(drain=True, timeout=10)
            for r in reps.values():
                r.stop()


    def test_warm_respawn_rollout_zero_compiles(self, decoder):
        """Warm-start plane × rolling deploy (ISSUE 18): once the
        fleet has served a single request, the decode executable
        lives in the process-global cache — so a FULL rolling restart
        resolves every respawned engine warm, the deploy's own
        ``max_compiles=0`` budget gate passes, and post-rollout
        traffic is token-identical. This is the
        ``fleet_deploy.rollout_compiles == 0`` bench contract."""
        from paddle_tpu.analysis.sanitizer import compile_watch
        reps, router = self._fleet(decoder)
        try:
            # prime: one request pays the only compile of the test
            want = router.generate([1, 2, 3, 4], 6).tokens

            def restart(rid):
                reps[rid].stop()
                reps[rid] = Replica(rid, decoder)
                return {"endpoint": reps[rid].endpoint}

            roll = RollingDeploy(router, restart,
                                 watchdog=FakeWatchdog(),
                                 settle_timeout=30.0, max_compiles=0)
            with compile_watch() as cw:
                out = roll.run()
                # traffic lands on respawned replicas, still warm
                got = router.generate([1, 2, 3, 4], 6).tokens
            assert out["status"] == "complete", out
            assert out["rollout_compiles"] == 0, out
            assert out["compile_budget_ok"] is True
            step_compiles = {k: v for k, v in cw.per_function.items()
                             if "_step_impl" in k}
            assert step_compiles == {}, step_compiles
            assert got == want
        finally:
            router.shutdown(drain=True, timeout=10)
            for r in reps.values():
                r.stop()

    def test_compile_budget_breach_is_journaled(self, decoder):
        """The inverse gate: a rollout that DOES compile (cold
        executables dropped mid-deploy) reports the breach and
        journals it with per-function evidence instead of passing
        silently."""
        from paddle_tpu import artifacts as A
        reps, router = self._fleet(decoder)
        try:
            def restart(rid):
                reps[rid].stop()
                # simulate a cold respawn: the warm rung is emptied,
                # so the new replica's first decode must re-compile
                A.EXECUTABLES.clear()
                jax.clear_caches()
                reps[rid] = Replica(rid, decoder)
                r = urllib.request.urlopen(
                    reps[rid].endpoint + "/generate",
                    json.dumps({"prompt": [1, 2, 3], "max_new_tokens":
                                2}).encode(), timeout=60)
                assert r.status == 200
                return {"endpoint": reps[rid].endpoint}

            seq0 = JOURNAL.last_seq
            out = RollingDeploy(router, restart,
                                watchdog=FakeWatchdog(),
                                settle_timeout=30.0,
                                max_compiles=0).run(["r0"])
            assert out["status"] == "complete"
            assert out["rollout_compiles"] > 0
            assert out["compile_budget_ok"] is False
            breach = _journal_since(
                seq0, kind="deploy_compile_budget_breach")
            assert breach and breach[-1]["budget"] == 0
            assert breach[-1]["per_function"]
        finally:
            router.shutdown(drain=True, timeout=10)
            for r in reps.values():
                r.stop()


class TestAdminQuit:
    def test_quit_endpoint_wires_hook_and_501s_without(self, decoder):
        r = Replica("rq", decoder)      # built WITHOUT on_quit
        quits = []
        server2 = InferenceServer(None, max_queue=4, workers=1,
                                  breaker=False,
                                  engine=DecodeEngine(
                                      decoder, num_slots=2,
                                      page_size=PAGE,
                                      max_seq_len=32)).start()
        httpd2 = build_http_server(server2, "127.0.0.1", 0,
                                   on_quit=lambda: quits.append(1))
        t2 = threading.Thread(target=httpd2.serve_forever, daemon=True,
                              name="pt-test-quit-http")
        t2.start()
        ep2 = f"http://127.0.0.1:{httpd2.server_address[1]}"
        try:
            req = urllib.request.Request(r.endpoint + "/admin/quit",
                                         data=b"{}", method="POST")
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(req, timeout=10)
            assert ei.value.code == 501
            req2 = urllib.request.Request(ep2 + "/admin/quit",
                                          data=b"{}", method="POST")
            with urllib.request.urlopen(req2, timeout=10) as resp:
                body = json.loads(resp.read())
            assert body["quitting"] is True
            deadline = time.monotonic() + 5.0
            while time.monotonic() < deadline and not quits:
                time.sleep(0.02)
            assert quits == [1]
        finally:
            r.stop()
            httpd2.shutdown()
            httpd2.server_close()
            server2.shutdown(drain=True, timeout=30)


class TestProvisionerSeam:
    def test_callback_provisioner_defaults_restart_to_stop_spawn(self):
        calls = []
        prov = CallbackProvisioner(
            spawn=lambda rid: calls.append(("spawn", rid)) or
            {"endpoint": "http://x"},
            stop=lambda rid: calls.append(("stop", rid)))
        info = prov.restart("r7")
        assert calls == [("stop", "r7"), ("spawn", "r7")]
        assert info["replica_id"] == "r7"
        assert info["endpoint"] == "http://x"

    def test_base_provisioner_is_abstract(self):
        with pytest.raises(NotImplementedError):
            ReplicaProvisioner().spawn("r0")
        with pytest.raises(NotImplementedError):
            ReplicaProvisioner().stop("r0")
