"""Test config: force an 8-device virtual CPU platform before any backend
initialization.

This is the 'CPU build as fake device' discipline from the reference
(paddle/cuda/include/stub/* let everything unit-test without GPUs): the CPU
XLA backend is the universal fake TPU, and 8 virtual devices exercise every
mesh/sharding path without hardware. The environment may pin JAX_PLATFORMS
to a TPU plugin via sitecustomize, so we override via jax.config (which wins
as long as no computation ran yet).
"""

import os

if os.environ.get("PADDLE_TPU_SMOKE"):
    # real-hardware lane (tests/test_tpu_smoke.py): keep the default
    # TPU backend instead of the virtual CPU mesh
    import jax  # noqa: E402
else:
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()
    os.environ["JAX_PLATFORMS"] = "cpu"

    import jax  # noqa: E402

    jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import pytest  # noqa: E402


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: long-running tests (deselected in tier-1)")
    config.addinivalue_line(
        "markers",
        "chaos(timeout=120): fault-injection chaos tests — faulthandler "
        "dumps all thread stacks if the test exceeds its timeout, so a "
        "deadlocked serving test prints stacks instead of dying to a "
        "silent `timeout -k` kill")


@pytest.fixture(autouse=True)
def _chaos_faulthandler(request):
    """Dump-on-timeout for @pytest.mark.chaos: if a chaos test wedges
    (a serving deadlock, a stuck worker join), every thread's stack is
    printed to stderr before the outer timeout kills the run."""
    marker = request.node.get_closest_marker("chaos")
    if marker is None:
        yield
        return
    import faulthandler
    timeout = float(marker.kwargs.get("timeout", 120.0))
    faulthandler.dump_traceback_later(timeout, exit=False)
    try:
        yield
    finally:
        faulthandler.cancel_dump_traceback_later()


@pytest.fixture(autouse=True)
def _reset_layer_names():
    """Fresh auto-name counters per test so graphs don't collide."""
    from paddle_tpu.core import registry
    registry.reset_name_counters()
    yield


@pytest.fixture
def rng():
    return np.random.RandomState(0)
