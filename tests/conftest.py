"""Test config: force an 8-device virtual CPU platform before any backend
initialization.

This is the 'CPU build as fake device' discipline from the reference
(paddle/cuda/include/stub/* let everything unit-test without GPUs): the CPU
XLA backend is the universal fake TPU, and 8 virtual devices exercise every
mesh/sharding path without hardware. The environment may pin JAX_PLATFORMS
to a TPU plugin via sitecustomize, so we override via jax.config (which wins
as long as no computation ran yet).
"""

import os

if os.environ.get("PADDLE_TPU_SMOKE"):
    # real-hardware lane (tests/test_tpu_smoke.py): keep the default
    # TPU backend instead of the virtual CPU mesh
    import jax  # noqa: E402
else:
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()
    os.environ["JAX_PLATFORMS"] = "cpu"

    import jax  # noqa: E402

    jax.config.update("jax_platforms", "cpu")

    # Persistent XLA compile cache: scores of tests rebuild the same tiny
    # models, and every fresh jit wrapper re-pays the identical XLA
    # compile — the dominant share of tier-1 wall clock. The disk cache
    # is keyed by HLO hash, so it dedupes within one run as well as
    # across runs. Cache HITS still log "Compiling <name>", so
    # compile_watch / recompile_budget counts are unaffected.
    # Deliberately process-local (jax.config, NOT env): the SIGKILL
    # chaos tests time their kills against a worker subprocess's
    # compile-dominated startup, so spawned workers must stay cold.
    # PADDLE_TPU_COMPILE_CACHE=0 disables; any other value overrides
    # the directory. The knobs live in paddle_tpu/artifacts/cache.py
    # (the productionized seam — train/serve/router/soak wire the
    # same grammar via --compile_cache).
    from paddle_tpu.artifacts import cache as _compile_cache

    _compile_cache.enable_from_env()

import numpy as np  # noqa: E402
import pytest  # noqa: E402


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: long-running tests (deselected in tier-1)")
    config.addinivalue_line(
        "markers",
        "chaos(timeout=120): fault-injection chaos tests — faulthandler "
        "dumps all thread stacks if the test exceeds its timeout, so a "
        "deadlocked serving test prints stacks instead of dying to a "
        "silent `timeout -k` kill")
    config.addinivalue_line(
        "markers",
        "recompile_budget(max_compiles=4): enforce an XLA compile "
        "budget — the test fails if any single jitted function "
        "compiles more than max_compiles times while it runs "
        "(paddle_tpu/analysis/sanitizer.py; docs/static_analysis.md)")
    config.addinivalue_line(
        "markers",
        "lockdep_allow_inversion: this test deliberately provokes a "
        "lock-order inversion (chaos/deadlock-witness tests) — skip "
        "the autouse zero-inversions assertion "
        "(paddle_tpu/analysis/lockdep.py)")
    config.addinivalue_line(
        "markers",
        "soak: the long soak acceptance lane (paddle_tpu/loadgen) — "
        "select with `-m soak`; soak-marked tests are implicitly "
        "`slow` so tier-1's `-m 'not slow'` never runs them (the "
        "bounded smoke slice in tests/test_soak.py stays tier-1)")
    config.addinivalue_line(
        "markers",
        "protocol_violation_expected: this test deliberately breaks a "
        "declared event protocol (orphan terminals etc.) — skip the "
        "autouse zero-violations assertion of the protocol witness "
        "(paddle_tpu/obs/protocol.py; docs/observability.md "
        "'Protocol contracts')")


def pytest_collection_modifyitems(config, items):
    """Every soak-marked test is implicitly slow: `-m soak` selects
    the lane, tier-1's `-m 'not slow'` excludes it — one marker, both
    behaviors."""
    for item in items:
        if item.get_closest_marker("soak") is not None:
            item.add_marker(pytest.mark.slow)


@pytest.fixture(autouse=True)
def _chaos_faulthandler(request):
    """Dump-on-timeout for @pytest.mark.chaos: if a chaos test wedges
    (a serving deadlock, a stuck worker join), every thread's stack is
    printed to stderr before the outer timeout kills the run."""
    marker = request.node.get_closest_marker("chaos")
    if marker is None:
        yield
        return
    import faulthandler
    timeout = float(marker.kwargs.get("timeout", 120.0))
    faulthandler.dump_traceback_later(timeout, exit=False)
    try:
        yield
    finally:
        faulthandler.cancel_dump_traceback_later()


@pytest.hookimpl(tryfirst=True, hookwrapper=True)
def pytest_runtest_makereport(item, call):
    """Expose each phase's outcome to fixtures (the thread-leak check
    only fires on tests that PASSED — a failing test's traceback can
    legitimately pin an abandoned generator alive)."""
    outcome = yield
    rep = outcome.get_result()
    setattr(item, "rep_" + rep.when, rep)


@pytest.fixture(autouse=True)
def _no_pipeline_thread_leaks(request):
    """Fail any test that leaks a data-pipeline thread (buffered /
    xmap_readers / supervised / the trainer's feed prefetcher — all
    named 'pt-data-*') or a serving worker ('pt-serve-*'), so a
    shutdown regression is caught by CI as a failure instead of as a
    hang. The grace window lets just-closed generators' threads observe
    their stop events (they poll every 0.1s) and drained serving
    workers observe _stopping (they poll every 0.2s)."""
    import gc
    import threading
    import time

    def leaked():
        from paddle_tpu.reader.pipeline import THREAD_PREFIX
        prefixes = (THREAD_PREFIX, "pt-serve", "pt-obs", "pt-coord",
                    "pt-embed", "pt-loadgen")
        return [t for t in threading.enumerate()
                if t.is_alive() and t.name.startswith(prefixes)]

    yield
    rep = getattr(request.node, "rep_call", None)
    if rep is None or not rep.passed:
        return
    if leaked():
        gc.collect()          # close abandoned generators deterministically
    deadline = time.time() + 5.0
    while leaked() and time.time() < deadline:
        time.sleep(0.05)
    left = leaked()
    assert not left, (
        f"test leaked {len(left)} pipeline/serving thread(s): "
        f"{[t.name for t in left]} — a reader or InferenceServer was "
        "abandoned without its fill/worker threads shutting down "
        "(reader/pipeline.py / serving/server.py lifecycle contract)")


@pytest.fixture(autouse=True)
def _recompile_budget(request):
    """@pytest.mark.recompile_budget(max_compiles=N): count XLA
    compilations per jitted function while the test runs and FAIL it
    (at teardown, only when the test body passed) if any one function
    compiled more than N times — the runtime twin of ptlint R2
    (analysis/sanitizer.py). The watch is exposed as
    ``request.node._compile_watch`` for tests that want the counts."""
    marker = request.node.get_closest_marker("recompile_budget")
    if marker is None:
        yield
        return
    from paddle_tpu.analysis.sanitizer import compile_watch
    budget = int(marker.kwargs.get(
        "max_compiles", marker.args[0] if marker.args else 4))
    with compile_watch() as watch:
        request.node._compile_watch = watch
        yield
    rep = getattr(request.node, "rep_call", None)
    if rep is not None and rep.passed:
        watch.check(budget)


@pytest.fixture(autouse=True, scope="module")
def _drop_xla_executables():
    """Release each module's in-memory XLA executables at teardown.

    Every compiled executable holds mmap'd code pages; across ~1000
    tests the suite's map count climbs toward the kernel's
    vm.max_map_count ceiling (65530 default), and crossing it turns
    later native allocations — thread-stack guard pages included —
    into segfaults deep in XLA or pthread_create. Clearing per module
    is nearly free: the persistent disk compile cache above dedupes
    the recompiles, so only re-tracing is paid. The warm-start
    plane's in-process executable cache pins loaded executables the
    same way, so it drops with them."""
    yield
    import gc
    from paddle_tpu.artifacts import EXECUTABLES
    EXECUTABLES.clear()
    jax.clear_caches()
    gc.collect()


@pytest.fixture(autouse=True)
def _reset_layer_names():
    """Fresh auto-name counters per test so graphs don't collide."""
    from paddle_tpu.core import registry
    registry.reset_name_counters()
    yield


@pytest.fixture(autouse=True)
def _reset_observability():
    """Zero the observability surfaces BEFORE each test — metrics
    registry values, event-journal ring + sink, tracer, and the
    utils/stats global counters/timers — so no test reads another
    test's metric bleed (paddle_tpu/obs; counter hygiene contract in
    docs/observability.md)."""
    from paddle_tpu.obs import reset_all
    reset_all()
    yield


@pytest.fixture(autouse=True)
def _lockdep_witness(request):
    """Deadlock witness for tier-1: every test runs under the lockdep
    runtime (paddle_tpu/analysis/lockdep.py — instrumented locks feed a
    global acquisition-order graph) and FAILS at teardown if any
    lock-order inversion was observed, unless it is marked
    ``lockdep_allow_inversion`` (chaos tests that provoke one on
    purpose). The graph is reset per-test by _reset_observability
    (obs.reset_all -> LOCKDEP.reset), so an inversion is attributed to
    the test that created it."""
    yield
    if request.node.get_closest_marker("lockdep_allow_inversion"):
        return
    rep = getattr(request.node, "rep_call", None)
    if rep is None or not rep.passed:
        return
    from paddle_tpu.analysis.lockdep import LOCKDEP
    count = LOCKDEP.inversion_count
    assert count == 0, (
        f"lockdep witness observed {count} lock-order inversion(s) "
        "during this test — two locks were taken in opposite orders "
        "on different paths (one interleaving deadlocks). The journal "
        "holds a lockdep/inversion record with both stacks; see "
        "docs/static_analysis.md 'Lock discipline'")


@pytest.fixture(autouse=True)
def _protocol_witness(request):
    """Protocol witness for tier-1: every test runs under the
    declared-protocol state machines (paddle_tpu/obs/protocol.py — a
    journal observer advancing obs.catalog.PROTOCOLS per correlation
    key) and FAILS at teardown if any machine was BROKEN (a terminal
    for a key never started), unless marked
    ``protocol_violation_expected``. Machines merely left open are NOT
    violations here — a SIGKILL'd replica legitimately leaves a hop
    that never settles (tests/test_fleet_faults.py); only an explicit
    ``WITNESS.finalize()`` reports those. State is reset per-test by
    _reset_observability (obs.reset_all -> WITNESS.reset)."""
    yield
    if request.node.get_closest_marker("protocol_violation_expected"):
        return
    rep = getattr(request.node, "rep_call", None)
    if rep is None or not rep.passed:
        return
    from paddle_tpu.obs import WITNESS
    count = WITNESS.violation_count
    assert count == 0, (
        f"protocol witness observed {count} protocol violation(s) "
        "during this test — a declared event machine "
        "(obs/catalog.py PROTOCOLS) saw a terminal for a key it never "
        "tracked. The journal holds a protocol/violation record with "
        "the offending chain; see docs/observability.md "
        "'Protocol contracts'")


@pytest.fixture
def rng():
    return np.random.RandomState(0)
