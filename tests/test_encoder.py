"""Bidirectional encoder + masked-LM objective (models.transformer_encoder):
per-token cost weighting, bidirectionality, and training descent.
"""

import jax
import jax.numpy as jnp
import numpy as np

import paddle_tpu as paddle
from paddle_tpu.core import registry
from paddle_tpu.core.sequence import SequenceBatch
from paddle_tpu.models import transformer_encoder

V, D, H, L, T, B = 50, 32, 4, 2, 12, 4


def _spec():
    registry.reset_name_counters()
    paddle.init(seed=0)
    return transformer_encoder(vocab_size=V, d_model=D, n_heads=H,
                               n_layers=L, d_ff=2 * D, max_len=T)


def _feed(rng, mask_frac=0.3):
    ids = rng.randint(1, V, (B, T)).astype("int32")
    mask = (rng.rand(B, T) < mask_frac)
    mask[:, 0] = True                       # at least one masked slot
    corrupted = np.where(mask, 0, ids).astype("int32")   # 0 = [MASK]
    lens = np.full((B,), T, np.int32)

    def sb(a):
        return SequenceBatch(jnp.asarray(a), jnp.asarray(lens))

    w = mask.astype("float32")[..., None]
    return ({"enc_tokens": sb(corrupted), "enc_positions": sb(
                np.tile(np.arange(T, dtype="int32"), (B, 1))),
             "enc_labels": sb(ids), "enc_mlm_weight": sb(w)},
            ids, mask)


class TestMaskedLM:
    def test_only_masked_positions_contribute(self):
        """The cost with the 0/1 weight must equal a hand-computed CE
        summed over exactly the masked positions."""
        spec = _spec()
        topo = paddle.Topology(spec.cost, extra_outputs=[spec.output])
        params = topo.init_params(jax.random.PRNGKey(1))
        feed, ids, mask = _feed(np.random.RandomState(0))
        outs, _ = topo.forward(params, topo.init_state(), feed,
                               mode="test")
        cost = np.asarray(outs[spec.cost.name])          # [B]
        probs = np.asarray(outs[spec.output.name].data,
                           np.float64)                   # [B,T,V]
        ce = -np.log(np.maximum(
            np.take_along_axis(probs, ids[..., None], axis=-1)[..., 0],
            1e-10))                                      # [B,T]
        want = (ce * mask).sum(axis=1)
        np.testing.assert_allclose(cost, want, rtol=2e-3, atol=1e-4)

    def test_zero_weight_means_zero_gradient(self):
        spec = _spec()
        # extra_outputs heeds the orphan-output warning: the cost graph
        # alone does not contain the declared probs head
        topo = paddle.Topology(spec.cost, extra_outputs=[spec.output])
        params = topo.init_params(jax.random.PRNGKey(1))
        feed, _, _ = _feed(np.random.RandomState(0))
        z = jax.tree_util.tree_map(jnp.zeros_like,
                                   feed["enc_mlm_weight"].data)
        feed["enc_mlm_weight"] = SequenceBatch(
            z, feed["enc_mlm_weight"].lengths)

        def loss(p):
            outs, _ = topo.forward(p, topo.init_state(), feed,
                                   mode="train", rng=jax.random.PRNGKey(0))
            return jnp.sum(outs[spec.cost.name])

        g = jax.grad(loss)(params)
        for name, v in g.items():
            assert float(jnp.max(jnp.abs(v))) == 0.0, name

    def test_attention_is_bidirectional(self):
        """Changing a LATER token must change an EARLIER position's
        probs — impossible under the LM's causal mask."""
        spec = _spec()
        topo = paddle.Topology(spec.output)
        params = topo.init_params(jax.random.PRNGKey(1))
        feed, ids, _ = _feed(np.random.RandomState(0))
        outs1, _ = topo.forward(params, topo.init_state(),
                                feed, mode="test")
        toks = np.asarray(feed["enc_tokens"].data).copy()
        toks[:, -1] = (toks[:, -1] + 7) % V
        feed2 = dict(feed)
        feed2["enc_tokens"] = SequenceBatch(jnp.asarray(toks),
                                            feed["enc_tokens"].lengths)
        outs2, _ = topo.forward(params, topo.init_state(),
                                feed2, mode="test")
        p1 = np.asarray(outs1[spec.output.name].data)
        p2 = np.asarray(outs2[spec.output.name].data)
        assert np.abs(p1[:, 0] - p2[:, 0]).max() > 1e-6

    def test_mlm_trains(self):
        spec = _spec()
        params = paddle.create_parameters(
            paddle.Topology(spec.cost, extra_outputs=[spec.output]))
        tr = paddle.SGD(cost=spec.cost, parameters=params,
                        extra_layers=[spec.output],
                        update_equation=paddle.optimizer.Adam(
                            learning_rate=2e-3))
        rng = np.random.RandomState(0)

        def reader():
            for _ in range(12):
                feed, _, _ = _feed(rng)
                yield [tuple(np.asarray(feed[k].data[i]) for k in
                             ("enc_tokens", "enc_positions",
                              "enc_labels", "enc_mlm_weight"))
                       for i in range(B)]

        losses = []
        tr.train(reader, num_passes=2,
                 event_handler=lambda e: losses.append(e.cost)
                 if isinstance(e, paddle.event.EndIteration) else None)
        assert np.isfinite(losses).all()
        assert np.mean(losses[-4:]) < np.mean(losses[:4]), losses


class TestClassifier:
    def test_classifier_trains_and_loads_mlm_trunk(self):
        """transformer_classifier: trains on sequence labels, and an
        MLM-pretrained trunk loads directly (shared param names)."""
        from paddle_tpu.models import transformer_classifier
        registry.reset_name_counters()
        paddle.init(seed=0)
        spec = transformer_classifier(vocab_size=V, num_classes=3,
                                      d_model=D, n_heads=H, n_layers=L,
                                      d_ff=2 * D, max_len=T, name="enc")
        params = paddle.create_parameters(paddle.Topology(spec.cost))
        tr = paddle.SGD(cost=spec.cost, parameters=params,
                        update_equation=paddle.optimizer.Adam(
                            learning_rate=2e-3),
                        extra_layers=spec.extra_layers)
        rng = np.random.RandomState(0)

        def reader():
            for _ in range(10):
                rows = []
                for _ in range(B):
                    ids = rng.randint(1, V, T).astype("int32")
                    # learnable signal: class = first token mod 3
                    rows.append((ids, np.arange(T, dtype="int32"),
                                 int(ids[0] % 3)))
                yield rows

        losses = []
        tr.train(reader, num_passes=3,
                 event_handler=lambda e: losses.append(e.cost)
                 if isinstance(e, paddle.event.EndIteration) else None)
        assert np.isfinite(losses).all()
        assert np.mean(losses[-4:]) < np.mean(losses[:4])

        # the MLM spec's trunk params are a subset with identical names
        registry.reset_name_counters()
        mlm = transformer_encoder(vocab_size=V, d_model=D, n_heads=H,
                                  n_layers=L, d_ff=2 * D, max_len=T,
                                  name="enc")
        mlm_names = set(paddle.Topology(
            mlm.cost, extra_outputs=[mlm.output]).param_specs)
        cls_names = set(paddle.Topology(spec.cost).param_specs)
        trunk = {n for n in mlm_names if "_head" not in n}
        assert trunk <= cls_names, sorted(trunk - cls_names)[:5]
