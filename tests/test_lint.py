"""Tier-1 lint gate: the repo must be ptlint-clean.

Runs the same analysis as `paddle_tpu lint` / tools/ptlint.py over the
paths configured in pyproject [tool.ptlint] (paddle_tpu/, tools/,
tests/) and fails on ANY non-baselined finding — so every future PR is
gated on the six JAX rules (docs/static_analysis.md). Also enforces
the hygiene of the escape hatches themselves: every inline suppression
carries a reason and every baseline entry a real justification (no
TODOs), and stale baseline entries (the finding was fixed) must be
deleted so they cannot mask a future regression.
"""

import os

from paddle_tpu.analysis.baseline import load_baseline
from paddle_tpu.analysis.runner import (format_findings, lint_paths,
                                        load_config)

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _result():
    # one lint sweep shared by the assertions below (module cache)
    if not hasattr(_result, "cached"):
        _result.cached = lint_paths(load_config(ROOT))
    return _result.cached


def test_configured_paths_cover_the_tree():
    cfg = load_config(ROOT)
    assert "paddle_tpu" in cfg.paths
    assert "tools" in cfg.paths
    assert "tests" in cfg.paths
    assert cfg.rules == ["R1", "R2", "R3", "R4", "R5", "R6", "R7",
                         "R8", "R9", "R10", "R11", "R12", "R13"]
    # the contract rules run with stale-entry reporting ON in the
    # full-repo sweep (pyproject [tool.ptlint.journal-contract] etc.)
    assert cfg.rule_options.get("R11", {}).get("stale") is True
    assert cfg.rule_options.get("R12", {}).get("stale") is True


def test_repo_is_lint_clean():
    res = _result()
    assert res.files > 100, (
        f"ptlint only saw {res.files} files — the [tool.ptlint] paths "
        "are misconfigured")
    assert not res.errors, "\n".join(res.errors)
    assert not res.new, (
        f"{len(res.new)} new ptlint finding(s) — fix them, or "
        "suppress with '# ptlint: disable=RULE(reason)' (see "
        "docs/static_analysis.md):\n"
        + "\n".join(f.format() for f in res.new))


def test_contract_rules_clean_repo_wide():
    """The ptproto gate: zero non-baselined R11/R12/R13 findings over
    the whole tree, stale catalog entries INCLUDED — emit sites,
    metric registrations, docs/observability.md tables and
    obs/catalog.py must all agree (docs/static_analysis.md 'Event &
    protocol contracts')."""
    from paddle_tpu.analysis.runner import _contracts_view
    res = _contracts_view(load_config(ROOT), use_baseline=True)
    assert not res.errors, "\n".join(res.errors)
    assert not res.new, (
        f"{len(res.new)} contract finding(s) — the catalog, the code "
        "and the docs drifted apart:\n"
        + "\n".join(f.format() for f in res.new))
    assert not res.stale_baseline


def test_no_stale_baseline_entries():
    res = _result()
    assert not res.stale_baseline, (
        "baseline entries whose finding no longer exists — delete "
        "them from tools/ptlint_baseline.json so they cannot mask a "
        "future regression:\n"
        + "\n".join(f"{e['rule']} {e['path']}: {e['source'][:70]}"
                    for e in res.stale_baseline))


def test_every_suppression_has_a_reason():
    res = _result()
    bare = [f.format() for f, reason in res.suppressed if not reason]
    assert not bare, (
        "suppressions without a reason — write "
        "'# ptlint: disable=RULE(why it is safe)':\n" + "\n".join(bare))


def test_every_baseline_entry_is_justified():
    entries = load_baseline(os.path.join(ROOT,
                                         "tools/ptlint_baseline.json"))
    bad = [e for e in entries
           if not e["why"].strip() or "TODO" in e["why"]]
    assert not bad, (
        "baseline entries need a real one-line justification:\n"
        + "\n".join(f"{e['rule']} {e['path']}: {e['why']!r}"
                    for e in bad))


def test_github_format_renders_annotations(tmp_path):
    """--format=github output is the GitHub Actions annotation
    protocol (CI renders findings inline on the PR diff)."""
    bad = tmp_path / "hot.py"
    bad.write_text(
        "import jax\n"
        "def train(xs):\n"
        "    for x in xs:\n"
        "        jax.jit(lambda v: v)(x)\n")
    cfg = load_config(ROOT)
    cfg.paths = [str(bad)]
    cfg.baseline = ""
    # R2 is the finding under test; the contract rules' stale sweep
    # (R11/R12) would add repo-level findings to this one-file run
    cfg.rules = ["R2"]
    res = lint_paths(cfg, use_baseline=False)
    assert len(res.new) == 1
    out = format_findings(res, "github")
    assert out.startswith("::error file=")
    assert ",line=4," in out
    assert "R2[recompile]" in out


def test_github_format_renders_stale_baseline_as_warning(tmp_path):
    """A baseline entry whose finding was fixed renders as a
    ``::warning`` annotation (hygiene debt) anchored to the surviving
    source line — new findings stay ``::error``."""
    from paddle_tpu.analysis.baseline import write_baseline

    bad = tmp_path / "hot.py"
    bad.write_text(
        "import jax\n"
        "def train(xs):\n"
        "    for x in xs:\n"
        "        jax.jit(lambda v: v)(x)\n")
    cfg = load_config(ROOT)
    cfg.paths = [str(bad)]
    cfg.baseline = str(tmp_path / "baseline.json")
    cfg.rules = ["R2"]
    res = lint_paths(cfg, use_baseline=False)
    assert len(res.new) == 1
    write_baseline(cfg.baseline, res.new, [])

    # fix the finding but keep the identical source text at module
    # level, so the stale entry can still be anchored to a line
    bad.write_text(
        "import jax\n"
        "x = 1\n"
        "jax.jit(lambda v: v)(x)\n")
    res2 = lint_paths(cfg)
    assert not res2.new and res2.stale_baseline
    out = format_findings(res2, "github", root=str(tmp_path))
    assert out.startswith("::warning file=")
    assert ",line=3" in out
    assert "stale ptlint baseline entry" in out
