"""Ring/Ulysses attention vs the single-chip reference on the virtual
8-device mesh (the CPU-vs-TPU parity discipline of test_matrixCompare)."""

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from paddle_tpu.parallel import create_mesh, SP_AXIS
from paddle_tpu.parallel import sequence_parallel as sp


def _qkv(b=2, t=32, h=4, d=8, seed=0):
    rng = np.random.RandomState(seed)
    mk = lambda: jnp.asarray(rng.randn(b, t, h, d).astype("float32"))
    return mk(), mk(), mk()


@pytest.fixture(scope="module")
def mesh():
    return create_mesh([(SP_AXIS, 8)])


class TestRingAttention:
    def test_matches_full_attention(self, mesh):
        q, k, v = _qkv()
        ref = sp.attention(q, k, v)
        out = sp.ring_attention(q, k, v, mesh)
        np.testing.assert_allclose(np.asarray(ref), np.asarray(out),
                                   rtol=2e-5, atol=2e-5)

    def test_causal(self, mesh):
        q, k, v = _qkv(seed=1)
        b, t = q.shape[:2]
        cm = jnp.tril(jnp.ones((t, t), bool))[None].repeat(b, 0)
        ref = sp.attention(q, k, v, mask=cm)
        out = sp.ring_attention(q, k, v, mesh, causal=True)
        np.testing.assert_allclose(np.asarray(ref), np.asarray(out),
                                   rtol=2e-5, atol=2e-5)

    def test_ragged_lengths(self, mesh):
        q, k, v = _qkv(seed=2)
        b, t = q.shape[:2]
        lengths = jnp.asarray([17, 32], jnp.int32)
        valid = jnp.arange(t)[None, :] < lengths[:, None]
        mask = jnp.broadcast_to(valid[:, None, :], (b, t, t))
        ref = sp.attention(q, k, v, mask=mask)
        ref = jnp.where(valid[:, :, None, None], ref, 0.0)
        out = sp.ring_attention(q, k, v, mesh, lengths=lengths)
        np.testing.assert_allclose(np.asarray(ref), np.asarray(out),
                                   rtol=2e-5, atol=2e-5)

    def test_differentiable(self, mesh):
        q, k, v = _qkv(seed=3, t=16)

        def loss_ring(q, k, v):
            return jnp.sum(sp.ring_attention(q, k, v, mesh, causal=True) ** 2)

        def loss_ref(q, k, v):
            t = q.shape[1]
            cm = jnp.tril(jnp.ones((t, t), bool))[None].repeat(q.shape[0], 0)
            return jnp.sum(sp.attention(q, k, v, mask=cm) ** 2)

        g_ring = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
        g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b_ in zip(g_ring, g_ref):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                       rtol=3e-4, atol=3e-5)

    def test_inside_jit(self, mesh):
        q, k, v = _qkv(seed=4)

        @jax.jit
        def f(q, k, v):
            return sp.ring_attention(q, k, v, mesh)

        out = f(q, k, v)
        ref = sp.attention(q, k, v)
        np.testing.assert_allclose(np.asarray(ref), np.asarray(out),
                                   rtol=2e-5, atol=2e-5)


class TestUlyssesAttention:
    def test_matches_full_attention(self, mesh):
        q, k, v = _qkv(t=32, h=8)
        ref = sp.attention(q, k, v)
        out = sp.ulysses_attention(q, k, v, mesh)
        np.testing.assert_allclose(np.asarray(ref), np.asarray(out),
                                   rtol=2e-5, atol=2e-5)

    def test_causal_ragged(self, mesh):
        q, k, v = _qkv(t=32, h=8, seed=5)
        b, t = q.shape[:2]
        lengths = jnp.asarray([20, 32], jnp.int32)
        valid = jnp.arange(t)[None, :] < lengths[:, None]
        mask = jnp.logical_and(
            jnp.broadcast_to(valid[:, None, :], (b, t, t)),
            jnp.tril(jnp.ones((t, t), bool))[None])
        ref = sp.attention(q, k, v, mask=mask)
        # contract shared with ring_attention: padded query rows are zeroed
        ref = jnp.where(valid[:, :, None, None], ref, 0.0)
        out = sp.ulysses_attention(q, k, v, mesh, lengths=lengths,
                                   causal=True)
        np.testing.assert_allclose(np.asarray(ref), np.asarray(out),
                                   rtol=2e-5, atol=2e-5)


class TestTransformerOverSpMesh:
    def test_transformer_lm_trains_on_dp_sp_mesh(self):
        """The full transformer LM over a dp2 x sp2 mesh: the attention
        layer auto-engages ring attention across sp; two steps must run
        and the loss must be finite and match the meshless run's first
        loss (same params, same data)."""
        import paddle_tpu as paddle
        from paddle_tpu import models
        from paddle_tpu.core import registry
        from paddle_tpu.parallel import create_mesh, DP_AXIS, SP_AXIS

        def build():
            registry.reset_name_counters()
            paddle.init(use_tpu=False, seed=0)
            spec = models.transformer_lm(vocab_size=50, d_model=32,
                                         n_heads=4, n_layers=2, d_ff=64,
                                         max_len=16)
            params = paddle.create_parameters(
                paddle.Topology(spec.cost, extra_outputs=[spec.output]))
            return spec, params

        rng = np.random.RandomState(0)

        def batch(b=4, T=8):
            rows = []
            for _ in range(b):
                ids = rng.randint(0, 50, T + 1)
                rows.append(([int(v) for v in ids[:T]], list(range(T)),
                             [int(v) for v in ids[1:]]))
            return rows

        data = batch()
        losses = {}
        for name, mesh in [("single", None),
                           ("dp2sp2", create_mesh([(DP_AXIS, 2),
                                                   (SP_AXIS, 2)]))]:
            spec, params = build()
            tr = paddle.SGD(cost=spec.cost, parameters=params,
                            extra_layers=[spec.output],
                            update_equation=paddle.optimizer.Adam(
                                learning_rate=1e-3), mesh=mesh)
            loss, _ = tr.train_batch(list(data))
            losses[name] = loss
        assert np.isfinite(list(losses.values())).all(), losses
        np.testing.assert_allclose(losses["dp2sp2"], losses["single"],
                                   rtol=2e-4)
