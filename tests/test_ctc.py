"""CTC loss goldens: brute-force alignment enumeration, hand-checked
lattices, and parity with optax's independent implementation.

Reference: LinearChainCTC.cpp:86-200 (the lattice this reimplements) and
test_CTCLayer.cpp (the reference checks its CTC against alternate
implementations the same way).
"""

import itertools

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.ops.ctc import ctc_loss


def collapse(path, blank):
    """B(pi): merge repeats then strip blanks."""
    out = []
    prev = None
    for s in path:
        if s != prev:
            if s != blank:
                out.append(s)
            prev = s
    return tuple(out)


def brute_force_nll(logits, label, blank):
    """-log sum over all alignments that collapse to `label`."""
    T, C = logits.shape
    p = np.exp(logits - np.log(np.exp(logits).sum(-1, keepdims=True)))
    total = 0.0
    for path in itertools.product(range(C), repeat=T):
        if collapse(path, blank) == tuple(label):
            total += np.prod([p[t, path[t]] for t in range(T)])
    return -np.log(total)


def run_ctc(logits_rows, labels_rows, blank=0):
    """Pad ragged per-sample logits/labels into the batched call."""
    b = len(logits_rows)
    T = max(r.shape[0] for r in logits_rows)
    C = logits_rows[0].shape[1]
    U = max((len(l) for l in labels_rows), default=1) or 1
    logits = np.zeros((b, T, C), np.float32)
    lpad = np.ones((b, T), np.float32)
    labels = np.zeros((b, U), np.int32)
    labpad = np.ones((b, U), np.float32)
    for i, (lr, lab) in enumerate(zip(logits_rows, labels_rows)):
        logits[i, :lr.shape[0]] = lr
        lpad[i, :lr.shape[0]] = 0.0
        labels[i, :len(lab)] = lab
        labpad[i, :len(lab)] = 0.0
    return np.asarray(ctc_loss(jnp.asarray(logits), jnp.asarray(lpad),
                               jnp.asarray(labels), jnp.asarray(labpad),
                               blank_id=blank))


class TestBruteForceGoldens:
    @pytest.mark.parametrize("T,C,label,blank", [
        (2, 2, [1], 0),
        (3, 3, [1, 2], 0),
        (4, 3, [1, 1], 0),          # repeated label needs a blank between
        (4, 3, [2], 2 - 1),         # nonzero blank id
        (3, 3, [0, 1], 2),          # blank = last class (ctc default)
        (5, 2, [1, 1, 1], 0),       # tight fit: single feasible alignment
    ])
    def test_matches_alignment_enumeration(self, T, C, label, blank):
        rng = np.random.RandomState(hash((T, C, blank)) % 2**31)
        logits = rng.randn(T, C).astype(np.float32)
        want = brute_force_nll(logits, label, blank)
        got = run_ctc([logits], [label], blank)[0]
        np.testing.assert_allclose(got, want, rtol=1e-4)

    def test_impossible_label_is_inf(self):
        # T=1 cannot emit two labels
        logits = np.zeros((1, 3), np.float32)
        got = run_ctc([logits], [[1, 2]], 0)[0]
        assert got > 1e10

    def test_hand_computed_uniform_lattice(self):
        # T=2, C=2, uniform probs (each 0.5), label [1], blank 0:
        # alignments: (1,1), (0,1), (1,0) -> 3 * 0.25
        logits = np.zeros((2, 2), np.float32)
        got = run_ctc([logits], [[1]], 0)[0]
        np.testing.assert_allclose(got, -np.log(0.75), rtol=1e-5)


class TestOptaxParity:
    def test_random_batch_matches_optax(self):
        optax = pytest.importorskip("optax")
        rng = np.random.RandomState(0)
        b, T, C, U = 4, 12, 7, 4
        logits = rng.randn(b, T, C).astype(np.float32)
        lpad = np.zeros((b, T), np.float32)
        lpad[1, 9:] = 1.0
        lpad[3, 6:] = 1.0
        labels = rng.randint(1, C, (b, U)).astype(np.int32)
        labpad = np.zeros((b, U), np.float32)
        labpad[0, 2:] = 1.0
        labpad[3, 1:] = 1.0
        args = (jnp.asarray(logits), jnp.asarray(lpad), jnp.asarray(labels),
                jnp.asarray(labpad))
        ours = np.asarray(ctc_loss(*args, blank_id=0))
        theirs = np.asarray(optax.ctc_loss(*args, blank_id=0))
        np.testing.assert_allclose(ours, theirs, rtol=1e-4, atol=1e-4)

    def test_gradients_match_optax(self):
        optax = pytest.importorskip("optax")
        rng = np.random.RandomState(1)
        b, T, C, U = 2, 6, 4, 2
        logits = jnp.asarray(rng.randn(b, T, C).astype(np.float32))
        lpad = jnp.zeros((b, T))
        labels = jnp.asarray(rng.randint(1, C, (b, U)).astype(np.int32))
        labpad = jnp.zeros((b, U))
        g_ours = jax.grad(lambda x: ctc_loss(
            x, lpad, labels, labpad, blank_id=0).sum())(logits)
        g_opt = jax.grad(lambda x: optax.ctc_loss(
            x, lpad, labels, labpad, blank_id=0).sum())(logits)
        np.testing.assert_allclose(np.asarray(g_ours), np.asarray(g_opt),
                                   rtol=1e-3, atol=1e-4)

    def test_empty_label(self):
        # all-blank path only
        logits = np.zeros((1, 3, 2), np.float32)
        got = run_ctc([logits[0]], [[]], 0)[0]
        np.testing.assert_allclose(got, -3 * np.log(0.5), rtol=1e-5)
