"""The million-user soak (ISSUE 17) — live-topology tests.

Two lanes off one harness (paddle_tpu/loadgen):

- **tier-1 smoke slice** (``TestSoakSmoke``, chaos-marked, NOT soak):
  a seconds-bounded mixed run over the full in-process estate with a
  reduced fault set, so every tier-1 run proves the soak machinery
  end to end without paying the acceptance duration;
- **the soak lane** (``@pytest.mark.soak``, select with ``-m soak``):
  the ISSUE's acceptance run — coordinator + 2 router planes + 2
  replicas + 2 embedding shards, all four fault families composed in
  ONE run, every verdict check green, and the executed fault schedule
  byte-equal to the recomputed plan (same seed, same schedule).

Both assert on the verdict REPORT, never on harness internals: the
report is a pure function of the journal, so these tests also pin
that every proof survives the journal round-trip.
"""

import pytest

from paddle_tpu.loadgen import plan_faults, run_soak


def _assert_verdict(report, families):
    checks = report["checks"]
    assert report["ok"], report
    # exactly-once settle fleet-wide, including the scripted
    # mid-stream client disconnects
    eo = checks["exactly_once"]
    assert eo["ok"] and eo["expected"] > 0
    assert eo["duplicates"] == {} and eo["lost"] == []
    # client-side latency SLOs were measured, not vacuous
    assert checks["latency_slo"]["ok"]
    assert checks["latency_slo"]["streams_measured"] > 0
    # no embedding gather served past its staleness bound
    assert checks["staleness"]["ok"]
    assert checks["staleness"]["stale_reads"] == 0
    # zero leaked KV pages / stuck slots on every survivor
    assert checks["kv_leaks"]["ok"]
    assert checks["kv_leaks"]["survivors"] > 0
    # every injected fault's chain reconstructs from the records
    fc = checks["fault_chains"]
    assert fc["ok"] and fc["injected"] == len(families)
    assert fc["families"] == sorted(families)
    # the CTR freshness loop closed (mixed workload always runs it)
    assert checks["ctr_loop"]["ok"]
    assert checks["ctr_loop"]["online_samples"] > 0


class TestSoakSmoke:
    """Tier-1's bounded slice: short duration, two fault families
    ((o) shard kill in the commit window + (p) replica kill
    mid-stream), full verdict."""

    # The soak topology writes every plane into ONE journal and the
    # (p) family kills a replica mid-stream, so the victim's late
    # serving/hop torn terminal can race the failover attempt's hop
    # machine for the same trace_id and orphan its settle (timing-
    # dependent). Exactly-once in the soak is proven by the verdict's
    # journal audit, not the live witness (docs/observability.md
    # "Protocol contracts").
    @pytest.mark.protocol_violation_expected
    @pytest.mark.chaos(timeout=240)
    def test_smoke_slice_passes_verdict(self):
        report = run_soak(seed=11, duration_s=4.0, workload="mixed",
                          families="po")
        _assert_verdict(report, "po")
        # the schedule the conductor executed IS the recomputed plan
        planned = plan_faults(11, 4.0, "po")
        assert [(f["family"], f["action"], f["target"])
                for f in report["faults"]] == \
            [(a.family, a.action, a.target) for a in planned]
        for f, a in zip(report["faults"], planned):
            assert f["at_s"] == pytest.approx(a.at_s, abs=1e-3)
            assert f["fired"]


class TestSoakAcceptance:
    """The acceptance run (`pytest -m soak`): all four fault families
    composed in one seeded run over the full topology."""

    @pytest.mark.protocol_violation_expected
    @pytest.mark.soak
    @pytest.mark.chaos(timeout=420)
    def test_full_soak_all_families(self):
        report = run_soak(seed=7, duration_s=10.0, workload="mixed",
                          families="pokq")
        _assert_verdict(report, "kopq")
        assert len(report["faults"]) >= 3        # ISSUE floor: >=3 families
        assert report["counts"]["chat"] > 10
        assert report["counts"]["ctr"] > 10
        # same seed -> identical fault schedule, replayed verbatim
        planned = plan_faults(7, 10.0, "pokq")
        assert [(f["family"], f["action"], f["target"])
                for f in report["faults"]] == \
            [(a.family, a.action, a.target) for a in planned]
        assert all(f["fired"] for f in report["faults"])

    @pytest.mark.protocol_violation_expected
    @pytest.mark.soak
    @pytest.mark.chaos(timeout=420)
    def test_chat_only_soak(self):
        """Chat-only workload: no CTR traffic means no ctr_loop check,
        but exactly-once + latency + KV integrity still prove out
        under the replica-kill and lease-lapse families."""
        report = run_soak(seed=23, duration_s=6.0, workload="chat",
                          families="pk", chat_rate=6.0)
        assert report["ok"], report
        assert "ctr_loop" not in report["checks"]
        assert report["checks"]["exactly_once"]["ok"]
        assert report["checks"]["fault_chains"]["injected"] == 2
