"""End-to-end training tests (mirrors paddle/trainer/tests
test_Trainer / test_TrainerOnePass: a few batches of a real config must
run and converge)."""

import io
import os

import numpy as np
import pytest

import paddle_tpu as paddle


def _mnist_like_net(dim=64, n_classes=10):
    img = paddle.layer.data("pixel", paddle.data_type.dense_vector(dim))
    h1 = paddle.layer.fc(img, size=32, act=paddle.activation.Relu())
    out = paddle.layer.fc(h1, size=n_classes,
                          act=paddle.activation.Softmax(), name="output")
    lbl = paddle.layer.data("label", paddle.data_type.integer_value(n_classes))
    cost = paddle.layer.classification_cost(out, lbl, name="cost")
    err = paddle.layer.classification_error(out, lbl, name="error")
    return cost, out, err


def _clustered_reader(n, dim, k, seed):
    from paddle_tpu.dataset import synthetic

    def reader():
        feats, labels = synthetic.class_clustered(n, dim, k, seed)
        for i in range(n):
            yield feats[i], int(labels[i])
    return reader


class TestSGDTrain:
    def test_converges_and_reports_metrics(self):
        paddle.init(use_tpu=False, seed=0)
        cost, out, err = _mnist_like_net()
        topo = paddle.Topology(cost)
        params = paddle.create_parameters(topo)
        opt = paddle.optimizer.Momentum(momentum=0.9, learning_rate=0.05)
        trainer = paddle.SGD(cost=cost, parameters=params,
                             update_equation=opt, extra_layers=[err])
        costs, errors = [], []

        def handler(e):
            if isinstance(e, paddle.event.EndIteration):
                costs.append(e.cost)
                errors.append(e.metrics["error"])

        reader = paddle.reader.batch(
            paddle.reader.shuffle(_clustered_reader(512, 64, 10, 7), 512,
                                  seed=1), 64)
        trainer.train(reader, num_passes=6, event_handler=handler)
        assert len(costs) == 48
        first, last = np.mean(costs[:4]), np.mean(costs[-4:])
        assert last < first * 0.5, f"did not converge: {first} -> {last}"
        assert np.mean(errors[-4:]) < 0.2

    def test_adam_and_test_eval(self):
        paddle.init(use_tpu=False)
        cost, out, err = _mnist_like_net()
        params = paddle.create_parameters(paddle.Topology(cost))
        trainer = paddle.SGD(cost=cost, parameters=params,
                             update_equation=paddle.optimizer.Adam(
                                 learning_rate=1e-2),
                             extra_layers=[err])
        reader = paddle.reader.batch(_clustered_reader(256, 64, 10, 3), 64)
        trainer.train(reader, num_passes=4)
        res = trainer.test(reader)
        assert res.cost < 1.0
        assert res.metrics["error"] < 0.3

    def test_partial_batch_and_checkpoint(self, tmp_path):
        paddle.init(use_tpu=False)
        cost, out, err = _mnist_like_net()
        params = paddle.create_parameters(paddle.Topology(cost))
        trainer = paddle.SGD(cost=cost, parameters=params,
                             update_equation=paddle.optimizer.Momentum(
                                 learning_rate=0.01))
        # 100 samples, batch 64 -> one partial batch of 36
        reader = paddle.reader.batch(_clustered_reader(100, 64, 10, 5), 64)
        trainer.train(reader, num_passes=1)
        trainer.save_pass(str(tmp_path), 0)
        assert (tmp_path / "pass-00000" / "params.tar").exists()
        with open(tmp_path / "pass-00000" / "params.tar", "rb") as f:
            loaded = paddle.Parameters.from_tar(f)
        for name in params.names():
            np.testing.assert_array_equal(params[name], loaded[name])

    def test_infer(self):
        paddle.init(use_tpu=False)
        cost, out, err = _mnist_like_net()
        params = paddle.create_parameters(paddle.Topology(cost))
        data = [(np.random.RandomState(0).randn(64).astype(np.float32),)]
        probs = paddle.infer(output_layer=out, parameters=params,
                             input=data * 5,
                             feeding={"pixel": 0})
        assert probs.shape == (5, 10)
        np.testing.assert_allclose(probs.sum(-1), np.ones(5), rtol=1e-4)

    def test_regression_uci(self):
        paddle.init(use_tpu=False)
        x = paddle.layer.data("x", paddle.data_type.dense_vector(13))
        y = paddle.layer.data("y", paddle.data_type.dense_vector(1))
        pred = paddle.layer.fc(x, size=1)
        cost = paddle.layer.mse_cost(pred, y)
        params = paddle.create_parameters(paddle.Topology(cost))
        trainer = paddle.SGD(cost=cost, parameters=params,
                             update_equation=paddle.optimizer.Momentum(
                                 learning_rate=0.01, momentum=0.9))
        from paddle_tpu.dataset import uci_housing
        costs = []

        def handler(e):
            if isinstance(e, paddle.event.EndIteration):
                costs.append(e.cost)

        trainer.train(paddle.reader.batch(uci_housing.train(), 32,
                                          drop_last=True),
                      num_passes=12, event_handler=handler)
        assert np.mean(costs[-3:]) < np.mean(costs[:3]) * 0.3


def test_debug_nans_flag_raises_at_source():
    """config.init(debug_nans=True) = the FPE-trap discipline
    (TrainerMain.cpp:49): NaN-producing math raises instead of propagating.

    Runs in a fresh subprocess: jax_debug_nans only instruments newly
    compiled executables, so a warm in-process compilation cache (from any
    earlier test) would defeat the trap and make this test order-dependent.
    """
    import subprocess
    import sys
    script = (
        "from paddle_tpu import config as cfg\n"
        "import jax.numpy as jnp\n"
        "cfg.init(debug_nans=True)\n"
        "assert cfg.global_config().debug_nans\n"
        "try:\n"
        "    jnp.log(jnp.zeros(())) * 0.0  # -inf * 0 -> nan, must trap\n"
        "except FloatingPointError:\n"
        "    print('TRAPPED')\n"
    )
    r = subprocess.run([sys.executable, "-c", script], capture_output=True,
                       text=True, timeout=300,
                       env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert "TRAPPED" in r.stdout, f"nan did not trap:\n{r.stdout}\n{r.stderr}"


def test_reader_exception_propagates_through_prefetch():
    """The background feed-conversion thread must surface reader errors
    in the caller, not swallow them."""
    paddle.init(use_tpu=False, seed=0)
    from paddle_tpu.core import registry
    registry.reset_name_counters()
    x = paddle.layer.data("x", paddle.data_type.dense_vector(4))
    y = paddle.layer.data("y", paddle.data_type.integer_value(2))
    cost = paddle.layer.classification_cost(
        paddle.layer.fc(x, size=2, act=paddle.activation.Softmax()), y)
    params = paddle.create_parameters(paddle.Topology(cost))
    tr = paddle.SGD(cost=cost, parameters=params,
                    update_equation=paddle.optimizer.Adam(1e-3))
    rng = np.random.RandomState(0)

    def bad_reader():
        yield [(rng.randn(4).astype("float32"), 1) for _ in range(8)]
        raise RuntimeError("reader blew up")

    with pytest.raises(RuntimeError, match="reader blew up"):
        tr.train(bad_reader, num_passes=1, event_handler=lambda e: None)


def test_stale_bias_in_loaded_table_warns():
    """A checkpoint carrying X.wbias for a layer the topology builds
    bias-free must warn at SGD bind time: training ignores the entry but
    raw-table inference paths may still apply it (silent divergence)."""
    paddle.init(use_tpu=False, seed=0)
    from paddle_tpu.core import registry
    registry.reset_name_counters()
    x = paddle.layer.data("x", paddle.data_type.dense_vector(4))
    y = paddle.layer.data("y", paddle.data_type.integer_value(2))
    logits = paddle.layer.fc(x, size=2, bias_attr=False, name="hd")
    cost = paddle.layer.classification_cost(
        paddle.layer.addto([logits], act=paddle.activation.Softmax()), y)
    params = paddle.create_parameters(paddle.Topology(cost))
    import jax.numpy as jnp
    params.raw["_hd.wbias"] = jnp.zeros((2,), jnp.float32)
    with pytest.warns(UserWarning, match="bias entries.*_hd.wbias"):
        paddle.SGD(cost=cost, parameters=params,
                   update_equation=paddle.optimizer.Adam(1e-3))
    # params for layers absent from the topology entirely stay silent
    registry.reset_name_counters()
    params2 = paddle.create_parameters(paddle.Topology(cost))
    params2.raw["_other_layer.wbias"] = jnp.zeros((2,), jnp.float32)
    import warnings as _w
    with _w.catch_warnings():
        _w.simplefilter("error")
        paddle.SGD(cost=cost, parameters=params2,
                   update_equation=paddle.optimizer.Adam(1e-3))
