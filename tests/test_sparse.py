"""Row-sparse embedding path tests (SparseRowMatrix / prefetch parity —
math/SparseRowMatrix.h, MultiGradientMachine.h:99-166).

The contract: tables marked ParamAttr(sparse=True) train through a
prefetched row block — gradients and optimizer updates touch only the
batch's unique ids. Momentum carries an EXACT catch-up (sparse == dense
bit-for-tolerance); Adam is lazy (moments decay on touch)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.core.registry import ParamAttr
from paddle_tpu.ops import embedding as emb_ops


class TestRowOps:
    def test_touched_rows_unique_sorted_sentinel(self):
        table = jnp.arange(20.0).reshape(10, 2)
        ids = jnp.array([[3, 7], [3, 1]])
        uids, rows = emb_ops.touched_rows(table, ids)
        assert uids.shape == (4,)                       # static: ids.size
        np.testing.assert_array_equal(np.asarray(uids), [1, 3, 7, 10])
        np.testing.assert_array_equal(np.asarray(rows[:3]),
                                      np.asarray(table)[[1, 3, 7]])

    def test_row_sub_lookup_matches_dense(self):
        rng = np.random.RandomState(0)
        table = jnp.asarray(rng.randn(50, 8).astype("float32"))
        ids = jnp.asarray(rng.randint(0, 50, (4, 6)).astype("int32"))
        uids, rows = emb_ops.touched_rows(table, ids)
        got = emb_ops.row_sub_lookup(uids, rows, ids, 50)
        want = emb_ops.embedding_lookup(table, ids)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want))

    def test_row_sub_lookup_grad_is_row_shaped(self):
        table = jnp.ones((100, 4))
        ids = jnp.array([2, 2, 5])
        uids, rows = emb_ops.touched_rows(table, ids)

        def loss(r):
            return jnp.sum(emb_ops.row_sub_lookup(uids, r, ids, 100) ** 2)

        g = jax.grad(loss)(rows)
        assert g.shape == rows.shape                    # [k, emb], not [V, emb]
        # duplicated id 2 accumulates both occurrences on its single row
        pos2 = int(np.searchsorted(np.asarray(uids), 2))
        np.testing.assert_allclose(np.asarray(g[pos2]), 4.0)


def _emb_model(vocab, emb, sparse):
    ids = paddle.layer.data("ids", paddle.data_type.integer_value(vocab))
    lbl = paddle.layer.data("y", paddle.data_type.integer_value(2))
    e = paddle.layer.embedding(
        ids, size=emb, name="tbl",
        param_attr=ParamAttr(name="_tbl_w", sparse=sparse))
    out = paddle.layer.fc(e, size=2, act=paddle.activation.Softmax(),
                          name="out")
    cost = paddle.layer.classification_cost(out, lbl, name="cost")
    return cost


def _run(sparse, opt_fn, batches, vocab=32, emb=4, seed=7):
    from paddle_tpu.core import registry
    registry.reset_name_counters()
    paddle.init(seed=seed)
    cost = _emb_model(vocab, emb, sparse)
    params = paddle.create_parameters(paddle.Topology(cost))
    tr = paddle.SGD(cost=cost, parameters=params, update_equation=opt_fn())

    def reader():
        for ids, ys in batches:
            yield [(int(i), int(y)) for i, y in zip(ids, ys)]

    tr.train(reader, num_passes=1)
    return tr


class TestSparseDenseEquivalence:
    def _batches(self, n=6, b=8, vocab=32):
        rng = np.random.RandomState(3)
        # skewed ids so many rows go untouched for several steps
        return [(rng.randint(0, vocab // 2, b) * 2, rng.randint(0, 2, b))
                for _ in range(n)]

    def test_momentum_exact_match(self):
        batches = self._batches()
        mk = lambda: paddle.optimizer.Momentum(learning_rate=0.1,
                                               momentum=0.9)
        tr_d = _run(False, mk, batches)
        tr_s = _run(True, mk, batches)
        # untouched sparse rows are stale until fetched: compare the
        # MATERIALIZED view (what eval/export reads) against dense
        d = tr_d.optimizer.test_params(tr_d.parameters.raw, tr_d.opt_state)
        s = tr_s.optimizer.test_params(tr_s.parameters.raw, tr_s.opt_state)
        for k in d:
            np.testing.assert_allclose(np.asarray(d[k]), np.asarray(s[k]),
                                       rtol=1e-5, atol=1e-6, err_msg=k)

    def test_sgd_exact_match(self):
        batches = self._batches()
        mk = lambda: paddle.optimizer.Momentum(learning_rate=0.1)
        tr_d = _run(False, mk, batches)
        tr_s = _run(True, mk, batches)
        for k in tr_d.parameters.raw:
            np.testing.assert_allclose(
                np.asarray(tr_d.parameters.raw[k]),
                np.asarray(tr_s.parameters.raw[k]), rtol=1e-5, atol=1e-6)

    def test_adagrad_exact_match(self):
        batches = self._batches()
        mk = lambda: paddle.optimizer.AdaGrad(learning_rate=0.1)
        tr_d = _run(False, mk, batches)
        tr_s = _run(True, mk, batches)
        for k in tr_d.parameters.raw:
            np.testing.assert_allclose(
                np.asarray(tr_d.parameters.raw[k]),
                np.asarray(tr_s.parameters.raw[k]), rtol=1e-5, atol=1e-6)

    def test_adam_untouched_rows_frozen(self):
        vocab = 32
        # only even ids ever touched
        batches = [(np.arange(8) * 2, np.ones(8, np.int64))
                   for _ in range(4)]
        mk = lambda: paddle.optimizer.Adam(learning_rate=0.05)
        tr_s = _run(True, mk, batches, vocab=vocab)
        # untouched (odd) rows: moments and clock unchanged from init
        slots = tr_s.opt_state["slots"]["_tbl_w"]
        m = np.asarray(slots["m"])
        odd = np.arange(1, vocab, 2)
        np.testing.assert_array_equal(m[odd], 0.0)
        t_row = np.asarray(slots["_t"])
        assert (t_row[odd] == 0).all()
        assert (t_row[np.arange(0, 16, 2)] > 0).all()


class TestWideDeepE2E:
    def test_trains_and_touches_only_batch_rows(self):
        from paddle_tpu import models as M
        spec = M.wide_and_deep(sparse_dims=(200, 200, 50), dense_dim=4,
                               emb_size=8, hidden_sizes=(16,))
        params = paddle.create_parameters(paddle.Topology(spec.cost))
        tr = paddle.SGD(cost=spec.cost, parameters=params,
                        update_equation=paddle.optimizer.Adam(
                            learning_rate=5e-3))
        rng = np.random.RandomState(0)
        used = set()

        def reader():
            batch = []
            for _ in range(32):
                ids = [int(rng.randint(20)) for _ in range(3)]  # ids < 20
                used.update(ids)
                batch.append((*ids, rng.randn(4).astype("float32"),
                              int(ids[0] % 2)))
            yield batch

        losses = []
        tr.train(reader, num_passes=20,
                 event_handler=lambda e: losses.append(e.cost)
                 if isinstance(e, paddle.event.EndIteration) else None)
        assert np.mean(losses[-5:]) < np.mean(losses[:5])
        # rows >= 20 never appeared: Adam moments there must be zero
        m = np.asarray(tr.opt_state["slots"]["_wd_emb0_w"]["m"])
        assert np.abs(m[20:]).max() == 0.0
        assert np.abs(m[:20]).max() > 0.0


class TestBigVocabSharded:
    def test_1m_row_table_dpxmp(self):
        """VERDICT exit criterion: a 1M-row sharded sparse table trains a
        step over the dp x mp mesh."""
        import __graft_entry__ as g
        g.dryrun_sparse_multichip(8)
