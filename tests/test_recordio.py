"""PTRecordIO codec: native <-> python cross-compat, crc validation,
chunk-seek semantics, and the coordinator integration (chunk = task).

Reference discipline: the Go RecordIO tests + master service tests
(go/master/service_internal_test.go) exercise the chunk/task contract
without a cluster; same here, with the added twist that the native C++
codec and the pure-Python twin must produce byte-identical files.
"""

import os
import struct

import numpy as np
import pytest

from paddle_tpu.reader import recordio as rio


def records(n=100, seed=0):
    rng = np.random.RandomState(seed)
    return [rng.bytes(int(rng.randint(1, 400))) for _ in range(n)]


def has_native():
    return rio._native() is not None


class TestRoundTrip:
    @pytest.mark.parametrize("use_native", [False, True])
    def test_write_read_all_chunks(self, tmp_path, use_native):
        if use_native and not has_native():
            pytest.skip("no compiler for the native codec")
        recs = records()
        p = str(tmp_path / "data.ptrec")
        rio.write_records(p, recs, max_chunk_bytes=2048,
                          use_native=use_native)
        nc = rio.num_chunks(p, use_native=use_native)
        assert nc > 1, "want multiple chunks for a real test"
        got = []
        for k in range(nc):
            got.extend(rio.read_chunk(p, k, use_native=use_native))
        assert got == recs

    def test_native_and_python_files_are_byte_identical(self, tmp_path):
        if not has_native():
            pytest.skip("no compiler for the native codec")
        recs = records(60, seed=1)
        pn = str(tmp_path / "n.ptrec")
        pp = str(tmp_path / "p.ptrec")
        rio.write_records(pn, recs, max_chunk_bytes=1024, use_native=True)
        rio.write_records(pp, recs, max_chunk_bytes=1024, use_native=False)
        assert open(pn, "rb").read() == open(pp, "rb").read()

    def test_cross_read(self, tmp_path):
        if not has_native():
            pytest.skip("no compiler for the native codec")
        recs = records(40, seed=2)
        p = str(tmp_path / "x.ptrec")
        rio.write_records(p, recs, max_chunk_bytes=512, use_native=True)
        got = []
        for k in range(rio.num_chunks(p, use_native=False)):
            got.extend(rio.read_chunk(p, k, use_native=False))
        assert got == recs


class TestIntegrity:
    def test_crc_detects_corruption(self, tmp_path):
        recs = records(30, seed=3)
        p = str(tmp_path / "c.ptrec")
        rio.write_records(p, recs, max_chunk_bytes=512, use_native=False)
        blob = bytearray(open(p, "rb").read())
        blob[20] ^= 0xFF                      # flip a payload byte
        open(p, "wb").write(bytes(blob))
        with pytest.raises(ValueError, match="crc"):
            rio.read_chunk(p, 0, use_native=False)
        if has_native():
            with pytest.raises(ValueError, match="crc"):
                rio.read_chunk(p, 0, use_native=True)

    def test_seek_is_random_access(self, tmp_path):
        recs = [struct.pack("<I", i) for i in range(64)]
        p = str(tmp_path / "s.ptrec")
        rio.write_records(p, recs, max_chunk_bytes=64, use_native=False)
        nc = rio.num_chunks(p)
        last = rio.read_chunk(p, nc - 1)
        first = rio.read_chunk(p, 0)
        assert struct.unpack("<I", first[0])[0] == 0
        assert struct.unpack("<I", last[-1])[0] == 63


class TestCoordinatorIntegration:
    def test_chunks_feed_the_elastic_reader(self, tmp_path):
        """chunk_descriptors + chunk_reader drive the real Coordinator:
        the file's chunks become tasks and every record arrives once."""
        from paddle_tpu.trainer.coordinator import Coordinator, task_reader
        recs = [struct.pack("<I", i) for i in range(50)]
        p = str(tmp_path / "t.ptrec")
        rio.write_records(p, recs, max_chunk_bytes=128, use_native=None)
        coord = Coordinator(rio.chunk_descriptors(p), chunks_per_task=1,
                            timeout_s=30.0)
        reader = task_reader(
            coord, rio.chunk_reader(
                lambda b: struct.unpack("<I", b)[0]),
            idle_timeout=10.0)
        seen = sorted(reader())
        assert seen == list(range(50))


class TestCreatorReaders:
    """reader.creator.recordio / cloud_reader parity
    (python/paddle/v2/reader/creator.py:60,91)."""

    def test_recordio_creator_roundtrip(self, tmp_path):
        import paddle_tpu as paddle
        from paddle_tpu.dataset import common

        def src():
            for i in range(57):
                yield (i, [i, i + 1], float(i) / 2)

        paths = common.convert(str(tmp_path), src, 10, "mini")
        got = list(paddle.reader.creator.recordio(paths)())
        assert sorted(got) == [(i, [i, i + 1], float(i) / 2)
                               for i in range(57)]
        # comma-joined string form too
        got2 = list(paddle.reader.creator.recordio(",".join(paths))())
        assert sorted(got2) == sorted(got)

    def test_cloud_reader_drains_coordinator(self, tmp_path):
        import paddle_tpu as paddle
        from paddle_tpu.dataset import common
        from paddle_tpu.reader import recordio as rio
        from paddle_tpu.trainer.coordinator import (Coordinator,
                                                    CoordinatorServer)

        def src():
            for i in range(40):
                yield (i,)

        paths = common.convert(str(tmp_path), src, 8, "cloud")
        descs = [d for p in paths for d in rio.chunk_descriptors(p)]
        coord = Coordinator(descs, chunks_per_task=1, timeout_s=60.0)
        srv = CoordinatorServer(coord).start()
        try:
            rdr = paddle.reader.creator.cloud_reader(
                "127.0.0.1", srv.port, timeout_sec=30.0)
            got = sorted(r[0] for r in rdr())
            assert got == list(range(40))
        finally:
            srv.stop()


class TestXmapReaders:
    """reader.decorator.xmap_readers parity (decorator.py:233)."""

    def test_unordered_maps_everything(self):
        import paddle_tpu as paddle
        rdr = paddle.reader.xmap_readers(lambda x: x * 2,
                                         lambda: iter(range(50)),
                                         process_num=4, buffer_size=8)
        assert sorted(rdr()) == [2 * i for i in range(50)]

    def test_ordered_preserves_order(self):
        import random
        import time

        import paddle_tpu as paddle

        def jitter(x):
            time.sleep(random.random() * 0.002)   # scramble completion
            return x + 100

        rdr = paddle.reader.xmap_readers(jitter, lambda: iter(range(40)),
                                         process_num=4, buffer_size=4,
                                         order=True)
        assert list(rdr()) == [i + 100 for i in range(40)]

    def test_mapper_error_surfaces(self):
        import paddle_tpu as paddle
        import pytest as _pytest

        def boom(x):
            if x == 7:
                raise RuntimeError("mapper blew up")
            return x

        rdr = paddle.reader.xmap_readers(boom, lambda: iter(range(20)),
                                         process_num=2, buffer_size=4)
        with _pytest.raises(RuntimeError, match="mapper blew up"):
            list(rdr())
