"""PTRecordIO codec: native <-> python cross-compat, crc validation,
chunk-seek semantics, and the coordinator integration (chunk = task).

Reference discipline: the Go RecordIO tests + master service tests
(go/master/service_internal_test.go) exercise the chunk/task contract
without a cluster; same here, with the added twist that the native C++
codec and the pure-Python twin must produce byte-identical files.
"""

import os
import struct

import numpy as np
import pytest

from paddle_tpu.reader import recordio as rio


def records(n=100, seed=0):
    rng = np.random.RandomState(seed)
    return [rng.bytes(int(rng.randint(1, 400))) for _ in range(n)]


def has_native():
    return rio._native() is not None


class TestRoundTrip:
    @pytest.mark.parametrize("use_native", [False, True])
    def test_write_read_all_chunks(self, tmp_path, use_native):
        if use_native and not has_native():
            pytest.skip("no compiler for the native codec")
        recs = records()
        p = str(tmp_path / "data.ptrec")
        rio.write_records(p, recs, max_chunk_bytes=2048,
                          use_native=use_native)
        nc = rio.num_chunks(p, use_native=use_native)
        assert nc > 1, "want multiple chunks for a real test"
        got = []
        for k in range(nc):
            got.extend(rio.read_chunk(p, k, use_native=use_native))
        assert got == recs

    def test_native_and_python_files_are_byte_identical(self, tmp_path):
        if not has_native():
            pytest.skip("no compiler for the native codec")
        recs = records(60, seed=1)
        pn = str(tmp_path / "n.ptrec")
        pp = str(tmp_path / "p.ptrec")
        rio.write_records(pn, recs, max_chunk_bytes=1024, use_native=True)
        rio.write_records(pp, recs, max_chunk_bytes=1024, use_native=False)
        assert open(pn, "rb").read() == open(pp, "rb").read()

    def test_cross_read(self, tmp_path):
        if not has_native():
            pytest.skip("no compiler for the native codec")
        recs = records(40, seed=2)
        p = str(tmp_path / "x.ptrec")
        rio.write_records(p, recs, max_chunk_bytes=512, use_native=True)
        got = []
        for k in range(rio.num_chunks(p, use_native=False)):
            got.extend(rio.read_chunk(p, k, use_native=False))
        assert got == recs


class TestIntegrity:
    def test_crc_detects_corruption(self, tmp_path):
        recs = records(30, seed=3)
        p = str(tmp_path / "c.ptrec")
        rio.write_records(p, recs, max_chunk_bytes=512, use_native=False)
        blob = bytearray(open(p, "rb").read())
        blob[20] ^= 0xFF                      # flip a payload byte
        open(p, "wb").write(bytes(blob))
        with pytest.raises(ValueError, match="crc"):
            rio.read_chunk(p, 0, use_native=False)
        if has_native():
            with pytest.raises(ValueError, match="crc"):
                rio.read_chunk(p, 0, use_native=True)

    def test_seek_is_random_access(self, tmp_path):
        recs = [struct.pack("<I", i) for i in range(64)]
        p = str(tmp_path / "s.ptrec")
        rio.write_records(p, recs, max_chunk_bytes=64, use_native=False)
        nc = rio.num_chunks(p)
        last = rio.read_chunk(p, nc - 1)
        first = rio.read_chunk(p, 0)
        assert struct.unpack("<I", first[0])[0] == 0
        assert struct.unpack("<I", last[-1])[0] == 63


class TestTornAndCorruptShards:
    """Robustness satellites (docs/robustness.md): torn tails are EOF,
    crc mismatches are skippable, writes are atomic."""

    def test_write_is_atomic_under_crash(self, tmp_path):
        p = str(tmp_path / "a.ptrec")
        rio.write_records(p, records(20, seed=5), use_native=False)
        before = open(p, "rb").read()

        def crashing():
            yield b"x" * 100
            raise RuntimeError("crash mid-write")

        with pytest.raises(RuntimeError, match="crash mid-write"):
            rio.write_records(p, crashing(), use_native=False)
        # the previous shard survives intact; no torn .tmp remains
        assert open(p, "rb").read() == before
        assert not os.path.exists(p + ".tmp")

    @pytest.mark.parametrize("use_native", [False, True])
    def test_garbage_tail_is_eof_not_fatal(self, tmp_path, use_native):
        if use_native and not has_native():
            pytest.skip("no compiler for the native codec")
        recs = records(30, seed=6)
        p = str(tmp_path / "g.ptrec")
        rio.write_records(p, recs, max_chunk_bytes=512,
                          use_native=use_native)
        nc = rio.num_chunks(p, use_native=use_native)
        with open(p, "ab") as f:               # torn append: bad magic
            f.write(b"GARBAGE-NOT-A-CHUNK")
        assert rio.num_chunks(p, use_native=use_native) == nc
        got = []
        for k in range(nc):
            got.extend(rio.read_chunk(p, k, use_native=use_native))
        assert got == recs

    @pytest.mark.parametrize("use_native", [False, True])
    def test_truncated_tail_chunk_dropped(self, tmp_path, use_native):
        if use_native and not has_native():
            pytest.skip("no compiler for the native codec")
        recs = records(30, seed=7)
        p = str(tmp_path / "t.ptrec")
        rio.write_records(p, recs, max_chunk_bytes=512,
                          use_native=use_native)
        nc = rio.num_chunks(p, use_native=use_native)
        assert nc > 2
        blob = open(p, "rb").read()
        open(p, "wb").write(blob[:-10])        # payload runs past EOF
        nc2 = rio.num_chunks(p, use_native=use_native)
        assert nc2 == nc - 1                   # torn tail chunk dropped
        for k in range(nc2):                   # intact prefix readable
            rio.read_chunk(p, k, use_native=use_native)

    def _flip_chunk_payload(self, path, k):
        """Corrupt one byte inside chunk k's payload."""
        idx = rio._py_index(path)
        off, n, plen, crc = idx[k]
        blob = bytearray(open(path, "rb").read())
        blob[off + rio._HDR.size + plen // 2] ^= 0xFF
        open(path, "wb").write(bytes(blob))

    @pytest.mark.parametrize("use_native", [False, True])
    def test_skip_corrupt_drops_only_that_chunk(self, tmp_path,
                                                use_native):
        if use_native and not has_native():
            pytest.skip("no compiler for the native codec")
        recs = [struct.pack("<I", i) for i in range(64)]
        p = str(tmp_path / "c.ptrec")
        rio.write_records(p, recs, max_chunk_bytes=64,
                          use_native=use_native)
        nc = rio.num_chunks(p, use_native=use_native)
        assert nc > 3
        self._flip_chunk_payload(p, 1)
        # strict mode still aborts
        with pytest.raises(ValueError, match="crc"):
            rio.read_chunk(p, 1, use_native=use_native)
        # skip_corrupt: that chunk's records are missing, counted
        before = rio.corrupt_chunks_skipped()
        got = []
        for k in range(nc):
            got.extend(rio.read_chunk(p, k, use_native=use_native,
                                      skip_corrupt=True))
        assert rio.corrupt_chunks_skipped() == before + 1
        lost = rio.read_chunk(str(tmp_path / "c.ptrec"), 1,
                              use_native=use_native, skip_corrupt=True)
        assert lost == []
        good = [struct.unpack("<I", r)[0] for r in got]
        all_ids = set(range(64))
        missing = all_ids - set(good)
        # exactly one chunk's worth of contiguous records is gone
        assert missing and missing != all_ids
        assert sorted(good) == sorted(all_ids - missing)

    def test_corrupt_shard_completes_epoch_via_coordinator(self,
                                                           tmp_path):
        """Acceptance: one crc-flipped chunk; the elastic epoch under
        skip_corrupt=True completes with exactly that chunk's records
        missing and the skip counted."""
        from paddle_tpu.trainer.coordinator import Coordinator, task_reader
        recs = [struct.pack("<I", i) for i in range(50)]
        p = str(tmp_path / "e.ptrec")
        rio.write_records(p, recs, max_chunk_bytes=128, use_native=False)
        # which records live in chunk 2?
        chunk2 = {struct.unpack("<I", r)[0]
                  for r in rio.read_chunk(p, 2, use_native=False)}
        self._flip_chunk_payload(p, 2)
        before = rio.corrupt_chunks_skipped()
        coord = Coordinator(rio.chunk_descriptors(p), chunks_per_task=1,
                            timeout_s=30.0)
        reader = task_reader(
            coord,
            rio.chunk_reader(lambda b: struct.unpack("<I", b)[0],
                             skip_corrupt=True),
            idle_timeout=10.0)
        seen = sorted(reader())                # completes the epoch
        assert rio.corrupt_chunks_skipped() == before + 1
        assert seen == sorted(set(range(50)) - chunk2)


class TestCoordinatorIntegration:
    def test_chunks_feed_the_elastic_reader(self, tmp_path):
        """chunk_descriptors + chunk_reader drive the real Coordinator:
        the file's chunks become tasks and every record arrives once."""
        from paddle_tpu.trainer.coordinator import Coordinator, task_reader
        recs = [struct.pack("<I", i) for i in range(50)]
        p = str(tmp_path / "t.ptrec")
        rio.write_records(p, recs, max_chunk_bytes=128, use_native=None)
        coord = Coordinator(rio.chunk_descriptors(p), chunks_per_task=1,
                            timeout_s=30.0)
        reader = task_reader(
            coord, rio.chunk_reader(
                lambda b: struct.unpack("<I", b)[0]),
            idle_timeout=10.0)
        seen = sorted(reader())
        assert seen == list(range(50))


class TestCreatorReaders:
    """reader.creator.recordio / cloud_reader parity
    (python/paddle/v2/reader/creator.py:60,91)."""

    def test_recordio_creator_roundtrip(self, tmp_path):
        import paddle_tpu as paddle
        from paddle_tpu.dataset import common

        def src():
            for i in range(57):
                yield (i, [i, i + 1], float(i) / 2)

        paths = common.convert(str(tmp_path), src, 10, "mini")
        got = list(paddle.reader.creator.recordio(paths)())
        assert sorted(got) == [(i, [i, i + 1], float(i) / 2)
                               for i in range(57)]
        # comma-joined string form too
        got2 = list(paddle.reader.creator.recordio(",".join(paths))())
        assert sorted(got2) == sorted(got)

    def test_cloud_reader_drains_coordinator(self, tmp_path):
        import paddle_tpu as paddle
        from paddle_tpu.dataset import common
        from paddle_tpu.reader import recordio as rio
        from paddle_tpu.trainer.coordinator import (Coordinator,
                                                    CoordinatorServer)

        def src():
            for i in range(40):
                yield (i,)

        paths = common.convert(str(tmp_path), src, 8, "cloud")
        descs = [d for p in paths for d in rio.chunk_descriptors(p)]
        coord = Coordinator(descs, chunks_per_task=1, timeout_s=60.0)
        srv = CoordinatorServer(coord).start()
        try:
            rdr = paddle.reader.creator.cloud_reader(
                "127.0.0.1", srv.port, timeout_sec=30.0)
            got = sorted(r[0] for r in rdr())
            assert got == list(range(40))
        finally:
            srv.stop()


class TestXmapReaders:
    """reader.decorator.xmap_readers parity (decorator.py:233)."""

    def test_unordered_maps_everything(self):
        import paddle_tpu as paddle
        rdr = paddle.reader.xmap_readers(lambda x: x * 2,
                                         lambda: iter(range(50)),
                                         process_num=4, buffer_size=8)
        assert sorted(rdr()) == [2 * i for i in range(50)]

    def test_ordered_preserves_order(self):
        import random
        import time

        import paddle_tpu as paddle

        def jitter(x):
            time.sleep(random.random() * 0.002)   # scramble completion
            return x + 100

        rdr = paddle.reader.xmap_readers(jitter, lambda: iter(range(40)),
                                         process_num=4, buffer_size=4,
                                         order=True)
        assert list(rdr()) == [i + 100 for i in range(40)]

    def test_mapper_error_surfaces(self):
        import paddle_tpu as paddle
        import pytest as _pytest

        def boom(x):
            if x == 7:
                raise RuntimeError("mapper blew up")
            return x

        rdr = paddle.reader.xmap_readers(boom, lambda: iter(range(20)),
                                         process_num=2, buffer_size=4)
        with _pytest.raises(RuntimeError, match="mapper blew up"):
            list(rdr())
