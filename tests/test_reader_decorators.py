"""Reader decorator parity (python/paddle/v2/reader/decorator.py).

The reference's test file is python/paddle/v2/reader/tests/decorator_test.py;
these mirror its cases: compose alignment (incl. ComposeNotAligned), chain,
map_readers, buffered order preservation, firstn, shuffle buffering.
"""

import pytest

import paddle_tpu as paddle

R = paddle.reader


def counts(n):
    def reader():
        return iter(range(n))
    return reader


class TestCompose:
    def test_tuples_flattened(self):
        rdr = R.compose(counts(3), lambda: iter([(10, 11), (20, 21),
                                                 (30, 31)]))
        assert list(rdr()) == [(0, 10, 11), (1, 20, 21), (2, 30, 31)]

    def test_misaligned_raises(self):
        rdr = R.compose(counts(3), counts(5))
        with pytest.raises(R.ComposeNotAligned):
            list(rdr())

    def test_unchecked_truncates(self):
        rdr = R.compose(counts(3), counts(5), check_alignment=False)
        assert list(rdr()) == [(0, 0), (1, 1), (2, 2)]


class TestDecorators:
    def test_chain(self):
        assert list(R.chain(counts(2), counts(3))()) == [0, 1, 0, 1, 2]

    def test_map_readers(self):
        got = list(R.map_readers(lambda a, b: a + b, counts(4), counts(4))())
        assert got == [0, 2, 4, 6]

    def test_buffered_preserves_order(self):
        assert list(R.buffered(counts(100), 10)()) == list(range(100))

    def test_firstn(self):
        assert list(R.firstn(counts(100), 5)()) == [0, 1, 2, 3, 4]

    def test_shuffle_is_permutation(self):
        got = list(R.shuffle(counts(50), buf_size=16, seed=0)())
        assert sorted(got) == list(range(50)) and got != list(range(50))

    def test_cache_replays(self):
        calls = []

        def once():
            calls.append(1)
            return iter(range(4))

        rdr = R.cache(once)
        assert list(rdr()) == list(rdr()) == [0, 1, 2, 3]
        assert len(calls) == 1


def _raising_reader(n_good, exc):
    def reader():
        yield from range(n_good)
        raise exc
    return reader


def _pipeline_threads():
    import threading
    from paddle_tpu.reader.pipeline import THREAD_PREFIX
    return [t for t in threading.enumerate()
            if t.is_alive() and t.name.startswith(THREAD_PREFIX)]


def _assert_threads_drain(timeout=3.0):
    import time
    deadline = time.time() + timeout
    while _pipeline_threads() and time.time() < deadline:
        time.sleep(0.05)
    assert not _pipeline_threads(), [t.name for t in _pipeline_threads()]


class TestDecoratorLifecycle:
    """Satellite (docs/robustness.md "Data pipeline"): exception
    propagation and clean shutdown through the threaded decorators — a
    source error must reach the CONSUMER (never a silently truncated
    epoch), and abandoning a decorated reader mid-epoch must not leak
    fill/worker threads."""

    def test_buffered_propagates_source_error(self):
        rdr = R.buffered(_raising_reader(3, OSError("disk gone")), 2)
        got = []
        with pytest.raises(OSError, match="disk gone"):
            for v in rdr():
                got.append(v)
        assert got == [0, 1, 2]     # the good prefix was delivered

    def test_xmap_propagates_source_error(self):
        rdr = R.xmap_readers(lambda v: v, _raising_reader(3, OSError("x")),
                             2, 4)
        with pytest.raises(OSError):
            list(rdr())

    def test_xmap_mapper_error_surfaces_promptly(self):
        """The failing sample's error must arrive AT that sample, not
        after the whole epoch drains: with a 10k-sample source and a
        mapper failing at sample 1, the consumer must raise long before
        the source could have been drained through the size-4 queues."""
        def mapper(v):
            if v == 1:
                raise RuntimeError("bad sample")
            return v

        rdr = R.xmap_readers(mapper, counts(10000), 2, 4)
        seen = 0
        with pytest.raises(RuntimeError, match="bad sample"):
            for _ in rdr():
                seen += 1
        assert seen < 1000          # not an end-of-epoch deferral

    def test_compose_propagates_and_component_error(self):
        rdr = R.compose(counts(5), _raising_reader(2, ValueError("c2")))
        with pytest.raises(ValueError, match="c2"):
            list(rdr())

    def test_no_thread_leak_on_abandon(self):
        """Abandoning each threaded decorator mid-epoch returns the
        thread census to baseline (the conftest fixture enforces the
        same invariant globally; this pins it per decorator)."""
        makers = [
            lambda: R.buffered(counts(100000), 2),
            lambda: R.xmap_readers(lambda v: v, counts(100000), 3, 2),
            lambda: R.supervised(counts(100000), mapper=lambda v: v,
                                 num_workers=3, buffer_size=2),
        ]
        for make in makers:
            g = make()()
            for _ in range(5):
                next(g)
            g.close()
            _assert_threads_drain()

    def test_no_thread_leak_after_error(self):
        rdr = R.buffered(_raising_reader(2, OSError("gone")), 2)
        with pytest.raises(OSError):
            list(rdr())
        _assert_threads_drain()
