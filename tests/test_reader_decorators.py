"""Reader decorator parity (python/paddle/v2/reader/decorator.py).

The reference's test file is python/paddle/v2/reader/tests/decorator_test.py;
these mirror its cases: compose alignment (incl. ComposeNotAligned), chain,
map_readers, buffered order preservation, firstn, shuffle buffering.
"""

import pytest

import paddle_tpu as paddle

R = paddle.reader


def counts(n):
    def reader():
        return iter(range(n))
    return reader


class TestCompose:
    def test_tuples_flattened(self):
        rdr = R.compose(counts(3), lambda: iter([(10, 11), (20, 21),
                                                 (30, 31)]))
        assert list(rdr()) == [(0, 10, 11), (1, 20, 21), (2, 30, 31)]

    def test_misaligned_raises(self):
        rdr = R.compose(counts(3), counts(5))
        with pytest.raises(R.ComposeNotAligned):
            list(rdr())

    def test_unchecked_truncates(self):
        rdr = R.compose(counts(3), counts(5), check_alignment=False)
        assert list(rdr()) == [(0, 0), (1, 1), (2, 2)]


class TestDecorators:
    def test_chain(self):
        assert list(R.chain(counts(2), counts(3))()) == [0, 1, 0, 1, 2]

    def test_map_readers(self):
        got = list(R.map_readers(lambda a, b: a + b, counts(4), counts(4))())
        assert got == [0, 2, 4, 6]

    def test_buffered_preserves_order(self):
        assert list(R.buffered(counts(100), 10)()) == list(range(100))

    def test_firstn(self):
        assert list(R.firstn(counts(100), 5)()) == [0, 1, 2, 3, 4]

    def test_shuffle_is_permutation(self):
        got = list(R.shuffle(counts(50), buf_size=16, seed=0)())
        assert sorted(got) == list(range(50)) and got != list(range(50))

    def test_cache_replays(self):
        calls = []

        def once():
            calls.append(1)
            return iter(range(4))

        rdr = R.cache(once)
        assert list(rdr()) == list(rdr()) == [0, 1, 2, 3]
        assert len(calls) == 1
