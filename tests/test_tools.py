"""User-tool parity: model diagram (make_model_diagram.py), torch weight
import (torch2paddle.py), plotcurve (plotcurve.py), and the CLI
dump_config job (dump_config.py / show_pb.py)."""

import json
import os
import subprocess
import sys

import jax
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.core.topology import Topology
from paddle_tpu.utils.diagram import make_diagram, topology_to_dot
from paddle_tpu.utils.torch_import import import_torch_state_dict

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

L = paddle.layer


def small_topo():
    x = L.data("pixel", paddle.data_type.dense_vector(8))
    h = L.fc(x, size=4, act=paddle.activation.Relu(), name="hidden")
    out = L.fc(h, size=2, act=paddle.activation.Softmax(), name="prob")
    lbl = L.data("label", paddle.data_type.integer_value(2))
    cost = L.classification_cost(out, lbl, name="cost")
    return Topology(cost)


class TestDiagram:
    def test_dot_structure(self):
        dot = topology_to_dot(small_topo(), "net")
        assert dot.startswith('digraph "net"')
        for node in ("pixel", "hidden", "prob", "cost"):
            assert f'"{node}"' in dot
        assert '"pixel" -> "hidden"' in dot
        assert '"prob" -> "cost"' in dot
        assert "shape=oval" in dot            # data layers
        assert "peripheries=2" in dot         # output head

    def test_roundtrip_through_serialized_json(self, tmp_path):
        topo = small_topo()
        cfg = tmp_path / "model.json"
        cfg.write_text(topo.serialize())
        dot = make_diagram(str(cfg), str(tmp_path / "m.dot"))
        assert (tmp_path / "m.dot").read_text() == dot
        assert '"hidden"' in dot


class TestTorchImport:
    def _params(self):
        from paddle_tpu.core.registry import reset_name_counters
        reset_name_counters()
        return paddle.create_parameters(small_topo())

    def test_positional_import_transposes_linear(self):
        torch = pytest.importorskip("torch")
        params = self._params()
        names = list(params.names())
        sd = {}
        mapping = {}
        for i, n in enumerate(names):
            shape = params.get_shape(n)
            t = torch.randn(*(tuple(reversed(shape)) if len(shape) == 2
                              else shape))
            sd[f"t{i}"] = t
            mapping[n] = f"t{i}"
        count = import_torch_state_dict(params, sd, name_map=mapping)
        assert count == len(names)
        for i, n in enumerate(names):
            src = sd[f"t{i}"].numpy()
            got = np.asarray(params[n])
            want = src.T if src.ndim == 2 else src
            np.testing.assert_allclose(got, want, rtol=1e-6)

    def test_shape_mismatch_raises(self):
        torch = pytest.importorskip("torch")
        params = self._params()
        name = list(params.names())[0]
        with pytest.raises(ValueError):
            import_torch_state_dict(params, {"w": torch.randn(3, 5, 7)},
                                    name_map={name: "w"})

    def test_positional_count_mismatch(self):
        params = self._params()
        with pytest.raises(ValueError):
            import_torch_state_dict(params, {"only_one": np.zeros((2, 2))})

    def test_positional_non_strict_warns_with_skip_count(self):
        # strict=False on a length mismatch must say HOW MUCH was
        # skipped instead of silently truncating via dict(zip(...))
        params = self._params()
        names = list(params.names())
        sd = {"t0": np.zeros(params.get_shape(names[0]), np.float32)}
        with pytest.warns(UserWarning, match=r"skipped"):
            n = import_torch_state_dict(params, sd, strict=False)
        assert n == 1

    def test_square_matrix_warns_and_transpose_true_forces(self):
        # a square Linear weight is layout-ambiguous under 'auto': the
        # exact-match branch keeps it as-is but must warn; transpose=True
        # is the explicit escape hatch
        from paddle_tpu.core.registry import reset_name_counters
        reset_name_counters()
        x = L.data("x", paddle.data_type.dense_vector(4))
        out = L.fc(x, size=4, bias_attr=False, name="sq")
        params = paddle.create_parameters(Topology(out))
        name = list(params.names())[0]
        src = np.arange(16, dtype=np.float32).reshape(4, 4)
        with pytest.warns(UserWarning, match="square"):
            import_torch_state_dict(params, {"w": src},
                                    name_map={name: "w"})
        np.testing.assert_array_equal(np.asarray(params[name]), src)
        import_torch_state_dict(params, {"w": src}, name_map={name: "w"},
                                transpose=True)
        np.testing.assert_array_equal(np.asarray(params[name]), src.T)

    def test_transpose_false_requires_exact(self):
        params = self._params()
        fc_w = [n for n in params.names()
                if params.get_shape(n) == (8, 4)][0]
        with pytest.raises(ValueError):
            import_torch_state_dict(params, {"w": np.zeros((4, 8),
                                                           np.float32)},
                                    name_map={fc_w: "w"}, transpose=False)


class TestPlotcurve:
    def test_parses_cli_and_demo_formats(self, tmp_path):
        sys.path.insert(0, os.path.join(REPO, "tools"))
        try:
            import plotcurve
        finally:
            sys.path.pop(0)
        log = [
            "Pass 0, Batch 0, Cost 2.400000, {}",
            "Pass 0, Batch 100, Cost 1.600000, {}",
            "pass 1 batch 16 cost 0.3622 cost=0.362165 error=0",
            "noise line",
        ]
        pts = plotcurve.parse(log)
        assert pts == [(0, 2.4), (0, 1.6), (1, 0.362165)]
        curve = plotcurve.per_pass_avg(pts)
        assert curve[0] == (0, 2.0) and curve[1][0] == 1
        csv = tmp_path / "c.csv"
        logf = tmp_path / "train.log"
        logf.write_text("\n".join(log))
        assert plotcurve.main([str(logf), "--csv", str(csv)]) == 0
        body = csv.read_text().splitlines()
        assert body[0] == "pass,avg_cost" and body[1] == "0,2.000000"


class TestDumpConfig:
    def test_dump_config_prints_topology_json(self):
        env = dict(os.environ)
        env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
        env["JAX_PLATFORMS"] = "cpu"
        cfg = os.path.join(REPO, "demo", "mnist", "config.py")
        r = subprocess.run(
            [sys.executable, "-m", "paddle_tpu.cli", "train",
             "--config", cfg, "--job", "dump_config"],
            capture_output=True, text=True, timeout=600, env=env)
        assert r.returncode == 0, r.stderr[-2000:]
        blob = json.loads(r.stdout)
        assert "layers" in blob and len(blob["layers"]) >= 3

    def test_diagram_subcommand(self, tmp_path):
        env = dict(os.environ)
        env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
        env["JAX_PLATFORMS"] = "cpu"
        cfg = os.path.join(REPO, "demo", "mnist", "config.py")
        out = str(tmp_path / "mnist.dot")
        r = subprocess.run(
            [sys.executable, "-m", "paddle_tpu.cli", "diagram",
             "--config", cfg, "--out", out],
            capture_output=True, text=True, timeout=600, env=env)
        assert r.returncode == 0, r.stderr[-2000:]
        dot = open(out).read()
        assert dot.startswith("digraph") and "->" in dot
