"""Model-zoo tests — every builder constructs, runs a forward pass, and the
trainable ones take a full jitted train step (mirrors
paddle/trainer/tests/test_Trainer over the benchmark configs)."""

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import models as M
from paddle_tpu.core.data_type import SeqType


def _random_sample(itype, rng, max_len=6):
    if itype.seq_type == SeqType.SEQUENCE:
        n = rng.randint(2, max_len)
        if itype.kind == "integer":
            return [int(v) for v in rng.randint(0, itype.dim, n)]
        return [rng.randn(itype.dim).astype("float32") for _ in range(n)]
    if itype.kind == "integer":
        return int(rng.randint(0, itype.dim))
    return rng.randn(itype.dim).astype("float32")


def _make_reader(topo, rng, n=8):
    types = [t for _, t in topo.data_type()]

    def reader():
        batch = [tuple(_random_sample(t, rng) for t in types)
                 for _ in range(n)]
        yield batch
    return reader


def _train_steps(spec, steps=2, opt=None, n=8):
    topo = paddle.Topology(spec.cost)
    params = paddle.create_parameters(topo)
    trainer = paddle.SGD(
        cost=spec.cost, parameters=params,
        update_equation=opt or paddle.optimizer.Momentum(learning_rate=1e-3),
        extra_layers=spec.extra_layers)
    costs = []

    def handler(e):
        if isinstance(e, paddle.event.EndIteration):
            costs.append(e.cost)

    rng = np.random.RandomState(0)
    for _ in range(steps):
        trainer.train(_make_reader(trainer.topology, rng, n=n),
                      num_passes=1, event_handler=handler)
    assert all(np.isfinite(c) for c in costs), costs
    return costs


def _forward_only(spec, n=2):
    topo = paddle.Topology(spec.cost)
    params = topo.init_params()
    state = topo.init_state()
    from paddle_tpu.trainer.data_feeder import DataFeeder
    rng = np.random.RandomState(0)
    feeder = DataFeeder(topo.data_type())
    types = [t for _, t in topo.data_type()]
    batch = [tuple(_random_sample(t, rng) for t in types) for _ in range(n)]
    feed = feeder(batch)
    feed.pop("__batch_size__")
    outs, _ = topo.forward(params, state, feed, mode="test")
    v = outs[spec.cost.name]
    assert np.all(np.isfinite(np.asarray(v))), spec.name
    return outs


class TestImageModels:
    def test_mnist_mlp_trains(self):
        costs = _train_steps(M.mnist_mlp(), steps=2)
        assert len(costs) == 2

    def test_smallnet_trains(self):
        _train_steps(M.smallnet(height=16, width=16), steps=1)

    def test_alexnet_forward(self):
        _forward_only(M.alexnet(height=67, width=67, num_classes=10))

    def test_vgg16_forward(self):
        _forward_only(M.vgg16(height=32, width=32, num_classes=10))

    def test_googlenet_forward(self):
        _forward_only(M.googlenet(height=64, width=64, num_classes=10))

    def test_resnet18_trains(self):
        _train_steps(M.resnet(18, height=32, width=32, num_classes=10),
                     steps=1, n=4)

    def test_resnet18_tpu_stem_trains(self):
        _train_steps(M.resnet(18, height=32, width=32, num_classes=10,
                              tpu_stem=True), steps=1, n=4)

    def test_resnet_tpu_stem_shape_chain(self):
        """The s2d stem must reproduce the default stem's 112->56 map chain
        (so every stage downstream sees identical shapes)."""
        spec = M.resnet50(num_classes=10, tpu_stem=True)
        topo = paddle.Topology(spec.cost)
        bn = topo.by_name["rn_stem_bn"].meta
        assert (bn.height, bn.width, bn.channels) == (112, 112, 64)

    def test_resnet50_builds(self):
        spec = M.resnet50(num_classes=1000)
        topo = paddle.Topology(spec.cost)
        n_params = sum(int(np.prod(p.shape))
                       for p in topo.param_specs.values())
        # ResNet-50 has ~25.5M params
        assert 24e6 < n_params < 27e6, n_params


class TestTextModels:
    def test_stacked_lstm_trains(self):
        spec = M.stacked_lstm_net(vocab_size=100, emb_size=16,
                                  hidden_size=16, lstm_num=2)
        _train_steps(spec, steps=1)

    def test_bidi_lstm_forward(self):
        _forward_only(M.bidi_lstm_net(vocab_size=50, emb_size=8,
                                      hidden_size=8))

    def test_convolution_net_trains(self):
        spec = M.convolution_net(vocab_size=100, emb_size=16, hidden_size=16)
        _train_steps(spec, steps=1)

    def test_ngram_lm_trains(self):
        _train_steps(M.ngram_lm(vocab_size=50, emb_size=8, hidden_size=16),
                     steps=1)


class TestSeq2Seq:
    def test_nmt_attention_trains(self):
        spec = M.nmt_attention(src_vocab=40, trg_vocab=40, emb_size=8,
                               enc_size=8, dec_size=8)
        _train_steps(spec, steps=1, n=4)

    def test_nmt_generator_builds_and_shares_params(self):
        train_spec = M.nmt_attention(src_vocab=40, trg_vocab=40, emb_size=8,
                                     enc_size=8, dec_size=8)
        train_topo = paddle.Topology(train_spec.cost)
        gen = M.nmt_generator(src_vocab=40, trg_vocab=40, emb_size=8,
                              enc_size=8, dec_size=8, beam_size=2,
                              max_length=5)
        gen_topo = paddle.Topology(gen)
        shared = set(train_topo.param_specs) & set(gen_topo.param_specs)
        # every decoder/encoder weight must be shared by fixed name
        assert "_dec_emb_w" in shared
        assert "_dec_gru_w" in shared
        assert "_enc_proj_w" in shared


class TestRecommender:
    def test_wide_and_deep_trains(self):
        spec = M.wide_and_deep(sparse_dims=(50, 30), dense_dim=5,
                               emb_size=8, hidden_sizes=(16, 8))
        _train_steps(spec, steps=1)

    def test_movielens_trains(self):
        spec = M.movielens_regression(user_dim=20, movie_dim=30, emb_size=8)
        _train_steps(spec, steps=1)


class TestTagger:
    def test_crf_tagger_trains(self):
        spec = M.crf_tagger(vocab_size=50, num_labels=5, emb_size=8,
                            hidden_size=8, context_len=3)
        _train_steps(spec, steps=1, n=4)

    def test_rnn_crf_tagger_forward(self):
        _forward_only(M.rnn_crf_tagger(vocab_size=50, num_labels=5,
                                       emb_size=8, hidden_size=8))


class TestTransformerLM:
    def test_trains_and_uses_attention(self):
        from paddle_tpu import models
        from paddle_tpu.core import registry
        registry.reset_name_counters()
        paddle.init(seed=0)
        spec = models.transformer_lm(vocab_size=50, d_model=32, n_heads=4,
                                     n_layers=2, d_ff=64, max_len=32)
        params = paddle.create_parameters(
            paddle.Topology(spec.cost, extra_outputs=[spec.output]))
        tr = paddle.SGD(cost=spec.cost, parameters=params,
                        extra_layers=[spec.output],
                        update_equation=paddle.optimizer.Adam(
                            learning_rate=3e-3))
        rng = np.random.RandomState(0)

        def batch(b=8, T=12):
            rows = []
            for _ in range(b):
                # learnable pattern: next token = (tok + 1) % 50
                start = rng.randint(0, 50)
                ids = [(start + i) % 50 for i in range(T + 1)]
                rows.append((ids[:T], list(range(T)), ids[1:]))
            return rows

        first = None
        for _ in range(30):
            loss, _ = tr.train_batch(batch())
            first = first if first is not None else loss
        assert loss < first * 0.8, (first, loss)


class TestTransformerOptions:
    def test_dropout_trains_and_test_mode_deterministic(self):
        spec = M.transformer_lm(vocab_size=40, d_model=16, n_heads=2,
                                n_layers=1, d_ff=32, max_len=16,
                                dropout=0.2)
        topo = paddle.Topology(spec.cost, extra_outputs=[spec.output])
        params = topo.init_params()
        from paddle_tpu.core.sequence import SequenceBatch
        import jax.numpy as jnp
        rng = np.random.RandomState(0)
        ids = rng.randint(0, 40, (2, 6)).astype("int32")
        lens = jnp.full((2,), 6, jnp.int32)
        sb = lambda a: SequenceBatch(jnp.asarray(a), lens)
        pos = np.tile(np.arange(6, dtype="int32"), (2, 1))
        feed = {spec.data.name: sb(ids), spec.positions.name: sb(pos),
                spec.label.name: sb(ids)}
        import jax
        # test mode: dropout is identity -> deterministic
        o1, _ = topo.forward(params, topo.init_state(), feed, mode="test")
        o2, _ = topo.forward(params, topo.init_state(), feed, mode="test")
        np.testing.assert_array_equal(
            np.asarray(o1[spec.cost.name]), np.asarray(o2[spec.cost.name]))
        # train mode: two rng keys give different costs (dropout active)
        t1, _ = topo.forward(params, topo.init_state(), feed, mode="train",
                             rng=jax.random.PRNGKey(1))
        t2, _ = topo.forward(params, topo.init_state(), feed, mode="train",
                             rng=jax.random.PRNGKey(2))
        assert not np.allclose(np.asarray(t1[spec.cost.name]),
                               np.asarray(t2[spec.cost.name]))

    def test_noam_schedule_shape(self):
        from paddle_tpu.optimizer.schedules import make_schedule
        import jax.numpy as jnp
        f = make_schedule("noam", lr=1.0, a=100.0)
        warm = [float(f(jnp.asarray(t, jnp.float32))) for t in
                (1, 50, 100, 400, 10000)]
        assert warm[0] < warm[1] < warm[2]          # rising during warmup
        assert warm[2] > warm[3] > warm[4]          # decaying after
        np.testing.assert_allclose(warm[2], 100 ** -0.5, rtol=1e-5)
        np.testing.assert_allclose(warm[3], 400 ** -0.5, rtol=1e-5)
