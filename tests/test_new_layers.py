"""Forward-semantics tests for the round-2 layer zoo additions: id/sampling
helpers, multiplex, selective_fc, row_conv, data_norm, elementwise utils,
sequence selection (sub_seq / kmax_seq_score / sub_nested_seq), 3D conv/pool,
MDLstm, and the SSD detection family.

Gradient coverage for these types comes from the generated matrix in
test_layers_grad.py; here we pin VALUES against hand-computed expectations,
the way test_LayerGrad.cpp's sibling unit tests (test_KmaxSeqScore.cpp,
test_CrossEntropyOverBeamGrad.cpp, test_PriorBox.cpp, test_DetectionOutput.cpp)
do in the reference.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.core.sequence import pack_nested_sequences, pack_sequences
from paddle_tpu.core.topology import Topology
from paddle_tpu.ops import detection as det_ops


def run(out, feed, mode="test", seed=0):
    topo = Topology(out)
    params = topo.init_params(jax.random.PRNGKey(seed))
    outs, _ = topo.forward(params, topo.init_state(), feed, mode=mode,
                           rng=jax.random.PRNGKey(seed + 1))
    return outs[out.name], params


class TestIdLayers:
    def test_maxid(self):
        x = paddle.layer.data("x", paddle.data_type.dense_vector(4))
        out = paddle.layer.max_id(x)
        v = np.array([[0.1, 0.9, 0.0, 0.2], [0.5, 0.1, 0.3, 0.7]], np.float32)
        got, _ = run(out, {"x": jnp.asarray(v)})
        np.testing.assert_array_equal(np.asarray(got)[:, 0], [1, 3])

    def test_maxid_beam(self):
        x = paddle.layer.data("x", paddle.data_type.dense_vector(4))
        out = paddle.layer.max_id(x, beam_size=2)
        v = np.array([[0.1, 0.9, 0.0, 0.2]], np.float32)
        got, _ = run(out, {"x": jnp.asarray(v)})
        np.testing.assert_array_equal(np.asarray(got)[0], [1, 3])

    def test_sampling_id_valid_and_deterministic_in_test(self):
        x = paddle.layer.data("x", paddle.data_type.dense_vector(5))
        out = paddle.layer.sampling_id(x)
        probs = np.full((3, 5), 0.01, np.float32)
        probs[:, 2] = 0.96
        got, _ = run(out, {"x": jnp.asarray(probs)})
        np.testing.assert_array_equal(np.asarray(got)[:, 0], [2, 2, 2])

    def test_sampling_id_train_mode_samples(self):
        x = paddle.layer.data("x", paddle.data_type.dense_vector(3))
        out = paddle.layer.sampling_id(x)
        probs = np.tile(np.array([[0.2, 0.5, 0.3]], np.float32), (64, 1))
        got, _ = run(out, {"x": jnp.asarray(probs)}, mode="train")
        ids = np.asarray(got)[:, 0]
        assert set(np.unique(ids)) <= {0, 1, 2}
        assert len(np.unique(ids)) > 1     # actually stochastic

    def test_eos(self):
        x = paddle.layer.data("x", paddle.data_type.integer_value(10))
        out = paddle.layer.eos(x, eos_id=7)
        got, _ = run(out, {"x": jnp.asarray([3, 7, 7, 1])})
        np.testing.assert_array_equal(np.asarray(got)[:, 0], [0, 1, 1, 0])

    def test_multiplex(self):
        idx = paddle.layer.data("idx", paddle.data_type.integer_value(2))
        a = paddle.layer.data("a", paddle.data_type.dense_vector(3))
        b = paddle.layer.data("b", paddle.data_type.dense_vector(3))
        out = paddle.layer.multiplex([idx, a, b])
        av = np.arange(6, dtype=np.float32).reshape(2, 3)
        bv = -np.arange(6, dtype=np.float32).reshape(2, 3)
        got, _ = run(out, {"idx": jnp.asarray([1, 0]),
                           "a": jnp.asarray(av), "b": jnp.asarray(bv)})
        np.testing.assert_allclose(np.asarray(got),
                                   np.stack([bv[0], av[1]]))


class TestElementwiseUtils:
    def test_clip(self):
        x = paddle.layer.data("x", paddle.data_type.dense_vector(3))
        out = paddle.layer.clip(x, min=-1.0, max=1.0)
        got, _ = run(out, {"x": jnp.asarray([[-5.0, 0.5, 3.0]])})
        np.testing.assert_allclose(np.asarray(got), [[-1.0, 0.5, 1.0]])

    def test_scale_shift_initial_identity(self):
        x = paddle.layer.data("x", paddle.data_type.dense_vector(3))
        out = paddle.layer.scale_shift(x)
        v = np.random.RandomState(0).randn(2, 3).astype(np.float32)
        got, _ = run(out, {"x": jnp.asarray(v)})
        np.testing.assert_allclose(np.asarray(got), v, rtol=1e-6)

    def test_power(self):
        w = paddle.layer.data("w", paddle.data_type.dense_vector(1))
        x = paddle.layer.data("x", paddle.data_type.dense_vector(2))
        out = paddle.layer.power(x, w)
        got, _ = run(out, {"w": jnp.asarray([[2.0]]),
                           "x": jnp.asarray([[3.0, 4.0]])})
        np.testing.assert_allclose(np.asarray(got), [[9.0, 16.0]], rtol=1e-5)

    def test_featmap_expand(self):
        x = paddle.layer.data("x", paddle.data_type.dense_vector(2))
        out = paddle.layer.featmap_expand(x, num_filters=3)
        assert out.meta.size == 6
        got, _ = run(out, {"x": jnp.asarray([[1.0, 2.0]])})
        np.testing.assert_allclose(np.asarray(got),
                                   [[1.0, 2.0, 1.0, 2.0, 1.0, 2.0]])

    def test_rotate(self):
        x = paddle.layer.data("x", paddle.data_type.dense_vector(6),
                              height=2, width=3)
        out = paddle.layer.rotate(x)
        assert (out.meta.height, out.meta.width) == (3, 2)
        img = np.arange(6, dtype=np.float32).reshape(1, 6)  # [1, c*h*w], c=1
        got, _ = run(out, {"x": jnp.asarray(img)})
        # chw [[0,1,2],[3,4,5]] rotated 90 ccw -> [[2,5],[1,4],[0,3]]
        np.testing.assert_allclose(np.asarray(got).reshape(3, 2),
                                   [[2, 5], [1, 4], [0, 3]])

    def test_data_norm_zscore_default_identity(self):
        x = paddle.layer.data("x", paddle.data_type.dense_vector(3))
        out = paddle.layer.data_norm(x)
        v = np.random.RandomState(1).randn(2, 3).astype(np.float32)
        got, _ = run(out, {"x": jnp.asarray(v)})
        np.testing.assert_allclose(np.asarray(got), v, rtol=1e-6)

    def test_data_norm_minmax(self):
        x = paddle.layer.data("x", paddle.data_type.dense_vector(2))
        out = paddle.layer.data_norm(x, data_norm_strategy="min-max")
        got, _ = run(out, {"x": jnp.asarray([[0.25, 0.5]])})
        np.testing.assert_allclose(np.asarray(got), [[0.25, 0.5]], rtol=1e-6)


class TestSelectiveFCAndRowConv:
    def test_selective_fc_mask(self):
        x = paddle.layer.data("x", paddle.data_type.dense_vector(4))
        sel = paddle.layer.data("sel", paddle.data_type.dense_vector(6))
        out = paddle.layer.selective_fc(x, size=6, select=sel)
        xv = np.random.RandomState(2).randn(3, 4).astype(np.float32)
        mask = np.zeros((3, 6), np.float32)
        mask[:, [1, 4]] = 1.0
        got, params = run(out, {"x": jnp.asarray(xv), "sel": jnp.asarray(mask)})
        got = np.asarray(got)
        assert np.all(got[:, [0, 2, 3, 5]] == 0.0)
        # selected columns equal the plain fc value
        w = np.asarray(params[f"_{out.name}.w0"])
        b = np.asarray(params[f"_{out.name}.wbias"])
        full = xv @ w.T + b
        np.testing.assert_allclose(got[:, [1, 4]], full[:, [1, 4]], rtol=1e-5)

    def test_selective_fc_no_select_is_fc(self):
        x = paddle.layer.data("x", paddle.data_type.dense_vector(4))
        out = paddle.layer.selective_fc(x, size=5)
        xv = np.random.RandomState(3).randn(2, 4).astype(np.float32)
        got, params = run(out, {"x": jnp.asarray(xv)})
        w = np.asarray(params[f"_{out.name}.w0"])
        b = np.asarray(params[f"_{out.name}.wbias"])
        np.testing.assert_allclose(np.asarray(got), xv @ w.T + b, rtol=1e-5)

    def test_row_conv_lookahead(self):
        s = paddle.layer.data("s", paddle.data_type.dense_vector_sequence(3))
        out = paddle.layer.row_conv(s, context_len=2)
        rows = [np.eye(3, dtype=np.float32)[:2] * 0 + np.array(
            [[1, 0, 0], [0, 1, 0]], np.float32)]
        seq = pack_sequences([np.array([[1., 1, 1], [2, 2, 2], [4, 4, 4]],
                                       np.float32)])
        topo = Topology(out)
        params = dict(topo.init_params(jax.random.PRNGKey(0)))
        pname = f"_{out.name}.w0"
        params[pname] = jnp.asarray(np.stack(
            [np.ones(3, np.float32), 0.5 * np.ones(3, np.float32)]))
        outs, _ = topo.forward(params, topo.init_state(), {"s": seq},
                               mode="test", rng=jax.random.PRNGKey(1))
        got = np.asarray(outs[out.name].data[0])
        # out[t] = x[t] + 0.5*x[t+1]; last step sees zero future (masked)
        np.testing.assert_allclose(got[0], [2.0, 2.0, 2.0])
        np.testing.assert_allclose(got[1], [4.0, 4.0, 4.0])
        np.testing.assert_allclose(got[2], [4.0, 4.0, 4.0])


class TestSequenceSelection:
    def test_sub_seq(self):
        s = paddle.layer.data("s", paddle.data_type.dense_vector_sequence(2))
        off = paddle.layer.data("off", paddle.data_type.integer_value(10))
        sz = paddle.layer.data("sz", paddle.data_type.integer_value(10))
        out = paddle.layer.sub_seq(s, off, sz)
        seq = pack_sequences([np.arange(10, dtype=np.float32).reshape(5, 2)])
        got, _ = run(out, {"s": seq, "off": jnp.asarray([1]),
                           "sz": jnp.asarray([2])})
        assert int(got.lengths[0]) == 2
        np.testing.assert_allclose(np.asarray(got.data[0, :2]),
                                   [[2, 3], [4, 5]])

    def test_kmax_seq_score(self):
        s = paddle.layer.data("s", paddle.data_type.dense_vector_sequence(1))
        out = paddle.layer.kmax_seq_score(s, beam_size=3)
        seq = pack_sequences([np.array([[0.1], [0.9], [0.5], [0.7]],
                                       np.float32),
                              np.array([[0.3], [0.2]], np.float32)])
        got, _ = run(out, {"s": seq})
        got = np.asarray(got)
        np.testing.assert_array_equal(got[0], [1, 3, 2])
        np.testing.assert_array_equal(got[1], [0, 1, -1])  # padded past len

    def test_sub_nested_seq(self):
        s = paddle.layer.data(
            "s", paddle.data_type.dense_vector_sub_sequence(1))
        idx = paddle.layer.data("idx", paddle.data_type.integer_value(4))
        out = paddle.layer.sub_nested_seq(s, idx)
        nested = pack_nested_sequences(
            [[np.array([[1.], [2.]]), np.array([[3.]]),
              np.array([[4.], [5.], [6.]])],
             [np.array([[7.]]), np.array([[8.], [9.]])]])
        sel = jnp.asarray([[2, 0], [1, -1]], jnp.int32)
        got, _ = run(out, {"s": nested, "idx": sel})
        # row 0: segment 2 (4,5,6) then segment 0 (1,2)
        np.testing.assert_allclose(
            np.asarray(got.data[0, :5, 0]), [4, 5, 6, 1, 2])
        np.testing.assert_array_equal(
            np.asarray(got.segment_ids[0, :5]), [0, 0, 0, 1, 1])
        assert int(got.lengths[0]) == 5 and int(got.num_segments[0]) == 2
        # row 1: segment 1 (8,9) only
        np.testing.assert_allclose(np.asarray(got.data[1, :2, 0]), [8, 9])
        assert int(got.lengths[1]) == 2 and int(got.num_segments[1]) == 1


class TestConv3D:
    def test_conv3d_shape(self):
        x = paddle.layer.data("x", paddle.data_type.dense_vector(2 * 4 * 4 * 4))
        out = paddle.layer.img_conv3d(x, filter_size=3, num_filters=5,
                                      input_depth=4, num_channels=2,
                                      input_height=4, input_width=4,
                                      padding=1)
        assert out.meta.size == 5 * 4 * 4 * 4
        v = np.random.RandomState(0).randn(2, 2 * 64).astype(np.float32)
        got, _ = run(out, {"x": jnp.asarray(v)})
        assert got.shape == (2, 4, 4, 4, 5)

    def test_deconv3d_shape(self):
        x = paddle.layer.data("x", paddle.data_type.dense_vector(3 * 2 * 2 * 2))
        out = paddle.layer.img_conv3d(x, filter_size=2, num_filters=4,
                                      input_depth=2, num_channels=3,
                                      input_height=2, input_width=2,
                                      stride=2, trans=True)
        assert out.meta.size == 4 * 4 * 4 * 4
        v = np.random.RandomState(0).randn(1, 24).astype(np.float32)
        got, _ = run(out, {"x": jnp.asarray(v)})
        assert got.shape == (1, 4, 4, 4, 4)

    def test_pool3d_values(self):
        x = paddle.layer.data("x", paddle.data_type.dense_vector(8))
        out = paddle.layer.img_pool3d(x, pool_size=2, input_depth=2,
                                      num_channels=1, input_height=2,
                                      input_width=2, stride=2)
        v = np.arange(8, dtype=np.float32).reshape(1, 8)
        got, _ = run(out, {"x": jnp.asarray(v)})
        assert float(np.asarray(got).ravel()[0]) == 7.0


class TestMDLstm:
    def test_mdlstm_shape_and_finite(self):
        h, H, W = 3, 4, 5
        x = paddle.layer.data("x", paddle.data_type.dense_vector(5 * h * H * W),
                              height=H, width=W)
        out = paddle.layer.mdlstm(x)
        assert out.meta.channels == h
        v = np.random.RandomState(0).randn(2, 5 * h * H * W).astype(np.float32)
        got, _ = run(out, {"x": jnp.asarray(v)})
        assert got.shape == (2, H, W, h)
        assert np.all(np.isfinite(np.asarray(got)))

    def test_mdlstm_matches_manual_cell_chain(self):
        # 1x1 grid degenerates to a single LSTM cell with zero recurrence
        h = 2
        x = paddle.layer.data("x", paddle.data_type.dense_vector(5 * h),
                              height=1, width=1)
        out = paddle.layer.mdlstm(x)
        v = np.random.RandomState(1).randn(1, 5 * h).astype(np.float32)
        got, _ = run(out, {"x": jnp.asarray(v)})
        pre = v.reshape(5, h)
        a_in = np.tanh(pre[0])
        sig = lambda z: 1 / (1 + np.exp(-z))
        c = sig(pre[1]) * a_in
        expect = sig(pre[4]) * np.tanh(c)
        np.testing.assert_allclose(np.asarray(got).reshape(h), expect,
                                   rtol=1e-5)

    def test_mdlstm_direction_flip_changes_output(self):
        h, H, W = 2, 3, 3
        xv = np.random.RandomState(2).randn(1, 5 * h * H * W).astype(np.float32)

        def build(directions):
            x = paddle.layer.data(
                "x", paddle.data_type.dense_vector(5 * h * H * W),
                height=H, width=W)
            out = paddle.layer.mdlstm(x, directions=directions)
            topo = Topology(out)
            params = topo.init_params(jax.random.PRNGKey(5))
            outs, _ = topo.forward(params, topo.init_state(),
                                   {"x": jnp.asarray(xv)}, mode="test",
                                   rng=jax.random.PRNGKey(0))
            return np.asarray(outs[out.name])

        fwd = build([True, True])
        rev = build([False, False])
        assert not np.allclose(fwd, rev)


class TestDetection:
    def _priors(self):
        return det_ops.prior_boxes(2, 2, 8, 8, [2.0], [4.0], [2.0],
                                   [0.1, 0.1, 0.2, 0.2])

    def test_prior_boxes_values(self):
        pb = np.asarray(self._priors())
        # 2x2 cells x (1 min + 1 max + 2 ratio) priors x 8
        assert pb.shape == (2 * 2 * 4, 8)
        # first prior of cell (0,0): center (2,2), box 2x2 -> [1,1,3,3]/8
        np.testing.assert_allclose(pb[0, :4],
                                   [1 / 8, 1 / 8, 3 / 8, 3 / 8], rtol=1e-6)
        np.testing.assert_allclose(pb[0, 4:], [0.1, 0.1, 0.2, 0.2])
        # second: sqrt(2*4) box
        d = np.sqrt(8.0)
        np.testing.assert_allclose(
            pb[1, :4], [(2 - d / 2) / 8, (2 - d / 2) / 8,
                        (2 + d / 2) / 8, (2 + d / 2) / 8], rtol=1e-6)
        assert pb[:, :4].min() >= 0.0 and pb[:, :4].max() <= 1.0

    def test_encode_decode_roundtrip(self):
        priors = self._priors()
        rng = np.random.RandomState(0)
        gt = np.sort(rng.rand(priors.shape[0], 4).astype(np.float32), axis=1)
        enc = det_ops.encode_boxes(jnp.asarray(gt), priors)
        dec = det_ops.decode_boxes(enc, priors)
        np.testing.assert_allclose(np.asarray(dec), gt, atol=1e-4)

    def test_nms_suppresses_overlaps(self):
        boxes = jnp.asarray([[0., 0., 1., 1.],
                             [0.02, 0.02, 1.02, 1.02],   # heavy overlap
                             [2., 2., 3., 3.]])
        scores = jnp.asarray([0.9, 0.8, 0.7])
        _, kept_scores, keep = det_ops.nms(boxes, scores, iou_threshold=0.5,
                                           top_k=3)
        assert bool(keep[0]) and not bool(keep[1]) and bool(keep[2])

    def test_match_priors_bipartite(self):
        priors = jnp.asarray([[0., 0., .5, .5, .1, .1, .2, .2],
                              [.5, .5, 1., 1., .1, .1, .2, .2]])
        gt = jnp.asarray([[0.05, 0.05, 0.45, 0.45]])
        midx, _ = det_ops.match_priors(priors, gt, jnp.asarray([True]))
        assert int(midx[0]) == 0 and int(midx[1]) == -1

    def test_match_priors_ignores_padded_gt(self):
        # padded gt slots must not clobber a valid gt's bipartite claim
        priors = jnp.asarray([[0., 0., .4, .4, .1, .1, .2, .2],
                              [.6, .6, 1., 1., .1, .1, .2, .2]])
        gt = jnp.asarray([[0., 0., .2, .2], [0., 0., 0., 0.]])
        midx, _ = det_ops.match_priors(priors, gt,
                                       jnp.asarray([True, False]))
        assert int(midx[0]) == 0 and int(midx[1]) == -1

    def test_cross_channel_norm(self):
        x = paddle.layer.data("x", paddle.data_type.dense_vector(2 * 2 * 2),
                              height=2, width=2)
        out = paddle.layer.cross_channel_norm(x)
        v = np.random.RandomState(0).randn(1, 8).astype(np.float32)
        got, _ = run(out, {"x": jnp.asarray(v)})
        got = np.asarray(got)
        # default scale 20 -> per-position channel norm == 20
        norms = np.linalg.norm(got, axis=-1)
        np.testing.assert_allclose(norms, 20.0, rtol=1e-4)

    def _ssd_head(self, with_label):
        C = 3
        feat = paddle.layer.data("feat", paddle.data_type.dense_vector(
            4 * 2 * 2), height=2, width=2)   # 4 ch, 2x2
        img = paddle.layer.data("img", paddle.data_type.dense_vector(
            3 * 8 * 8), height=8, width=8)
        pb = paddle.layer.priorbox(feat, img, aspect_ratio=[2.0],
                                   variance=[0.1, 0.1, 0.2, 0.2],
                                   min_size=[2.0], max_size=[4.0])
        n_priors = 4
        loc = paddle.layer.img_conv(feat, filter_size=1,
                                    num_filters=n_priors * 4, padding=0)
        conf = paddle.layer.img_conv(feat, filter_size=1,
                                     num_filters=n_priors * C, padding=0)
        feed = {
            "feat": jnp.asarray(np.random.RandomState(0).randn(
                2, 16).astype(np.float32)),
            "img": jnp.asarray(np.zeros((2, 192), np.float32)),
        }
        if with_label:
            lbl = paddle.layer.data(
                "label", paddle.data_type.dense_vector_sequence(6))
            feed["label"] = pack_sequences(
                [np.array([[1, .1, .1, .4, .4, 0],
                           [2, .5, .5, .9, .9, 0]], np.float32),
                 np.array([[1, .2, .2, .6, .6, 0]], np.float32)])
            out = paddle.layer.multibox_loss(loc, conf, pb, lbl,
                                             num_classes=C)
        else:
            out = paddle.layer.detection_output(loc, conf, pb, num_classes=C,
                                                keep_top_k=10, nms_top_k=16)
        return out, feed

    def test_multibox_loss_finite_positive(self):
        out, feed = self._ssd_head(with_label=True)
        got, _ = run(out, feed, mode="train")
        got = np.asarray(got)
        assert got.shape == (2, 1)
        assert np.all(np.isfinite(got)) and np.all(got > 0)

    def test_detection_output_shape_and_labels(self):
        out, feed = self._ssd_head(with_label=False)
        got, _ = run(out, feed)
        got = np.asarray(got).reshape(2, 10, 7)
        # image ids stamped, labels in {-1, 1, 2}, boxes finite
        np.testing.assert_array_equal(got[0, :, 0], 0.0)
        np.testing.assert_array_equal(got[1, :, 0], 1.0)
        assert set(np.unique(got[..., 1])) <= {-1.0, 1.0, 2.0}
        assert np.all(np.isfinite(got))


class TestPrintLayer:
    def test_print_is_identity(self, capfd):
        x = paddle.layer.data("x", paddle.data_type.dense_vector(2))
        out = paddle.layer.print_layer(x)
        v = np.array([[1.0, 2.0]], np.float32)
        got, _ = run(out, {"x": jnp.asarray(v)})
        np.testing.assert_allclose(np.asarray(got), v)
